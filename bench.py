# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Headline benchmark: CSR SpMV achieved HBM bandwidth on one chip.

Prints ONE JSON line::

    {"metric": "csr_spmv_bandwidth", "value": <GB/s>, "unit": "GB/s",
     "vs_baseline": <fraction of measured stream bandwidth>, ...}

Config matches the reference's SpMV microbenchmark default (banded
matrix, nnz/row=11 — reference ``examples/spmv_microbenchmark.py:34-52``,
``examples/common.py:206-249``) at 2^24 rows.  ``vs_baseline`` is the
achieved fraction of this chip's *measured* stream (triad) bandwidth,
i.e. the roofline fraction BASELINE.md's north-star targets (>= 0.70).
The reference publishes no absolute numbers (BASELINE.md).

Timing methodology (``legate_sparse_tpu/bench_timing.py``): ops run
chained inside one jitted fori_loop at two trip counts and the delta is
divided by the trip-count difference, with a host scalar fetch as the
only trusted sync — on this TPU tunnel ``block_until_ready`` returns at
dispatch-ack, not completion, so naive timing reports impossible
numbers (measured 10x above the HBM roofline).  The stream measurement
uses 2^26 lanes (512 MB working set) so it cannot hide in VMEM.

Extra keys in the same JSON object (driver contract stays one line):
``platform`` (tpu/cpu), ``stream_gbs`` (measured roofline — the MEDIAN
of 5 samples interleaved with the workload phases; ``stream_samples``
/ ``stream_gbs_min`` / ``stream_gbs_median`` / ``stream_gbs_max``
record the spread that motivated the median),
``irregular_gbs``/``irregular_frac`` (random-sparsity matrix through the
gather/segment-sum path banded never exercises), ``spmv_ms`` (per-
iteration time), ``path`` (dia/ell/csr — which kernel the dispatch
picked; "dia" means the Pallas band kernel on TPU).  A
``cpu_roofline_ratio`` below 0.7 arrives itemized
(``cpu_roofline_items``: mask / pad-allocation / segment-sum-vs-
shifted-add loss terms, each measured); the pde scale anchor carries
its own stream bound and, when more than ~1.3x off it, a ``pde_items``
decomposition.

Robustness: the TPU backend is probed in a SUBPROCESS with a timeout and
retries before this process commits to it — a hung or erroring tunnel
(round-1 failure: ``BENCH_r01.json`` rc=1 backend-init crash) degrades
to a CPU run with ``"platform": "cpu"`` recorded.  Each phase is
individually guarded so a mid-bench device fault still emits a JSON
line with whatever was measured (round-2 failure mode: a TPU worker
crash midway lost the whole round's data).

Comm/mem ledger (schema_version 7): a distributed phase over all
visible devices records ``dist_shards``/``dist_spmv_ms`` and the
STATIC interconnect predictions ``dist_spmv_comm_bytes`` /
``dist_cg_comm_bytes`` (obs/comm.py — deterministic given the mesh, so
``tools/bench_compare.py`` gates them at 1% where timing fields get
the stream-spread noise band), plus ``comm_total_bytes`` and
``mem_peak_rss_mb``.  ``--smoke`` (or LEGATE_SPARSE_TPU_BENCH_SMOKE=1)
is the hermetic CI lane: an 8-virtual-device CPU mesh, no probe or
canary, tiny sizes — the whole schema in seconds, exercised by
``tests/test_bench_smoke.py`` against ``evidence/BENCH_golden_smoke.json``.

Engine phase (schema_version 8, ``docs/ENGINE.md``): cold (plan
compile) vs warm-cache (same shape bucket, different n — the
zero-retrace hit path) vs micro-batched dispatch, recorded as
``engine_cold_ms``/``engine_warm_ms``/``engine_batched_ms_per_req``
plus the deterministic ``engine_plan_hits``/``engine_plan_misses``
that the smoke golden pins.

Resilience phase (schema_version 9, ``docs/RESILIENCE.md``): a
deterministic fault drill — inject fail-twice-then-recover, trip a
circuit breaker, shed one expired-deadline request — recording the
exact ``resil_retries``/``resil_shed``/``resil_breaker_trips``/
``resil_faults_injected`` the smoke golden pins, plus the
recovered-vs-clean latency pair ``resil_clean_ms``/
``resil_recovered_ms``.

Saturation phase (schema_version 10, obs v3 —
``docs/OBSERVABILITY.md``): a closed-loop arrival generator sweeps
offered load (concurrent closed-loop clients) against the
micro-batching request executor, recording per level the p50/p99
request latency (from the always-on ``lat.engine.request.*``
histograms), throughput, shed count, and mean batch occupancy
(``saturation`` list + top-level ``saturation_p50_ms``/
``saturation_p99_ms``), plus the golden-gated deterministic totals
``saturation_requests``/``saturation_shed``/
``saturation_batched_requests``.

Autotune phase (schema_version 11, ``docs/AUTOTUNER.md``): the
irregular-SpMV proof for the sparsity-fingerprint autotuner — a
seeded power-law matrix (``gallery.powerlaw``) is tuned, one eager
dispatch proves the verdict actually routes (``autotune.route.hits``
delta), and the winning row-binned sliced-ELL kernel is timed against
the flat CSR gather baseline: ``irregular_spmv_ms`` /
``irregular_csr_ms`` / ``irregular_spmv_speedup`` (target >= 1.3x on
the CPU lane) plus the routed-kernel label ``irregular_spmv_path``
and the golden-gated deterministic ``autotune_verdicts``.  The smoke
lane pins the verdict instead of measuring (deterministic golden);
everything restores on exit — the autotuner stays inert for every
other phase.

Gateway fairness phase (schema_version 12, ``docs/ENGINE.md``): a
3-tenant sweep against the multi-tenant admission gateway — a WFQ
packing stage (the interactive tenant's alternating same-bucket
matrices dispatch as stacked multi-matrix batches) and a flood stage
(a background tenant offers 4x its queue quota and deterministically
rejects ``queue_full`` while interactive service is unaffected) —
recording the golden-gated ``gateway_requests`` /
``gateway_dispatches`` / ``gateway_packed`` /
``gateway_rejected_queue_full`` and per-tenant served/shed totals.

Observability: with ``LEGATE_SPARSE_TPU_OBS=1`` the run additionally
writes a ``BENCH_<stamp>.trace.json`` Chrome-trace artifact (path
override: ``LEGATE_SPARSE_TPU_OBS_FILE``) containing phase spans
(``bench.spmv``/``bench.spgemm``/``bench.cg``/``bench.gmg``/... with
nnz/bytes attributes) plus every op-level span and counter the package
recorded — machine-readable per-op evidence instead of one blob (the
v5 VERDICT ask).  ``tools/trace_summary.py`` renders the per-op
table.  If tracing was requested but no spans were produced (silent
no-op wiring), the process exits nonzero.  With tracing disabled the
span API is a no-op and ``bench_wall_s`` is unaffected.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

# Probe budget must stay well inside any plausible driver timeout: a
# hung tunnel costs (retries+1)*timeout before the CPU fallback starts,
# and the fallback run itself still needs a few minutes.
PROBE_TIMEOUT_S = int(os.environ.get("LEGATE_SPARSE_TPU_PROBE_TIMEOUT", "90"))
PROBE_RETRIES = int(os.environ.get("LEGATE_SPARSE_TPU_PROBE_RETRIES", "1"))

# Global wall-clock budget: after this many seconds, remaining optional
# phases are skipped (recorded in the JSON) so the contract line always
# lands inside the driver's timeout.  The first on-chip capture attempt
# (2026-07-31) showed tunnel-remote phases can take many minutes each —
# host->device uploads ride the network tunnel.
DEADLINE_S = float(os.environ.get("LEGATE_SPARSE_TPU_BENCH_DEADLINE", "1800"))


def _probe_accelerator() -> bool:
    """Can a fresh process initialize the default (accelerator) backend
    AND run one op to completion?  Delegates to the shared subprocess
    probe (``_platform.ensure_live_backend``), which also pins the cpu
    platform on failure — the fallback this bench then runs on."""
    from legate_sparse_tpu._platform import ensure_live_backend

    return ensure_live_backend(
        timeout_s=PROBE_TIMEOUT_S, retries=PROBE_RETRIES
    )


# The canary probes EXACTLY the fault surface — the Pallas kernel
# embedded in a jitted fori_loop at the bench size — with a synthetic
# band built directly on device (row sums 1.0 keep the chain stable).
# The r3 on-chip evidence shows the CSR->DIA build and eager launches
# pass; skipping the full diags->CSR->pack build cuts each rung from
# ~3-4 minutes of tunnel-bound build time to one compile + a few
# launches, so the whole ladder fits comfortably in a window.
_CANARY_CODE = r"""
import sys
import numpy as np
import jax.numpy as jnp
from legate_sparse_tpu.bench_timing import loop_ms_per_iter
from legate_sparse_tpu.ops import pallas_dia
n = 1 << int(sys.argv[1])
W = 11
half = W // 2
offsets = tuple(range(-half, half + 1))
tile = pallas_dia.supported(offsets, np.float32, masked=False)
assert tile is not None
# Pad rows to a tile multiple (the kernel's grid works in whole
# tiles; row_align does the same padding on the production path) so
# sub-tile bench sizes don't misreport a trace error as a fault.
rows_pad = -(-n // tile) * tile
val = np.float32(1.0 / W)
rdata = jnp.full((W, rows_pad // 128, 128), val, dtype=jnp.float32)
x = jnp.ones((n,), dtype=jnp.float32)

def step(v):
    return pallas_dia.pallas_dia_spmv(rdata, None, v, offsets, (n, n),
                                      tile)

float(jnp.sum(step(x)))                    # eager launch
try:
    loop_ms_per_iter(step, x, k_lo=2, k_hi=6, k_cap=24)
except RuntimeError:
    # "unresolvable timing" under the capped trip count is NOT a
    # fault: both looped programs ran to completion, which is all the
    # canary needs to prove.
    pass

# The bench's later phases also run the SpMM and banded-SpGEMM Mosaic
# kernels under the selected variant (the variant env changes all
# three lowerings), so each rung must prove those survive the looped
# composition too — eager launch + a short capped fori_loop each.
import jax

class _Pk:
    pass

pk = _Pk()
pk.rdata, pk.rmask, pk.offsets, pk.shape, pk.tile = (
    rdata, None, offsets, (n, n), tile)
k = 4
mm_tile = pallas_dia._spmm_tile(pk, k)
if mm_tile is not None:
    X = jnp.ones((n, k), dtype=jnp.float32)

    def mm_step(V):
        return pallas_dia.pallas_dia_spmm(rdata, None, V, offsets,
                                          (n, n), mm_tile)

    float(jnp.sum(mm_step(X)))
    float(jnp.sum(jax.lax.fori_loop(0, 8, lambda i, V: mm_step(V), X)))

# Banded SpGEMM at a reduced size (its working set scales with the
# output band): scipy-layout ones band, eager + short loop.
ng = min(n, 1 << 22)
offs_c = tuple(sorted({a + b for a in offsets for b in offsets}))
gg_tile = pallas_dia._spgemm_tile(
    offsets, W, W, len(offs_c), np.dtype(np.float32))
if gg_tile is not None:
    band = jnp.full((W, ng), val, dtype=jnp.float32)

    def gg(b):
        return pallas_dia.pallas_dia_spgemm(
            b, band, offsets, offsets, offs_c, (ng, ng), (ng, ng),
            gg_tile)

    float(jnp.sum(gg(band)[0]))
    # Carry-dependent operand so the kernel stays INSIDE the loop
    # (the r3 fault signature is specifically kernel-in-loop).
    float(jnp.sum(jax.lax.fori_loop(
        0, 4,
        lambda i, c: c * 0.5 + gg(
            band.at[0, 0].add((c[0, 0] * 1e-30).astype(band.dtype))
        )[0][:1],
        jnp.zeros((1, ng), dtype=jnp.float32))))
print("canary-ok")
"""

# Wrapper separating Python-level bugs from device faults: a trace-time
# exception (bad shape from a future refactor, assert, dtype mismatch)
# prints a marker and exits 3, so the caller does NOT score it as a
# worker fault and silently demote the bench to a slower variant.
# Device-runtime errors (XlaRuntimeError and friends from jaxlib) keep
# the plain-failure exit: those ARE the fault surface the canary hunts.
_CANARY_WRAPPER = r"""
import sys
try:
    exec(sys.argv[2])
except Exception as e:
    import traceback
    traceback.print_exc()
    mod = (type(e).__module__ or "").lower()
    name = type(e).__name__
    # Device/runtime fault classes across jax generations: jax 0.9
    # raises jax.errors.JaxRuntimeError; older stacks raised
    # jaxlib...XlaRuntimeError.  Any RuntimeError out of a jax-owned
    # module is treated as the device side too — misclassifying a real
    # worker fault as 'trace-error' would skip the recovery probe.
    is_device = (
        name in ("XlaRuntimeError", "JaxRuntimeError")
        or "jaxlib" in mod or "xla" in mod
        or (mod.startswith("jax") and isinstance(e, RuntimeError))
    )
    if is_device:
        sys.exit(1)
    print("canary-trace-error")
    sys.exit(3)
"""


def _pallas_canary(log2n: int, timeout_s: int = 480,
                   env_extra: dict = None) -> str:
    """Run the exact banded Pallas path (eager + chained loop) in a
    throwaway subprocess: "ok" | "crash" | "timeout" | "trace-error".

    The 2026-07-31 on-chip capture showed the production kernel can
    fault the TPU worker ("TPU worker process crashed"); a fault inside
    the measurement process would cost the whole contract line, so the
    canary takes the hit instead and the caller degrades to the XLA
    band path (and to CPU when the worker doesn't come back).
    """
    import subprocess

    env = dict(os.environ)
    if env_extra:
        env.update(env_extra)
    try:
        r = subprocess.run(
            [sys.executable, "-c", _CANARY_WRAPPER, str(log2n),
             _CANARY_CODE],
            capture_output=True, text=True, timeout=timeout_s, env=env,
        )
    except subprocess.TimeoutExpired:
        return "timeout"
    if r.returncode == 3 and "canary-trace-error" in (r.stdout or ""):
        sys.stderr.write(
            "bench: canary raised a Python-level error (NOT a worker "
            "fault) — fix the composition, don't demote the variant:\n"
            + (r.stderr or "")[-2000:] + "\n"
        )
        return "trace-error"
    return "ok" if ("canary-ok" in (r.stdout or "")
                    and r.returncode == 0) else "crash"


def _select_band_variant(log2n: int, timeout_s: int) -> tuple:
    """Pick the fastest banded lowering that SURVIVES the looped
    composition on this chip, most-performant-first:

    1. Pallas kernel, Mosaic ``pltpu.roll`` lowering (622 GB/s class);
    2. Pallas kernel with DISTINCT tile-shifted x inputs and plain
       index maps (kills the aliased-operand / clamped-index-map
       suspects at ~15% extra traffic);
    3. Pallas kernel, ``jnp.roll``-in-VMEM lowering (kills the Mosaic
       roll-primitive suspect);
    4. XLA band path (``dia_spmv_fused``, 84 GB/s class) — never
       faults.

    Returns ``(verdict_log, alive)``: the env of the chosen variant is
    applied to ``os.environ`` for the phases that follow; ``alive``
    False means the worker stopped answering probes entirely.
    """
    attempts = []
    ladder = [
        ("pallas", {}),
        ("pallas-shift3", {"LEGATE_SPARSE_TPU_PALLAS_INPUTS": "distinct"}),
        ("pallas-jroll", {"LEGATE_SPARSE_TPU_PALLAS_ROLL": "xla"}),
    ]
    pinned_roll = os.environ.get("LEGATE_SPARSE_TPU_PALLAS_ROLL")
    pinned_inputs = os.environ.get("LEGATE_SPARSE_TPU_PALLAS_INPUTS")
    if pinned_roll is not None:
        # Operator pinned the lowering: probe only that rung, never
        # override the pin ("xla" -> jroll rung, anything else -> the
        # Mosaic-roll rung — labeled shift3 when the INPUTS pin means
        # that is what the inherited env actually probes).
        if pinned_roll == "xla":
            ladder = [ladder[2]]
        elif pinned_inputs == "distinct":
            ladder = [ladder[1]]
        else:
            ladder = [("pallas", {})]
    elif pinned_inputs == "distinct":
        # The canary subprocess inherits os.environ, so rung 1 would
        # probe the de-aliased variant while recording it as "pallas"
        # (and rung 2 would re-probe the identical config).  Start —
        # and label — the ladder at the shift3 rung instead.
        ladder = ladder[1:]
    for name, env_extra in ladder:
        verdict = _pallas_canary(log2n, timeout_s=timeout_s,
                                 env_extra=env_extra)
        attempts.append(f"{name}:{verdict}")
        if verdict == "ok":
            os.environ.update(env_extra)
            _persist_variant(name, env_extra)
            return attempts, True
        sys.stderr.write(
            f"bench: band canary '{name}' verdict '{verdict}'\n"
        )
        if verdict == "trace-error":
            # Python-level bug in the composition (already surfaced on
            # stderr with its traceback): the worker is alive, so skip
            # the recovery probe and try the next rung.
            continue
        # A crash/timeout usually takes the worker down with it; give
        # it one recovery probe before the next rung (the probe also
        # pins CPU if the worker never comes back).
        if not _probe_accelerator():
            os.environ["LEGATE_SPARSE_TPU_PALLAS_DIA"] = "0"
            _persist_variant("xla", {"LEGATE_SPARSE_TPU_PALLAS_DIA": "0"})
            return attempts, False
    os.environ["LEGATE_SPARSE_TPU_PALLAS_DIA"] = "0"
    _persist_variant("xla", {"LEGATE_SPARSE_TPU_PALLAS_DIA": "0"})
    return attempts, True


def _persist_variant(name: str, env_extra: dict) -> None:
    """Record the surviving band variant so LATER capture phases (pde,
    SpMV sweep — separate processes in tools/round4_capture.sh) can
    export the same env instead of re-running a possibly-faulting
    default.  Best-effort: bench works without the evidence dir."""
    try:
        os.makedirs("evidence", exist_ok=True)
        with open("evidence/band_variant.env", "w") as f:
            f.write(f"# chosen band variant: {name}\n")
            for k, v in env_extra.items():
                f.write(f"export {k}={v}\n")
    except OSError:
        pass


def _record_stream_stats(result: dict, samples: list) -> float:
    """min/median/max of the interleaved stream samples into the JSON;
    returns the median — the denominator of record.  ``stream_gbs``
    keeps its historical key (now the median) and ``stream2_gbs`` stays
    a superset-contract alias for the second sample."""
    import statistics

    med = statistics.median(samples)
    result["stream_samples"] = [round(s, 2) for s in samples]
    result["stream_gbs_min"] = round(min(samples), 2)
    result["stream_gbs_median"] = round(med, 2)
    result["stream_gbs_max"] = round(max(samples), 2)
    result["stream_gbs"] = round(med, 2)
    if len(samples) > 1:
        result["stream2_gbs"] = round(samples[1], 2)
    return med


def _gflops_cap() -> float:
    """Measured dense-matmul FLOP rate (GFLOP/s) — the box's compute
    ceiling.  Emitted so the CPU fallback ratio is decomposable into
    "provably machine-bound" vs "implementation loss" (VERDICT r4 weak
    #1): banded SpMV at 11 FMAs/element is COMPUTE-bound on a 1-core
    box where STREAM triad (1 FMA per 12 bytes) is not.  The operand is
    an orthogonal matrix, so hundreds of chained applications keep unit
    norm with zero per-iteration normalization cost."""
    import jax.numpy as jnp

    from legate_sparse_tpu.bench_timing import loop_ms_per_iter

    m = 256
    rng = np.random.default_rng(3)
    q, _ = np.linalg.qr(rng.standard_normal((m, m)))
    Q = jnp.asarray(q, dtype=jnp.float32)
    X = jnp.asarray(
        np.linalg.qr(rng.standard_normal((m, m)))[0], dtype=jnp.float32
    )
    ms = loop_ms_per_iter(lambda v: v @ Q, X, k_lo=20, k_hi=200)
    return 2.0 * m * m * m / (ms * 1e-3) / 1e9


def _band_compute_bound_ms(n: int, nnz_per_row: int,
                           gflops: float) -> float:
    """Predicted compute-bound time for one banded SpMV: W multiplies +
    (W-1) adds per output element at the measured matmul FLOP rate."""
    flops = (2 * nnz_per_row - 1) * n
    return flops / (gflops * 1e9) * 1e3


def _banded_config(sparse, n: int, nnz_per_row: int, dtype=np.float32):
    half = nnz_per_row // 2
    offsets = list(range(-half, half + 1))
    # Row sums of 1.0 keep the chained x_{t+1} = A @ x_t magnitude-stable.
    val = np.float32(1.0 / nnz_per_row)
    diagonals = [np.full(n - abs(o), val, dtype=np.float32)
                 for o in offsets]
    return sparse.diags(diagonals, offsets, shape=(n, n), format="csr",
                        dtype=dtype)


def _engine_config(sparse, n: int, nnz_per_row: int, seed: int = 7):
    """Random-column CSR with a DETERMINISTIC nnz and one heavy row:
    random columns defeat band detection and the heavy row blows the
    ELL (and BSR) budgets, so the matrix is engine-eligible on every
    platform — on TPU the engine declines ELL-packable matrices (the
    roofline gather path wins there), and a uniform-row config would
    silently skip the whole phase.  nnz = nnz_per_row * (n + 63)
    exactly, so the shape buckets — and the golden-gated plan
    hit/miss counts — are the same on every machine.  ``seed`` varies
    the column pattern/values only, never the nnz: different seeds
    yield DISTINCT matrices in the SAME shape bucket (the gateway
    phase packs them into one stacked dispatch)."""
    rng = np.random.default_rng(seed)
    counts = np.full(n, nnz_per_row, dtype=np.int64)
    counts[0] = min(64 * nnz_per_row, n)   # ELL-budget breaker
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    nnz = int(indptr[-1])
    indices = rng.integers(0, n, size=nnz).astype(np.int32)
    row_ids = np.repeat(np.arange(n), counts)
    order = np.lexsort((indices, row_ids))
    indices = indices[order]
    data = rng.standard_normal(nnz).astype(np.float32)
    return sparse.csr_array((data, indices, indptr), shape=(n, n))


def _dist2d_config(sparse, n: int, nnz_per_row: int, seed: int = 7):
    """Random-column CSR, symmetrized and diagonally dominated: the
    random columns defeat band detection — so the 1-D baseline pays
    the all_gather x realization a non-banded matrix forces at scale,
    exactly the fight the 2-d-block layout exists to win — while
    A + A^T + 2I keeps the fixed-iteration CG drill numerically tame.
    nnz is a pure function of (n, seed), so the shard shapes — and
    the ``dist2d_*_comm_bytes`` fields derived from them — are
    deterministic and golden-pinnable."""
    import scipy.sparse as sp

    rng = np.random.default_rng(seed)
    nnz = n * max(nnz_per_row // 2, 1)
    rows = rng.integers(0, n, size=nnz)
    cols = rng.integers(0, n, size=nnz)
    vals = rng.standard_normal(nnz).astype(np.float32) / nnz_per_row
    A = sp.coo_array((vals, (rows, cols)), shape=(n, n)).tocsr()
    A = (A + A.T + 2.0 * sp.eye(n, format="csr")).tocsr()
    return sparse.csr_array(
        (A.data.astype(np.float32), A.indices.astype(np.int32),
         A.indptr), shape=A.shape)


def _irregular_config(sparse, n: int, nnz_per_row: int):
    """Random-sparsity CSR with skewed row lengths: defeats band/ELL
    detection (one heavy row) so the gather/segment-sum path runs."""
    rng = np.random.default_rng(0)
    counts = rng.integers(1, 2 * nnz_per_row, size=n).astype(np.int64)
    counts[0] = min(64 * nnz_per_row, n)  # heavy row blows the ELL budget
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    nnz = int(indptr[-1])
    indices = rng.integers(0, n, size=nnz).astype(np.int32)
    # Sort column indices within each row (canonical CSR).
    row_ids = np.repeat(np.arange(n), counts)
    order = np.lexsort((indices, row_ids))
    indices = indices[order]
    data = np.ones(nnz, dtype=np.float32)
    return sparse.csr_array((data, indices, indptr), shape=(n, n))


def _spmv_bytes(A, x) -> int:
    """Byte-traffic model matching the kernel that actually runs —
    delegates to ``csr_array.spmv_traffic_bytes`` (single source of
    truth with the obs spans) after warming the structure caches the
    dispatch would build."""
    if A._get_dia() is None:
        A._get_ell()
    return A.spmv_traffic_bytes(x)


def _time_spmv_ms(A, x, normalize: bool, k_lo: int, k_hi: int) -> float:
    """Chained A @ x per-iteration time; ``normalize`` rescales between
    iterations for matrices whose row sums aren't ~1 (adds 2n words of
    traffic, accounted by the caller)."""
    import jax
    import jax.numpy as jnp

    from legate_sparse_tpu.bench_timing import loop_ms_per_iter

    # Build structure caches eagerly (outside the trace).
    _ = A @ x

    if normalize:
        def step(v):
            y = A @ v
            return y * jax.lax.rsqrt(jnp.mean(y * y) + 1e-20)
    else:
        def step(v):
            return A @ v

    return loop_ms_per_iter(step, x, k_lo=k_lo, k_hi=k_hi)


def _cpu_roofline_items(sparse, A, x, dt_ms: float, bw_ms: float,
                        compute_ms: float) -> dict:
    """Named, MEASURED loss terms for a sub-0.7 ``cpu_roofline_ratio``
    — where the bytes actually go, instead of a bare fraction:

    - ``bound_bw_ms`` / ``bound_compute_ms``: the two roofline legs the
      ratio's numerator is the max of.
    - ``mask_ms``: hole-mask traffic + per-slot select (0.0 when the
      band has no holes — the headline config's band is full).
    - ``pad_alloc_ms``: the padded single-pass form's allocation loss
      vs the interior/edge-split kernel (what ``dia-xla-nopad`` saves).
    - ``segment_sum_ms`` vs ``shifted_add_ms`` at ``segment_sum_n``
      rows: the gather/segment-sum CSR path against the banded
      shifted-add on the same structure — the format choice the dia
      dispatch makes, quantified (measured at a reduced size; the
      segment-sum path is orders of magnitude off and would blow the
      phase budget at full n).
    """
    import jax.numpy as jnp

    from legate_sparse_tpu.bench_timing import loop_ms_per_iter
    from legate_sparse_tpu.ops import dia_ops
    from legate_sparse_tpu.ops import spmv as spmv_ops

    items = {
        "measured_ms": round(dt_ms, 4),
        "bound_bw_ms": round(bw_ms, 4),
        "bound_compute_ms": round(compute_ms, 4),
    }
    dia = A._get_dia()
    if dia is not None:
        data, offs, mask = dia
        shape = A.shape
        ms_nopad = loop_ms_per_iter(
            lambda v: dia_ops.dia_spmv_nopad(data, mask, v, offs, shape),
            x, k_lo=3, k_hi=12)
        items["shifted_add_ms"] = round(ms_nopad, 4)
        if mask is not None:
            ms_nomask = loop_ms_per_iter(
                lambda v: dia_ops.dia_spmv_nopad(data, None, v, offs,
                                                 shape),
                x, k_lo=3, k_hi=12)
            items["mask_ms"] = round(ms_nopad - ms_nomask, 4)
        else:
            items["mask_ms"] = 0.0
        dpad, mpad = A._get_dia_fused()
        ms_fused = loop_ms_per_iter(
            lambda v: dia_ops.dia_spmv_fused(dpad, mpad, v, offs, shape),
            x, k_lo=3, k_hi=12)
        items["pad_alloc_ms"] = round(ms_fused - ms_nopad, 4)
    # Segment-sum referee at a reduced size on the same band structure.
    n_seg = max(min(A.shape[0] // 64, 1 << 18), 1 << 14)
    nnz_per_row = max(len(dia[1]) if dia is not None else 11, 1)
    A_seg = _banded_config(sparse, n_seg, nnz_per_row)
    x_seg = jnp.full((n_seg,), 1.0, dtype=jnp.float32)
    rid = A_seg._get_row_ids()
    items["segment_sum_n"] = n_seg
    items["segment_sum_ms"] = round(loop_ms_per_iter(
        lambda v: spmv_ops.csr_spmv_rowids(
            A_seg.data, A_seg.indices, rid, v, n_seg),
        x_seg, k_lo=2, k_hi=6, k_cap=12), 4)
    dia_seg = A_seg._get_dia()
    if dia_seg is not None:
        items["shifted_add_seg_ms"] = round(loop_ms_per_iter(
            lambda v: dia_ops.dia_spmv_nopad(
                dia_seg[0], dia_seg[2], v, dia_seg[1], A_seg.shape),
            x_seg, k_lo=3, k_hi=12), 4)
    return items


# Bench JSON schema version: bumped whenever the key set or a key's
# meaning changes (BASELINE.md documents the history; the superset
# contract still holds within a version).  7 = comm/mem ledger fields
# + dist phase + schema_version itself.  8 = execution-engine phase
# (engine_cold_ms / engine_warm_ms / engine_batched_ms_per_req +
# golden-gated engine_plan_hits / engine_plan_misses).  9 =
# resilience phase (docs/RESILIENCE.md): deterministic fault drill
# recording golden-gated resil_retries / resil_shed /
# resil_breaker_trips / resil_faults_injected + the recovered-vs-clean
# latency pair resil_clean_ms / resil_recovered_ms.  10 = saturation
# phase (obs v3, docs/OBSERVABILITY.md): closed-loop offered-load
# sweep against the request executor — per-level p50/p99 latency,
# shed count, mean batch occupancy and throughput in ``saturation``,
# top-level ``saturation_p50_ms``/``saturation_p99_ms`` (highest
# level) and the golden-gated deterministic totals
# ``saturation_requests`` / ``saturation_shed`` /
# ``saturation_batched_requests``.  11 = autotune phase
# (docs/AUTOTUNER.md): verdict-routed irregular SpMV on a seeded
# power-law matrix — irregular_spmv_ms / irregular_csr_ms /
# irregular_spmv_speedup / irregular_spmv_path + the golden-gated
# autotune_verdicts.  12 = gateway fairness phase (docs/ENGINE.md):
# 3-tenant admission-gateway sweep (WFQ packing stage + flood stage)
# with the golden-gated deterministic totals ``gateway_requests`` /
# ``gateway_dispatches`` / ``gateway_packed`` /
# ``gateway_rejected_queue_full`` / per-tenant served/shed.  13 =
# dist-2d phase (docs/DIST.md): the same all-device mesh factored as
# a (rows, cols) grid with the auto layout router — golden-gated
# deterministic ``dist2d_spmv_comm_bytes`` /
# ``dist2d_spmv_1d_comm_bytes`` / ``dist2d_cg_comm_bytes`` /
# ``dist2d_spgemm_comm_bytes`` / ``dist2d_spgemm_1d_comm_bytes``
# (the 1-D fields are the equal-device-count baseline the 2-D layout
# must beat) plus ``dist2d_layout`` / ``dist2d_grid`` /
# ``dist2d_cg_iters`` and the timing field ``dist2d_spmv_ms``.  14 =
# obs-overhead probe (docs/OBSERVABILITY.md): the SpMV micro-loop
# re-timed with spans on vs off — ``obs_overhead_pct`` records the
# toggled tracing tax on the hot path (clamped at 0; the always-on
# counters/histograms appear in both arms by design).  15 =
# compressed-storage byte columns (``csr_array.compress``): the
# deterministic per-nnz traffic models ``spmv_bytes_per_nnz`` /
# ``spmv_bytes_per_nnz_bf16`` (golden-pinned exactly), the
# compressed pde anchor ``pde_bytes_per_iter_bf16`` /
# ``pde_ms_per_iter_bf16`` / ``pde_bytes_ratio`` (full lane), and
# the 2-D dist panel field ``dist2d_spmv_comm_bytes_bf16`` — bf16
# panels + int16 block-local indices, exactly half the f32 panel
# bytes, golden-gated through the 1% ``*_comm_bytes`` band.  16 =
# recovery phase (docs/RESILIENCE.md): a deterministic device-loss
# drill mid-``dist_cg`` on the all-device mesh — checkpoint saves at
# the conv-fetch cadence, one seeded loss, shrink -> reshard ->
# restore -> resume — recording the golden-pinned exact
# ``resil_ckpt_saves`` / ``resil_recoveries`` / ``resil_restored``
# plus the measured ``resil_reshard_bytes`` and the timing pair
# ``recovery_clean_ms`` / ``recovery_recovered_ms``.  17 = graph
# phase (docs/GRAPH.md): the four semiring algorithms (BFS or-and,
# SSSP min-plus, CC min-label, PageRank plus-times) on one seeded
# R-MAT matrix over the all-device mesh — golden-pinned exact
# ``graph_n`` / ``graph_nnz`` / ``graph_<alg>_iters`` plus the
# comm-ledger deltas ``graph_<alg>_comm_bytes`` (the
# ``*_comm_bytes`` band) and the informational timing field
# ``graph_ms``.  18 = multi-tenant attribution phase
# (docs/OBSERVABILITY.md): a 3-tenant gateway load plus dist SpMV
# dispatches under tenant contexts and a packed multi-tenant attrib
# scope, with the attribution ledger armed — golden-pinned exact
# ``attrib_requests`` / ``attrib_tenants`` / ``attrib_conserved`` /
# ``attrib_tenant_bytes`` and the comm-ledger delta
# ``attrib_comm_bytes`` (the ``*_comm_bytes`` band), plus the
# informational timing field ``attrib_ms``.  19 = elastic-placement
# phase (docs/PLACEMENT.md): two placed tenants served through the
# gateway's placement routing, a burning-tenant carve planned by the
# pure ``propose()`` over a fixed sensor snapshot and executed by the
# live-migration registry — golden-pinned exact
# ``placement_migrations`` / ``placement_reshard_bytes`` /
# ``placement_routes`` / per-tenant served counts, plus the
# informational timing field ``placement_ms``.  20 = streaming-
# mutation phase (docs/MUTATION.md): a DeltaCSR served through the
# gateway's delta routing while a seeded ``gallery.mutation_stream``
# update storm lands in the side-buffer, then one background
# compaction with an atomic version swap and a post-swap serving
# round — golden-pinned exact ``mutation_updates`` /
# ``mutation_applied`` / ``mutation_merged`` /
# ``mutation_compactions`` / ``mutation_version_swaps`` /
# ``mutation_served`` / ``mutation_routes``, plus the timing pair
# ``mutation_ms`` / ``mutation_compaction_ms`` (serve-while-mutating
# wall time and the off-path merge cost).
SCHEMA_VERSION = 20


def main() -> None:
    import argparse
    import time as _time_mod

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--smoke", action="store_true",
        help="CI smoke lane: pin an 8-virtual-device CPU mesh, skip "
             "the accelerator probe/canary and the heavyweight phases, "
             "shrink everything to seconds — exists so the obs/comm "
             "wiring and the bench JSON schema are exercised on every "
             "tier-1 run, not once per capture round.")
    args, _ = ap.parse_known_args()
    smoke = (args.smoke
             or os.environ.get("LEGATE_SPARSE_TPU_BENCH_SMOKE") == "1")

    t_start = _time_mod.perf_counter()

    def past_deadline(result, phase: str) -> bool:
        elapsed = _time_mod.perf_counter() - t_start
        if elapsed > deadline_s:
            result.setdefault("skipped_after_deadline", []).append(phase)
            return True
        return False

    canary = None
    deadline_s = DEADLINE_S
    if smoke:
        # Deterministic hermetic lane: no probe subprocesses, no
        # canary ladder, an 8-way virtual CPU mesh so the dist phase
        # moves real (predicted) bytes over a real collective program.
        # Inherited env must not change the program away from the
        # committed golden: JAX_PLATFORMS is overridden (a tpu pin
        # would swap the backend), the virtual device count is forced
        # to EXACTLY 8 (pin_cpu alone keeps a larger inherited count,
        # which would change dist_shards and every comm prediction),
        # and the deadline env knob is ignored (a short inherited
        # deadline would drop the dist phase and its gated fields).
        import re as _re

        from legate_sparse_tpu._platform import pin_cpu

        flags = _re.sub(r"--xla_force_host_platform_device_count=\d+",
                        "", os.environ.get("XLA_FLAGS", ""))
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
        pin_cpu(8)
        deadline_s = 1800.0
        use_accel = False
    else:
        use_accel = _probe_accelerator()
        if (use_accel
                and os.environ.get("LEGATE_SPARSE_TPU_PALLAS_DIA",
                                   "1") != "0"
                and os.environ.get("LEGATE_SPARSE_TPU_BENCH_CANARY",
                                   "1") != "0"):
            log2n = int(os.environ.get(
                "LEGATE_SPARSE_TPU_BENCH_LOG2_ROWS", "24"))
            canary_timeout = int(os.environ.get(
                "LEGATE_SPARSE_TPU_BENCH_CANARY_TIMEOUT", "480"))
            attempts, use_accel = _select_band_variant(log2n,
                                                       canary_timeout)
            canary = ",".join(attempts)
    if not use_accel and not smoke:
        from legate_sparse_tpu._platform import pin_cpu

        pin_cpu()

    import jax
    import jax.numpy as jnp

    import legate_sparse_tpu as sparse
    from legate_sparse_tpu import obs

    obs_requested = obs.enabled()

    try:
        platform = jax.devices()[0].platform
    except RuntimeError as e:  # probe passed but in-process init failed
        sys.stderr.write(f"bench: backend init failed in-process: {e}\n")
        from legate_sparse_tpu._platform import pin_cpu

        pin_cpu()
        platform = jax.devices()[0].platform

    result = {
        "metric": "csr_spmv_bandwidth",
        "value": None,
        "unit": "GB/s",
        "vs_baseline": None,
        "platform": platform,
        "schema_version": SCHEMA_VERSION,
    }
    if smoke:
        result["smoke"] = True
    if canary is not None:
        result["pallas_canary"] = canary

    # On CPU shrink everything: the fallback exists to record *a* number.
    default_log2 = "24" if platform != "cpu" else "20"
    if smoke:
        # The hermetic lane ignores the size/skip env knobs outright:
        # an inherited LOG2_ROWS or SKIP_DIST must not change the
        # program (and so the deterministic *_comm_bytes) away from
        # the committed golden.
        n = 1 << 12
    else:
        n = 1 << int(os.environ.get("LEGATE_SPARSE_TPU_BENCH_LOG2_ROWS",
                                    default_log2))
    nnz_per_row = 11

    # Interleaved stream sampling: 2 samples before the SpMV phase, 3
    # after it, median of the 5 as the denominator of record.  A single
    # pre-workload sample (r05's method) moved 25%+ against the phases
    # it was supposed to referee; the bracketing median samples the
    # machine the numerators actually ran on.  CPU lane only: on-chip
    # HBM is stable run-to-run (r3-r5 captures) and each tunnel-remote
    # 512 MB triad sample costs real wall time against the phase
    # deadline, so TPU keeps the single measurement.
    stream = None
    stream_samples = []
    n_pre, n_post = (2, 3) if platform == "cpu" else (1, 0)
    stream_lanes = 26
    if smoke:
        # One bracketing pair over a 16 MB working set: enough to give
        # the JSON a spread for the regression gate's noise band, small
        # enough to keep the lane in seconds.
        n_pre, n_post = 1, 1
        stream_lanes = 22

    from legate_sparse_tpu.bench_timing import triad_gbs

    def _sample_stream(k: int) -> None:
        for _ in range(k):
            try:
                stream_samples.append(triad_gbs(log2_lanes=stream_lanes))
            except Exception as e:
                sys.stderr.write(f"bench: stream sample failed: {e!r}\n")

    _sample_stream(n_pre)
    if stream_samples:
        stream = _record_stream_stats(result, stream_samples)

    A = x = dt_ms = None
    try:
        with obs.span("bench.spmv") as _sp, \
                obs.memory.watermark("bench.spmv"):
            A = _banded_config(sparse, n, nnz_per_row)
            x = jnp.full((n,), 1.0, dtype=jnp.float32)
            dt_ms = _time_spmv_ms(A, x, normalize=False, k_lo=5, k_hi=35)
            if _sp is not None:
                _sp.set(nnz=A.nnz, bytes=_spmv_bytes(A, x),
                        rows=n, spmv_ms=round(dt_ms, 4))
    except Exception as e:
        sys.stderr.write(f"bench: banded config failed: {e!r}\n")
        result["error"] = repr(e)[:300]

    _sample_stream(n_post)
    if stream_samples:
        stream = _record_stream_stats(result, stream_samples)

    if dt_ms is not None:
        bw = _spmv_bytes(A, x) / (dt_ms * 1e-3) / 1e9
        result["value"] = round(bw, 2)
        result["spmv_ms"] = round(dt_ms, 4)
        result["path"] = (
            "dia" if A._get_dia() is not None
            else "ell" if A._get_ell() is not None else "csr"
        )
        # Storage-traffic trajectory columns (schema 15): the byte
        # model per nonzero, canonical f32 vs compressed storage
        # (``csr_array.compress``: bf16 values + narrowed indices)
        # against the same f32 operand.  Deterministic — the model
        # reads actual storage itemsizes — so the smoke golden pins
        # both exactly.
        result["spmv_bytes_per_nnz"] = round(
            _spmv_bytes(A, x) / A.nnz, 4)
        try:
            C_s = A.compress()
            result["spmv_bytes_per_nnz_bf16"] = round(
                _spmv_bytes(C_s, x) / C_s.nnz, 4)
            del C_s
        except Exception as e:
            sys.stderr.write(
                f"bench: compressed spmv bytes failed: {e!r}\n")
        if stream:
            frac = round(bw / stream, 4)
            # The contract metric must not be satisfiable by the CPU
            # fallback: report null off-TPU, fallback number separately.
            if platform != "cpu":
                result["vs_baseline"] = frac
            else:
                result["cpu_vs_baseline"] = frac
        if platform == "cpu" and not smoke:
            # (Skipped in --smoke: the gflops cap + itemized roofline
            # cost seconds and the smoke golden gates only the
            # deterministic comm/schema fields.)
            # Decompose the fallback ratio (VERDICT r4 weak #1): the
            # banded SpMV is compute-bound on this box, so the honest
            # denominator for spmv_ms is max(bandwidth time, compute
            # time).  cpu_roofline_ratio ~1.0 = machine-bound; below
            # that = implementation loss.
            try:
                gf = _gflops_cap()
                result["cpu_gflops_cap"] = round(gf, 2)
                pred = _band_compute_bound_ms(n, nnz_per_row, gf)
                result["spmv_compute_bound_ms"] = round(pred, 4)
                if stream:
                    bw_ms = _spmv_bytes(A, x) / (stream * 1e9) * 1e3
                    bound = max(pred, bw_ms)
                    ratio = round(bound / dt_ms, 4)
                    result["cpu_roofline_ratio"] = ratio
                    if ratio < 0.7:
                        # Sub-roofline ratios must arrive itemized into
                        # named, measured loss terms — "0.41, shrug"
                        # (r05) is not actionable evidence.
                        try:
                            result["cpu_roofline_items"] = (
                                _cpu_roofline_items(
                                    sparse, A, x, dt_ms, bw_ms, pred))
                        except Exception as e:
                            sys.stderr.write(
                                f"bench: roofline items failed: {e!r}\n")
            except Exception as e:
                sys.stderr.write(f"bench: gflops cap failed: {e!r}\n")

    # Phase: observability overhead (obs v4, schema 14).  The same
    # SpMV micro-loop timed with spans on vs off — the explicit
    # ``bench.obs_probe`` span per iteration is the toggled cost being
    # measured (counters/histograms are always-on by design and appear
    # in both arms).  Negative deltas are measurement noise, clamped:
    # the field answers "how much does OBS=1 tax the hot path", not
    # "which arm won the coin flip".
    if A is not None and dt_ms is not None:
        try:
            from legate_sparse_tpu.bench_timing import loop_ms_per_iter

            def _obs_probe_step(v):
                with obs.span("bench.obs_probe"):
                    return A @ v

            was_on = obs.enabled()
            try:
                obs.enable()
                ms_on = loop_ms_per_iter(_obs_probe_step, x,
                                         k_lo=3, k_hi=15)
                obs.disable()
                ms_off = loop_ms_per_iter(_obs_probe_step, x,
                                          k_lo=3, k_hi=15)
            finally:
                (obs.enable if was_on else obs.disable)()
            if ms_off > 0:
                result["obs_overhead_pct"] = round(
                    max(0.0, (ms_on - ms_off) / ms_off * 100.0), 2)
        except Exception as e:
            sys.stderr.write(f"bench: obs overhead probe failed: "
                             f"{e!r}\n")

    # Solver evidence in the same JSON line: CG ms/iter on the pde
    # operator (reference examples/pde.py headline).  Two maxiter
    # variants, host-fetch synced; the delta cancels fixed costs.
    if (os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_CG", "0") != "1"
            and not smoke
            and not past_deadline(result, "cg")):
        try:
            import time as _time

            import legate_sparse_tpu.linalg as linalg

            grid = 1 << (10 if platform != "cpu" else 7)
            ng = grid * grid
            main = np.full(ng, 4.0, np.float32)
            off1 = np.full(ng - 1, -1.0, np.float32)
            off1[np.arange(1, grid) * grid - 1] = 0.0
            offn = np.full(ng - grid, -1.0, np.float32)
            A_cg = sparse.diags(
                [main, off1, off1, offn, offn],
                [0, 1, -1, grid, -grid],
                shape=(ng, ng), format="csr", dtype=np.float32,
            )
            b = np.ones(ng, np.float32)

            def timed(maxiter):
                best = float("inf")
                for rep in range(3):
                    t0 = _time.perf_counter()
                    xs, _ = linalg.cg(A_cg, b, rtol=0.0, maxiter=maxiter)
                    _ = float(np.asarray(xs[0]))
                    if rep:
                        best = min(best, _time.perf_counter() - t0)
                return best

            with obs.span("bench.cg") as _sp:
                if _sp is not None:
                    _sp.set(nnz=A_cg.nnz, rows=ng,
                            bytes=_spmv_bytes(
                                A_cg, jnp.ones((ng,), jnp.float32)))
                t1, t2 = timed(100), timed(300)
                if t2 > t1:
                    result["cg_grid"] = f"{grid}x{grid}"
                    result["cg_ms_per_iter"] = round(
                        (t2 - t1) / 200 * 1e3, 4
                    )
                    if _sp is not None:
                        _sp.set(ms_per_iter=result["cg_ms_per_iter"])
                else:
                    sys.stderr.write(
                        f"bench: cg timing unresolvable "
                        f"(t100={t1:.4f}s, t300={t2:.4f}s)\n"
                    )
        except Exception as e:
            sys.stderr.write(f"bench: cg config failed: {e!r}\n")

    if (os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_IRREGULAR", "0") != "1"
            and not smoke
            and not past_deadline(result, "irregular")):
        try:
            A_ir = _irregular_config(sparse, max(n // 16, 1 << 16),
                                     nnz_per_row)
            x_ir = jnp.ones((A_ir.shape[0],), dtype=jnp.float32)
            dt_ms = _time_spmv_ms(A_ir, x_ir, normalize=True,
                                  k_lo=2, k_hi=12)
            extra = 2 * 4 * A_ir.shape[0]  # normalize read+write
            bw_ir = (_spmv_bytes(A_ir, x_ir) + extra) / (dt_ms * 1e-3) / 1e9
            result["irregular_gbs"] = round(bw_ir, 2)
            if stream:
                result["irregular_frac"] = round(bw_ir / stream, 4)
        except Exception as e:
            sys.stderr.write(f"bench: irregular config failed: {e!r}\n")

    # Block-sparse (BSR) irregular path: moderate-density random matrix
    # through the MXU block kernel (ops/bsr.py).  TPU only — interpret
    # mode is pure-Python slow and measures nothing.
    if (platform == "tpu"
            and os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_BSR",
                               "0") != "1"
            and not past_deadline(result, "bsr")):
        try:
            from legate_sparse_tpu.bench_timing import loop_ms_per_iter
            from legate_sparse_tpu.ops.bsr import BsrStructure, bsr_pack

            import scipy.sparse as sp

            nb_n = 1 << 13
            A_sp = sp.random(nb_n, nb_n, density=0.05, format="csr",
                             random_state=np.random.default_rng(1),
                             dtype=np.float32)
            pack = bsr_pack(A_sp.data, A_sp.indices, A_sp.indptr,
                            A_sp.shape, max_expand=1e9)
            st = BsrStructure(*pack, nb_n, nb_n)
            xb = jnp.ones((nb_n,), jnp.float32)
            ms = loop_ms_per_iter(
                lambda v: st.matvec(v, interpret=False), xb,
                k_lo=3, k_hi=13,
            )
            result["bsr_ms"] = round(ms, 4)
            # CSR-equivalent useful bytes (value + index per nnz).
            result["bsr_gbs"] = round(
                A_sp.nnz * 8 / (ms * 1e-3) / 1e9, 2
            )
            result["bsr_stream_gbs"] = round(
                st.nblocks * 128 * 128 * 4 / (ms * 1e-3) / 1e9, 1
            )
        except Exception as e:
            sys.stderr.write(f"bench: bsr config failed: {e!r}\n")

    # Banded SpGEMM end-to-end (BASELINE config 4, reference
    # ``examples/spgemm_microbenchmark.py:74-79``).  Host-coupled (nnz
    # size oracle), so wall-time with a true result fetch.
    if (os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_SPGEMM", "0") != "1"
            and not smoke
            and not past_deadline(result, "spgemm")):
        try:
            import time as _time

            n_gm = 1 << (20 if platform != "cpu" else 16)
            with obs.span("bench.spgemm") as _sp, \
                    obs.memory.watermark("bench.spgemm"):
                A_gm = _banded_config(sparse, n_gm, nnz_per_row)
                best = float("inf")
                for rep in range(3):
                    t0 = _time.perf_counter()
                    C = A_gm @ A_gm
                    _ = float(np.asarray(C.data[0]))
                    if rep:
                        best = min(best, _time.perf_counter() - t0)
                if _sp is not None:
                    itm = C.dtype.itemsize
                    _sp.set(n=n_gm, nnz=C.nnz,
                            bytes=(2 * A_gm.nnz + C.nnz) * itm,
                            spgemm_ms=round(best * 1e3, 2))
            result["spgemm_n"] = n_gm
            result["spgemm_ms"] = round(best * 1e3, 2)
            # Tracked referee (VERDICT r4 weak #3): host scipy on the
            # SAME matrix, same box — the only way to tell shared-VM
            # noise from a real regression round over round.
            try:
                import scipy.sparse as _sp

                A_host = _sp.csr_matrix(
                    (np.asarray(A_gm.data), np.asarray(A_gm.indices),
                     np.asarray(A_gm.indptr)), shape=A_gm.shape)
                best_sp = float("inf")
                for rep in range(3):
                    t0 = _time.perf_counter()
                    _C = A_host @ A_host
                    if rep:
                        best_sp = min(best_sp,
                                      _time.perf_counter() - t0)
                result["spgemm_scipy_ms"] = round(best_sp * 1e3, 2)
                result["spgemm_vs_scipy"] = round(
                    best_sp / max(best, 1e-9), 4
                )
            except Exception as e:
                sys.stderr.write(f"bench: scipy spgemm ref: {e!r}\n")
        except Exception as e:
            sys.stderr.write(f"bench: spgemm config failed: {e!r}\n")

    # GMG-preconditioned CG ms/iter (BASELINE config 5, reference
    # ``examples/gmg.py:397-417``) through the package-native
    # distributed hierarchy on a 1-device mesh (the same code path that
    # scales out).  Two maxiter variants; the delta cancels fixed costs.
    if (os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_GMG", "0") != "1"
            and not smoke
            and not past_deadline(result, "gmg")):
        try:
            import time as _time

            from legate_sparse_tpu.parallel import (
                DistGMG, dist_cg, make_row_mesh, shard_csr,
            )

            grid = 1 << (9 if platform != "cpu" else 6)
            ngm = grid * grid
            main_d = np.full(ngm, 4.0, np.float32)
            off1 = np.full(ngm - 1, -1.0, np.float32)
            off1[np.arange(1, grid) * grid - 1] = 0.0
            offn = np.full(ngm - grid, -1.0, np.float32)
            A_g = sparse.diags(
                [main_d, off1, off1, offn, offn],
                [0, 1, -1, grid, -grid],
                shape=(ngm, ngm), format="csr", dtype=np.float32,
            )
            mesh1 = make_row_mesh(1)
            with obs.span("bench.gmg") as _sp, \
                    obs.memory.watermark("bench.gmg"):
                dA_g = shard_csr(A_g, mesh=mesh1)
                gmg = DistGMG(dA_g, levels=3)
                b_g = np.ones(ngm, np.float32)
                if _sp is not None:
                    _sp.set(nnz=A_g.nnz, rows=ngm,
                            bytes=_spmv_bytes(
                                A_g, jnp.ones((ngm,), jnp.float32)),
                            gmg_cycle_comm_bytes=gmg.cycle_comm_bytes)

                def timed_gmg(maxiter):
                    best = float("inf")
                    for rep in range(3):
                        t0 = _time.perf_counter()
                        xs, _ = dist_cg(dA_g, b_g, M=gmg.cycle,
                                        rtol=0.0, maxiter=maxiter)
                        _ = float(np.asarray(xs[0]))
                        if rep:
                            best = min(best, _time.perf_counter() - t0)
                    return best

                # Robust metric first: chained V-cycle applications (the
                # preconditioner IS the GMG work; magnitude-normalized so
                # hundreds of chained cycles stay finite).  The CG-delta
                # metric can go unresolvable when f32 GMG-CG hits an
                # exactly-zero residual before the low trip count and
                # stops despite rtol=0.
                from legate_sparse_tpu.bench_timing import loop_ms_per_iter
                from legate_sparse_tpu.parallel.dist_csr import shard_vector

                bs = shard_vector(b_g, mesh1, dA_g.rows_padded)

                def cycle_step(v):
                    y = gmg.cycle(v)
                    return y * jax.lax.rsqrt(jnp.mean(y * y) + 1e-20)

                result["gmg_grid"] = f"{grid}x{grid}"
                try:
                    ms_cycle = loop_ms_per_iter(cycle_step, bs, k_lo=3,
                                                k_hi=13)
                    result["gmg_cycle_ms"] = round(ms_cycle, 4)
                except RuntimeError as e:
                    sys.stderr.write(f"bench: gmg cycle timing: {e}\n")

                t1, t2 = timed_gmg(20), timed_gmg(60)
                if t2 > t1:
                    result["gmg_cg_ms_per_iter"] = round(
                        (t2 - t1) / 40 * 1e3, 4
                    )
                else:
                    sys.stderr.write(
                        f"bench: gmg cg timing unresolvable "
                        f"(t20={t1:.4f}s, t60={t2:.4f}s); gmg_cycle_ms "
                        f"is the metric of record for this run\n"
                    )
        except Exception as e:
            sys.stderr.write(f"bench: gmg config failed: {e!r}\n")

    # Distributed phase over ALL visible devices (virtual 8-way CPU
    # mesh in --smoke): the collective program the multi-chip scaling
    # story rides on, with its interconnect bytes priced by the comm
    # ledger (obs/comm.py) and recorded as bench fields — the
    # regression gate treats *_comm_bytes as deterministic, so a code
    # change that silently inflates the collective volume fails
    # tools/bench_compare.py even when the timing noise would hide it.
    if ((smoke
         or os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_DIST",
                           "0") != "1")
            and not past_deadline(result, "dist")):
        try:
            from legate_sparse_tpu.bench_timing import loop_ms_per_iter
            from legate_sparse_tpu.parallel import (
                dist_cg, make_row_mesh, shard_csr,
            )
            from legate_sparse_tpu.parallel.dist_csr import (
                cg_comm_volumes, dist_spmv, shard_vector,
                spmv_comm_volumes,
            )

            mesh_d = make_row_mesh()
            R_d = int(mesh_d.shape["rows"])
            n_d = 1 << (12 if smoke
                        else (22 if platform != "cpu" else 16))
            with obs.span("bench.dist") as _sp, \
                    obs.memory.watermark("bench.dist"):
                A_d = _banded_config(sparse, n_d, nnz_per_row)
                dA = shard_csr(A_d, mesh=mesh_d)
                x_d = shard_vector(np.ones(n_d, np.float32), mesh_d,
                                   dA.rows_padded)
                _ = float(jnp.sum(dist_spmv(dA, x_d)))  # compile+warm
                vols_d = spmv_comm_volumes(dA, dA.rows_padded // R_d, 4)
                result["dist_shards"] = R_d
                result["dist_spmv_comm_bytes"] = sum(vols_d.values())
                try:
                    ms_d = loop_ms_per_iter(
                        lambda v: dist_spmv(dA, v), x_d,
                        k_lo=2, k_hi=8 if smoke else 16,
                    )
                    result["dist_spmv_ms"] = round(ms_d, 4)
                except RuntimeError as e:
                    sys.stderr.write(f"bench: dist spmv timing: {e}\n")
                # Fixed-iteration CG (rtol=0 never converges early):
                # the iteration count — and so the predicted comm
                # volume — is deterministic across machines.
                maxit = 8 if smoke else 25
                xs_d, it_d = dist_cg(dA, np.ones(n_d, np.float32),
                                     rtol=0.0, maxiter=maxit)
                _ = float(np.asarray(xs_d[0]))
                it_d = int(it_d)
                cg_vols, _cg_calls = cg_comm_volumes(dA, 4, it_d)
                result["dist_cg_iters"] = it_d
                result["dist_cg_comm_bytes"] = sum(cg_vols.values())
                if _sp is not None:
                    _sp.set(shards=R_d, rows=n_d,
                            comm_bytes=(sum(vols_d.values())
                                        + sum(cg_vols.values())))
            result["comm_total_bytes"] = int(
                obs.counters.get("comm.total_bytes"))
        except Exception as e:
            sys.stderr.write(f"bench: dist phase failed: {e!r}\n")

    # Distributed 2-d-block phase (schema 13, docs/DIST.md): the same
    # all-device mesh factored as a (rows, cols) grid, on a NON-banded
    # matrix — the case where the 1-D layout degenerates to a full
    # all_gather of x and the communication-avoiding 2-D program
    # (x panels broadcast along mesh rows, partial products
    # reduce-scattered along mesh columns) wins.  Both the 2-D fields
    # and the equal-device-count 1-D baselines are recorded so the
    # golden pins the WIN, not just the totals; the auto router's
    # ``shard_csr.routing`` event cites both predictions.
    if ((smoke
         or os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_DIST",
                           "0") != "1")
            and not past_deadline(result, "dist2d")):
        try:
            from legate_sparse_tpu.bench_timing import loop_ms_per_iter
            from legate_sparse_tpu.parallel import (
                dist_cg, dist_spgemm, make_grid_mesh, make_row_mesh,
                shard_csr,
            )
            from legate_sparse_tpu.parallel.dist_csr import (
                cg_comm_volumes, dist_spmv, shard_vector,
                spmv_comm_volumes,
            )

            def _spgemm_ledger() -> int:
                return sum(
                    v for k, v in obs.counters.snapshot().items()
                    if k.startswith("comm.dist_spgemm.")
                    and k.endswith("_bytes"))

            n_2 = 1 << (10 if smoke
                        else (20 if platform != "cpu" else 14))
            mesh_g = make_grid_mesh()
            gr = int(mesh_g.shape[
                "rows"]), int(mesh_g.shape["cols"])
            with obs.span("bench.dist2d") as _sp2, \
                    obs.memory.watermark("bench.dist2d"):
                A_2 = _dist2d_config(sparse, n_2, nnz_per_row)
                # Equal-device-count 1-D baseline (recorded bytes).
                dA1 = shard_csr(A_2, mesh=make_row_mesh())
                vols1 = spmv_comm_volumes(
                    dA1, dA1.rows_padded // dA1.num_shards, 4)
                result["dist2d_spmv_1d_comm_bytes"] = sum(
                    vols1.values())
                led0 = _spgemm_ledger()
                C1 = dist_spgemm(dA1, dA1)
                result["dist2d_spgemm_1d_comm_bytes"] = (
                    _spgemm_ledger() - led0)
                del C1, dA1
                # 2-D block layout via the byte-predicting router.
                dA2 = shard_csr(A_2, mesh=mesh_g, layout="auto")
                result["dist2d_layout"] = dA2.layout
                result["dist2d_grid"] = f"{gr[0]}x{gr[1]}"
                vols2 = spmv_comm_volumes(
                    dA2, dA2.rows_padded // dA2.num_shards, 4)
                result["dist2d_spmv_comm_bytes"] = sum(vols2.values())
                x_2 = shard_vector(np.ones(n_2, np.float32), mesh_g,
                                   dA2.rows_padded, layout=dA2.layout)
                _ = float(jnp.sum(dist_spmv(dA2, x_2)))  # compile+warm
                try:
                    ms_2 = loop_ms_per_iter(
                        lambda v: dist_spmv(dA2, v), x_2,
                        k_lo=2, k_hi=8 if smoke else 16,
                    )
                    result["dist2d_spmv_ms"] = round(ms_2, 4)
                except RuntimeError as e:
                    sys.stderr.write(
                        f"bench: dist2d spmv timing: {e}\n")
                # Compressed panels (schema 15): the same matrix
                # through ``compress()`` — bf16 panel values, int16
                # block-local indices — with a bf16 x, priced by the
                # SAME ledger formulas as the f32 field (itemsize 2):
                # the all_gather panel bytes exactly halve, and the
                # golden pins the halved total through the 1%
                # ``*_comm_bytes`` gate.  One dispatch exercises the
                # low-precision 2-D kernel for real.
                try:
                    dC2 = shard_csr(A_2.compress(), mesh=mesh_g,
                                    layout=dA2.layout)
                    volsb = spmv_comm_volumes(
                        dC2, dC2.rows_padded // dC2.num_shards, 2)
                    result["dist2d_spmv_comm_bytes_bf16"] = sum(
                        volsb.values())
                    xb_2 = shard_vector(
                        jnp.ones(n_2, jnp.bfloat16), mesh_g,
                        dC2.rows_padded, layout=dC2.layout)
                    _ = float(jnp.sum(dist_spmv(dC2, xb_2)))
                    del dC2, xb_2
                except Exception as e:
                    sys.stderr.write(
                        f"bench: dist2d compressed failed: {e!r}\n")
                # Fixed-iteration CG, as in the 1-D dist phase: the
                # iteration count and so the comm volume are
                # deterministic across machines.
                maxit2 = 8 if smoke else 25
                xs2, it2 = dist_cg(dA2, np.ones(n_2, np.float32),
                                   rtol=0.0, maxiter=maxit2)
                _ = float(np.asarray(xs2[0]))
                it2 = int(it2)
                cg2_vols, _cg2_calls = cg_comm_volumes(dA2, 4, it2)
                result["dist2d_cg_iters"] = it2
                result["dist2d_cg_comm_bytes"] = sum(cg2_vols.values())
                led1 = _spgemm_ledger()
                C2 = dist_spgemm(dA2, dA2)
                result["dist2d_spgemm_comm_bytes"] = (
                    _spgemm_ledger() - led1)
                del C2
                if _sp2 is not None:
                    _sp2.set(grid=gr, layout=dA2.layout,
                             comm_bytes=(sum(vols2.values())
                                         + sum(cg2_vols.values())))
            result["comm_total_bytes"] = int(
                obs.counters.get("comm.total_bytes"))
        except Exception as e:
            sys.stderr.write(f"bench: dist2d phase failed: {e!r}\n")

    # Execution-engine phase (docs/ENGINE.md): cold (plan compile) vs
    # warm-cache (same bucket, DIFFERENT n — the zero-retrace hit
    # path) vs micro-batched dispatch, on a fixed-nnz random matrix.
    # Runs in --smoke too: the plan hit/miss deltas are deterministic
    # given the call sequence below, so the smoke golden pins them and
    # the *_ms fields join the bench_compare trajectory gate.
    if ((smoke
         or os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_ENGINE",
                           "0") != "1")
            and not past_deadline(result, "engine")):
        try:
            import time as _time

            from legate_sparse_tpu.engine import Engine, RequestExecutor

            n_cold = (1 << 12 if smoke else 1 << 16) - 37
            n_warm = (1 << 12 if smoke else 1 << 16) - 101
            with obs.span("bench.engine") as _sp, \
                    obs.memory.watermark("bench.engine"):
                A_cold = _engine_config(sparse, n_cold, nnz_per_row)
                A_warm = _engine_config(sparse, n_warm, nnz_per_row)
                x_cold = jnp.ones((n_cold,), jnp.float32)
                x_warm = jnp.ones((n_warm,), jnp.float32)
                # Fresh engine: the cold number really is a plan build
                # even when the routing flag was on earlier.
                eng = Engine()
                hm0 = (obs.counters.get("engine.plan.hits"),
                       obs.counters.get("engine.plan.misses"))
                t0 = _time.perf_counter()
                y = eng.matvec(A_cold, x_cold)
                if y is None:
                    # A silent decline must be a recorded phase error,
                    # not a TypeError swallowed as one.
                    raise RuntimeError(
                        "engine declined the bench matrix "
                        "(eligibility drifted?)")
                _ = float(np.asarray(y[0]))
                cold_ms = (_time.perf_counter() - t0) * 1e3
                # One untimed hit absorbs A_warm's pack build + the
                # tail-pad op compile; the timed calls are the pure
                # cached-executable path.
                _ = float(np.asarray(eng.matvec(A_warm, x_warm)[0]))
                warm_ms = float("inf")
                for _rep in range(5):
                    t0 = _time.perf_counter()
                    y = eng.matvec(A_warm, x_warm)
                    _ = float(np.asarray(y[0]))
                    warm_ms = min(warm_ms,
                                  (_time.perf_counter() - t0) * 1e3)
                # Batched: 8 same-matrix requests -> ONE stacked SpMM
                # dispatch (deterministic: timeout 0 = flush-only).
                ex = RequestExecutor(eng, max_batch=8, queue_depth=64,
                                     timeout_ms=0)
                reqs = 8
                t0 = _time.perf_counter()
                futs = [ex.submit(A_warm, x_warm) for _r in range(reqs)]
                _ = [float(np.asarray(f.result()[0])) for f in futs]
                batched_ms = (_time.perf_counter() - t0) * 1e3 / reqs
                ex.shutdown()
                result["engine_cold_ms"] = round(cold_ms, 4)
                result["engine_warm_ms"] = round(warm_ms, 4)
                result["engine_warm_speedup"] = round(
                    cold_ms / max(warm_ms, 1e-9), 2)
                result["engine_batched_ms_per_req"] = round(batched_ms,
                                                            4)
                result["engine_batch_requests"] = reqs
                result["engine_plan_hits"] = int(
                    obs.counters.get("engine.plan.hits") - hm0[0])
                result["engine_plan_misses"] = int(
                    obs.counters.get("engine.plan.misses") - hm0[1])
                if _sp is not None:
                    _sp.set(nnz=A_cold.nnz + A_warm.nnz,
                            cold_ms=result["engine_cold_ms"],
                            warm_ms=result["engine_warm_ms"])
        except Exception as e:
            sys.stderr.write(f"bench: engine phase failed: {e!r}\n")

    # Resilience phase (docs/RESILIENCE.md): a deterministic fault
    # drill — fail-twice-then-recover on the csr.dot site (2 retries),
    # a K=3 breaker trip with the typed fast-fail, and one deadline
    # shed through the executor.  The counter deltas are exact given
    # the call sequence, so the smoke golden pins them
    # (resil_retries / resil_shed / resil_breaker_trips /
    # resil_faults_injected) and the recovered-vs-clean latency pair
    # joins the trajectory.  Everything restores on exit: the phase
    # must not leak armed faults or flipped settings into later
    # phases.
    if ((smoke
         or os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_RESIL",
                           "0") != "1")
            and not past_deadline(result, "resil")):
        try:
            import time as _time

            from legate_sparse_tpu import resilience as _resil
            from legate_sparse_tpu.engine import Engine as _REngine
            from legate_sparse_tpu.engine import \
                RequestExecutor as _RExecutor
            from legate_sparse_tpu.resilience import deadline as _rdl
            from legate_sparse_tpu.settings import settings as _rst

            n_r = (1 << 12 if smoke else 1 << 16) - 57
            saved = (_rst.resil, _rst.resil_retries,
                     _rst.resil_backoff_ms, _rst.resil_breaker_k,
                     _rst.resil_breaker_cooldown_ms)
            with obs.span("bench.resil") as _sp:
                try:
                    _rst.resil = True
                    _rst.resil_retries = 2
                    _rst.resil_backoff_ms = 0.0
                    _rst.resil_breaker_k = 3
                    _rst.resil_breaker_cooldown_ms = 50.0
                    _resil.reset()
                    c0 = {k: obs.counters.get(k) for k in (
                        "resil.retry.attempts", "resil.shed",
                        "resil.breaker.trips", "resil.fault.injected")}
                    A_r = _engine_config(sparse, n_r, nnz_per_row)
                    x_r = jnp.ones((n_r,), jnp.float32)
                    _ = float(np.asarray(A_r.dot(x_r)[0]))  # compile
                    t0 = _time.perf_counter()
                    _ = float(np.asarray(A_r.dot(x_r)[0]))
                    clean_ms = (_time.perf_counter() - t0) * 1e3
                    # Drill 1: fail-twice-then-succeed, same path.
                    _resil.inject("csr.dot", kind="error", count=2)
                    t0 = _time.perf_counter()
                    _ = float(np.asarray(A_r.dot(x_r)[0]))
                    recovered_ms = (_time.perf_counter() - t0) * 1e3
                    _resil.faults.clear()
                    # Drill 2: K consecutive failures trip the
                    # breaker; the open breaker fast-fails typed.
                    _rst.resil_retries = 0
                    _resil.inject("csr.dot", kind="error", count=3)
                    for _i in range(4):   # 3 faults + 1 short-circuit
                        try:
                            A_r.dot(x_r)
                        except _resil.ResilienceError:
                            pass
                    _resil.faults.clear()
                    _rst.resil_retries = 2
                    # Drill 3: expired-deadline submit is shed with
                    # the typed Rejected outcome, never dispatched.
                    eng_r = _REngine()
                    ex_r = _RExecutor(eng_r, max_batch=8,
                                      queue_depth=64, timeout_ms=0)
                    with _rdl.scope(0.0):
                        fut = ex_r.submit(A_r, x_r)
                    shed_out = fut.result(timeout=10)
                    ex_r.shutdown()
                    if type(shed_out).__name__ != "Rejected":
                        raise RuntimeError(
                            f"expected Rejected outcome, got "
                            f"{type(shed_out).__name__}")
                    result["resil_clean_ms"] = round(clean_ms, 4)
                    result["resil_recovered_ms"] = round(recovered_ms,
                                                         4)
                    result["resil_recovery_delta_ms"] = round(
                        recovered_ms - clean_ms, 4)
                    result["resil_retries"] = int(obs.counters.get(
                        "resil.retry.attempts")
                        - c0["resil.retry.attempts"])
                    result["resil_shed"] = int(obs.counters.get(
                        "resil.shed") - c0["resil.shed"])
                    result["resil_breaker_trips"] = int(
                        obs.counters.get("resil.breaker.trips")
                        - c0["resil.breaker.trips"])
                    result["resil_faults_injected"] = int(
                        obs.counters.get("resil.fault.injected")
                        - c0["resil.fault.injected"])
                    if _sp is not None:
                        _sp.set(retries=result["resil_retries"],
                                shed=result["resil_shed"],
                                trips=result["resil_breaker_trips"])
                finally:
                    (_rst.resil, _rst.resil_retries,
                     _rst.resil_backoff_ms, _rst.resil_breaker_k,
                     _rst.resil_breaker_cooldown_ms) = saved
                    _resil.reset()
        except Exception as e:
            sys.stderr.write(f"bench: resil phase failed: {e!r}\n")

    # Recovery phase (schema_version 16, docs/RESILIENCE.md): a
    # deterministic device-loss drill mid-``dist_cg`` on the
    # all-device mesh.  Checkpoints ride the conv-fetch cadence
    # (every 10 iterations), a seeded loss fires at the second fetch,
    # and the ladder shrinks the mesh, reshards the operands, restores
    # the it=20 snapshot and resumes the remaining budget.  With
    # rtol=0 the iteration plan is fixed, so the counter deltas are
    # exact and the smoke golden pins them: 4 checkpoint saves
    # (two pre-loss + two post-restore), 1 recovery restoring 20
    # iterations, and the measured survivor-repartition bytes.
    if ((smoke
         or os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_RECOVERY",
                           "0") != "1")
            and not past_deadline(result, "recovery")):
        try:
            import time as _time

            from legate_sparse_tpu import resilience as _resil
            from legate_sparse_tpu.parallel import (
                dist_cg, make_row_mesh, shard_csr,
            )
            from legate_sparse_tpu.settings import settings as _rst

            mesh_rec = make_row_mesh()
            if int(mesh_rec.shape["rows"]) >= 2:
                n_rec = 1 << (12 if smoke else 16)
                maxit_rec, cti_rec = 40, 10
                saved = (_rst.resil, _rst.resil_ckpt_iters,
                         _rst.resil_backoff_ms)
                with obs.span("bench.recovery") as _sp:
                    try:
                        _rst.resil = True
                        _rst.resil_ckpt_iters = cti_rec
                        _rst.resil_backoff_ms = 0.0
                        _resil.reset()
                        A_rec = _banded_config(sparse, n_rec,
                                               nnz_per_row)
                        dA_rec = shard_csr(A_rec, mesh=mesh_rec)
                        b_rec = np.ones(n_rec, np.float32)
                        _ = dist_cg(dA_rec, b_rec, rtol=0.0,
                                    maxiter=maxit_rec,
                                    conv_test_iters=cti_rec)  # compile
                        t0 = _time.perf_counter()
                        _ = dist_cg(dA_rec, b_rec, rtol=0.0,
                                    maxiter=maxit_rec,
                                    conv_test_iters=cti_rec)
                        clean_ms = (_time.perf_counter() - t0) * 1e3
                        c0 = {k: obs.counters.get(k) for k in (
                            "resil.ckpt.saves",
                            "resil.recovery.attempts",
                            "resil.recovery.restored_iters",
                            "resil.recovery.reshard_bytes")}
                        _resil.inject("solver.cg.conv", "device_loss",
                                      after=2, device=1)
                        t0 = _time.perf_counter()
                        _x, it_rec = dist_cg(dA_rec, b_rec, rtol=0.0,
                                             maxiter=maxit_rec,
                                             conv_test_iters=cti_rec)
                        recovered_ms = (_time.perf_counter() - t0) * 1e3
                        result["recovery_clean_ms"] = round(clean_ms, 4)
                        result["recovery_recovered_ms"] = round(
                            recovered_ms, 4)
                        result["resil_ckpt_saves"] = int(
                            obs.counters.get("resil.ckpt.saves")
                            - c0["resil.ckpt.saves"])
                        result["resil_recoveries"] = int(
                            obs.counters.get("resil.recovery.attempts")
                            - c0["resil.recovery.attempts"])
                        result["resil_restored"] = int(
                            obs.counters.get(
                                "resil.recovery.restored_iters")
                            - c0["resil.recovery.restored_iters"])
                        result["resil_reshard_bytes"] = int(
                            obs.counters.get(
                                "resil.recovery.reshard_bytes")
                            - c0["resil.recovery.reshard_bytes"])
                        if _sp is not None:
                            _sp.set(
                                saves=result["resil_ckpt_saves"],
                                recoveries=result["resil_recoveries"],
                                reshard_bytes=result[
                                    "resil_reshard_bytes"],
                                iters=int(it_rec))
                    finally:
                        (_rst.resil, _rst.resil_ckpt_iters,
                         _rst.resil_backoff_ms) = saved
                        _resil.reset()
        except Exception as e:
            sys.stderr.write(f"bench: recovery phase failed: {e!r}\n")

    # Graph phase (schema_version 17, docs/GRAPH.md): the four
    # semiring algorithms on one seeded R-MAT matrix over the
    # all-device mesh.  Every input is deterministic (fixed rng,
    # fixed shapes) and the host loops run a fixed number of sweeps
    # given the structure (BFS/CC to their fixed points, SSSP to the
    # Bellman-Ford fixed point, PageRank with tol=0 to exactly
    # ``pr_iters``), so the smoke golden pins the per-algorithm
    # iteration counts exactly and the per-algorithm
    # ``graph_<alg>_comm_bytes`` (delta of ``comm.total_bytes``
    # around each run) through the ``*_comm_bytes`` band.  Timings
    # stay informational.
    if ((smoke
         or os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_GRAPH",
                           "0") != "1")
            and not past_deadline(result, "graph")):
        try:
            import time as _time

            from legate_sparse_tpu import gallery as _gallery
            from legate_sparse_tpu import graph as _graph

            scale_g = 9 if smoke else 13
            pr_iters = 20
            A_g = _gallery.rmat(scale_g, nnz_per_row=4,
                                rng=np.random.default_rng(1234),
                                directed=True)
            result["graph_n"] = int(A_g.shape[0])
            result["graph_nnz"] = int(A_g.nnz)
            runs = (
                ("bfs", lambda: _graph.bfs(A_g, source=0)),
                ("sssp", lambda: _graph.sssp(A_g, source=0)),
                ("cc", lambda: _graph.connected_components(A_g)),
                ("pagerank", lambda: _graph.pagerank(
                    A_g, tol=0.0, max_iters=pr_iters)),
            )
            with obs.span("bench.graph") as _sp:
                t0 = _time.perf_counter()
                for name_g, run_g in runs:
                    it_key = f"graph.{name_g}.iters"
                    it0 = obs.counters.get(it_key)
                    b0 = obs.counters.get("comm.total_bytes")
                    out_g = run_g()
                    jax.block_until_ready(
                        out_g[1] if isinstance(out_g, tuple)
                        else out_g)
                    result[f"graph_{name_g}_iters"] = int(
                        obs.counters.get(it_key) - it0)
                    result[f"graph_{name_g}_comm_bytes"] = int(
                        obs.counters.get("comm.total_bytes") - b0)
                result["graph_ms"] = round(
                    (_time.perf_counter() - t0) * 1e3, 4)
                if _sp is not None:
                    _sp.set(n=result["graph_n"],
                            nnz=result["graph_nnz"],
                            bfs_iters=result["graph_bfs_iters"],
                            pagerank_iters=result[
                                "graph_pagerank_iters"])
        except Exception as e:
            sys.stderr.write(f"bench: graph phase failed: {e!r}\n")

    # Saturation phase (schema_version 10, obs v3): offered load vs
    # the request executor — the p50/p99-vs-load curve ROADMAP item 1
    # (the serving gateway) is judged by.  A closed-loop arrival
    # generator (``clients`` threads, each submit -> wait -> resubmit)
    # sweeps concurrency levels against one executor; per level the
    # always-on ``lat.engine.request.*`` histograms yield p50/p99 and
    # the counter deltas yield throughput and mean batch occupancy.
    # SpMM plans for every batch width are warmed first, so the curve
    # measures queueing + dispatch, not compiles.  Totals are
    # deterministic given the fixed sweep (request count, occupancy
    # total = every request batched exactly once, and one
    # deadline-shed drill request), so the smoke golden pins
    # ``saturation_requests`` / ``saturation_shed`` /
    # ``saturation_batched_requests``; per-level timings stay
    # informational (thread-timing dependent).
    if ((smoke
         or os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_SATURATION",
                           "0") != "1")
            and not past_deadline(result, "saturation")):
        try:
            import threading as _threading
            import time as _time

            from legate_sparse_tpu.engine import Engine as _SEngine
            from legate_sparse_tpu.engine import \
                RequestExecutor as _SExecutor
            from legate_sparse_tpu.obs import latency as _lat_s
            from legate_sparse_tpu.resilience import deadline as _sdl
            from legate_sparse_tpu.settings import settings as _sst

            n_s = (1 << 12 if smoke else 1 << 16) - 73
            levels = [1, 2, 4, 8] if smoke else [1, 2, 4, 8, 16]
            per_client = 4 if smoke else 8
            with obs.span("bench.saturation") as _sp:
                A_s = _engine_config(sparse, n_s, nnz_per_row)
                x_s = jnp.ones((n_s,), jnp.float32)
                eng_s = _SEngine()
                ex_s = _SExecutor(eng_s, max_batch=8, queue_depth=64,
                                  timeout_ms=0.5)
                # Pre-compile every plan the sweep can hit (spmv for
                # width-1 flushes, spmm per pow2 batch width): the
                # latency curve must measure queueing + dispatch, not
                # XLA compiles.  The whole sweep runs under
                # try/finally: a failed level or drill must not leak
                # the executor (daemon worker + anchored matrix) into
                # the phases that follow.
                try:
                    eng_s.warmup(
                        [{"op": "spmv", "rows": n_s, "nnz": A_s.nnz}]
                        + [{"op": "spmm", "rows": n_s,
                            "nnz": A_s.nnz, "k": k} for k in levels
                           if 1 < k <= 8])  # widths cap at max_batch=8
                    _ = np.asarray(ex_s.submit(A_s, x_s).result(
                        timeout=60))     # pack build outside the sweep
                    c0 = {k: obs.counters.get(k) for k in (
                        "engine.exec.outcome.resolved",
                        "engine.exec.batched_requests",
                        "resil.shed")}
                    sat_levels = []
                    for clients in levels:
                        _lat_s.reset("lat.engine.request")
                        b0_breq = obs.counters.get(
                            "engine.exec.batched_requests")
                        b0_bat = obs.counters.get(
                            "engine.exec.batches")
                        errors = []

                        def _client():
                            try:
                                for _r in range(per_client):
                                    f = ex_s.submit(A_s, x_s)
                                    _ = np.asarray(
                                        f.result(timeout=120))
                            except Exception as e:  # raised after join
                                errors.append(e)

                        t0 = _time.perf_counter()
                        ts = [_threading.Thread(target=_client)
                              for _c in range(clients)]
                        for t in ts:
                            t.start()
                        for t in ts:
                            t.join()
                        wall = _time.perf_counter() - t0
                        if errors:
                            raise errors[0]
                        merged = None
                        for h in _lat_s.snapshot(
                                "lat.engine.request").values():
                            merged = (h if merged is None
                                      else merged.merge(h))
                        d_bat = (obs.counters.get(
                            "engine.exec.batches") - b0_bat)
                        d_breq = (obs.counters.get(
                            "engine.exec.batched_requests") - b0_breq)
                        reqs = clients * per_client
                        sat_levels.append({
                            "clients": clients,
                            "requests": reqs,
                            "p50_ms": round(merged.quantile(0.5), 4),
                            "p99_ms": round(merged.quantile(0.99), 4),
                            "throughput_rps": round(
                                reqs / max(wall, 1e-9), 1),
                            "mean_batch_occupancy": round(
                                d_breq / max(d_bat, 1), 2),
                            "shed": 0,   # no deadlines in the sweep
                        })
                    # Deadline-shed drill: one pre-expired request
                    # proves the shed path records its wait and the
                    # shed total moves — deterministic (+1),
                    # golden-gated.
                    saved_res = _sst.resil
                    try:
                        _sst.resil = True
                        with _sdl.scope(0.0):
                            fut = ex_s.submit(A_s, x_s)
                        out_shed = fut.result(timeout=10)
                        if type(out_shed).__name__ != "Rejected":
                            raise RuntimeError(
                                f"expected Rejected outcome, got "
                                f"{type(out_shed).__name__}")
                    finally:
                        _sst.resil = saved_res
                finally:
                    # A failed level/drill must not leak the executor
                    # (daemon worker + anchored 65k-row matrix) into
                    # the phases that follow.
                    ex_s.shutdown()
                result["saturation"] = sat_levels
                result["saturation_requests"] = int(
                    obs.counters.get("engine.exec.outcome.resolved")
                    - c0["engine.exec.outcome.resolved"])
                result["saturation_shed"] = int(
                    obs.counters.get("resil.shed") - c0["resil.shed"])
                result["saturation_batched_requests"] = int(
                    obs.counters.get("engine.exec.batched_requests")
                    - c0["engine.exec.batched_requests"])
                result["saturation_p50_ms"] = sat_levels[-1]["p50_ms"]
                result["saturation_p99_ms"] = sat_levels[-1]["p99_ms"]
                if _sp is not None:
                    _sp.set(levels=len(levels),
                            requests=result["saturation_requests"],
                            p99_ms=result["saturation_p99_ms"])
        except Exception as e:
            sys.stderr.write(f"bench: saturation phase failed: {e!r}\n")

    # Gateway fairness phase (schema_version 12, docs/ENGINE.md): the
    # multi-tenant admission gateway under a 3-tenant load, in two
    # stages.  Stage A (max_batch=4) proves WFQ batch formation and
    # cross-matrix packing: the interactive tenant alternates two
    # distinct same-bucket matrices, so its batches dispatch as ONE
    # stacked multi-matrix kernel (gateway.packed moves).  Stage B
    # (flush-only, wide batch, tenant_quota=8) proves overload
    # isolation: a background tenant floods 32 requests against an
    # 8-deep quota — deterministically 24 ``queue_full`` rejections —
    # while the interactive tenant's served count is unaffected.  All
    # totals are deterministic given the fixed submission sequence, so
    # the smoke golden pins them.
    if ((smoke
         or os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_GATEWAY",
                           "0") != "1")
            and not past_deadline(result, "gateway")):
        try:
            from legate_sparse_tpu.engine import Engine as _GEngine
            from legate_sparse_tpu.engine import Gateway as _GGateway
            from legate_sparse_tpu.settings import settings as _gst

            n_g = (1 << 12 if smoke else 1 << 14) - 91
            with obs.span("bench.gateway") as _sp:
                A_g1 = _engine_config(sparse, n_g, nnz_per_row)
                A_g2 = _engine_config(sparse, n_g, nnz_per_row,
                                      seed=13)
                A_g3 = _engine_config(sparse, n_g, nnz_per_row,
                                      seed=29)
                x_g = jnp.ones((n_g,), jnp.float32)
                gw_counters = (
                    "gateway.submitted",
                    "gateway.dispatches",
                    "gateway.packed",
                    "gateway.rejected.queue_full",
                    "gateway.tenant.interactive.served",
                    "gateway.tenant.interactive.shed",
                    "gateway.tenant.batch.served",
                    "gateway.tenant.background.served",
                    "gateway.tenant.background.shed",
                )
                c0g = {k: obs.counters.get(k) for k in gw_counters}
                saved_gw = _gst.gateway
                try:
                    _gst.gateway = True

                    def _load(gw):
                        futs = []
                        for i in range(8):
                            futs.append(gw.submit(
                                A_g1 if i % 2 == 0 else A_g2, x_g,
                                tenant="interactive",
                                qos="interactive"))
                        for _i in range(8):
                            futs.append(gw.submit(
                                A_g3, x_g, tenant="batch",
                                qos="batch"))
                        for _i in range(32):
                            futs.append(gw.submit(
                                A_g1, x_g, tenant="background",
                                qos="background"))
                        gw.flush()
                        for f in futs:
                            _ = f.result(timeout=120)

                    # Stage A: tight batches — the 4th pending request
                    # triggers dispatch in the submitting thread, so
                    # the interactive tenant's alternating matrices
                    # land in packed multi-matrix batches.
                    gw_a = _GGateway(
                        _GEngine(), max_batch=4, queue_depth=128,
                        tenant_quota=64, rate=0.0, burst=16.0,
                        slack_ms=5.0, timeout_ms=0.0)
                    try:
                        _load(gw_a)
                    finally:
                        gw_a.shutdown()
                    # Stage B: flood — nothing dispatches during
                    # submission (max_batch exceeds the offered load),
                    # so the background tenant fills its 8-deep quota
                    # and the remaining 24 submissions reject.
                    gw_b = _GGateway(
                        _GEngine(), max_batch=32, queue_depth=128,
                        tenant_quota=8, rate=0.0, burst=16.0,
                        slack_ms=5.0, timeout_ms=0.0)
                    try:
                        _load(gw_b)
                    finally:
                        gw_b.shutdown()
                finally:
                    _gst.gateway = saved_gw

                def _dg(name):
                    return int(obs.counters.get(name) - c0g[name])

                result["gateway_requests"] = _dg("gateway.submitted")
                result["gateway_dispatches"] = _dg(
                    "gateway.dispatches")
                result["gateway_packed"] = _dg("gateway.packed")
                result["gateway_rejected_queue_full"] = _dg(
                    "gateway.rejected.queue_full")
                result["gateway_interactive_served"] = _dg(
                    "gateway.tenant.interactive.served")
                result["gateway_interactive_shed"] = _dg(
                    "gateway.tenant.interactive.shed")
                result["gateway_batch_served"] = _dg(
                    "gateway.tenant.batch.served")
                result["gateway_background_served"] = _dg(
                    "gateway.tenant.background.served")
                result["gateway_background_shed"] = _dg(
                    "gateway.tenant.background.shed")
                if _sp is not None:
                    _sp.set(requests=result["gateway_requests"],
                            packed=result["gateway_packed"],
                            rejected=result[
                                "gateway_rejected_queue_full"])
        except Exception as e:
            sys.stderr.write(f"bench: gateway phase failed: {e!r}\n")

    # Multi-tenant attribution phase (schema 18,
    # docs/OBSERVABILITY.md): the elastic-placement sensor proof.
    # With the attribution ledger armed (restored on exit — it must
    # stay inert for every other phase): (a) a 2-tenant gateway load
    # whose alternating matrices land in packed multi-tenant batches,
    # exercising the declared split rule on real dispatches; (b) two
    # dist SpMV dispatches — one under a single-tenant TraceContext,
    # one under a packed 3-member attrib scope — pushing real
    # comm-ledger bytes (remainder included) through the apportioner.
    # The conservation verdict (per-tenant byte sum == the untagged
    # comm.total_bytes delta, exactly) and the deterministic totals
    # are golden-pinned in smoke.
    if ((smoke
         or os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_ATTRIB",
                           "0") != "1")
            and not past_deadline(result, "attrib")):
        try:
            from legate_sparse_tpu.engine import Engine as _AEngine
            from legate_sparse_tpu.engine import Gateway as _AGateway
            from legate_sparse_tpu.obs import attrib as _attrib_mod
            from legate_sparse_tpu.obs import context as _actx
            from legate_sparse_tpu.parallel import (
                make_row_mesh as _a_mesh, shard_csr as _a_shard,
            )
            from legate_sparse_tpu.parallel.dist_csr import (
                dist_spmv as _a_spmv, shard_vector as _a_shard_vec,
            )
            from legate_sparse_tpu.settings import settings as _ast2

            t_attr0 = _time_mod.perf_counter()
            n_a = (1 << 12 if smoke else 1 << 14) - 91
            with obs.span("bench.attrib") as _sp:
                A_a1 = _engine_config(sparse, n_a, nnz_per_row)
                A_a2 = _engine_config(sparse, n_a, nnz_per_row,
                                      seed=13)
                x_a = jnp.ones((n_a,), jnp.float32)
                at_tenants = ("interactive", "batch", "background")
                comm0 = int(obs.counters.get("comm.total_bytes"))
                at_counters = ["attrib.total.comm_bytes",
                               "gateway.packed", "gateway.submitted"]
                at_counters += [f"attrib.tenant.{t}.comm_bytes"
                                for t in at_tenants
                                + ("__untagged__",)]
                c0a = {k: obs.counters.get(k) for k in at_counters}
                saved_gw2 = _ast2.gateway
                saved_attr = _ast2.obs_attrib
                try:
                    _ast2.gateway = True
                    _ast2.obs_attrib = True
                    gw_at = _AGateway(
                        _AEngine(), max_batch=4, queue_depth=128,
                        tenant_quota=64, rate=0.0, burst=16.0,
                        slack_ms=5.0, timeout_ms=0.0)
                    try:
                        futs = []
                        for i in range(8):
                            futs.append(gw_at.submit(
                                A_a1 if i % 2 == 0 else A_a2, x_a,
                                tenant="interactive",
                                qos="interactive"))
                        for _i in range(8):
                            futs.append(gw_at.submit(
                                A_a2, x_a, tenant="batch",
                                qos="batch"))
                        gw_at.flush()
                        for f in futs:
                            _ = f.result(timeout=120)
                    finally:
                        gw_at.shutdown()
                    # Dist segment: real collective bytes through the
                    # apportioner — the conservation proof is only
                    # meaningful on non-zero volumes.
                    mesh_a = _a_mesh()
                    A_ad = _banded_config(
                        sparse, 1 << (12 if smoke else 14),
                        nnz_per_row)
                    dA_a = _a_shard(A_ad, mesh=mesh_a)
                    x_ad = _a_shard_vec(
                        np.ones(A_ad.shape[0], np.float32), mesh_a,
                        dA_a.rows_padded)
                    with _actx.use(_actx.TraceContext(
                            "bench-attrib-one", tenant="interactive",
                            qos="interactive")):
                        _ = float(jnp.sum(_a_spmv(dA_a, x_ad)))
                    with _attrib_mod.scope([(t, t)
                                            for t in at_tenants]):
                        _ = float(jnp.sum(_a_spmv(dA_a, x_ad)))
                finally:
                    _ast2.gateway = saved_gw2
                    _ast2.obs_attrib = saved_attr

                def _da(name):
                    return int(obs.counters.get(name) - c0a[name])

                comm_delta = int(
                    obs.counters.get("comm.total_bytes")) - comm0
                # Conservation sums over EVERY attribution target —
                # the named tenants plus the __untagged__ sink — so
                # the invariant stays exact even if an untagged comm
                # source ever lands inside the armed window.
                tenant_bytes = sum(
                    _da(f"attrib.tenant.{t}.comm_bytes")
                    for t in at_tenants + ("__untagged__",))
                result["attrib_requests"] = _da("gateway.submitted")
                result["attrib_packed"] = _da("gateway.packed")
                result["attrib_comm_bytes"] = comm_delta
                result["attrib_tenant_comm_bytes"] = tenant_bytes
                result["attrib_tenants"] = sum(
                    1 for t in at_tenants
                    if _da(f"attrib.tenant.{t}.comm_bytes"))
                result["attrib_conserved"] = int(
                    tenant_bytes == _da("attrib.total.comm_bytes")
                    == comm_delta and comm_delta > 0)
                result["attrib_ms"] = round(
                    (_time_mod.perf_counter() - t_attr0) * 1e3, 3)
                if _sp is not None:
                    _sp.set(requests=result["attrib_requests"],
                            comm_bytes=comm_delta,
                            conserved=result["attrib_conserved"])
        except Exception as e:
            sys.stderr.write(f"bench: attrib phase failed: {e!r}\n")

    # Elastic-placement phase (schema 19, docs/PLACEMENT.md): the
    # planner + actuator proof.  Two placed tenants serve through the
    # gateway's placement routing (pre-carve on the plain local path),
    # then a burning-tenant plan from the pure ``propose()`` carves
    # the noisy tenant a 7-device submesh and live-migrates both —
    # declared ``comm.dist_reshard.*`` bytes equal the priced plan by
    # construction — and a second round serves on the new carve.  The
    # snapshot is FIXED, not sensed: the live attribution ledger's
    # busy/wait numbers are timing-noisy and would flap the carve
    # (and so the golden-pinned priced bytes); the sensed closed loop
    # is pinned end-to-end by tests/test_placement.py instead.  All
    # counted totals are deterministic, so the smoke golden pins them.
    if ((smoke
         or os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_PLACEMENT",
                           "0") != "1")
            and not past_deadline(result, "placement")):
        try:
            import jax as _pjax

            from legate_sparse_tpu import placement as _placement
            from legate_sparse_tpu.engine import Engine as _PEngine
            from legate_sparse_tpu.engine import Gateway as _PGateway
            from legate_sparse_tpu.engine.gateway import (
                QOS_WEIGHTS as _p_weights,
            )
            from legate_sparse_tpu.settings import settings as _pst

            t_p0 = _time_mod.perf_counter()
            n_p = (1 << 12 if smoke else 1 << 14) - 91
            with obs.span("bench.placement") as _sp:
                A_p1 = _engine_config(sparse, n_p, nnz_per_row)
                A_p2 = _engine_config(sparse, n_p, nnz_per_row,
                                      seed=13)
                x_p = jnp.ones((n_p,), jnp.float32)
                p_counters = (
                    "placement.migrations",
                    "placement.migration.bytes",
                    "placement.routes",
                    "gateway.tenant.noisy.served",
                    "gateway.tenant.noisy.shed",
                    "gateway.tenant.quiet.served",
                    "gateway.tenant.quiet.shed",
                )
                c0p = {k: obs.counters.get(k) for k in p_counters}
                saved_p = (_pst.gateway, _pst.placement)
                try:
                    _pst.gateway = True
                    _pst.placement = True
                    _placement.reset()
                    _placement.place("noisy", A_p1)
                    _placement.place("quiet", A_p2)

                    def _pload(gw, n_noisy, n_quiet):
                        futs = [gw.submit(A_p1, x_p, tenant="noisy",
                                          qos="interactive")
                                for _i in range(n_noisy)]
                        futs += [gw.submit(A_p2, x_p, tenant="quiet",
                                           qos="background")
                                 for _i in range(n_quiet)]
                        gw.flush()
                        for f in futs:
                            _ = f.result(timeout=120)

                    gw_p = _PGateway(
                        _PEngine(), max_batch=4, queue_depth=128,
                        tenant_quota=64, rate=0.0, burst=16.0,
                        slack_ms=5.0, timeout_ms=0.0)
                    try:
                        _pload(gw_p, 16, 4)
                        devs = _pjax.devices()
                        reg = _placement.registry()
                        snap = _placement.PlacementSnapshot(
                            demand={
                                "noisy": {"busy_ns": 8_000_000_000,
                                          "qos": "interactive"},
                                "quiet": {"busy_ns": 1_000_000_000,
                                          "qos": "background"},
                            },
                            qos_weights=dict(_p_weights),
                            burns={"interactive": 1000.0},
                            devices=len(devs),
                            current=reg.slices(),
                            payload_bytes=reg.payload_bytes(),
                            shrink=())
                        decision = _placement.propose(snap)
                        if decision.act:
                            reg.apply(decision.moves, devs)
                        # Warm the post-migration dist path outside
                        # the serving round (the first submesh
                        # dist_spmv compiles).
                        for t_p, A_t in (("noisy", A_p1),
                                         ("quiet", A_p2)):
                            h_p = _placement.route(A_t, t_p)
                            _ = np.asarray(h_p.dot(x_p))
                        _pload(gw_p, 8, 2)   # serve on the new carve
                    finally:
                        gw_p.shutdown()
                finally:
                    _pst.gateway, _pst.placement = saved_p
                    _placement.reset()

                def _dp(name):
                    return int(obs.counters.get(name) - c0p[name])

                result["placement_migrations"] = _dp(
                    "placement.migrations")
                result["placement_reshard_bytes"] = _dp(
                    "placement.migration.bytes")
                result["placement_routes"] = _dp("placement.routes")
                result["placement_noisy_served"] = _dp(
                    "gateway.tenant.noisy.served")
                result["placement_quiet_served"] = _dp(
                    "gateway.tenant.quiet.served")
                result["placement_ms"] = round(
                    (_time_mod.perf_counter() - t_p0) * 1e3, 3)
                if _sp is not None:
                    _sp.set(migrations=result["placement_migrations"],
                            reshard_bytes=result[
                                "placement_reshard_bytes"],
                            routes=result["placement_routes"])
        except Exception as e:
            sys.stderr.write(f"bench: placement phase failed: {e!r}\n")

    # Streaming-mutation phase (schema 20, docs/MUTATION.md): the
    # serve-while-mutating proof.  A DeltaCSR serves through the
    # gateway's delta routing while the seeded update storm lands in
    # the side-buffer (two-term dispatch, pinned views), then one
    # background compaction merges the buffer into a fresh base with
    # an atomic version swap and a final round serves the merged
    # base.  The stream is ``gallery.mutation_stream`` under a fixed
    # seed over a fixed pattern, so every counted total is
    # deterministic and the smoke golden pins them exactly.
    if ((smoke
         or os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_MUTATION",
                           "0") != "1")
            and not past_deadline(result, "mutation")):
        try:
            from legate_sparse_tpu import gallery as _mgallery
            from legate_sparse_tpu.delta import DeltaCSR as _MDelta
            from legate_sparse_tpu.engine import Engine as _MEngine
            from legate_sparse_tpu.engine import Gateway as _MGateway
            from legate_sparse_tpu.settings import settings as _mst

            t_m0 = _time_mod.perf_counter()
            n_m = (1 << 12 if smoke else 1 << 14) - 91
            with obs.span("bench.mutation") as _msp:
                A_m = _engine_config(sparse, n_m, nnz_per_row,
                                     seed=29)
                x_m = jnp.ones((n_m,), jnp.float32)
                m_counters = (
                    "delta.updates",
                    "delta.applied",
                    "delta.compaction.merged",
                    "delta.compactions",
                    "delta.swap.versions",
                    "delta.served",
                    "delta.routes",
                )
                c0m = {k: obs.counters.get(k) for k in m_counters}
                saved_m = (_mst.gateway, _mst.delta)
                t_compact = 0.0
                try:
                    _mst.gateway = True
                    _mst.delta = True
                    D_m = _MDelta(A_m, capacity=256)
                    gw_m = _MGateway(
                        _MEngine(), max_batch=4, queue_depth=128,
                        tenant_quota=64, rate=0.0, burst=16.0,
                        slack_ms=5.0, timeout_ms=0.0)
                    try:
                        def _mserve(k):
                            futs = [gw_m.submit(D_m, x_m,
                                                tenant="mut",
                                                qos="interactive")
                                    for _i in range(k)]
                            gw_m.flush()
                            for f in futs:
                                _ = f.result(timeout=120)

                        # Warm the two delta compiles outside the
                        # timed serving rounds (base bucket + the
                        # coo-segment capacity bucket).
                        _ = np.asarray(D_m.dot(x_m))
                        D_m.update([0], [0], [1.0])
                        _ = np.asarray(D_m.dot(x_m))
                        # Serve-while-mutating: 10 seeded update
                        # batches (100 entry updates) interleaved
                        # with gateway rounds on the live buffer.
                        for rows_m, cols_m, vals_m in (
                                _mgallery.mutation_stream(
                                    23, A_m, 100, batch=10)):
                            D_m.update(rows_m, cols_m, vals_m)
                            _mserve(2)
                        # Background compaction + atomic version
                        # swap, off the serving path.
                        t_c0 = _time_mod.perf_counter()
                        D_m.compact()
                        t_compact = (_time_mod.perf_counter()
                                     - t_c0) * 1e3
                        # Post-swap round serves the merged base
                        # (empty buffer — base dispatch alone).
                        _mserve(4)
                    finally:
                        gw_m.shutdown()
                finally:
                    _mst.gateway, _mst.delta = saved_m

                def _dm(name):
                    return int(obs.counters.get(name) - c0m[name])

                result["mutation_updates"] = _dm("delta.updates")
                result["mutation_applied"] = _dm("delta.applied")
                result["mutation_merged"] = _dm(
                    "delta.compaction.merged")
                result["mutation_compactions"] = _dm(
                    "delta.compactions")
                result["mutation_version_swaps"] = _dm(
                    "delta.swap.versions")
                result["mutation_served"] = _dm("delta.served")
                result["mutation_routes"] = _dm("delta.routes")
                result["mutation_compaction_ms"] = round(t_compact, 3)
                result["mutation_ms"] = round(
                    (_time_mod.perf_counter() - t_m0) * 1e3, 3)
                if _msp is not None:
                    _msp.set(updates=result["mutation_updates"],
                             merged=result["mutation_merged"],
                             swaps=result["mutation_version_swaps"])
        except Exception as e:
            sys.stderr.write(f"bench: mutation phase failed: {e!r}\n")

    # Autotune phase (schema_version 11, docs/AUTOTUNER.md): the
    # irregular-SpMV speedup proof.  A seeded power-law matrix gets a
    # sliced-ELL verdict (measured here in the full lane, PINNED in
    # smoke so the golden stays deterministic), one eager dispatch
    # proves the verdict routes (autotune.route.hits delta), and the
    # routed kernel races the flat CSR gather baseline.  Settings and
    # the process verdict store restore on exit: the autotuner must
    # stay inert for every other phase.
    if ((smoke
         or os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_AUTOTUNE",
                           "0") != "1")
            and not past_deadline(result, "autotune")):
        try:
            from legate_sparse_tpu import autotune as _at
            from legate_sparse_tpu import gallery as _gallery
            from legate_sparse_tpu.bench_timing import loop_ms_per_iter
            from legate_sparse_tpu.ops import spmv as _at_spmv
            from legate_sparse_tpu.settings import settings as _ast

            n_at = 1 << 10 if smoke else 1 << 18
            saved_at = _ast.autotune
            with obs.span("bench.autotune") as _sp, \
                    obs.memory.watermark("bench.autotune"):
                try:
                    _at.reset()
                    _ast.autotune = True
                    A_at = _gallery.powerlaw(
                        n_at, nnz_per_row=4 if smoke else 8, rng=11)
                    A_at.sum_duplicates()
                    x_at = jnp.ones((n_at,), dtype=A_at.dtype)
                    rec0 = obs.counters.get("autotune.verdict.records")
                    if smoke:
                        # Pinned verdict: no measurement, so the
                        # golden totals stay exact.
                        key_at = _at.key_for(A_at, "spmv")
                        _at.get_store().record(key_at, "sliced-ell", {})
                        label_at = "sliced-ell"
                    else:
                        verdict_at = _at.tune(A_at, x_at)
                        label_at = verdict_at.label
                    hits0 = obs.counters.get("autotune.route.hits")
                    y_at = A_at @ x_at    # eager: the verdict routes
                    _ = float(np.asarray(y_at[0]))
                    if obs.counters.get("autotune.route.hits") <= hits0:
                        raise RuntimeError(
                            "autotune verdict did not route "
                            "(decline ladder drifted?)")
                    # Kernel race at honest iteration counts: routing
                    # declines inside jitted loop bodies by design
                    # (tracer contexts), so the proof times the routed
                    # kernel and the CSR baseline directly.
                    bins_at = A_at._get_sliced_ell()
                    rid_at = A_at._get_row_ids()
                    # deadline_s bounds escalation per kernel: on the
                    # CPU lane the flat-CSR baseline runs seconds per
                    # iteration at 1<<18, and this phase must not eat
                    # the whole bench budget.
                    k_hi_at = 4 if smoke else None
                    sliced_ms = loop_ms_per_iter(
                        lambda v: _at_spmv.sliced_ell_spmv(
                            bins_at, v, n_at),
                        x_at, k_lo=2 if smoke else 5, k_hi=k_hi_at,
                        deadline_s=None if smoke else 90.0)
                    csr_ms = loop_ms_per_iter(
                        lambda v: _at_spmv.csr_spmv_rowids(
                            A_at.data, A_at.indices, rid_at, v, n_at),
                        x_at, k_lo=2 if smoke else 5, k_hi=k_hi_at,
                        deadline_s=None if smoke else 90.0)
                    result["irregular_spmv_n"] = n_at
                    result["irregular_spmv_nnz"] = A_at.nnz
                    result["irregular_spmv_ms"] = round(sliced_ms, 4)
                    result["irregular_csr_ms"] = round(csr_ms, 4)
                    result["irregular_spmv_speedup"] = round(
                        csr_ms / max(sliced_ms, 1e-9), 2)
                    result["irregular_spmv_path"] = label_at
                    result["autotune_verdicts"] = int(
                        obs.counters.get("autotune.verdict.records")
                        - rec0)
                    if _sp is not None:
                        _sp.set(n=n_at, nnz=A_at.nnz, path=label_at,
                                speedup=result[
                                    "irregular_spmv_speedup"])
                finally:
                    _ast.autotune = saved_at
                    _at.reset()
        except Exception as e:
            sys.stderr.write(f"bench: autotune phase failed: {e!r}\n")

    # Non-toy scale anchors (VERDICT r4 weak #6): one 1e6-row CG and
    # one 4096^2 pde datapoint, recorded REGARDLESS of tunnel state so
    # every round carries a scaling story (the r4 configs above are
    # deliberately small for the 1-core fallback; these two are the
    # BASELINE.md bring-up configs 2-3 at honest size).
    if (os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_SCALE", "0") != "1"
            and not smoke
            and not past_deadline(result, "cg_1m")):
        try:
            import time as _time

            import legate_sparse_tpu.linalg as linalg

            grid_m = 1000                    # 1e6 unknowns
            ngm2 = grid_m * grid_m
            main2 = np.full(ngm2, 4.0, np.float32)
            o1 = np.full(ngm2 - 1, -1.0, np.float32)
            o1[np.arange(1, grid_m) * grid_m - 1] = 0.0
            oN = np.full(ngm2 - grid_m, -1.0, np.float32)
            A_1m = sparse.diags(
                [main2, o1, o1, oN, oN], [0, 1, -1, grid_m, -grid_m],
                shape=(ngm2, ngm2), format="csr", dtype=np.float32,
            )
            b_1m = np.ones(ngm2, np.float32)

            def timed_1m(maxiter):
                best = float("inf")
                for rep in range(3):
                    t0 = _time.perf_counter()
                    xs, _ = linalg.cg(A_1m, b_1m, rtol=0.0,
                                      maxiter=maxiter)
                    _ = float(np.asarray(xs[0]))
                    if rep:
                        best = min(best, _time.perf_counter() - t0)
                return best

            t1, t2 = timed_1m(50), timed_1m(150)
            if t2 > t1:
                result["cg_1m_rows"] = ngm2
                result["cg_1m_ms_per_iter"] = round(
                    (t2 - t1) / 100 * 1e3, 4
                )
            else:
                sys.stderr.write(
                    f"bench: cg_1m timing unresolvable "
                    f"(t50={t1:.3f}s, t150={t2:.3f}s)\n")
        except Exception as e:
            sys.stderr.write(f"bench: cg_1m config failed: {e!r}\n")

    if (os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_SCALE", "0") != "1"
            and not smoke
            and not past_deadline(result, "pde_4096")):
        try:
            from legate_sparse_tpu.bench_timing import loop_ms_per_iter
            from legate_sparse_tpu.ops import dia_ops as _dops

            grid_p = 4096                    # BASELINE config 3
            np2 = grid_p * grid_p
            main3 = np.full(np2, 4.0, np.float32)
            p1 = np.full(np2 - 1, -1.0, np.float32)
            p1[np.arange(1, grid_p) * grid_p - 1] = 0.0
            pN = np.full(np2 - grid_p, -1.0, np.float32)
            A_p = sparse.diags(
                [main3, p1, p1, pN, pN], [0, 1, -1, grid_p, -grid_p],
                shape=(np2, np2), format="csr", dtype=np.float32,
            )
            x_p = jnp.ones((np2,), dtype=jnp.float32)
            b_p = jnp.full((np2,), 1e-6, dtype=jnp.float32)
            _ = A_p @ x_p        # build structure caches outside timing

            # The pde example's hot loop is the explicit update: ONE
            # SpMV + axpy per step — which is what this measures now.
            # rho(I - 0.25 A) <= 1 for this operator (spec(A) in
            # [0, 8]), so the chain is magnitude-stable by itself; the
            # r5 rsqrt-normalize pass was bench harness, not pde work,
            # and cost ~40% of the reported iteration.
            def pde_step(v):
                return v - 0.25 * (A_p @ v) + b_p

            ms_p = loop_ms_per_iter(pde_step, x_p, k_lo=2, k_hi=8)
            by_p = _spmv_bytes(A_p, x_p) + 4 * np2  # + the b read
            result["pde_grid"] = f"{grid_p}x{grid_p}"
            result["pde_ms_per_iter"] = round(ms_p, 3)
            result["pde_bytes_per_iter"] = by_p
            # Compressed-pipeline anchor (schema 15): the same
            # explicit update with bf16 operator AND state — the
            # magnitude-stable chain tolerates rounded state, and
            # compressed banded storage drops the DIA hole mask
            # (zero-filled band, ``compress()`` docstring), so the
            # iteration streams 16 bytes/row against f32's 37:
            # the recorded ratio is the tentpole's byte win.
            try:
                C_p = A_p.compress()
                vb_p = x_p.astype(jnp.bfloat16)
                bb_p = b_p.astype(jnp.bfloat16)

                def pde_step_bf16(v):
                    return v - 0.25 * (C_p @ v) + bb_p

                ms_pb = loop_ms_per_iter(pde_step_bf16, vb_p,
                                         k_lo=2, k_hi=8)
                by_pb = _spmv_bytes(C_p, vb_p) + 2 * np2
                result["pde_ms_per_iter_bf16"] = round(ms_pb, 3)
                result["pde_bytes_per_iter_bf16"] = by_pb
                result["pde_bytes_ratio"] = round(by_p / by_pb, 4)
                del C_p, vb_p, bb_p
            except Exception as e:
                sys.stderr.write(
                    f"bench: compressed pde failed: {e!r}\n")
            if stream:
                bound_p = by_p / (stream * 1e9) * 1e3
                result["pde_stream_bound_ms"] = round(bound_p, 3)
                result["pde_roofline_ratio"] = round(bound_p / ms_p, 4)
                if ms_p > 1.3 * bound_p:
                    # Itemize the residual: which part of the explicit
                    # update is off its bound, measured not asserted.
                    # Kernel-split terms only on the CPU lane — there
                    # the dispatch runs the XLA kernels being A/B'd
                    # below; on TPU the dispatch is the Pallas kernel,
                    # and subtracting an XLA-kernel loop from a
                    # Pallas-kernel loop would label the pallas-vs-XLA
                    # delta "axpy cost" (possibly negative).  The
                    # referee for axpy_b_ms/mask_ms is the SAME
                    # lowering the dispatch picked (settings can pin
                    # it to fused); pad_alloc_ms is always the
                    # fused-minus-nopad counterfactual.
                    try:
                        from legate_sparse_tpu.csr import _dia_xla_nopad

                        dia_p = A_p._get_dia()
                        pit = {
                            "measured_ms": round(ms_p, 3),
                            "bound_bw_ms": round(bound_p, 3),
                        }
                        if dia_p is not None and platform == "cpu":
                            datp, offp, mskp = dia_p
                            dpp, mpp = A_p._get_dia_fused()
                            use_nopad = _dia_xla_nopad()

                            def spmv_as_dispatched(v, mask_on=True):
                                if use_nopad:
                                    return _dops.dia_spmv_nopad(
                                        datp, mskp if mask_on else None,
                                        v, offp, A_p.shape)
                                return _dops.dia_spmv_fused(
                                    dpp, mpp if mask_on else None,
                                    v, offp, A_p.shape)

                            ms_sp = loop_ms_per_iter(
                                lambda v: v - 0.25 * spmv_as_dispatched(v),
                                x_p, k_lo=2, k_hi=8)
                            pit["axpy_b_ms"] = round(ms_p - ms_sp, 3)
                            if mskp is not None:
                                ms_nm = loop_ms_per_iter(
                                    lambda v: v - 0.25
                                    * spmv_as_dispatched(v, mask_on=False),
                                    x_p, k_lo=2, k_hi=8)
                                pit["mask_ms"] = round(ms_sp - ms_nm, 3)
                            ms_np = loop_ms_per_iter(
                                lambda v: v - 0.25 * _dops.dia_spmv_nopad(
                                    datp, mskp, v, offp, A_p.shape),
                                x_p, k_lo=2, k_hi=8) if not use_nopad \
                                else ms_sp
                            ms_fu = loop_ms_per_iter(
                                lambda v: v - 0.25 * _dops.dia_spmv_fused(
                                    dpp, mpp, v, offp, A_p.shape),
                                x_p, k_lo=2, k_hi=8) if use_nopad \
                                else ms_sp
                            pit["pad_alloc_ms"] = round(ms_fu - ms_np, 3)
                        result["pde_items"] = pit
                    except Exception as e:
                        sys.stderr.write(
                            f"bench: pde items failed: {e!r}\n")
        except Exception as e:
            sys.stderr.write(f"bench: pde_4096 config failed: {e!r}\n")

    # LAST on purpose, and in a THROWAWAY SUBPROCESS: bf16 compiles a
    # distinct Mosaic kernel the f32 canary ladder never validated; a
    # worker fault inside this process would cost the whole contract
    # line (the documented round-2 failure mode), so the subprocess
    # takes that risk and reports its numbers on stdout.
    # bfloat16 banded SpMV -- the TPU-native extension beyond the
    # reference's f32/f64 gate (README "dtype policy"): SpMV is
    # bandwidth-bound, so bf16 storage halves the traffic and should
    # land near 2x the f32 rate on chip.  Reported as its own keys;
    # the contract metric stays f32.
    if (os.environ.get("LEGATE_SPARSE_TPU_BENCH_SKIP_BF16", "0") != "1"
            and platform != "cpu"      # no native bf16 off-TPU
            and not past_deadline(result, "bf16")):
        import subprocess as _subp

        bf16_code = (
            "import json, sys\n"
            "import numpy as np\n"
            "import jax.numpy as jnp\n"
            "import legate_sparse_tpu as sparse\n"
            "import bench\n"
            f"n = {n}\n"
            f"A16 = bench._banded_config(sparse, n, {nnz_per_row}, "
            "dtype=jnp.bfloat16)\n"
            "x16 = jnp.full((n,), 1.0, dtype=jnp.bfloat16)\n"
            "ms = bench._time_spmv_ms(A16, x16, normalize=False, "
            "k_lo=5, k_hi=35)\n"
            "by = bench._spmv_bytes(A16, x16)\n"
            "print(json.dumps({'bf16_ms': round(ms, 4), "
            "'bf16_gbs': round(by / (ms * 1e-3) / 1e9, 2)}))\n"
        )
        try:
            r16 = _subp.run(
                [sys.executable, "-c", bf16_code],
                capture_output=True, text=True, timeout=600,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            parsed = None
            for ln in reversed((r16.stdout or "").strip().splitlines()):
                try:
                    parsed = json.loads(ln)
                    break
                except ValueError:
                    continue
            if r16.returncode == 0 and parsed:
                result.update(parsed)
            else:
                result["bf16_error"] = (
                    f"rc={r16.returncode}: "
                    + (r16.stderr or "")[-200:].strip()
                )
        except _subp.TimeoutExpired:
            result["bf16_error"] = "timeout"
        except Exception as e:
            result["bf16_error"] = repr(e)[:200]

    # Memory watermark of the whole run (the per-phase deltas live as
    # mem.* events in the trace artifact; the JSON keeps the headline).
    mem_final = obs.memory.snapshot()
    if "peak_rss_mb" in mem_final:
        result["mem_peak_rss_mb"] = mem_final["peak_rss_mb"]
    if "device_peak_mb" in mem_final:
        result["mem_device_peak_mb"] = mem_final["device_peak_mb"]

    result["bench_wall_s"] = round(_time_mod.perf_counter() - t_start, 1)

    if obs_requested or obs.enabled():
        # Structured perf artifact: every span/counter recorded by the
        # package during this run, Chrome-trace format (Perfetto /
        # tools/trace_summary.py both read it).
        import time as _ts

        trace_path = os.environ.get("LEGATE_SPARSE_TPU_OBS_FILE")
        if not trace_path:
            stamp = _ts.strftime("%Y%m%dT%H%M%S", _ts.gmtime())
            trace_path = f"BENCH_{stamp}.trace.json"
        n_spans = sum(1 for r in obs.records() if r["type"] == "span")
        try:
            obs.write_chrome_trace(
                trace_path,
                extra_metadata={"platform": platform,
                                "bench_result": result},
            )
            result["trace_file"] = trace_path
        except OSError as e:
            # The export must never cost the measurements (the round-2
            # lost-data failure mode): record the error, still print.
            sys.stderr.write(f"bench: trace export failed: {e!r}\n")
            result["trace_error"] = repr(e)[:200]
        result["trace_spans"] = n_spans
        print(json.dumps(result))
        if n_spans == 0:
            # Tracing was requested but produced nothing: the wiring
            # silently no-opped (e.g. a refactor dropped the spans).
            # Fail loudly so the driver can't archive empty evidence.
            sys.stderr.write(
                "bench: tracing requested but 0 spans recorded "
                f"({trace_path})\n"
            )
            sys.exit(1)
        return

    print(json.dumps(result))


if __name__ == "__main__":
    main()
