# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Headline benchmark: CSR SpMV achieved HBM bandwidth on one chip.

Prints ONE JSON line::

    {"metric": "csr_spmv_bandwidth", "value": <GB/s>, "unit": "GB/s",
     "vs_baseline": <fraction of measured stream bandwidth>, ...}

Config matches the reference's SpMV microbenchmark default (banded
matrix, nnz/row=11 — reference ``examples/spmv_microbenchmark.py:34-52``,
``examples/common.py:206-249``) at 2^24 rows (~870 MB of DIA traffic,
sized to match the stream measurement's so per-dispatch overhead does
not mask bandwidth; override via LEGATE_SPARSE_TPU_BENCH_LOG2_ROWS).  ``vs_baseline`` is the
achieved fraction of this chip's *measured* stream bandwidth (triad-style
copy), i.e. the roofline fraction BASELINE.md's north-star targets
(>= 0.70).  The reference publishes no absolute numbers (BASELINE.md).

Extra keys in the same JSON object (driver contract stays one line):
``platform`` (tpu/cpu), ``stream_gbs`` (measured roofline),
``irregular_gbs``/``irregular_frac`` (random-sparsity matrix through the
segment-sum fallback — the path banded ELL never exercises), and
``spmv_ms`` (raw per-iteration time).

Robustness: the TPU backend is probed in a SUBPROCESS with a timeout and
retries before this process commits to it — a hung or erroring tunnel
(round-1 failure: ``BENCH_r01.json`` rc=1 backend-init crash) degrades
to a CPU run with ``"platform": "cpu"`` recorded instead of losing the
round's data.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# Probe budget must stay well inside any plausible driver timeout: a
# hung tunnel costs (retries+1)*timeout before the CPU fallback starts,
# and the fallback run itself still needs a few minutes.
PROBE_TIMEOUT_S = int(os.environ.get("LEGATE_SPARSE_TPU_PROBE_TIMEOUT", "90"))
PROBE_RETRIES = int(os.environ.get("LEGATE_SPARSE_TPU_PROBE_RETRIES", "1"))


def _probe_accelerator() -> bool:
    """Can a fresh process initialize the default (accelerator) backend?

    Runs ``jax.devices()`` in a subprocess so a hang (unavailable TPU
    tunnel) costs a bounded timeout, not the whole bench.
    """
    code = (
        "import jax; ds = jax.devices(); "
        "assert ds and ds[0].platform != 'cpu', ds; print('ok')"
    )
    for attempt in range(PROBE_RETRIES + 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                timeout=PROBE_TIMEOUT_S,
                capture_output=True,
                text=True,
            )
            if r.returncode == 0 and "ok" in r.stdout:
                return True
            sys.stderr.write(
                f"bench: accelerator probe attempt {attempt + 1} failed "
                f"(rc={r.returncode}): {r.stderr.strip()[-400:]}\n"
            )
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"bench: accelerator probe attempt {attempt + 1} timed out "
                f"after {PROBE_TIMEOUT_S}s\n"
            )
        if attempt < PROBE_RETRIES:
            time.sleep(min(5 * (attempt + 1), 15))
    return False


def _time_fn(fn, *args, warmup: int = 5, iters: int = 20) -> float:
    import jax

    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def _stream_bandwidth() -> float:
    """Measured triad bandwidth (GB/s): z = a*x + y on 2^26 f32 lanes."""
    import jax
    import jax.numpy as jnp

    n = 1 << 26
    x = jnp.ones((n,), dtype=jnp.float32)
    y = jnp.ones((n,), dtype=jnp.float32)
    triad = jax.jit(lambda x, y: 1.000001 * x + y)
    dt = _time_fn(triad, x, y)
    bytes_moved = 3 * 4 * n  # read x, read y, write z
    return bytes_moved / dt / 1e9


def _banded_config(sparse, n: int, nnz_per_row: int):
    half = nnz_per_row // 2
    offsets = list(range(-half, half + 1))
    diagonals = [np.full(n - abs(o), 1.0, dtype=np.float32) for o in offsets]
    return sparse.diags(diagonals, offsets, shape=(n, n), format="csr",
                        dtype=np.float32)


def _irregular_config(sparse, n: int, nnz_per_row: int):
    """Random-sparsity CSR with skewed row lengths: defeats the ELL
    budget (one heavy row) so the segment-sum fallback is what runs."""
    rng = np.random.default_rng(0)
    counts = rng.integers(1, 2 * nnz_per_row, size=n).astype(np.int64)
    counts[0] = min(64 * nnz_per_row, n)  # heavy row blows the ELL budget
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    nnz = int(indptr[-1])
    indices = rng.integers(0, n, size=nnz).astype(np.int32)
    # Sort column indices within each row (canonical CSR).
    row_ids = np.repeat(np.arange(n), counts)
    order = np.lexsort((indices, row_ids))
    indices = indices[order]
    data = np.ones(nnz, dtype=np.float32)
    return sparse.csr_array((data, indices, indptr), shape=(n, n))


def _spmv_bytes(A, x) -> int:
    """Byte-traffic model matching the kernel that actually runs.

    With an active DIA cache (exactly-banded matrix) the shifted-add
    kernel streams the (num_diags, cols) diagonal array + x + y.  With
    an active ELL cache (``A._get_ell()``) the kernel streams the
    (rows, W) padded data/cols blocks + per-row counts (never indptr);
    otherwise the cached-structure path (``csr_spmv_rowids``) reads
    values + column indices + an nnz-length row-id array + x, and
    writes y.
    """
    n = A.shape[0]
    dia = A._get_dia()
    if dia is not None:
        dia_data, _offsets, mask = dia
        return int(
            dia_data.size * dia_data.dtype.itemsize
            + (mask.size * mask.dtype.itemsize if mask is not None else 0)
            + x.size * x.dtype.itemsize
            + n * dia_data.dtype.itemsize
        )
    ell = A._get_ell()
    if ell is not None:
        ell_data, ell_cols, ell_counts = ell
        return int(
            ell_data.size * ell_data.dtype.itemsize
            + ell_cols.size * ell_cols.dtype.itemsize
            + ell_counts.size * ell_counts.dtype.itemsize
            + n * x.dtype.itemsize          # gathered x (≥; gathers re-read)
            + n * ell_data.dtype.itemsize   # written y
        )
    nnz = A.nnz
    row_ids = A._get_row_ids()
    return int(
        nnz * (A.data.dtype.itemsize + A.indices.dtype.itemsize)
        + row_ids.size * row_ids.dtype.itemsize
        + n * x.dtype.itemsize
        + n * A.data.dtype.itemsize
    )


def main() -> None:
    use_accel = _probe_accelerator()
    if not use_accel:
        from legate_sparse_tpu._platform import pin_cpu

        pin_cpu()

    import jax
    import jax.numpy as jnp

    import legate_sparse_tpu as sparse

    try:
        platform = jax.devices()[0].platform
    except RuntimeError as e:  # probe passed but in-process init failed
        sys.stderr.write(f"bench: backend init failed in-process: {e}\n")
        from legate_sparse_tpu._platform import pin_cpu

        pin_cpu()
        platform = jax.devices()[0].platform

    # Size the banded config so its byte traffic (~870 MB at 2^24 rows,
    # W=11, f32) matches the stream measurement's (~800 MB): this chip
    # has a multi-ms fixed dispatch overhead per op, so a small working
    # set would measure overhead, not bandwidth.  Overridable for
    # smaller test chips.
    n = 1 << int(os.environ.get("LEGATE_SPARSE_TPU_BENCH_LOG2_ROWS", "24"))
    nnz_per_row = 11
    A = _banded_config(sparse, n, nnz_per_row)
    x = jnp.ones((n,), dtype=jnp.float32)

    # Time the shipped hot path (A @ x -> cached ELL kernel), exactly
    # what every solver iteration executes.
    dt = _time_fn(lambda: A @ x)
    bw = _spmv_bytes(A, x) / dt / 1e9

    stream = _stream_bandwidth()

    # Secondary config: irregular matrix -> segment-sum fallback path.
    irregular_gbs = None
    try:
        A_ir = _irregular_config(sparse, n // 4, nnz_per_row)
        x_ir = jnp.ones((A_ir.shape[0],), dtype=jnp.float32)
        dt_ir = _time_fn(lambda: A_ir @ x_ir)
        irregular_gbs = _spmv_bytes(A_ir, x_ir) / dt_ir / 1e9
    except Exception as e:  # secondary metric must not kill the headline
        sys.stderr.write(f"bench: irregular config failed: {e!r}\n")

    # The contract metric (vs_baseline >= 0.70 of TPU HBM roofline) must
    # not be satisfiable by the CPU fallback: report null off-TPU and put
    # the fallback's roofline fraction in its own key.
    frac = round(bw / stream, 4)
    result = {
        "metric": "csr_spmv_bandwidth",
        "value": round(bw, 2),
        "unit": "GB/s",
        "vs_baseline": frac if platform != "cpu" else None,
        "platform": platform,
        "stream_gbs": round(stream, 2),
        "spmv_ms": round(dt * 1e3, 4),
        "path": ("dia" if A._get_dia() is not None
                 else "ell" if A._get_ell() is not None else "csr"),
    }
    if platform == "cpu":
        result["cpu_vs_baseline"] = frac
    if irregular_gbs is not None:
        result["irregular_gbs"] = round(irregular_gbs, 2)
        result["irregular_frac"] = round(irregular_gbs / stream, 4)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
