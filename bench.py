# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Headline benchmark: CSR SpMV achieved HBM bandwidth on one chip.

Prints ONE JSON line::

    {"metric": "csr_spmv_bandwidth", "value": <GB/s>, "unit": "GB/s",
     "vs_baseline": <fraction of measured stream bandwidth>}

Config matches the reference's SpMV microbenchmark default (banded
matrix, nnz/row=11 — reference ``examples/spmv_microbenchmark.py:34-52``,
``examples/common.py:206-249``) at 2^20 rows.  ``vs_baseline`` is the
achieved fraction of this chip's *measured* stream bandwidth (triad-style
copy), i.e. the roofline fraction BASELINE.md's north-star targets
(>= 0.70).  The reference publishes no absolute numbers (BASELINE.md).
"""

from __future__ import annotations

import json
import time

import numpy as np


def _time_fn(fn, *args, warmup: int = 5, iters: int = 20) -> float:
    import jax

    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def _stream_bandwidth() -> float:
    """Measured triad bandwidth (GB/s): z = a*x + y on 2^26 f32 lanes."""
    import jax
    import jax.numpy as jnp

    n = 1 << 26
    x = jnp.ones((n,), dtype=jnp.float32)
    y = jnp.ones((n,), dtype=jnp.float32)
    triad = jax.jit(lambda x, y: 1.000001 * x + y)
    dt = _time_fn(triad, x, y)
    bytes_moved = 3 * 4 * n  # read x, read y, write z
    return bytes_moved / dt / 1e9


def main() -> None:
    import jax
    import jax.numpy as jnp

    import legate_sparse_tpu as sparse

    n = 1 << 20
    nnz_per_row = 11
    half = nnz_per_row // 2
    offsets = list(range(-half, half + 1))
    diagonals = [np.full(n - abs(o), 1.0, dtype=np.float32) for o in offsets]
    A = sparse.diags(diagonals, offsets, shape=(n, n), format="csr",
                     dtype=np.float32)
    x = jnp.ones((n,), dtype=jnp.float32)

    # Time the shipped hot path (A @ x -> cached ELL kernel), exactly
    # what every solver iteration executes.
    dt = _time_fn(lambda: A @ x)

    data, indices, indptr = A.data, A.indices, A.indptr
    nnz = A.nnz
    # Byte traffic (BASELINE.md): values + column indices + row pointers
    # + gathered x + written y.
    bytes_moved = (
        nnz * (data.dtype.itemsize + indices.dtype.itemsize)
        + (n + 1) * indptr.dtype.itemsize
        + n * x.dtype.itemsize
        + n * data.dtype.itemsize
    )
    bw = bytes_moved / dt / 1e9
    stream = _stream_bandwidth()
    print(json.dumps({
        "metric": "csr_spmv_bandwidth",
        "value": round(bw, 2),
        "unit": "GB/s",
        "vs_baseline": round(bw / stream, 4),
    }))


if __name__ == "__main__":
    main()
