# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Shared benchmark harness for the examples.

Parity target: the reference's ``examples/common.py`` (triple-backend
``--package`` switch ``common.py:162-199``, Timer protocol
``common.py:52-101``, matrix generators ``common.py:206-347``).

TPU-first re-design:

- Backends are ``tpu`` (this framework: jax-backed sparse + jitted
  solvers) and ``scipy`` (host differential baseline).  The reference's
  third backend (cupy) has no TPU analog.
- ``JaxTimer`` brackets timed regions with a host round-trip fetch —
  the XLA analog of ``legate.timing.time``'s implicit execution fence
  (reference ``common.py:52-66``), and the only sync that holds on
  detached-dispatch backends (see the class docstring).
- Phase scoping (reference ``Machine.only`` CPU-build/GPU-solve,
  ``common.py:128-159``) is a no-op scope: on TPU the build phase runs
  on host numpy and the solve phase under jit — the split is structural
  rather than machine-scoped.
- Matrix generators build with vectorized host numpy, then hand off to
  the sparse package; every generator matches the reference's output
  pattern exactly (checked by tests/test_examples.py).
"""

import argparse
import importlib
import importlib.util
import os
import sys

import numpy

# Make the repo checkout importable when examples run uninstalled
# (`python examples/pde.py` puts examples/ on sys.path, not the root).
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if os.path.isdir(os.path.join(_ROOT, "legate_sparse_tpu")):
    # find_spec tests importability without executing the package (the
    # scipy baseline path must stay JAX-free, and importing the package
    # pulls in jax).
    if importlib.util.find_spec("legate_sparse_tpu") is None:
        sys.path.insert(0, _ROOT)


def harness_float():
    """Value dtype for the matrix generators.

    tpu package runs follow the platform policy (f32 on TPU where f64
    is emulated, scipy-parity f64 on CPU — ``settings.x64`` auto mode),
    avoiding f64 host arrays that would be silently downcast at device
    put.  ``--package scipy`` runs always get float64: the host
    differential baseline keeps its independent f64 precision and stays
    JAX-free."""
    sparse_mod = globals().get("sparse")
    if sparse_mod is not None and sparse_mod.__name__.startswith("scipy"):
        return numpy.float64
    try:
        from legate_sparse_tpu.runtime import runtime

        return runtime.default_float
    except Exception:
        return numpy.float64


def get_arg_number(arg: str) -> int:
    """Parse '4k' / '2m' / '1g' style sizes (reference ``common.py:22-37``)."""
    arg = arg.lower()
    if not arg:
        return 1
    mult = 1
    if arg[-1] == "k":
        mult, arg = 1024, arg[:-1]
    elif arg[-1] == "m":
        mult, arg = 1024 * 1024, arg[:-1]
    elif arg[-1] == "g":
        mult, arg = 1024 * 1024 * 1024, arg[:-1]
    return int(arg) * mult


class JaxTimer:
    """Wall-clock timer with device synchronization at both ends.

    Synchronization is a host ROUND TRIP (fetch a scalar computed from
    a device buffer), not ``block_until_ready``: on detached-dispatch
    backends (the axon TPU tunnel) ``block_until_ready`` returns at
    dispatch-ack, before the device finishes, and a barrier-timed
    region measures nothing (see ``legate_sparse_tpu/bench_timing.py``).
    Execution is in-order per device, so fetching a freshly dispatched
    scalar waits for all previously dispatched work.
    """

    def __init__(self):
        self._start = None
        self._token = None

    def _sync(self):
        import jax
        import jax.numpy as jnp

        jax.effects_barrier()
        if self._token is None:
            self._token = jnp.zeros((1,), jnp.float32)
        # Device-dependent fetch: queued behind all prior dispatches.
        float((self._token + 1.0)[0])

    def start(self):
        import time

        # Drain everything already dispatched so it is not charged to
        # the timed region (the reference's implicit fence).
        self._sync()
        self._start = time.perf_counter_ns()

    def stop(self, result=None):
        """Milliseconds since start(); round-trip syncs (on ``result``'s
        first element if given — the cheapest true completion proof)."""
        import time
        import numpy as _np

        if result is not None:
            import jax

            leaves = jax.tree_util.tree_leaves(result)
            for leaf in leaves:
                if hasattr(leaf, "ravel") and getattr(leaf, "size", 0):
                    float(_np.asarray(leaf.ravel()[0]))
                    break
            else:
                self._sync()
        else:
            self._sync()
        return (time.perf_counter_ns() - self._start) / 1e6


class NumPyTimer:
    def __init__(self):
        self._start = None

    def start(self):
        import time

        self._start = time.perf_counter_ns()

    def stop(self, result=None):
        import time

        return (time.perf_counter_ns() - self._start) / 1e6


class DummyScope:
    """No-op context manager standing in for the reference's
    phase-scoped Machine contexts (``common.py:104-159``)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def get_phase_procs(use_tpu: bool):
    """Build/solve phase scopes.  On TPU both phases are the whole
    device set; XLA owns placement (reference ``common.py:128-159``)."""
    return DummyScope(), DummyScope()


def parse_common_args():
    parser = argparse.ArgumentParser(add_help=False)
    parser.add_argument(
        "--package",
        type=str,
        default="tpu",
        choices=["tpu", "legate", "scipy"],
        help="'tpu' (alias 'legate') = this framework; 'scipy' = host baseline",
    )
    parser.add_argument(
        "--profile",
        type=str,
        default=None,
        metavar="DIR",
        help="capture a jax.profiler trace of the run into DIR "
             "(the Legion Prof analog: named_scope provenance from the "
             "coverage layer shows up as trace annotations)",
    )
    args, _ = parser.parse_known_args()

    if args.package in ("tpu", "legate"):
        # Probe the accelerator BEFORE any jax backend init: a dead
        # tunnel hangs indefinitely on first device use (it does not
        # error), and the environment's sitecustomize re-overrides
        # JAX_PLATFORMS, so env-pinning alone cannot save the run.
        # Degrades to the cpu platform when unreachable — same policy
        # as bench.py / __graft_entry__ / tests/conftest.py.
        from legate_sparse_tpu import _platform

        _platform.ensure_live_backend()

    if args.profile and args.package in ("tpu", "legate"):
        # tpu path only: the scipy baseline must stay JAX-free (and its
        # trace would carry none of the named_scope annotations anyway).
        import atexit

        import jax

        jax.profiler.start_trace(args.profile)
        atexit.register(jax.profiler.stop_trace)
        print(f"profiling -> {args.profile} (view with TensorBoard)")
    elif args.profile:
        print("--profile ignored for --package scipy (JAX-free baseline)")

    if args.package in ("tpu", "legate"):
        timer = JaxTimer()
        np_mod = numpy
        sparse = importlib.import_module("legate_sparse_tpu")
        linalg = importlib.import_module("legate_sparse_tpu.linalg")
        use_tpu = True
    else:
        timer = NumPyTimer()
        np_mod = numpy
        sparse = importlib.import_module("scipy.sparse")
        linalg = importlib.import_module("scipy.sparse.linalg")
        use_tpu = False

    globals()["np"] = np_mod
    globals()["sparse"] = sparse
    globals()["linalg"] = linalg
    return args.package, timer, np_mod, sparse, linalg, use_tpu


def banded_matrix(N: int, nnz_per_row: int, from_diags: bool = False):
    """Banded CSR with 1.0 values (reference ``common.py:206-249``).

    ``from_diags=False`` builds (data, indices, indptr) directly with
    vectorized numpy — same construction the reference uses, minus its
    per-backend branching.
    """
    if from_diags:
        return sparse.diags(
            [1.0] * nnz_per_row,
            [d - nnz_per_row // 2 for d in range(nnz_per_row)],
            shape=(N, N),
            format="csr",
            dtype=harness_float(),
        )
    assert N > nnz_per_row and nnz_per_row % 2 == 1
    half = nnz_per_row // 2
    cols = numpy.tile(
        numpy.arange(-half, nnz_per_row - half), N
    ) + numpy.repeat(numpy.arange(N), nnz_per_row)
    mask = (cols >= 0) & (cols < N)
    cols = cols[mask]
    data = numpy.ones(cols.shape[0], dtype=harness_float())
    counts = mask.reshape(N, nnz_per_row).sum(axis=1)
    indptr = numpy.zeros(N + 1, dtype=numpy.int64)
    numpy.cumsum(counts, out=indptr[1:])
    return sparse.csr_array(
        (data, cols.astype(numpy.int64), indptr), shape=(N, N)
    )


def stencil_grid(S, grid, dtype=None):
    """CSR operator applying stencil ``S`` over an N-D ``grid`` with
    zero (Dirichlet) boundaries (reference ``common.py:252-310``).

    Vectorized: one DIA band per nonzero stencil entry, boundary
    connections zeroed by index arithmetic instead of slice loops.
    """
    dtype = harness_float() if dtype is None else dtype
    S = numpy.asarray(S, dtype=dtype)
    grid = tuple(int(g) for g in grid)
    N_v = int(numpy.prod(grid))
    strides = numpy.cumprod([1] + list(reversed(grid)))[:-1][::-1]

    offsets = []
    bands = []
    centered = [idx - (s // 2) for idx, s in zip(numpy.nonzero(S), S.shape)]
    coords_nd = numpy.unravel_index(numpy.arange(N_v), grid)
    for entry in range(centered[0].shape[0]):
        off_nd = [int(c[entry]) for c in centered]
        diag = int(sum(o * st for o, st in zip(off_nd, strides)))
        if abs(diag) >= N_v:
            continue
        val = S[tuple(idx[entry] for idx in numpy.nonzero(S))]
        band = numpy.full(N_v, val, dtype=dtype)
        # Zero connections that would wrap across the grid boundary:
        # position p connects to p+diag only if every coordinate stays
        # in range after the per-axis offset.
        ok = numpy.ones(N_v, dtype=bool)
        for axis, o in enumerate(off_nd):
            c = coords_nd[axis]
            ok &= (c + o >= 0) & (c + o < grid[axis])
        band[~ok] = 0.0
        # DIA convention: band value for column j lives at band[j].
        shifted = numpy.zeros(N_v, dtype=dtype)
        src = numpy.arange(N_v)
        dst = src + diag
        sel = (dst >= 0) & (dst < N_v)
        shifted[dst[sel]] = band[src[sel]]
        offsets.append(diag)
        bands.append(shifted)

    offsets_a = numpy.array(offsets)
    order = numpy.argsort(offsets_a)
    uniq, inv = numpy.unique(offsets_a[order], return_inverse=True)
    data = numpy.zeros((uniq.shape[0], N_v), dtype=dtype)
    for k, band in enumerate(numpy.asarray(bands)[order]):
        data[inv[k]] += band
    return sparse.dia_array((data, uniq), shape=(N_v, N_v)).tocsr()


def poisson2D(N: int):
    """5-point 2-D Poisson operator, N*N unknowns (reference
    ``common.py:313-327``)."""
    first = numpy.full(N - 1, -1.0)
    chunks = numpy.concatenate([numpy.zeros(1), first])
    diag_size = N * N - 1
    diag_a = numpy.concatenate(
        [first, numpy.tile(chunks, (diag_size - (N - 1)) // N)]
    )
    diag_g = -1.0 * numpy.ones(N * (N - 1))
    diag_c = 4.0 * numpy.ones(N * N)
    return sparse.diags(
        [diag_g, diag_a, diag_c, diag_a, diag_g],
        [-N, -1, 0, 1, N],
        dtype=harness_float(),
    ).tocsr()


def diffusion2D(N: int, epsilon: float = 1.0, theta: float = 0.0):
    """9-point rotated-anisotropy diffusion operator (reference
    ``common.py:330-347``)."""
    eps = float(epsilon)
    C = numpy.cos(float(theta))
    S = numpy.sin(float(theta))
    CS, CC, SS = C * S, C * C, S * S
    a = (-1 * eps - 1) * CC + (-1 * eps - 1) * SS + (3 * eps - 3) * CS
    b = (2 * eps - 4) * CC + (-4 * eps + 2) * SS
    c = (-1 * eps - 1) * CC + (-1 * eps - 1) * SS + (-3 * eps + 3) * CS
    d = (-4 * eps + 2) * CC + (2 * eps - 4) * SS
    e = (8 * eps + 8) * CC + (8 * eps + 8) * SS
    stencil = numpy.array([[a, b, c], [d, e, d], [c, b, a]]) / 6.0
    return stencil_grid(stencil, (N, N))
