# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Geometric multigrid-preconditioned CG for 2-D Poisson/diffusion
(reference ``examples/gmg.py``): V-cycle preconditioner with weighted-
Jacobi smoothing, injection/linear intergrid transfer operators built as
CSR, and Galerkin coarse operators ``A_c = R @ A @ P`` via SpGEMM
(reference ``gmg.py:90-102``).

TPU-first notes:
- Restriction operators are built with vectorized numpy (the reference
  builds the linear operator with a per-row Python loop,
  ``gmg.py:215-292``).
- The V-cycle is pure traceable ops over cached-structure CSR matrices,
  so the whole preconditioned CG solve runs inside one jitted
  while_loop (reference runs it as a Python-driven deferred pipeline).
"""

import argparse

import numpy

from common import diffusion2D, get_phase_procs, parse_common_args, poisson2D


def max_eigenvalue(A, iters=15):
    """Spectral-radius estimate by power iteration + Rayleigh quotient
    (reference ``gmg.py:146-158``)."""
    rng = numpy.random.default_rng(7)
    x1 = rng.random(A.shape[1]).reshape(-1, 1)
    for _ in range(iters):
        x1 = np.asarray(A @ x1)
        x1 = x1 / np.linalg.norm(x1)
    return float(np.dot(x1.T, np.asarray(A @ x1)).item())


class WeightedJacobi:
    """Weighted-Jacobi smoother, omega scaled by the spectral radius of
    D^-1 A per level (reference ``gmg.py:146-198``).  ``power_iters``
    controls the spectral-radius power iteration (the reference leaves
    the count to the caller; 1 matches its examples' usage but is a
    crude Rayleigh quotient — raise it for run-for-run parity checks)."""

    def __init__(self, omega=4.0 / 3.0, power_iters=1):
        self.level_params = []
        self._init_omega = omega
        self._power_iters = power_iters

    def init_level_params(self, A, level):
        D_inv = 1.0 / np.asarray(A.diagonal())
        n = min(A.shape[0], A.shape[1])
        D_inv_mat = sparse.csr_array(
            (
                numpy.asarray(D_inv),
                (numpy.arange(n, dtype=numpy.int64),
                 numpy.arange(n, dtype=numpy.int64)),
            ),
            shape=A.shape,
        )
        spectral_radius = max_eigenvalue(A @ D_inv_mat, self._power_iters)
        omega = self._init_omega / spectral_radius
        self.level_params.append((omega, D_inv))
        assert len(self.level_params) - 1 == level

    def pre(self, A, r, x, level):
        assert x is None
        omega, D_inv = self.level_params[level]
        return omega * r * D_inv

    def post(self, A, r, x, level):
        omega, D_inv = self.level_params[level]
        return x + omega * (r - A @ x) * D_inv

    def coarse(self, A, r, x, level):
        return self.pre(A, r, x, level)


def injection_operator(fine_dim):
    """Injection restriction: coarse (i, j) samples fine (2i, 2j)
    (reference ``gmg.py:201-211``; index arithmetic corrected to the
    standard row-major even-point subsample)."""
    fine_shape = (int(numpy.sqrt(fine_dim)),) * 2
    coarse_shape = (fine_shape[0] // 2, fine_shape[1] // 2)
    coarse_dim = int(numpy.prod(coarse_shape))
    ij = numpy.arange(coarse_dim, dtype=numpy.int64)
    i = ij // coarse_shape[1]
    j = ij % coarse_shape[1]
    Rj = 2 * i * fine_shape[1] + 2 * j
    Rp = numpy.arange(coarse_dim + 1, dtype=numpy.int64)
    Rx = numpy.ones(coarse_dim, dtype=numpy.float64)
    R = sparse.csr_matrix((Rx, Rj, Rp), shape=(coarse_dim, fine_dim))
    return R, coarse_dim


def linear_operator(fine_dim):
    """Full-weighting (bilinear) restriction: 9-point stencil with
    weights 1/16, 2/16, 4/16 (reference ``gmg.py:215-292``), built
    vectorized instead of the reference's per-row loop."""
    fine_shape = (int(numpy.sqrt(fine_dim)),) * 2
    coarse_shape = (fine_shape[0] // 2, fine_shape[1] // 2)
    coarse_dim = int(numpy.prod(coarse_shape))
    ij = numpy.arange(coarse_dim, dtype=numpy.int64)
    ci = ij // coarse_shape[1]
    cj = ij % coarse_shape[1]

    rows, cols, vals = [], [], []
    for di, dj, w in (
        (-1, -1, 1 / 16), (-1, 0, 2 / 16), (-1, 1, 1 / 16),
        (0, -1, 2 / 16), (0, 0, 4 / 16), (0, 1, 2 / 16),
        (1, -1, 1 / 16), (1, 0, 2 / 16), (1, 1, 1 / 16),
    ):
        fi = 2 * ci + di
        fj = 2 * cj + dj
        ok = (fi >= 0) & (fi < fine_shape[0]) & (fj >= 0) & (
            fj < fine_shape[1]
        )
        rows.append(ij[ok])
        cols.append(fi[ok] * fine_shape[1] + fj[ok])
        vals.append(numpy.full(int(ok.sum()), w))
    R = sparse.csr_matrix(
        (
            numpy.concatenate(vals),
            (numpy.concatenate(rows), numpy.concatenate(cols)),
        ),
        shape=(coarse_dim, fine_dim),
    )
    return R, coarse_dim


class GMG:
    """Geometric multigrid V-cycle used as a CG preconditioner
    (reference ``gmg.py:61-143``)."""

    def __init__(self, A, shape, levels, smoother, gridop, power_iters=1):
        self.A = A
        self.shape = shape
        self.N = int(numpy.prod(shape))
        self.levels = levels
        self.restriction_op = {
            "injection": injection_operator,
            "linear": linear_operator,
        }[gridop]
        self.smoother = {"jacobi": WeightedJacobi}[smoother](
            power_iters=power_iters
        )
        self.operators = self.compute_operators(A)

    def compute_operators(self, A):
        operators = []
        dim = self.N
        self.smoother.init_level_params(A, 0)
        for level in range(self.levels):
            R, dim = self.restriction_op(dim)
            P = R.T
            A = R @ A @ P  # Galerkin triple product: two SpGEMMs
            self.smoother.init_level_params(A, level + 1)
            operators.append((R, A, P))
        return operators

    def cycle(self, r):
        return self._cycle(self.A, r, 0)

    def _cycle(self, A, r, level):
        if level == self.levels - 1:
            return self.smoother.coarse(A, r, None, level=level)
        R, coarse_A, P = self.operators[level]
        x = self.smoother.pre(A, r, None, level=level)
        fine_r = r - A.dot(x)
        coarse_r = R.dot(fine_r)
        coarse_x = self._cycle(coarse_A, coarse_r, level + 1)
        x_corrected = x + P @ coarse_x
        return self.smoother.post(A, r, x_corrected, level=level)

    def linear_operator(self):
        return linalg.LinearOperator(
            self.A.shape, dtype=float, matvec=lambda r: self.cycle(r)
        )


def print_diagnostics(operators):
    """Multigrid hierarchy report (reference ``gmg.py:307-324``)."""
    output = "MultilevelSolver\n"
    output += f"Number of Levels:     {len(operators)}\n"
    total_nnz = sum(level[1].nnz for level in operators)
    output += "  level   unknowns     nonzeros\n"
    for n, level in enumerate(operators):
        A = level[1]
        ratio = 100 * A.nnz / total_nnz
        output += f"{n:>6} {A.shape[1]:>11} {A.nnz:>12} [{ratio:2.2f}%]\n"
    print(output)


def execute_distributed(N, data, gridop, levels, maxiter, tol, verbose,
                        power_iters, timer):
    """Distributed GMG+CG over the device mesh (DistCSR hierarchy +
    collective V-cycle) — the multi-chip rendition of this app."""
    import numpy as host_np

    from legate_sparse_tpu.parallel import DistGMG, shard_csr
    from legate_sparse_tpu.parallel.dist_csr import dist_cg
    from legate_sparse_tpu.parallel.mesh import make_row_mesh

    timer.start()
    rng = numpy.random.default_rng(0)
    if data == "poisson":
        from common import poisson2D as gen
        A = gen(N)
    elif data == "diffusion":
        from common import diffusion2D as gen
        A = gen(N)
    else:
        raise NotImplementedError(data)
    b = rng.random(N**2)
    print(f"GMG (distributed): {A.shape}")
    print(f"Data creation time: {timer.stop()} ms")

    timer.start()
    mesh = make_row_mesh()
    dA = shard_csr(A, mesh=mesh)
    gmg = DistGMG(dA, levels=levels, gridop=gridop,
                  power_iters=power_iters)
    print(f"GMG init time: {timer.stop()} ms")
    print(gmg.diagnostics())

    callback = None
    if verbose:
        def callback(x):
            print(f"Residual: {host_np.linalg.norm(b - np.asarray(A @ np.asarray(x)))}")

    timer.start()
    x, iters = dist_cg(dA, b, M=gmg.cycle, rtol=tol, maxiter=maxiter,
                       callback=callback)
    total = timer.stop(x)

    norm_ini = float(host_np.linalg.norm(b))
    norm_res = float(
        host_np.linalg.norm(b - host_np.asarray(A @ np.asarray(x)))
    )
    status = "Converged" if norm_res <= norm_ini * tol else (
        "Failed to converge"
    )
    print(
        f"{status} in {iters} iterations, final residual relative "
        f"norm: {norm_res / norm_ini}"
    )
    print(f"Solve Time: {total} ms")
    print(f"Iteration time: {total / max(int(iters), 1)} ms")


def execute(N, data, smoother, gridop, levels, maxiter, tol, verbose,
            warmup, timer, power_iters=1):
    build, solve = get_phase_procs(use_tpu)

    if warmup:
        tA = diffusion2D(64, epsilon=0.1, theta=numpy.pi / 4)
        tC = tA.T @ tA  # noqa: F841

    timer.start()
    rng = numpy.random.default_rng(0)
    if data == "poisson":
        A = poisson2D(N)
        b = rng.random(N**2)
    elif data == "diffusion":
        A = diffusion2D(N)
        b = rng.random(N**2)
    else:
        raise NotImplementedError(data)
    print(f"GMG: {A.shape}")
    print(f"Data creation time: {timer.stop()} ms")

    assert smoother == "jacobi"

    callback = None
    if verbose:
        def callback(x):
            print(f"Residual: {np.linalg.norm(b - np.asarray(A @ x))}")

    timer.start()
    mg_solver = GMG(A=A, shape=(N, N), levels=levels, smoother=smoother,
                    gridop=gridop, power_iters=power_iters)
    M = mg_solver.linear_operator()
    print(f"GMG init time: {timer.stop()} ms")
    print_diagnostics(mg_solver.operators)

    # Warm up kernels/caches outside the timed region.
    float(np.linalg.norm(np.asarray(A.dot(numpy.zeros(A.shape[1])))))
    float(np.linalg.norm(np.asarray(M.matvec(numpy.zeros(M.shape[1])))))

    timer.start()
    x, iters = linalg.cg(A, b, rtol=tol, maxiter=maxiter, M=M,
                         callback=callback)
    total = timer.stop(x)

    norm_ini = float(np.linalg.norm(b))
    norm_res = float(np.linalg.norm(b - np.asarray(A @ x)))
    if norm_res <= norm_ini * tol:
        print(
            f"Converged in {iters} iterations, final residual relative"
            f" norm: {norm_res / norm_ini}"
        )
    else:
        print(
            f"Failed to converge in {iters} iterations, final residual"
            f" relative norm: {norm_res / norm_ini}"
        )
    print(f"Solve Time: {total} ms")
    print(f"Iteration time: {total / iters} ms")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("-n", "--num", type=int, default=16, dest="N")
    parser.add_argument("-d", "--data", choices=["poisson", "diffusion"],
                        default="poisson")
    parser.add_argument("-s", "--smoother", choices=["jacobi"],
                        default="jacobi")
    parser.add_argument("-g", "--gridop", choices=["linear", "injection"],
                        default="injection")
    parser.add_argument("-l", "--levels", type=int, default=2)
    parser.add_argument("-m", "--maxiter", type=int, default=200)
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("--tol", type=float, default=1e-10)
    parser.add_argument("-w", "--warmup", action="store_true")
    parser.add_argument("--power-iters", type=int, default=1,
                        dest="power_iters",
                        help="spectral-radius power-iteration count")
    parser.add_argument("--distributed", action="store_true",
                        help="run the DistCSR/collective V-cycle path "
                        "over the device mesh")
    args, _ = parser.parse_known_args()
    _, timer, np, sparse, linalg, use_tpu = parse_common_args()
    if args.distributed:
        execute_distributed(
            N=args.N, data=args.data, gridop=args.gridop,
            levels=args.levels, maxiter=args.maxiter, tol=args.tol,
            verbose=args.verbose, power_iters=args.power_iters,
            timer=timer,
        )
    else:
        execute(
            N=args.N, data=args.data, smoother=args.smoother,
            gridop=args.gridop, levels=args.levels, maxiter=args.maxiter,
            tol=args.tol, verbose=args.verbose, warmup=args.warmup,
            timer=timer, power_iters=args.power_iters,
        )
