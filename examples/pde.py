# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""2-D Poisson PDE solver with Dirichlet boundaries (reference
``examples/pde.py``): penta-diagonal operator via ``diags().tocsr()``,
CG solve, ``--throughput`` mode subtracting warmup iterations.

On the tpu backend the whole CG solve runs as one jitted while_loop;
``--throughput`` therefore measures steady-state device iteration time
with zero host round-trips (reference measures Legion pipeline
throughput the same way, ``pde.py:180-205``).
"""

import argparse
import sys

from common import get_phase_procs, harness_float, parse_common_args


def d2_mat_dirichlet_2d(nx, ny, dx, dy):
    """Centered second-order 2-D Laplacian with Dirichlet BCs on the
    (nx-2)(ny-2) interior unknowns (reference ``pde.py:24-88``)."""
    a = 1.0 / dx**2
    g = 1.0 / dy**2
    c = -2.0 * a - 2.0 * g

    # x-coupling diagonal, zeroed where consecutive unknowns cross a
    # grid-row boundary (every (nx-2)-th entry after the first row).
    diag_size = (nx - 2) * (ny - 2) - 1
    diag_a = np.full(diag_size, a)
    diag_a[nx - 3 :: nx - 2] = 0.0
    diag_g = g * np.ones((nx - 2) * (ny - 3))
    diag_c = c * np.ones((nx - 2) * (ny - 2))
    return sparse.diags(
        [diag_g, diag_a, diag_c, diag_a, diag_g],
        [-(nx - 2), -1, 0, 1, nx - 2],
        dtype=harness_float(),
    ).tocsr()


def p_exact_2d(X, Y):
    """Exact solution for the manufactured rhs (reference ``pde.py:92-116``)."""
    return -1.0 / (2.0 * np.pi**2) * np.sin(np.pi * X) * np.cos(
        np.pi * Y
    ) - 1.0 / (50.0 * np.pi**2) * np.sin(5.0 * np.pi * X) * np.cos(
        5.0 * np.pi * Y
    )


def execute(nx, ny, throughput, tol, max_iters, warmup_iters, timer):
    xmin, xmax = 0.0, 1.0
    ymin, ymax = -0.5, 0.5
    dx = (xmax - xmin) / (nx - 1)
    dy = (ymax - ymin) / (ny - 1)

    build, solve = get_phase_procs(use_tpu)

    with build:
        x = np.linspace(xmin, xmax, nx)
        y = np.linspace(ymin, ymax, ny)
        X, Y = np.meshgrid(x, y, indexing="ij")
        b = np.sin(np.pi * X) * np.cos(np.pi * Y) + np.sin(
            5.0 * np.pi * X
        ) * np.cos(5.0 * np.pi * Y)
        if throughput:
            n = b.shape[0] - 2
            bflat = np.ones((n * n,))
        else:
            bflat = b[1:-1, 1:-1].flatten("F")
        A = d2_mat_dirichlet_2d(nx, ny, dx, dy)

    with solve:
        # Warm up: one SpMV builds/caches the matrix structure and
        # triggers kernel compilation before timing.
        _ = A.dot(np.ones((A.shape[1],)))

        if throughput:
            assert max_iters > warmup_iters
            p_sol, iters = linalg.cg(A, bflat, rtol=tol,
                                     maxiter=warmup_iters)
            max_iters = max_iters - warmup_iters
            print(f"max_iters has been updated to: {max_iters}")

        timer.start()
        if throughput:
            p_sol, iters = linalg.cg(A, bflat, rtol=tol, maxiter=max_iters)
        else:
            p_sol, iters = linalg.cg(A, bflat, rtol=tol)
        total = timer.stop(p_sol)

        if throughput:
            print(
                f"CG Mesh: {nx}x{ny}, A numrows: {A.shape[0]} , ms / iter:"
                f" {total / max_iters}"
            )
            sys.exit(0)
        norm_ini = float(np.linalg.norm(bflat))
        norm_res = float(np.linalg.norm(bflat - np.asarray(A @ p_sol)))
        if norm_res <= norm_ini * tol:
            print(
                f"CG converged after {iters} iterations, final residual"
                f" relative norm: {norm_res / norm_ini}"
            )
        else:
            print(
                f"CG didn't converge after {iters} iterations, final"
                f" residual relative norm: {norm_res / norm_ini}"
            )
        print(f"Total time: {total} ms")


def execute_explicit(nx, ny, max_iters, warmup_iters, timer):
    """Explicit damped-Jacobi update throughput: ``p' = p + tau (A p - b)``
    — ONE SpMV + axpy per step, the hot loop the bench's ``pde_*`` scale
    anchor measures.  ``tau`` is chosen inside the stability region
    (spec(A) in [-4(a+g), 0] by Gershgorin, so tau <= 0.5/(a+g) keeps
    ``I + tau A`` non-expansive); warmup iterations are subtracted like
    ``--throughput`` mode."""
    xmin, xmax = 0.0, 1.0
    ymin, ymax = -0.5, 0.5
    dx = (xmax - xmin) / (nx - 1)
    dy = (ymax - ymin) / (ny - 1)
    a, g = 1.0 / dx**2, 1.0 / dy**2
    tau = 0.4 / (a + g)

    build, solve = get_phase_procs(use_tpu)
    with build:
        A = d2_mat_dirichlet_2d(nx, ny, dx, dy)
        n = A.shape[0]
        b = np.ones((n,), dtype=harness_float())
        p = np.zeros((n,), dtype=harness_float())

    with solve:
        warmup = warmup_iters if warmup_iters else max(1, max_iters // 10)
        assert max_iters > warmup

        def step(v):
            return v + tau * (A.dot(v) - b)

        for _ in range(warmup):
            p = step(p)
        timer.start()
        for _ in range(max_iters - warmup):
            p = step(p)
        total = timer.stop(p)
        print(
            f"Explicit Mesh: {nx}x{ny}, A numrows: {n}, ms / iter:"
            f" {total / (max_iters - warmup)}"
        )


def execute_distributed(nx, ny, throughput, tol, max_iters, warmup_iters,
                        timer):
    """Distributed rendition: the interior Laplacian is built
    shard-locally (``dist_diags`` — the global CSR never exists on the
    host, the scale path for the 1e8-row north star) and solved with
    the collective CG over the device mesh."""
    import jax.numpy as jnp

    from legate_sparse_tpu.parallel.dist_build import dist_diags
    from legate_sparse_tpu.parallel.dist_csr import dist_cg
    from legate_sparse_tpu.parallel.mesh import make_row_mesh

    xmin, xmax = 0.0, 1.0
    ymin, ymax = -0.5, 0.5
    dx = (xmax - xmin) / (nx - 1)
    dy = (ymax - ymin) / (ny - 1)
    a = 1.0 / dx**2
    g = 1.0 / dy**2
    c = -2.0 * a - 2.0 * g
    m = nx - 2
    n = m * (ny - 2)

    def off1(i):
        # x-coupling zeroed across grid-row boundaries (same pattern as
        # the host build's strided-slice zeroing above).
        return jnp.where((i + 1) % m == 0, 0.0, a)

    timer.start()
    mesh = make_row_mesh()
    dA = dist_diags(
        [c, off1, off1, g, g], [0, 1, -1, m, -m], shape=(n, n),
        mesh=mesh, dtype=harness_float(),
        # Solver-only use: skip the ELL blocks, keep per-device matrix
        # memory at one DIA copy (the 1e8-row scale configuration).
        materialize_ell=False,
    )
    print(f"CG (distributed) Mesh: {nx}x{ny}, A numrows: {n}, "
          f"devices: {int(np.prod(mesh.devices.shape))}")
    print(f"Matrix build time: {timer.stop()} ms")

    if throughput:
        bflat = np.ones((n,))
        assert max_iters > warmup_iters
        _, _ = dist_cg(dA, bflat, rtol=tol, maxiter=warmup_iters)
        max_iters = max_iters - warmup_iters
    else:
        # Same manufactured rhs as the host path, so the two modes solve
        # the identical problem.
        xg = np.linspace(xmin, xmax, nx)
        yg = np.linspace(ymin, ymax, ny)
        X, Y = np.meshgrid(xg, yg, indexing="ij")
        bfield = np.sin(np.pi * X) * np.cos(np.pi * Y) + np.sin(
            5.0 * np.pi * X
        ) * np.cos(5.0 * np.pi * Y)
        bflat = bfield[1:-1, 1:-1].flatten("F")

    timer.start()
    p_sol, iters = dist_cg(
        dA, bflat, rtol=tol,
        maxiter=(max_iters if throughput else None),
    )
    total = timer.stop(p_sol)
    if throughput:
        print(f"ms / iter: {total / max_iters}")
        sys.exit(0)
    norm_ini = float(np.linalg.norm(bflat))
    from legate_sparse_tpu.parallel.dist_csr import shard_vector, dist_spmv

    xs = shard_vector(np.asarray(p_sol), dA.mesh, dA.rows_padded)
    norm_res = float(
        np.linalg.norm(bflat - np.asarray(dist_spmv(dA, xs))[:n])
    )
    status = "converged" if norm_res <= norm_ini * tol else (
        "didn't converge"
    )
    print(f"CG {status} after {iters} iterations, final residual"
          f" relative norm: {norm_res / norm_ini}")
    print(f"Total time: {total} ms")


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("-n", "--nx", type=int, default=128)
    parser.add_argument("-m", "--ny", type=int, default=128)
    parser.add_argument("-t", "--throughput", action="store_true")
    parser.add_argument("--tol", type=float, default=1e-10)
    parser.add_argument("-i", "--max-iters", type=int, default=None,
                        dest="max_iters")
    parser.add_argument("-w", "--warmup-iters", type=int, default=None,
                        dest="warmup_iters")
    parser.add_argument("--distributed", action="store_true",
                        help="shard-local build + collective CG over "
                             "the device mesh (tpu backend only)")
    parser.add_argument("--explicit", action="store_true",
                        help="measure the explicit damped-Jacobi "
                             "update (one SpMV + axpy per step) "
                             "instead of the CG solve")
    args, _ = parser.parse_known_args()
    _, timer, np, sparse, linalg, use_tpu = parse_common_args()

    if (args.throughput or args.explicit) and args.max_iters is None:
        print("Must provide --max-iters when using --throughput or "
              "--explicit.")
        sys.exit(1)

    if args.explicit:
        execute_explicit(
            nx=args.nx, ny=args.ny, max_iters=args.max_iters,
            warmup_iters=args.warmup_iters, timer=timer,
        )
        sys.exit(0)

    if args.distributed:
        if not use_tpu:
            print("--distributed requires the tpu (default) backend.")
            sys.exit(1)
        execute_distributed(
            nx=args.nx, ny=args.ny, throughput=args.throughput,
            tol=args.tol, max_iters=args.max_iters,
            warmup_iters=args.warmup_iters, timer=timer,
        )
        sys.exit(0)

    execute(
        nx=args.nx, ny=args.ny, throughput=args.throughput, tol=args.tol,
        max_iters=args.max_iters, warmup_iters=args.warmup_iters,
        timer=timer,
    )
