# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Spectral graph analysis end-to-end (beyond-reference example).

Pipeline on a random sparse graph, entirely through the package
surface: connected components (native min-label propagation), the
normalized graph Laplacian (device-built), its smallest eigenpairs
(native Lanczos ``eigsh``), and a spectral bipartition quality check.
With ``--package scipy`` the identical script runs on host scipy as a
baseline — both the numbers and the API calls line up one-to-one.

The reference has no graph or eigensolver surface (SURVEY §2); this
example exists to show the drop-in story extends beyond the
scipy.sparse core: ``csgraph`` + ``linalg.eigsh`` compose with the
same arrays the solvers use.

Run:
    python examples/spectral.py -n 4000 --clusters 4
    python examples/spectral.py --package scipy -n 4000
"""

import argparse

import numpy

from common import parse_common_args


def clustered_graph(n: int, clusters: int, p_in: float, p_out: float,
                    rng):
    """Sparse block-model adjacency: dense-ish within clusters, sparse
    across — the classic spectral-clustering testbed."""
    import scipy.sparse as host_sparse

    size = n // clusters
    blocks = []
    for i in range(clusters):
        row = []
        for j in range(clusters):
            p = p_in if i == j else p_out
            row.append(host_sparse.random(
                size, size, density=p, format="coo",
                random_state=rng))
        blocks.append(row)
    A = host_sparse.bmat(blocks, format="csr")
    A = ((A + A.T) > 0).astype(numpy.float64)
    A.setdiag(0)
    A.eliminate_zeros()
    return A.tocsr()


def main():
    parser = argparse.ArgumentParser(parents=[])
    parser.add_argument("-n", type=int, default=4000)
    parser.add_argument("--clusters", type=int, default=4)
    parser.add_argument("-k", type=int, default=6,
                        help="eigenpairs to compute")
    args, _ = parser.parse_known_args()

    package, timer, np, sparse, linalg, use_tpu = parse_common_args()

    rng = numpy.random.default_rng(0)
    host_A = clustered_graph(args.n, args.clusters, p_in=0.02,
                             p_out=0.0005, rng=rng)
    A = sparse.csr_array(host_A)
    print(f"graph: {A.shape[0]} nodes, {A.nnz} edges "
          f"({args.clusters} planted clusters), package={package}")

    if use_tpu:
        from legate_sparse_tpu import csgraph
    else:
        import scipy.sparse.csgraph as csgraph

    timer.start()
    ncomp, labels = csgraph.connected_components(A, directed=False)
    t_cc = timer.stop()
    print(f"connected components: {ncomp}  [{t_cc:.1f} ms]")

    timer.start()
    L = csgraph.laplacian(A, normed=True)
    t_lap = timer.stop()

    timer.start()
    w, V = linalg.eigsh(L, k=args.k, which="SA")
    t_eig = timer.stop()
    w = numpy.sort(numpy.asarray(w))
    print(f"laplacian [{t_lap:.1f} ms]; eigsh k={args.k} SA "
          f"[{t_eig:.1f} ms]")
    print("smallest normalized-Laplacian eigenvalues:",
          numpy.round(w, 5))

    # Fiedler-style check: the number of near-zero eigenvalues equals
    # the number of connected components; the spectral gap after the
    # cluster count reflects the planted structure.
    near_zero = int((w < 1e-8).sum())
    print(f"near-zero eigenvalues: {near_zero} "
          f"(= components: {near_zero == ncomp})")
    if args.clusters < args.k:
        gap = w[args.clusters] - w[args.clusters - 1]
        print(f"spectral gap after {args.clusters} clusters: {gap:.4f}")


if __name__ == "__main__":
    main()
