# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""SpGEMM microbenchmark (reference
``examples/spgemm_microbenchmark.py``).

``--stable`` reuses the same matrices (the framework's cached-structure
analog of Legion partition caching); without it, fresh matrices per
iteration measure the full build+multiply cost.
"""

import argparse

from common import (
    banded_matrix,
    get_arg_number,
    get_phase_procs,
    parse_common_args,
)


def get_matrices(N, nnz_per_row, fname1, fname2):
    if fname1:
        A = sparse.mmread(fname1)
        if not hasattr(A, "dot"):
            A = A.tocsr()
        B = sparse.mmread(fname2).tocsr() if fname2 else A.copy()
        return A, B
    A = banded_matrix(N, nnz_per_row)
    return A, A.copy()


def run_spgemm(N, nnz_per_row, fname1, fname2, iters, stable, timer):
    warmup = 5
    if stable:
        A, B = get_matrices(N, nnz_per_row, fname1, fname2)
        C = None
        for _ in range(warmup):
            C = A @ B
        timer.start()
        for _ in range(iters):
            C = A @ B
        total = timer.stop(C.data if hasattr(C, "data") else None)
    else:
        total = 0.0
        for i in range(iters + warmup):
            A, B = get_matrices(N, nnz_per_row, fname1, fname2)
            timer.start()
            C = A @ B
            t = timer.stop(C.data if hasattr(C, "data") else None)
            if i >= warmup:
                total += t
    Cnnz = (A @ B).nnz
    print(
        f"SPGEMM {A.shape}x{B.shape} , nnz ({A.nnz})x({B.nnz})->({Cnnz}) :"
        f" ms / iteration: {total / iters}"
    )


def run_spgemm_distributed(N, nnz_per_row, iters, timer):
    """Distributed banded product over the device mesh: exact-band
    operands ride the ppermute-halo Minkowski kernel (no all_gather)."""
    from legate_sparse_tpu.parallel import dist_spgemm, shard_csr
    from legate_sparse_tpu.parallel.mesh import make_row_mesh

    warmup = 5
    mesh = make_row_mesh()
    A = banded_matrix(N, nnz_per_row)
    dA = shard_csr(A, mesh=mesh)
    dB = shard_csr(A.copy(), mesh=mesh)
    C = None
    for _ in range(warmup):
        C = dist_spgemm(dA, dB)
    timer.start()
    for _ in range(iters):
        C = dist_spgemm(dA, dB)
    total = timer.stop(C.dia_data if C.dia_data is not None else C.data)
    path = "band" if C.dia_data is not None else "esc"
    print(
        f"SPGEMM (distributed, {path}) {A.shape}x{A.shape} over "
        f"{int(np.prod(mesh.devices.shape))} devices : "
        f"ms / iteration: {total / iters}"
    )


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("-n", "--nrows", type=str, default="1k", dest="n")
    parser.add_argument("--nnz-per-row", type=int, default=5,
                        dest="nnz_per_row")
    parser.add_argument("--stable", action="store_true")
    parser.add_argument("--filename1", dest="fname_first", type=str,
                        default="")
    parser.add_argument("--filename2", dest="fname_second", type=str,
                        default="")
    parser.add_argument("-i", "--iters", type=int, default=100)
    parser.add_argument("--distributed", action="store_true",
                        help="banded product over the device mesh "
                             "(tpu backend only)")
    args, _ = parser.parse_known_args()
    _, timer, np, sparse, linalg, use_tpu = parse_common_args()
    get_phase_procs(use_tpu)

    if args.distributed:
        if not use_tpu:
            raise SystemExit("--distributed requires the tpu backend")
        if args.stable or args.fname_first or args.fname_second:
            raise SystemExit(
                "--distributed benchmarks the banded config only; "
                "--stable/--filename1/--filename2 are not supported"
            )
        run_spgemm_distributed(
            get_arg_number(args.n), args.nnz_per_row, args.iters, timer
        )
        raise SystemExit(0)

    run_spgemm(
        get_arg_number(args.n),
        args.nnz_per_row,
        args.fname_first,
        args.fname_second,
        args.iters,
        args.stable,
        timer,
    )
