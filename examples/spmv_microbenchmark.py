# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""SpMV microbenchmark: banded matrix, N sweep (reference
``examples/spmv_microbenchmark.py``).

Prints ``SPMV rows: <N>, nnz: <nnz> , ms / iter: <t>`` per size, same
shape as the reference (``spmv_microbenchmark.py:52``).
"""

import argparse

from common import (
    banded_matrix,
    get_arg_number,
    get_phase_procs,
    parse_common_args,
)


def spmv_dispatch(A, x, y, i, repartition, use_out):
    if use_out:
        if repartition and i % 2:
            A.dot(y, out=x)
            return x
        A.dot(x, out=y)
        return y
    if repartition and i % 2:
        return A @ y
    return A @ x


def run_spmv(A, iters, repartition, timer, use_out):
    x = np.ones((A.shape[1],))
    y = np.zeros((A.shape[0],))
    assert not repartition or A.shape[0] == A.shape[1]

    last = None
    for i in range(5):  # warmup (reference uses 5)
        last = spmv_dispatch(A, x, y, i, repartition, use_out)

    timer.start()
    for i in range(iters):
        last = spmv_dispatch(A, x, y, i, repartition, use_out)
    total = timer.stop(last)

    print(
        f"SPMV rows: {A.shape[0]}, nnz: {A.nnz} , ms / iter: {total / iters}"
    )


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--nmin", type=str, default="1k")
    parser.add_argument("--nmax", type=str, default="1k")
    parser.add_argument("--nnz-per-row", type=int, default=11,
                        dest="nnz_per_row")
    parser.add_argument("--repartition", action="store_true")
    parser.add_argument("-f", "--filename", dest="fname", type=str,
                        default="")
    parser.add_argument("-i", "--iters", type=int, default=100)
    parser.add_argument("-d", "--from-diags", action="store_true",
                        dest="from_diags")
    parser.add_argument("--use-out", action="store_true", dest="use_out",
                        help="write into a preallocated output array")
    args, _ = parser.parse_known_args()
    _, timer, np, sparse, linalg, use_tpu = parse_common_args()
    init_procs, bench_procs = get_phase_procs(use_tpu)

    if args.fname:
        A = sparse.mmread(args.fname)
        if not hasattr(A, "dot"):
            A = A.tocsr()
        with bench_procs:
            run_spmv(A, args.iters, args.repartition, timer, args.use_out)
    else:
        N = get_arg_number(args.nmin)
        while N <= get_arg_number(args.nmax):
            with init_procs:
                A = banded_matrix(N, args.nnz_per_row, args.from_diags)
            with bench_procs:
                run_spmv(A, args.iters, args.repartition, timer,
                         args.use_out)
            N *= 2
