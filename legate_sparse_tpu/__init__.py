# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""legate_sparse_tpu: TPU-native distributed sparse linear algebra.

A drop-in ``scipy.sparse`` replacement built on JAX/XLA/Pallas with
``jax.sharding`` distribution — the TPU-native counterpart of the
reference Legion/CUDA framework (reference: ``legate_sparse/__init__.py``
which clones the scipy.sparse namespace over its native symbols,
``__init__.py:20-26``).

Usage::

    import legate_sparse_tpu as sparse
    A = sparse.diags([1, -2, 1], [-1, 0, 1], shape=(N, N), format="csr")
    y = A @ x
    x, iters = sparse.linalg.cg(A, y)
"""

import scipy.sparse as _scipy_sparse

from .runtime import runtime  # noqa: F401  (configures x64 at import)
from .module import *  # noqa: F401,F403
from .module import (  # explicit re-exports for linters
    csr_array, csr_matrix, dia_array, dia_matrix, diags, eye, identity,
    kron, tril, triu, load_npz, save_npz,
    mmread, mmwrite, spmv, spgemm_csr_csr_csr, issparse, isspmatrix,
    isspmatrix_csr, isspmatrix_dia, is_sparse_matrix, coord_ty, nnz_ty,
)
from .coverage import clone_module
from . import linalg  # noqa: F401
from . import parallel  # noqa: F401
from . import engine  # noqa: F401
from . import graph  # noqa: F401

__version__ = "25.07.1"

# Fill every remaining scipy.sparse name as a fallback so this module is
# namespace-complete (reference ``__init__.py:26``).
clone_module(_scipy_sparse, globals())

# clone_module re-exported scipy's csgraph module object verbatim
# (non-callable), which rejects this package's arrays; replace it with
# the adapted facade (native laplacian/connected_components + boundary-
# converted fallbacks).  NOTE: `from . import csgraph` would return the
# existing (scipy) attribute without importing the submodule — the
# absolute import forces ours and rebinds the package attribute.
import legate_sparse_tpu.csgraph  # noqa: F401,E402

csgraph = legate_sparse_tpu.csgraph

del _scipy_sparse, clone_module, legate_sparse_tpu
