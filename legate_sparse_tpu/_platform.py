# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Host-platform (CPU) pinning for tests / dryruns / fallbacks.

The execution environment's sitecustomize may force-register an
accelerator platform and override ``JAX_PLATFORMS`` programmatically,
so setting the env var alone is not sufficient — ``jax.config.update``
must be called after the jax import as well.  And the whole pin must
happen before any backend initializes: initializing an unavailable TPU
tunnel can hang indefinitely (the round-1 ``MULTICHIP`` failure mode).

Single source of truth for the three call sites: ``tests/conftest.py``,
``__graft_entry__.dryrun_multichip`` and ``bench.py``'s CPU fallback.
"""

from __future__ import annotations

import os
import re
import sys

_COUNT_FLAG = "--xla_force_host_platform_device_count"

# Subprocess snippet shared by bench.py and tools/tpu_capture.py: an
# accelerator is "reachable" only if backend init succeeds AND one op
# round-trips to completion (a crashed TPU worker can pass init but
# hang on execution).
ACCEL_PROBE_CODE = (
    "import jax, jax.numpy as jnp; ds = jax.devices(); "
    "assert ds and ds[0].platform != 'cpu', ds; "
    "assert float(jnp.ones((8, 128)).sum()) == 1024.0; print('ok')"
)

# State captured by the first pin_cpu() call, for restore_platform().
_saved: dict | None = None

# --------------------------------------------------------------------------
# TTL-cached probe verdict, shared with the tunnel watcher
# --------------------------------------------------------------------------
# A down tunnel costs (retries+1) * timeout_s of subprocess probing —
# 2 x 90 s at the defaults — and EVERY CLI entry point (bench, examples,
# dryrun) pays it again.  The verdict barely changes minute-to-minute,
# so it is cached in a small JSON state file shared between this module
# and ``tools/tunnel_watch.sh`` (which re-probes every 3 min anyway and
# keeps the cache warm): a second down-tunnel CLI run reaches compute in
# seconds instead of re-burning the full probe budget.
#
# Invalidation: the cache records whether the watcher's live-tunnel
# marker (``/tmp/tpu_alive``) existed at verdict time; a transition of
# that marker — the tunnel coming up or going down under a running
# watcher — makes the cached verdict stale immediately, TTL regardless.
# ``LEGATE_SPARSE_TPU_PROBE_FORCE=1`` bypasses the cache entirely
# (capture scripts set it so on-chip evidence never trusts a stale
# verdict), and ``LEGATE_SPARSE_TPU_PROBE_TTL=0`` disables caching.
#
# Only the DEAD verdict is ever served from the cache: committing to a
# backend on a cached "live" would reintroduce the indefinite-hang
# failure mode the subprocess probe ladder exists to prevent (a tunnel
# can die inside the TTL with no marker transition); a genuinely live
# tunnel answers its real probe in seconds anyway, so caching "live"
# buys little and risks everything.
_ALIVE_MARKER = "/tmp/tpu_alive"


def _probe_state_path() -> str:
    # uid-scoped default: on a shared host another user's state file
    # would be unwritable (sticky /tmp) AND would describe *their*
    # tunnel — and a world-writable fixed name would let any local
    # user plant a verdict.
    return os.environ.get(
        "LEGATE_SPARSE_TPU_PROBE_STATE",
        f"/tmp/lst_probe.{os.getuid()}.json")


def _probe_ttl_s() -> float:
    try:
        return float(os.environ.get("LEGATE_SPARSE_TPU_PROBE_TTL", "600"))
    except ValueError:
        return 600.0


def _tunnel_marker_alive() -> bool:
    return os.path.exists(_ALIVE_MARKER)


def read_cached_probe() -> bool | None:
    """The cached accelerator verdict, or None when no usable cache
    exists (missing/corrupt/expired file, forced fresh probe, or a
    live-tunnel-marker transition since the verdict was recorded).
    ``ensure_live_backend`` only ever ACTS on the False ("dead")
    verdict; True is informational (watcher dashboards, tests)."""
    import json
    import time

    ttl = _probe_ttl_s()
    if ttl <= 0 or os.environ.get(
            "LEGATE_SPARSE_TPU_PROBE_FORCE", "0") == "1":
        return None
    try:
        with open(_probe_state_path()) as f:
            st = json.load(f)
        if not isinstance(st, dict):
            return None
        # Wall clock is the contract here: the TTL compares against an
        # epoch timestamp recorded in a FILE shared with an external
        # watcher (tunnel_watch.sh) — monotonic time is per-process
        # and cannot age a cross-process artifact.
        age = time.time() - float(st["ts"])  # lint: disable=monotonic-clock — file-TTL vs shared epoch timestamp
        if age < 0 or age > ttl:
            return None
        verdict = st.get("verdict")
        if verdict not in ("live", "dead"):
            return None
        if bool(st.get("tunnel_marker")) != _tunnel_marker_alive():
            return None     # tunnel transitioned: verdict is stale
        # A verdict probed by a DIFFERENT interpreter does not speak
        # for this one: a watcher running a cpu-only-jax python would
        # otherwise pin every CLI (whose own python has the TPU
        # plugin) to cpu, 180 s-refreshed, forever.
        exe = st.get("exe")
        if not exe or os.path.realpath(exe) != os.path.realpath(
                sys.executable):
            return None
        return verdict == "live"
    except Exception:
        return None


def write_probe_state(live: bool, source: str = "probe") -> None:
    """Record a fresh probe verdict (atomic rename; best-effort — a
    read-only /tmp must never break the probe itself)."""
    import json
    import tempfile
    import time

    path = _probe_state_path()
    tmp = None
    try:
        payload = json.dumps({
            "verdict": "live" if live else "dead",
            "ts": time.time(),  # lint: disable=monotonic-clock — epoch ts read cross-process by tunnel_watch.sh
            "tunnel_marker": _tunnel_marker_alive(),
            "source": source,
            "pid": os.getpid(),
            "exe": sys.executable,
        })
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(path) or ".", prefix=".lst_probe.")
        with os.fdopen(fd, "w") as f:
            f.write(payload + "\n")
        os.replace(tmp, path)
        tmp = None
    except Exception:
        pass
    finally:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _obs_event(name: str, **attrs) -> None:
    """Structured trace event + always-on counter for probe/pinning
    outcomes — the machine-readable replacement for grepping stderr
    when a round's accelerator evidence goes missing."""
    from .obs import counters, trace

    counters.inc(name)
    trace.event(name, **attrs)


def pin_cpu(n_devices: int = 0, *, override_env: bool = True) -> None:
    """Pin jax to the host (cpu) platform with >= n_devices devices.

    Safe to call whether or not jax is already imported, but must run
    before any jax backend is initialized (XLA_FLAGS and platform
    selection are frozen at first backend init).  ``n_devices=0`` pins
    the platform without touching the virtual device count.
    """
    global _saved
    if _saved is None:
        _saved = {
            "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS"),
            "XLA_FLAGS": os.environ.get("XLA_FLAGS"),
        }

    if override_env:
        os.environ["JAX_PLATFORMS"] = "cpu"
    else:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    if n_devices > 0:
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(_COUNT_FLAG + r"=(\d+)", flags)
        if m is None:
            flags = f"{flags} {_COUNT_FLAG}={n_devices}".strip()
            os.environ["XLA_FLAGS"] = flags
        elif int(m.group(1)) < n_devices:
            os.environ["XLA_FLAGS"] = (
                flags[: m.start()] + f"{_COUNT_FLAG}={n_devices}"
                + flags[m.end():]
            )

    import jax
    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        # Too late for XLA_FLAGS to take effect; still repoint the
        # platform selection and drop stale backend caches.
        sys.stderr.write(
            "legate_sparse_tpu: pin_cpu called after backend init; "
            "clearing backends (virtual device count may be stale)\n"
        )
        _obs_event("platform.pin_cpu_late", n_devices=n_devices)
        from jax.extend.backend import clear_backends

        clear_backends()
    if "jax_platforms_prior" not in _saved:
        _saved["jax_platforms_prior"] = jax.config.jax_platforms
    jax.config.update("jax_platforms", "cpu")
    _re_resolve_dtype_policy()


def _re_resolve_dtype_policy() -> None:
    """The x64 default is platform-dependent (settings ``auto`` mode),
    and the package is usually imported *before* pin_cpu runs (importing
    this module imports the package) — so re-resolve after repinning."""
    from .settings import settings, _resolve_x64

    import jax

    settings.x64 = _resolve_x64()
    jax.config.update("jax_enable_x64", settings.x64)


def ensure_live_backend(timeout_s: int | None = None,
                        retries: int | None = None) -> bool:
    """Probe the default accelerator in a subprocess (a dead tunnel
    hangs rather than errors); pin the cpu platform when unreachable.
    Returns True when the accelerator is live.

    Defaults come from ``LEGATE_SPARSE_TPU_PROBE_TIMEOUT`` (seconds,
    default 90 — first device init on a cold tunnel can exceed 30) and
    ``LEGATE_SPARSE_TPU_PROBE_RETRIES`` (default 1), so every caller
    (bench.py, examples, dryrun, conftest) classifies the same tunnel
    the same way.

    Plain CPU hosts (cpu-pinned, or no TPU signal at all) skip the
    subprocess entirely — they'd pay a cold jax import for nothing.
    """
    import subprocess
    import time

    if timeout_s is None:
        timeout_s = int(os.environ.get("LEGATE_SPARSE_TPU_PROBE_TIMEOUT", "90"))
    if retries is None:
        retries = int(os.environ.get("LEGATE_SPARSE_TPU_PROBE_RETRIES", "1"))

    # In-process state wins over the environment: pin_cpu() updates
    # jax.config (the env var may still say an accelerator — e.g. the
    # axon sitecustomize re-exports it), and a backend that already
    # initialized in this process needs no subprocess probe at all.
    if "jax" in sys.modules:
        import jax
        from jax._src import xla_bridge

        cfg = (jax.config.jax_platforms or "").split(",")[0].strip()
        if cfg == "cpu":
            return False
        if xla_bridge.backends_are_initialized():
            try:
                return jax.devices()[0].platform != "cpu"
            except Exception:
                return False

    first = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip().lower()
    if first == "cpu":
        return False
    if not first:
        # Env unset: probe only when some accelerator signal exists —
        # a plain CPU host should not pay a cold subprocess jax import.
        # (A named accelerator platform, e.g. cuda, always probes.)
        from .settings import _looks_tpu_hosted

        gpu_hint = bool(os.environ.get("CUDA_VISIBLE_DEVICES")) or (
            os.path.exists("/dev/nvidia0")
        )
        if not _looks_tpu_hosted() and not gpu_hint:
            return False
    if read_cached_probe() is False:
        # Fresh shared DEAD verdict (this process or the tunnel
        # watcher probed recently, and the live-tunnel marker hasn't
        # flipped): skip the 90 s-per-attempt subprocess ladder.  A
        # cached "live" is deliberately NOT served — see the module
        # comment — so that path falls through to the real probe.
        _obs_event("platform.probe_cached", verdict="dead")
        sys.stderr.write(
            "legate_sparse_tpu: cached probe verdict 'dead' "
            f"({_probe_state_path()}); pinning cpu without re-probing "
            "(LEGATE_SPARSE_TPU_PROBE_FORCE=1 forces a fresh probe)\n"
        )
        pin_cpu()
        return False
    for attempt in range(retries + 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c", ACCEL_PROBE_CODE],
                timeout=timeout_s, capture_output=True, text=True,
            )
            if r.returncode == 0 and "ok" in r.stdout:
                _obs_event("platform.probe_ok", attempt=attempt + 1)
                write_probe_state(True)
                return True
            sys.stderr.write(
                f"legate_sparse_tpu: accelerator probe attempt "
                f"{attempt + 1} failed (rc={r.returncode}): "
                f"{r.stderr.strip()[-400:]}\n"
            )
            _obs_event(
                "platform.probe_fail", attempt=attempt + 1,
                rc=int(r.returncode),
                stderr_tail=r.stderr.strip()[-400:],
            )
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"legate_sparse_tpu: accelerator probe attempt "
                f"{attempt + 1} timed out after {timeout_s}s\n"
            )
            _obs_event("platform.probe_timeout", attempt=attempt + 1,
                       timeout_s=timeout_s)
        if attempt < retries:
            time.sleep(min(5 * (attempt + 1), 15))
    sys.stderr.write(
        "legate_sparse_tpu: accelerator unreachable; pinning cpu\n"
    )
    _obs_event("platform.unreachable_pin_cpu", retries=retries,
               timeout_s=timeout_s)
    write_probe_state(False)
    pin_cpu()
    return False


def restore_platform() -> None:
    """Undo pin_cpu: put back the env vars and platform selection so a
    later accelerator use in the same process is not silently degraded
    (clears the now-stale cpu backend caches)."""
    global _saved
    if _saved is None:
        return
    for key in ("JAX_PLATFORMS", "XLA_FLAGS"):
        val = _saved.get(key)
        if val is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = val

    import jax

    if "jax_platforms_prior" in _saved:
        jax.config.update("jax_platforms", _saved["jax_platforms_prior"])
    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        from jax.extend.backend import clear_backends

        clear_backends()
    _saved = None
    _re_resolve_dtype_policy()
