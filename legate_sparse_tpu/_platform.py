# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Host-platform (CPU) pinning for tests / dryruns / fallbacks.

The execution environment's sitecustomize may force-register an
accelerator platform and override ``JAX_PLATFORMS`` programmatically,
so setting the env var alone is not sufficient — ``jax.config.update``
must be called after the jax import as well.  And the whole pin must
happen before any backend initializes: initializing an unavailable TPU
tunnel can hang indefinitely (the round-1 ``MULTICHIP`` failure mode).

Single source of truth for the three call sites: ``tests/conftest.py``,
``__graft_entry__.dryrun_multichip`` and ``bench.py``'s CPU fallback.
"""

from __future__ import annotations

import os
import re
import sys

_COUNT_FLAG = "--xla_force_host_platform_device_count"

# Subprocess snippet shared by bench.py and tools/tpu_capture.py: an
# accelerator is "reachable" only if backend init succeeds AND one op
# round-trips to completion (a crashed TPU worker can pass init but
# hang on execution).
ACCEL_PROBE_CODE = (
    "import jax, jax.numpy as jnp; ds = jax.devices(); "
    "assert ds and ds[0].platform != 'cpu', ds; "
    "assert float(jnp.ones((8, 128)).sum()) == 1024.0; print('ok')"
)

# State captured by the first pin_cpu() call, for restore_platform().
_saved: dict | None = None


def _obs_event(name: str, **attrs) -> None:
    """Structured trace event + always-on counter for probe/pinning
    outcomes — the machine-readable replacement for grepping stderr
    when a round's accelerator evidence goes missing."""
    from .obs import counters, trace

    counters.inc(name)
    trace.event(name, **attrs)


def pin_cpu(n_devices: int = 0, *, override_env: bool = True) -> None:
    """Pin jax to the host (cpu) platform with >= n_devices devices.

    Safe to call whether or not jax is already imported, but must run
    before any jax backend is initialized (XLA_FLAGS and platform
    selection are frozen at first backend init).  ``n_devices=0`` pins
    the platform without touching the virtual device count.
    """
    global _saved
    if _saved is None:
        _saved = {
            "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS"),
            "XLA_FLAGS": os.environ.get("XLA_FLAGS"),
        }

    if override_env:
        os.environ["JAX_PLATFORMS"] = "cpu"
    else:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")

    if n_devices > 0:
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(_COUNT_FLAG + r"=(\d+)", flags)
        if m is None:
            flags = f"{flags} {_COUNT_FLAG}={n_devices}".strip()
            os.environ["XLA_FLAGS"] = flags
        elif int(m.group(1)) < n_devices:
            os.environ["XLA_FLAGS"] = (
                flags[: m.start()] + f"{_COUNT_FLAG}={n_devices}"
                + flags[m.end():]
            )

    import jax
    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        # Too late for XLA_FLAGS to take effect; still repoint the
        # platform selection and drop stale backend caches.
        sys.stderr.write(
            "legate_sparse_tpu: pin_cpu called after backend init; "
            "clearing backends (virtual device count may be stale)\n"
        )
        _obs_event("platform.pin_cpu_late", n_devices=n_devices)
        from jax.extend.backend import clear_backends

        clear_backends()
    if "jax_platforms_prior" not in _saved:
        _saved["jax_platforms_prior"] = jax.config.jax_platforms
    jax.config.update("jax_platforms", "cpu")
    _re_resolve_dtype_policy()


def _re_resolve_dtype_policy() -> None:
    """The x64 default is platform-dependent (settings ``auto`` mode),
    and the package is usually imported *before* pin_cpu runs (importing
    this module imports the package) — so re-resolve after repinning."""
    from .settings import settings, _resolve_x64

    import jax

    settings.x64 = _resolve_x64()
    jax.config.update("jax_enable_x64", settings.x64)


def ensure_live_backend(timeout_s: int | None = None,
                        retries: int | None = None) -> bool:
    """Probe the default accelerator in a subprocess (a dead tunnel
    hangs rather than errors); pin the cpu platform when unreachable.
    Returns True when the accelerator is live.

    Defaults come from ``LEGATE_SPARSE_TPU_PROBE_TIMEOUT`` (seconds,
    default 90 — first device init on a cold tunnel can exceed 30) and
    ``LEGATE_SPARSE_TPU_PROBE_RETRIES`` (default 1), so every caller
    (bench.py, examples, dryrun, conftest) classifies the same tunnel
    the same way.

    Plain CPU hosts (cpu-pinned, or no TPU signal at all) skip the
    subprocess entirely — they'd pay a cold jax import for nothing.
    """
    import subprocess
    import time

    if timeout_s is None:
        timeout_s = int(os.environ.get("LEGATE_SPARSE_TPU_PROBE_TIMEOUT", "90"))
    if retries is None:
        retries = int(os.environ.get("LEGATE_SPARSE_TPU_PROBE_RETRIES", "1"))

    # In-process state wins over the environment: pin_cpu() updates
    # jax.config (the env var may still say an accelerator — e.g. the
    # axon sitecustomize re-exports it), and a backend that already
    # initialized in this process needs no subprocess probe at all.
    if "jax" in sys.modules:
        import jax
        from jax._src import xla_bridge

        cfg = (jax.config.jax_platforms or "").split(",")[0].strip()
        if cfg == "cpu":
            return False
        if xla_bridge.backends_are_initialized():
            try:
                return jax.devices()[0].platform != "cpu"
            except Exception:
                return False

    first = os.environ.get("JAX_PLATFORMS", "").split(",")[0].strip().lower()
    if first == "cpu":
        return False
    if not first:
        # Env unset: probe only when some accelerator signal exists —
        # a plain CPU host should not pay a cold subprocess jax import.
        # (A named accelerator platform, e.g. cuda, always probes.)
        from .settings import _looks_tpu_hosted

        gpu_hint = bool(os.environ.get("CUDA_VISIBLE_DEVICES")) or (
            os.path.exists("/dev/nvidia0")
        )
        if not _looks_tpu_hosted() and not gpu_hint:
            return False
    for attempt in range(retries + 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c", ACCEL_PROBE_CODE],
                timeout=timeout_s, capture_output=True, text=True,
            )
            if r.returncode == 0 and "ok" in r.stdout:
                _obs_event("platform.probe_ok", attempt=attempt + 1)
                return True
            sys.stderr.write(
                f"legate_sparse_tpu: accelerator probe attempt "
                f"{attempt + 1} failed (rc={r.returncode}): "
                f"{r.stderr.strip()[-400:]}\n"
            )
            _obs_event(
                "platform.probe_fail", attempt=attempt + 1,
                rc=int(r.returncode),
                stderr_tail=r.stderr.strip()[-400:],
            )
        except subprocess.TimeoutExpired:
            sys.stderr.write(
                f"legate_sparse_tpu: accelerator probe attempt "
                f"{attempt + 1} timed out after {timeout_s}s\n"
            )
            _obs_event("platform.probe_timeout", attempt=attempt + 1,
                       timeout_s=timeout_s)
        if attempt < retries:
            time.sleep(min(5 * (attempt + 1), 15))
    sys.stderr.write(
        "legate_sparse_tpu: accelerator unreachable; pinning cpu\n"
    )
    _obs_event("platform.unreachable_pin_cpu", retries=retries,
               timeout_s=timeout_s)
    pin_cpu()
    return False


def restore_platform() -> None:
    """Undo pin_cpu: put back the env vars and platform selection so a
    later accelerator use in the same process is not silently degraded
    (clears the now-stale cpu backend caches)."""
    global _saved
    if _saved is None:
        return
    for key in ("JAX_PLATFORMS", "XLA_FLAGS"):
        val = _saved.get(key)
        if val is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = val

    import jax

    if "jax_platforms_prior" in _saved:
        jax.config.update("jax_platforms", _saved["jax_platforms_prior"])
    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        from jax.extend.backend import clear_backends

        clear_backends()
    _saved = None
    _re_resolve_dtype_policy()
