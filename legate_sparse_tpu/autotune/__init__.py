# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Sparsity-fingerprint autotuner: measured kernel selection.

``legate_sparse_tpu`` carries several SpMV/SpMM kernel families
(segment-sum vs rowids CSR, flat ELL, sliced ELL, DIA, BSR) picked by
hardcoded thresholds.  This package replaces the threshold guesswork
with measurement where it matters — the gather-class kernels whose
ranking depends on structure the thresholds can't see:

- :mod:`.fingerprint` — cheap deterministic structure descriptors,
  cached on ``csr_array``, discretized into a class label;
- :mod:`.registry` — the candidate-kernel catalog (cross-checked by
  ``tools/check_kernel_registry.py``);
- :mod:`.harness` — warmup + median-of-k candidate races;
- :mod:`.store` — the verdict LRU with epoch/platform invalidation
  and optional on-disk JSON warm start.

Routing (``route_matvec`` / ``route_matmat``, consulted by
``csr_array.dot`` right after the engine rung) serves a stored verdict
or silently declines — tuning off (``LEGATE_SPARSE_TPU_AUTOTUNE``
unset, the default), tracer contexts, dtype promotion (save the
declared bf16/f16 -> f32 widening, which the ``*-bf16`` candidates
serve), DIA/BSR structure, or a store miss all fall through to
today's heuristics.
The engine consults :func:`plan_preference` in its eligibility check
and defers to any verdict naming a non-CSR kernel.

Off is inert by contract: every dispatch site pays one settings
attribute read and nothing else (pinned by ``tests/test_autotune.py``
via the ``trace.*`` compile counters).  On, a routed dispatch runs the
verdict's kernel exactly as a direct dispatch of that kernel would —
bit-for-bit (same jitted entry point, same operands).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from .. import obs as _obs
from ..settings import settings as _settings_ref
from .fingerprint import Fingerprint, compute_fingerprint  # noqa: F401
from .harness import (  # noqa: F401
    eligible_candidates, measure_candidates, time_kernel, tune,
)
from .registry import CANDIDATES, Candidate  # noqa: F401
from .store import (  # noqa: F401
    Verdict, VerdictKey, VerdictStore, key_for, platform_fingerprint,
)

_store: Optional[VerdictStore] = None
_store_lock = threading.Lock()


def get_store() -> VerdictStore:
    """The process-wide verdict store (created on first use)."""
    global _store
    # Double-checked init: the unlocked reads are GIL-atomic single
    # references and can at worst observe None and take the lock.
    if _store is None:  # lint: disable=lock-discipline — double-checked fast path
        with _store_lock:
            if _store is None:
                _store = VerdictStore()
    return _store  # lint: disable=lock-discipline — GIL-atomic ref read


def reset() -> None:
    """Drop the process store (tests / bench phase hygiene)."""
    global _store
    with _store_lock:
        _store = None


def autotune_enabled() -> bool:
    """Fast routing check: one attribute read on the settings
    singleton (the same inert-off contract as ``engine_enabled``)."""
    return _settings_ref.autotune


def route_matvec(A, x):
    """Verdict-routed ``A @ x``: ``(y, path_label)`` or None (fall
    through to the heuristic dispatch chain)."""
    if not _settings_ref.autotune:
        return None
    return _route(A, x, "spmv")


def route_matmat(A, X):
    if not _settings_ref.autotune:
        return None
    return _route(A, X, "spmm")


def _route(A, operand, op: str):
    from ..csr import csr_array

    if not isinstance(A, csr_array):
        return None
    if not csr_array._can_build_cache(A.data, A.indices, A.indptr,
                                      operand):
        _obs.inc("autotune.route.decline")
        return None  # ambient trace / tracer operands: caches would leak
    widening = False
    if np.result_type(A.dtype, operand.dtype) != A.dtype:
        # Promotion: verdicts are keyed on the matrix dtype.  The one
        # declared exception is the low-precision-storage widening
        # (bf16/f16 matrix x f32 operand -> f32): the ``*-bf16``
        # candidates accumulate in f32 anyway, so their routed output
        # is bit-for-bit the direct dispatch under promotion.
        widening = (str(A.dtype) in ("bfloat16", "float16")
                    and np.result_type(A.dtype, operand.dtype)
                    == np.float32)
        if not widening:
            _obs.inc("autotune.route.decline")
            return None
    if A._get_dia() is not None or A._get_bsr() is not None:
        _obs.inc("autotune.route.decline")
        return None  # structure-specialized paths keep priority
    k = 1
    if op == "spmm":
        k = int(operand.shape[1])
        if k == 0:
            _obs.inc("autotune.route.decline")
            return None
    key = key_for(A, op, k=k)
    if key is None:
        _obs.inc("autotune.route.decline")
        return None
    verdict = get_store().lookup(key)
    if verdict is None:
        _obs.inc("autotune.route.miss")
        return None  # no measurement yet: heuristics serve
    cand = CANDIDATES.get(verdict.label)
    if cand is None or op not in cand.ops or not cand.eligible(A):
        # A stale/foreign verdict naming a kernel this matrix can't
        # run (e.g. flat ELL over budget) must not error the dispatch.
        _obs.inc("autotune.route.decline")
        return None
    if widening and not verdict.label.endswith("-bf16"):
        # Under the declared widening only the f32-accumulation family
        # may serve: its out dtype is result_type(A, x) by
        # construction, so routed == direct dispatch stays bit-for-bit
        # regardless of the operand dtype the verdict was raced with.
        _obs.inc("autotune.route.decline")
        return None
    y = cand.run(A, operand, op)
    _obs.inc("autotune.route.hits")
    _obs.inc("autotune.route." + verdict.label)
    return y, verdict.label


def plan_preference(A) -> Optional[str]:
    """Engine-side consult: the stored SpMV verdict label for ``A``'s
    key, or None (tuning off / tracer context / store miss).  The
    engine declines routing when this names a non-CSR kernel, so the
    autotune route right below it in ``csr_array.dot`` serves."""
    if not _settings_ref.autotune:
        return None
    from ..csr import csr_array

    if not isinstance(A, csr_array):
        return None
    if not csr_array._can_build_cache(A.data, A.indices, A.indptr):
        return None
    key = key_for(A, "spmv")
    if key is None:
        return None
    verdict = get_store().lookup(key)
    return verdict.label if verdict is not None else None
