# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Sparsity fingerprints: cheap, deterministic structure descriptors.

The dispatch heuristics (``csr.py`` chain, ``engine`` eligibility)
pick kernels from structure *thresholds*; the autotuner instead keys
measured verdicts on a coarse structure *class*.  The fingerprint is
the bridge: a handful of O(rows) / O(nnz) reductions computed once per
matrix (cached on ``csr_array`` beside the ELL/DIA structure caches),
discretized into a class label stable across runs — and across row
permutations that preserve the row-length histogram, since every term
is either a histogram moment or a whole-array mean.

Fields (all deterministic for a given structure on a given platform):

- ``row_mean`` / ``row_cv`` / ``row_max_ratio`` — row-length histogram
  moments: mean nnz/row, coefficient of variation (std/mean, the skew
  signal), and max/mean (the flat-ELL padding blowup factor).
- ``spread`` — bandedness: mean ``|col - row|`` normalized by cols.
  Banded matrices score ~bandwidth/cols; uniform random ~1/3.
- ``block_score`` — fraction of adjacent stored entries sharing an
  8-wide column block: dense sub-block (FEM/BSR-friendly) structure
  scores high, scattered structure low.
- ``width_bucket`` — pow2 bucket of the mean row length (the density
  bucket; reuses the engine's ``next_pow2`` policy).

The class label (``Fingerprint.klass``) is what verdict keys carry:
``<kind>/w<width_bucket>`` where kind is one of ``banded`` / ``blocky``
/ ``uniform`` / ``skewed`` / ``powerlaw`` / ``empty``.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax.numpy as jnp

from ..engine.buckets import next_pow2


@dataclass(frozen=True)
class Fingerprint:
    """Structure descriptor of one CSR matrix (host scalars only)."""

    rows: int
    cols: int
    nnz: int
    row_mean: float
    row_cv: float
    row_max_ratio: float
    spread: float
    block_score: float
    width_bucket: int

    @property
    def klass(self) -> str:
        """Coarse class label — the verdict-key term.  Thresholds are
        deliberately wide: a verdict should cover every matrix the
        same kernel ranking plausibly applies to, and the shape
        buckets in the key already separate sizes."""
        if self.nnz == 0:
            return "empty/w1"
        if self.spread < 0.02 and self.row_cv < 0.5:
            kind = "banded"
        elif self.block_score >= 0.6:
            kind = "blocky"
        elif self.row_cv < 0.25:
            kind = "uniform"
        elif self.row_cv < 1.0:
            kind = "skewed"
        else:
            kind = "powerlaw"
        return f"{kind}/w{self.width_bucket}"


def compute_fingerprint(A) -> Fingerprint:
    """Fingerprint of a ``csr_array`` (concrete context only — the
    caller guards with ``_can_build_cache``; two device reductions
    plus one (rows+1,) host pull)."""
    rows, cols = A.shape
    nnz = A.nnz
    if nnz == 0 or rows == 0:
        return Fingerprint(rows, cols, nnz, 0.0, 0.0, 0.0, 0.0, 0.0, 1)
    indptr = np.asarray(A.indptr)
    counts = (indptr[1:] - indptr[:-1]).astype(np.float64)
    mean = float(counts.mean())
    cv = float(counts.std() / mean) if mean > 0 else 0.0
    mx = float(counts.max() / mean) if mean > 0 else 0.0
    row_ids = A._get_row_ids()
    spread = float(jnp.mean(jnp.abs(
        A.indices.astype(jnp.float32) - row_ids.astype(jnp.float32)
    ))) / max(cols, 1)
    if nnz >= 2:
        block_score = float(jnp.mean(
            (A.indices[1:] // 8 == A.indices[:-1] // 8)
            .astype(jnp.float32)))
    else:
        block_score = 1.0
    return Fingerprint(
        rows=rows, cols=cols, nnz=nnz,
        row_mean=round(mean, 6), row_cv=round(cv, 6),
        row_max_ratio=round(mx, 6), spread=round(spread, 6),
        block_score=round(block_score, 6),
        width_bucket=next_pow2(max(int(round(mean)), 1)),
    )
