# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Measurement harness: race the eligible candidates, record a verdict.

Timing discipline is borrowed from ``bench_timing.py``: warmup
dispatches absorb compile + first-touch allocation, every sample ends
on a ``block_until_ready`` sync, and the reported figure is the median
of k samples (outlier-robust without the variance bookkeeping).  The
harness deliberately stops short of ``loop_ms_per_iter``'s chained
fori_loop protocol: a verdict compares kernels *against each other on
the same matrix*, so the fixed per-dispatch cost biases every
candidate equally and a quick median settles the ranking in
milliseconds.  Bench phases proving absolute numbers (the irregular
SpMV speedup) keep using ``loop_ms_per_iter``.

The trial/warmup budget comes from ``settings.autotune_trials`` /
``settings.autotune_warmup`` (``LEGATE_SPARSE_TPU_AUTOTUNE_TRIALS`` /
``_WARMUP``) unless overridden per call.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from .. import obs as _obs
from ..settings import settings as _settings
from .registry import CANDIDATES
from .store import key_for


def time_kernel(fn, warmup: Optional[int] = None,
                trials: Optional[int] = None) -> float:
    """Median-of-k wall ms of ``fn()`` (a zero-arg dispatch closure),
    after ``warmup`` unmeasured calls.  Each call is synced."""
    warmup = _settings.autotune_warmup if warmup is None else warmup
    trials = _settings.autotune_trials if trials is None else trials
    for _ in range(max(int(warmup), 1)):
        jax.block_until_ready(fn())
    samples = []
    for _ in range(max(int(trials), 1)):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        samples.append((time.perf_counter() - t0) * 1e3)
    _obs.inc("autotune.measure.trials", len(samples))
    samples.sort()
    return samples[len(samples) // 2]


def eligible_candidates(A, op: str = "spmv") -> dict:
    """{label: Candidate} of the registry entries that can serve
    ``op`` on this matrix (structural predicates; builds lazy caches
    the same way the dispatch chain would)."""
    return {label: cand for label, cand in CANDIDATES.items()
            if op in cand.ops and cand.eligible(A)}


def measure_candidates(A, x=None, op: str = "spmv",
                       warmup: Optional[int] = None,
                       trials: Optional[int] = None
                       ) -> Dict[str, float]:
    """Time every eligible candidate for ``op`` on ``A``; returns
    {label: median ms}.  ``x`` defaults to a ones operand of the
    matrix dtype (k=4 columns for spmm)."""
    if x is None:
        if op == "spmv":
            x = jnp.ones((A.shape[1],), dtype=A.dtype)
        else:
            x = jnp.ones((A.shape[1], 4), dtype=A.dtype)
    timings: Dict[str, float] = {}
    for label, cand in eligible_candidates(A, op).items():
        timings[label] = time_kernel(
            lambda c=cand: c.run(A, x, op),
            warmup=warmup, trials=trials)
    return timings


def tune(A, x=None, op: str = "spmv", store=None,
         warmup: Optional[int] = None, trials: Optional[int] = None):
    """Race the candidates and record the winner into ``store`` (the
    process store by default).  Returns the recorded
    :class:`~.store.Verdict`, or None when no key/candidate is
    available (tracer context, empty registry slice)."""
    timings = measure_candidates(A, x=x, op=op,
                                 warmup=warmup, trials=trials)
    if not timings:
        return None
    k = 1
    if op == "spmm" and x is not None and getattr(x, "ndim", 1) == 2:
        k = int(x.shape[1])
    key = key_for(A, op, k=k)
    if key is None:
        return None
    if store is None:
        from . import get_store

        store = get_store()
    label = min(timings, key=timings.get)
    trials_used = (_settings.autotune_trials if trials is None
                   else int(trials))
    return store.record(key, label, timings_ms=timings,
                        trials=trials_used)
