# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Candidate-kernel registry: what the autotuner may race and route.

One :class:`Candidate` per routable kernel family, keyed by its
dispatch label (the same labels ``csr_array.dot`` records as the span
``path`` attr).  Each entry declares:

- ``kernel`` — its ``ops/spmv.py`` entry point (must exist and bump a
  ``trace.<kernel>`` counter: the instrumentation contract);
- ``ops`` — which dispatch ops it can serve;
- ``eligible`` — a structural predicate (builds/reads the matrix's
  lazy caches; False means the candidate is skipped, never errored);
- ``run`` — the dispatch closure the harness times and routing serves.

``tools/check_kernel_registry.py`` cross-checks this catalog three
ways (mirroring ``check_fault_sites.py``): kernel entry points exist
and are trace-counted, every label appears as a quoted literal at a
dispatch site outside this module (rot detection), and every label is
documented in ``docs/AUTOTUNER.md``.

Deliberately absent: DIA and BSR.  Those structure-specialized paths
keep unconditional dispatch priority (the engine makes the same call),
so the autotuner only races the gather-class kernels where measurement
can actually change the choice.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Tuple

from ..ops import spmv as _sp


def _run_csr_rowids(A, operand, op: str):
    rid = A._get_row_ids()
    if op == "spmv":
        return _sp.csr_spmv_rowids(
            A.data, A.indices, rid, operand, A.shape[0])
    return _sp.csr_spmm_rowids(
        A.data, A.indices, rid, operand, A.shape[0])


def _run_ell(A, operand, op: str):
    ell = A._get_ell()
    if op == "spmv":
        return _sp.ell_spmv(ell[0], ell[1], ell[2], operand)
    return _sp.ell_spmm(ell[0], ell[1], ell[2], operand)


def _run_sliced_ell(A, operand, op: str):
    return _sp.sliced_ell_spmv(A._get_sliced_ell(), operand, A.shape[0])


# Low-precision-storage family: bf16/f16 values with f32 accumulation
# (ops/spmv.py ``*_f32acc`` kernels).  Eligible only when the matrix
# already stores narrow values — the race must never silently round an
# f32 matrix down to win on bytes.
def _low_precision(A) -> bool:
    return str(A.dtype) in ("bfloat16", "float16")


def _run_csr_rowids_bf16(A, operand, op: str):
    rid = A._get_row_ids()
    if op == "spmv":
        return _sp.csr_spmv_rowids_f32acc(
            A.data, A.indices, rid, operand, A.shape[0])
    return _sp.csr_spmm_rowids_f32acc(
        A.data, A.indices, rid, operand, A.shape[0])


def _run_ell_bf16(A, operand, op: str):
    ell = A._get_ell()
    return _sp.ell_spmv_f32acc(ell[0], ell[1], ell[2], operand)


def _run_sliced_ell_bf16(A, operand, op: str):
    return _sp.sliced_ell_spmv_f32acc(
        A._get_sliced_ell(), operand, A.shape[0])


# Semiring-generalized family (graph/semiring.py catalog): the same
# three memory layouts with the (add, multiply) pair threaded through
# as static strings.  Raced under the default plus-times pair — where
# each is bit-identical to its specialized sibling — so the verdicts
# transfer to every semiring dispatch of the same structure
# (graph.matvec routes by these labels).
def _run_semiring_csr(A, operand, op: str):
    rid = A._get_row_ids()
    nnz = A.data.shape[0]
    if op == "spmv":
        return _sp.csr_semiring_spmv_rowids_masked(
            A.data, A.indices, rid, nnz, operand, A.shape[0],
            "sum", "times")
    return _sp.csr_semiring_spmm_rowids_masked(
        A.data, A.indices, rid, nnz, operand, A.shape[0],
        "sum", "times")


def _run_semiring_ell(A, operand, op: str):
    ell = A._get_ell()
    if op == "spmv":
        return _sp.ell_semiring_spmv(ell[0], ell[1], ell[2], operand,
                                     "sum", "times")
    return _sp.ell_semiring_spmm(ell[0], ell[1], ell[2], operand,
                                 "sum", "times")


def _run_semiring_sliced_ell(A, operand, op: str):
    return _sp.sliced_ell_semiring_spmv(
        A._get_sliced_ell(), operand, A.shape[0], "sum", "times")


# Delta-layer serving kernel (delta/core.py, docs/MUTATION.md): the
# masked COO segment-sum over a pow2-padded update buffer.  Registered
# so its planverify contract has an owner and the kernel-registry
# three-view check covers it, but never raced: the side-buffer is tiny
# by construction (capacity-bounded), always rides on top of a
# base-matrix dispatch the autotuner already owns, and its bucket
# identity (pow2 capacity) is not the sparsity fingerprint the verdict
# store keys on — so ``eligible`` declines every matrix and the delta
# layer dispatches it directly.
def _run_coo_segment(A, operand, op: str):
    rid = A._get_row_ids()
    nnz = A.data.shape[0]
    return _sp.coo_spmv_segment(A.data, rid, A.indices, nnz, operand,
                                A.shape[0])


@dataclass(frozen=True)
class Candidate:
    """One routable kernel family (see module docstring)."""

    label: str
    kernel: str
    ops: Tuple[str, ...]
    eligible: Callable
    run: Callable


CANDIDATES = {
    "csr-rowids": Candidate(
        label="csr-rowids", kernel="csr_spmv_rowids",
        ops=("spmv", "spmm"),
        eligible=lambda A: True,
        run=_run_csr_rowids,
    ),
    "ell": Candidate(
        label="ell", kernel="ell_spmv",
        ops=("spmv", "spmm"),
        eligible=lambda A: A._get_ell() is not None,
        run=_run_ell,
    ),
    "sliced-ell": Candidate(
        label="sliced-ell", kernel="sliced_ell_spmv",
        ops=("spmv",),
        eligible=lambda A: A._get_sliced_ell() is not None,
        run=_run_sliced_ell,
    ),
    "csr-rowids-bf16": Candidate(
        label="csr-rowids-bf16", kernel="csr_spmv_rowids_f32acc",
        ops=("spmv", "spmm"),
        eligible=_low_precision,
        run=_run_csr_rowids_bf16,
    ),
    "ell-bf16": Candidate(
        label="ell-bf16", kernel="ell_spmv_f32acc",
        ops=("spmv",),
        eligible=lambda A: _low_precision(A)
        and A._get_ell() is not None,
        run=_run_ell_bf16,
    ),
    "sliced-ell-bf16": Candidate(
        label="sliced-ell-bf16", kernel="sliced_ell_spmv_f32acc",
        ops=("spmv",),
        eligible=lambda A: _low_precision(A)
        and A._get_sliced_ell() is not None,
        run=_run_sliced_ell_bf16,
    ),
    "semiring-csr": Candidate(
        label="semiring-csr", kernel="csr_semiring_spmv_rowids_masked",
        ops=("spmv", "spmm"),
        eligible=lambda A: True,
        run=_run_semiring_csr,
    ),
    "semiring-ell": Candidate(
        label="semiring-ell", kernel="ell_semiring_spmv",
        ops=("spmv", "spmm"),
        eligible=lambda A: A._get_ell() is not None,
        run=_run_semiring_ell,
    ),
    "semiring-sliced-ell": Candidate(
        label="semiring-sliced-ell",
        kernel="sliced_ell_semiring_spmv",
        ops=("spmv",),
        eligible=lambda A: A._get_sliced_ell() is not None,
        run=_run_semiring_sliced_ell,
    ),
    "coo-segment": Candidate(
        label="coo-segment", kernel="coo_spmv_segment",
        ops=("spmv",),
        # Autotune-decline path: the delta layer owns this dispatch
        # (see _run_coo_segment's comment).
        eligible=lambda A: False,
        run=_run_coo_segment,
    ),
}
