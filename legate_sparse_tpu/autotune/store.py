# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Verdict store: measured kernel choices, LRU + optional on-disk JSON.

A *verdict* is the harness's measured answer ("for matrices of this
fingerprint class / op / dtype / shape bucket on this platform, kernel
X wins") and the store is its home — the autotune analog of the
engine's plan cache, with the same thread-safe move-to-end LRU shape
(``engine/plan_cache.py``).

Key and invalidation contract
-----------------------------
:class:`VerdictKey` carries ``(op, dtype, fingerprint class, rows
bucket, nnz bucket, k bucket, platform fingerprint, settings.epoch,
storage)``.  Shape terms reuse the engine's bucket policy, so one
verdict covers a bucket, not an exact shape.  The ``dtype`` term is
the *storage* value dtype (``csr_array.compress`` keeps ``.dtype``
honest), and ``storage`` tags the index representation — so a verdict
measured over bf16 values or int16 indices can never replay against
f32/int32 storage of the same logical matrix.  Two terms invalidate
without eviction:

- ``epoch`` — any post-import mutation of a lowering-relevant setting
  bumps ``settings.epoch`` (settings.py contract), so stale verdicts
  simply stop matching;
- ``platform`` — device platform + kind + local device count; a
  verdict measured on one machine class never routes on another.

Persistence: when ``LEGATE_SPARSE_TPU_AUTOTUNE_STORE`` names a file,
every record atomically rewrites it (temp + rename) and construction
loads it back, dropping entries whose platform fingerprint or epoch
does not match the current process — the on-disk file is a warm-start
cache, never an authority.
"""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict
from dataclasses import asdict, dataclass, field
from typing import Dict, Optional

import numpy as np

from .. import obs as _obs
from ..engine import buckets as _buckets
from ..settings import settings as _settings

_PLATFORM_FP: Optional[str] = None


def platform_fingerprint() -> str:
    """``platform:device_kind:local_device_count`` of device 0 (cached;
    initializes the backend on first call — routing reaches here only
    in concrete contexts where a backend already exists)."""
    global _PLATFORM_FP
    if _PLATFORM_FP is None:
        import jax

        dev = jax.devices()[0]
        kind = (getattr(dev, "device_kind", "") or "").replace(" ", "_")
        _PLATFORM_FP = f"{dev.platform}:{kind}:{jax.local_device_count()}"
    return _PLATFORM_FP


@dataclass(frozen=True)
class VerdictKey:
    op: str
    dtype: str
    fp_class: str
    rows_b: int
    nnz_b: int
    k_b: int
    platform: str
    epoch: int
    # Storage-representation tag beyond the value dtype (which the
    # ``dtype`` term already keys): "" for canonical int32 column
    # indices, "i16" for compressed indices.  A verdict measured over
    # one byte layout never replays against another — the index width
    # changes the gather traffic the race actually measured.
    storage: str = ""

    @property
    def key_id(self) -> str:
        """Compact display/serialization id (obs events, --autotune
        table, the on-disk JSON)."""
        storage = f"/s{self.storage}" if self.storage else ""
        return (f"{self.op}/{self.dtype}/{self.fp_class}"
                f"/r{self.rows_b}/z{self.nnz_b}/k{self.k_b}{storage}"
                f"@{self.platform}/e{self.epoch}")


@dataclass
class Verdict:
    """One measured choice: the winning label plus the full timing
    table it was drawn from (kept for the tune CLI / evidence)."""

    label: str
    timings_ms: Dict[str, float] = field(default_factory=dict)
    trials: int = 0


def key_for(A, op: str = "spmv", k: int = 1) -> Optional[VerdictKey]:
    """Verdict key of a ``csr_array`` for ``op``, or None when the
    fingerprint can't be built now (tracer context)."""
    fp = A._get_fingerprint()
    if fp is None:
        return None
    storage = ""
    if np.dtype(A.indices.dtype).itemsize < 4:
        storage = f"i{np.dtype(A.indices.dtype).itemsize * 8}"
    return VerdictKey(
        op=op,
        dtype=np.dtype(A.dtype).name,
        fp_class=fp.klass,
        rows_b=_buckets.bucket(A.shape[0]),
        nnz_b=_buckets.bucket(A.nnz),
        k_b=_buckets.k_bucket(k),
        platform=platform_fingerprint(),
        epoch=_settings.epoch,
        storage=storage,
    )


class VerdictStore:
    """Thread-safe LRU of verdicts with optional JSON persistence."""

    def __init__(self, capacity: Optional[int] = None,
                 path: Optional[str] = None):
        self._capacity = (capacity if capacity is not None
                          else _settings.autotune_store_size)
        self._path = (path if path is not None
                      else (_settings.autotune_store_path or None))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[VerdictKey, Verdict]" = OrderedDict()
        if self._path:
            self._load()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def lookup(self, key: Optional[VerdictKey]) -> Optional[Verdict]:
        if key is None:
            return None
        with self._lock:
            verdict = self._entries.get(key)
            if verdict is not None:
                self._entries.move_to_end(key)
        if verdict is None:
            _obs.inc("autotune.verdict.misses")
            return None
        _obs.inc("autotune.verdict.hits")
        return verdict

    def record(self, key: VerdictKey, label: str,
               timings_ms: Optional[Dict[str, float]] = None,
               trials: int = 0) -> Verdict:
        verdict = Verdict(label=label,
                          timings_ms=dict(timings_ms or {}),
                          trials=int(trials))
        with self._lock:
            self._entries[key] = verdict
            self._entries.move_to_end(key)
            while len(self._entries) > max(self._capacity, 1):
                self._entries.popitem(last=False)
                _obs.inc("autotune.verdict.evictions")
        _obs.inc("autotune.verdict.records")
        _obs.event("autotune.verdict", key=key.key_id, label=label)
        if self._path:
            self._save()
        return verdict

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def stats(self) -> Dict[str, int]:
        snap = _obs.counters.snapshot("autotune.verdict.")
        return {
            "size": len(self),
            "hits": int(snap.get("autotune.verdict.hits", 0)),
            "misses": int(snap.get("autotune.verdict.misses", 0)),
            "records": int(snap.get("autotune.verdict.records", 0)),
            "evictions": int(snap.get("autotune.verdict.evictions", 0)),
        }

    # ---------------- persistence ----------------

    def _save(self) -> None:
        with self._lock:
            entries = [dict(asdict(key), label=v.label,
                            timings_ms=v.timings_ms, trials=v.trials)
                       for key, v in self._entries.items()]
        doc = {"platform": platform_fingerprint(),
               "epoch": _settings.epoch, "verdicts": entries}
        tmp = f"{self._path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, self._path)
        except OSError as e:
            _obs.event("autotune.store.error", error=repr(e)[:200])
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return
        _obs.inc("autotune.store.save")

    def _load(self) -> None:
        try:
            with open(self._path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return  # absent/corrupt warm-start file: start empty
        dropped = 0
        for entry in doc.get("verdicts", []):
            try:
                key = VerdictKey(
                    op=entry["op"], dtype=entry["dtype"],
                    fp_class=entry["fp_class"],
                    rows_b=int(entry["rows_b"]),
                    nnz_b=int(entry["nnz_b"]),
                    k_b=int(entry["k_b"]),
                    platform=entry["platform"],
                    epoch=int(entry["epoch"]),
                    storage=str(entry.get("storage", "")),
                )
            except (KeyError, TypeError, ValueError):
                dropped += 1
                continue
            # Invalidation contract: platform + epoch must match the
            # current process, or the entry is a different machine
            # class / settings generation.
            if (key.platform != platform_fingerprint()
                    or key.epoch != _settings.epoch):
                dropped += 1
                continue
            with self._lock:
                self._entries[key] = Verdict(
                    label=entry.get("label", ""),
                    timings_ms=dict(entry.get("timings_ms", {})),
                    trials=int(entry.get("trials", 0)),
                )
        _obs.inc("autotune.store.load")
        if dropped:
            _obs.event("autotune.store.dropped", count=dropped)
