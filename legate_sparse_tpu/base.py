# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Shared base classes for compressed sparse formats.

Parity with the reference's ``CompressedBase``/``DenseSparseBase``
(reference: ``legate_sparse/base.py:63-268``): structure-sharing
``_with_data``, ``astype``, ``sum(axis)``, and the auto-generated family
of zero-preserving unary ufuncs applied to ``.data``
(``base.py:209-250``).  The rect-pair ``pos`` encoding and its
pack/unpack helpers (``base.py:272-296``) have no TPU analog — plain
``indptr`` arrays are kept throughout, which XLA handles natively.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp


class CsrDelegateMixin:
    """Operations every format supports by converting to CSR (where the
    kernel implementations live); classes override any of these with a
    native version.  Keeps the scipy surface uniform across
    csr/csc/coo/dia without per-format reimplementation."""

    # numpy must defer binary ops to the sparse operand (scipy sets the
    # same priority), else ndarray.__mul__ coerces us to object arrays.
    __array_priority__ = 10.1

    def multiply(self, other):
        return self.tocsr().multiply(other)

    def power(self, n, dtype=None):
        return self.tocsr().power(n, dtype=dtype)

    def maximum(self, other):
        return self.tocsr().maximum(other)

    def minimum(self, other):
        return self.tocsr().minimum(other)

    def trace(self, offset: int = 0):
        return self.tocsr().trace(offset)

    def count_nonzero(self, axis=None):
        return self.tocsr().count_nonzero(axis=axis)

    def argmax(self, axis=None, out=None):
        return self.tocsr().argmax(axis=axis, out=out)

    def argmin(self, axis=None, out=None):
        return self.tocsr().argmin(axis=axis, out=out)

    def reshape(self, *shape, order="C"):
        return self.tocsr().reshape(*shape, order=order)

    def tocoo(self, copy: bool = False):
        return self.tocsr().tocoo(copy=copy)

    def todok(self, copy: bool = False):
        return self.tocsr().todok(copy=copy)

    def tolil(self, copy: bool = False):
        return self.tocsr().tolil(copy=copy)

    # Arithmetic (formats with a native implementation override; the
    # rest go through CSR where the kernels live).  Scalar scaling
    # stays in the operand's own format via _with_data when available.
    # *_matrix flavors set this True: their ``*`` is matmul, and
    # CSR-routed results keep the spmatrix flavor.
    _is_spmatrix = False

    def _flavored(self, out):
        if self._is_spmatrix:
            from .csr import csr_array, csr_matrix

            if type(out) is csr_array:
                out.__class__ = csr_matrix
        return out

    def __mul__(self, other):
        if np.isscalar(other) or getattr(other, "ndim", None) == 0:
            if hasattr(self, "_with_data"):
                return self._with_data(self.data * other)
            return self._flavored(self.tocsr() * other)
        if self._is_spmatrix:
            return self._flavored(self.tocsr() @ other)  # spmatrix: matmul
        return self.multiply(other)

    def __rmul__(self, other):
        if np.isscalar(other) or getattr(other, "ndim", None) == 0:
            return self.__mul__(other)
        if self._is_spmatrix:
            # scipy spmatrix: x * A is x @ A (row-vector matmul).
            other = np.asarray(other)
            AT = self.tocsr().transpose()
            if other.ndim == 1:
                return np.asarray(AT @ other)
            return np.asarray((AT @ other.T)).T
        return self.__mul__(other)   # element-wise * commutes

    def __neg__(self):
        if hasattr(self, "_with_data"):
            return self._with_data(-self.data)  # dtype-preserving
        return self._flavored(-self.tocsr())

    def __truediv__(self, other):
        if np.isscalar(other) or getattr(other, "ndim", None) == 0:
            if hasattr(self, "_with_data"):
                return self._with_data(self.data / other)
        return self._flavored(self.tocsr() / other)

    def __add__(self, other):
        if np.isscalar(other) and other == 0:
            return self.copy()   # sum()/accumulate start at 0
        return self._flavored(self.tocsr() + other)

    def __radd__(self, other):
        return self.__add__(other)

    def __sub__(self, other):
        return self._flavored(self.tocsr() - other)

    def __rsub__(self, other):
        if np.isscalar(other) and other == 0:
            return self.__neg__()
        # dense - sparse densifies in scipy; keep the explicit-densify
        # policy used everywhere else on this surface.
        raise NotImplementedError(
            "dense - sparse is not supported; densify explicitly"
        )

    def __matmul__(self, other):
        return self.tocsr() @ other

    def __rmatmul__(self, other):
        raise NotImplementedError(
            f"dense @ {type(self).__name__} is not supported"
        )

    # Element-wise comparisons (scipy semantics, via the CSR kernels).
    # Defining __eq__ clears hashing — sparse arrays are mutable and
    # unhashable, same as scipy's.
    __hash__ = None

    def __eq__(self, other):
        return self.tocsr() == other

    def __ne__(self, other):
        return self.tocsr() != other

    def __lt__(self, other):
        return self.tocsr() < other

    def __gt__(self, other):
        return self.tocsr() > other

    def __le__(self, other):
        return self.tocsr() <= other

    def __ge__(self, other):
        return self.tocsr() >= other

    def __abs__(self):
        return abs(self.tocsr())

    def __pow__(self, n):
        import numpy as _np

        if _np.isscalar(n) and n == 0:
            raise NotImplementedError(
                "zero power is not supported as it would densify the "
                "matrix; use np.ones(A.shape, dtype=A.dtype)"
            )
        return self.power(n)

    def nonzero(self):
        return self.tocsr().nonzero()


class CompressedBase(CsrDelegateMixin):
    """Base for csr/dia arrays: dtype casting, sums, zero-preserving ufuncs."""

    def asformat(self, format, copy: bool = False):
        """Dispatch to ``to<format>()`` (reference ``base.py:92-108``)."""
        if format is None or format == self.format:
            if copy:
                return self.copy()
            return self
        convert = getattr(self, "to" + format, None)
        if convert is None:
            raise ValueError(f"Format {format} is unknown.")
        return convert(copy=copy)

    def astype(self, dtype, casting: str = "unsafe", copy: bool = True):
        """Cast the value array, sharing structure (reference ``base.py:198-206``)."""
        dtype = np.dtype(dtype)
        if self.dtype != dtype:
            return self._with_data(self.data.astype(dtype), copy=copy)
        return self.copy() if copy else self

    def sum(self, axis=None, dtype=None, out=None):
        """Row/column/global sums.

        The reference computes axis sums as SpMV against a ones vector
        (``base.py:111-171``); here segment-reductions do it in one pass.
        """
        from .csr import csr_array

        if not isinstance(self, csr_array):
            return self.tocsr().sum(axis=axis, dtype=dtype, out=out)
        rows, cols = self.shape
        if axis is None:
            result = jnp.sum(self.data)
        elif axis in (0, -2):
            result = jnp.zeros((cols,), dtype=self.data.dtype).at[
                self.indices
            ].add(self.data)
        elif axis in (1, -1):
            import jax

            result = jax.ops.segment_sum(
                self.data, self._get_row_ids(), num_segments=rows,
                indices_are_sorted=True,
            )
        else:
            raise ValueError(f"invalid axis {axis}")
        if dtype is not None:
            result = result.astype(dtype)
        if out is not None:
            out[...] = result
            return out
        return result

    def _minmax(self, axis, op_name: str):
        """Shared max/min: scipy semantics — implicit zeros participate
        whenever a row/column/matrix is not completely dense."""
        import jax

        from .csr import csr_array

        if not isinstance(self, csr_array):
            return getattr(self.tocsr(), op_name)(axis=axis)
        if self.nnz and not self.has_canonical_format:
            # scipy canonicalizes before min/max: duplicates must
            # contribute their SUM, and the density test below counts
            # coordinates, not stored slots.
            self.sum_duplicates()
        rows, cols = self.shape
        # scipy raises for zero-size reductions; match it.
        if axis is None and rows * cols == 0:
            raise ValueError("zero-size array to reduction operation")
        if axis in (1, -1) and cols == 0 and rows > 0:
            raise ValueError("zero-size array to reduction operation")
        if axis in (0, -2) and rows == 0 and cols > 0:
            raise ValueError("zero-size array to reduction operation")
        data = self.data
        zero = jnp.zeros((), data.dtype)
        if np.issubdtype(np.dtype(data.dtype), np.integer):
            info = np.iinfo(np.dtype(data.dtype))
            init = info.min if op_name == "max" else info.max
        else:
            init = -np.inf if op_name == "max" else np.inf
        if op_name == "max":
            seg, scat, red = jax.ops.segment_max, "max", jnp.max
            pick = jnp.maximum
        else:
            seg, scat, red = jax.ops.segment_min, "min", jnp.min
            pick = jnp.minimum
        if axis is None:
            if self.nnz == 0:
                return zero
            r = red(data)
            return pick(r, zero) if self.nnz < rows * cols else r
        if axis in (1, -1):
            row_ids = self._get_row_ids()
            r = seg(data, row_ids, num_segments=rows,
                    indices_are_sorted=True)
            counts = jnp.diff(self.indptr)
            r = jnp.where(counts > 0, r, zero)
            return jnp.where(counts < cols, pick(r, zero), r)
        if axis in (0, -2):
            full = jnp.full((cols,), init, dtype=data.dtype)
            r = getattr(full.at[self.indices], scat)(data)
            counts = jnp.zeros((cols,), jnp.int32).at[self.indices].add(1)
            r = jnp.where(counts > 0, r, zero)
            return jnp.where(counts < rows, pick(r, zero), r)
        raise ValueError(f"invalid axis {axis}")

    def max(self, axis=None, out=None):
        """Maximum (scipy semantics: implicit zeros count unless the
        reduced extent is fully dense)."""
        result = self._minmax(axis, "max")
        if out is not None:
            out[...] = result
            return out
        return result

    def min(self, axis=None, out=None):
        """Minimum (scipy ``min`` semantics)."""
        result = self._minmax(axis, "min")
        if out is not None:
            out[...] = result
            return out
        return result

    def mean(self, axis=None, dtype=None, out=None):
        rows, cols = self.shape
        denom = {None: rows * cols, 0: rows, -2: rows, 1: cols, -1: cols}[axis]
        s = self.sum(axis=axis, dtype=dtype)
        result = s / denom
        if out is not None:
            out[...] = result
            return out
        return result

    @property
    def ndim(self) -> int:
        return 2


# Univariate ufuncs with f(0) = 0, applied elementwise to .data
# (reference ``base.py:209-250``; same function list).
_UFUNCS_WITH_FIXED_POINT_AT_ZERO = (
    "sin", "tan", "arcsin", "arctan", "sinh", "tanh", "arcsinh", "arctanh",
    "rint", "sign", "expm1", "log1p", "deg2rad", "rad2deg", "floor", "ceil",
    "trunc", "sqrt",
)


def _install_unary_ufuncs(cls) -> None:
    for name in _UFUNCS_WITH_FIXED_POINT_AT_ZERO:
        op = getattr(jnp, name)

        def method(self, _op=op):
            return self._with_data(_op(self.data))

        method.__name__ = name
        method.__doc__ = f"Element-wise {name} (zero-preserving)."
        setattr(cls, name, method)


_install_unary_ufuncs(CompressedBase)


class DenseSparseBase:
    """Base for {Dense, Sparse}-format matrices (CSR/CSC), reference
    ``base.py:256-268``.  Partition caching is XLA's job here, so this
    only carries the structure-sharing constructor."""

    @classmethod
    def make_with_same_nnz_structure(cls, mat, arg, shape=None, dtype=None):
        if shape is None:
            shape = mat.shape
        if dtype is None:
            dtype = mat.dtype
        return cls(arg, shape=shape, dtype=dtype)
