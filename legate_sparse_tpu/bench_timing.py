# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Honest device timing for detached-dispatch backends.

On the axon TPU tunnel ``jax.block_until_ready`` returns as soon as the
dispatch is acknowledged — NOT when the device finishes — so classic
warmup + block timing reports fantasy numbers (measured: a 200 MB triad
"finishing" in 25 us, 10x the chip's HBM bandwidth).  The only reliable
sync is a host fetch of a result scalar, which costs a full RPC round
trip (~80 ms measured), so per-op timing is useless too.

The methodology here: run the op chained inside ONE jitted
``lax.fori_loop`` at two different trip counts, fetch a scalar from
each result (true sync), and divide the time difference by the trip
count difference.  Fixed costs (dispatch RPC, fetch RPC, compile-cache
lookup) cancel; what remains is true device time per iteration.

Chaining (each iteration consumes the previous result) also defeats
any result caching / elision across iterations.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Callable

import jax


def triad_gbs(log2_lanes: int = 26, k_lo: int = 3,
              k_hi: int = 18) -> float:
    """One measured STREAM-triad bandwidth sample (GB/s): x' = a*x + y
    over ``2**log2_lanes`` f32 lanes (the default 2^26 = 512 MB working
    set cannot hide in VMEM/LLC).  Callers wanting a *trustworthy*
    denominator should take several samples interleaved with their
    workload phases and use the median — on shared CPU boxes single
    samples vary run-to-run by 25%+ (BENCH_r05's 66 vs 29 GB/s pair),
    and a wild denominator poisons every roofline fraction computed
    from it."""
    import jax.numpy as jnp

    n = 1 << log2_lanes
    x = jnp.ones((n,), dtype=jnp.float32)
    y = jnp.full((n,), 1e-9, dtype=jnp.float32)
    ms = loop_ms_per_iter(lambda v: 1.0000001 * v + y, x,
                          k_lo=k_lo, k_hi=k_hi)
    return 3 * 4 * n / (ms * 1e-3) / 1e9


def fixed_cost_s(x0, repeats: int = 3) -> float:
    """Measured fixed cost of one dispatch + scalar-fetch round trip
    (the constant both ends of the two-point measurement share).  On
    the axon tunnel this is ~1 s; on a local backend, microseconds."""
    import jax.numpy as jnp

    @jax.jit
    def probe(x):
        return jnp.ravel(x)[0] * 1.0

    float(probe(x0))  # compile + warm
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(probe(x0))
        best = min(best, time.perf_counter() - t0)
    return best


def loop_ms_per_iter(step: Callable, x0, k_lo: int = 5, k_hi: int = None,
                     repeats: int = 2, deadline_s: float = None,
                     k_cap: int = 4000) -> float:
    """True device ms per ``step`` application (see module docstring).

    ``step``: jax-traceable x -> x (magnitude-preserving so hundreds of
    chained applications neither overflow nor denormalize).

    Every distinct trip count is a separate XLA compile — expensive
    through the tunnel (tens of seconds at large shapes) — so beyond
    the caller's first guess the trip counts are chosen from MEASURED
    cost estimates instead of blind x4 escalation: normally at most
    three loop compiles run (plus one trivial fixed-cost probe).
    ``k_hi`` is the first high trial (caller's domain knowledge; None
    picks it from the fixed-cost estimate); ``k_cap`` bounds every
    trip count (pass a small cap to bound total work on a kernel that
    might fault the worker); ``deadline_s`` (wall clock for this call)
    stops escalation early.
    """
    import jax.numpy as jnp

    t_start = time.perf_counter()

    @partial(jax.jit, static_argnames=("k",))
    def loop(x, k: int):
        out = jax.lax.fori_loop(0, k, lambda i, v: step(v), x)
        return jnp.ravel(out)[0]

    def timed(k: int) -> float:
        float(loop(x0, k))  # compile + warm
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            float(loop(x0, k))
            best = min(best, time.perf_counter() - t0)
        return best

    def left() -> float:
        if deadline_s is None:
            return float("inf")
        return deadline_s - (time.perf_counter() - t_start)

    fixed = fixed_cost_s(x0)
    t_lo = timed(k_lo)
    # Delta target sized so the loop-body difference dominates
    # fixed-cost jitter; per-iter upper bound from the low point alone.
    per_iter_est = max(t_lo - fixed, 0.25 * t_lo) / k_lo
    delta_target = max(4.0 * fixed, 0.4, 0.5 * t_lo)
    if k_hi is None:
        k_hi = k_lo + int(delta_target / max(per_iter_est, 1e-9)) + 1
    k_hi = min(k_cap, max(3 * k_lo, k_hi))
    while True:
        t_hi = timed(k_hi)
        good = t_hi >= t_lo + max(2.0 * fixed, 0.2 * t_lo)
        if good or k_hi >= k_cap:
            break
        if left() < 3 * t_hi + 30:
            # Not enough wall budget for another compile+run cycle:
            # use what we have if it resolves at all, else fail loudly.
            break
        # Re-aim from the measured points (one jump, not x4 blind).
        per_iter = ((t_hi - t_lo) / (k_hi - k_lo)
                    if t_hi > t_lo else per_iter_est / 8)
        k_next = k_lo + int(delta_target / max(per_iter, 1e-9)) + 1
        k_hi = min(k_cap, max(k_next, 2 * k_hi))
    if not good:
        # t_hi <= t_lo, or above it by less than the noise floor: a
        # silent clamp (or a noise-dominated slope) would report fantasy
        # bandwidth in the driver-contract JSON; fail loudly instead
        # (callers guard each phase and record the error).
        raise RuntimeError(
            f"unresolvable timing: {k_hi} iters ({t_hi:.4f}s) not "
            f"measurably slower than {k_lo} ({t_lo:.4f}s; "
            f"noise floor {max(2.0 * fixed, 0.2 * t_lo):.4f}s)"
        )
    return (t_hi - t_lo) / (k_hi - k_lo) * 1e3
