# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Honest device timing for detached-dispatch backends.

On the axon TPU tunnel ``jax.block_until_ready`` returns as soon as the
dispatch is acknowledged — NOT when the device finishes — so classic
warmup + block timing reports fantasy numbers (measured: a 200 MB triad
"finishing" in 25 us, 10x the chip's HBM bandwidth).  The only reliable
sync is a host fetch of a result scalar, which costs a full RPC round
trip (~80 ms measured), so per-op timing is useless too.

The methodology here: run the op chained inside ONE jitted
``lax.fori_loop`` at two different trip counts, fetch a scalar from
each result (true sync), and divide the time difference by the trip
count difference.  Fixed costs (dispatch RPC, fetch RPC, compile-cache
lookup) cancel; what remains is true device time per iteration.

Chaining (each iteration consumes the previous result) also defeats
any result caching / elision across iterations.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Callable

import jax


def loop_ms_per_iter(step: Callable, x0, k_lo: int = 5, k_hi: int = 55,
                     repeats: int = 2) -> float:
    """True device ms per ``step`` application (see module docstring).

    ``step``: jax-traceable x -> x (magnitude-preserving so hundreds of
    chained applications neither overflow nor denormalize).
    """
    import jax.numpy as jnp

    @partial(jax.jit, static_argnames=("k",))
    def loop(x, k: int):
        out = jax.lax.fori_loop(0, k, lambda i, v: step(v), x)
        return jnp.ravel(out)[0]

    def timed(k: int) -> float:
        float(loop(x0, k))  # compile + warm
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            float(loop(x0, k))
            best = min(best, time.perf_counter() - t0)
        return best

    # Escalate the trip count until the loop body dominates the fixed
    # dispatch/fetch cost, else the delta is timing noise.
    t_lo = timed(k_lo)
    while True:
        t_hi = timed(k_hi)
        if t_hi >= 1.5 * t_lo or k_hi >= 4000:
            break
        k_hi *= 4
    if t_hi <= t_lo:
        # A silent clamp here would report fantasy bandwidth in the
        # driver-contract JSON; fail loudly instead (callers guard each
        # phase and record the error).
        raise RuntimeError(
            f"unresolvable timing: {k_hi} iters ({t_hi:.4f}s) not "
            f"measurably slower than {k_lo} ({t_lo:.4f}s)"
        )
    return (t_hi - t_lo) / (k_hi - k_lo) * 1e3
