# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""COO sparse array.

Beyond-reference format (the reference's facade falls back to host
scipy for COO): a device-resident (row, col, data) triple.  COO is the
assembly format — construction, concatenation, IO — while compute
routes through CSR (``tocsr()`` is one device stable-sort,
``ops/convert.py:100``); that split matches scipy's own design.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax.numpy as jnp


from .base import CsrDelegateMixin


class coo_array(CsrDelegateMixin):
    """Coordinate-format sparse array (scipy ``coo_array`` surface)."""

    format = "coo"

    def __init__(self, arg, shape=None, dtype=None, copy: bool = False):
        from .csr import csr_array, _is_scipy_sparse
        from .types import coord_dtype_for

        if isinstance(arg, coo_array):
            row, col, data = arg.row, arg.col, arg.data
            shape = arg.shape if shape is None else tuple(shape)
        elif isinstance(arg, tuple) and len(arg) == 2 and isinstance(
            arg[1], tuple
        ):
            data, (row, col) = arg
            row = jnp.asarray(row)
            col = jnp.asarray(col)
            data = jnp.asarray(data)
            if shape is None:
                shape = (
                    int(row.max()) + 1 if row.size else 0,
                    int(col.max()) + 1 if col.size else 0,
                )
        elif _is_scipy_sparse(arg):
            sc = arg.tocoo()
            row, col, data = (jnp.asarray(sc.row), jnp.asarray(sc.col),
                              jnp.asarray(sc.data))
            shape = sc.shape if shape is None else tuple(shape)
        elif hasattr(arg, "tocsr"):  # csr_array / dia_array / csc_array
            base = arg if isinstance(arg, csr_array) else arg.tocsr()
            row, col, data = base._coo_parts()
            shape = base.shape if shape is None else tuple(shape)
        else:
            dense = jnp.asarray(arg)
            if dense.ndim != 2:
                raise ValueError(
                    f"coo_array requires a 2-D input, got ndim={dense.ndim}"
                )
            base = csr_array(dense)
            row, col, data = base._coo_parts()
            shape = base.shape

        self.shape: Tuple[int, int] = tuple(int(s) for s in shape)
        cdt = coord_dtype_for(max(self.shape) if self.shape else 1)
        self.row = jnp.asarray(row).astype(cdt)
        self.col = jnp.asarray(col).astype(cdt)
        data = jnp.asarray(data)
        if dtype is not None:
            data = data.astype(np.dtype(dtype))
        self.data = jnp.array(data) if copy else data

    # ---------------- properties ----------------
    @property
    def dtype(self) -> np.dtype:
        return np.dtype(self.data.dtype)

    @property
    def nnz(self) -> int:
        return int(self.data.shape[0])

    @property
    def dim(self) -> int:
        return 2

    @property
    def ndim(self) -> int:
        return 2

    @property
    def T(self):
        return self.transpose()

    # ---------------- conversions ----------------
    def tocsr(self, copy: bool = False):
        from .csr import csr_array

        return csr_array((self.data, (self.row, self.col)),
                         shape=self.shape)

    def tocsc(self, copy: bool = False):
        return self.tocsr().tocsc()

    def _coo_parts(self):
        return self.row, self.col, self.data

    def tocoo(self, copy: bool = False):
        return coo_array(self, copy=copy) if copy else self

    def asformat(self, format, copy: bool = False):
        if format in (None, "coo"):
            return self
        return self.tocsr().asformat(format, copy=copy)

    def toarray(self, order=None, out=None):
        return np.asarray(self.tocsr().todense())

    def todense(self, order=None, out=None):
        return self.toarray(order=order, out=out)

    def toscipy(self):
        import scipy.sparse as sp

        return sp.coo_array(
            (np.asarray(self.data),
             (np.asarray(self.row), np.asarray(self.col))),
            shape=self.shape,
        )

    def transpose(self, axes=None, copy: bool = False):
        if axes is not None:
            raise ValueError(
                "Sparse matrices do not support an 'axes' parameter"
            )
        out = coo_array.__new__(coo_array)
        out.shape = (self.shape[1], self.shape[0])
        out.row, out.col = self.col, self.row
        out.data = jnp.array(self.data) if copy else self.data
        return out

    # ---------------- ops ----------------
    def copy(self):
        return coo_array(self, copy=True)

    def astype(self, dtype, casting: str = "unsafe", copy: bool = True):
        out = coo_array.__new__(coo_array)
        out.shape = self.shape
        out.row, out.col = self.row, self.col
        out.data = self.data.astype(np.dtype(dtype))
        return out

    def conj(self, copy: bool = True):
        out = coo_array.__new__(coo_array)
        out.shape = self.shape
        out.row, out.col = self.row, self.col
        out.data = jnp.conj(self.data)
        return out

    def sum_duplicates(self):
        """Coalesce duplicate coordinates in place (via CSR round trip)."""
        A = self.tocsr()
        A.sum_duplicates()
        self.row, self.col, self.data = A._coo_parts()

    def diagonal(self, k: int = 0):
        return self.tocsr().diagonal(k)

    def sum(self, axis=None, dtype=None, out=None):
        return self.tocsr().sum(axis=axis, dtype=dtype, out=out)

    def dot(self, other, out=None):
        return self.tocsr().dot(other, out=out)

    def __matmul__(self, other):
        return self.dot(other)

    def __mul__(self, other):
        if np.isscalar(other) or getattr(other, "ndim", None) == 0:
            out = type(self).__new__(type(self))
            out.shape = self.shape
            out.row, out.col = self.row, self.col
            out.data = self.data * other
            return out
        # sparray semantics: * is element-wise.
        return self.multiply(other)

    def multiply(self, other):
        """Element-wise product in the operand's own format (scipy
        semantics)."""
        return self.tocsr().multiply(other).asformat("coo")

    # __rmul__ intentionally NOT overridden: CsrDelegateMixin.__rmul__
    # routes scalars back here and handles the spmatrix x*A = x@A case
    # (a local "element-wise commutes" override silently computed A@x
    # for coo_matrix).

    def __neg__(self):
        return self * -1.0

    def __repr__(self) -> str:
        return (
            f"<{self.shape[0]}x{self.shape[1]} sparse array of type "
            f"'{self.dtype}' with {self.nnz} stored elements in "
            f"COOrdinate format>"
        )


class coo_matrix(coo_array):
    _is_spmatrix = True
    def __pow__(self, n):
        # spmatrix semantics: matrix power.
        from .csr import csr_matrix

        out = (csr_matrix(self.tocsr()) ** n).asformat("coo")
        out.__class__ = type(self)   # keep the matrix flavor
        return out

    """spmatrix-flavored alias: ``*`` is matrix multiplication."""

    def __mul__(self, other):
        if np.isscalar(other) or getattr(other, "ndim", None) == 0:
            return coo_array.__mul__(self, other)
        return self.dot(other)

    pass
