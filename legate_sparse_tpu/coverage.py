# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""scipy.sparse namespace cloning with provenance wrappers.

Parity with the reference's coverage layer (reference:
``legate_sparse/coverage.py:50-107``): every name in ``scipy.sparse``
not implemented natively is re-exported as a scipy fallback, and every
implemented callable is wrapped so profilers attribute device work to
the user-level API call.  The reference tags Legion tasks via
``@track_provenance``; the JAX-native analog is ``jax.named_scope`` +
``jax.profiler.TraceAnnotation``-visible names.
"""

from __future__ import annotations

import functools
import types as pytypes
from typing import Any, Container, Mapping

import jax

MOD_INTERNAL = {"__dir__", "__getattr__"}

_WRAP_BLOCKLIST = ("__class__", "__init__", "__init_subclass__", "__new__",
                   "__getattribute__", "__setattr__", "__subclasshook__")


def wrap(func, name: str | None = None):
    """Wrap a callable in a profiler scope (analog of reference
    ``coverage.py:50-56`` ``@track_provenance``)."""
    scope = f"legate_sparse_tpu.{name or getattr(func, '__qualname__', 'op')}"

    @functools.wraps(func)
    def wrapper(*args: Any, **kwargs: Any) -> Any:
        with jax.named_scope(scope):
            return func(*args, **kwargs)

    return wrapper


def _to_scipy(x):
    """Convert this package's sparse arrays (and containers of them) to
    scipy objects a raw scipy function understands; everything else
    passes through."""
    if hasattr(x, "toscipy"):
        return x.toscipy()
    if hasattr(x, "tocsr") and hasattr(x, "nnz"):
        import scipy.sparse as _sp

        if not _sp.issparse(x):
            # Sparse-like without a direct scipy conversion: via CSR.
            return x.tocsr().toscipy()
        return x        # already scipy
    if isinstance(x, (list, tuple)):
        converted = [_to_scipy(v) for v in x]
        return type(x)(converted) if isinstance(x, tuple) else converted
    return x


def _from_scipy(x):
    """Convert scipy sparse results back into this package's arrays
    (format-preserving for the formats we implement natively)."""
    import scipy.sparse as _sp

    if _sp.issparse(x):
        from . import coo, csc, csr, dia

        by_fmt = {
            "csr": csr.csr_array, "csc": csc.csc_array,
            "coo": coo.coo_array, "dia": dia.dia_array,
        }
        ctor = by_fmt.get(getattr(x, "format", "csr"))
        if ctor is None:
            return csr.csr_array(x.tocsr())
        if x.format == "dia":
            return ctor((x.data, x.offsets), shape=x.shape)
        return ctor(x)
    if isinstance(x, tuple):
        return tuple(_from_scipy(v) for v in x)
    return x


def scipy_fallback(func, name: str):
    """Adapter for raw scipy fallbacks: this package's arrays convert
    to scipy on the way in (scipy would otherwise coerce them to object
    arrays and produce garbage) and sparse results convert back on the
    way out.  A documented host-side escape hatch — device arrays round
    trip through the host."""

    scope = f"legate_sparse_tpu.{name}"

    @functools.wraps(func)
    def wrapper(*args: Any, **kwargs: Any) -> Any:
        from . import obs as _obs

        _obs.inc("scipy_fallback." + name)
        args = tuple(_to_scipy(a) for a in args)
        kwargs = {k: _to_scipy(v) for k, v in kwargs.items()}
        with jax.named_scope(scope), _obs.span("scipy_fallback",
                                               func=name):
            return _from_scipy(func(*args, **kwargs))

    wrapper._lst_scipy_fallback = True
    return wrapper


def clone_module(
    origin_module: pytypes.ModuleType,
    new_globals: Mapping[str, Any],
    include_self: bool = True,
) -> None:
    """Fill unimplemented ``origin_module`` names into ``new_globals``.

    Mirrors reference ``coverage.py:59-85``: for every public symbol of
    the origin (scipy.sparse), if the caller's globals already define it,
    keep the native version (wrapped for provenance); otherwise install
    the scipy fallback — adapted so this package's arrays convert at
    the boundary — so the namespace is drop-in complete.
    """
    mod_names = set(new_globals.keys())
    for attr in dir(origin_module):
        if attr.startswith("_") or attr in MOD_INTERNAL:
            continue
        value = getattr(origin_module, attr)
        if attr in mod_names:
            native = new_globals[attr]
            if callable(native) and not isinstance(native, type):
                new_globals[attr] = wrap(native, attr)  # type: ignore[index]
            continue
        # scipy fallback (host-side; documented escape hatch).
        if callable(value) and not isinstance(value, type):
            new_globals[attr] = scipy_fallback(value, attr)  # type: ignore[index]
        else:
            new_globals[attr] = value  # type: ignore[index]


def clone_scipy_arr_kind(origin_class):
    """Class decorator stamping scipy-facade metadata on native array
    classes (reference ``coverage.py:87-107``); methods stay native."""

    def decorator(cls):
        cls.__doc__ = cls.__doc__ or origin_class.__doc__
        cls._scipy_origin = origin_class
        return cls

    return decorator
