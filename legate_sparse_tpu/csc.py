# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""CSC sparse array.

Beyond-reference format (the reference exposes only CSR/DIA and lets
its facade fall back to host scipy for CSC): a ``csc_array`` here is
the CSR representation of the transpose plus CSC-view semantics, so
every kernel — SpMV, SpMM, SpGEMM, conversions — reuses the CSR device
paths with one transposition identity:

    A (m, n) in CSC  ==  A.T stored CSR (n, m)

Compute (matvec/matmat/SpGEMM) routes through ``tocsr()`` — one device
stable-sort transpose, cached on first use — so iterative callers pay
the conversion once and then hit the CSR structure-cached hot paths.

Construction from (data, indices, indptr) follows scipy's CSC layout:
``indices`` are row ids per column extent.  That triple IS the CSR
triple of A.T, so construction is free; ``tocsr()`` is one device
transpose (reference analog: ``csr.py:512-542``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax.numpy as jnp


from .base import CsrDelegateMixin


class csc_array(CsrDelegateMixin):
    """Compressed Sparse Column array (scipy ``csc_array`` surface)."""

    format = "csc"

    def __init__(self, arg, shape=None, dtype=None, copy: bool = False):
        from .csr import csr_array

        if isinstance(arg, csc_array):
            self._t = csr_array(arg._t, dtype=dtype, copy=copy)
            self.shape = arg.shape
            return
        if isinstance(arg, tuple) and len(arg) == 3:
            # (data, indices, indptr) in CSC layout == CSR triple of A.T.
            data, indices, indptr = arg
            if shape is None:
                raise ValueError("csc_array((data, indices, indptr)) "
                                 "requires shape")
            m, n = int(shape[0]), int(shape[1])
            self._t = csr_array((data, indices, indptr), shape=(n, m),
                                dtype=dtype, copy=copy)
            self.shape = (m, n)
            return
        from .csr import _is_scipy_sparse

        if _is_scipy_sparse(arg):
            # scipy CSC's triple IS the CSR triple of A.T: adopt the
            # buffers with zero conversion.
            sc = arg.tocsc()
            m, n = sc.shape
            self._t = csr_array((sc.data, sc.indices, sc.indptr),
                                shape=(n, m), dtype=dtype, copy=copy)
            self.shape = (m, n)
            return
        # Anything else (dense, csr_array, dia/coo, COO tuple):
        # normalize through csr_array then transpose.
        if hasattr(arg, "tocsr") and not isinstance(arg, csr_array):
            arg = arg.tocsr()
        A = csr_array(arg, shape=shape, dtype=dtype, copy=copy)
        self._t = A.transpose()
        self.shape = A.shape

    # ---------------- properties ----------------
    @property
    def dtype(self) -> np.dtype:
        return self._t.dtype

    @property
    def nnz(self) -> int:
        return self._t.nnz

    @property
    def data(self):
        return self._t.data

    @property
    def indices(self):
        return self._t.indices

    @property
    def indptr(self):
        return self._t.indptr

    @property
    def dim(self) -> int:
        return 2

    @property
    def ndim(self) -> int:
        return 2

    @property
    def T(self):
        return self.transpose()

    # ---------------- conversions ----------------
    def tocsr(self, copy: bool = False):
        # Cache the device transpose (one stable sort) on first use;
        # hand out structure-sharing wrappers so callers mutating the
        # result cannot corrupt the cache.
        if getattr(self, "_csr", None) is None:
            self._csr = self._t.transpose()
        return self._csr._with_data(self._csr.data, copy=copy)

    def tocsc(self, copy: bool = False):
        return csc_array(self, copy=copy) if copy else self

    def asformat(self, format, copy: bool = False):
        if format in (None, "csc"):
            return self
        if format == "csr":
            return self.tocsr()
        return self.tocsr().asformat(format, copy=copy)

    def toarray(self, order=None, out=None):
        return np.asarray(self._t.todense()).T

    def todense(self, order=None, out=None):
        return self.toarray(order=order, out=out)

    def toscipy(self):
        return self._t.toscipy().T.tocsc()

    def transpose(self, axes=None, copy: bool = False):
        if axes is not None:
            raise ValueError(
                "Sparse matrices do not support an 'axes' parameter"
            )
        # Transpose of CSC is the stored CSR — hand out a structure-
        # sharing wrapper, not the internal object (in-place mutation
        # of the result must not rewrite this array).
        return self._t._with_data(self._t.data, copy=copy)

    # ---------------- ops ----------------
    def copy(self):
        return csc_array(self, copy=True)

    def astype(self, dtype, casting: str = "unsafe", copy: bool = True):
        out = csc_array.__new__(csc_array)
        out._t = self._t.astype(dtype, casting=casting, copy=copy)
        out.shape = self.shape
        return out

    def conj(self, copy: bool = True):
        out = csc_array.__new__(csc_array)
        out._t = self._t.conj(copy=copy)
        out.shape = self.shape
        return out

    def diagonal(self, k: int = 0):
        # diag_k(A) == diag_{-k}(A.T)
        return self._t.diagonal(-k)

    def sum(self, axis=None, dtype=None, out=None):
        if axis is None:
            return self._t.sum(axis=None, dtype=dtype, out=out)
        if axis in (0, -2):
            return self._t.sum(axis=1, dtype=dtype, out=out)
        if axis in (1, -1):
            return self._t.sum(axis=0, dtype=dtype, out=out)
        raise ValueError(f"invalid axis {axis}")

    def dot(self, other, out=None):
        # csr_array.dot already normalizes scipy/sparse/dense operands.
        return self.tocsr().dot(other, out=out)

    def __matmul__(self, other):
        return self.dot(other)

    def __mul__(self, other):
        if np.isscalar(other) or getattr(other, "ndim", None) == 0:
            out = type(self).__new__(type(self))
            out._t = self._t * other
            out.shape = self.shape
            return out
        # sparray semantics: * is element-wise.
        return self.multiply(other)

    def multiply(self, other):
        """Element-wise product, column-compressed result (scipy
        returns the operand's own format)."""
        return self.tocsr().multiply(other).tocsc()

    # __rmul__ intentionally NOT overridden: CsrDelegateMixin.__rmul__
    # routes scalars back here and handles the spmatrix x*A = x@A case.

    def __neg__(self):
        return self * -1.0

    def __repr__(self) -> str:
        return (
            f"<{self.shape[0]}x{self.shape[1]} sparse array of type "
            f"'{self.dtype}' with {self.nnz} stored elements in "
            f"Compressed Sparse Column format>"
        )


# scipy.sparse.*_matrix alias.
class csc_matrix(csc_array):
    _is_spmatrix = True
    def __pow__(self, n):
        # spmatrix semantics: matrix power.
        from .csr import csr_matrix

        out = (csr_matrix(self.tocsr()) ** n).asformat("csc")
        out.__class__ = type(self)   # keep the matrix flavor
        return out

    """spmatrix-flavored alias: ``*`` is matrix multiplication."""

    def __mul__(self, other):
        if np.isscalar(other) or getattr(other, "ndim", None) == 0:
            return csc_array.__mul__(self, other)
        return self.dot(other)

    pass
