# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""scipy.sparse.csgraph facade: native device algorithms + adapted
fallbacks.

The cloned top-level namespace used to re-export scipy's csgraph
module object unchanged, so ``sparse.csgraph.connected_components(A)``
rejected this package's arrays ("graph should have two dimensions").
This module makes the namespace drop-in: every csgraph callable takes
package arrays (converted at the boundary for host fallbacks), and the
bulk-parallel algorithms run natively on device:

- ``laplacian``: L = D - A from one degree reduction (SpMV-shaped).
- ``connected_components`` (undirected/weak): min-label propagation —
  each sweep is two scatter-min ops over the edge list, O(diameter)
  sweeps, all inside one jitted while_loop.  A graph BFS/union-find is
  sequential; label propagation is the TPU-shaped equivalent.

The reference has no graph surface at all (exhaustive tree read,
SURVEY §2).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["connected_components", "laplacian"]


def _as_package_csr(graph):
    from .csr import _is_scipy_sparse, csr_array

    if _is_scipy_sparse(graph):
        return csr_array(graph)
    if hasattr(graph, "tocsr") and hasattr(graph, "nnz"):
        return graph.tocsr()
    return csr_array(jnp.asarray(graph))


def _narrow_indices(x):
    """scipy.sparse.csgraph's Cython kernels are int32-indexed; narrow
    int64 index arrays when they fit (raw scipy rejects them outright —
    'Buffer dtype mismatch' — so this is a strict usability win)."""
    import scipy.sparse as _sp

    if (_sp.issparse(x) and x.format == "csr"
            and x.indices.dtype == np.int64
            and x.shape[1] <= np.iinfo(np.int32).max
            and x.nnz <= np.iinfo(np.int32).max):
        return _sp.csr_array(
            (x.data, x.indices.astype(np.int32),
             x.indptr.astype(np.int32)), shape=x.shape)
    return x


def _host_fallback(name):
    """Compose the shared boundary adapter with csgraph-specific index
    narrowing.  The outer wrapper converts package arrays AND narrows;
    scipy_fallback's own ``_to_scipy`` then passes the already-scipy
    operands through unchanged (idempotent), so the boundary behavior
    stays defined in exactly one place (``coverage.scipy_fallback``)."""
    import functools

    import scipy.sparse.csgraph as _csg

    from .coverage import _to_scipy, scipy_fallback

    inner = scipy_fallback(getattr(_csg, name), f"csgraph.{name}")

    @functools.wraps(inner)
    def wrapper(*args, **kwargs):
        args = tuple(_narrow_indices(_to_scipy(a)) for a in args)
        kwargs = {k: _narrow_indices(_to_scipy(v))
                  for k, v in kwargs.items()}
        return inner(*args, **kwargs)

    return wrapper


@partial(jax.jit, static_argnames=("n",))
def _label_propagation(rows, cols, n: int):
    """Min-label propagation over an undirected edge list.  Converges
    to per-component minimum node ids in O(diameter) sweeps."""
    labels0 = jnp.arange(n, dtype=jnp.int64)

    def cond(state):
        _, changed = state
        return changed

    def body(state):
        labels, _ = state
        new = labels.at[rows].min(labels[cols])
        new = new.at[cols].min(new[rows])
        return new, jnp.any(new != labels)

    labels, _ = jax.lax.while_loop(
        cond, body, (labels0, jnp.asarray(True)))
    return labels


def connected_components(csgraph, directed=True, connection="weak",
                         return_labels=True):
    """Number of connected components (+ labels) — scipy signature.

    Undirected graphs and directed/'weak' run natively (weak
    connectivity ignores edge direction, so both reduce to the same
    symmetrized propagation).  Directed 'strong' delegates to host
    scipy (Tarjan is inherently sequential).
    """
    connection = str(connection).lower()
    if connection not in ("weak", "strong"):
        raise ValueError("connection must be 'weak' or 'strong'")
    if directed and connection == "strong":
        return _host_fallback("connected_components")(
            csgraph, directed=directed, connection=connection,
            return_labels=return_labels)
    A = _as_package_csr(csgraph)
    n = A.shape[0]
    if A.shape[0] != A.shape[1]:
        raise ValueError("graph must be square")
    if n == 0:
        return (0, np.zeros(0, dtype=np.int32)) if return_labels else 0
    rows = A._get_row_ids()
    cols = A._indices
    raw = np.asarray(_label_propagation(rows, cols, n))
    # scipy labels components 0..k-1 in order of first appearance.
    # Raw labels are component-minimum node ids, whose first occurrence
    # is the id itself — so np.unique's sorted order IS first-
    # appearance order and `inverse` is already the scipy labeling.
    uniq, inverse = np.unique(raw, return_inverse=True)
    labels = inverse.astype(np.int32)
    return (len(uniq), labels) if return_labels else len(uniq)


def laplacian(csgraph, normed=False, return_diag=False,
              use_out_degree=False, *, copy=True, form="array",
              dtype=None, symmetrized=False):
    """Graph Laplacian L = D - A (scipy signature), built on device
    from one degree reduction.  ``form != 'array'`` (callable/LO forms)
    delegates to host scipy."""
    if form != "array":
        return _host_fallback("laplacian")(
            csgraph, normed=normed, return_diag=return_diag,
            use_out_degree=use_out_degree, copy=copy, form=form,
            dtype=dtype, symmetrized=symmetrized)
    A = _as_package_csr(csgraph)
    if A.shape[0] != A.shape[1]:
        raise ValueError("csgraph must be a square matrix or array")
    if dtype is not None:
        A = A.astype(dtype)
    elif normed and not np.issubdtype(np.dtype(A.dtype), np.inexact):
        A = A.astype(np.float64)   # int input; complex is preserved
    if symmetrized:
        A = A + A.T.conj().tocsr()   # scipy: m += m.T.conj()
    # scipy semantics (``_laplacian_sparse``): degrees EXCLUDE
    # self-loops, and the result diagonal is overwritten outright.
    axis = 1 if use_out_degree else 0
    d = (jnp.asarray(A.sum(axis=axis)).reshape(-1)
         - jnp.asarray(A.diagonal()))
    row_ids = A._get_row_ids()
    if not normed:
        L = A._with_data(-A._data)
        L.setdiag(np.asarray(d))
        return (L, np.asarray(d)) if return_diag else L
    isolated = d == 0
    w = jnp.where(isolated, 1.0, jnp.sqrt(jnp.where(isolated, 1.0, d)))
    L = A._with_data(-A._data / (w[row_ids] * w[A._indices]))
    L.setdiag(np.asarray(1.0 - isolated.astype(w.dtype)))
    return (L, np.asarray(w)) if return_diag else L


def __getattr__(name):
    import scipy.sparse.csgraph as _csg

    try:
        value = getattr(_csg, name)
    except AttributeError:
        raise AttributeError(
            f"module 'legate_sparse_tpu.csgraph' has no attribute "
            f"{name!r}") from None
    if callable(value) and not isinstance(value, type):
        value = _host_fallback(name)
    globals()[name] = value
    return value
