# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""scipy.sparse.csgraph facade: native device algorithms + adapted
fallbacks.

The cloned top-level namespace used to re-export scipy's csgraph
module object unchanged, so ``sparse.csgraph.connected_components(A)``
rejected this package's arrays ("graph should have two dimensions").
This module makes the namespace drop-in: every csgraph callable takes
package arrays (converted at the boundary for host fallbacks), and the
bulk-parallel algorithms run natively on device:

- ``laplacian``: L = D - A from one degree reduction (SpMV-shaped).
- ``connected_components`` (undirected/weak): min-label propagation —
  each sweep is two scatter-min ops over the edge list, O(diameter)
  sweeps, all inside one jitted while_loop.  A graph BFS/union-find is
  sequential; label propagation is the TPU-shaped equivalent.
- ``shortest_path`` / ``bellman_ford`` / ``dijkstra`` / ``johnson``:
  min-plus relaxation — each sweep is one vectorized gather + scatter-
  min over the edge list for ALL sources at once (a min-plus SpMM),
  inside one jitted while_loop; a priority queue is inherently
  sequential, edge relaxation is the TPU-shaped equivalent and is
  correct for negative weights too (so ``dijkstra`` here matches
  ``johnson`` instead of silently degrading).
- ``floyd_warshall``: the classic k-loop as a ``fori_loop`` of rank-1
  min-plus outer updates on the dense (n, n) distance matrix.

The reference has no graph surface at all (exhaustive tree read,
SURVEY §2).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from .types import index_dtype

__all__ = [
    "connected_components", "laplacian", "shortest_path",
    "bellman_ford", "dijkstra", "johnson", "floyd_warshall",
    "minimum_spanning_tree", "NegativeCycleError",
]

# scipy's exception class so callers' except clauses work unchanged.
from scipy.sparse.csgraph import NegativeCycleError  # noqa: E402

_UNREACHABLE = -9999  # scipy's predecessor/source sentinel


def _as_package_csr(graph):
    from .csr import _is_scipy_sparse, csr_array

    if _is_scipy_sparse(graph):
        return csr_array(graph)
    if hasattr(graph, "tocsr") and hasattr(graph, "nnz"):
        return graph.tocsr()
    return csr_array(jnp.asarray(graph))


def _narrow_indices(x):
    """scipy.sparse.csgraph's Cython kernels are int32-indexed; narrow
    int64 index arrays when they fit (raw scipy rejects them outright —
    'Buffer dtype mismatch' — so this is a strict usability win)."""
    import scipy.sparse as _sp

    if (_sp.issparse(x) and x.format == "csr"
            and x.indices.dtype == np.int64
            and x.shape[1] <= np.iinfo(np.int32).max
            and x.nnz <= np.iinfo(np.int32).max):
        return _sp.csr_array(
            (x.data, x.indices.astype(np.int32),
             x.indptr.astype(np.int32)), shape=x.shape)
    return x


def _host_fallback(name):
    """Compose the shared boundary adapter with csgraph-specific index
    narrowing.  The outer wrapper converts package arrays AND narrows;
    scipy_fallback's own ``_to_scipy`` then passes the already-scipy
    operands through unchanged (idempotent), so the boundary behavior
    stays defined in exactly one place (``coverage.scipy_fallback``)."""
    import functools

    import scipy.sparse.csgraph as _csg

    from .coverage import _to_scipy, scipy_fallback

    inner = scipy_fallback(getattr(_csg, name), f"csgraph.{name}")

    @functools.wraps(inner)
    def wrapper(*args, **kwargs):
        args = tuple(_narrow_indices(_to_scipy(a)) for a in args)
        kwargs = {k: _narrow_indices(_to_scipy(v))
                  for k, v in kwargs.items()}
        return inner(*args, **kwargs)

    return wrapper


@partial(jax.jit, static_argnames=("n",))
def _label_propagation(rows, cols, n: int):
    """Min-label propagation over an undirected edge list.  Converges
    to per-component minimum node ids in O(diameter) sweeps."""
    labels0 = jnp.arange(n, dtype=index_dtype())

    def cond(state):
        _, changed = state
        return changed

    def body(state):
        labels, _ = state
        new = labels.at[rows].min(labels[cols])
        new = new.at[cols].min(new[rows])
        return new, jnp.any(new != labels)

    labels, _ = jax.lax.while_loop(
        cond, body, (labels0, jnp.asarray(True)))
    return labels


def connected_components(csgraph, directed=True, connection="weak",
                         return_labels=True):
    """Number of connected components (+ labels) — scipy signature.

    Undirected graphs and directed/'weak' run natively (weak
    connectivity ignores edge direction, so both reduce to the same
    symmetrized propagation).  Directed 'strong' delegates to host
    scipy (Tarjan is inherently sequential).
    """
    connection = str(connection).lower()
    if connection not in ("weak", "strong"):
        raise ValueError("connection must be 'weak' or 'strong'")
    if directed and connection == "strong":
        return _host_fallback("connected_components")(
            csgraph, directed=directed, connection=connection,
            return_labels=return_labels)
    A = _as_package_csr(csgraph)
    n = A.shape[0]
    if A.shape[0] != A.shape[1]:
        raise ValueError("graph must be square")
    if n == 0:
        return (0, np.zeros(0, dtype=np.int32)) if return_labels else 0
    rows = A._get_row_ids()
    cols = A._indices
    raw = np.asarray(_label_propagation(rows, cols, n))
    # scipy labels components 0..k-1 in order of first appearance.
    # Raw labels are component-minimum node ids, whose first occurrence
    # is the id itself — so np.unique's sorted order IS first-
    # appearance order and `inverse` is already the scipy labeling.
    uniq, inverse = np.unique(raw, return_inverse=True)
    labels = inverse.astype(np.int32)
    return (len(uniq), labels) if return_labels else len(uniq)


def laplacian(csgraph, normed=False, return_diag=False,
              use_out_degree=False, *, copy=True, form="array",
              dtype=None, symmetrized=False):
    """Graph Laplacian L = D - A (scipy signature), built on device
    from one degree reduction.  ``form != 'array'`` (callable/LO forms)
    delegates to host scipy."""
    if form != "array":
        return _host_fallback("laplacian")(
            csgraph, normed=normed, return_diag=return_diag,
            use_out_degree=use_out_degree, copy=copy, form=form,
            dtype=dtype, symmetrized=symmetrized)
    A = _as_package_csr(csgraph)
    if A.shape[0] != A.shape[1]:
        raise ValueError("csgraph must be a square matrix or array")
    if dtype is not None:
        A = A.astype(dtype)
    elif normed and not np.issubdtype(np.dtype(A.dtype), np.inexact):
        A = A.astype(np.float64)   # int input; complex is preserved
    if symmetrized:
        A = A + A.T.conj().tocsr()   # scipy: m += m.T.conj()
    # scipy semantics (``_laplacian_sparse``): degrees EXCLUDE
    # self-loops, and the result diagonal is overwritten outright.
    axis = 1 if use_out_degree else 0
    d = (jnp.asarray(A.sum(axis=axis)).reshape(-1)
         - jnp.asarray(A.diagonal()))
    row_ids = A._get_row_ids()
    if not normed:
        L = A._with_data(-A._data)
        L.setdiag(np.asarray(d))
        return (L, np.asarray(d)) if return_diag else L
    isolated = d == 0
    w = jnp.where(isolated, 1.0, jnp.sqrt(jnp.where(isolated, 1.0, d)))
    L = A._with_data(-A._data / (w[row_ids] * w[A._indices]))
    L.setdiag(np.asarray(1.0 - isolated.astype(w.dtype)))
    return (L, np.asarray(w)) if return_diag else L


# ---------------------------------------------------------------------------
# Shortest paths: min-plus relaxation (all sources at once) + Floyd-Warshall.
# ---------------------------------------------------------------------------

def _graph_edges(csgraph, directed, unweighted):
    """Edge list (rows, cols, w) of the traversal graph.  Stored zeros
    ARE edges (scipy semantics, verified); ``directed=False`` appends
    the reversed edges — scatter-min relaxation then takes the min of
    the two directions automatically."""
    from .runtime import runtime

    A = _as_package_csr(csgraph)
    if A.shape[0] != A.shape[1]:
        raise ValueError("graph must be a square matrix or array")
    n = A.shape[0]
    rows = A._get_row_ids()
    cols = A._indices
    fdt = runtime.default_float
    if unweighted:
        w = jnp.ones(rows.shape, dtype=fdt)
    else:
        w = A._data.astype(fdt) if A._data.dtype != fdt else A._data
    if not directed:
        rows, cols = jnp.concatenate([rows, cols]), jnp.concatenate(
            [cols, rows])
        w = jnp.concatenate([w, w])
    return rows, cols, w, n


@partial(jax.jit, static_argnames=("n",))
def _relax_all(rows, cols, w, sources, n: int):
    """Bellman-Ford for all sources at once.  One sweep = one min-plus
    semiring SpMM (``ops/spmv.py csr_semiring_spmm_rowids_masked``) of
    the transposed edge operator against the (n, S) tentative-distance
    block — the SAME kernel the distributed graph engine dispatches
    (``legate_sparse_tpu.graph``), so single-device and distributed
    relaxation share one code path.  Bit-compatible with the previous
    scatter-min form: min over the identical multiset of dist[u]+w
    candidates is order-insensitive, unlike a sum.  Runs at most n
    sweeps; a sweep that still improves after n-1 of them can only
    mean a reachable negative cycle."""
    from .ops import spmv as _sp

    S = sources.shape[0]
    # Sort edges by head so segment_min sees sorted segment ids (the
    # kernel's indices_are_sorted contract); tails become the gather.
    order = jnp.argsort(cols, stable=True)
    heads, tails, we = cols[order], rows[order], w[order]
    nnz = jnp.asarray(we.shape[0], dtype=jnp.int32)
    dist0 = jnp.full((n, S), jnp.inf, dtype=w.dtype)
    dist0 = dist0.at[sources, jnp.arange(S)].set(0.0)

    def sweep(dist):
        relaxed = _sp.csr_semiring_spmm_rowids_masked(
            we, tails, heads, nnz, dist, n, "min", "plus")
        return jnp.minimum(dist, relaxed)

    def body(state):
        dist, k, _ = state
        new = sweep(dist)
        return new, k + 1, jnp.any(new < dist)

    def cond(state):
        _, k, changed = state
        return changed & (k < n)

    dist, _, _ = jax.lax.while_loop(
        cond, body, (dist0, jnp.asarray(0), jnp.asarray(True)))
    extra = sweep(dist)
    return dist.T, jnp.any(extra < dist)


@partial(jax.jit, static_argnames=("n",))
def _predecessors(rows, cols, w, dist, sources, n: int):
    """Predecessor matrix consistent with a converged distance matrix:
    node j's predecessor (per source) is the smallest-indexed edge tail
    u with dist[u] + w == dist[j].  One gather + one scatter-min."""
    S = dist.shape[0]
    tail = dist[:, rows]
    # inf + w == inf would mark edges between unreachable nodes as
    # "tight"; scipy keeps -9999 there
    tight = jnp.isfinite(tail) & (tail + w[None, :] == dist[:, cols])
    cand = jnp.where(tight, rows[None, :], n)
    pred = jnp.full((S, n), n, dtype=rows.dtype).at[:, cols].min(cand)
    pred = jnp.where(pred == n, _UNREACHABLE, pred)
    return pred.at[jnp.arange(S), sources].set(_UNREACHABLE)


def _resolve_indices(indices, n):
    """(sources array, squeeze?) per scipy: None → all nodes, scalar →
    1-D result, negative wraps, out of range raises."""
    if indices is None:
        return np.arange(n, dtype=np.int64), False
    idx = np.asarray(indices, dtype=np.int64)
    scalar = idx.ndim == 0
    idx = np.atleast_1d(idx)
    if idx.size and (np.any(idx < -n) or np.any(idx >= n)):
        raise ValueError("indices out of range 0...N")
    return idx % max(n, 1), scalar


def _minplus_paths(csgraph, directed, indices, return_predecessors,
                   unweighted, limit=None, edges=None):
    rows, cols, w, n = (edges if edges is not None
                        else _graph_edges(csgraph, directed, unweighted))
    src, scalar = _resolve_indices(indices, n)
    if n == 0 or src.size == 0:
        dist = np.zeros((src.size, n))
        pred = np.full((src.size, n), _UNREACHABLE, dtype=np.int32)
    else:
        jsrc = jnp.asarray(src)
        ddist, neg = _relax_all(rows, cols, w, jsrc, n)
        if bool(neg):
            raise NegativeCycleError(
                "Negative cycle detected on the graph")
        if return_predecessors:
            pred = np.asarray(
                _predecessors(rows, cols, w, ddist, jsrc, n),
                dtype=np.int32)
        dist = np.asarray(ddist, dtype=np.float64)
    if limit is not None and limit != np.inf:
        # any prefix of a within-limit path is within limit for
        # non-negative weights, so post-filtering equals scipy's
        # in-search cutoff
        over = dist > limit
        dist = np.where(over, np.inf, dist)
        if return_predecessors:
            pred = np.where(over, np.int32(_UNREACHABLE), pred)
    if scalar:
        dist = dist[0]
        if return_predecessors:
            pred = pred[0]
    return (dist, pred) if return_predecessors else dist


def bellman_ford(csgraph, directed=True, indices=None,
                 return_predecessors=False, unweighted=False,
                 overwrite=False):
    """Bellman-Ford shortest paths (scipy signature), computed as
    jitted min-plus edge relaxation for all sources simultaneously.
    Raises :class:`NegativeCycleError` like scipy."""
    return _minplus_paths(csgraph, directed, indices,
                          return_predecessors, unweighted)


def dijkstra(csgraph, directed=True, indices=None,
             return_predecessors=False, unweighted=False,
             limit=np.inf, min_only=False):
    """Dijkstra-compatible shortest paths (scipy signature).  A binary
    heap is inherently sequential; the same distances come out of the
    min-plus relaxation sweep, which also stays correct under negative
    weights (scipy's dijkstra only warns and degrades there — we keep
    the warning for parity but return the exact answer).

    Deviation from scipy: when the graph contains a *reachable negative
    cycle* this raises :class:`NegativeCycleError` (no finite shortest
    path exists), whereas scipy's dijkstra warns and returns
    inaccurate finite values.  Callers that need scipy's
    never-raise behavior should catch ``NegativeCycleError`` (also
    raised by ``shortest_path(method='D')`` through this routine)."""
    edges = _graph_edges(csgraph, directed, unweighted)
    w_ = edges[2]
    if w_.size and bool(jnp.any(w_ < 0)):
        import warnings

        warnings.warn("Graph has negative weights: dijkstra will give "
                      "inaccurate results if the graph contains "
                      "negative cycles. Consider johnson or "
                      "bellman_ford.", UserWarning, stacklevel=2)
    res = _minplus_paths(csgraph, directed, indices,
                         return_predecessors=return_predecessors,
                         unweighted=unweighted, limit=limit,
                         edges=edges)
    if not min_only:
        return res
    # min_only: collapse the per-source rows to the elementwise best
    # source; scipy returns (dist, predecessors, sources).
    dist, pred = res if return_predecessors else (res, None)
    dist2 = np.atleast_2d(dist)
    src, _ = _resolve_indices(indices, dist2.shape[1])
    win = np.argmin(dist2, axis=0)
    ar = np.arange(dist2.shape[1])
    best = dist2[win, ar]
    sources = np.where(np.isinf(best), _UNREACHABLE,
                       src[win]).astype(np.int32)
    if not return_predecessors:
        return best
    return best, np.atleast_2d(pred)[win, ar], sources


def johnson(csgraph, directed=True, indices=None,
            return_predecessors=False, unweighted=False):
    """Johnson's algorithm (scipy signature).  Its whole point is
    making negative weights safe for a heap — the min-plus relaxation
    already is, so this is the same kernel as :func:`bellman_ford`."""
    return _minplus_paths(csgraph, directed, indices,
                          return_predecessors, unweighted)


@partial(jax.jit, static_argnames=("n", "want_pred"))
def _fw_kernel(dense, pred0, n: int, want_pred: bool):
    def body(k, state):
        dist, pred = state
        via = dist[:, k][:, None] + dist[k, :][None, :]
        better = via < dist
        dist = jnp.where(better, via, dist)
        if want_pred:
            pred = jnp.where(better, pred[k, :][None, :], pred)
        return dist, pred

    return jax.lax.fori_loop(0, n, body, (dense, pred0))


def floyd_warshall(csgraph, directed=True, return_predecessors=False,
                   unweighted=False, overwrite=False):
    """Floyd-Warshall all-pairs shortest paths (scipy signature): the
    k-loop is a ``fori_loop`` of rank-1 min-plus outer-product updates
    on the dense (n, n) distance matrix — each step is one broadcast
    add + elementwise min, ideal VPU shape."""
    rows, cols, w, n = _graph_edges(csgraph, directed, unweighted)
    if n == 0:
        dist = np.zeros((0, 0))
        return (dist, np.zeros((0, 0), np.int32)) \
            if return_predecessors else dist
    dense = jnp.full((n, n), jnp.inf, dtype=w.dtype)
    dense = dense.at[rows, cols].min(w)
    diag = jnp.minimum(jnp.diagonal(dense), 0.0)  # self-loops can only
    dense = dense.at[jnp.arange(n), jnp.arange(n)].set(diag)  # lower 0
    if return_predecessors:
        pred0 = jnp.where(
            jnp.isfinite(dense)
            & (jnp.arange(n)[:, None] != jnp.arange(n)[None, :]),
            jnp.arange(n, dtype=jnp.int32)[:, None],
            jnp.int32(_UNREACHABLE))
    else:
        pred0 = jnp.zeros((1, 1), dtype=jnp.int32)
    dist, pred = _fw_kernel(dense, pred0, n, return_predecessors)
    if bool(jnp.any(jnp.diagonal(dist) < 0)):
        raise NegativeCycleError(
            "Negative cycle detected on the graph")
    dist = np.asarray(dist, dtype=np.float64)
    if return_predecessors:
        return dist, np.asarray(pred, dtype=np.int32)
    return dist


def shortest_path(csgraph, method="auto", directed=True,
                  return_predecessors=False, unweighted=False,
                  overwrite=False, indices=None):
    """Dispatch front-end matching ``scipy.sparse.csgraph
    .shortest_path``.  'FW' runs the dense kernel; 'D'/'BF'/'J' and
    'auto' run the min-plus relaxation (correct for every weight sign,
    so 'auto' never needs scipy's heuristics)."""
    if method == "FW":
        if indices is not None:
            raise ValueError("Cannot specify indices with method == 'FW'")
        return floyd_warshall(csgraph, directed=directed,
                              return_predecessors=return_predecessors,
                              unweighted=unweighted, overwrite=overwrite)
    if method not in ("auto", "D", "BF", "J"):
        raise ValueError(f"unrecognized method '{method}'")
    return _minplus_paths(csgraph, directed, indices,
                          return_predecessors, unweighted)


# ---------------------------------------------------------------------------
# Minimum spanning tree: Boruvka rounds, fully jitted.
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("n",))
def _boruvka(rows, cols, w, n: int):
    """Boruvka MST over the stored (directed) edge list, treated as
    undirected.  Each round every component scatter-mins its cheapest
    outgoing edge under the STRICT total order (weight, stored index);
    stored CSR order is (row, col), so this is lexicographic
    lowest-(weight, row, col) — ties never depend on scatter order,
    and the perturbed-weight MST is unique, so the returned edge set
    is a deterministic function of the input (pinned by the tie-heavy
    regression test against a reference lexicographic Kruskal).
    Mutual duplicate picks are dropped on the larger component id, and
    components merge by min-label propagation with path compression.
    O(log n) rounds, each a handful of gathers/scatter-mins — the
    TPU-shaped replacement for Kruskal's inherently sequential
    sort + union-find.  Returns the in-tree mask over stored edges."""
    E = rows.shape[0]
    eidx = jnp.arange(E, dtype=index_dtype())
    comp0 = jnp.arange(n, dtype=index_dtype())
    in_tree0 = jnp.zeros((E,), dtype=bool)
    big_w = jnp.asarray(jnp.inf, dtype=w.dtype)

    def round_(state):
        comp, in_tree, _ = state
        cu, cv = comp[rows], comp[cols]
        cross = cu != cv
        Wc = jnp.where(cross, w, big_w)
        # Cheapest cross edge per component (either endpoint side).
        best_w = (jnp.full((n,), big_w, dtype=w.dtype)
                  .at[cu].min(Wc).at[cv].min(Wc))
        tie_u = cross & (Wc == best_w[cu])
        tie_v = cross & (Wc == best_w[cv])
        best_e = (jnp.full((n,), E, dtype=index_dtype())
                  .at[cu].min(jnp.where(tie_u, eidx, E))
                  .at[cv].min(jnp.where(tie_v, eidx, E)))
        has = best_e < E
        be = jnp.minimum(best_e, E - 1)
        # Mutual picks: components c and p chose edges over the same
        # unordered pair {c, p} (possibly the two stored copies of one
        # undirected edge) — keep only the pick of min(c, p).
        ecu, ecv = comp[rows[be]], comp[cols[be]]
        partner = jnp.where(ecu == comp0, ecv, ecu)
        pe = jnp.minimum(best_e[jnp.clip(partner, 0, n - 1)], E - 1)
        p_cu, p_cv = comp[rows[pe]], comp[cols[pe]]
        mutual = (jnp.minimum(p_cu, p_cv) == jnp.minimum(ecu, ecv)) & (
            jnp.maximum(p_cu, p_cv) == jnp.maximum(ecu, ecv))
        keep = has & ~(mutual & (partner < comp0))
        sel = (jnp.zeros((E + 1,), dtype=bool)
               .at[jnp.where(keep, be, E)].set(True))[:E]
        in_tree = in_tree | sel
        # Merge: min-label propagation restricted to selected edges
        # (out-of-range index n drops unselected scatters/gathers),
        # plus one pointer-jump per sweep for long chains.
        r_i = jnp.where(sel, rows, n)
        c_i = jnp.where(sel, cols, n)

        def prop_cond(s):
            _, changed = s
            return changed

        def prop_body(s):
            lab, _ = s
            lab_pad = jnp.concatenate(
                [lab, jnp.full((1,), n, dtype=lab.dtype)])
            # Hook at the CLASS labels of the endpoints (not just the
            # endpoint nodes): the class root learns the merged min
            # directly, so the pointer-jump below flattens the whole
            # class in one sweep and chain-like merges keep the
            # O(log n) round bound (advisor r3).  Unselected edges
            # carry index n -> label n -> writes land in the pad slot,
            # which is dropped by the [:n] slice.
            lu = lab_pad[r_i]
            lv = lab_pad[c_i]
            new = lab_pad.at[lu].min(lv)
            new = new.at[lv].min(new[lu])
            new = new.at[r_i].min(new[c_i])
            new = new.at[c_i].min(new[r_i])[:n]
            new = jnp.minimum(new, new[jnp.clip(new, 0, n - 1)])
            return new, jnp.any(new != lab)

        labels, _ = jax.lax.while_loop(
            prop_cond, prop_body, (comp, jnp.asarray(True)))
        return labels, in_tree, jnp.any(cross)

    def cond(state):
        _, _, progressed = state
        return progressed

    comp, in_tree, _ = jax.lax.while_loop(
        cond, round_, (comp0, in_tree0, jnp.asarray(True)))
    return in_tree


def minimum_spanning_tree(csgraph, overwrite=False):
    """Minimum spanning tree / forest (scipy signature and output
    shape: CSR holding each chosen edge at its stored position, other
    entries implicit).  Runs Boruvka rounds natively on device; with
    distinct weights the MST is unique, so the edge set matches
    scipy's Kruskal exactly.  Equal-weight ties break by the
    DETERMINISTIC lowest-(weight, row, col) policy: among tied
    candidates the edge at the lexicographically smallest stored
    (row, col) wins — equivalently the smallest stored index, so for
    a symmetric input the row-major-first copy is the one kept.
    scipy's own tie-breaks may differ edge-by-edge, but the total
    tree weight always agrees.

    scipy-wart parity, both verified against scipy 1.17: the output
    data is float64 regardless of input dtype, and a CHOSEN zero-
    weight edge is dropped from the stored structure (scipy's CSR
    construction loses explicit zeros — the tree edge exists
    mathematically but not in the returned matrix).
    """
    A = _as_package_csr(csgraph)
    if A.shape[0] != A.shape[1]:
        raise ValueError("graph must be a square matrix or array")
    n = A.shape[0]
    from .csr import csr_array

    if n == 0 or A.nnz == 0:
        return csr_array(
            (np.zeros(0, np.float64), np.zeros(0, np.int64),
             np.zeros(n + 1, np.int64)), shape=(n, n))
    rows = A._get_row_ids().astype(index_dtype())
    cols = A._indices.astype(index_dtype())
    from .runtime import runtime

    w = A._data.astype(runtime.default_float)
    in_tree = _boruvka(rows, cols, w, n)
    mask = np.asarray(in_tree)
    v = np.asarray(A._data)[mask].astype(np.float64)
    keep = v != 0                      # scipy drops chosen zero edges
    r = np.asarray(rows)[mask][keep]
    c = np.asarray(cols)[mask][keep]
    v = v[keep]
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(np.bincount(r, minlength=n), out=indptr[1:])
    return csr_array((jnp.asarray(v), jnp.asarray(c), jnp.asarray(indptr)),
                     shape=(n, n))


def __getattr__(name):
    import scipy.sparse.csgraph as _csg

    try:
        value = getattr(_csg, name)
    except AttributeError:
        raise AttributeError(
            f"module 'legate_sparse_tpu.csgraph' has no attribute "
            f"{name!r}") from None
    if callable(value) and not isinstance(value, type):
        value = _host_fallback(name)
    globals()[name] = value
    return value
