# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""CSR arrays on JAX/XLA — the core data structure.

Parity target: the reference's ``csr_array`` (reference:
``legate_sparse/csr.py:88-555``) and its free functions ``spmv``
(``csr.py:562-593``) and ``spgemm_csr_csr_csr`` (``csr.py:598-748``).

TPU-first re-design (not a port):

- Storage is three ``jax.Array``s — ``data`` (nnz), ``indices`` (nnz),
  ``indptr`` (rows+1) — instead of the reference's Legion stores with a
  Rect<1> ``pos`` encoding (``csr.py:88-107``).  ``indptr`` is what XLA
  consumes directly; rect packing/unpacking disappears.
- Every method is a thin driver over jitted kernels in ``ops/``; there is
  no task runtime, mapper, or CFFI layer.
- nnz is always concrete (host int): the XLA analog of the reference
  blocking on its nnz future (``csr.py:130,714``) — static shapes are
  what let XLA tile for the MXU/VPU.
- Distribution: a ``csr_array`` may carry a row-block sharding produced
  by ``legate_sparse_tpu.parallel`` (the analog of the reference's
  ``align``/``image`` constraints, ``csr.py:580-593``); single-device
  semantics are identical.
"""

from __future__ import annotations

import warnings
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from scipy.sparse import SparseEfficiencyWarning

from . import obs as _obs
from .obs import latency as _lat
from .engine import route_matmat as _engine_route_matmat
from .engine import route_matvec as _engine_route_matvec
from .autotune import route_matmat as _autotune_route_matmat
from .autotune import route_matvec as _autotune_route_matvec
from .resilience import faults as _rfaults
from .resilience import policy as _rpolicy
from .settings import settings as _rsettings
from .base import CompressedBase, DenseSparseBase
from .runtime import runtime
from .types import check_nnz, coord_dtype_for, index_dtype, nnz_dtype
from .utils import cast_to_common_type, fill_out, require_supported_dtype
from .ops import convert as _convert
from .ops import dia_ops as _dia_ops
from .ops import spmv as _spmv_ops
from .ops import spgemm as _spgemm_ops

try:  # scipy is an optional interop dependency, always present in tests
    import scipy.sparse as _scipy_sparse
except ImportError:  # pragma: no cover
    _scipy_sparse = None


def _is_scipy_sparse(obj) -> bool:
    return _scipy_sparse is not None and _scipy_sparse.issparse(obj)


def _is_sparse_like(obj) -> bool:
    """Sparse-format object of this package or another library (has a
    CSR conversion, is not dense-array-like)."""
    return hasattr(obj, "tocsr") and not hasattr(obj, "__array__")


def _dia_xla_nopad() -> bool:
    """Pick the XLA banded-SpMV lowering (``settings.dia_xla_variant``):
    the interior/edge-split ``dia_spmv_nopad`` skips the padded-x
    materialization — a measured ~20-25% win on bandwidth-starved CPU
    backends — while TPU keeps the padded ``dia_spmv_fused`` layout
    whose same-length slices Mosaic/XLA:TPU handle best."""
    from .settings import settings

    variant = settings.dia_xla_variant
    if variant == "nopad":
        return True
    if variant == "auto":
        try:
            return jax.devices()[0].platform == "cpu"
        except Exception:
            return False
    return False


class csr_array(CompressedBase, DenseSparseBase):
    """Compressed Sparse Row array backed by jax.Arrays.

    Constructor forms (same set as reference ``csr.py:89-286``):

    - ``csr_array(dense_2d)`` — two-pass nonzero count + compaction
      (fully shardable, unlike the reference's single-process fill,
      ``csr.py:134-145``).
    - ``csr_array(scipy_sparse)`` — adopt scipy's buffers.
    - ``csr_array(other_csr, copy=...)``.
    - ``csr_array((data, (row, col)), shape=...)`` — COO with stable
      row sort (``csr.py:183-219`` semantics).
    - ``csr_array((data, indices, indptr), shape=...)``.
    """

    format = "csr"

    def __init__(self, arg, shape=None, dtype=None, copy: bool = False):
        self._sharding_info = None  # set by parallel.shard_csr
        # None = unknown (computed lazily by has_canonical_format).
        canonical: Optional[bool] = None
        if isinstance(arg, csr_array):
            shape = arg.shape if shape is None else tuple(shape)
            data, indices, indptr = arg.data, arg.indices, arg.indptr
            canonical = arg._canonical
            if dtype is not None and np.dtype(dtype) != arg.dtype:
                data = data.astype(np.dtype(dtype))
        elif _is_scipy_sparse(arg):
            arg = arg.tocsr()
            if shape is None:
                shape = arg.shape
            check_nnz(int(arg.nnz))
            data = jnp.asarray(arg.data)
            indices = jnp.asarray(
                arg.indices, dtype=coord_dtype_for(max(arg.shape))
            )
            indptr = jnp.asarray(arg.indptr, dtype=nnz_dtype())
            canonical = bool(arg.has_canonical_format)
            if dtype is not None:
                data = data.astype(np.dtype(dtype))
        elif (isinstance(arg, tuple) and len(arg) == 2
              and all(isinstance(s, (int, np.integer)) for s in arg)):
            # Empty matrix from a shape tuple (scipy ``csr_array((M, N))``).
            shape = (int(arg[0]), int(arg[1]))
            out_dtype = np.dtype(dtype) if dtype is not None else (
                runtime.default_float
            )
            data = jnp.zeros((0,), dtype=out_dtype)
            indices = jnp.zeros((0,), dtype=coord_dtype_for(max(shape)))
            indptr = jnp.zeros((shape[0] + 1,), dtype=nnz_dtype())
            canonical = True
        elif isinstance(arg, tuple) and len(arg) == 2 and isinstance(arg[1], tuple):
            # COO: (data, (row, col))
            data_in, (row, col) = arg
            row = jnp.asarray(row)
            col = jnp.asarray(col)
            data_in = jnp.asarray(data_in)
            check_nnz(int(data_in.shape[0]))
            if shape is None:
                shape = (int(row.max()) + 1, int(col.max()) + 1)
            shape = tuple(int(s) for s in shape)
            # Pow2 shape-bucketed COO-build counter (bounded
            # cardinality): repeated same-bucket rebuilds are the
            # doctor's delta-disabled-but-rebuilding signal — a
            # workload paying full CSR reconstruction for what the
            # delta layer serves as a streamed second term
            # (docs/MUTATION.md).
            _obs.inc("build.csr.coo."
                     f"{1 << max(shape[0] - 1, 0).bit_length()}x"
                     f"{1 << max(shape[1] - 1, 0).bit_length()}")
            cdt = coord_dtype_for(max(shape))
            data, indices, indptr = _convert.coo_to_csr(
                row.astype(cdt), col.astype(cdt), data_in, shape[0]
            )
            if dtype is not None:
                data = data.astype(np.dtype(dtype))
        elif isinstance(arg, tuple) and len(arg) == 3:
            data_in, indices_in, indptr_in = arg
            if hasattr(indices_in, "__len__") or hasattr(indices_in, "shape"):
                check_nnz(int(np.shape(indices_in)[0]))
            indptr = jnp.asarray(indptr_in, dtype=nnz_dtype())
            rows = indptr.shape[0] - 1
            if shape is None:
                cols = int(jnp.max(jnp.asarray(indices_in))) + 1 if len(indices_in) else 0
                shape = (rows, cols)
            shape = tuple(int(s) for s in shape)
            indices = jnp.asarray(indices_in, dtype=coord_dtype_for(max(shape)))
            data = jnp.asarray(data_in)
            if dtype is not None:
                data = data.astype(np.dtype(dtype))
        else:
            # Dense (jax / numpy / nested list).
            dense = jnp.asarray(arg)
            if dense.ndim != 2:
                raise ValueError(
                    f"csr_array requires a 2-D input, got ndim={dense.ndim}"
                )
            if dtype is not None:
                dense = dense.astype(np.dtype(dtype))
            if shape is not None and tuple(shape) != dense.shape:
                raise ValueError("shape mismatch with dense input")
            shape = dense.shape
            nnz = _convert.dense_nnz(dense)
            data, indices, indptr = _convert.dense_to_csr(dense, nnz)
            canonical = True

        if copy:
            data = jnp.array(data)
            indices = jnp.array(indices)
            indptr = jnp.array(indptr)

        self._data = data
        self._indices = indices
        self._indptr = indptr
        self._canonical = canonical
        self._sorted = True if canonical else None
        # Cached static structure for the SpMV hot path (the analog of
        # Legion caching image partitions across solver iterations,
        # reference §3.2): built lazily on first matvec.
        self._row_ids = None
        self._ell = None
        self._ell_width = None
        self._dia = None
        self._dia_offsets = None
        self._dia_pack = None
        self._dia_fused = None
        self._bsr = None
        # Engine bucket pack: (key terms, padded operands) — built by
        # legate_sparse_tpu.engine on first routed dispatch.
        self._engine_pack = None
        # Autotune caches: structure fingerprint (verdict-key term) and
        # the row-binned sliced-ELL pack (False = tried, not viable).
        self._fingerprint = None
        self._sliced_ell = None
        self.shape: Tuple[int, int] = tuple(int(s) for s in shape)
        assert self._indptr.shape[0] == self.shape[0] + 1, (
            f"indptr length {self._indptr.shape[0]} != rows+1 "
            f"({self.shape[0] + 1})"
        )
        from .settings import settings as _settings

        if _settings.check_bounds:
            self._check_bounds()

    def _check_bounds(self) -> None:
        """Debug-mode index validation (LEGATE_SPARSE_TPU_CHECK_BOUNDS;
        the accessor-bounds-check analog of the reference's
        ``Legion_BOUNDS_CHECKS``, ``install.py:375-381``).  Host syncs —
        only for debugging."""
        import numpy as _np

        indptr = _np.asarray(self._indptr)
        indices = _np.asarray(self._indices)
        if indptr[0] != 0 or indptr[-1] != indices.shape[0]:
            raise IndexError(
                f"indptr endpoints [{indptr[0]}, {indptr[-1]}] "
                f"inconsistent with nnz={indices.shape[0]}"
            )
        if _np.any(_np.diff(indptr) < 0):
            raise IndexError("indptr is not monotonically non-decreasing")
        if indices.size and (
            indices.min() < 0 or indices.max() >= self.shape[1]
        ):
            raise IndexError(
                f"column indices out of range [0, {self.shape[1]}): "
                f"min={indices.min()}, max={indices.max()}"
            )

    @classmethod
    def _from_parts(cls, data, indices, indptr, shape,
                    canonical: Optional[bool] = True) -> "csr_array":
        """Internal fast constructor for kernel outputs (which are always
        row-sorted; ``canonical=True`` unless duplicates may remain)."""
        obj = cls((data, indices, indptr), shape=shape)
        obj._canonical = canonical
        return obj

    # -- structure-sharing constructor (reference ``base.py:174-196``) --
    def _with_data(self, data, copy: bool = False):
        if copy:
            data = jnp.array(data)
        out = type(self)._from_parts(
            data, self._indices, self._indptr, self.shape,
            canonical=self._canonical,
        )
        out._row_ids = self._row_ids  # sparsity structure is shared
        out._ell_width = self._ell_width
        out._dia_offsets = self._dia_offsets
        out._fingerprint = self._fingerprint
        out._sorted = self._sorted
        return out

    # ---------------- properties ----------------
    @property
    def dim(self) -> int:
        return len(self.shape)

    @property
    def nnz(self) -> int:
        return int(self._data.shape[0])

    @property
    def dtype(self) -> np.dtype:
        return np.dtype(self._data.dtype)

    @property
    def data(self):
        return self._data

    @data.setter
    def data(self, value):
        value = jnp.asarray(value)
        if value.shape != self._data.shape:
            raise ValueError("cannot change nnz via data setter")
        self._data = value
        self._invalidate_caches(structure_changed=False)

    @property
    def indices(self):
        return self._indices

    @indices.setter
    def indices(self, value):
        value = jnp.asarray(value, dtype=self._indices.dtype)
        if value.shape != self._indices.shape:
            raise ValueError("cannot change nnz via indices setter")
        self._indices = value
        # Column indices changed but the row partition (indptr) did
        # not: every cache except the per-nnz row ids is stale.
        rid = self._row_ids
        self._invalidate_caches(structure_changed=True)
        self._row_ids = rid

    @property
    def indptr(self):
        return self._indptr

    @property
    def has_canonical_format(self) -> bool:
        """True when indices are strictly increasing within every row
        (sorted, no duplicates).  Computed lazily and cached for inputs
        whose canonicalness is unknown (COO / raw-triple constructors,
        which keep duplicates to match reference ``csr.py:183-219``)."""
        if self._canonical is None:
            if self.nnz < 2:
                self._canonical = True
            else:
                row_ids = _convert.row_ids_from_indptr(self._indptr, self.nnz)
                same_row = row_ids[1:] == row_ids[:-1]
                increasing = self._indices[1:] > self._indices[:-1]
                self._canonical = bool(
                    jnp.all(jnp.logical_or(~same_row, increasing))
                )
        return self._canonical

    @property
    def has_sorted_indices(self) -> bool:
        """Non-decreasing indices within every row (duplicates allowed —
        weaker than canonical; scipy's ``has_sorted_indices``)."""
        if self._canonical:
            return True
        if getattr(self, "_sorted", None) is None:
            if self.nnz < 2:
                self._sorted = True
            else:
                row_ids = _convert.row_ids_from_indptr(
                    self._indptr, self.nnz
                )
                same_row = row_ids[1:] == row_ids[:-1]
                nondecreasing = self._indices[1:] >= self._indices[:-1]
                self._sorted = bool(
                    jnp.all(jnp.logical_or(~same_row, nondecreasing))
                )
        return self._sorted

    def sum_duplicates(self) -> None:
        """Merge duplicate (row, col) entries in place (scipy contract)."""
        if self.has_canonical_format:
            return
        row_ids, cols, vals = self._coo_parts()
        data, indices, indptr = _spgemm_ops.coalesce_coo(
            row_ids, cols, vals, self.shape[0]
        )
        self._data = data
        self._indices = indices.astype(self._indices.dtype)
        self._indptr = indptr
        self._invalidate_caches(structure_changed=True)
        self._canonical = True
        self._sorted = True

    def _canonicalized(self) -> "csr_array":
        if self.has_canonical_format:
            return self
        out = csr_array(self, copy=False)
        out.sum_duplicates()
        return out

    # ---------------- storage compression ----------------
    def compress(self, values="bfloat16", indices="auto",
                 copy: bool = False) -> "csr_array":
        """Narrow the storage representation (structure shared).

        Every hot path here is bandwidth-bound, so shrinking the
        dominant byte streams — nnz values + nnz column indices — is
        speed.  ``values`` names the target value dtype (default
        ``"bfloat16"``; ``None`` keeps the current values; any
        supported dtype is accepted, so :meth:`astype_storage` can
        widen back).  ``indices`` is ``"auto"`` (int16 whenever the
        column extent fits ``int16``, else unchanged), ``None``
        (keep), or an explicit integer dtype — which raises when the
        column extent overflows it.

        ``.dtype`` stays honest (it reports the storage dtype) while
        ``.dot`` keeps f32-grade semantics: low-precision storage
        against an f32 operand dispatches the ``ops/spmv.py``
        ``*_f32acc`` kernels (f32 accumulation, f32 output) — or the
        DIA shifted-add lowerings, whose products promote to f32 per
        element — without ever materializing a widened copy of the
        matrix.

        Declared IEEE trade for banded matrices: compressed storage
        drops the DIA hole mask (the band data is zero-filled, so
        hole products are exact zeros for every *finite* operand, and
        the mask stream is a full quarter of a bf16 band's bytes).  A
        non-finite operand entry aligned with a band hole therefore
        propagates NaN where canonical f32 storage masks it — values
        are already rounded; compression is opt-in lossy.
        """
        data = self._data
        if values is not None:
            vdt = np.dtype(values)
            require_supported_dtype(vdt)
            if vdt != data.dtype:
                data = data.astype(vdt)
        idx = self._indices
        if indices is not None:
            if isinstance(indices, str) and indices == "auto":
                idt = (np.dtype(np.int16)
                       if self.shape[1] - 1 <= np.iinfo(np.int16).max
                       else None)
            else:
                idt = np.dtype(indices)
                if idt.kind != "i":
                    raise ValueError(
                        f"index storage must be a signed integer "
                        f"dtype, got {idt}")
                if self.shape[1] - 1 > np.iinfo(idt).max:
                    raise ValueError(
                        f"column extent {self.shape[1]} overflows "
                        f"index dtype {idt}")
            if idt is not None and idt != np.dtype(idx.dtype):
                idx = idx.astype(idt)
            elif copy:
                idx = jnp.array(idx)
        # _with_data shares the index-dtype-independent structure
        # caches (row ids, ELL width, DIA offsets, fingerprint); the
        # value/format packs rebuild lazily at the new storage dtypes.
        out = self._with_data(data, copy=copy and data is self._data)
        out._indices = idx
        return out

    def astype_storage(self, values=None, indices=None,
                       copy: bool = False) -> "csr_array":
        """Explicit storage-representation cast: :meth:`compress` with
        keep-by-default arguments (``astype`` changes the *logical*
        dtype and upcasts operands to match; this changes only how the
        bytes are stored)."""
        return self.compress(values=values, indices=indices, copy=copy)

    @property
    def T(self):
        return self.transpose()

    # ---------------- cached matvec structure ----------------
    @staticmethod
    def _can_build_cache(*arrays) -> bool:
        """True when structure caches may be built *now*: no tracer
        operands and no ambient trace (under omnistaging even ops on
        concrete arrays stage into an active trace, so caching their
        results on the Python object would leak tracers)."""
        if any(isinstance(a, jax.core.Tracer) for a in arrays):
            return False
        try:
            from jax._src.core import trace_state_clean
        except ImportError:  # pragma: no cover - jax internals moved
            # Unknown trace state: never cache (the uncached path is
            # always correct; caching inside a trace leaks tracers).
            return False
        return trace_state_clean()

    def _get_ell(self):
        """Cached ELL packing, or None (padding too big / can't build
        under an active trace).  The pack runs on device (one fused
        gather); only the max-row-width W is a host sync, cached with
        the structure."""
        if self._ell is not None:
            return self._ell if self._ell is not False else None
        if not self._can_build_cache(self._data, self._indices,
                                     self._indptr):
            return None
        from .settings import settings

        if self._ell_width is None:
            rows = self.shape[0]
            self._ell_width = (
                max(int(jnp.max(jnp.diff(self._indptr))), 1)
                if rows and self.nnz
                else 1
            )
        W = self._ell_width
        if not _spmv_ops.ell_within_budget(
            self.shape[0], W, self.nnz, settings.ell_max_expand
        ):
            self._ell = False
            return None
        self._ell = _spmv_ops.ell_pack_device(
            self._data, self._indices, self._indptr, self.shape[0], W
        )
        return self._ell

    def _get_bsr(self):
        """Cached block-sparse (BSR) structure, or None.

        The irregular-path kernel (``ops/bsr.py``): densified present
        128x128 blocks streamed through the MXU, skipping absent
        blocks.  Built only where it can win — on TPU (the XLA gather
        SpMV runs ~2 orders of magnitude under roofline there; on CPU
        the gather path is already fine and Pallas interpret mode is
        pure-Python slow), for f32/bf16 values, within the
        ``bsr_max_expand`` densification budget.  Matrices that are
        banded never reach here (``_get_dia`` wins the dispatch).
        ``LEGATE_SPARSE_TPU_BSR_FORCE=1`` builds it on any platform
        (differential tests run the kernel in interpret mode).

        Semantic note: densified zero slots inside *present* blocks
        multiply x (scipy's own ``bsr_array`` semantics), so a
        non-finite x entry in a column CSR never stores can produce
        NaN where exact-CSR paths stay finite.  Under
        ``LEGATE_SPARSE_TPU_CHECK_BOUNDS`` (which enables
        ``jax_debug_nans``) BSR is therefore disabled.
        """
        if self._bsr is not None:
            return self._bsr if self._bsr is not False else None
        if not self._can_build_cache(self._data, self._indices,
                                     self._indptr):
            return None
        from .settings import settings

        if not settings.bsr_force and jax.devices()[0].platform != "tpu":
            self._bsr = False
            return None
        if (settings.bsr_max_expand <= 0
                or settings.check_bounds
                or self.dtype not in (jnp.float32, jnp.bfloat16)
                or not self.has_canonical_format):
            self._bsr = False
            return None
        from .ops import bsr as _bsr_ops

        pack = _bsr_ops.bsr_pack(
            self._data, self._indices, self._indptr, self.shape,
            settings.bsr_max_expand,
        )
        if pack is None:
            self._bsr = False
            return None
        self._bsr = _bsr_ops.BsrStructure(*pack, *self.shape,
                                          dtype=self.dtype)
        return self._bsr

    def _get_dia(self):
        """Cached banded (DIA) structure, or None.

        On TPU, HBM gathers run orders of magnitude below roofline while
        shifted-add streams hit it (measured this chip: ELL gather 1.1
        GB/s vs DIA 38 GB/s at matched size).  When the matrix is
        banded — few distinct ``col - row`` diagonals within the
        expansion budget — SpMV runs gather-free.  Returns
        ``(dia_data, offsets, mask)`` where ``mask`` is None for an
        *exact* band (every in-bounds slot is an explicit nonzero:
        bit-identical semantics for free) or an explicit-entry mask for
        a *holey* band (e.g. ``diags().tocsr()`` dropped zeros), so a
        hole never multiplies x — IEEE behavior against non-finite x
        matches CSR exactly in both cases.  The reference always pays
        the CSR gather (``dia.py:152-190`` converts DIA→CSR before any
        matvec); keeping the band structure is a deliberate TPU-first
        improvement, and it covers every headline benchmark config
        (banded SpMV sweep, 5-pt Poisson PDE, GMG fine grids).
        """
        if self._dia is not None:
            return self._dia if self._dia is not False else None
        if not self._can_build_cache(self._data, self._indices,
                                     self._indptr):
            return None
        from .settings import settings

        rows, cols = self.shape
        nnz = self.nnz
        if (settings.dia_max_expand <= 0 or not nnz or not rows
                or not self.has_canonical_format):
            self._dia = False
            return None
        if self._dia_offsets is None:
            max_nd = int(min(
                settings.dia_max_diags,
                settings.dia_max_expand * nnz / max(cols, 1),
            ))
            offsets = (
                _dia_ops.csr_band_offsets(
                    self._indices, self._get_row_ids(), max_nd
                )
                if max_nd >= 1
                else None
            )
            self._dia_offsets = offsets if offsets is not None else False
        if self._dia_offsets is False:
            self._dia = False
            return None
        offsets = self._dia_offsets
        # Exact band (every in-bounds slot explicit): no mask needed.
        exact = _dia_ops.band_cover(offsets, self.shape, cols) == nnz
        # Compressed-value storage (``compress()``) declares the hole
        # trade: ``dia_from_csr`` zero-fills, so hole products are
        # exact zeros for finite x and the mask stream — 1 byte/slot,
        # a full quarter of a bf16 band's traffic — is dropped.  The
        # cost is that a non-finite x entry at a hole propagates
        # (0*inf) where canonical storage masks it; values are already
        # rounded, and the compress() docstring documents both.
        if str(self.dtype) in ("bfloat16", "float16"):
            exact = True
        if exact:
            dia_data = _dia_ops.dia_from_csr(
                self._data, self._indices, self._get_row_ids(),
                offsets, cols,
            )
            self._dia = (dia_data, offsets, None)
        else:
            dia_data, mask = _dia_ops.dia_from_csr(
                self._data, self._indices, self._get_row_ids(),
                offsets, cols, with_mask=True,
            )
            self._dia = (dia_data, offsets, mask)
        return self._dia

    def _get_dia_pack(self):
        """Cached row-aligned band pack for the Pallas DIA kernel
        (``ops/pallas_dia.py``), or None when the matrix isn't banded
        or the kernel doesn't support it (f64, band reach > tile cap).
        Built once per structure, on top of ``_get_dia()``."""
        if self._dia_pack is not None:
            return self._dia_pack if self._dia_pack is not False else None
        dia = self._get_dia()
        if dia is None or not self._can_build_cache(
            self._data, self._indices, self._indptr
        ):
            if dia is None:
                self._dia_pack = False
            return None
        from .ops import pallas_dia as _pallas_dia

        dia_data, offsets, mask = dia
        packed = _pallas_dia.pack_band(dia_data, offsets, self.shape,
                                       mask=mask)
        self._dia_pack = packed if packed is not None else False
        return packed

    def _get_dia_fused(self):
        """Cached padded band layout for the fused XLA SpMV
        (``ops/dia_ops.py::dia_spmv_fused``), or None when not banded.
        One extra band-sized buffer, built once per structure; pays for
        itself on the first few matvecs (the fused form runs in one
        pass where the ``at[].add`` chain runs num_diags passes)."""
        if self._dia_fused is not None:
            return self._dia_fused if self._dia_fused is not False else None
        dia = self._get_dia()
        if dia is None:
            self._dia_fused = False
            return None
        dia_data, offsets, mask = dia
        fused = _dia_ops.pad_dia(dia_data, offsets, self.shape,
                                 mask=mask, with_mask=mask is not None)
        if self._can_build_cache(self._data, self._indices,
                                 self._indptr):
            self._dia_fused = fused      # else: inside a trace, no cache
        return fused

    def _get_row_ids(self):
        """Cached per-nnz row ids, or a non-cached computation when a
        cache can't be built (inside a trace / tracer structure)."""
        if self._row_ids is not None:
            return self._row_ids
        if not self._can_build_cache(self._indptr):
            return _convert.row_ids_from_indptr(self._indptr, self.nnz)
        self._row_ids = _convert.row_ids_from_indptr(self._indptr, self.nnz)
        return self._row_ids

    def _get_fingerprint(self):
        """Cached sparsity fingerprint (``autotune.Fingerprint``), or
        None when it can't be built now (tracer structure / ambient
        trace — fingerprints feed verdict keys, which only concrete
        dispatches consult)."""
        if self._fingerprint is not None:
            return self._fingerprint
        if not self._can_build_cache(self._data, self._indices,
                                     self._indptr):
            return None
        from .autotune import compute_fingerprint

        self._fingerprint = compute_fingerprint(self)
        return self._fingerprint

    def _get_sliced_ell(self):
        """Cached row-binned ("sliced") ELL pack, or None (empty /
        oversized / can't build under an active trace).  Unlike flat
        ELL there is no expansion budget: pow2 row bins bound padding
        below 2x nnz regardless of row-length skew."""
        if self._sliced_ell is not None:
            return self._sliced_ell if self._sliced_ell is not False else None
        if not self._can_build_cache(self._data, self._indices,
                                     self._indptr):
            return None
        rows = self.shape[0]
        if rows == 0 or self.nnz == 0 or rows > np.iinfo(np.int32).max:
            self._sliced_ell = False
            return None
        self._sliced_ell = _spmv_ops.sliced_ell_pack(
            self._data, self._indices, self._indptr, rows
        )
        if self._sliced_ell is None:
            self._sliced_ell = False
            return None
        return self._sliced_ell

    # ---------------- conversions ----------------
    def todense(self, order=None, out=None):
        if order is not None:
            raise NotImplementedError("order parameter is not supported")
        result = _convert.csr_to_dense(
            self._data, self._indices, self._indptr, self.shape
        )
        return fill_out(result, out)

    toarray = todense

    def tocsr(self, copy: bool = False):
        return self.copy() if copy else self

    def _coo_parts(self):
        """(row, col, data) coordinate view as jax arrays (internal —
        the public ``tocoo`` returns a ``coo_array`` like scipy)."""
        row_ids = _convert.row_ids_from_indptr(self._indptr, self.nnz)
        return row_ids.astype(self._indices.dtype), self._indices, self._data

    def tocoo(self, copy: bool = False):
        """COO-format view (scipy ``tocoo`` semantics)."""
        from .coo import coo_array

        return coo_array(self)

    def toscipy(self):
        """Interop: materialize as a scipy.sparse.csr_array on host."""
        return _scipy_sparse.csr_array(
            (
                np.asarray(self._data),
                np.asarray(self._indices),
                np.asarray(self._indptr),
            ),
            shape=self.shape,
        )

    def todia(self, copy: bool = False):
        """Convert to ``dia_array`` (scipy ``.todia()`` semantics: every
        distinct ``col - row`` becomes a stored diagonal).  Reuses the
        banded-structure machinery behind the SpMV fast path."""
        from .dia import dia_array

        a = self._canonicalized()
        rows, cols = self.shape
        if a.nnz == 0:
            # scipy parity: empty DIA (no stored diagonals).
            return dia_array(
                (jnp.zeros((0, 0), dtype=self.dtype),
                 jnp.zeros((0,), dtype=index_dtype())),
                shape=self.shape,
            )
        offsets = _dia_ops.csr_band_offsets(
            a._indices, a._get_row_ids(), max(rows + cols, 1)
        )
        dia_data = _dia_ops.dia_from_csr(
            a._data, a._indices, a._get_row_ids(), offsets, cols
        )
        return dia_array(
            (dia_data, jnp.asarray(offsets, dtype=index_dtype())),
            shape=self.shape,
        )

    def asformat(self, format, copy: bool = False):
        """Return this matrix in the given format, scipy ``asformat``
        semantics ('csr', 'csc', 'coo', 'dia')."""
        if format is None or format == "csr":
            return self.tocsr(copy=copy)
        if format == "dia":
            return self.todia(copy=copy)
        if format == "csc":
            return self.tocsc(copy=copy)
        if format == "coo":
            from .coo import coo_array

            return coo_array(self)
        raise ValueError(f"unsupported format: {format!r}")

    def tocsc(self, copy: bool = False):
        from .csc import csc_array

        return csc_array(self)

    # ---------------- structure maintenance ----------------
    def getnnz(self, axis=None):
        """nnz total, or per-row / per-column counts (scipy semantics)."""
        if axis is None:
            return self.nnz
        if axis in (1, -1):
            return jnp.diff(self._indptr)
        if axis == 0:
            return (
                jnp.zeros((self.shape[1],), dtype=nnz_dtype())
                .at[self._indices]
                .add(1)
            )
        raise ValueError(f"invalid axis: {axis}")

    def eliminate_zeros(self):
        """Drop explicit zero entries in place (scipy semantics; one
        host sync for the new nnz — the XLA static-shape analog of the
        reference's blocking ``int(nnz)``)."""
        mask = self._data != 0
        new_nnz = int(jnp.sum(mask))
        if new_nnz == self.nnz:
            return
        keep = jnp.nonzero(mask, size=new_nnz)[0]
        row_ids = _convert.row_ids_from_indptr(self._indptr, self.nnz)
        new_rows = row_ids[keep]
        self._data = self._data[keep]
        self._indices = self._indices[keep]
        self._indptr = _convert.indptr_from_row_ids(
            new_rows, self.shape[0]
        )
        self._row_ids = None
        self._ell = None
        self._ell_width = None
        self._dia = None
        self._dia_offsets = None
        self._dia_pack = None
        self._dia_fused = None
        self._bsr = None
        self._engine_pack = None
        self._fingerprint = None
        self._sliced_ell = None

    def sort_indices(self):
        """Sort column indices within each row in place (stable; no
        duplicate merging — scipy ``sort_indices`` semantics)."""
        if self.has_sorted_indices:
            return
        row_ids = _convert.row_ids_from_indptr(self._indptr, self.nnz)
        _, indices, data = jax.lax.sort(
            [row_ids, self._indices, self._data], num_keys=2,
            is_stable=True,
        )
        self._data = data
        self._indices = indices
        self._canonical = None
        self._sorted = True
        self._row_ids = None
        self._ell = None
        self._dia = None
        self._dia_offsets = None
        self._dia_pack = None
        self._dia_fused = None
        self._bsr = None
        self._engine_pack = None
        self._fingerprint = None  # block_score reads stored-entry order
        self._sliced_ell = None

    def power(self, n, dtype=None):
        """Element-wise power (scipy semantics: duplicates are summed
        first — scipy applies ``_deduped_data()`` — then each stored
        entry is raised)."""
        a = self._canonicalized()
        data = a._data
        if dtype is not None:
            data = data.astype(dtype)
        return a._with_data(data**n)

    # ---------------- element/structure ops ----------------
    def diagonal(self, k: int = 0):
        rows, cols = self.shape
        if k != 0:
            # Improvement over the reference (k=0 only, ``csr.py:345-368``):
            # any diagonal; length follows scipy convention.
            length = max(0, min(rows + min(k, 0), cols - max(k, 0)))
            full = _convert.csr_diagonal(
                self._data, self._indices, self._indptr, rows, k
            )
            start = -min(k, 0)
            return full[start : start + length]
        return _convert.csr_diagonal(
            self._data, self._indices, self._indptr, rows, 0
        )[: min(rows, cols)]

    def transpose(self, axes=None, copy: bool = False):
        if axes is not None:
            raise ValueError(
                "Sparse matrices do not support an 'axes' parameter"
            )
        rows, cols = self.shape
        data, indices, indptr = _convert.csr_transpose(
            self._data, self._indices, self._indptr, rows, cols
        )
        # Transpose of a canonical matrix is canonical; duplicates survive
        # transposition otherwise.  type(self) keeps the spmatrix flavor.
        return type(self)._from_parts(
            data, indices, indptr, (cols, rows), canonical=self._canonical
        )

    def conj(self, copy: bool = True):
        if np.issubdtype(self.dtype, np.complexfloating):
            return self._with_data(jnp.conj(self._data), copy=copy)
        return self.copy() if copy else self

    conjugate = conj

    def copy(self):
        return type(self)(self, copy=True)

    def trace(self, offset: int = 0):
        """Sum along diagonal ``offset`` (scipy ``trace``)."""
        return jnp.sum(self.diagonal(offset))

    def count_nonzero(self, axis=None):
        """Number of entries whose value is nonzero after duplicate
        merging (scipy semantics: explicit/cancelled zeros are not
        counted)."""
        a = self._canonicalized()
        nz = (a._data != 0)
        if axis is None:
            return int(jnp.sum(nz))
        if axis not in (0, 1, -1, -2):
            raise ValueError(f"invalid axis {axis}")
        axis = int(axis) % 2
        if axis == 0:
            counts = jnp.zeros(
                (a.shape[1],), jnp.int32
            ).at[a._indices].add(nz.astype(jnp.int32))
            return np.asarray(counts)
        row_ids = _convert.row_ids_from_indptr(a._indptr, a.nnz)
        return np.asarray(jax.ops.segment_sum(
            nz.astype(jnp.int32), row_ids, num_segments=a.shape[0],
            indices_are_sorted=True,
        ))

    def _minmax_binary(self, other, op):
        """Element-wise maximum/minimum vs a scalar or sparse operand
        over the union structure, implicit zeros included (scipy
        ``maximum``/``minimum`` semantics)."""
        if np.isscalar(other) or getattr(other, "ndim", None) == 0:
            # scipy materializes a dense result only for scalars that
            # beat the implicit zeros; match its sparse-where-possible
            # contract: op(v, s) at stored slots, op(0, s) elsewhere.
            # (Computed with the jnp op so complex scalars follow
            # numpy's ordering rather than crashing on float().)
            zero = jnp.zeros((), jnp.result_type(self.dtype, other))
            fill = op(zero, other)
            if bool(fill != 0):
                import warnings as _w

                _w.warn(
                    "Taking maximum/minimum with a scalar that is "
                    "nonzero against the zero fill produces a dense "
                    "result", SparseEfficiencyWarning, stacklevel=3,
                )
                dense = op(self.toarray(), other)
                return csr_array(np.asarray(dense))
            a = self._canonicalized()   # op distributes over values,
            return a._with_data(op(a._data, other))  # not duplicates
        if _is_scipy_sparse(other):
            other = csr_array(other)
        if not isinstance(other, csr_array):
            other = csr_array(jnp.asarray(other))
        if other.shape != self.shape:
            raise ValueError("inconsistent shapes")
        a, b = cast_to_common_type(self._canonicalized(),
                                   other._canonicalized())
        rows, cols = a.shape
        ra, ca, va = a._coo_parts()
        rb, cb, vb = b._coo_parts()
        # Union structure: where a key appears on one side only, the
        # other side contributes its implicit zero.
        row = jnp.concatenate([ra, rb])
        col = jnp.concatenate([ca, cb])
        try:
            # Union keys are row*cols+col: the key space is rows*cols,
            # not max(shape) — coord_dtype_for raises under no-x64.
            key_dt = coord_dtype_for(rows * cols)
        except OverflowError as e:
            raise OverflowError(
                f"maximum/minimum union keys: {e}"
            ) from None
        key = row.astype(key_dt) * cols + col.astype(key_dt)
        val = jnp.concatenate([va, vb])
        order = jnp.argsort(key, stable=True)
        key = key[order]
        val = val[order]
        nxt = jnp.concatenate([key[1:], jnp.full((1,), -1, key.dtype)])
        prv = jnp.concatenate([jnp.full((1,), -1, key.dtype), key[:-1]])
        paired = jnp.logical_or(key == nxt, key == prv)
        pair_val = jnp.where(
            key == nxt, op(val, jnp.roll(val, -1)), jnp.zeros_like(val)
        )
        single_val = op(val, jnp.zeros_like(val))
        out_val = jnp.where(
            paired,
            jnp.where(key == nxt, pair_val, jnp.zeros_like(val)),
            single_val,
        )
        out = csr_array(
            (out_val, (row[order], col[order])), shape=self.shape
        )
        out.sum_duplicates()   # merges the zeroed pair slot
        out.eliminate_zeros()
        return out

    def maximum(self, other):
        return self._minmax_binary(other, jnp.maximum)

    def minimum(self, other):
        return self._minmax_binary(other, jnp.minimum)

    def argmax(self, axis=None, out=None):
        """Index of the maximum element, implicit zeros included (host
        delegation — exact scipy tie-breaking; not a hot op)."""
        return self.toscipy().argmax(axis=axis, out=out)

    def argmin(self, axis=None, out=None):
        return self.toscipy().argmin(axis=axis, out=out)

    def reshape(self, *shape, order="C"):
        """Reshape preserving entry count (host structural op).  Only
        2-D targets: this package has no 1-D sparse type (scipy's
        sparray returns 1-D for a single-int shape)."""
        if len(shape) == 1:
            if isinstance(shape[0], (int, np.integer)):
                raise ValueError(
                    "1-D reshape targets are not supported (no 1-D "
                    "sparse type); pass a 2-D shape"
                )
            shape = tuple(shape[0])
        if len(shape) != 2:
            raise ValueError(f"expected a 2-D shape, got {shape}")
        return csr_array(
            self.toscipy().reshape(shape, order=order).tocsr()
        )

    def resize(self, *shape):
        """In-place resize: entries outside the new shape are dropped
        (scipy ``resize``)."""
        if len(shape) == 1:
            shape = tuple(shape[0])
        nr, nc = (int(shape[0]), int(shape[1]))
        r, c, v = self._coo_parts()
        keep = jnp.logical_and(r < nr, c < nc)
        nnz_new = int(jnp.sum(keep))
        r2, c2, v2 = _convert.compact_mask(keep, (r, c, v), nnz_new)
        new = csr_array((v2, (r2, c2)), shape=(nr, nc))
        self._data = new._data
        self._indices = new._indices
        self._indptr = new._indptr
        self.shape = (nr, nc)
        self._invalidate_caches(structure_changed=True)

    def todok(self, copy: bool = False):
        """Host conversion (no native DOK type — scipy's is returned)."""
        return self.toscipy().todok(copy=copy)

    def tolil(self, copy: bool = False):
        """Host conversion (no native LIL type — scipy's is returned)."""
        return self.toscipy().tolil(copy=copy)

    # ---------------- arithmetic ----------------
    def multiply(self, other):
        """Element-wise product with a scalar, dense array/vector, or any
        sparse operand (pattern intersection)."""
        if np.isscalar(other) or getattr(other, "ndim", None) == 0:
            return self._with_data(self._data * other)
        if _is_scipy_sparse(other):
            other = csr_array(other)
        elif not isinstance(other, csr_array) and _is_sparse_like(other):
            other = other.tocsr()   # csc/coo/dia operand
        if isinstance(other, csr_array):
            if other.shape != self.shape:
                raise ValueError("inconsistent shapes for multiply")
            a, b = cast_to_common_type(
                self._canonicalized(), other._canonicalized()
            )
            return _elementwise_intersect_multiply(a, b)
        other = jnp.asarray(other)
        if other.ndim == 2 and other.shape == self.shape:
            row_ids = _convert.row_ids_from_indptr(self._indptr, self.nnz)
            return self._with_data(self._data * other[row_ids, self._indices])
        if other.ndim == 1 and other.shape[0] == self.shape[1]:
            return self._with_data(self._data * other[self._indices])
        # scipy broadcasting: a (1, n) row or (m, 1) column vector
        # scales columns / rows without densifying.
        if other.ndim == 2 and other.shape == (1, self.shape[1]):
            return self._with_data(self._data * other[0, self._indices])
        if other.ndim == 2 and other.shape == (self.shape[0], 1):
            row_ids = _convert.row_ids_from_indptr(self._indptr, self.nnz)
            return self._with_data(self._data * other[row_ids, 0])
        raise ValueError(f"inconsistent shapes for multiply: {other.shape}")

    def __mul__(self, other):
        if np.isscalar(other) or getattr(other, "ndim", None) == 0:
            return self._with_data(self._data * other)
        # sparray semantics: ``*`` is element-wise (scipy's csr_array;
        # the spmatrix subclass below overrides to matmul).
        return self.multiply(other)

    def __rmul__(self, other):
        return self.__mul__(other)

    def __truediv__(self, other):
        if np.isscalar(other) or getattr(other, "ndim", None) == 0:
            return self._with_data(self._data / other)
        if _is_scipy_sparse(other) or _is_sparse_like(other):
            if tuple(other.shape) != self.shape:
                raise ValueError(
                    f"inconsistent shapes {self.shape} and "
                    f"{tuple(other.shape)}"
                )
            # scipy: sparse / sparse densifies (0/0 -> nan included).
            other = other.toarray() if hasattr(other, "toarray") else other
            return jnp.asarray(self.toarray()) / jnp.asarray(other)
        # Dense divisor: division applies at stored entries only
        # (implicit zeros stay zero — scipy returns sparse here too).
        # Row/column-vector divisors broadcast like scipy.
        recip = 1.0 / jnp.asarray(other)
        if recip.ndim == 2 and recip.shape != self.shape:
            recip = jnp.broadcast_to(recip, self.shape)
        return self.multiply(recip)

    def __neg__(self):
        return self._with_data(-self._data)

    def __abs__(self):
        return self._with_data(jnp.abs(self._data))

    def __pow__(self, n):
        if np.isscalar(n) and n == 0:
            raise NotImplementedError(
                "zero power is not supported as it would densify the "
                "matrix; use np.ones(A.shape, dtype=A.dtype)"
            )
        return self.power(n)

    # -- element-wise comparisons (scipy semantics: a bool sparse array
    #    storing the True positions).  Whether the True set is
    #    dense-shaped depends on the implicit-zero pair: op(0, fill) —
    #    those cases warn (like scipy) and materialize; the
    #    sparse-shaped cases stay sparse end to end. --
    def _compare(self, other, op):
        cls = type(self)
        scalar = np.isscalar(other) or getattr(other, "ndim", None) == 0
        sparse_other = _is_scipy_sparse(other) or _is_sparse_like(other)
        if sparse_other and tuple(other.shape) != self.shape:
            raise ValueError("inconsistent shapes")
        if not scalar and not sparse_other:
            # Dense operand: scipy returns a dense bool ndarray.
            return np.asarray(
                op(np.asarray(self.toarray()), np.asarray(other))
            )
        # Implicit-zero pair (full scalar value — complex included).
        fill_true = bool(np.asarray(op(0, other if scalar else 0)))
        if fill_true:
            warnings.warn(
                "Comparing a sparse array using a comparison that is "
                "True for implicit zeros is inefficient "
                "(dense-shaped result)",
                SparseEfficiencyWarning, stacklevel=3,
            )
        if scalar:
            if fill_true:
                res = op(np.asarray(self.toarray()), other)
                return cls(np.asarray(res))
            a = self._canonicalized()
            out = cls(a._with_data(op(a._data, other)))
            out.eliminate_zeros()
            return out
        if fill_true:
            res = op(np.asarray(self.toarray()),
                     np.asarray(other.toarray()))
            return cls(np.asarray(res))
        return self._compare_sparse_union(other, op)

    def _compare_sparse_union(self, other, op):
        """op over the union structure of two sparse operands (used for
        the sparse-result comparisons: no dense materialization).  Two-
        key sort — no fused integer key, safe for any rows*cols under
        x64-off (same pattern as ``_elementwise_intersect_multiply``)."""
        if not isinstance(other, csr_array):
            other = csr_array(other) if _is_scipy_sparse(other) \
                else other.tocsr()
        a, b = (self._canonicalized(), other._canonicalized())
        rows, cols = a.shape
        ra, ca, va = a._coo_parts()
        rb, cb, vb = b._coo_parts()
        row = jnp.concatenate([ra, rb])
        col = jnp.concatenate([ca, cb])
        cha = jnp.concatenate([va, jnp.zeros_like(vb)])
        chb = jnp.concatenate([jnp.zeros_like(va), vb])
        row, col, cha, chb = jax.lax.sort(
            [row, col, cha, chb], num_keys=2, is_stable=True
        )
        same_next = jnp.concatenate([
            jnp.logical_and(row[1:] == row[:-1], col[1:] == col[:-1]),
            jnp.zeros((1,), bool),
        ])
        same_prev = jnp.concatenate([
            jnp.zeros((1,), bool),
            jnp.logical_and(row[1:] == row[:-1], col[1:] == col[:-1]),
        ])
        first = jnp.logical_not(same_prev)
        # Merge pair channels onto the first slot of each key group.
        va_m = cha + jnp.where(same_next, jnp.roll(cha, -1), 0)
        vb_m = chb + jnp.where(same_next, jnp.roll(chb, -1), 0)
        res = jnp.logical_and(first, op(va_m, vb_m))
        out = type(self)((res, (row, col)), shape=self.shape)
        out.eliminate_zeros()
        return out

    def __eq__(self, other):
        return self._compare(other, jnp.equal)

    def __ne__(self, other):
        return self._compare(other, jnp.not_equal)

    def __lt__(self, other):
        return self._compare(other, jnp.less)

    def __gt__(self, other):
        return self._compare(other, jnp.greater)

    def __le__(self, other):
        return self._compare(other, jnp.less_equal)

    def __ge__(self, other):
        return self._compare(other, jnp.greater_equal)

    # Defining __eq__ clears the default hash; sparse arrays are
    # mutable and unhashable, same as scipy's.
    __hash__ = None

    def nonzero(self):
        """(row, col) of nonzero entries (scipy ``nonzero``)."""
        from .gallery import find as _find

        r, c, _v = _find(self)
        return r, c

    def _add_sub(self, other, sign):
        if not isinstance(other, csr_array):
            if np.isscalar(other) and other == 0:
                return self.copy()   # sum()/accumulate start at 0
            if _is_scipy_sparse(other):
                other = csr_array(other)
            elif _is_sparse_like(other):
                other = other.tocsr()   # csc/coo/dia operand
            else:
                raise NotImplementedError(
                    "sparse +/- dense is not supported; densify explicitly"
                )
        if other.shape != self.shape:
            raise ValueError("inconsistent shapes")
        a, b = cast_to_common_type(self, other)
        rows, cols = self.shape
        ra, ca, va = a._coo_parts()
        rb, cb, vb = b._coo_parts()
        row = jnp.concatenate([ra, rb])
        col = jnp.concatenate([ca, cb])
        val = jnp.concatenate([va, sign * vb])
        # Merge duplicates through the shared coalesce machinery.
        data, indices, indptr = _spgemm_ops.coalesce_coo(row, col, val, rows)
        return type(self)._from_parts(data, indices, indptr, self.shape)

    def __add__(self, other):
        return self._add_sub(other, 1)

    def __sub__(self, other):
        return self._add_sub(other, -1)

    # ---------------- matmul ----------------
    def __rmatmul__(self, other):
        raise NotImplementedError("dense @ csr is not yet supported")

    def __matmul__(self, other):
        return self.dot(other)

    def dot(self, other, out=None):
        """SpMV / SpMM / SpGEMM dispatch (reference ``csr.py:419-493``).

        With resilience on (``LEGATE_SPARSE_TPU_RESIL``,
        docs/RESILIENCE.md) the dispatch runs under the ``csr.dot``
        site policy: injectable via ``resilience.faults``, transient
        failures retried with deterministic backoff, K consecutive
        failures tripping the site breaker (typed fast-fail while
        open).  Off — the default — this is one flag read."""
        if _rsettings.resil and self._can_build_cache(self._data):
            # Eager contexts only: inside an ambient jax trace the
            # wrapper must vanish (a retry there would re-stage the
            # traced program, and injection is trace-suppressed
            # anyway).
            def attempt():
                # The fault hook wraps the VALUE so an armed
                # ``nonfinite`` fault can poison the product; error/
                # latency kinds fire before results are returned.
                return _rfaults.fault_point(
                    "csr.dot", self._dot_impl(other, out=out))

            return _rpolicy.run("csr.dot", attempt)
        return self._dot_impl(other, out=out)

    def _dot_impl(self, other, out=None):
        require_supported_dtype(self.dtype)
        if _is_scipy_sparse(other):
            other = csr_array(other)  # adopt scipy operand for SpGEMM
        elif not isinstance(other, csr_array) and _is_sparse_like(other):
            other = other.tocsr()  # csc/dia operand -> CSR SpGEMM
        if isinstance(other, csr_array):
            if out is not None:
                raise ValueError("out not supported for sparse-sparse matmul")
            return spgemm_csr_csr_csr(*cast_to_common_type(self, other))
        other_arr = jnp.asarray(other)
        squeeze = False
        if other_arr.ndim == 2 and other_arr.shape[1] == 1:
            # (N, 1) treated as a vector (reference ``csr.py:433-452``).
            other_arr = other_arr.reshape(-1)
            squeeze = True
        if other_arr.ndim == 1:
            if other_arr.shape[0] != self.shape[1]:
                raise ValueError(
                    f"dimension mismatch: {self.shape} @ {other_arr.shape}"
                )
            _obs.inc("op.spmv")
            # Low-precision-storage widening (bf16/f16 matrix, f32
            # operand): keep the compressed operand — the generic cast
            # below would materialize an f32 copy of the value stream,
            # undoing the whole byte win — and dispatch the
            # f32-accumulation kernels, whose output is
            # result_type(A, x) exactly as promotion demands.
            lowp = (str(self.dtype) in ("bfloat16", "float16")
                    and np.result_type(self.dtype, other_arr.dtype)
                    == np.float32
                    and other_arr.dtype != self.dtype)
            if lowp:
                A, x = self, other_arr
                src = self
            else:
                A, x = cast_to_common_type(self, other_arr)
                src = self if A is self else None
            # Always-on dispatch-latency histogram, keyed by the pow2
            # shape bucket (obs/latency.py): the distribution the
            # autotuner/serving arc consult — spans only exist while
            # tracing is enabled.
            with _lat.timer("lat.spmv."
                            + _lat.shape_bucket(self.shape[0])), \
                    _obs.span("spmv") as sp:
                if src is not None:
                    # Engine route (settings.engine): bucketed plan
                    # dispatch with zero retraces under n/nnz drift.
                    # Declines (off, tracer context, structure fast
                    # path) fall through to the normal chain.
                    y = _engine_route_matvec(src, x)
                    if y is not None:
                        if sp is not None:
                            # Traffic model: the engine kernel is the
                            # CSR gather path over padded operands.
                            sp.set(path="engine", rows=self.shape[0],
                                   nnz=self.nnz, flops=2 * self.nnz,
                                   bytes=A.spmv_traffic_bytes(
                                       x, path="csr"))
                        if squeeze:
                            y = y[:, None]
                        return fill_out(y, out)
                if src is not None:
                    # Autotune route (settings.autotune): a stored
                    # measured verdict picks the kernel.  Declines
                    # (off — the default, tracer context, dtype
                    # promotion, DIA/BSR structure, verdict miss)
                    # fall through to the heuristic chain below.
                    routed = _autotune_route_matvec(src, x)
                    if routed is not None:
                        y, path = routed
                        if sp is not None:
                            sp.set(path=path, rows=self.shape[0],
                                   nnz=self.nnz, flops=2 * self.nnz,
                                   bytes=A.spmv_traffic_bytes(
                                       x, path=path))
                        if squeeze:
                            y = y[:, None]
                        return fill_out(y, out)
                # Under the declared widening DIA keeps serving: its
                # XLA lowerings are shifted multiply-adds whose bf16 x
                # f32 products promote to f32 before the reduction —
                # f32-grade accumulation for free, band bytes halved.
                # BSR stands down (the Mosaic kernel is compiled
                # same-dtype); the gather-class f32acc kernels cover
                # the rest.
                dia = src._get_dia() if src is not None else None
                bsr = (src._get_bsr()
                       if src is not None and not lowp and dia is None
                       else None)
                ell = (src._get_ell()
                       if src is not None and dia is None and bsr is None
                       else None)
                if dia is not None:
                    from .ops.pallas_dia import (
                        dia_spmv_maybe_pallas, pallas_dia_active,
                    )

                    y = (dia_spmv_maybe_pallas(src._get_dia_pack(), x)
                         if pallas_dia_active() and not lowp else None)
                    path = "dia-pallas"
                    if y is None:
                        offs = dia[1]
                        if _dia_xla_nopad():
                            y = _dia_ops.dia_spmv_nopad(
                                dia[0], dia[2], x, offs, self.shape)
                            path = "dia-xla-nopad"
                        else:
                            dpad, mpad = src._get_dia_fused()
                            y = _dia_ops.dia_spmv_fused(
                                dpad, mpad, x, offs, self.shape)
                            path = "dia-xla"
                elif bsr is not None:
                    y = bsr.matvec(
                        x, interpret=jax.devices()[0].platform != "tpu"
                    )
                    path = "bsr"
                elif ell is not None and lowp:
                    y = _spmv_ops.ell_spmv_f32acc(
                        ell[0], ell[1], ell[2], x)
                    path = "ell-bf16"
                elif ell is not None:
                    y = _spmv_ops.ell_spmv(ell[0], ell[1], ell[2], x)
                    path = "ell"
                elif src is not None and lowp:
                    y = _spmv_ops.csr_spmv_rowids_f32acc(
                        A.data, A.indices, src._get_row_ids(), x,
                        self.shape[0]
                    )
                    path = "csr-rowids-bf16"
                elif src is not None:
                    y = _spmv_ops.csr_spmv_rowids(
                        A.data, A.indices, src._get_row_ids(), x,
                        self.shape[0]
                    )
                    path = "csr-rowids"
                else:
                    y = _spmv_ops.csr_spmv(
                        A.data, A.indices, A.indptr, x, self.shape[0]
                    )
                    path = "csr"
                if sp is not None:
                    sp.set(path=path, rows=self.shape[0], nnz=self.nnz,
                           bytes=A.spmv_traffic_bytes(x, path=path),
                           flops=2 * self.nnz)
            if squeeze:
                y = y[:, None]
            return fill_out(y, out)
        if other_arr.ndim == 2:
            if other_arr.shape[0] != self.shape[1]:
                raise ValueError(
                    f"dimension mismatch: {self.shape} @ {other_arr.shape}"
                )
            _obs.inc("op.spmm")
            # Same declared widening as the SpMV branch: compressed
            # storage stays compressed, f32 accumulation serves.
            lowp = (str(self.dtype) in ("bfloat16", "float16")
                    and np.result_type(self.dtype, other_arr.dtype)
                    == np.float32
                    and other_arr.dtype != self.dtype)
            if lowp:
                A, X = self, other_arr
                src = self
            else:
                A, X = cast_to_common_type(self, other_arr)
                src = self if A is self else None
            with _lat.timer("lat.spmm."
                            + _lat.shape_bucket(self.shape[0])), \
                    _obs.span("spmm") as sp:
                if src is not None:
                    Y = _engine_route_matmat(src, X)
                    if Y is not None:
                        if sp is not None:
                            k = int(X.shape[1])
                            sp.set(path="engine", rows=self.shape[0],
                                   k=k, nnz=self.nnz,
                                   flops=2 * self.nnz * k,
                                   bytes=A.spmv_traffic_bytes(
                                       X, path="csr"))
                        return fill_out(Y, out)
                if src is not None:
                    routed = _autotune_route_matmat(src, X)
                    if routed is not None:
                        Y, path = routed
                        if sp is not None:
                            k = int(X.shape[1])
                            sp.set(path=path, rows=self.shape[0],
                                   k=k, nnz=self.nnz,
                                   flops=2 * self.nnz * k,
                                   bytes=A.spmv_traffic_bytes(
                                       X, path=path))
                        return fill_out(Y, out)
                # DIA serves under the widening (same promotion logic
                # as the SpMV branch); BSR/flat-ELL stand down — no
                # f32-accumulation spmm variants for those families.
                dia = src._get_dia() if src is not None else None
                from .ops.bsr import SPMM_MAX_K as _BSR_MAX_K

                bsr = (src._get_bsr()
                       if src is not None and not lowp and dia is None
                       and 0 < X.shape[1] <= _BSR_MAX_K
                       else None)
                ell = (src._get_ell()
                       if src is not None and not lowp
                       and dia is None and bsr is None
                       else None)
                if dia is not None:
                    from .ops.pallas_dia import (
                        SPMM_MAX_K, dia_spmm_maybe_pallas,
                        pallas_dia_active,
                    )

                    # Cheap k gate first: the pack build doubles band
                    # storage and must not run for calls that can only
                    # take the XLA path anyway.
                    Y = (
                        dia_spmm_maybe_pallas(src._get_dia_pack(), X)
                        if 0 < X.shape[1] <= SPMM_MAX_K
                        and pallas_dia_active() and not lowp
                        else None
                    )
                    path = "dia-pallas"
                    if Y is None:
                        offs = dia[1]
                        dpad, mpad = src._get_dia_fused()
                        Y = _dia_ops.dia_spmm_fused(dpad, mpad, X, offs,
                                                    self.shape)
                        path = "dia-xla"
                elif bsr is not None:
                    Y = bsr.matmat(
                        X, interpret=jax.devices()[0].platform != "tpu"
                    )
                    path = "bsr"
                elif ell is not None:
                    Y = _spmv_ops.ell_spmm(ell[0], ell[1], ell[2], X)
                    path = "ell"
                elif src is not None and lowp:
                    Y = _spmv_ops.csr_spmm_rowids_f32acc(
                        A.data, A.indices, src._get_row_ids(), X,
                        self.shape[0]
                    )
                    path = "csr-rowids-bf16"
                elif src is not None:
                    Y = _spmv_ops.csr_spmm_rowids(
                        A.data, A.indices, src._get_row_ids(), X,
                        self.shape[0]
                    )
                    path = "csr-rowids"
                else:
                    Y = _spmv_ops.csr_spmm(
                        A.data, A.indices, A.indptr, X, self.shape[0]
                    )
                    path = "csr"
                if sp is not None:
                    k = int(X.shape[1])
                    sp.set(path=path, rows=self.shape[0], k=k,
                           nnz=self.nnz, flops=2 * self.nnz * k,
                           bytes=A.spmv_traffic_bytes(X, path=path))
            return fill_out(Y, out)
        raise ValueError(f"cannot multiply csr_array by ndim={other_arr.ndim}")

    def spmv_traffic_bytes(self, x, path: str = None) -> int:
        """Useful-traffic byte model of one ``A @ x`` through the
        kernel named by ``path`` (the dispatch labels: dia-*, bsr,
        ell, csr*) — or, with ``path=None``, whatever kernel the
        structure caches say the dispatch WOULD pick (bench.py's
        usage).  Lower bound: x counted once even where a kernel
        re-reads neighbor windows.  Reads the already-built structure
        caches only — call after the op (``bench.py`` and the obs
        spans both do); an uncached matrix falls through to the CSR
        gather model.
        """
        n = self.shape[0]
        if path in ("csr-rowids-bf16", "ell-bf16", "sliced-ell-bf16"):
            # The f32-accumulation variants stream the same blocks as
            # their full-precision families — the models below read
            # the actual storage itemsizes, so the narrowing is
            # already priced.
            path = path[: -len("-bf16")]
        x_bytes = int(x.size) * x.dtype.itemsize
        out_bytes = n * jnp.dtype(
            jnp.result_type(self.dtype, x.dtype)).itemsize
        if x.ndim == 2:
            out_bytes *= int(x.shape[1])
        # Caches use the False sentinel for "tried, not applicable".
        dia = self._dia if self._dia is not False else None
        if path is not None and not path.startswith("dia"):
            dia = None
        if path == "bsr" and self._bsr not in (None, False):
            # Present blocks stream densified through the MXU.
            return int(
                self._bsr.nblocks * 128 * 128 * self.dtype.itemsize
                + x_bytes + out_bytes
            )
        if dia is not None:
            dia_data, _offsets, mask = dia
            mask_bytes = 0
            if mask is not None:
                # The Pallas kernel streams an int8 mask; the XLA
                # fallback streams the bool (also 1 byte/slot).
                mask_bytes = mask.size
            return int(dia_data.size * dia_data.dtype.itemsize
                       + mask_bytes + x_bytes + out_bytes)
        if path == "sliced-ell" and self._sliced_ell not in (None, False):
            # Each pow2 row bin streams its (rows_b, W_b) data+cols
            # blocks plus the count/row-index sideband.
            total = x_bytes + out_bytes
            for ell_data, ell_cols, cnt, row_idx in self._sliced_ell:
                total += (ell_data.size * ell_data.dtype.itemsize
                          + ell_cols.size * ell_cols.dtype.itemsize
                          + cnt.size * cnt.dtype.itemsize
                          + row_idx.size * row_idx.dtype.itemsize)
            return int(total)
        ell = self._ell if self._ell is not False else None
        if path is not None and path != "ell":
            ell = None
        if ell is not None:
            ell_data, ell_cols, ell_counts = ell
            return int(
                ell_data.size * ell_data.dtype.itemsize
                + ell_cols.size * ell_cols.dtype.itemsize
                + ell_counts.size * ell_counts.dtype.itemsize
                + x_bytes + out_bytes
            )
        nnz = self.nnz
        rid_bytes = (self._row_ids.size * self._row_ids.dtype.itemsize
                     if self._row_ids is not None
                     else nnz * np.dtype(np.int32).itemsize)
        return int(
            nnz * (self.data.dtype.itemsize + self.indices.dtype.itemsize)
            + rid_bytes + x_bytes + out_bytes
        )

    def _invalidate_caches(self, structure_changed: bool) -> None:
        """Drop stale structure caches after in-place mutation.  With
        ``structure_changed`` False only value-derived caches reset
        (sparsity pattern intact)."""
        self._ell = None
        self._dia = None
        self._dia_pack = None
        self._dia_fused = None
        self._bsr = None
        self._engine_pack = None
        self._sliced_ell = None  # packs values, not just structure
        if structure_changed:
            self._row_ids = None
            self._ell_width = None
            self._dia_offsets = None
            self._fingerprint = None
            self._canonical = None
            self._sorted = None

    def setdiag(self, values, k: int = 0) -> None:
        """Set diagonal ``k`` in place (scipy ``setdiag``): existing
        stored entries are overwritten on device; rows whose diagonal
        slot has no stored entry get structure inserted (one COO
        rebuild — the scipy 'changing the sparsity structure' case)."""
        import numpy as _np

        rows, cols = self.shape
        if k <= -rows or k >= cols:
            raise ValueError("k exceeds matrix dimensions")
        length = min(rows + min(k, 0), cols - max(k, 0))
        vals = jnp.asarray(values, dtype=self.dtype)
        if vals.ndim == 0:
            vals = jnp.full((length,), vals)
        length = min(length, int(vals.shape[0]))
        vals = vals[:length]
        if length <= 0:
            # Zero-length values: scipy's setdiag silently no-ops.
            return
        if self.nnz and not self.has_canonical_format:
            self.sum_duplicates()

        i0 = max(0, -k)
        row_ids = self._get_row_ids()
        on_diag = jnp.logical_and(
            self._indices.astype(index_dtype())
            - row_ids.astype(index_dtype()) == k,
            row_ids < i0 + length,
        )
        # Overwrite stored diagonal entries.
        safe_rel = jnp.clip(row_ids.astype(index_dtype()) - i0, 0, length - 1)
        new_data = jnp.where(on_diag, vals[safe_rel], self._data)

        # Rows in [i0, i0+length) missing a stored diagonal slot.
        has = _np.zeros(length, dtype=bool)
        hit_rows = _np.asarray(row_ids)[_np.asarray(on_diag)]
        has[hit_rows - i0] = True
        missing = _np.nonzero(~has)[0]
        if missing.size == 0:
            self._data = new_data
            self._invalidate_caches(structure_changed=False)
            return
        cdt = coord_dtype_for(max(self.shape))
        add_rows = jnp.asarray(missing + i0, dtype=cdt)
        add_cols = jnp.asarray(missing + i0 + k, dtype=cdt)
        add_vals = vals[jnp.asarray(missing)]
        r, c, _ = self._coo_parts()
        self._data, self._indices, self._indptr = _convert.coo_to_csr(
            jnp.concatenate([r.astype(cdt), add_rows]),
            jnp.concatenate([c.astype(cdt), add_cols]),
            jnp.concatenate([new_data, add_vals]),
            rows,
        )
        self._invalidate_caches(structure_changed=True)

    # ---------------- indexing ----------------
    def _pointwise_get(self, rows_idx, cols_pt):
        """Vectorized A[rows, cols] pointwise gather: three host
        transfers total, then numpy searchsorted per pair (duplicates
        summed, matching element access)."""
        import numpy as _np

        n_rows, n_cols = self.shape
        out_shape = rows_idx.shape
        rows_idx = _np.where(rows_idx < 0, rows_idx + n_rows,
                             rows_idx).ravel()
        cols_pt = _np.where(cols_pt < 0, cols_pt + n_cols,
                            cols_pt).ravel()
        if rows_idx.size and (
            rows_idx.min() < 0 or rows_idx.max() >= n_rows
            or cols_pt.min() < 0 or cols_pt.max() >= n_cols
        ):
            raise IndexError("pointwise index out of range")
        indptr = _np.asarray(self._indptr)
        indices = _np.asarray(self._indices)
        data = _np.asarray(self._data)
        if rows_idx.shape[0] <= 64:
            # Small queries: per-row probes bounded by the row length —
            # the global key build below is O(nnz) and would make a
            # single A[i, j] scan the whole matrix.
            out = _np.zeros(rows_idx.shape[0], dtype=self.dtype)
            sorted_rows = bool(self.has_sorted_indices)
            for t, (i, j) in enumerate(zip(rows_idx, cols_pt)):
                lo, hi = int(indptr[i]), int(indptr[i + 1])
                seg = indices[lo:hi]
                if sorted_rows:
                    a = _np.searchsorted(seg, j, "left")
                    b = _np.searchsorted(seg, j, "right")
                    out[t] = data[lo + a: lo + b].sum()
                else:
                    out[t] = data[lo:hi][seg == j].sum()
            return out.reshape(out_shape)
        # Batched queries: one global binary search instead of a Python
        # loop per element — nnz keyed by row*ncols+col is globally
        # sorted once, then every (i, j) is two vectorized probes.
        row_ids = _np.repeat(
            _np.arange(n_rows, dtype=_np.int64), _np.diff(indptr)
        )
        key = row_ids * _np.int64(n_cols) + indices.astype(_np.int64)
        if not self.has_sorted_indices:
            order = _np.argsort(key, kind="stable")
            key = key[order]
            data = data[order]
        q = (rows_idx.astype(_np.int64) * _np.int64(n_cols)
             + cols_pt.astype(_np.int64))
        a = _np.searchsorted(key, q, "left")
        b = _np.searchsorted(key, q, "right")
        out = _np.zeros(q.shape[0], dtype=self.dtype)
        single = (b - a) == 1
        out[single] = data[a[single]]
        # Duplicate groups (non-canonical matrices only) sum exactly.
        for t in _np.nonzero(b - a > 1)[0]:
            out[t] = data[a[t]: b[t]].sum()
        return out.reshape(out_shape)

    def _select_rows(self, rows_idx) -> "csr_array":
        import numpy as _np

        rows_idx = _np.asarray(rows_idx, dtype=_np.int64)
        if rows_idx.ndim != 1:
            raise IndexError("row index arrays must be 1-D")
        n_rows = self.shape[0]
        if rows_idx.size and (
            rows_idx.min() < -n_rows or rows_idx.max() >= n_rows
        ):
            raise IndexError("row index out of range")
        rows_idx = _np.where(rows_idx < 0, rows_idx + n_rows, rows_idx)
        idx_d = jnp.asarray(rows_idx)
        counts = _np.asarray(
            self._indptr[idx_d + 1] - self._indptr[idx_d]
        )
        nnz_out = int(counts.sum())
        data, indices, indptr = _convert.select_rows(
            self._data, self._indices, self._indptr,
            jnp.asarray(rows_idx), nnz_out,
        )
        return csr_array._from_parts(
            data, indices, indptr, (len(rows_idx), self.shape[1]),
            canonical=self._canonical,
        )

    @staticmethod
    def _checked_index(i: int, extent: int, axis: str) -> int:
        if not -extent <= i < extent:
            raise IndexError(
                f"{axis} index {i} out of range for extent {extent}"
            )
        return i + extent if i < 0 else i

    @staticmethod
    def _bool_mask_to_idx(mask, extent: int, axis: str):
        import numpy as _np

        if mask.shape[0] != extent:
            raise IndexError(
                f"boolean {axis} mask length {mask.shape[0]} != {extent}"
            )
        return _np.nonzero(mask)[0]

    def __getitem__(self, key):
        """Row selection / element access (the scipy subset users hit
        in practice; the reference supports no indexing at all):

        - ``A[i]`` / ``A[i, :]`` -> (1, cols) csr row.  DEVIATION:
          scipy's ``csr_array`` (sparray) returns a 1-D result here;
          this package has no 1-D sparse type, so row access is always
          2-D (scipy's ``csr_matrix`` semantics).  Shape-sensitive
          callers should ``.toarray().ravel()``.
        - ``A[i, j]`` -> scalar (sum of duplicates at that coordinate)
        - ``A[i0:i1:step]`` / ``A[row_index_array]`` -> csr row subset
        - ``A[:, j0:j1]`` / ``A[rows, :]`` etc. via one row pass + a
          column mask compaction.
        """
        import numpy as _np

        col_key = None
        if isinstance(key, tuple):
            if len(key) != 2:
                raise IndexError("too many indices for 2-D sparse array")
            key, col_key = key

        # Element access A[i, j].
        if (col_key is not None
                and isinstance(key, (int, _np.integer))
                and isinstance(col_key, (int, _np.integer))):
            i = self._checked_index(int(key), self.shape[0], "row")
            j = self._checked_index(int(col_key), self.shape[1], "column")
            lo = int(self._indptr[i])
            hi = int(self._indptr[i + 1])
            seg = _np.asarray(self._indices[lo:hi])
            vals = _np.asarray(self._data[lo:hi])
            return self.dtype.type(vals[seg == j].sum())

        # Normalize the row key to an index array (or full slice).
        if isinstance(key, slice):
            rows_idx = _np.arange(*key.indices(self.shape[0]))
            full_rows = (key == slice(None))
        elif isinstance(key, (int, _np.integer)):
            rows_idx = _np.asarray([int(key)])
            full_rows = False
        else:
            rows_idx = _np.asarray(key)
            if rows_idx.dtype == bool:
                rows_idx = self._bool_mask_to_idx(
                    rows_idx, self.shape[0], "row"
                )
            full_rows = False
            # numpy/scipy pointwise semantics: two index ARRAYS pick
            # individual elements, not the outer-product submatrix.
            if (col_key is not None
                    and not isinstance(col_key, (slice, int, _np.integer))):
                cols_pt = _np.asarray(col_key)
                if cols_pt.dtype == bool:
                    cols_pt = self._bool_mask_to_idx(
                        cols_pt, self.shape[1], "column"
                    )
                if rows_idx.shape != cols_pt.shape:
                    raise IndexError(
                        "pointwise row/column index arrays must have "
                        "the same shape"
                    )
                return self._pointwise_get(rows_idx, cols_pt)

        # Full row slice: hand out an independent wrapper (buffers are
        # immutable jax arrays, so sharing them is safe; in-place
        # mutators replace per-instance references) — scipy's A[:]
        # copy semantics without the copy.
        out = (self._with_data(self._data) if full_rows
               else self._select_rows(rows_idx))

        if col_key is None or (isinstance(col_key, slice)
                               and col_key == slice(None)):
            return out

        # Column restriction.  Integer/bool arrays may carry duplicates
        # or arbitrary order, which a position remap cannot express —
        # go through the transpose and reuse row selection (duplicate-
        # capable).  Slices keep the cheaper mask + compact + rebase.
        if not isinstance(col_key, (slice, int, _np.integer)):
            cols_sel = _np.asarray(col_key)
            if cols_sel.dtype == bool:
                cols_sel = self._bool_mask_to_idx(
                    cols_sel, self.shape[1], "column"
                )
            return out.transpose()._select_rows(cols_sel).transpose()
        if isinstance(col_key, slice):
            start, stop, step = col_key.indices(self.shape[1])
            cols_sel = _np.arange(start, stop, step)
        else:
            cols_sel = _np.asarray([
                self._checked_index(int(col_key), self.shape[1], "column")
            ])
        remap = _np.full(self.shape[1], -1, dtype=_np.int64)
        remap[cols_sel] = _np.arange(len(cols_sel))
        remap_d = jnp.asarray(remap)
        new_cols = remap_d[out.indices]
        keep = new_cols >= 0
        nnz_new = int(jnp.sum(keep))
        row_ids = _convert.row_ids_from_indptr(out.indptr, out.nnz)
        data, cols2, rows_kept = _convert.compact_mask(
            keep, (out.data, new_cols, row_ids), nnz_new
        )
        return csr_array._from_parts(
            data, cols2.astype(coord_dtype_for(max(len(cols_sel), 1))),
            _convert.indptr_from_row_ids(rows_kept, out.shape[0]),
            (out.shape[0], len(cols_sel)),
            canonical=None,
        )

    def __str__(self) -> str:
        row_ids, cols, vals = self._coo_parts()
        lines = [
            f"  ({int(r)}, {int(c)})\t{v}"
            for r, c, v in zip(
                np.asarray(row_ids), np.asarray(cols), np.asarray(vals)
            )
        ]
        return "\n".join(lines)

    def __repr__(self) -> str:
        return (
            f"<{self.shape[0]}x{self.shape[1]} sparse array of type "
            f"'{self.dtype}' with {self.nnz} stored elements in "
            f"Compressed Sparse Row format>"
        )


# scipy.sparse.*_matrix alias (reference defines csr_matrix the same way).
class csr_matrix(csr_array):
    """spmatrix-flavored alias: ``*`` means matrix multiplication
    (scipy's csr_matrix), unlike the element-wise sparray ``*``; the
    legacy getrow/getcol/getH accessors exist here only, as in scipy."""

    _is_spmatrix = True

    def __pow__(self, n):
        # spmatrix semantics: matrix power (scipy's csr_matrix ** n),
        # not the element-wise sparray power.
        if not isinstance(n, (int, np.integer)) or n < 0:
            raise ValueError("matrix power requires a non-negative int")
        if self.shape[0] != self.shape[1]:
            raise TypeError("matrix is not square")
        from .gallery import identity as _identity

        result = csr_matrix(
            _identity(self.shape[0], dtype=self.dtype, format="csr")
        )
        base = self
        n = int(n)
        while n:
            if n & 1:
                result = csr_matrix(result.dot(base))
            n >>= 1
            if n:
                base = csr_matrix(base.dot(base))
        return result

    def getrow(self, i):
        return csr_matrix(self[int(i), :])

    def getcol(self, j):
        return csr_matrix(self[:, int(j)])

    def getH(self):
        return self.conj().transpose()

    def __mul__(self, other):
        if np.isscalar(other) or getattr(other, "ndim", None) == 0:
            return self._with_data(self._data * other)
        return self.dot(other)

    def __rmul__(self, other):
        if np.isscalar(other) or getattr(other, "ndim", None) == 0:
            return self._with_data(self._data * other)
        # scipy spmatrix: x * A is x @ A (row-vector matmul).
        other = np.asarray(other)
        AT = self.transpose()
        if other.ndim == 1:
            return np.asarray(AT @ other)
        return np.asarray(AT @ other.T).T


def _elementwise_intersect_multiply(a: csr_array, b: csr_array) -> csr_array:
    """Hadamard product of two canonical CSR matrices.

    Two-key sort of the concatenated coordinate lists with a value
    channel per operand: since both inputs are canonical, a coordinate
    present in both becomes an adjacent pair after the sort, and the
    product of the channel sums over the pair is the output value.  No
    fused integer key — safe for any rows*cols.
    """
    rows, cols = a.shape
    ra, ca, va = a._coo_parts()
    rb, cb, vb = b._coo_parts()
    r = jnp.concatenate([ra, rb])
    c = jnp.concatenate([ca, cb])
    ch_a = jnp.concatenate([va, jnp.zeros_like(vb)])
    ch_b = jnp.concatenate([jnp.zeros_like(va), vb])
    r, c, ch_a, ch_b = jax.lax.sort([r, c, ch_a, ch_b], num_keys=2)
    pair = jnp.logical_and(r[1:] == r[:-1], c[1:] == c[:-1])
    prod = (ch_a[:-1] + ch_a[1:]) * (ch_b[:-1] + ch_b[1:])
    nnz_out = int(jnp.sum(pair))
    idx = jnp.nonzero(pair, size=nnz_out, fill_value=0)[0]
    out_rows = r[idx]
    out_cols = c[idx]
    out_vals = prod[idx]
    indptr = _convert.indptr_from_row_ids(out_rows, rows)
    return csr_array._from_parts(
        out_vals, out_cols, indptr, (rows, cols)
    )


def spmv(A: csr_array, x, y):
    """Free-function SpMV: y <- A @ x (reference ``csr.py:562-593``)."""
    return A.dot(jnp.asarray(x), out=y)


def spgemm_csr_csr_csr(A: csr_array, B: csr_array) -> csr_array:
    """C = A @ B (reference ``csr.py:598-748``).

    Banded fast path: when both operands are *exact* bands (DIA caches
    with no hole mask), C is the Minkowski-sum band computed as
    nd_a*nd_b shifted elementwise multiplies — no expansion, no device
    sort.  This covers the SpGEMM microbenchmark's banded config and
    products of stencil operators.  Everything else runs the general
    expand-sort-compress kernel.
    """
    assert A.shape[1] == B.shape[0], "dimension mismatch in spgemm"
    m, k = A.shape
    n = B.shape[1]

    from .settings import settings

    _obs.inc("op.spgemm")
    with _lat.timer("lat.spgemm." + _lat.shape_bucket(m)), \
            _obs.span("spgemm", m=m, k=k, n=n, nnz_a=A.nnz,
                      nnz_b=B.nnz) as sp:
        dia_a = A._get_dia()
        dia_b = B._get_dia() if dia_a is not None else None
        if (
            dia_a is not None
            and dia_b is not None
            and dia_a[2] is None
            and dia_b[2] is None
        ):
            offs_c = _dia_ops.band_product_offsets(dia_a[1], dia_b[1])
            nnz_c = _dia_ops.band_cover(offs_c, (m, n), n)
            if (
                len(offs_c) <= settings.dia_max_diags
                and len(offs_c) * n
                <= settings.dia_max_expand * max(nnz_c, 1)
                # scipy pattern parity: every in-bounds product slot must
                # be structurally reachable, else the ESC kernel decides
                # nnz.
                and _dia_ops.band_product_is_full(
                    dia_a[1], dia_b[1], offs_c, A.shape, B.shape
                )
            ):
                from .ops.pallas_dia import (
                    dia_spgemm_maybe_pallas, pallas_dia_active,
                )

                Cd = (
                    dia_spgemm_maybe_pallas(
                        dia_a[0], dia_b[0], dia_a[1], dia_b[1], offs_c,
                        A.shape, B.shape,
                    )
                    if pallas_dia_active() else None
                )
                path = "dia-pallas"
                if Cd is None:
                    Cd = _dia_ops.dia_spgemm(
                        dia_a[0], dia_b[0], dia_a[1], dia_b[1], offs_c,
                        A.shape, B.shape,
                    )
                    path = "dia-xla"
                data, indices, indptr = _dia_ops.band_to_csr(
                    Cd, offs_c, (m, n), nnz_c
                )
                C = csr_array._from_parts(data, indices, indptr, (m, n))
                # The product band is exact by construction: warm C's own
                # fast-path cache for downstream matvecs (GMG coarse ops).
                C._dia_offsets = offs_c
                C._dia = (Cd, offs_c, None)
                if sp is not None:
                    itm = C.dtype.itemsize
                    sp.set(path=path, nnz=nnz_c,
                           bytes=(dia_a[0].size + dia_b[0].size
                                  + Cd.size) * itm,
                           flops=2 * len(dia_a[1]) * len(dia_b[1]) * n)
                return C

        data, indices, indptr = _spgemm_ops.spgemm_csr_csr_csr_impl(
            A.data, A.indices, A.indptr, B.data, B.indices, B.indptr,
            m, k, n
        )
        C = csr_array._from_parts(data, indices, indptr, (m, n))
        if sp is not None:
            itm = C.dtype.itemsize
            idx = C.indices.dtype.itemsize
            sp.set(path="esc", nnz=C.nnz,
                   chunks=_spgemm_ops._last_num_chunks,
                   bytes=(A.nnz + B.nnz + C.nnz) * (itm + idx))
        return C
