# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Streaming matrix mutation under live traffic (docs/MUTATION.md).

Inert by default behind ``LEGATE_SPARSE_TPU_DELTA``: a
:class:`~.core.DeltaCSR` serves an immutable base ``csr_array`` plus a
bounded COO side-buffer of entry updates as ``base @ x + delta @ x``,
with background compaction merging the buffer into a fresh base CSR
off the serving path and atomically swapping versions behind the
gateway.  :class:`~.dist.DistDeltaCSR` is the mesh-scale twin: updates
route to owner shards by the layout arithmetic and are priced in the
comm ledger as ``comm.delta.*``.
"""

from .core import (  # noqa: F401
    DeltaCapacityError, DeltaCSR, DeltaView, is_delta, route,
)
from .dist import DistDeltaCSR  # noqa: F401

__all__ = [
    "DeltaCSR", "DeltaView", "DistDeltaCSR", "DeltaCapacityError",
    "is_delta", "route",
]
