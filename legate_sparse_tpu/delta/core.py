# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Delta layer: mutable serving views over immutable CSR matrices.

Every matrix in the package is immutable after build; the production
workloads the north star names (recommender graphs, risk matrices, PDE
remeshing) mutate *while serving*.  :class:`DeltaCSR` closes that gap
without giving up the immutability the engine's plan caches rely on:

- the **base** stays an untouched ``csr_array``, serving through every
  existing path (engine buckets, autotune verdicts, packs);
- mutations land in a **bounded COO side-buffer** of absolute entry
  updates (overwrite-wins within the buffer; a 0.0 target deletes the
  entry at compaction), padded to pow2 capacity buckets on device so
  streaming mutation never retraces;
- ``.dot`` serves ``base @ x + delta @ x`` — the delta term through
  the masked :func:`~..ops.spmv.coo_spmv_segment` kernel, skipped
  bit-for-bit when the buffer is empty;
- :meth:`DeltaCSR.compact` merges the buffer into a **fresh base**
  off the serving path and atomically swaps an immutable
  :class:`DeltaView` exactly like ``placement/migrate.py`` swaps
  placements: in-flight requests drain on the view pinned at
  admission, later admissions serve the new version.  Fresh bases are
  new objects, so fingerprint/autotune/plan caches invalidate
  structurally — no epoch bump, no retrace of unrelated plans.

The additive trick: an absolute update ``A[r, c] = v`` is stored on
device as the difference ``v - base[r, c]`` (``v`` for an insert), so
the two-term product is exact without rewriting the base — the
in-situ streamed-second-term scheduling of PAPERS.md 2311.03826, with
compaction as SpArch's background merge pass (2002.08947).

Inert by default: constructing a :class:`DeltaCSR` without
``LEGATE_SPARSE_TPU_DELTA`` raises, the gateway's routing hook is one
flag read, and no ``delta.*`` counter moves while the flag is off
(pinned by test).

Counters / events / histograms (docs/OBSERVABILITY.md):

- ``delta.updates`` / ``delta.applied`` / ``delta.overwrites`` /
  ``delta.served`` / ``delta.compactions`` /
  ``delta.compaction.merged`` / ``delta.compaction.bytes`` /
  ``delta.swap.versions`` / ``delta.routes`` /
  ``delta.watermark.exceeded``
- events ``delta.update`` / ``delta.compaction`` /
  ``delta.watermark``
- histograms ``lat.delta.update`` / ``lat.delta.compaction``
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Dict, Optional, Tuple

import numpy as np

from .. import obs as _obs
from ..obs import latency as _latency
from ..resilience import faults as _rfaults
from ..resilience import policy as _rpolicy
from ..settings import settings as _settings

__all__ = [
    "DeltaCapacityError", "DeltaCSR", "DeltaView", "is_delta", "route",
]


class DeltaCapacityError(ValueError):
    """The bounded side-buffer is full: compact before updating."""

    def __init__(self, pending: int, capacity: int):
        self.pending = pending
        self.capacity = capacity
        super().__init__(
            f"delta buffer full: {pending} pending update slots "
            f"exceed capacity {capacity} "
            f"(LEGATE_SPARSE_TPU_DELTA_CAPACITY) — call compact() or "
            f"arm the watermark worker")


def _pow2_bucket(n: int) -> int:
    """Smallest power of two >= max(n, 1) — the padded device-buffer
    width, so a growing buffer recompiles the serving kernel only at
    bucket crossings (log2(capacity) compiles, ever)."""
    return 1 << max(int(n) - 1, 0).bit_length()


def _base_values_at(base, rows: np.ndarray,
                    cols: np.ndarray) -> np.ndarray:
    """Host lookup of ``base[r, c]`` per update coordinate (0.0 where
    the slot is structurally absent — an insert)."""
    indptr = np.asarray(base.indptr)
    indices = np.asarray(base.indices)
    data = np.asarray(base.data)
    out = np.zeros(rows.shape[0], dtype=data.dtype)
    for i, (r, c) in enumerate(zip(rows, cols)):
        lo, hi = int(indptr[r]), int(indptr[r + 1])
        j = lo + int(np.searchsorted(indices[lo:hi], c))
        if j < hi and int(indices[j]) == int(c):
            out[i] = data[j]
    return out


class DeltaView:
    """One immutable serving snapshot: (base, padded device buffer,
    version).  Quacks enough like ``csr_array`` for the gateway
    (shape/nnz/dtype/dot) while deliberately failing the engine's
    ``isinstance`` eligibility gate — delta traffic serves inline
    through its own two-term dispatch, the ``PlacedHandle`` trick.
    Readers never lock: a compaction swaps the owner's current view;
    requests admitted before the swap drain on this one."""

    __slots__ = ("base", "version", "pending", "_rows_dev",
                 "_cols_dev", "_dvals_dev", "_valid")

    def __init__(self, base, version: int, pending: int,
                 rows_dev=None, cols_dev=None, dvals_dev=None,
                 valid: int = 0):
        self.base = base
        self.version = int(version)
        self.pending = int(pending)
        self._rows_dev = rows_dev
        self._cols_dev = cols_dev
        self._dvals_dev = dvals_dev
        self._valid = int(valid)

    @property
    def shape(self):
        return self.base.shape

    @property
    def nnz(self):
        return self.base.nnz

    @property
    def dtype(self):
        return self.base.dtype

    def dot(self, x):
        """Serve one SpMV on the pinned version: the base term through
        the full existing dispatch ladder (engine/autotune included),
        plus the masked COO delta term.  An empty buffer is bit-for-bit
        the base dispatch alone (no ``+ 0`` term — IEEE signed zeros
        forbid a free-riding add)."""
        y = self.base.dot(x)
        if self._valid == 0:
            return y
        import jax.numpy as jnp

        from ..ops.spmv import coo_spmv_segment

        _obs.inc("delta.served")
        xa = jnp.asarray(x)
        cdt = jnp.result_type(self.base.dtype, xa.dtype)
        with _obs.span("delta.serve", version=self.version,
                       pending=self.pending, path="coo-segment"):
            yd = coo_spmv_segment(
                self._dvals_dev.astype(cdt), self._rows_dev,
                self._cols_dev, self._valid, xa.astype(cdt),
                self.base.shape[0])
        return y + yd

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"DeltaView(v{self.version}, pending={self.pending}, "
                f"base={self.base.shape})")


class _Buffer:
    """The bounded overwrite-wins update ledger, shared by the local
    and distributed wrappers.  Host truth is an insertion-ordered
    ``{(row, col): (target, additive)}`` dict; the device image is the
    (row, col)-sorted triple padded to the pow2 capacity bucket with
    the out-of-range row sentinel."""

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        self.entries: Dict[Tuple[int, int], Tuple[float, float]] = {}

    @property
    def pending(self) -> int:
        return len(self.entries)

    def ingest(self, rows, cols, vals, base_vals) -> Tuple[int, int]:
        """Apply one absolute-update batch (later wins on a repeated
        coordinate, within the batch and against earlier batches).
        Returns ``(new_slots, overwrites)``; raises
        :class:`DeltaCapacityError` before mutating anything when the
        resolved batch would overflow."""
        # A batch may hit one new coordinate twice; resolve exactly.
        seen = set()
        new_slots = 0
        for r, c in zip(rows, cols):
            key = (int(r), int(c))
            if key not in self.entries and key not in seen:
                new_slots += 1
                seen.add(key)
        if self.pending + new_slots > self.capacity:
            raise DeltaCapacityError(self.pending + new_slots,
                                     self.capacity)
        overwrites = 0
        for r, c, v, bv in zip(rows, cols, vals, base_vals):
            key = (int(r), int(c))
            if key in self.entries:
                overwrites += 1
            self.entries[key] = (float(v), float(v) - float(bv))
        return new_slots, overwrites

    def device_image(self, dtype, sentinel_row: int):
        """(row_ids, col_ids, additive_vals, valid) padded to the pow2
        bucket, sorted by (row, col) so the serving kernel's
        ``indices_are_sorted`` contract holds."""
        import jax.numpy as jnp

        n = self.pending
        cap = _pow2_bucket(min(max(n, 1), self.capacity))
        rows = np.full(cap, sentinel_row, dtype=np.int32)
        cols = np.zeros(cap, dtype=np.int32)
        vals = np.zeros(cap, dtype=dtype)
        if n:
            keys = sorted(self.entries)
            rows[:n] = [k[0] for k in keys]
            cols[:n] = [k[1] for k in keys]
            vals[:n] = [self.entries[k][1] for k in keys]
        return (jnp.asarray(rows), jnp.asarray(cols),
                jnp.asarray(vals), n)

    def snapshot_arrays(self):
        """Host numpy triple of the resolved buffer (checkpoint
        payload: survives any device loss by construction)."""
        keys = sorted(self.entries)
        return (np.asarray([k[0] for k in keys], dtype=np.int64),
                np.asarray([k[1] for k in keys], dtype=np.int64),
                np.asarray([self.entries[k][0] for k in keys],
                           dtype=np.float64))


def _require_enabled(what: str) -> None:
    if not _settings.delta:
        raise RuntimeError(
            f"{what} requires the delta layer "
            f"(set LEGATE_SPARSE_TPU_DELTA=1, docs/MUTATION.md); off "
            f"by default so the immutable serving path stays "
            f"bit-for-bit and counter-inert")


class DeltaCSR:
    """A served matrix that mutates: immutable base ``csr_array`` +
    bounded COO side-buffer, versioned compaction (module docstring).

    All mutation runs under one lock and publishes a fresh immutable
    :class:`DeltaView`; ``dot``/routing read the current view with a
    single reference load, so serving never blocks on an in-progress
    compaction and a mid-compaction request drains on the version it
    was admitted under."""

    def __init__(self, base, capacity: Optional[int] = None):
        _require_enabled("DeltaCSR")
        from ..csr import csr_array

        if not isinstance(base, csr_array):
            base = csr_array(base)
        self._lock = threading.RLock()
        self._buffer = _Buffer(
            _settings.delta_capacity if capacity is None else capacity)
        self._view = DeltaView(base._canonicalized(), version=0,
                               pending=0)
        self._worker: Optional[threading.Thread] = None
        self._worker_stop = threading.Event()

    # ---------------- serving surface ----------------

    @property
    def shape(self):
        return self._view.shape

    @property
    def nnz(self):
        return self._view.nnz

    @property
    def dtype(self):
        return self._view.dtype

    @property
    def base(self):
        return self._view.base

    @property
    def version(self) -> int:
        return self._view.version

    @property
    def pending(self) -> int:
        return self._view.pending

    @property
    def capacity(self) -> int:
        return self._buffer.capacity

    def view(self) -> DeltaView:
        """The current immutable serving snapshot (what the gateway
        pins at admission)."""
        return self._view

    def dot(self, x):
        return self._view.dot(x)

    # ---------------- mutation ----------------

    def update(self, rows, cols, vals):
        """Absolute entry updates ``A[rows[i], cols[i]] = vals[i]``
        (overwrite-wins on repeats; a 0.0 target deletes the entry at
        compaction).  Bounded: raises :class:`DeltaCapacityError`
        without mutating anything when the batch would overflow the
        buffer."""
        t0 = time.perf_counter_ns()
        rows = np.atleast_1d(np.asarray(rows, dtype=np.int64))
        cols = np.atleast_1d(np.asarray(cols, dtype=np.int64))
        vals = np.atleast_1d(np.asarray(vals))
        if not (rows.shape == cols.shape == vals.shape):
            raise ValueError(
                f"delta update: rows/cols/vals shapes disagree "
                f"({rows.shape}, {cols.shape}, {vals.shape})")
        m, n = self.shape
        if rows.size and (rows.min() < 0 or rows.max() >= m
                          or cols.min() < 0 or cols.max() >= n):
            raise IndexError(
                f"delta update: coordinates out of range for shape "
                f"{self.shape}")
        with self._lock:
            view = self._view
            base_vals = _base_values_at(view.base, rows, cols)
            new_slots, overwrites = self._buffer.ingest(
                rows, cols, vals, base_vals)
            self._publish(view.base, view.version)
            pending = self._buffer.pending
        _obs.inc("delta.updates")
        _obs.inc("delta.applied", new_slots)
        if overwrites:
            _obs.inc("delta.overwrites", overwrites)
        _latency.observe("lat.delta.update",
                         (time.perf_counter_ns() - t0) / 1e6)
        _obs.event("delta.update", applied=new_slots,
                   overwrites=overwrites, pending=pending,
                   version=self.version)
        if pending >= self._watermark_slots():
            _obs.inc("delta.watermark.exceeded")
            _obs.event("delta.watermark", pending=pending,
                       capacity=self._buffer.capacity)
            self._ensure_worker()

    # scipy-flavoured alias: the row/entry-set API is the same
    # absolute overwrite-wins ingestion.
    set_entries = update

    def entries(self) -> Dict[Tuple[int, int], float]:
        """Pending buffered targets ``{(row, col): value}`` (host
        snapshot; 0.0 marks a pending delete)."""
        with self._lock:
            return {k: tv for k, (tv, _d) in
                    self._buffer.entries.items()}

    # ---------------- compaction / versioned swap ----------------

    def compact(self) -> int:
        """Merge the buffer into a fresh base CSR off the serving path
        and atomically swap versions: in-flight requests drain on
        their admitted view, later admissions serve the merged base
        with an empty buffer.  Returns the number of entries merged
        (0 = nothing pending, no swap, no counter movement).

        Resilience: with ``LEGATE_SPARSE_TPU_RESIL`` the merge runs
        under the ``delta.compact`` site policy (injectable, retried
        with backoff), and an active ``resilience.checkpoint`` scope
        snapshots the resolved buffer to host first — a device loss
        mid-compaction recovers by re-merging from host truth."""
        t0 = time.perf_counter_ns()
        with self._lock:
            view = self._view
            merged = self._buffer.pending
            if merged == 0:
                return 0
            if _settings.resil:
                from ..resilience import checkpoint as _ckpt

                ck = _ckpt.current()
                if ck is not None:
                    ck.save(view.version,
                            self._buffer.snapshot_arrays())

                def attempt():
                    _rfaults.fault_point("delta.compact")
                    return self._merged_base(view.base)

                new_base = _rpolicy.run("delta.compact", attempt)
            else:
                new_base = self._merged_base(view.base)
            self._buffer.entries.clear()
            self._publish(new_base, view.version + 1)
            version = self._view.version
        nbytes = (int(np.asarray(new_base.data).nbytes)
                  + int(np.asarray(new_base.indices).nbytes)
                  + int(np.asarray(new_base.indptr).nbytes))
        _obs.inc("delta.compactions")
        _obs.inc("delta.compaction.merged", merged)
        _obs.inc("delta.compaction.bytes", nbytes)
        _obs.inc("delta.swap.versions")
        _latency.observe("lat.delta.compaction",
                         (time.perf_counter_ns() - t0) / 1e6)
        _obs.event("delta.compaction", merged=merged, version=version,
                   nnz=new_base.nnz, bytes=nbytes)
        return merged

    def _merged_base(self, base):
        """Fresh canonical base = base entries overridden by buffered
        targets (0.0 deletes).  Goes through the public COO
        constructor — the same canonicalization a cold rebuild of the
        mutated matrix uses, so post-compaction serving is bitwise the
        cold rebuild (acceptance criterion c)."""
        from ..csr import csr_array

        brows, bcols, bdata = (np.asarray(a) for a in
                               base._coo_parts())
        merged: Dict[Tuple[int, int], float] = {
            (int(r), int(c)): v
            for r, c, v in zip(brows, bcols, bdata)
        }
        for key, (target, _d) in self._buffer.entries.items():
            if target == 0.0:
                merged.pop(key, None)
            else:
                merged[key] = target
        keys = sorted(merged)
        rows = np.asarray([k[0] for k in keys], dtype=np.int64)
        cols = np.asarray([k[1] for k in keys], dtype=np.int64)
        vals = np.asarray([merged[k] for k in keys], dtype=base.dtype)
        return csr_array((vals, (rows, cols)), shape=base.shape,
                         dtype=base.dtype)

    def _publish(self, base, version: int) -> None:
        """Swap in a fresh immutable view (callers hold the lock)."""
        if self._buffer.pending:
            rid, cid, dvals, valid = self._buffer.device_image(
                base.dtype, sentinel_row=base.shape[0])
            self._view = DeltaView(base, version,
                                   self._buffer.pending, rid, cid,
                                   dvals, valid)
        else:
            self._view = DeltaView(base, version, 0)

    # ---------------- watermark worker ----------------

    def _watermark_slots(self) -> int:
        frac = max(float(_settings.delta_watermark), 0.0)
        return max(int(frac * self._buffer.capacity), 1)

    def maybe_compact(self) -> int:
        """Compact iff the watermark is exceeded (the worker's step,
        callable inline by serving loops that poll their own
        cadence)."""
        if self._buffer.pending >= self._watermark_slots():
            return self.compact()
        return 0

    def _ensure_worker(self) -> None:
        cadence_ms = float(_settings.delta_worker_ms)
        if cadence_ms <= 0:
            return
        with self._lock:
            if self._worker is not None and self._worker.is_alive():
                return
            self._worker_stop.clear()
            ref = weakref.ref(self)
            stop = self._worker_stop

            def loop():
                while not stop.wait(cadence_ms / 1e3):
                    owner = ref()
                    if owner is None:
                        return
                    try:
                        owner.maybe_compact()
                    except Exception:  # pragma: no cover - worker
                        # A failed background merge must never kill
                        # the daemon; the next step retries and the
                        # serving path is untouched either way.
                        _obs.inc("delta.worker.errors")
                    if owner._buffer.pending == 0:
                        return
                    del owner

            t = threading.Thread(target=loop, daemon=True,
                                 name="delta-compaction-worker")
            self._worker = t
            t.start()

    def stop_worker(self) -> None:
        """Stop a running background compaction worker (tests)."""
        self._worker_stop.set()
        t = self._worker
        if t is not None and t.is_alive():
            t.join(timeout=5.0)

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"DeltaCSR(v{self.version}, "
                f"pending={self.pending}/{self.capacity}, "
                f"base={self.base.shape})")


def is_delta(A) -> bool:
    return isinstance(A, DeltaCSR)


def route(A):
    """Admission-time routing (``engine/gateway.py``): a submitted
    :class:`DeltaCSR` swaps for its current immutable
    :class:`DeltaView` — the version pinned NOW — so in-flight
    requests drain on the pre-compaction view while later admissions
    serve the merged base.  Anything else passes through untouched."""
    if not isinstance(A, DeltaCSR):
        return A
    _obs.inc("delta.routes")
    return A.view()
