# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Mesh-scale delta serving: :class:`DistDeltaCSR` (docs/MUTATION.md).

The distributed twin of :class:`~.core.DeltaCSR`: an immutable base
:class:`~..parallel.dist_csr.DistCSR` plus the same bounded
overwrite-wins side-buffer, so time-evolving graph analytics
(PageRank/BFS over a mutating edge set) and serve-while-mutating
traffic work at mesh scale.  Differences from the local wrapper:

- **updates route to owner shards** by the existing layout
  arithmetic (``shard_row_starts`` / ``rows_per_shard``) and are
  priced in the comm ledger as ``comm.delta.scatter*`` — a host
  update batch is a scatter of (row, col, value) records to the
  shards that own the rows;
- **the delta term is an all_gather-realized second term**: the
  padded sharded ``x`` is realized once (priced as
  ``comm.delta.all_gather*``), run through the same masked
  :func:`~..ops.spmv.coo_spmv_segment` kernel over
  ``rows_padded`` segments, and re-sharded onto the row partition
  before the add — zero new collective programs;
- **compaction is a repartition**: the merge runs on the retained
  host source (the same path :func:`~..parallel.reshard.reshard`
  uses), then ``shard_csr`` rebuilds the base on the same mesh and
  layout and the version swaps atomically.

``reshard()`` on a wrapper with pending updates must never silently
drop them: the hook :meth:`DistDeltaCSR._delta_reshard_carry` carries
the buffer across the repartition (additive deltas are
base-relative, and a reshard preserves the logical base, so the
buffer transfers verbatim) — pinned by regression test.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from .. import obs as _obs
from ..obs import comm as _comm
from ..obs import latency as _latency
from ..settings import settings as _settings
from .core import _Buffer, _base_values_at, _require_enabled

__all__ = ["DistDeltaCSR"]


class DistDeltaCSR:
    """A served distributed matrix that mutates: immutable base
    ``DistCSR`` + bounded COO side-buffer with owner-shard routed
    updates, versioned compaction-by-repartition (module docstring).

    1d-row layouts only: the delta term's re-shard add and the
    owner-shard routing arithmetic are row-partition identities; a
    2-d-block wrapper would need block-local column rebasing with no
    workload behind it yet."""

    def __init__(self, base, capacity: Optional[int] = None):
        _require_enabled("DistDeltaCSR")
        from ..parallel.dist_csr import DistCSR
        from ..parallel.mesh import LAYOUT_1D_ROW

        if not isinstance(base, DistCSR):
            raise TypeError(
                f"DistDeltaCSR wraps a DistCSR (got "
                f"{type(base).__name__}); shard first via shard_csr")
        if base.layout != LAYOUT_1D_ROW:
            raise ValueError(
                f"DistDeltaCSR supports the 1d-row layout only (got "
                f"{base.layout!r}): the owner-shard routing and the "
                f"delta-term re-shard are row-partition arithmetic")
        if getattr(base, "_src_csr", None) is None:
            raise ValueError(
                "DistDeltaCSR: base DistCSR carries no retained "
                "source matrix (_src_csr); build it via shard_csr")
        self._lock = threading.RLock()
        self._base = base
        self._buffer = _Buffer(
            _settings.delta_capacity if capacity is None else capacity)
        self._version = 0
        self._image = None  # (rid, cid, dvals, valid) device snapshot

    # ---------------- serving surface ----------------

    @property
    def shape(self):
        return self._base.shape

    @property
    def dtype(self):
        return self._base.dtype

    @property
    def base(self):
        return self._base

    @property
    def mesh(self):
        return self._base.mesh

    @property
    def layout(self) -> str:
        return self._base.layout

    @property
    def num_shards(self) -> int:
        return self._base.num_shards

    @property
    def rows_padded(self) -> int:
        return self._base.rows_padded

    @property
    def version(self) -> int:
        return self._version

    @property
    def pending(self) -> int:
        return self._buffer.pending

    @property
    def capacity(self) -> int:
        return self._buffer.capacity

    def dot(self, x):
        """``y = base (x) + delta (x)`` on the row partition: the base
        term through the full ``dist_spmv`` dispatch, the delta term
        as an all_gather-realized masked COO segment sum re-sharded
        onto the row blocks (priced as ``comm.delta.all_gather*``).
        ``x`` and the result are row-block sharded padded vectors of
        length ``base.rows_padded`` (the ``dist_spmv`` contract);
        an empty buffer is bit-for-bit the base dispatch alone."""
        from ..parallel.dist_csr import dist_spmv

        with self._lock:
            base = self._base
            image = self._image
            version = self._version
            pending = self._buffer.pending
        y = dist_spmv(base, x)
        if image is None:
            return y
        import jax.numpy as jnp

        from ..ops.spmv import coo_spmv_segment
        from ..parallel.dist_csr import shard_vector

        _obs.inc("delta.served")
        rid, cid, dvals, valid = image
        shards = base.num_shards
        chunk_bytes = (base.rows_per_shard
                       * np.dtype(base.dtype).itemsize)
        _comm.record(
            "delta", {"all_gather": shards * (shards - 1)
                      * chunk_bytes},
            calls={"all_gather": 1}, layout=base.layout)
        xg = jnp.asarray(x)
        cdt = jnp.result_type(base.dtype, xg.dtype)
        with _obs.span("delta.serve", version=version,
                       pending=pending, path="coo-segment",
                       dist=True):
            yd = coo_spmv_segment(
                dvals.astype(cdt), rid, cid, valid, xg.astype(cdt),
                base.rows_padded)
        return y + shard_vector(np.asarray(yd), base.mesh,
                                base.rows_padded, base.layout)

    # ---------------- mutation ----------------

    def update(self, rows, cols, vals):
        """Absolute entry updates, routed to owner shards by the row
        partition and priced as ``comm.delta.scatter*``.  Semantics
        match :meth:`DeltaCSR.update` exactly (overwrite-wins, 0.0
        deletes at compaction, typed capacity error)."""
        t0 = time.perf_counter_ns()
        rows = np.atleast_1d(np.asarray(rows, dtype=np.int64))
        cols = np.atleast_1d(np.asarray(cols, dtype=np.int64))
        vals = np.atleast_1d(np.asarray(vals))
        if not (rows.shape == cols.shape == vals.shape):
            raise ValueError(
                f"delta update: rows/cols/vals shapes disagree "
                f"({rows.shape}, {cols.shape}, {vals.shape})")
        m, n = self.shape
        if rows.size and (rows.min() < 0 or rows.max() >= m
                          or cols.min() < 0 or cols.max() >= n):
            raise IndexError(
                f"delta update: coordinates out of range for shape "
                f"{self.shape}")
        with self._lock:
            base = self._base
            src = base._src_csr
            base_vals = _base_values_at(src, rows, cols)
            new_slots, overwrites = self._buffer.ingest(
                rows, cols, vals, base_vals)
            self._refresh_image()
            pending = self._buffer.pending
        # Owner-shard routing: each record travels to the shard whose
        # row block owns it — (row, col) int32 coords + the value.
        owners = rows // np.int64(base.rows_per_shard)
        rec_bytes = 2 * 4 + np.dtype(base.dtype).itemsize
        _comm.record(
            "delta", {"scatter": int(rows.size) * rec_bytes},
            calls={"scatter": 1}, layout=base.layout)
        _obs.inc("delta.updates")
        _obs.inc("delta.applied", new_slots)
        if overwrites:
            _obs.inc("delta.overwrites", overwrites)
        _latency.observe("lat.delta.update",
                         (time.perf_counter_ns() - t0) / 1e6)
        _obs.event("delta.update", applied=new_slots,
                   overwrites=overwrites, pending=pending,
                   version=self._version, dist=True,
                   shards_touched=int(np.unique(owners).size))
        if pending >= self._watermark_slots():
            _obs.inc("delta.watermark.exceeded")
            _obs.event("delta.watermark", pending=pending,
                       capacity=self._buffer.capacity)

    set_entries = update

    def entries(self) -> Dict[Tuple[int, int], float]:
        """Pending buffered targets ``{(row, col): value}``."""
        with self._lock:
            return {k: tv for k, (tv, _d) in
                    self._buffer.entries.items()}

    # ---------------- compaction / versioned swap ----------------

    def compact(self) -> int:
        """Merge the buffer into the retained host source, re-shard
        onto the same mesh/layout (the repartition path ``reshard``
        uses) and atomically swap versions.  Returns entries merged."""
        from ..parallel.dist_csr import shard_csr

        t0 = time.perf_counter_ns()
        with self._lock:
            base = self._base
            merged = self._buffer.pending
            if merged == 0:
                return 0
            src = base._src_csr
            new_src = self._merged_src(src)
            with _obs.span("delta.compaction", dist=True,
                           merged=merged):
                new_base = shard_csr(new_src, mesh=base.mesh,
                                     layout=base.layout)
            self._buffer.entries.clear()
            self._base = new_base
            self._image = None
            self._version += 1
            version = self._version
        nbytes = (int(np.asarray(new_src.data).nbytes)
                  + int(np.asarray(new_src.indices).nbytes)
                  + int(np.asarray(new_src.indptr).nbytes))
        _obs.inc("delta.compactions")
        _obs.inc("delta.compaction.merged", merged)
        _obs.inc("delta.compaction.bytes", nbytes)
        _obs.inc("delta.swap.versions")
        _latency.observe("lat.delta.compaction",
                         (time.perf_counter_ns() - t0) / 1e6)
        _obs.event("delta.compaction", merged=merged, version=version,
                   nnz=new_src.nnz, bytes=nbytes, dist=True)
        return merged

    def _merged_src(self, src):
        """Fresh canonical source = source entries overridden by
        buffered targets (0.0 deletes) — the same merge the local
        wrapper runs, so a compacted distributed matrix equals a cold
        ``shard_csr`` of the mutated source."""
        from ..csr import csr_array

        brows, bcols, bdata = (np.asarray(a) for a in
                               src._coo_parts())
        merged: Dict[Tuple[int, int], float] = {
            (int(r), int(c)): v
            for r, c, v in zip(brows, bcols, bdata)
        }
        for key, (target, _d) in self._buffer.entries.items():
            if target == 0.0:
                merged.pop(key, None)
            else:
                merged[key] = target
        keys = sorted(merged)
        rows = np.asarray([k[0] for k in keys], dtype=np.int64)
        cols = np.asarray([k[1] for k in keys], dtype=np.int64)
        vals = np.asarray([merged[k] for k in keys], dtype=src.dtype)
        return csr_array((vals, (rows, cols)), shape=src.shape,
                         dtype=src.dtype)

    def _watermark_slots(self) -> int:
        frac = max(float(_settings.delta_watermark), 0.0)
        return max(int(frac * self._buffer.capacity), 1)

    def maybe_compact(self) -> int:
        """Compact iff the watermark is exceeded."""
        if self._buffer.pending >= self._watermark_slots():
            return self.compact()
        return 0

    def _refresh_image(self) -> None:
        """Rebuild the device buffer snapshot (callers hold the
        lock).  Sentinel row = ``rows_padded`` so padded slots drop
        out of the ``rows_padded``-segment sum."""
        if self._buffer.pending == 0:
            self._image = None
            return
        rid, cid, dvals, valid = self._buffer.device_image(
            self._base.dtype, sentinel_row=self._base.rows_padded)
        self._image = (rid, cid, dvals, valid)

    # ---------------- reshard carry (the ride-along bugfix) -------

    def _delta_reshard_carry(self, mesh, layout):
        """``reshard()`` hook: repartition the base and CARRY the
        pending buffer — never silently drop updates.  Additive
        deltas are base-relative and the repartition preserves the
        logical base, so the buffer transfers verbatim; the routing
        scatter onto the new row partition is re-priced."""
        from ..parallel.reshard import reshard as _reshard

        with self._lock:
            new_base = _reshard(self._base, mesh=mesh, layout=layout)
            if new_base is self._base:
                return self  # same placement — zero-byte fast path
            out = DistDeltaCSR(new_base,
                               capacity=self._buffer.capacity)
            out._buffer.entries.update(self._buffer.entries)
            out._version = self._version
            out._refresh_image()
        if out._buffer.pending:
            rec_bytes = 2 * 4 + np.dtype(out._base.dtype).itemsize
            _comm.record(
                "delta",
                {"scatter": out._buffer.pending * rec_bytes},
                calls={"scatter": 1}, layout=out._base.layout)
            _obs.event("delta.reshard_carry",
                       pending=out._buffer.pending,
                       version=out._version)
        return out

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"DistDeltaCSR(v{self._version}, "
                f"pending={self.pending}/{self.capacity}, "
                f"shape={self.shape}, "
                f"shards={self._base.num_shards})")
