# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""DIA (diagonal) format arrays.

Parity with the reference's ``dia_array`` (reference:
``legate_sparse/dia.py:65-190``): storage is a 2-D ``data`` array of
shape (num_diags, cols) plus a 1-D ``offsets`` array, with scipy's
layout convention ``A[j - offset[k], j] = data[k, j]``.

The DIA format is the TPU-sweet-spot representation for the banded
matrices the benchmarks use: SpMV in DIA is a sum of statically-shifted
elementwise products — no gathers at all (``ops/dia_ops.py``, wired
into ``dia_array.dot``).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

import jax.numpy as jnp

from .base import CompressedBase
from .types import check_nnz, coord_dtype_for, index_dtype, nnz_dtype
from .runtime import runtime


def _band_slot_gather(data, offs, extent: int):
    """Diagonal-realignment gather over scipy's column-aligned band
    layout: ``gathered[d, p] = data[d, p + offs[d]]`` when that source
    column is in ``[0, width)``, else 0.  Returns ``(gathered, valid,
    src)`` — shared by ``transpose`` (p = column of A.T) and ``tocsr``
    (p = row, so src is the CSR column index), so the clamp/mask
    semantics live in exactly one place."""
    num_d, width = data.shape
    src = jnp.arange(extent)[None, :] + offs[:, None]
    valid = (src >= 0) & (src < width)
    gathered = jnp.where(
        valid,
        data[jnp.arange(num_d)[:, None], jnp.clip(src, 0, width - 1)],
        jnp.zeros((), dtype=data.dtype),
    )
    return gathered, valid, src


class dia_array(CompressedBase):
    """Sparse matrix with DIAgonal storage, backed by jax.Arrays."""

    format = "dia"

    def __init__(self, arg, shape=None, dtype=None, copy: bool = False):
        if isinstance(arg, dia_array):
            data, offsets = arg.data, arg.offsets
            shape = arg.shape if shape is None else tuple(shape)
        elif isinstance(arg, tuple) and len(arg) == 2:
            data_in, offsets_in = arg
            data = jnp.atleast_2d(jnp.asarray(data_in))
            offsets = jnp.atleast_1d(
                jnp.asarray(offsets_in, dtype=index_dtype())
            )
            if shape is None:
                raise ValueError("dia_array from (data, offsets) needs shape")
        else:
            raise NotImplementedError(
                "dia_array supports (data, offsets) or dia_array inputs; "
                "use csr_array for dense/scipy sources"
            )
        if dtype is not None:
            data = data.astype(np.dtype(dtype))
        elif data.dtype == np.float16:
            data = data.astype(runtime.default_float)
        if copy:
            data = jnp.array(data)
            offsets = jnp.array(offsets)
        if int(offsets.shape[0]) != int(data.shape[0]):
            raise ValueError("number of diagonals != number of offsets")
        if len(set(np.asarray(offsets).tolist())) != offsets.shape[0]:
            raise ValueError("offset array contains duplicate values")
        self._data = data
        self._offsets = offsets
        self._pack = None  # cached Pallas band pack (built lazily)
        self.shape: Tuple[int, int] = tuple(int(s) for s in shape)

    @property
    def dtype(self) -> np.dtype:
        return np.dtype(self._data.dtype)

    @property
    def data(self):
        return self._data

    @property
    def offsets(self):
        return self._offsets

    @property
    def nnz(self) -> int:
        """Count of stored values inside the matrix bounds, computed
        analytically from offsets (reference ``dia.py:90-99``)."""
        rows, cols = self.shape
        offs = np.asarray(self._offsets)
        # diagonal k has min(rows + min(k,0), cols - max(k,0)) in-bounds slots
        lengths = np.minimum(rows + np.minimum(offs, 0), cols - np.maximum(offs, 0))
        return int(np.maximum(lengths, 0).sum())

    def copy(self):
        return dia_array((self._data, self._offsets), shape=self.shape,
                         copy=True)

    def _with_data(self, data, copy: bool = False):
        return type(self)((data, self._offsets), shape=self.shape,
                          copy=copy)

    def astype(self, dtype, casting: str = "unsafe", copy: bool = True):
        dtype = np.dtype(dtype)
        if self.dtype != dtype:
            return self._with_data(self._data.astype(dtype), copy=copy)
        return self.copy() if copy else self

    def transpose(self, axes=None, copy: bool = False):
        """Transpose by realigning each diagonal (reference
        ``dia.py:114-148`` fancy-index realignment, vectorized here).

        In the transposed matrix, diagonal k becomes diagonal -k; scipy's
        column-aligned layout means entry (i, j)=data[k, j] moves to
        data'[-k, i] with i = j - k.
        """
        if axes is not None:
            raise ValueError("axes parameter not supported")
        rows, cols = self.shape
        max_dim = max(rows, cols)
        offs = self._offsets
        # new_data[d, j'] = data[d, j' + offset[d]] for j' = column in A.T
        gathered, _, _ = _band_slot_gather(self._data, offs, max_dim)
        return dia_array(
            (gathered, -offs), shape=(cols, rows)
        )

    @property
    def T(self):
        return self.transpose()

    def todia(self, copy: bool = False):
        return self.copy() if copy else self

    def toscipy(self):
        """Host scipy ``dia_array`` (format-preserving)."""
        import numpy as _np

        import scipy.sparse as _sp

        return _sp.dia_array(
            (_np.asarray(self.data), _np.asarray(self.offsets)),
            shape=self.shape,
        )

    def tocsr(self, copy: bool = False):
        """DIA -> CSR, sort-free.

        The reference routes through a transpose and a masked-cumsum CSC
        build (``dia.py:152-190``, scipy's DIA->CSC algorithm).  No
        global sort is ever needed: distinct offsets mean distinct
        columns within a row, and with offsets pre-sorted ascending
        (host-side, num_d elements) a row-major flatten of the
        (row, diag) slot grid IS CSR order — one mask + one compacting
        gather replaces the previous two-key ``lax.sort`` over every
        band slot (184M elements at the 2^24 bench size, the largest
        single device op in the banded build path).
        """
        from .csr import csr_array

        rows, cols = self.shape
        num_d, width = self._data.shape
        w = min(width, cols)
        cdt = coord_dtype_for(max(rows, cols) + 1)
        order = np.argsort(np.asarray(self._offsets), kind="stable")
        if np.array_equal(order, np.arange(num_d)):
            offs, data = self._offsets.astype(cdt), self._data
        else:   # gather copies the whole band; skip when already sorted
            offs = self._offsets.astype(cdt)[jnp.asarray(order)]
            data = self._data[jnp.asarray(order)]
        # scipy DIA storage is column-aligned: data[d, col] holds
        # A[col - off_d, col].
        vals, _, col = _band_slot_gather(data, offs, rows)
        keep = (col >= 0) & (col < w) & (vals != 0)  # scipy drops zeros
        nnz = int(jnp.sum(keep))
        check_nnz(nnz)
        idx = jnp.nonzero(keep.T.reshape(-1), size=nnz, fill_value=0)[0]
        cdata = vals.T.reshape(-1)[idx]
        cindices = col.T.reshape(-1)[idx].astype(cdt)
        # indptr counts nnz, not coordinates: platform-width ints
        # (int64 under x64, else int32 with the documented 2^31-1
        # per-process nnz limit — check_nnz above fails loudly first).
        counts = jnp.sum(keep, axis=0, dtype=nnz_dtype())
        cindptr = jnp.concatenate(
            [jnp.zeros((1,), dtype=nnz_dtype()), jnp.cumsum(counts)]
        )
        return csr_array._from_parts(
            cdata, cindices, cindptr, self.shape
        )

    # ---------------- products (DIA fast path) ----------------
    def _get_pack(self):
        """Cached Pallas band pack (same layout/dispatch as csr's
        ``_get_dia_pack``; DIA has no holes, so the pack is unmasked —
        every in-bounds slot is an entry, matching ``dia_spmv``)."""
        from .csr import csr_array
        from .ops import pallas_dia

        if self._pack is not None:
            return self._pack if self._pack is not False else None
        if not csr_array._can_build_cache(self._data):
            return None
        offsets = tuple(int(o) for o in np.asarray(self._offsets))
        packed = pallas_dia.pack_band(self._data, offsets, self.shape)
        self._pack = packed if packed is not None else False
        return packed

    def dot(self, other, out=None):
        """SpMV/SpMM via the Mosaic band kernel on TPU (same dispatch
        as csr's banded path), else shifted adds (``ops/dia_ops.py``);
        sparse operands route through CSR."""
        from .ops.dia_ops import dia_spmm, dia_spmv
        from .ops.pallas_dia import (
            SPMM_MAX_K, dia_spmm_maybe_pallas, dia_spmv_maybe_pallas,
            pallas_dia_active,
        )
        from .utils import fill_out, require_supported_dtype

        require_supported_dtype(self.dtype)
        from .utils import is_sparse_matrix

        if is_sparse_matrix(other):
            return self.tocsr().dot(other)
        other = jnp.asarray(other)
        offsets = tuple(int(o) for o in np.asarray(self._offsets))
        squeeze = False
        if other.ndim == 2 and other.shape[1] == 1:
            other = other.reshape(-1)
            squeeze = True
        if other.ndim == 1:
            if other.shape[0] != self.shape[1]:
                raise ValueError(
                    f"dimension mismatch: {self.shape} @ {other.shape}"
                )
            y = (dia_spmv_maybe_pallas(self._get_pack(), other)
                 if (pallas_dia_active()
                     and other.dtype == self._data.dtype) else None)
            if y is None:
                y = dia_spmv(self._data, other, offsets, self.shape)
            if squeeze:
                y = y[:, None]
            return fill_out(y, out)
        if other.ndim == 2:
            if other.shape[0] != self.shape[1]:
                raise ValueError(
                    f"dimension mismatch: {self.shape} @ {other.shape}"
                )
            Y = (dia_spmm_maybe_pallas(self._get_pack(), other)
                 if (pallas_dia_active()
                     and 0 < other.shape[1] <= SPMM_MAX_K
                     and other.dtype == self._data.dtype) else None)
            if Y is None:
                Y = dia_spmm(self._data, other, offsets, self.shape)
            return fill_out(Y, out)
        raise ValueError(f"cannot multiply dia_array by ndim={other.ndim}")

    def __matmul__(self, other):
        return self.dot(other)

    def todense(self, order=None, out=None):
        return self.tocsr().todense(order=order, out=out)

    toarray = todense

    def __repr__(self) -> str:
        return (
            f"<{self.shape[0]}x{self.shape[1]} sparse array of type "
            f"'{self.dtype}' with {self.nnz} stored elements "
            f"({self._data.shape[0]} diagonals) in DIAgonal format>"
        )


class dia_matrix(dia_array):
    _is_spmatrix = True
    def __pow__(self, n):
        # spmatrix semantics: matrix power.
        from .csr import csr_matrix

        out = (csr_matrix(self.tocsr()) ** n).asformat("dia")
        out.__class__ = type(self)   # keep the matrix flavor
        return out

    pass
