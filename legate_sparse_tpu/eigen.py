# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Device-native sparse eigensolvers: ``eigs``, ``eigsh``, ``lobpcg``,
``svds``.

The reference's linalg surface stops at cg/gmres (its ``linalg.py`` has
no eigensolvers); this package's scipy-compatibility layer previously
served the eigensolver names through host scipy at the module
boundary.  These are the native TPU paths for the common cases:

- ``eigs``: non-symmetric restarted Arnoldi — the full Hessenberg
  recurrence (MGS applied twice) as one jitted ``lax.scan``, real
  arithmetic for real operators; only the small (m, m) ``eig`` runs on
  host.
- ``eigsh``: m-step Lanczos with full reorthogonalization.  The matvec
  chain runs as one jitted ``lax.scan`` on device (SpMV is the hot op);
  only the m x m tridiagonal eigenproblem is solved on host (O(m^2)
  scalar work, m ~ tens — MXU-irrelevant by design).
- ``lobpcg``: blocked Rayleigh-Ritz iteration via
  ``jax.experimental.sparse.linalg.lobpcg_standard`` (all block matmuls
  and the 3k x 3k dense eigensolves stay on device).
- ``svds``: Lanczos on the Gram operator ``v -> A^T (A v)`` (never
  materializes A^T A — two SpMVs per step); left vectors recovered as
  ``U = A V / s``.

Shift-invert ``sigma`` runs NATIVELY (VERDICT r4 #6): the inner
``(A - sigma I)^{-1} v`` apply is an inexact Krylov solve — the
package's jitted MINRES while_loop for symmetric/Hermitian operators
(indefinite-safe), BiCGSTAB for general ones — nested inside the same
Lanczos/Arnoldi ``lax.scan``, so the whole outer-inner iteration
compiles to ONE device program (where scipy/ARPACK factorizes with
``splu`` — a sequential host path with no TPU analog, this is the
device-native rendition).  Complex-Hermitian ``lobpcg`` likewise runs
through the native Lanczos machinery (jax's ``lobpcg_standard`` builds
mixed real/complex while_loop carries on complex operands).

``which='SM'`` (eigsh and eigs) also runs natively — shift-invert at
sigma=0 — with a probe solve that detects a singular/ill-conditioned
operator up front and falls back to host ARPACK's direct mode (an
inexact inverse would otherwise silently drop null-space eigenvalues).

Generalized symmetric pencils run natively too: ``eigsh(A, M=M)`` and
``lobpcg(A, X, B=B)`` (M/B SPD) use an M-inner-product Lanczos whose
basis recurrence, inner ``M^{-1}`` CG solves and
M-reorthogonalization compile as one ``lax.scan`` program
(``_lanczos_general`` — ARPACK mode 2's device rendition), guarded by
an M-solve probe and a pencil-residual acceptance test.

``svds(which='SM')`` runs the same shift-invert-at-0 machinery on the
Gram operator, and the ``buckling``/``cayley`` shift-invert modes
(ARPACK 4/5) run through the same B-inner Lanczos with their own
inner-product matrices and back-transforms.  Generalized
non-symmetric ``eigs(M=...)`` — with or without sigma — runs Arnoldi
on ``M^{-1} A`` / ``(A - sigma M)^{-1} M`` with the same inner-solve
and guard machinery.

Remaining host-fallback corners: preconditioned/constrained lobpcg
and complex lobpcg past 32k rows.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["eigs", "eigsh", "lobpcg", "svds"]


def _operator_parts(A):
    """(matvec, n_rows, n_cols, dtype) for a sparse array, dense array,
    LinearOperator, or scipy sparse operand."""
    from .linalg import LinearOperator, make_linear_operator

    if isinstance(A, LinearOperator):
        op = A
    else:
        op = make_linear_operator(A)
    m, n = op.shape
    dtype = op.dtype
    if dtype is None:
        op._init_dtype()
        dtype = op.dtype
    return op.matvec, int(m), int(n), np.dtype(dtype)


def _host_fallback(name):
    import scipy.sparse.linalg as _ssl

    from .coverage import scipy_fallback

    return scipy_fallback(getattr(_ssl, name), f"linalg.{name}")


def _complex_matvec(matvec, dtype, cdtype):
    """Complex basis over a REAL operator: two real matvecs per apply
    (shared by ``eigs``'s complex-start case and the complex-shift
    shift-invert path)."""

    def mv(x):
        return (matvec(jnp.real(x).astype(dtype)).astype(cdtype)
                + 1j * matvec(jnp.imag(x).astype(dtype)).astype(cdtype))

    return mv


def _restart_direction(V, key0, j, n, rdtype, dtype, mask=None):
    """Fresh random direction orthogonal to the rows of V — the shared
    breakdown restart for the Lanczos and Arnoldi scans (an invariant
    subspace was found; the zero vector would fabricate spectrum)."""
    eps = jnp.finfo(rdtype).eps
    fresh = jax.random.normal(jax.random.fold_in(key0, j), (n,),
                              rdtype).astype(dtype)
    if mask is not None:
        fresh = fresh * mask
    for _ in range(2):
        fresh = fresh - V.T @ (jnp.conj(V) @ fresh)
    return fresh / jnp.maximum(jnp.linalg.norm(fresh), eps)


def _outer_atol(tol, rdtype):
    """Default convergence tolerance (single source for the escalation
    drivers AND the shift-invert inner-solve sizing)."""
    return float(tol) if tol else float(np.finfo(rdtype).eps ** 0.5)


def _validate_be_k(which, k):
    """scipy/ARPACK parity shared by eigsh and dist_eigsh: NEV=1 with
    BE is info=-13; returning a single high-end value would silently
    alias which='LA'."""
    if which == "BE" and k < 2:
        from scipy.sparse.linalg import ArpackError

        raise ArpackError(
            -13, {-13: "NEV and WHICH = 'BE' are incompatible."})


def _require_real_sigma(sigma):
    """scipy parity: float(sigma) raises on ANY complex (even with a
    zero imaginary part) — a Hermitian spectrum is real."""
    if np.iscomplexobj(sigma):
        raise TypeError(
            "eigsh sigma must be a real number, not complex")


def _escalation_params(tol, rdtype, ncv, k, rank, maxiter,
                       min_extra: int = 1):
    """Shared host-side escalation knobs for the eigsh/eigs drivers:
    (atol, first subspace size m, retry count)."""
    atol = _outer_atol(tol, rdtype)
    m = int(ncv) if ncv is not None else min(rank, max(2 * k + 1, 20))
    m = min(max(m, k + min_extra), rank)
    tries = max(int(maxiter) if maxiter is not None else 6, 1)
    return atol, m, tries


def _require_converged(resid, atol, scale, m, cap, w_k, X=None):
    """scipy parity on escalation exhaustion: raise
    ``ArpackNoConvergence`` (carrying the converged subset) instead of
    silently returning unconverged Ritz pairs.  ``m >= cap`` means the
    Krylov space is the whole (masked) space — exact up to roundoff,
    never an error."""
    ok = resid <= atol * scale
    if bool(np.all(ok)) or m >= cap:
        return
    from scipy.sparse.linalg import ArpackNoConvergence

    raise ArpackNoConvergence(
        f"ARPACK-style error: no convergence "
        f"({int(ok.sum())}/{ok.size} eigenvalues converged; "
        f"subspace m={m}, cap={cap})",
        np.asarray(w_k)[ok],
        (np.asarray(X)[:, ok] if X is not None
         else np.empty((0, int(ok.sum())))),
    )


# ----------------------------------------------------- shift-invert inner


def _shift_invert_op(matvec, sigma, dtype, n, outer_atol, sym: bool):
    """Jax-traceable ``v -> (A - sigma I)^{-1} v`` via an inexact inner
    Krylov solve (MINRES for symmetric/Hermitian — A - sigma I is
    indefinite for interior sigma; BiCGSTAB for general operators).

    The returned closure nests inside the outer Lanczos/Arnoldi
    ``lax.scan``, so outer+inner compile to one device program.  The
    operands fed to it by the outer recurrences are unit-norm, so a
    fixed absolute inner tolerance (two digits tighter than the outer
    Ritz tolerance, floored near eps) bounds the backward error of the
    inexact apply below the outer convergence test's resolution.
    """
    from .krylov_extra import _minres_loop
    from .linalg import _bicgstab_loop

    rdtype = jnp.finfo(jnp.dtype(dtype)).dtype
    inner_atol, inner_maxiter = _inner_solver_params(outer_atol, rdtype,
                                                     n)
    shift = jnp.asarray(sigma, dtype=dtype)
    ident = lambda r: r  # noqa: E731

    if sym:
        def solve(v):
            v = jnp.asarray(v, dtype=dtype)
            x, _ = _minres_loop(matvec, ident, v, jnp.zeros_like(v),
                                shift, inner_atol, inner_maxiter, 10)
            return x
    else:
        def shifted(x):
            return matvec(x) - shift * x

        def solve(v):
            v = jnp.asarray(v, dtype=dtype)
            x, _ = _bicgstab_loop(shifted, ident, v, jnp.zeros_like(v),
                                  inner_atol, inner_maxiter, 10)
            return x

    return solve, inner_atol


def _probe_inverse(matvec, solve, sigma, dtype, n, inner_atol, name,
                   mask=None):
    """One explicit (A - sigma I)x = v solve with a TRUE residual check
    before any Lanczos/Arnoldi runs.

    This is the honesty gate a Krylov inner solve owes the caller that
    an exact splu factorization does not need: on a SINGULAR (A - sigma
    I) the iterative solve converges to a pseudo-inverse apply whose
    Ritz pairs are genuine eigenpairs of A — they pass every residual
    test — while silently MISSING the null-space eigenvalue nearest
    sigma (found empirically: eigsh(diag(0..n), which='SM') returned
    [1, 2], not [0, 1]).  A stagnated probe residual is the observable
    signature; raise ``ArpackNoConvergence`` so sigma callers surface
    it and the SM route falls back to host ARPACK's direct mode."""
    shift = jnp.asarray(sigma, dtype=dtype)
    _probe_apply(lambda x: matvec(x) - shift * x, solve, n, dtype,
                 inner_atol, f"shift-invert {name}", mask=mask)


def _check_original_residuals(matvec, lam, X, atol, name):
    """Post-hoc guard for the inexact shift-invert paths: judge the
    returned Ritz pairs in the ORIGINAL operator's metric (k matvecs).
    A stagnated inner solve (sigma pathologically close to an
    eigenvalue for the iterative inner tolerance) corrupts OP silently;
    the outer recurrence then converges *on the corrupted operator*, so
    this original-spectrum check is the only honest acceptance test —
    scipy/ARPACK's splu factorization is exact and needs none.  Raises
    ``ArpackNoConvergence`` (carrying the converged subset) like scipy
    does on its own convergence failures."""
    Xj = jnp.asarray(X)
    AX = np.asarray(jax.vmap(matvec, in_axes=1, out_axes=1)(Xj))
    resid = np.linalg.norm(AX - np.asarray(X) * lam[None, :], axis=0)
    scale = np.maximum(np.abs(lam), 1.0)
    # Slack x50: the inner solve is inexact by design (inner_atol is
    # 1e-2 * atol); this bound rejects stagnation (errors orders of
    # magnitude out), not honest last-digit noise.
    ok = resid <= 50.0 * atol * scale
    if bool(np.all(ok)):
        return
    from scipy.sparse.linalg import ArpackNoConvergence

    raise ArpackNoConvergence(
        f"shift-invert {name}: inexact inner solve did not reach the "
        f"requested accuracy ({int(ok.sum())}/{ok.size} pairs pass the "
        f"original-spectrum residual test; sigma may be too close to "
        f"an eigenvalue for the iterative inner solver — widen sigma "
        f"or loosen tol)",
        np.asarray(lam)[ok], np.asarray(X)[:, ok],
    )


# ---------------------------------------------------------------- Lanczos


def _lanczos_general(matvec_a, matvec_m, solve_m, v0, m: int,
                     si: bool = False, rhs_fn=None):
    """m-step B-inner-product Lanczos for the generalized symmetric
    problem — ARPACK modes 2-5 re-designed for the device: the basis
    recurrence, the inner Krylov solves, and the full
    B-reorthogonalization all live in ONE ``lax.scan`` (one compiled
    program, no per-step dispatch).  ``matvec_m`` is the inner-product
    matrix B (M for modes 2/3/5, A for buckling).

    ``si=False`` (mode 2): the operator is ``M^{-1} A`` and ``solve_m``
    solves with M.  ``si=True`` (shift-invert family): the operator is
    ``(A - sigma M)^{-1} rhs(v)`` where ``rhs_fn`` defaults to the
    inner-product matvec (modes 3/4) or is ``(A + sigma M) v`` for
    cayley (mode 5); T then approximates the mode's transformed
    spectrum ``nu``.

    Returns (V, alphas, betas): V has B-orthonormal rows
    (``V B V^H = I``) and T = tridiag(betas[1:], alphas, betas[1:])
    holds the Ritz approximation of the operator's spectrum in the
    B-inner product.
    """
    n = v0.shape[0]
    dtype = v0.dtype
    rdtype = jnp.finfo(dtype).dtype
    eps = jnp.finfo(rdtype).eps
    key0 = jax.random.PRNGKey(23)

    def m_reorth(V, w):
        # w -= V^T <V, w>_M, applied twice (classical GS, Parlett).
        for _ in range(2):
            q = matvec_m(w)
            w = w - V.T @ (jnp.conj(V) @ q)
        return w

    def m_normalize(w):
        nrm = jnp.sqrt(jnp.maximum(
            jnp.real(jnp.vdot(w, matvec_m(w))), 0)).astype(rdtype)
        return w / jnp.where(nrm == 0, 1.0, nrm).astype(dtype), nrm

    def step(carry, j):
        V, v, beta, v_prev = carry
        if si:
            mv = matvec_m(v)
            rhs = mv if rhs_fn is None else rhs_fn(v)
            w = solve_m(rhs)                  # (A - sigma M)^{-1} rhs
            # <v, OP v>_B = (B v)^H w (B Hermitian).
            alpha = jnp.real(jnp.vdot(mv, w)).astype(dtype)
        else:
            av = matvec_a(v)
            w = solve_m(av)                   # M^{-1} A v
            alpha = jnp.real(jnp.vdot(v, av)).astype(dtype)  # <v, Av>
        w = w - alpha * v - beta * v_prev
        V = V.at[j].set(v)
        w = m_reorth(V, w)
        w, beta_next = m_normalize(w)
        broke = beta_next <= 100 * eps * jnp.maximum(
            jnp.abs(jnp.real(alpha)), 1.0)
        fresh = jax.random.normal(
            jax.random.fold_in(key0, j), (n,), rdtype).astype(dtype)
        fresh = m_reorth(V, fresh)
        fresh, _ = m_normalize(fresh)
        beta_out = jnp.where(broke, jnp.zeros((), rdtype), beta_next)
        v_next = jnp.where(broke, fresh, w)
        return (V, v_next, beta_out.astype(dtype), v), (
            alpha, beta_out.astype(dtype))

    V0 = jnp.zeros((m, n), dtype=dtype)
    (V, _, _, _), (alphas, betas) = jax.lax.scan(
        step, (V0, v0, jnp.zeros((), dtype), jnp.zeros_like(v0)),
        jnp.arange(m, dtype=jnp.int32))
    return V, alphas, betas


def _inner_solver_params(outer_atol: float, rdtype, n: int):
    """Shared inner-Krylov sizing for every inexact-inverse path
    (shift-invert, generalized pencil): (absolute atol for a UNIT-NORM
    rhs, iteration cap)."""
    eps = float(np.finfo(np.dtype(rdtype)).eps)
    return (max(1e-2 * float(outer_atol), 50.0 * eps),
            int(min(10 * n + 20, 100_000)))


def _select_sym_ritz(w, y, k: int, which: str):
    """Shared LA/SA/LM/SM/BE Ritz selection for the symmetric drivers
    (ascending-eigenvalue output order, scipy convention).  Under
    shift-invert the caller passes the TRANSFORMED spectrum, so SM
    there means smallest |nu| = farthest from sigma — exactly ARPACK's
    semantics."""
    if which == "LA":
        sel = np.argsort(w)[-k:]
    elif which == "SA":
        sel = np.argsort(w)[:k]
    elif which == "SM":
        sel = np.argsort(np.abs(w))[:k]
    elif which == "BE":
        # scipy: k/2 from each end, the extra one from the HIGH end.
        lo = k // 2
        order = np.argsort(w)
        sel = np.concatenate([order[:lo], order[lo - k:]])
    else:  # LM
        sel = np.argsort(np.abs(w))[-k:]
    sel = sel[np.argsort(w[sel])]
    return w[sel], y[:, sel]


def _normalized_rhs_solver(solve_unit):
    """Wrap a unit-rhs inner solver so its absolute tolerance applies
    RELATIVE to each right-hand side's norm.  The generalized apply's
    rhs is A v or M v with norm ~||A||/||M|| — NOT the unit norm of the
    standard shift-invert recurrences' operands — so an absolute inner
    tolerance would silently lose digits on small-norm pencils (found
    by review with a 1e-6-scaled operator repro) and never be reachable
    on large-norm ones."""

    def solve(b):
        nrm = jnp.linalg.norm(b)
        safe = jnp.where(nrm == 0, 1.0, nrm).astype(b.dtype)
        return solve_unit(b / safe) * safe

    return solve


def _probe_apply(apply_fn, solve, n, dtype, inner_atol, what,
                 mask=None):
    """One explicit solve of ``apply_fn(x) = v`` with a TRUE residual
    check before any recurrence runs — the honesty gate every inexact
    inner solve owes its caller (see ``_probe_inverse``): a stagnating
    probe means the operator is singular or too ill-conditioned for
    the iterative inner solver, in which case silent pseudo-inverse
    behavior would drop eigenvalues without failing any residual test.
    Returns the probe RNG so callers draw consistent start vectors.
    ``mask`` restricts the probe to the valid subspace (distributed
    padded operators: the padding block of A - sigma*I is singular at
    sigma=0 by construction, which must not trip the gate)."""
    rng = np.random.default_rng(20260801)
    v = jnp.asarray(rng.standard_normal(n), dtype=dtype)
    if mask is not None:
        v = v * mask
    v = v / jnp.linalg.norm(v)
    x = solve(v)
    res = float(jnp.linalg.norm(apply_fn(x) - v))
    if not np.isfinite(res) or res > 100.0 * inner_atol:
        from scipy.sparse.linalg import ArpackNoConvergence

        raise ArpackNoConvergence(
            f"{what}: inner solve stagnated at residual {res:.2e} "
            f"(target {inner_atol:.2e}) — operator singular or too "
            f"ill-conditioned for the iterative inner solver",
            np.empty(0), np.empty((n, 0)))
    return rng


def _m_normalized_start(v0, matvec_m, dtype, n, rng):
    """Start vector for the M-inner recurrences, M-normalized."""
    if v0 is None:
        v0 = rng.standard_normal(n)
    v0 = jnp.asarray(v0, dtype=dtype)
    mnrm = float(np.sqrt(max(
        float(jnp.real(jnp.vdot(v0, matvec_m(v0)))), 1e-300)))
    return v0 / v0.dtype.type(mnrm)


def _general_lanczos_drive(matvec_a, matvec_m, solve, si, v0, k, which,
                           ncv, maxiter, tol, rank, rdtype, dtype,
                           rhs_fn=None):
    """Shared escalation loop for the generalized modes 2-5: returns
    ``(w_k, X, resid, atol, scale, m)`` (w_k in the operator's own
    spectrum — pencil eigenvalues for mode 2, the mode's transformed
    nu otherwise)."""
    import scipy.linalg as _sl

    from .linalg import maybe_jit

    lanczos = maybe_jit(_lanczos_general, static_argnums=(0, 1, 2),
                        static_argnames=("m", "si", "rhs_fn"))
    atol, m, tries = _escalation_params(tol, rdtype, ncv, k, rank,
                                        maxiter)
    for try_i in range(tries):
        if try_i:
            m = min(rank, 2 * m)
        V, alphas, betas = lanczos(matvec_a, matvec_m, solve, v0, m=m,
                                   si=si, rhs_fn=rhs_fn)
        a = np.real(np.asarray(alphas)).astype(np.float64)
        b_all = np.real(np.asarray(betas)).astype(np.float64)
        w, y = _sl.eigh_tridiagonal(a, b_all[:-1])
        w_k, y_k = _select_sym_ritz(w, y, k, which)
        resid = np.abs(b_all[-1]) * np.abs(y_k[-1, :])
        # Relative scale with a SPECTRUM-magnitude floor (not the
        # absolute 1.0 of the standard driver): a pencil scaled by
        # 1e-6 must get 1e-6-scaled acceptance, else inexact digits
        # pass silently.
        floor = max(float(np.max(np.abs(w))), np.finfo(rdtype).tiny)
        scale = np.maximum(np.abs(w_k), floor)
        if np.all(resid <= atol * scale) or m >= rank:
            break
    X = np.asarray(jnp.einsum(
        "mn,mk->nk", V, jnp.asarray(y_k, dtype=dtype)))
    return w_k, X, resid, atol, scale, m


def _eigsh_generalized(matvec_a, matvec_m, n, dtype, k, which, v0, ncv,
                       maxiter, tol, return_eigenvectors,
                       max_rank=None):
    """Native generalized ``eigsh(A, M=M)`` (ARPACK mode 2): M-inner
    Lanczos on ``M^{-1} A`` with an inexact jitted inner CG solve.
    ``max_rank`` bounds the escalated basis (the lobpcg-B route passes
    its O(max(8k,128)) memory cap)."""
    rdtype = np.dtype(np.finfo(dtype).dtype)
    atol_outer = _outer_atol(tol, rdtype)
    inner_atol, inner_maxiter = _inner_solver_params(atol_outer, rdtype,
                                                    n)
    from .linalg import _cg_loop

    ident = lambda r: r  # noqa: E731
    solve_m = _normalized_rhs_solver(
        lambda b: _cg_loop(matvec_m, ident, b, jnp.zeros_like(b),
                           inner_atol, inner_maxiter, 10)[0])
    # Probe: M must be solvable to the inner tolerance (SPD and
    # nonsingular), else the whole pencil transform is untrustworthy.
    rng = _probe_apply(matvec_m, solve_m, n, dtype, inner_atol,
                       "generalized eigsh")
    v0 = _m_normalized_start(v0, matvec_m, dtype, n, rng)
    rank = int(max_rank) if max_rank is not None else n
    w_k, X, resid, atol, scale, m = _general_lanczos_drive(
        matvec_a, matvec_m, solve_m, False, v0, k, which, ncv, maxiter,
        tol, rank, rdtype, dtype)
    w_k = w_k.astype(rdtype)
    _pencil_residual_guard(matvec_a, matvec_m, w_k, X, atol_outer,
                           rdtype)
    _require_converged(resid, atol, scale, m, rank, w_k, X)
    if not return_eigenvectors:
        return w_k
    return w_k, X


def _pencil_residual_guard(matvec_a, matvec_m, w_k, X, atol_outer,
                           rdtype):
    """Original-PENCIL residual guard (the inexact-inner honesty test,
    shared by modes 2 and 3): ``||A x - lambda M x||`` judged RELATIVE
    to the pencil's own magnitude per pair."""
    AX = np.asarray(jax.vmap(matvec_a, in_axes=1, out_axes=1)(
        jnp.asarray(X)))
    MX = np.asarray(jax.vmap(matvec_m, in_axes=1, out_axes=1)(
        jnp.asarray(X)))
    res_p = np.linalg.norm(AX - MX * w_k[None, :], axis=0)
    denom = np.maximum.reduce([
        np.linalg.norm(AX, axis=0),
        np.abs(w_k) * np.linalg.norm(MX, axis=0),
        np.full(res_p.shape, np.finfo(rdtype).tiny),
    ])
    ok = res_p / denom <= 50.0 * atol_outer
    if not bool(np.all(ok)):
        from scipy.sparse.linalg import ArpackNoConvergence

        raise ArpackNoConvergence(
            f"generalized eigsh: {int(ok.sum())}/{ok.size} pairs pass "
            f"the pencil residual test", w_k[ok], X[:, ok])


def _eigsh_generalized_si(matvec_a, matvec_m, sigma: float, n, dtype,
                          k, which, v0, ncv, maxiter, tol,
                          return_eigenvectors, mode: str = "normal"):
    """Native generalized shift-invert (ARPACK modes 3/4/5):
    B-inner-product Lanczos on the mode's operator with an inexact
    jitted MINRES inner solve of the (symmetric indefinite) shifted
    pencil ``A - sigma M``.  ``which`` applies to the transformed
    spectrum ``nu`` (scipy semantics); results transform back and
    return ascending.

    ========  =========================  ==========  ====================
    mode      operator                   B (inner)   back-transform
    ========  =========================  ==========  ====================
    normal    (A - sM)^{-1} M            M           s + 1/nu
    buckling  (A - sM)^{-1} A            A           s*nu / (nu - 1)
    cayley    (A - sM)^{-1} (A + sM)     M           s*(nu+1) / (nu-1)
    ========  =========================  ==========  ====================
    """
    from .krylov_extra import _minres_loop

    rdtype = np.dtype(np.finfo(dtype).dtype)
    atol_outer = _outer_atol(tol, rdtype)
    inner_atol, inner_maxiter = _inner_solver_params(atol_outer, rdtype,
                                                    n)
    ident = lambda r: r  # noqa: E731
    sig = jnp.asarray(sigma, dtype=dtype)

    def shifted(x):
        return matvec_a(x) - sig * matvec_m(x)

    solve_si = _normalized_rhs_solver(
        lambda b: _minres_loop(shifted, ident, b, jnp.zeros_like(b),
                               jnp.zeros((), b.dtype), inner_atol,
                               inner_maxiter, 10)[0])
    # Probe the shifted solve (sigma on an eigenvalue of the pencil /
    # hopeless conditioning -> fall back, never silently corrupt).
    rng = _probe_apply(shifted, solve_si, n, dtype, inner_atol,
                       "generalized shift-invert")
    # Per-mode inner-product matrix, rhs, and back-transform.
    tiny = np.finfo(rdtype).tiny
    if mode == "buckling":
        inner_mv = matvec_a           # B = A (A must be positive)
        rhs_fn = None

        def back(nu):
            d = np.where(np.abs(nu - 1.0) < tiny, tiny, nu - 1.0)
            return (float(sigma) * nu / d).astype(rdtype)
    elif mode == "cayley":
        inner_mv = matvec_m

        def rhs_fn(v):
            return matvec_a(v) + sig * matvec_m(v)

        def back(nu):
            d = np.where(np.abs(nu - 1.0) < tiny, tiny, nu - 1.0)
            return (float(sigma) * (nu + 1.0) / d).astype(rdtype)
    else:
        inner_mv = matvec_m
        rhs_fn = None

        def back(nu):
            nz = np.where(nu == 0, tiny, nu)
            return (float(sigma) + 1.0 / nz).astype(rdtype)

    v0 = _m_normalized_start(v0, inner_mv, dtype, n, rng)
    w_nu, X, resid, atol, scale, m = _general_lanczos_drive(
        matvec_a, inner_mv, solve_si, True, v0, k, which, ncv, maxiter,
        tol, n, rdtype, dtype, rhs_fn=rhs_fn)
    lam = back(w_nu)
    # Unconverged Ritz pairs raise (scipy parity) — BEFORE reordering,
    # while resid/scale still align with lam's columns.
    _require_converged(resid, atol, scale, m, n, lam, X)
    order = np.argsort(lam)
    lam, X = lam[order], X[:, order]
    _pencil_residual_guard(matvec_a, matvec_m, lam, X, atol_outer,
                           rdtype)
    if not return_eigenvectors:
        return lam
    return lam, X


def _lanczos(matvec, v0, mask, m: int):
    """m-step Lanczos with full (twice-applied) reorthogonalization.

    Returns (V, alphas, betas): V is (m, n) with orthonormal rows,
    T = tridiag(betas[1:], alphas, betas[1:]).  Static shapes; the whole
    recurrence is one ``lax.scan`` so the SpMV chain compiles to a
    single device program (no per-step dispatch over the tunnel).
    """
    n = v0.shape[0]
    dtype = v0.dtype
    rdtype = jnp.finfo(dtype).dtype
    eps = jnp.finfo(rdtype).eps
    key0 = jax.random.PRNGKey(7)

    def step(carry, j):
        V, v, beta, v_prev, alphas, betas = carry
        w = matvec(v)
        alpha = jnp.real(jnp.vdot(v, w)).astype(dtype)
        w = w - alpha * v - beta * v_prev
        # Full reorthogonalization, applied twice (classical
        # Gram-Schmidt is unstable once; twice is enough — Parlett).
        # Rows j+1.. of V are zero so they contribute nothing.
        V = V.at[j].set(v)
        for _ in range(2):
            w = w - V.T @ (jnp.conj(V) @ w)
        beta_next = jnp.linalg.norm(w).astype(dtype)
        # Breakdown (invariant subspace found): continue with a fresh
        # random direction orthogonal to V — T decouples at the zero
        # off-diagonal and its spectrum stays a valid union, instead of
        # the zero vector padding T with fabricated zero eigenvalues.
        broke = jnp.real(beta_next) <= 100 * eps * jnp.maximum(
            jnp.abs(jnp.real(alpha)), 1.0)
        # Restart inside the valid subspace only (padded/masked entries
        # must stay exactly zero — distributed operators carry inert
        # padding rows).
        fresh = _restart_direction(V, key0, j, n, rdtype, dtype,
                                   mask=mask)
        beta_next = jnp.where(broke, jnp.zeros((), dtype), beta_next)
        v_next = jnp.where(
            broke, fresh,
            w / jnp.where(beta_next == 0, 1.0, beta_next))
        # alphas/betas accumulate in the CARRY at our int32 j rather
        # than as stacked scan outputs: with x64 on, sharding
        # propagation shards the scan-ys stacking buffer and its s64
        # loop-counter index trips the spmd partitioner's hlo verifier
        # ("compare s64 vs s32") on the installed jaxlib.
        alphas = alphas.at[j].set(alpha)
        betas = betas.at[j].set(beta_next)
        return (V, v_next, beta_next, v, alphas, betas), None

    V0 = jnp.zeros((m, n), dtype=dtype)
    (V, _, _, _, alphas, betas), _ = jax.lax.scan(
        step, (V0, v0, jnp.zeros((), dtype), jnp.zeros_like(v0),
               jnp.zeros((m,), dtype), jnp.zeros((m,), dtype)),
        jnp.arange(m, dtype=jnp.int32))
    return V, alphas, betas


def _lanczos_eigsh(matvec, n, dtype, k, which, v0, ncv, maxiter, tol,
                   return_eigenvectors, mask=None, max_rank=None):
    import scipy.linalg as _sl

    # The REAL precision of the operand dtype (complex64 -> float32):
    # an itemsize test would hand complex64 float64-grade tolerances.
    rdtype = np.dtype(np.finfo(dtype).dtype)
    if v0 is None:
        rng = np.random.default_rng(0)
        v0 = rng.standard_normal(n)
    # jnp.asarray keeps device (incl. sharded) arrays in place.
    v0 = jnp.asarray(v0, dtype=dtype)
    v0 = v0 / jnp.linalg.norm(v0)

    rank = int(max_rank) if max_rank is not None else n
    # matvec is a closure: static (hashable) so the scan jits around it.
    from .linalg import maybe_jit

    lanczos = maybe_jit(_lanczos, static_argnums=(0,),
                        static_argnames=("m",))

    # Escalate the subspace until the Ritz residuals converge (scipy's
    # implicit restarts analog, kept host-side and simple: each retry
    # doubles m; n caps it).  tol=0 means machine precision (scipy).
    atol, m, tries = _escalation_params(tol, rdtype, ncv, k, rank,
                                        maxiter)
    for try_i in range(tries):
        if try_i:
            m = min(rank, 2 * m)
        # m is only ever doubled right before a run, so the post-loop
        # convergence checks always judge the size actually run.
        V, alphas, betas = lanczos(matvec, v0, mask, m=m)
        a = np.real(np.asarray(alphas)).astype(np.float64)
        b_all = np.real(np.asarray(betas)).astype(np.float64)
        b = b_all[:-1]            # off-diagonal of T
        beta_last = b_all[-1]     # final recurrence beta: residual term
        w, y = _sl.eigh_tridiagonal(a, b)
        # Select k per `which` from the Ritz values (ascending, scipy).
        w_k, y_k = _select_sym_ritz(w, y, k, which)
        # Ritz residual bound: |beta_{m+1} * e_m^T y_i| — the *final*
        # recurrence beta, not T's last off-diagonal.
        resid = np.abs(beta_last) * np.abs(y_k[-1, :])
        scale = np.maximum(np.abs(w_k), 1.0)
        if np.all(resid <= atol * scale) or m >= rank:
            break
    w_k = w_k.astype(rdtype)
    converged = bool(np.all(resid <= atol * scale)) or m >= rank
    if converged and not return_eigenvectors:
        return w_k          # skip forming X entirely
    X = np.asarray(jnp.einsum("mn,mk->nk", V, jnp.asarray(y_k, dtype=dtype)))
    _require_converged(resid, atol, scale, m, rank, w_k, X)
    if not return_eigenvectors:
        return w_k
    return w_k, X


def eigsh(A, k=6, M=None, sigma=None, which="LM", v0=None, ncv=None,
          maxiter=None, tol=0, return_eigenvectors=True, **kwargs):
    """k eigenpairs of a symmetric/Hermitian operator (scipy
    ``eigsh``).

    Capability split: the standard problem with ``which`` in
    {LM, LA, SA} runs the NATIVE device Lanczos below; shift-invert
    ``sigma`` (mode='normal') also runs natively — Lanczos on
    ``(A - sigma I)^{-1}`` with the inner apply an inexact jitted
    MINRES solve nested in the same scan (``_shift_invert_op``), where
    scipy/ARPACK uses a host ``splu`` factorization.  Per scipy
    semantics ``which`` then refers to the TRANSFORMED eigenvalues
    ``nu = 1/(lambda - sigma)`` (LM = closest to sigma) and results
    transform back via ``lambda = sigma + 1/nu``.  ``which='SM'``
    without sigma routes through the same machinery at sigma=0 (the
    classic trick — scipy documents it as the recommended alternative
    to its slow direct-SM mode), falling back to host ARPACK when the
    inexact inverse cannot converge (e.g. singular A).  Generalized
    pencils ``A x = lambda M x`` (SPD M) run natively too —
    M-inner-product Lanczos with a jitted inner CG for ``M^{-1}``
    (``_eigsh_generalized``) without sigma, and the shift-invert
    family ``mode='normal'/'buckling'/'cayley'`` (ARPACK modes 3/4/5,
    ``_eigsh_generalized_si``) with it — host fallback when an
    inner-solve probe stagnates.  Remaining delegations convert
    operands at the boundary and return scipy's results unchanged."""
    mode = kwargs.pop("mode", "normal")
    native_which = ("LM", "LA", "SA", "BE", "SM")
    si_modes = ("normal", "buckling", "cayley")
    sm_native = which == "SM" and sigma is None and M is None and not kwargs
    gen_native = (M is not None and sigma is None and mode == "normal"
                  and which in native_which and not kwargs)
    gen_si_native = (sigma is not None and mode in si_modes
                     and which in native_which and not kwargs
                     and (M is not None or mode != "normal"))
    if not sm_native and not gen_native and not gen_si_native and (
            M is not None or which not in native_which or kwargs
            or (sigma is not None and mode != "normal")):
        return _host_fallback("eigsh")(
            A, k=k, M=M, sigma=sigma, which=which, v0=v0, ncv=ncv,
            maxiter=maxiter, tol=tol, mode=mode,
            return_eigenvectors=return_eigenvectors, **kwargs)
    matvec, m_rows, n_cols, dtype = _operator_parts(A)
    if m_rows != n_cols:
        raise ValueError("expected square matrix")
    if not (0 < k < n_cols):
        raise ValueError(f"k={k} must satisfy 0 < k < n={n_cols}")
    _validate_be_k(which, k)
    if gen_native or gen_si_native:
        # Generalized pencil A x = lambda M x (M SPD): native B-inner
        # Lanczos — mode 2 (M^{-1} A, inner CG on M) without sigma;
        # modes 3/4/5 (normal/buckling/cayley shift-invert, inner
        # MINRES on the shifted pencil) with it; scipy factorizes on
        # host for all of them.  A stagnating inner-solve probe falls
        # back to host ARPACK.  M=None (buckling/cayley on a standard
        # problem) is the identity.
        from scipy.sparse.linalg import ArpackNoConvergence

        if gen_si_native:
            _require_real_sigma(sigma)
            if mode != "normal" and float(sigma) == 0.0:
                raise ValueError(
                    f"mode={mode!r} requires a nonzero sigma "
                    f"(the transform degenerates at 0)")
        if M is not None:
            mv_m, mr, mc, mdtype = _operator_parts(M)
            if (mr, mc) != (n_cols, n_cols):
                raise ValueError(
                    f"M has shape {(mr, mc)}, "
                    f"expected {(n_cols, n_cols)}")
            pdtype = np.promote_types(dtype, mdtype)
        else:
            mv_m = lambda x: x  # noqa: E731
            pdtype = dtype
        # Separate locals for the SM remap (same idiom as the eigs
        # generalized branch): the ArpackNoConvergence host fallback
        # below must see the CALLER's sigma/which — passing the
        # remapped sigma=0.0 makes scipy splu(A - 0*M), which raises
        # "Factor is exactly singular" for exactly the singular-A case
        # the fallback exists to serve (ADVICE r5 medium).
        use_si, sig, wch = gen_si_native, sigma, which
        if not gen_si_native and which == "SM":
            # Direct smallest-magnitude on a pencil is the hardest
            # Krylov target; serve it as generalized shift-invert at 0
            # (largest of (A - 0*M)^{-1} M = smallest |lambda|), the
            # same remap as the standard SM route.
            use_si, sig, wch = True, 0.0, "LM"
        try:
            if use_si:
                return _eigsh_generalized_si(
                    matvec, mv_m, float(sig), n_cols,
                    np.dtype(pdtype), int(k), wch, v0, ncv, maxiter,
                    tol, return_eigenvectors, mode=mode)
            return _eigsh_generalized(
                matvec, mv_m, n_cols, np.dtype(pdtype), int(k), wch,
                v0, ncv, maxiter, tol, return_eigenvectors)
        except ArpackNoConvergence:
            return _host_fallback("eigsh")(
                A, k=k, M=M, sigma=sigma, which=which, v0=v0, ncv=ncv,
                maxiter=maxiter, tol=tol, mode=mode,
                return_eigenvectors=return_eigenvectors)
    if sm_native:
        # Smallest-magnitude = largest of A^{-1}: shift-invert at 0.
        from scipy.sparse.linalg import ArpackNoConvergence

        try:
            return _eigsh_shift_invert(
                matvec, n_cols, dtype, int(k), 0.0, "LM", v0, ncv,
                maxiter, tol, return_eigenvectors)
        except ArpackNoConvergence:
            # Inexact inverse stagnated (singular / near-singular A):
            # host ARPACK's direct-SM Lanczos handles those.
            return _host_fallback("eigsh")(
                A, k=k, which="SM", v0=v0, ncv=ncv, maxiter=maxiter,
                tol=tol, return_eigenvectors=return_eigenvectors)
    if sigma is None:
        return _lanczos_eigsh(matvec, n_cols, dtype, int(k), which, v0,
                              ncv, maxiter, tol, return_eigenvectors)

    # Native shift-invert: Lanczos on OP = (A - sigma I)^{-1}.  Same
    # ArpackNoConvergence -> host ladder as the SM route above (ADVICE
    # r5 low): a sigma near an eigenvalue stagnates the inexact inner
    # MINRES where scipy's exact splu factorization succeeds — serve
    # those through host ARPACK instead of raising.
    _require_real_sigma(sigma)
    from scipy.sparse.linalg import ArpackNoConvergence

    try:
        return _eigsh_shift_invert(matvec, n_cols, dtype, int(k),
                                   float(sigma), which, v0, ncv,
                                   maxiter, tol, return_eigenvectors)
    except ArpackNoConvergence:
        return _host_fallback("eigsh")(
            A, k=k, sigma=sigma, which=which, v0=v0, ncv=ncv,
            maxiter=maxiter, tol=tol,
            return_eigenvectors=return_eigenvectors)


def _eigsh_shift_invert(matvec, n_cols, dtype, k, sigma, which, v0,
                        ncv, maxiter, tol, return_eigenvectors,
                        mask=None, max_rank=None, name="eigsh",
                        trunc_rows=None):
    """Native shift-invert eigsh body (see ``eigsh``): Lanczos on
    ``OP = (A - sigma I)^{-1}`` with the inexact MINRES inner apply.

    ``mask``/``max_rank``/``trunc_rows`` serve the DISTRIBUTED caller
    (``dist_eigsh``): the probe and Krylov space stay in the valid
    (non-padding) subspace, the Krylov dimension caps at the true row
    count, and every returned/raised eigenvector block is truncated to
    the true rows."""
    rdtype = np.dtype(np.finfo(dtype).dtype)
    atol_outer = _outer_atol(tol, rdtype)
    op, inner_atol = _shift_invert_op(matvec, float(sigma), dtype,
                                      n_cols, atol_outer, sym=True)
    _probe_inverse(matvec, op, float(sigma), dtype, n_cols, inner_atol,
                   name, mask=mask)

    # Always form X: the original-spectrum residual check below is what
    # catches a silently-stagnated INNER solve (sigma too close to an
    # eigenvalue) — the outer Ritz test alone only measures convergence
    # on the possibly-corrupted operator.
    def back_l(nu):
        nz = np.where(nu == 0, np.finfo(rdtype).tiny, nu)
        return (float(sigma) + 1.0 / nz).astype(rdtype)

    def trunc(Xa):
        Xa = np.asarray(Xa)
        return Xa if trunc_rows is None else Xa[:trunc_rows]

    from scipy.sparse.linalg import ArpackNoConvergence

    try:
        w_nu, X = _lanczos_eigsh(op, n_cols, dtype, int(k), which, v0,
                                 ncv, maxiter, tol, True, mask=mask,
                                 max_rank=max_rank)
    except Exception as e:
        if not isinstance(e, ArpackNoConvergence):
            raise
        # The inner escalation raised with TRANSFORMED nu values;
        # re-raise carrying back-transformed lambdas so a caller
        # salvaging e.eigenvalues gets actual eigenvalues (matching
        # the eigs shift-invert path).
        raise ArpackNoConvergence(
            str(e), back_l(np.asarray(e.eigenvalues)),
            trunc(e.eigenvectors),
        ) from None
    # nu = 1/(lambda - sigma): eigenvectors are shared with A.
    lam = back_l(w_nu)
    order = np.argsort(lam)                 # scipy returns ascending
    lam, X = lam[order], X[:, order]
    try:
        _check_original_residuals(matvec, lam, X, atol_outer, name)
    except ArpackNoConvergence as e:
        if trunc_rows is None:
            raise
        raise ArpackNoConvergence(
            str(e), np.asarray(e.eigenvalues), trunc(e.eigenvectors),
        ) from None
    if not return_eigenvectors:
        return lam
    return lam, trunc(X)


# ---------------------------------------------------------------- LOBPCG


def _block_seed(X, dtype):
    """Single Lanczos start vector carrying the WHOLE guess block: a
    fixed-seed random combination of the orthonormalized columns of X.

    Lanczos is a single-vector recurrence, so it cannot consume X as a
    block the way LOBPCG proper does; seeding with ``X[:, 0]`` alone
    (the pre-r6 behavior) silently discarded the remaining columns — a
    first column (near-)orthogonal to a target eigenvector that another
    column carries would only be recovered through breakdown restarts.
    Almost-surely-nonzero weights give the Krylov space overlap with
    every direction the block spans."""
    Xa = np.asarray(X)
    q, _ = np.linalg.qr(Xa.astype(np.promote_types(Xa.dtype, dtype)))
    w = np.random.default_rng(11).standard_normal(q.shape[1])
    return q @ w.astype(q.dtype)


def lobpcg(A, X, B=None, M=None, Y=None, tol=None, maxiter=20,
           largest=True, **kwargs):
    """Locally optimal block PCG eigensolver (scipy ``lobpcg``).

    Standard problem (no B/M/Y): runs fully on device via
    ``jax.experimental.sparse.linalg.lobpcg_standard``; smallest
    eigenvalues come from the negated operator.  Generalized ``B``
    (SPD) runs through the native M-inner Lanczos machinery
    (``_eigsh_generalized``) at lobpcg-class sizes, falling back to
    host scipy when B's inner CG stagnates or past 32k rows;
    preconditioned / constrained forms delegate to host scipy.

    Block-seed semantics of the Lanczos-backed routes (generalized
    ``B`` and complex-Hermitian): the driver is a single-vector Lanczos
    recurrence, not a block iteration, so the initial guess block
    enters as ONE start vector — a fixed random combination of the
    orthonormalized columns of ``X`` (``_block_seed``), which overlaps
    every direction the block spans.  Results match scipy's; per-column
    convergence *rates* of true block LOBPCG do not transfer.

    ``maxiter`` semantics: scipy counts *block iterations* — each one
    is one Rayleigh-Ritz step on the (X, R, P) subspace, and
    ``maxiter=20`` means at most 20 such steps.  The Lanczos-backed
    routes here have no block iteration to count; ``maxiter`` instead
    bounds the **escalation retry count** — how many times the driver
    may widen its Krylov subspace (growing ``ncv`` toward the
    ``max(8k, 128)`` basis cap) and restart after a non-converged
    attempt, clamped to [1, 10].  Consequences: (a) ``maxiter=1`` is
    one full Lanczos solve at the initial subspace width, not one
    Rayleigh-Ritz step — usually *more* work than scipy's first
    iteration; (b) raising ``maxiter`` past 10 buys nothing on these
    routes; (c) iteration-matched comparisons against scipy's
    ``lobpcg`` are not meaningful — compare residual tolerances
    instead.  The ``jax.experimental`` ``lobpcg_standard`` route (real
    standard problems) keeps scipy-style semantics: ``maxiter`` is the
    block-iteration count ``m`` passed straight through.
    """
    if (B is not None and M is None and Y is None and not kwargs
            and np.asarray(X).shape[0] <= (1 << 15)):
        from scipy.sparse.linalg import ArpackNoConvergence

        Xa = np.asarray(X)
        mv_a, ar, ac, adt = _operator_parts(A)
        mv_b, br, bc, bdt = _operator_parts(B)
        if ar != ac or (br, bc) != (ar, ac):
            raise ValueError("A and B must be square and conformal")
        if Xa.ndim != 2 or Xa.shape[0] != ac:
            raise ValueError(f"X must be (n, k) with n={ac}")
        kb = Xa.shape[1]
        cap_b = min(ac, max(8 * kb, 128))
        tries_b = max(1, min(int(maxiter) if maxiter is not None
                             else 6, 10))
        pdt_b = np.dtype(np.result_type(adt, bdt, Xa.dtype))
        try:
            w, V = _eigsh_generalized(
                mv_a, mv_b, ac, pdt_b,
                kb, "LA" if largest else "SA", _block_seed(Xa, pdt_b),
                None, tries_b, (tol if tol else 0), True,
                max_rank=cap_b)
            order = (np.argsort(w)[::-1] if largest
                     else np.argsort(w))
            return np.asarray(w)[order], np.asarray(V)[:, order]
        except ArpackNoConvergence:
            return _host_fallback("lobpcg")(
                A, Xa, B=B, tol=tol, maxiter=maxiter, largest=largest)
    if B is not None or M is not None or Y is not None or kwargs:
        return _host_fallback("lobpcg")(
            A, X, B=B, M=M, Y=Y, tol=tol, maxiter=maxiter,
            largest=largest, **kwargs)
    from jax.experimental.sparse.linalg import lobpcg_standard

    matvec, m_rows, n_cols, dtype = _operator_parts(A)
    if m_rows != n_cols:
        raise ValueError("expected square matrix")
    if (np.issubdtype(dtype, np.complexfloating)
            or np.iscomplexobj(np.asarray(X))):
        # jax's lobpcg_standard builds mixed real/complex while_loop
        # carries on complex operands (upstream limitation); serve
        # complex-Hermitian operators through the native device Lanczos
        # instead (same answers, one jitted scan — VERDICT r4 #6).
        Xa = np.asarray(X)
        if Xa.ndim != 2 or Xa.shape[0] != n_cols:
            raise ValueError(f"X must be (n, k) with n={n_cols}")
        k = Xa.shape[1]
        cdtype = np.result_type(dtype, np.complex64)
        if n_cols > (1 << 15):
            # The full-basis Lanczos route stores an (m, n) basis:
            # fine at the sizes complex lobpcg is actually called at,
            # but it loses LOBPCG's O(n k) memory story at large n —
            # keep the host boundary for those.
            return _host_fallback("lobpcg")(
                A, Xa, tol=tol, maxiter=maxiter, largest=largest)
        which = "LA" if largest else "SA"
        # Bound the basis at O(max(8k, 128) * n) — LOBPCG-class memory,
        # not full-rank Lanczos — and map lobpcg's maxiter onto the
        # (bounded) escalation retry count.
        cap = min(n_cols, max(8 * k, 128))
        tries = max(1, min(int(maxiter) if maxiter is not None else 6,
                           10))
        seed = _block_seed(Xa, np.dtype(cdtype))
        try:
            w, V = _lanczos_eigsh(
                matvec, n_cols, np.dtype(cdtype), k, which, seed,
                None, tries, (tol if tol else 0), True, max_rank=cap)
        except Exception as e:
            from scipy.sparse.linalg import ArpackNoConvergence

            if not isinstance(e, ArpackNoConvergence):
                raise
            # scipy's lobpcg NEVER raises on non-convergence — it
            # returns the current approximation with a warning.  Honor
            # that contract with ONE pass at the full capped subspace
            # (ncv=cap, tol=inf accepts its Ritz pairs), which matches
            # the best subspace the escalation reached.
            import warnings

            warnings.warn(
                "lobpcg (native Lanczos route) did not converge to the "
                "requested tolerance; returning the current "
                "approximation (scipy-compatible behavior)",
                UserWarning, stacklevel=2)
            w, V = _lanczos_eigsh(
                matvec, n_cols, np.dtype(cdtype), k, which, seed,
                cap, 1, np.inf, True, max_rank=cap)
        order = np.argsort(w)[::-1] if largest else np.argsort(w)
        return np.asarray(w)[order], np.asarray(V)[:, order]
    X = jnp.asarray(np.asarray(X), dtype=dtype)
    if X.ndim != 2 or X.shape[0] != n_cols:
        raise ValueError(f"X must be (n, k) with n={n_cols}")
    if 5 * X.shape[1] >= n_cols:
        # jax's lobpcg_standard requires 5k < n; scipy handles these
        # small/fat cases, so serve them the same way.
        return _host_fallback("lobpcg")(
            A, np.asarray(X), tol=tol, maxiter=maxiter, largest=largest)

    sign = 1.0 if largest else -1.0

    def mv_block(S):   # lobpcg_standard wants (n, k) -> (n, k)
        return sign * jax.vmap(matvec, in_axes=1, out_axes=1)(S)

    iters = int(maxiter) if maxiter is not None else 20
    theta, U, _n_iter = lobpcg_standard(mv_block, X, m=max(iters, 1),
                                        tol=tol)
    w = sign * np.asarray(theta)
    order = np.argsort(w)[::-1] if largest else np.argsort(w)
    return w[order], np.asarray(U)[:, order]


# ---------------------------------------------------------------- svds


def svds(A, k=6, ncv=None, tol=0, which="LM", v0=None, maxiter=None,
         return_singular_vectors=True, **kwargs):
    """k largest singular triplets (scipy ``svds``).

    Native path: Lanczos on the Gram operator ``v -> A^T (A v)`` (two
    SpMVs per step, A^T A never materialized), then ``U = A V / s``.
    ``which='SM'`` (smallest) also runs natively — shift-invert at 0 on
    the Gram operator (largest of (A^T A)^{-1}), the same machinery as
    ``eigsh(which='SM')`` — falling back to host scipy when the
    inexact inverse stagnates (rank-deficient A, or kappa(A)^2 beyond
    the iterative inner solver).
    """
    if which not in ("LM", "SM") or kwargs:
        return _host_fallback("svds")(
            A, k=k, ncv=ncv, tol=tol, which=which, v0=v0,
            maxiter=maxiter,
            return_singular_vectors=return_singular_vectors, **kwargs)
    from .linalg import LinearOperator, make_linear_operator

    op = A if isinstance(A, LinearOperator) else make_linear_operator(A)
    m_rows, n_cols = op.shape
    if not (0 < k < min(m_rows, n_cols)):
        raise ValueError(
            f"k={k} must satisfy 0 < k < min(shape)={min(m_rows, n_cols)}")
    if op.dtype is None:
        op._init_dtype()
    dtype = np.dtype(op.dtype)

    try:
        op.rmatvec(jnp.zeros((m_rows,), dtype=dtype))
        has_rmatvec = True
    except Exception:
        has_rmatvec = False

    if has_rmatvec:
        def gram(v):
            return op.rmatvec(op.matvec(v))
    else:
        # Fall back to transposing a sparse operand once.
        AT = A.transpose() if hasattr(A, "transpose") else None
        if AT is None:
            return _host_fallback("svds")(
                A, k=k, ncv=ncv, tol=tol, which=which, v0=v0,
                maxiter=maxiter,
                return_singular_vectors=return_singular_vectors, **kwargs)

        def gram(v):
            return AT @ (op.matvec(v))

    if which == "SM":
        from scipy.sparse.linalg import ArpackNoConvergence

        if m_rows < n_cols:
            # Wide operator: rank(A^T A) <= m_rows < n_cols, so the
            # Gram operator is singular BY CONSTRUCTION — the probe
            # would burn a full MINRES budget just to discover it.
            # Skip straight to the host path.
            return _host_fallback("svds")(
                A, k=k, ncv=ncv, tol=tol, which="SM", v0=v0,
                maxiter=maxiter,
                return_singular_vectors=return_singular_vectors)
        try:
            w, V = _eigsh_shift_invert(
                gram, int(n_cols), dtype, int(k), 0.0, "LM", v0, ncv,
                maxiter, tol, True, name="svds")
        except ArpackNoConvergence:
            return _host_fallback("svds")(
                A, k=k, ncv=ncv, tol=tol, which="SM", v0=v0,
                maxiter=maxiter,
                return_singular_vectors=return_singular_vectors)
    else:
        w, V = _lanczos_eigsh(gram, int(n_cols), dtype, int(k), "LA",
                              v0, ncv, maxiter, tol, True)
    s = np.sqrt(np.clip(w, 0.0, None))            # ascending (scipy order)
    if not return_singular_vectors:
        return s
    Vj = jnp.asarray(V, dtype=dtype)
    AV = np.asarray(jax.vmap(op.matvec, in_axes=1, out_axes=1)(Vj))
    U = AV / np.where(s > 0, s, 1.0)[None, :]
    return U, s, V.T


# ---------------------------------------------------------------- Arnoldi


def _arnoldi(matvec, v0, m: int):
    """m-step Arnoldi with full (twice-applied) reorthogonalization.

    Returns (V, H): V is (m, n) orthonormal, H is the (m + 1, m) upper
    Hessenberg with H[j+1, j] the recurrence norms.  One ``lax.scan``
    (same shape as ``_lanczos``, but the projection coefficients feed
    the full Hessenberg column rather than a tridiagonal pair).
    """
    n = v0.shape[0]
    dtype = v0.dtype
    rdtype = jnp.finfo(dtype).dtype
    eps = jnp.finfo(rdtype).eps
    key0 = jax.random.PRNGKey(11)

    def step(carry, j):
        V, v = carry
        V = V.at[j].set(v)
        w = matvec(v)
        # Modified-Gram-Schmidt-by-blocks, applied twice.
        h = jnp.conj(V) @ w
        w = w - V.T @ h
        h2 = jnp.conj(V) @ w
        w = w - V.T @ h2
        h = h + h2
        beta = jnp.linalg.norm(w).astype(rdtype)
        broke = beta <= 100 * eps * jnp.maximum(
            jnp.max(jnp.abs(h)), 1.0)
        fresh = _restart_direction(V, key0, j, n, rdtype, dtype)
        beta_out = jnp.where(broke, jnp.zeros((), rdtype), beta)
        v_next = jnp.where(
            broke, fresh,
            w / jnp.where(beta == 0, 1.0, beta).astype(dtype))
        # Hessenberg column j: projections h[0..j] on top, the
        # recurrence norm at SUBDIAGONAL position j+1 (h[j+1] is ~0 by
        # orthogonality, so a scatter-add is a clean set).
        col = jnp.concatenate([h, jnp.zeros((1,), dtype)])
        col = col.at[j + 1].add(beta_out.astype(dtype))
        return (V, v_next), col

    V0 = jnp.zeros((m, n), dtype=dtype)
    (V, _), cols = jax.lax.scan(step, (V0, v0), jnp.arange(m, dtype=jnp.int32))
    # cols[j] is the length-(m+1) Hessenberg column j (entries beyond
    # j+1 are ~0 by orthogonality).
    H = cols.T
    return V, H


def _select_ritz(w, k, which):
    if which == "LM":
        sel = np.argsort(np.abs(w))[-k:]
    elif which == "SM":
        # Under shift-invert (the only native route here): smallest
        # |nu| = farthest from sigma, ARPACK's transformed semantics.
        sel = np.argsort(np.abs(w))[:k]
    elif which == "LR":
        sel = np.argsort(np.real(w))[-k:]
    elif which == "SR":
        sel = np.argsort(np.real(w))[:k]
    elif which == "LI":
        sel = np.argsort(np.imag(w))[-k:]
    else:  # SI
        sel = np.argsort(np.imag(w))[:k]
    return sel


def eigs(A, k=6, M=None, sigma=None, which="LM", v0=None, ncv=None,
         maxiter=None, tol=0, return_eigenvectors=True, **kwargs):
    """k eigenpairs of a general (non-symmetric) operator (scipy
    ``eigs``).

    Capability split: the standard problem with ``which`` in
    {LM, LR, SR, LI, SI} runs the NATIVE restarted Arnoldi below;
    shift-invert ``sigma`` also runs natively — Arnoldi on
    ``(A - sigma I)^{-1}`` with an inexact jitted BiCGSTAB inner solve
    (``_shift_invert_op``) nested in the same scan, where scipy/ARPACK
    factorizes on host.  Per scipy semantics ``which`` then refers to
    the transformed ``nu = 1/(lambda - sigma)``; results transform back
    via ``lambda = sigma + 1/nu``.  ``which='SM'`` without sigma routes
    through the same shift-invert at sigma=0 (largest of A^{-1}),
    falling back to host ARPACK if the inexact inverse stagnates.
    Generalized pencils ``A x = lambda M x`` (positive-definite M) run
    natively too: Arnoldi on ``M^{-1} A`` (inner CG on M) without
    sigma, or on ``(A - sigma M)^{-1} M`` (inner BiCGSTAB) with it —
    ``_eigs_generalized`` — with host fallback when an inner-solve
    probe stagnates.  Eigenvalues return complex, like scipy."""
    native_which = ("LM", "LR", "SR", "LI", "SI")
    if M is not None and not kwargs and (
            which in native_which
            or which == "SM"):
        from scipy.sparse.linalg import ArpackNoConvergence

        sig = sigma
        wch = which
        if which == "SM" and sigma is None:
            sig, wch = 0.0, "LM"     # smallest |lambda| of the pencil
        try:
            return _eigs_generalized(
                A, M, int(k), (None if sig is None else complex(sig)),
                wch, v0, ncv, maxiter, tol, return_eigenvectors)
        except ArpackNoConvergence:
            return _host_fallback("eigs")(
                A, k=k, M=M, sigma=sigma, which=which, v0=v0, ncv=ncv,
                maxiter=maxiter, tol=tol,
                return_eigenvectors=return_eigenvectors)
    if which == "SM" and sigma is None and M is None and not kwargs:
        from scipy.sparse.linalg import ArpackNoConvergence

        try:
            return _eigs_shift_invert(A, int(k), complex(0.0), "LM",
                                      v0, ncv, maxiter, tol,
                                      return_eigenvectors)
        except ArpackNoConvergence:
            return _host_fallback("eigs")(
                A, k=k, which="SM", v0=v0, ncv=ncv, maxiter=maxiter,
                tol=tol, return_eigenvectors=return_eigenvectors)
    if (M is not None
            or which not in native_which + ("SM",) or kwargs):
        return _host_fallback("eigs")(
            A, k=k, M=M, sigma=sigma, which=which, v0=v0, ncv=ncv,
            maxiter=maxiter, tol=tol,
            return_eigenvectors=return_eigenvectors, **kwargs)
    if sigma is not None:
        if which == "SM":
            # Same fallback ladder as every other SM route: a
            # stagnating inexact inverse (sigma pathologically close
            # to an eigenvalue) serves through host ARPACK instead of
            # raising.
            from scipy.sparse.linalg import ArpackNoConvergence

            try:
                return _eigs_shift_invert(
                    A, int(k), complex(sigma), which, v0, ncv, maxiter,
                    tol, return_eigenvectors)
            except ArpackNoConvergence:
                return _host_fallback("eigs")(
                    A, k=k, sigma=sigma, which=which, v0=v0, ncv=ncv,
                    maxiter=maxiter, tol=tol,
                    return_eigenvectors=return_eigenvectors)
        # Explicit-sigma LM/LR/SR/LI/SI: same ArpackNoConvergence ->
        # host ladder as the SM route (ADVICE r5 low) — a sigma
        # pathologically close to an eigenvalue stagnates the inexact
        # BiCGSTAB inverse where scipy's splu succeeds.
        from scipy.sparse.linalg import ArpackNoConvergence

        try:
            return _eigs_shift_invert(A, int(k), complex(sigma), which,
                                      v0, ncv, maxiter, tol,
                                      return_eigenvectors)
        except ArpackNoConvergence:
            return _host_fallback("eigs")(
                A, k=k, sigma=sigma, which=which, v0=v0, ncv=ncv,
                maxiter=maxiter, tol=tol,
                return_eigenvectors=return_eigenvectors)
    matvec, m_rows, n_cols, dtype = _operator_parts(A)
    if m_rows != n_cols:
        raise ValueError("expected square matrix")
    n = n_cols
    if not (0 < k < n - 1):
        raise ValueError(f"k={k} must satisfy 0 < k < n - 1 = {n - 1}")

    # Real operators run the whole recurrence in REAL arithmetic (the
    # Krylov basis of a real operator from a real start is real — a
    # complex basis would double matvec cost and memory); only the
    # small host eig and the Ritz combination go complex.
    cdtype = np.result_type(dtype, np.complex64)
    basis_dtype = dtype
    mv = matvec
    if v0 is None:
        v0 = np.random.default_rng(0).standard_normal(n)
    elif (np.iscomplexobj(np.asarray(v0))
          and not np.issubdtype(dtype, np.complexfloating)):
        # Complex start on a real operator: complex basis, two real
        # matvecs per step (the only case that needs them).
        basis_dtype = cdtype
        mv = _complex_matvec(matvec, dtype, cdtype)
    v0 = jnp.asarray(v0, dtype=basis_dtype)
    v0 = v0 / jnp.linalg.norm(v0)
    return _arnoldi_eigs(mv, n, cdtype, k, which, v0, ncv, maxiter,
                         tol, return_eigenvectors)


def _arnoldi_eigs(mv, n, cdtype, k, which, v0, ncv, maxiter, tol,
                  return_eigenvectors, transform=None):
    """Shared restarted-Arnoldi driver: escalate the subspace until the
    Ritz residuals converge, then (optionally) map the Ritz values
    through ``transform`` (the shift-invert back-transform
    ``lambda = sigma + 1/nu``; residual control stays in the operator's
    own — i.e. transformed — spectrum, exactly like ARPACK)."""
    rdtype = np.finfo(cdtype).dtype
    from .linalg import maybe_jit

    arnoldi = maybe_jit(_arnoldi, static_argnums=(0,),
                        static_argnames=("m",))
    atol, m, tries = _escalation_params(tol, rdtype, ncv, k, n,
                                        maxiter, min_extra=2)
    for try_i in range(tries):
        if try_i:
            m = min(n, 2 * m)
        # Doubling only ever happens right before a run (see
        # _lanczos_eigsh): post-loop checks judge the size that ran.
        V, H = arnoldi(mv, v0, m=m)
        Hm = np.asarray(H)[:m, :m]
        beta_last = float(abs(np.asarray(H)[m, m - 1]))
        w, y = np.linalg.eig(Hm)
        sel = _select_ritz(w, k, which)
        w_k = w[sel]
        y_k = y[:, sel]
        resid = beta_last * np.abs(y_k[-1, :])
        scale = np.maximum(np.abs(w_k), 1.0)
        if np.all(resid <= atol * scale) or m >= n:
            break
    converged = bool(np.all(resid <= atol * scale)) or m >= n
    lam = transform(w_k) if transform is not None else w_k
    # scipy contract: eigs eigenvalues are ALWAYS complex, even when a
    # real Hessenberg's spectrum happens to be all-real (np.linalg.eig
    # returns float64 then) — cast here so every caller inherits it.
    lam = np.asarray(lam).astype(cdtype)
    if converged and not return_eigenvectors:
        return lam          # skip forming X entirely
    X = np.asarray(jnp.einsum("mn,mk->nk", V,
                              jnp.asarray(y_k, dtype=cdtype)))
    _require_converged(resid, atol, scale, m, n, lam, X)
    if not return_eigenvectors:
        return lam
    return lam, X


def _promote_real_operators(matvecs, dtypes, cdtype,
                            extra_complex: bool):
    """Shared complex-promotion ladder for the non-symmetric drivers:
    returns ``(base_dtype, wrapped, guards)`` — the working dtype, the
    matvecs promoted to a complex basis when anything (operand dtypes,
    a complex sigma, a complex start) requires it, and always-complex
    guard matvecs for the residual referees."""
    pdt = dtypes[0] if len(dtypes) == 1 else np.promote_types(*dtypes)
    is_complex = np.issubdtype(pdt, np.complexfloating)
    if is_complex or not extra_complex:
        base = np.dtype(pdt)
        wrapped = list(matvecs)
    else:
        base = np.dtype(cdtype)
        wrapped = [_complex_matvec(mv, np.dtype(d), cdtype)
                   for mv, d in zip(matvecs, dtypes)]
    if np.issubdtype(base, np.complexfloating):
        guards = list(wrapped)
    else:
        guards = [_complex_matvec(mv, np.dtype(d), cdtype)
                  for mv, d in zip(matvecs, dtypes)]
    return base, wrapped, guards


def _si_back_transform(sigma, rdtype, cdtype):
    """Shared ``lambda = sigma + 1/nu`` back-transform for the
    non-symmetric shift-invert drivers (zero-nu guarded by tiny)."""

    def back(nu):
        tiny = np.finfo(rdtype).tiny
        safe = np.where(nu == 0, tiny, nu)
        return (complex(sigma) + 1.0 / safe).astype(cdtype)

    return back


def _eigs_shift_invert(A, k, sigma, which, v0, ncv, maxiter, tol,
                       return_eigenvectors):
    """Native shift-invert ``eigs``: Arnoldi on ``(A - sigma I)^{-1}``
    with the inexact jitted BiCGSTAB inner apply (``_shift_invert_op``).
    A complex sigma (or complex start) on a real operator promotes the
    basis to complex with two real matvecs per inner apply."""
    matvec, m_rows, n_cols, dtype = _operator_parts(A)
    if m_rows != n_cols:
        raise ValueError("expected square matrix")
    n = n_cols
    if not (0 < k < n - 1):
        raise ValueError(f"k={k} must satisfy 0 < k < n - 1 = {n - 1}")
    cdtype = np.result_type(dtype, np.complex64)
    rdtype = np.finfo(cdtype).dtype
    extra_complex = (
        sigma.imag != 0
        or (v0 is not None and np.iscomplexobj(np.asarray(v0)))
    )
    base_dtype, (base_mv,), (check_mv,) = _promote_real_operators(
        [matvec], [dtype], cdtype, extra_complex)
    sig_val = (complex(sigma)
               if np.issubdtype(base_dtype, np.complexfloating)
               else float(sigma.real))
    atol_outer = _outer_atol(tol, rdtype)
    op, inner_atol = _shift_invert_op(base_mv, sig_val, base_dtype, n,
                                      atol_outer, sym=False)
    _probe_inverse(base_mv, op, sig_val, base_dtype, n, inner_atol,
                   "eigs")
    if v0 is None:
        v0 = np.random.default_rng(0).standard_normal(n)
    v0 = jnp.asarray(v0, dtype=base_dtype)
    v0 = v0 / jnp.linalg.norm(v0)

    back = _si_back_transform(sigma, rdtype, cdtype)

    # Always form X: the original-spectrum check below catches a
    # silently-stagnated inner solve (see _check_original_residuals).
    lam, X = _arnoldi_eigs(op, n, cdtype, k, which, v0, ncv, maxiter,
                           tol, True, transform=back)
    _check_original_residuals(check_mv, np.asarray(lam), X,
                              atol_outer, "eigs")
    if not return_eigenvectors:
        return lam
    return lam, X


def _eigs_generalized(A, M, k, sigma, which, v0, ncv, maxiter, tol,
                      return_eigenvectors):
    """Native generalized (non-symmetric) ``eigs``: Arnoldi on
    ``M^{-1} A`` (sigma None; eigenvalues of the operator ARE the
    pencil eigenvalues — no transform) or on ``(A - sigma M)^{-1} M``
    (shift-invert; ``which`` on the transformed nu, back-transform
    ``lambda = sigma + 1/nu``).  Inner solves: CG on the
    positive-definite M, BiCGSTAB on the general shifted pencil — both
    with normalized right-hand sides so the tolerance is relative.
    The pencil-residual guard referees the inexact inner solves."""
    from .linalg import _bicgstab_loop, _cg_loop

    matvec_a, ar, ac, adt = _operator_parts(A)
    mv_m, mr, mc, mdt = _operator_parts(M)
    if ar != ac:
        raise ValueError("expected square matrix")
    if (mr, mc) != (ar, ac):
        raise ValueError(f"M has shape {(mr, mc)}, expected {(ar, ac)}")
    n = ac
    if not (0 < k < n - 1):
        raise ValueError(f"k={k} must satisfy 0 < k < n - 1 = {n - 1}")
    cdtype = np.result_type(adt, mdt, np.complex64)
    rdtype = np.finfo(cdtype).dtype
    extra_complex = (
        (sigma is not None and sigma.imag != 0)
        or (v0 is not None and np.iscomplexobj(np.asarray(v0)))
    )
    base_dtype, (base_a, base_m), (guard_a, guard_m) = (
        _promote_real_operators([matvec_a, mv_m], [adt, mdt], cdtype,
                                extra_complex))
    atol_outer = _outer_atol(tol, rdtype)
    inner_atol, inner_maxiter = _inner_solver_params(atol_outer, rdtype,
                                                     n)
    ident = lambda r: r  # noqa: E731

    if sigma is None:
        solve = _normalized_rhs_solver(
            lambda b: _cg_loop(base_m, ident, b, jnp.zeros_like(b),
                               inner_atol, inner_maxiter, 10)[0])
        _probe_apply(base_m, solve, n, base_dtype, inner_atol,
                     "generalized eigs")
        transform = None
    else:
        sig_val = (complex(sigma) if np.issubdtype(
            base_dtype, np.complexfloating) else float(sigma.real))
        sig_dev = jnp.asarray(sig_val, dtype=base_dtype)

        def shifted(x):
            return base_a(x) - sig_dev * base_m(x)

        solve = _normalized_rhs_solver(
            lambda b: _bicgstab_loop(shifted, ident, b,
                                     jnp.zeros_like(b), inner_atol,
                                     inner_maxiter, 10)[0])
        _probe_apply(shifted, solve, n, base_dtype, inner_atol,
                     "generalized eigs shift-invert")

        transform = _si_back_transform(sigma, rdtype, cdtype)

    def op(v):
        return solve(base_m(v)) if sigma is not None else solve(
            base_a(v))

    if v0 is None:
        v0 = np.random.default_rng(0).standard_normal(n)
    v0 = jnp.asarray(v0, dtype=base_dtype)
    v0 = v0 / jnp.linalg.norm(v0)
    lam, X = _arnoldi_eigs(op, n, cdtype, k, which, v0, ncv, maxiter,
                           tol, True, transform=transform)
    # Pencil-residual referee in complex arithmetic (X is complex).
    _pencil_residual_guard(guard_a, guard_m, np.asarray(lam), X,
                           atol_outer, rdtype)
    if not return_eigenvectors:
        return lam
    return lam, X
