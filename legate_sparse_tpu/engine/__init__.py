# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""legate_sparse_tpu.engine: shape-bucketed plan cache + micro-batching
request executor.

The serving layer between user traffic and the kernels (see
``docs/ENGINE.md``).  Three pieces:

- **plan cache** (``plan_cache``): AOT-compiled executables keyed on
  (op, dtype, shape *bucket*, mesh fingerprint, settings epoch), with
  an explicit ``warmup(plans)`` API and optional persistent backing
  via JAX's compilation cache — nearby ``n``/``nnz`` hit one compiled
  program with zero retraces.
- **shape bucketing** (``buckets``): power-of-two (or user-ladder)
  padding with masked tails, bit-for-bit identical to the unpadded
  kernels.
- **request executor** (``executor``): thread-safe ``submit`` that
  micro-batches same-plan SpMV requests into one stacked SpMM
  dispatch, with queue-depth/timeout/backpressure knobs in
  ``settings``.
- **admission gateway** (``gateway``, ``LEGATE_SPARSE_TPU_GATEWAY``):
  the multi-tenant layer above the executor — QoS classes, per-tenant
  token buckets and queue quotas, weighted-fair-queueing batch
  formation (cross-matrix batches pack into one stacked
  ``multi_matvec`` dispatch), deadline-aware dispatch and typed
  shedding (``tools/trace_summary.py --gateway`` renders the
  per-tenant ledger).

Enable with ``LEGATE_SPARSE_TPU_ENGINE=1`` (or ``settings.engine =
True``): eligible ``csr_array.dot`` and ``linalg.cg`` hot paths then
route through the engine automatically.  All engine activity lands in
the obs counters/spans (``engine.*``); ``tools/trace_summary.py
--plans`` renders the per-plan table.
"""

from .buckets import bucket, k_bucket, next_pow2  # noqa: F401
from .core import (  # noqa: F401
    Engine, engine_enabled, get_engine, reset_engine, route_matmat,
    route_matvec, warmup,
)
from .executor import RequestExecutor  # noqa: F401
from .gateway import (  # noqa: F401
    QOS_CLASSES, QOS_WEIGHTS, Gateway, get_gateway, reset_gateway,
)
from .plan_cache import (  # noqa: F401
    Plan, PlanCache, PlanKey, maybe_enable_persistent_cache,
)

__all__ = [
    "bucket", "k_bucket", "next_pow2",
    "Engine", "engine_enabled", "get_engine", "reset_engine",
    "route_matvec", "route_matmat", "warmup",
    "RequestExecutor",
    "QOS_CLASSES", "QOS_WEIGHTS", "Gateway", "get_gateway",
    "reset_gateway",
    "Plan", "PlanCache", "PlanKey", "maybe_enable_persistent_cache",
]
