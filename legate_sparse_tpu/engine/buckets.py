# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Shape bucketing policy: the quantizer behind the plan cache.

Every entry point retraced whenever ``n``/``nnz`` drifted, and the obs
first-call split showed compiles dominating first-touch latency.  The
fix is the JITSPMM lesson (PAPERS.md): runtime specialization pays only
when the specialized artifact is REUSED — so specialize on a shape
*bucket*, not the exact shape.  Operands are padded up to the bucket
with masked tails (``ops.spmv.csr_spmv_rowids_masked`` /
``csr_spmm_rowids_masked`` drop padded products exactly), which keeps
results bit-for-bit identical to the unpadded kernels while nearby
sizes share one compiled executable.

Policy: the smallest rung of ``settings.engine_bucket_ladder`` that
holds the value, or — with an empty ladder (the default) or a value
above the top rung — the next power of two.  Either way the bucket is
floored at ``settings.engine_min_bucket`` so tiny matrices don't mint
one plan per size.  Padding waste is bounded: < 2x under the
power-of-two policy, operator-chosen under a ladder.
"""

from __future__ import annotations

from typing import Optional, Tuple


def next_pow2(value: int) -> int:
    """Smallest power of two >= ``value`` (>= 1)."""
    return 1 << max(int(value) - 1, 0).bit_length()


def bucket(value: int, ladder: Optional[Tuple[int, ...]] = None,
           minimum: Optional[int] = None) -> int:
    """Bucketed size for ``value`` under the active policy.

    ``ladder``/``minimum`` default to the live settings; pass
    explicitly for policy-independent uses (tests, warmup specs).
    """
    if ladder is None or minimum is None:
        from ..settings import settings

        if ladder is None:
            ladder = settings.engine_bucket_ladder
        if minimum is None:
            minimum = settings.engine_min_bucket
    value = max(int(value), 1)
    floor = max(int(minimum), 1)
    for rung in ladder:
        if rung >= value:
            return max(rung, floor)
    return max(next_pow2(value), floor)


def k_bucket(k: int) -> int:
    """Bucket for the dense-operand column count of an SpMM plan (the
    executor's stacked-batch width): plain next power of two, floor 1 —
    batch widths are small, a ladder buys nothing."""
    return next_pow2(max(int(k), 1))
