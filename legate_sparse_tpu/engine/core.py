# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Execution engine: bucketed plans + request-level dispatch.

``Engine`` ties the pieces together:

- :mod:`.buckets` quantizes ``(rows, cols, nnz, k)`` to policy buckets;
- :mod:`.plan_cache` holds one AOT-compiled executable per bucket key;
- per-matrix *packs* (operands padded to the bucket, cached on the
  ``csr_array`` like the ELL/DIA structure caches) supply the
  executable's inputs without per-call padding of the matrix;
- :mod:`.executor` micro-batches same-plan SpMV requests into one
  stacked SpMM dispatch.

The engine routes only what it can serve *better*: matrices whose
dispatch would take the gather/segment-sum (CSR/ELL) paths.  Banded
(DIA) and block (BSR) matrices keep their structure-specialized
kernels — those are shape-specialized for a reason, and bucketing them
is a different project.  Routing requires a concrete (non-tracer)
context and a single-controller process; everything else falls back to
the normal dispatch, so ``settings.engine = True`` is always safe.

Correctness contract: a bucketed dispatch is bit-for-bit identical to
the unpadded ``csr_spmv_rowids``/``csr_spmm_rowids`` kernels — padded
products are masked to exact zeros and padded row ids fall outside
``[0, rows_b)`` so ``segment_sum`` drops them (tests/test_engine.py
fuzzes this on f32/f64/c64 including bucket-boundary tails).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

from .. import obs as _obs
from ..obs import context as _context
from ..resilience import faults as _rfaults
from ..resilience import outcomes as _routcomes
from ..resilience import policy as _rpolicy
from ..settings import settings as _settings_ref
from . import buckets as _buckets
from .plan_cache import BUILDERS, Plan, PlanCache, PlanKey, \
    maybe_enable_persistent_cache

_INT32_MAX = np.iinfo(np.int32).max


class _Pack:
    """Bucket-padded operands of one matrix (device arrays)."""

    __slots__ = ("data", "indices", "row_ids", "valid", "rows", "cols",
                 "nnz")

    def __init__(self, data, indices, row_ids, valid, rows, cols, nnz):
        self.data = data
        self.indices = indices
        self.row_ids = row_ids
        self.valid = valid
        self.rows = rows
        self.cols = cols
        self.nnz = nnz


def _pad_tail(arr, total: int, fill):
    """Pad a 1-D array up to ``total`` with ``fill`` (device concat)."""
    import jax.numpy as jnp

    pad = total - arr.shape[0]
    if pad <= 0:
        return arr
    return jnp.concatenate(
        [arr, jnp.full((pad,), fill, dtype=arr.dtype)])


class Engine:
    """Shape-bucketed plan cache + request executor (one per process
    via :func:`get_engine`; independent instances are fine in tests)."""

    def __init__(self, plan_capacity: Optional[int] = None):
        from ..settings import settings

        self._settings = settings
        self._cache = PlanCache(
            plan_capacity if plan_capacity is not None
            else settings.engine_plan_cache_size)
        self._executor = None
        self._exec_lock = threading.Lock()
        maybe_enable_persistent_cache()

    # ---------------- keys / eligibility ----------------

    def _key(self, op: str, rows: int, cols: int, nnz: int,
             dtype, k: int = 1, mesh_fp: str = "") -> PlanKey:
        return PlanKey(
            op=op,
            dtype=np.dtype(dtype).name,
            rows_b=_buckets.bucket(rows),
            cols_b=_buckets.bucket(cols),
            nnz_b=_buckets.bucket(nnz),
            k_b=_buckets.k_bucket(k),
            mesh_fp=mesh_fp,
            epoch=self._settings.epoch,
        )

    def _eligible(self, A, x_dtype=None) -> bool:
        """Can (and should) this matrix route through bucketed plans?
        Declines are silent-by-design: the caller falls back to the
        normal dispatch."""
        import jax

        from ..csr import csr_array

        if not isinstance(A, csr_array):
            return False
        if jax.process_count() != 1:
            return False        # AOT executables + closure constants
        if not csr_array._can_build_cache(A.data, A.indices, A.indptr):
            return False        # ambient trace: pack/pad would leak
        if x_dtype is not None and np.result_type(
                A.dtype, x_dtype) != A.dtype:
            return False        # promotion would rebuild packs per call
        rows_b = _buckets.bucket(A.shape[0])
        cols_b = _buckets.bucket(A.shape[1])
        # All three BUCKETED: the kernel's iota/row ids run over the
        # padded nnz_b, which can cross int32 even when raw nnz fits.
        if (rows_b > _INT32_MAX or cols_b > _INT32_MAX
                or _buckets.bucket(A.nnz) > _INT32_MAX):
            return False        # int32 row-id/pad sentinel domain
        # Structure-specialized fast paths win over shape stability.
        if A._get_dia() is not None or A._get_bsr() is not None:
            return False
        if (jax.devices()[0].platform == "tpu"
                and A._get_ell() is not None):
            # On TPU the rectangular ELL gather runs at HBM roofline
            # while scatter/segment-sum kernels do not (csr.py kernel
            # notes): shape stability must not cost the roofline
            # there.  On CPU the two are the same class, so bucketed
            # plans take ELL-packable matrices too.
            return False
        from .. import autotune as _autotune

        pref = _autotune.plan_preference(A)
        if pref is not None and pref != "csr-rowids":
            # A measured verdict picked a non-CSR kernel; the engine's
            # bucketed plans only serve the CSR gather form, so defer
            # and let the autotune route downstream serve the verdict.
            _obs.inc("autotune.engine.defer")
            return False
        return True

    # ---------------- plans ----------------

    def plan_for(self, op: str, rows: int, cols: int, nnz: int, dtype,
                 k: int = 1, mesh_fp: str = "") -> Plan:
        """Fetch (or build) the plan for a bucketed shape — the
        ``warmup`` workhorse, also usable directly."""
        key = self._key(op, rows, cols, nnz, dtype, k=k, mesh_fp=mesh_fp)
        builder = BUILDERS.get(op)
        if builder is None:
            raise ValueError(f"unknown plan op {op!r}; known: "
                             f"{sorted(BUILDERS)}")
        plan, _hit = self._cache.get_or_build(key, builder)
        return plan

    def warmup(self, plans: Iterable[Dict[str, Any]]) -> List[str]:
        """Pre-compile plans before traffic arrives.

        Each spec is a dict: ``{"op": "spmv"|"spmm", "dtype": ...,
        "rows": n, "cols": m, "nnz": z, "k": 1}`` (``cols`` defaults to
        ``rows``, ``k`` to 1).  Shapes are bucketed exactly like live
        dispatch, so a warmed spec guarantees a plan hit for every
        workload landing in the same buckets.  Compiles against
        ``ShapeDtypeStruct``s — no operand materialization.  Returns
        the built plan ids."""
        built = []
        for spec in plans:
            rows = int(spec["rows"])
            plan = self.plan_for(
                spec.get("op", "spmv"),
                rows,
                int(spec.get("cols", rows)),
                int(spec["nnz"]),
                spec.get("dtype", np.float32),
                k=int(spec.get("k", 1)),
            )
            built.append(plan.key.plan_id)
        return built

    # ---------------- per-matrix packs ----------------

    def _pack_for(self, A, key: PlanKey) -> _Pack:
        import jax.numpy as jnp

        from ..csr import csr_array
        from ..types import coord_dtype_for

        terms = (key.rows_b, key.cols_b, key.nnz_b, key.dtype)
        cached = getattr(A, "_engine_pack", None)
        if cached is not None and cached[0] == terms:
            return cached[1]
        nnz = A.nnz
        cdt = coord_dtype_for(max(key.cols_b, 1))
        data = _pad_tail(A.data, key.nnz_b, 0)
        indices = _pad_tail(A.indices.astype(cdt), key.nnz_b, 0)
        # Padded row ids land OUT of [0, rows_b): segment_sum drops
        # them (no +0.0 ever touches a real row — the bit-for-bit
        # invariant; adding 0.0 would flip a -0.0 sum).
        row_ids = _pad_tail(
            A._get_row_ids().astype(jnp.int32), key.nnz_b, key.rows_b)
        valid = jnp.asarray(nnz, dtype=jnp.int32)
        pack = _Pack(data, indices, row_ids, valid,
                     A.shape[0], A.shape[1], nnz)
        if csr_array._can_build_cache(A.data, A.indices, A.indptr):
            A._engine_pack = (terms, pack)
        return pack

    # ---------------- dispatch ----------------

    def matvec(self, A, x, _checked: bool = False):
        """``A @ x`` through the bucketed SpMV plan; returns None when
        the matrix/context is ineligible (caller falls back)."""
        import jax.numpy as jnp

        x = jnp.asarray(x)
        if x.ndim != 1 or x.shape[0] != A.shape[1]:
            raise ValueError(
                f"engine.matvec: operand shape {x.shape} does not "
                f"match matrix {A.shape}"
            )
        if not _checked and not self._eligible(A, x.dtype):
            return None
        _rfaults.fault_point("engine.exec.dispatch")
        key = self._key("spmv", A.shape[0], A.shape[1], A.nnz, A.dtype)
        plan, _hit = self._cache.get_or_build(key, BUILDERS["spmv"])
        pack = self._pack_for(A, key)
        x_p = _pad_tail(x.astype(A.dtype), key.cols_b, 0)
        # Obs v4: a request-scoped dispatch (gateway/executor set the
        # trace context) additionally annotates the jax.profiler
        # timeline as engine.spmv[<trace-id>], joining obs flow arcs
        # to XLA profile rows; one contextvar read when no context.
        with _context.profiler_scope("engine.spmv"):
            y_p = plan(pack.data, pack.indices, pack.row_ids,
                       pack.valid, x_p)
        return y_p[: A.shape[0]]

    def matmat(self, A, X, _checked: bool = False):
        """``A @ X`` (dense ``(cols, k)`` operand) through the bucketed
        SpMM plan; None when ineligible.  ``k`` is bucketed too, with
        zero columns padded in and sliced back off."""
        import jax.numpy as jnp

        X = jnp.asarray(X)
        if X.ndim != 2 or X.shape[0] != A.shape[1]:
            raise ValueError(
                f"engine.matmat: operand shape {X.shape} does not "
                f"match matrix {A.shape}"
            )
        if not _checked and not self._eligible(A, X.dtype):
            return None
        k = int(X.shape[1])
        if k == 0:
            return None
        _rfaults.fault_point("engine.exec.dispatch")
        key = self._key("spmm", A.shape[0], A.shape[1], A.nnz, A.dtype,
                        k=k)
        plan, _hit = self._cache.get_or_build(key, BUILDERS["spmm"])
        pack = self._pack_for(A, key)
        X_p = X.astype(A.dtype)
        pad_r = key.cols_b - X_p.shape[0]
        if pad_r:
            X_p = jnp.concatenate(
                [X_p, jnp.zeros((pad_r, k), dtype=X_p.dtype)])
        pad_k = key.k_b - k
        if pad_k:
            X_p = jnp.concatenate(
                [X_p, jnp.zeros((X_p.shape[0], pad_k), dtype=X_p.dtype)],
                axis=1)
        with _context.profiler_scope("engine.spmm"):
            Y_p = plan(pack.data, pack.indices, pack.row_ids,
                       pack.valid, X_p)
        return Y_p[: A.shape[0], :k]

    def multi_matvec(self, pairs, _checked: bool = False):
        """``[A_i @ x_i]`` for matrices sharing ONE shape bucket, as a
        single stacked dispatch (the gateway's cross-tenant batch
        path).  ``pairs`` is a list of ``(A, x)``; every matrix must
        land in the same ``(rows_b, cols_b, nnz_b, dtype)`` bucket —
        the caller groups by that key, so a mismatch raises rather
        than silently splitting.  Returns the list of results, or
        None when any matrix is ineligible or the stacked segment-id
        domain would leave int32 (caller falls back to per-request
        dispatch).  Per matrix the result is bit-for-bit the
        single-matrix plan's (kernel contract)."""
        import jax.numpy as jnp

        if not pairs:
            return []
        if len(pairs) == 1:
            A, x = pairs[0]
            y = self.matvec(A, x, _checked=_checked)
            return None if y is None else [y]
        if not _checked:
            for A, x in pairs:
                if not self._eligible(A, jnp.asarray(x).dtype):
                    return None
        A0 = pairs[0][0]
        key = self._key("spmv_multi", A0.shape[0], A0.shape[1],
                        A0.nnz, A0.dtype, k=len(pairs))
        terms = (key.rows_b, key.cols_b, key.nnz_b, key.dtype)
        for A, _x in pairs[1:]:
            k1 = self._key("spmv", A.shape[0], A.shape[1], A.nnz,
                           A.dtype)
            if (k1.rows_b, k1.cols_b, k1.nnz_b, k1.dtype) != terms:
                raise ValueError(
                    "engine.multi_matvec: matrices span different "
                    "shape buckets")
        if key.k_b * (key.rows_b + 1) > _INT32_MAX:
            return None     # offset segment ids leave int32
        _rfaults.fault_point("engine.exec.dispatch")
        plan, _hit = self._cache.get_or_build(
            key, BUILDERS["spmv_multi"])
        packs = [self._pack_for(A, key) for A, _x in pairs]
        b_pad = key.k_b - len(pairs)
        # Batch-padding slots reuse pack 0's arrays with valid_nnz=0
        # (every product masked to an exact 0) and a zero operand.
        data = jnp.stack([p.data for p in packs]
                         + [packs[0].data] * b_pad)
        indices = jnp.stack([p.indices for p in packs]
                            + [packs[0].indices] * b_pad)
        row_ids = jnp.stack([p.row_ids for p in packs]
                            + [packs[0].row_ids] * b_pad)
        valid = jnp.stack(
            [p.valid for p in packs]
            + [jnp.zeros((), dtype=jnp.int32)] * b_pad)
        zero_x = jnp.zeros((key.cols_b,), dtype=A0.dtype)
        X = jnp.stack(
            [_pad_tail(jnp.asarray(x).astype(A.dtype), key.cols_b, 0)
             for A, x in pairs] + [zero_x] * b_pad)
        Y = plan(data, indices, row_ids, valid, X)
        return [Y[i, : A.shape[0]] for i, (A, _x) in enumerate(pairs)]

    def traceable_matvec(self, A) -> Optional[Callable]:
        """A jax-traceable ``x -> A @ x`` closure over the bucketed
        plan — for solver loops (``linalg.cg`` et al.), where the AOT
        executable cannot appear inside the trace.  Built eagerly
        (plan compiled, pack padded NOW, in a concrete context);
        returns None when ineligible.

        The closure's output is ``[: n]``-sliced before any reduction
        a solver performs, so solver iterates — and the converged
        result — are bit-for-bit the unpadded kernel's."""
        if not self._eligible(A):
            return None
        import jax.numpy as jnp

        key = self._key("spmv", A.shape[0], A.shape[1], A.nnz, A.dtype)
        plan, _hit = self._cache.get_or_build(key, BUILDERS["spmv"])
        pack = self._pack_for(A, key)
        n = A.shape[0]
        cols_b = key.cols_b
        dtype = A.dtype
        traced = plan.traced

        def mv(x):
            x_p = _pad_tail(jnp.asarray(x).astype(dtype), cols_b, 0)
            return traced(pack.data, pack.indices, pack.row_ids,
                          pack.valid, x_p)[:n]

        # Freshness token for callers that hold the closure across
        # possible matrix mutation (linalg's solver route): the pack
        # this closure captured.  A mutation clears A._engine_pack, so
        # `A._engine_pack is not None and A._engine_pack[1] is
        # mv.pack` iff the closure still reads the live operands.
        mv.pack = pack
        return mv

    def record_dist_plan(self, A, op: str = "dist_spmv") -> bool:
        """Ledger one distributed dispatch against its plan identity.

        The executables of distributed plans live in ``dist_csr``'s
        ``lru_cache``'d shard_map builders (keyed on the same layout
        terms); what the engine adds is the identity — mesh
        fingerprint + layout + dtype + epoch — and the hit/miss
        evidence that a second same-layout matrix on the same mesh
        reuses the compiled program instead of re-tracing.
        ``dist_spmv`` itself calls this when routing is enabled, so
        every production dispatch (solvers, bench) feeds the ledger.
        Returns True on a plan hit."""
        from ..parallel.dist_csr import dist_plan_fingerprint

        key = PlanKey(
            op=op,
            dtype=np.dtype(A.dtype).name,
            rows_b=A.rows_padded,
            cols_b=A.shape[1],
            nnz_b=0,
            k_b=1,
            mesh_fp=dist_plan_fingerprint(A),
            epoch=self._settings.epoch,
        )
        plan, hit = self._cache.get_or_build(
            key, lambda k: Plan(k, meta={"kind": op}))
        plan.execs += 1
        _obs.inc(f"engine.plan.{key.plan_id}.execs")
        return hit

    def dist_matvec(self, A, x):
        """Distributed SpMV with its plan-ledger entry recorded (the
        direct-call convenience; with routing enabled ``dist_spmv``
        itself records into the process engine, so this only records
        here when that path won't)."""
        from ..parallel.dist_csr import dist_spmv

        if not _settings_ref.engine:
            self.record_dist_plan(A)
        return dist_spmv(A, x)

    # ---------------- executor ----------------

    @property
    def executor(self):
        """Lazily constructed request executor (settings knobs)."""
        if self._executor is None:
            with self._exec_lock:
                if self._executor is None:
                    from .executor import RequestExecutor

                    self._executor = RequestExecutor(self)
        return self._executor

    def submit(self, A, x):
        """Async SpMV: enqueue for micro-batching, get a Future."""
        return self.executor.submit(A, x)

    # ---------------- introspection ----------------

    def stats(self) -> Dict[str, Any]:
        snap = _obs.counters.snapshot("engine.")
        return {
            "plans": self._cache.stats(),
            "counters": snap,
        }

    def clear(self) -> None:
        """Drop every cached plan (tests; live traffic just misses)."""
        self._cache.clear()

    def shutdown(self) -> None:
        if self._executor is not None:
            self._executor.shutdown()
            self._executor = None


# ---------------------------------------------------------------- singleton

_engine: Optional[Engine] = None
_engine_lock = threading.Lock()


def get_engine() -> Engine:
    """The process-wide engine (created on first use)."""
    global _engine
    # Double-checked init: the unlocked reads are GIL-atomic single
    # references and can at worst observe None and take the lock.
    if _engine is None:  # lint: disable=lock-discipline — double-checked fast path
        with _engine_lock:
            if _engine is None:
                _engine = Engine()
    return _engine  # lint: disable=lock-discipline — GIL-atomic ref read


def reset_engine() -> None:
    """Tear down the singleton (tests / fork hygiene)."""
    global _engine
    with _engine_lock:
        if _engine is not None:
            _engine.shutdown()
        _engine = None


def engine_enabled() -> bool:
    """Fast routing check for the dispatch hot path: one attribute
    read on the settings singleton (imported at module scope — the
    obs overhead contract applies to this dispatch site too)."""
    return _settings_ref.engine


def route_matvec(A, x):
    """Dispatch-site helper: engine result or None (fall through).

    Routing must never make ``A @ x`` fail where the normal dispatch
    would succeed ("settings.engine = True is always safe"): a plan
    build/dispatch error — XLA compile failure on the padded shapes, a
    misconfigured persist dir — is recorded and falls back.

    With resilience on, this is the top rung of the fallback ladder
    (engine -> plain jit dispatch -> scipy-coverage fallback): dispatch
    failures are retried per the ``engine.exec.dispatch`` policy, and
    K consecutive failures trip its circuit breaker — an open breaker
    short-circuits the engine rung entirely (returns None, so the
    plain dispatch serves) until the half-open probe heals it."""
    return _route(A, x, "matvec", "spmv")


def route_matmat(A, X):
    return _route(A, X, "matmat", "spmm")


def _route(A, operand, method: str, op: str):
    if not engine_enabled():
        return None
    if _settings_ref.resil:
        # policy.run owns errors here: retries absorb transients, the
        # breaker converts a persistent engine failure into a plain-
        # dispatch flip (fallback=None result) instead of paying a
        # doomed attempt per call.
        try:
            return _rpolicy.run(
                "engine.exec.dispatch",
                lambda: getattr(get_engine(), method)(A, operand),
                fallback=lambda: _route_error(op, "ladder_flip"),
            )
        except _routcomes.FinalOutcomeError:
            # A verdict from a NESTED engine site — an open
            # engine.plan.build breaker fast-failing a plan compile —
            # must not escape `A @ x`: the engine rung is unavailable,
            # so flip the ladder to the plain dispatch ("engine on is
            # always safe"), same as any other engine-rung failure.
            return _route_error(op, "final_outcome_ladder_flip")
    try:
        return getattr(get_engine(), method)(A, operand)
    except Exception as e:
        return _route_error(op, repr(e)[:200])


def _route_error(op: str, error: str):
    _obs.inc("engine.route.error")
    _obs.event("engine.route.error", op=op, error=error)
    return None


def warmup(plans: Iterable[Dict[str, Any]]) -> List[str]:
    """Module-level convenience: ``get_engine().warmup(plans)``."""
    return get_engine().warmup(plans)
