# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Micro-batching request executor: the admission layer above plans.

Serving-shaped traffic (the GPGPU-cluster SpMV paper's framing) is
many small same-matrix matvec requests arriving concurrently.  One
SpMV moves the whole matrix for one vector; k stacked requests move it
once for k vectors — so the executor coalesces same-plan SpMV
submissions into ONE stacked SpMM dispatch (``csr_spmm_rowids_masked``
computes each column exactly as the SpMV kernel would: batching is
bit-for-bit invisible to callers).

Contract
--------
- ``submit(A, x) -> concurrent.futures.Future`` — thread-safe; callers
  must not mutate ``A`` while requests are in flight.
- A batch dispatches when it reaches ``settings.engine_max_batch``
  requests (in the submitting thread), when its oldest request ages
  past ``settings.engine_batch_timeout_ms`` (background worker), or on
  an explicit ``flush()``.  ``timeout_ms <= 0`` disables the worker —
  fully deterministic dispatch for tests/benchmarks (max-batch and
  ``flush`` only).
- Backpressure: at ``settings.engine_queue_depth`` pending requests, a
  ``submit`` converts into an inline dispatch of the largest group
  (bounded queue without a deadlockable wait) — unless some group's
  oldest request has aged past 2x the batch timeout, in which case the
  oldest such group wins the eviction pick instead (largest-first
  alone would let a small old group starve indefinitely under
  sustained load; ``engine.exec.backpressure_aged`` counts these
  fairness picks).
- Ineligible submissions (matrix on a structure fast path, tracer
  context) dispatch inline through the normal ``A.dot`` — the Future
  contract holds either way.
- Resilience (``LEGATE_SPARSE_TPU_RESIL``, docs/RESILIENCE.md): a
  request submitted under a ``resilience.deadline`` scope carries its
  deadline; queue wait counts against it, and an expired request is
  SHED — its Future resolves with the typed ``outcomes.Rejected``
  value instead of being dispatched (``resil.shed.*`` counters).
- Shutdown safety: live executors are tracked in a module WeakSet and
  drained by one ``atexit`` hook (idempotent ``close``), so requests
  still queued when the interpreter exits are dispatched (or resolved
  with the teardown error) rather than silently dropped with a
  forever-pending Future — while an executor abandoned without
  ``shutdown()`` stays garbage-collectable.

Device-launch discipline: every batch dispatch happens in exactly one
thread at a time per executor (submitting thread or the worker), which
matches the XLA CPU backend's dislike of concurrent collective
launches (tests/test_obs_concurrency.py).

Counters: ``engine.exec.submitted`` / ``.batches`` /
``.batched_requests`` / ``.inline`` / ``.backpressure`` /
``.queue_ns``; each dispatch records an ``engine.batch`` span with the
plan id and batch width.

Request lifecycle telemetry (obs v3, docs/OBSERVABILITY.md): every
submit gets a process-unique request id and timestamped transitions —
submit(=queued) -> batched (popped from the queue into a dispatch
group) -> dispatched -> resolved/shed/inline/fallback/error/rejected.
At
resolution the request emits ONE ``engine.request`` span (cross-thread
complete-span: start at submit, duration = full lifetime) carrying the
decomposition as attrs (``queue_ms`` wait-for-batch, ``batch_ms``
pop-to-dispatch-start, ``dispatch_ms`` dispatch-to-result), an
``engine.exec.outcome.<outcome>`` counter, and the always-on
histograms ``lat.engine.wait.<outcome>`` (queue wait — recorded for
EVERY outcome, so the shed and served wait distributions are
comparable) plus ``lat.engine.request.<shape-bucket>`` (end-to-end
latency; resolved, inline- and fallback-served requests).
``lat.engine.batch_occupancy`` records the width of every dispatched
batch.
"""

from __future__ import annotations

import atexit
import itertools
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

from .. import obs as _obs
from ..obs import attrib as _attrib
from ..obs import context as _context
from ..obs import latency as _latency
from ..obs import trace as _trace
from ..resilience import deadline as _rdeadline
from ..resilience import faults as _rfaults
from ..resilience import outcomes as _routcomes
from ..settings import settings as _rsettings


# Executors with possibly-queued requests, drained once at interpreter
# exit.  A WeakSet (not per-instance ``atexit.register(self.close)``,
# which would hold a strong reference) so an executor abandoned without
# shutdown() stays garbage-collectable — its _anchors dict pins whole
# matrices, which must not accumulate for process lifetime in a
# long-lived server.
_LIVE_EXECUTORS: "weakref.WeakSet[RequestExecutor]" = weakref.WeakSet()


def _drain_live_executors() -> None:
    for ex in list(_LIVE_EXECUTORS):
        ex.close()


_exit_hook_installed = False


def _install_exit_hook_once() -> None:
    # Installed at FIRST construction, not module import: user code
    # that registers its own atexit hooks after importing this module
    # but before building an executor (the drain-regression drill
    # does) still sees the drain run first under atexit's LIFO order,
    # matching the old per-instance registration point.
    global _exit_hook_installed
    if not _exit_hook_installed:
        _exit_hook_installed = True
        atexit.register(_drain_live_executors)


# Process-unique request ids (itertools.count: next() is GIL-atomic).
_REQUEST_IDS = itertools.count(1)


class _Request:
    __slots__ = ("A", "x", "future", "rid", "t_ns", "t_popped",
                 "deadline", "tctx", "_finished")

    def __init__(self, A, x):
        self.A = A
        self.x = x
        self.future: Future = Future()
        self.rid = next(_REQUEST_IDS)
        # Causal identity (obs/context.py): joins an active caller
        # trace (a gateway-routed submit) or mints a fresh one.  Rides
        # the record because contextvars do not cross into the worker
        # thread that dispatches this request.
        self.tctx = _context.mint(rid=self.rid)
        self.t_ns = time.perf_counter_ns()
        # Stamped when the request is popped from the queue into a
        # dispatch group ("batched"); None when it never queued
        # (inline service, admission shed, rejection).
        self.t_popped: Optional[int] = None
        self._finished = False
        # Captured at submit time from the SUBMITTING thread's scope:
        # the worker thread dispatching later sheds against the
        # request's own budget, not its own (absent) scope.
        self.deadline = (_rdeadline.current() if _rsettings.resil
                         else None)

    def finish(self, outcome: str, t_dispatch: Optional[int] = None,
               batch_k: int = 0) -> None:
        """Close the lifecycle ledger for this request — exactly once,
        whatever path resolved it.  ``queue_ms`` is submit -> popped
        (for never-queued outcomes: submit -> now, the full wait),
        ``batch_ms`` popped -> dispatch-body start, ``dispatch_ms``
        dispatch start -> result."""
        if self._finished:
            return
        self._finished = True
        now = time.perf_counter_ns()
        t_pop = self.t_popped if self.t_popped is not None else now
        queue_ms = (t_pop - self.t_ns) / 1e6
        batch_ms = ((t_dispatch - t_pop) / 1e6
                    if t_dispatch is not None else 0.0)
        dispatch_ms = ((now - t_dispatch) / 1e6
                       if t_dispatch is not None else 0.0)
        _obs.inc(f"engine.exec.outcome.{outcome}")
        # Queue wait for EVERY outcome (the shed-vs-served wait
        # comparison the shedder is judged by); end-to-end latency by
        # shape bucket for requests that produced a result.  The
        # attribution ledger charges the same wait to the request's
        # (tenant, qos) identity — shed requests attribute wait only.
        _attrib.on_wait(self.tctx.tenant, self.tctx.qos,
                        t_pop - self.t_ns)
        _latency.observe(f"lat.engine.wait.{outcome}", queue_ms)
        if outcome in ("resolved", "inline", "fallback"):
            _latency.observe(
                "lat.engine.request."
                + _latency.shape_bucket(self.A.shape[0]),
                (now - self.t_ns) / 1e6)
        _trace.complete_span(
            "engine.request", self.t_ns, now - self.t_ns,
            rid=self.rid, outcome=outcome,
            trace_id=self.tctx.trace_id,
            queue_ms=round(queue_ms, 4),
            batch_ms=round(batch_ms, 4),
            dispatch_ms=round(dispatch_ms, 4),
            batch_k=batch_k)

    def shed(self, site: str, reason: str = "deadline_shed") -> None:
        """Resolve with the typed Rejected outcome (never dispatched)."""
        waited_ms = (time.perf_counter_ns() - self.t_ns) / 1e6
        _obs.inc("resil.shed")
        _obs.inc(f"resil.shed.{site}")
        _obs.event("resil.shed", site=site, reason=reason,
                   waited_ms=round(waited_ms, 3))
        self.finish("shed")
        self.future.set_result(_routcomes.Rejected(
            site=site, reason=reason, waited_ms=waited_ms,
            deadline_ms=(self.deadline.total_ms
                         if self.deadline is not None else None)))


class RequestExecutor:
    def __init__(self, engine, max_batch: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 timeout_ms: Optional[float] = None):
        from ..settings import settings

        self._engine = engine
        self.max_batch = max(int(
            max_batch if max_batch is not None
            else settings.engine_max_batch), 1)
        self.queue_depth = max(int(
            queue_depth if queue_depth is not None
            else settings.engine_queue_depth), 1)
        self.timeout_ms = float(
            timeout_ms if timeout_ms is not None
            else settings.engine_batch_timeout_ms)
        self._cv = threading.Condition()
        # Group token -> ordered requests.  Token is the matrix
        # identity: one group = one stacked dispatch against one pack.
        self._groups: Dict[int, List[_Request]] = {}
        self._anchors: Dict[int, object] = {}   # token -> A (strong ref)
        self._pending = 0
        self._worker: Optional[threading.Thread] = None
        self._shutdown = False
        # Serializes _dispatch bodies: a max-batch dispatch in a
        # submitting thread must not overlap the worker's timeout
        # dispatch — concurrent device launches are the pattern that
        # deadlocks the XLA CPU backend for collectives
        # (tests/test_obs_concurrency.py), and collective-backed plans
        # will eventually route through here.
        self._dispatch_lock = threading.Lock()
        # The worker is a daemon thread, so without the module's
        # atexit drain any request still queued at interpreter exit
        # would be silently dropped (its Future never resolves).
        # close() is idempotent and swallows teardown-order errors
        # (JAX may already be gone; the per-request error paths
        # deliver what they can).
        _install_exit_hook_once()
        _LIVE_EXECUTORS.add(self)

    # ---------------- public API ----------------

    def submit(self, A, x) -> Future:
        """Enqueue one SpMV request; resolve via the returned Future."""
        _obs.inc("engine.exec.submitted")
        import jax.numpy as jnp

        # Normalize NOW: an array-less operand (list) would otherwise
        # skip the dtype-promotion gate and batch-dependent casting
        # could change its result dtype.  Also reject a wrong-shape
        # request HERE: batched with others, its dispatch error would
        # fail every future in the group.
        x = jnp.asarray(x)
        if x.shape != (A.shape[1],):
            raise ValueError(
                f"engine submit: operand shape {x.shape} does not "
                f"match matrix {A.shape}"
            )
        req = _Request(A, x)
        if _rsettings.resil:
            # Resilience admission point.  An injected queue fault
            # (error kind) degrades to inline service — the Future
            # contract holds and the queue stays consistent; latency
            # kind sleeps HERE, before the deadline check, so queue-
            # admission delay counts against the request's budget.
            try:
                _rfaults.fault_point("engine.exec.queue")
            except _rfaults.InjectedFault:
                _obs.inc("resil.exec.queue_fault_inline")
                self._resolve_inline(req)
                return req.future
            if req.deadline is not None and req.deadline.expired():
                # Shed at admission: an expired request must never be
                # dispatched (it would displace on-time work).
                req.shed("engine.exec.queue")
                return req.future
        if not self._engine._eligible(A, x.dtype):
            # Serve through the normal dispatch, same Future contract.
            _obs.inc("engine.exec.inline")
            self._resolve_inline(req)
            return req.future
        to_dispatch: List[Tuple[object, List[_Request]]] = []
        with self._cv:
            if self._shutdown:
                # Checked under the lock: a submit racing shutdown()
                # must either land before the final flush or raise —
                # never enqueue into a drained queue (orphaned future).
                req.finish("rejected")
                raise RuntimeError("executor is shut down")
            if self._pending >= self.queue_depth:
                # Bounded queue without a deadlockable wait: the
                # submitter pays for the largest group inline.
                _obs.inc("engine.exec.backpressure")
                item = self._pop_largest_locked()
                if item is not None:
                    to_dispatch.append(item)
            token = id(A)
            group = self._groups.setdefault(token, [])
            self._anchors[token] = A
            group.append(req)
            self._pending += 1
            if len(group) >= self.max_batch:
                self._groups.pop(token)
                self._anchors.pop(token)
                self._pending -= len(group)
                self._stamp_popped(group)
                to_dispatch.append((A, group))
            elif self.timeout_ms > 0:
                self._ensure_worker_locked()
                self._cv.notify_all()
        for item in to_dispatch:
            self._dispatch(*item)
        return req.future

    def flush(self) -> None:
        """Dispatch every pending group now, in the calling thread
        (the deterministic drain used by tests and bench)."""
        while True:
            with self._cv:
                item = self._pop_oldest_locked()
            if item is None:
                return
            self._dispatch(*item)

    def shutdown(self, wait: bool = True) -> None:
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()
            worker = self._worker
        if worker is not None and wait:
            worker.join(timeout=5)
        self.flush()
        try:
            _LIVE_EXECUTORS.discard(self)
        except Exception:  # pragma: no cover - interpreter teardown
            pass

    def close(self) -> None:
        """Idempotent atexit drain: dispatch whatever is still queued
        so no accepted request is silently dropped at interpreter
        exit.  Safe late in teardown — a dispatch that fails because
        JAX is already torn down delivers its error through the
        per-request Future, and any residual error is swallowed (an
        atexit hook must not mask the process's real exit)."""
        try:
            self.shutdown(wait=False)
        except Exception:  # pragma: no cover - teardown-order dependent
            pass

    def pending(self) -> int:
        with self._cv:
            return self._pending

    # ---------------- internals ----------------

    @staticmethod
    def _stamp_popped(group: List[_Request]) -> None:
        """Lifecycle transition queued -> batched: the group just left
        the queue as one dispatch unit."""
        now = time.perf_counter_ns()
        for r in group:
            r.t_popped = now

    def _pop_largest_locked(self):
        """Backpressure eviction pick: normally the LARGEST group
        (best amortization for the inline dispatch the submitter is
        about to pay for) — but a largest-first pick alone is unfair
        under sustained load: a small old group can sit behind an
        endless series of fuller ones and never dispatch.  Any group
        whose oldest request has aged past 2x the batch timeout
        therefore wins the pick (oldest such group first); with
        ``timeout_ms <= 0`` (deterministic flush-only mode) the bound
        is zero and the pick is simply oldest-first."""
        if not self._groups:
            return None
        now = time.perf_counter_ns()
        age_bound_ns = 2.0 * self.timeout_ms * 1e6
        aged = [t for t, g in self._groups.items()
                if now - g[0].t_ns >= age_bound_ns]
        if aged:
            _obs.inc("engine.exec.backpressure_aged")
            token = min(aged, key=lambda t: self._groups[t][0].t_ns)
        else:
            token = max(self._groups,
                        key=lambda t: len(self._groups[t]))
        group = self._groups.pop(token)
        A = self._anchors.pop(token)
        self._pending -= len(group)
        self._stamp_popped(group)
        return A, group

    def _pop_oldest_locked(self):
        if not self._groups:
            return None
        token = min(self._groups,
                    key=lambda t: self._groups[t][0].t_ns)
        group = self._groups.pop(token)
        A = self._anchors.pop(token)
        self._pending -= len(group)
        self._stamp_popped(group)
        return A, group

    def _pop_expired_locked(self, now_ns: int):
        limit = self.timeout_ms * 1e6
        ready = []
        for token in [t for t, g in self._groups.items()
                      if now_ns - g[0].t_ns >= limit]:
            group = self._groups.pop(token)
            self._stamp_popped(group)
            ready.append((self._anchors.pop(token), group))
            self._pending -= len(group)
        return ready

    def _ensure_worker_locked(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._worker_loop,
                name="legate-sparse-engine-executor", daemon=True)
            self._worker.start()

    def _worker_loop(self) -> None:
        while True:
            with self._cv:
                while not self._shutdown and not self._groups:
                    self._cv.wait()
                if self._shutdown:
                    return
                now = time.perf_counter_ns()
                oldest = min(g[0].t_ns for g in self._groups.values())
                wait_s = (oldest + self.timeout_ms * 1e6 - now) / 1e9
                if wait_s > 0:
                    self._cv.wait(wait_s)
                    continue        # re-evaluate after sleep/notify
                ready = self._pop_expired_locked(now)
            for A, group in ready:
                self._dispatch(A, group)

    def _resolve_inline(self, req: _Request,
                        outcome: str = "inline") -> None:
        # Inline service still decomposes: wait ends HERE (the request
        # leaves the queue path), service time is the dispatch leg —
        # lat.engine.wait.inline must stay comparable to the shed/
        # resolved wait distributions, not absorb A.dot's runtime.
        # ``outcome`` distinguishes never-queued inline service
        # ("inline", ~0 wait) from a queued-and-batched request served
        # here after its batch dispatch failed ("fallback", real
        # queue wait) — conflating them would corrupt the ledger.
        t0 = time.perf_counter_ns()
        if req.t_popped is None:
            req.t_popped = t0
        try:
            with _context.use(req.tctx):
                y = req.A.dot(req.x)
            req.finish(outcome, t_dispatch=t0)
            req.future.set_result(y)
        except BaseException as e:   # noqa: BLE001 - future contract
            req.finish("error", t_dispatch=t0)
            req.future.set_exception(e)

    def _dispatch(self, A, group: List[_Request]) -> None:
        """One stacked dispatch for ``group`` (all against ``A``);
        bodies serialize on ``_dispatch_lock`` (one dispatching thread
        at a time per executor)."""
        with self._dispatch_lock:
            self._dispatch_locked(A, group)

    def _dispatch_locked(self, A, group: List[_Request]) -> None:
        import jax.numpy as jnp

        if any(r.deadline is not None for r in group):
            # Flush-time load shedding: queue wait counted against
            # each request's own deadline; expired ones resolve with
            # the typed Rejected outcome instead of being dispatched.
            live = []
            for r in group:
                if r.deadline is not None and r.deadline.expired():
                    r.shed("engine.exec.dispatch")
                else:
                    live.append(r)
            if not live:
                return
            group = live
        k = len(group)
        t_disp = time.perf_counter_ns()
        queue_ns = sum(t_disp - r.t_ns for r in group)
        _obs.inc("engine.exec.batches")
        _obs.inc("engine.exec.batched_requests", k)
        _obs.inc("engine.exec.queue_ns", queue_ns)
        _latency.observe("lat.engine.batch_occupancy", k)
        try:
            # The batch span names every member's trace id (obs v4):
            # the Chrome-trace flow arcs join each request's
            # engine.request span to the batch that served it.  A
            # single-request batch additionally activates that
            # request's context so downstream spans (spmv, dist
            # collectives) auto-tag — a multi-request batch has no
            # single identity to activate.
            with _attrib.scope([(r.tctx.tenant, r.tctx.qos)
                                for r in group]), \
                    _obs.span("engine.batch", reqs=k, rows=A.shape[0],
                              nnz=A.nnz,
                              trace_ids=[r.tctx.trace_id for r in group]
                              ) as sp:
                # Eligibility was checked at submit (_checked=True):
                # re-checking would rebuild structure caches per batch
                # for nothing; mutation-in-flight is out of contract.
                if k == 1:
                    with _context.use(group[0].tctx):
                        y = self._engine.matvec(A, group[0].x,
                                                _checked=True)
                    group[0].finish("resolved", t_dispatch=t_disp,
                                    batch_k=1)
                    group[0].future.set_result(y)
                    if sp is not None:
                        sp.set(path="spmv")
                    return
                X = jnp.stack(
                    [jnp.asarray(r.x).astype(A.dtype) for r in group],
                    axis=1)
                Y = self._engine.matmat(A, X, _checked=True)
                if sp is not None:
                    sp.set(path="spmm", k=k)
                for i, r in enumerate(group):
                    r.finish("resolved", t_dispatch=t_disp, batch_k=k)
                    r.future.set_result(Y[:, i])
        except Exception:
            # Engine-side failure (e.g. a cached plan-build error):
            # the 'engine on is always safe' contract holds for the
            # executor too — serve each request through the normal
            # dispatch; _resolve_inline delivers ITS error if even
            # that fails.
            _obs.inc("engine.exec.dispatch_fallback")
            for r in group:
                if not r.future.done():
                    self._resolve_inline(r, outcome="fallback")
        except BaseException as e:   # noqa: BLE001 - deliver, don't die
            for r in group:
                if not r.future.done():
                    r.finish("error")
                    r.future.set_exception(e)
