# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Multi-tenant admission gateway: QoS, fairness, and overload policy.

The executor (``engine/executor.py``) batches well but queues naively:
one FIFO, so a single flooding caller starves everyone and overload
behavior degrades to whatever backpressure happens to evict.  The
gateway is the admission layer a serving deployment puts in front of
it — per-tenant policy *before* work enters the system:

- **QoS classes** — every request names one of
  ``interactive`` / ``batch`` / ``background`` (:data:`QOS_CLASSES`),
  which sets its weighted-fair-queueing weight and its place in the
  eviction order.
- **Token-bucket rate limits** (``settings.gateway_rate`` requests/s,
  ``settings.gateway_burst`` capacity, per tenant) and **queue
  quotas** (``settings.gateway_tenant_quota`` queued requests per
  tenant): a tenant past its budget is rejected with a typed
  ``outcomes.Rejected`` (reason ``quota`` / ``queue_full``) — its
  flood never occupies another tenant's queue capacity.
- **Weighted fair queueing** — admitted requests get virtual finish
  tags (``start = max(V, tenant_last_finish)``,
  ``tag = start + 1/weight``); batches are formed in ascending-tag
  order across tenant FIFOs, so service share converges to the weight
  ratio regardless of arrival rates.  Requests against *different*
  matrices that land in the same plan-cache shape bucket pack into
  ONE stacked dispatch (``Engine.multi_matvec``; bit-for-bit equal to
  per-request dispatch — kernel contract).
- **Deadline-aware batching** — a request whose deadline slack is
  below ``settings.gateway_slack_ms`` is dispatched immediately (it
  seeds a batch in the submitting thread) instead of waiting for a
  fuller batch; an expired request is shed (reason ``deadline_shed``)
  at admission or at the dispatch flush, never executed.
- **Backpressure** — at ``settings.gateway_queue_depth`` total queued
  requests, admission evicts by *least slack within the lowest QoS
  class* (reason ``queue_full``); when the incoming request is itself
  the weakest candidate it is the one rejected.
- **Breaker-degraded mode** — while the ``gateway.dispatch`` circuit
  breaker is open, non-interactive admissions are shed (reason
  ``breaker``) and interactive ones are served inline through the
  plain dispatch: graceful degradation instead of a queue collapsing
  onto a broken dispatch path.

Isolation is the contract: one tenant's injected faults
(``gateway.admit`` / ``gateway.dispatch`` sites), breaker trips, or
deadline storms must not corrupt another tenant's results or starve
its queue — ``resilience/chaos.py`` drills exactly this under
composed random faults, checking every Future resolves exactly once
with a typed outcome, counters account exactly, and served results
stay bit-for-bit equal to plain dispatch.

Inert by default: with ``LEGATE_SPARSE_TPU_GATEWAY`` unset no call
path routes through the gateway, and ``Gateway.submit`` itself
degrades to a transparent inline dispatch emitting no ``gateway.*``
telemetry — behavior and counters are exactly the engine's.

Counters (``docs/OBSERVABILITY.md``): ``gateway.submitted`` /
``.admitted`` / ``.inline`` / ``.evicted`` / ``.dispatches`` /
``.dispatched_requests`` / ``.packed`` / ``.dispatch_fallback`` /
``.admit_fault_inline`` / ``.dispatch_fault_inline`` /
``.breaker_inline``; per reason ``gateway.rejected.<reason>``; per
outcome ``gateway.outcome.<outcome>``; per tenant
``gateway.tenant.<tenant>.submitted`` / ``.served`` / ``.shed`` /
``.error``.  Histograms: ``lat.gateway.wait.<qos>`` (admission ->
resolution wait, every outcome), ``lat.gateway.request.<qos>``
(end-to-end, served only), ``lat.gateway.batch_occupancy``.
"""

from __future__ import annotations

import atexit
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Dict, List, Optional

from .. import obs as _obs
from ..obs import attrib as _attrib
from ..obs import context as _context
from ..obs import latency as _latency
from ..resilience import deadline as _rdeadline
from ..resilience import faults as _rfaults
from ..resilience import outcomes as _routcomes
from ..resilience import policy as _rpolicy
from ..settings import settings as _rsettings
from .executor import _REQUEST_IDS

#: QoS classes in priority order (index = eviction rank: background is
#: evicted first, interactive last).
QOS_CLASSES = ("interactive", "batch", "background")

#: Default WFQ weights per class — an interactive request costs 1/8th
#: of a background request in virtual time, so under contention the
#: service ratio converges to 8:4:1.
QOS_WEIGHTS = {"interactive": 8.0, "batch": 4.0, "background": 1.0}

_QOS_RANK = {c: i for i, c in enumerate(QOS_CLASSES)}


class TokenBucket:
    """Per-tenant admission rate limit on the monotonic-ns clock.

    ``rate <= 0`` disables the limit (always admits).  Call under the
    gateway lock; refill is computed lazily from elapsed ns, so an
    idle tenant accrues burst capacity without any timer thread."""

    __slots__ = ("rate", "burst", "_tokens", "_t_ns")

    def __init__(self, rate_per_s: float, burst: float):
        self.rate = float(rate_per_s)
        self.burst = max(float(burst), 1.0)
        self._tokens = self.burst
        self._t_ns = time.monotonic_ns()

    def try_take(self) -> bool:
        if self.rate <= 0:
            return True
        now_ns = time.monotonic_ns()
        self._tokens = min(
            self.burst,
            self._tokens + (now_ns - self._t_ns) / 1e9 * self.rate)
        self._t_ns = now_ns
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        return False


class _Tenant:
    """Per-tenant admission state: FIFO of queued requests, WFQ last
    finish tag, token bucket."""

    __slots__ = ("name", "queue", "vfinish", "bucket")

    def __init__(self, name: str, rate: float, burst: float):
        self.name = name
        self.queue: List[_GwRequest] = []
        self.vfinish = 0.0
        self.bucket = TokenBucket(rate, burst)


class _GwRequest:
    """One gateway request and its exactly-once lifecycle ledger."""

    __slots__ = ("A", "x", "future", "rid", "tenant", "qos", "rank",
                 "vtag", "t_ns", "deadline", "shape_key", "tctx",
                 "_finished")

    def __init__(self, A, x, tenant: str, qos: str):
        self.A = A
        self.x = x
        self.future: Future = Future()
        self.rid = next(_REQUEST_IDS)
        self.tenant = tenant
        self.qos = qos
        # Causal identity (obs/context.py): rides the record across
        # the drain-worker thread boundary; the admit span, the batch
        # span's member list, and downstream dispatch spans all carry
        # this id, rendering one connected flow arc per request — and,
        # obs v5, the (tenant, qos) identity the attribution ledger
        # charges dispatch costs to.
        self.tctx = _context.mint(rid=self.rid, tenant=tenant, qos=qos)
        self.rank = _QOS_RANK[qos]
        self.vtag = 0.0
        self.t_ns = time.perf_counter_ns()
        # Submitting thread's deadline scope (same capture rule as the
        # executor: later dispatch sheds against the REQUEST's budget).
        self.deadline = (_rdeadline.current() if _rsettings.resil
                         else None)
        self.shape_key = None
        self._finished = False

    def slack_ms(self) -> float:
        """Milliseconds until this request's deadline (inf without
        one) — the urgency/eviction ordering term."""
        if self.deadline is None:
            return float("inf")
        return self.deadline.remaining_ms()

    def _finish(self, outcome: str) -> bool:
        """Close the ledger exactly once; False when already closed."""
        if self._finished:
            return False
        self._finished = True
        wait_ns = time.perf_counter_ns() - self.t_ns
        wait_ms = wait_ns / 1e6
        _obs.inc(f"gateway.outcome.{outcome}")
        # Every outcome attributes its queue wait (obs/attrib.py):
        # shed/errored requests show wait but zero dispatch cost.
        _attrib.on_wait(self.tenant, self.qos, wait_ns)
        _latency.observe(f"lat.gateway.wait.{self.qos}", wait_ms)
        if outcome == "served":
            _latency.observe(f"lat.gateway.request.{self.qos}",
                             wait_ms)
        return True

    def serve(self, y) -> None:
        if not self._finish("served"):
            return
        _obs.inc(f"gateway.tenant.{self.tenant}.served")
        self.future.set_result(y)

    def shed(self, site: str, reason: str) -> None:
        if not self._finish("shed"):
            return
        waited_ms = (time.perf_counter_ns() - self.t_ns) / 1e6
        _obs.inc(f"gateway.rejected.{reason}")
        _obs.inc(f"gateway.tenant.{self.tenant}.shed")
        _obs.event("gateway.shed", site=site, reason=reason,
                   tenant=self.tenant, qos=self.qos,
                   waited_ms=round(waited_ms, 3))
        self.future.set_result(_routcomes.Rejected(
            site=site, reason=reason, waited_ms=waited_ms,
            deadline_ms=(self.deadline.total_ms
                         if self.deadline is not None else None),
            tenant=self.tenant))

    def error(self, exc: BaseException) -> None:
        if not self._finish("error"):
            return
        _obs.inc(f"gateway.tenant.{self.tenant}.error")
        self.future.set_exception(exc)


# Gateways with possibly-queued requests, drained at interpreter exit
# (same WeakSet discipline as the executor's: abandoned instances stay
# collectable).
_LIVE_GATEWAYS: "weakref.WeakSet[Gateway]" = weakref.WeakSet()
_exit_hook_installed = False


def _drain_live_gateways() -> None:
    for gw in list(_LIVE_GATEWAYS):
        gw.close()


def _install_exit_hook_once() -> None:
    global _exit_hook_installed
    if not _exit_hook_installed:
        _exit_hook_installed = True
        atexit.register(_drain_live_gateways)


class Gateway:
    """Multi-tenant admission gateway over one :class:`Engine` (module
    docstring).  Constructor knobs default to the ``gateway_*``
    settings; tests pass explicit values for determinism
    (``timeout_ms=0`` disables the drain worker — dispatch happens
    only on max-batch, urgency, and ``flush()``)."""

    def __init__(self, engine=None, *, max_batch: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 tenant_quota: Optional[int] = None,
                 rate: Optional[float] = None,
                 burst: Optional[float] = None,
                 slack_ms: Optional[float] = None,
                 timeout_ms: Optional[float] = None):
        from .core import get_engine

        s = _rsettings
        self._engine = engine if engine is not None else get_engine()
        self.max_batch = max(int(max_batch if max_batch is not None
                                 else s.gateway_max_batch), 1)
        self.queue_depth = max(int(
            queue_depth if queue_depth is not None
            else s.gateway_queue_depth), 1)
        self.tenant_quota = max(int(
            tenant_quota if tenant_quota is not None
            else s.gateway_tenant_quota), 1)
        self.rate = float(rate if rate is not None else s.gateway_rate)
        self.burst = float(burst if burst is not None
                           else s.gateway_burst)
        self.slack_ms = float(slack_ms if slack_ms is not None
                              else s.gateway_slack_ms)
        self.timeout_ms = float(timeout_ms if timeout_ms is not None
                                else s.gateway_timeout_ms)
        self._cv = threading.Condition()
        self._tenants: Dict[str, _Tenant] = {}
        self._pending = 0
        self._vtime = 0.0
        self._shutdown = False
        self._worker: Optional[threading.Thread] = None
        # One dispatching thread at a time (same XLA-collective-launch
        # discipline as the executor).
        self._dispatch_lock = threading.Lock()
        _install_exit_hook_once()
        _LIVE_GATEWAYS.add(self)

    # ---------------- public API ----------------

    def submit(self, A, x, tenant: str = "default",
               qos: str = "batch") -> Future:
        """Admit one SpMV request for ``tenant`` at ``qos``; resolve
        via the returned Future (a result array, a typed
        ``outcomes.Rejected``, or an exception)."""
        if qos not in _QOS_RANK:
            raise ValueError(f"unknown qos {qos!r}; one of "
                             f"{QOS_CLASSES}")
        import jax.numpy as jnp

        x = jnp.asarray(x)
        if x.shape != (A.shape[1],):
            raise ValueError(
                f"gateway submit: operand shape {x.shape} does not "
                f"match matrix {A.shape}")
        if not _rsettings.gateway:
            # Inert mode: transparent inline dispatch, no gateway.*
            # telemetry — bit-for-bit and counter-inert vs the plain
            # path (the off-by-default contract).
            fut: Future = Future()
            try:
                fut.set_result(A.dot(x))
            except BaseException as e:  # noqa: BLE001 - future contract
                fut.set_exception(e)
            return fut
        if _rsettings.placement:
            # Per-tenant mesh routing (docs/PLACEMENT.md): a
            # registered tenant's own matrix swaps for a handle
            # pinning the placement version current NOW — in-flight
            # requests drain on their admitted placement while later
            # admissions route to wherever a migration moved the
            # tenant.  One flag read on this line when placement is
            # off (the inertness contract).
            from ..placement import migrate as _placement

            A = _placement.route(A, str(tenant))
        if _rsettings.delta:
            # Versioned mutation serving (docs/MUTATION.md): a
            # submitted DeltaCSR swaps for its current immutable
            # DeltaView — the version pinned NOW — so in-flight
            # requests drain on the pre-compaction view while later
            # admissions serve the freshly merged base.  Same
            # one-flag-read inertness discipline as placement above.
            from ..delta import core as _delta

            A = _delta.route(A)
        req = _GwRequest(A, x, tenant=str(tenant), qos=qos)
        # Obs v4: the whole admission decision runs under the
        # request's trace context, bracketed by one ``gateway.admit``
        # span — the first anchor of the request's flow arc (admit →
        # batch → dispatch).  Batch dispatch stays OUTSIDE the
        # context: a formed batch serves several requests and names
        # its members via the batch span's ``trace_ids`` list instead.
        batch = None
        with _context.use(req.tctx), \
                _obs.span("gateway.admit", rid=req.rid,
                          tenant=req.tenant, qos=req.qos):
            _obs.inc("gateway.submitted")
            _obs.inc(f"gateway.tenant.{req.tenant}.submitted")
            if _rsettings.resil:
                # Admission fault site: error kind degrades to inline
                # service (Future contract holds, queue stays
                # consistent); latency kind sleeps HERE so admission
                # delay counts against the request's own deadline.
                try:
                    _rfaults.fault_point("gateway.admit")
                except _rfaults.InjectedFault:
                    _obs.inc("gateway.admit_fault_inline")
                    self._serve_inline(req)
                    return req.future
                if req.deadline is not None and req.deadline.expired():
                    req.shed("gateway.admit", "deadline_shed")
                    return req.future
                if _rpolicy.breaker("gateway.dispatch").state == "open":
                    if _rsettings.placement:
                        from ..placement import migrate as _placement

                        if _placement.is_placed_handle(req.A):
                            # Breaker-degraded mode with a PLACED
                            # tenant: its traffic never touched the
                            # tripped shared dispatch path — keep
                            # serving on its own submesh and flag the
                            # tenant for a slice shrink instead of
                            # shedding globally (the controller's
                            # next step halves its slice).
                            _obs.inc("placement.degraded_serve")
                            _placement.flag_shrink(req.tenant)
                            self._serve_inline(req)
                            return req.future
                    # Degraded mode: the dispatch path is tripped —
                    # shed deferrable classes instead of queueing onto
                    # a broken path; interactive traffic is served
                    # inline through the plain dispatch.
                    if req.rank > 0:
                        req.shed("gateway.admit", "breaker")
                        return req.future
                    _obs.inc("gateway.breaker_inline")
                    self._serve_inline(req)
                    return req.future
            if not self._engine._eligible(A, x.dtype):
                _obs.inc("gateway.inline")
                self._serve_inline(req)
                return req.future
            key = self._engine._key("spmv", A.shape[0], A.shape[1],
                                    A.nnz, A.dtype)
            req.shape_key = (key.rows_b, key.cols_b, key.nnz_b,
                             key.dtype)
            to_shed: List = []   # (request, site, reason), shed unlocked
            with self._cv:
                if self._shutdown:
                    raise RuntimeError("gateway is shut down")
                ten = self._tenants.get(req.tenant)
                if ten is None:
                    ten = self._tenants[req.tenant] = _Tenant(
                        req.tenant, self.rate, self.burst)
                if not ten.bucket.try_take():
                    to_shed.append((req, "gateway.admit", "quota"))
                elif len(ten.queue) >= self.tenant_quota:
                    to_shed.append((req, "gateway.admit", "queue_full"))
                else:
                    admitted = True
                    if self._pending >= self.queue_depth:
                        victim = self._evict_pick_locked()
                        # Evict only a candidate strictly weaker than
                        # the incoming request; otherwise the incoming
                        # request IS the weakest and is the one
                        # rejected.
                        if (victim is not None
                                and self._evict_key(victim)
                                > self._evict_key(req)):
                            self._remove_locked(victim)
                            _obs.inc("gateway.evicted")
                            to_shed.append(
                                (victim, "gateway.admit", "queue_full"))
                        else:
                            admitted = False
                            to_shed.append(
                                (req, "gateway.admit", "queue_full"))
                    if admitted:
                        _obs.inc("gateway.admitted")
                        start = max(self._vtime, ten.vfinish)
                        weight = QOS_WEIGHTS[req.qos]
                        req.vtag = ten.vfinish = start + 1.0 / weight
                        ten.queue.append(req)
                        self._pending += 1
                        urgent = req.slack_ms() <= self.slack_ms
                        if urgent:
                            batch = self._pop_batch_locked(seed=req)
                        elif self._pending >= self.max_batch:
                            batch = self._pop_batch_locked()
                        elif self.timeout_ms > 0:
                            self._ensure_worker_locked()
                            self._cv.notify_all()
            for victim, site, reason in to_shed:
                victim.shed(site, reason)
        if batch:
            self._dispatch(batch)
        return req.future

    def flush(self) -> None:
        """Dispatch every queued request now, in the calling thread
        (deterministic drain for tests and bench)."""
        while True:
            with self._cv:
                batch = self._pop_batch_locked()
            if not batch:
                return
            self._dispatch(batch)

    def shutdown(self, wait: bool = True) -> None:
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()
            worker = self._worker
        if worker is not None and wait:
            worker.join(timeout=5)
        self.flush()
        try:
            _LIVE_GATEWAYS.discard(self)
        except Exception:  # pragma: no cover - interpreter teardown
            pass

    def close(self) -> None:
        """Idempotent atexit drain (executor ``close`` contract)."""
        try:
            self.shutdown(wait=False)
        except Exception:  # pragma: no cover - teardown-order dependent
            pass

    def pending(self) -> int:
        with self._cv:
            return self._pending

    def stats(self) -> Dict[str, object]:
        """Point-in-time queue snapshot (counters carry the ledger)."""
        with self._cv:
            return {
                "pending": self._pending,
                "tenants": {t.name: len(t.queue)
                            for t in self._tenants.values()},
                "vtime": self._vtime,
            }

    # ---------------- queue internals (hold self._cv) ----------------

    @staticmethod
    def _evict_key(r: _GwRequest):
        """Eviction preference, descending: lowest class first, then
        least slack (the request least likely to make its deadline
        anyway), newest last as the deterministic tie-break."""
        slack = r.slack_ms()
        return (r.rank, -slack if slack != float("inf") else
                float("-inf"), r.rid)

    def _evict_pick_locked(self) -> Optional[_GwRequest]:
        best = None
        for ten in self._tenants.values():
            for r in ten.queue:
                if best is None or self._evict_key(r) > \
                        self._evict_key(best):
                    best = r
        return best

    def _remove_locked(self, req: _GwRequest) -> None:
        ten = self._tenants[req.tenant]
        ten.queue.remove(req)
        self._pending -= 1

    def _wfq_head_locked(self, shape_key=None) -> Optional[_GwRequest]:
        """The next request in WFQ order: minimum virtual finish tag
        across tenant-queue heads (rank, then rid break ties
        deterministically), optionally restricted to one shape
        bucket."""
        best = None
        for ten in self._tenants.values():
            if not ten.queue:
                continue
            head = ten.queue[0]
            if shape_key is not None and head.shape_key != shape_key:
                continue
            if best is None or (head.vtag, head.rank, head.rid) < \
                    (best.vtag, best.rank, best.rid):
                best = head
        return best

    def _pop_batch_locked(self,
                          seed: Optional[_GwRequest] = None
                          ) -> List[_GwRequest]:
        """Form one batch: WFQ order across tenants, all requests from
        the seed's shape bucket (they pack into one stacked dispatch).
        ``seed`` pins an urgent request that must go NOW, wherever it
        sits in its tenant's FIFO."""
        if seed is not None:
            self._remove_locked(seed)
            self._vtime = max(self._vtime, seed.vtag)
            batch = [seed]
        else:
            head = self._wfq_head_locked()
            if head is None:
                return []
            self._remove_locked(head)
            self._vtime = max(self._vtime, head.vtag)
            batch = [head]
        shape_key = batch[0].shape_key
        while len(batch) < self.max_batch:
            nxt = self._wfq_head_locked(shape_key)
            if nxt is None:
                break
            self._remove_locked(nxt)
            self._vtime = max(self._vtime, nxt.vtag)
            batch.append(nxt)
        return batch

    def _ensure_worker_locked(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._worker_loop,
                name="legate-sparse-gateway", daemon=True)
            self._worker.start()

    def _worker_loop(self) -> None:
        while True:
            with self._cv:
                while not self._shutdown and self._pending == 0:
                    self._cv.wait()
                if self._shutdown:
                    return
                now = time.perf_counter_ns()
                oldest = min(t.queue[0].t_ns
                             for t in self._tenants.values()
                             if t.queue)
                wait_s = (oldest + self.timeout_ms * 1e6 - now) / 1e9
                if wait_s > 0:
                    self._cv.wait(wait_s)
                    continue        # re-evaluate after sleep/notify
                batch = self._pop_batch_locked()
            if batch:
                self._dispatch(batch)

    # ---------------- dispatch ----------------

    def _serve_inline(self, req: _GwRequest) -> None:
        """Serve one request through the plain ``A.dot`` dispatch
        (ineligible matrices, fault degradation, fallback) — errors
        resolve THIS request's future only, never a batchmate's.  The
        dispatch runs under a ``gateway.inline`` attribution span
        (``attrib.DISPATCH_SPANS``): placed tenants serve exclusively
        on this path, and without it their busy time — the placement
        controller's demand signal — would never reach the ledger."""
        try:
            with _context.use(req.tctx), \
                    _attrib.scope([(req.tenant, req.qos)]), \
                    _obs.span("gateway.inline", rid=req.rid,
                              tenant=req.tenant, qos=req.qos):
                y = req.A.dot(req.x)
            req.serve(y)
        except BaseException as e:   # noqa: BLE001 - future contract
            req.error(e)

    def _dispatch(self, batch: List[_GwRequest]) -> None:
        with self._dispatch_lock:
            self._dispatch_locked(batch)

    def _dispatch_locked(self, batch: List[_GwRequest]) -> None:
        live = []
        for r in batch:
            if r.deadline is not None and r.deadline.expired():
                # Deadline storm triage at the flush point: expired
                # work buys nothing and displaces on-time requests.
                r.shed("gateway.dispatch", "deadline_shed")
            else:
                live.append(r)
        if not live:
            return
        k = len(live)
        _obs.inc("gateway.dispatches")
        _obs.inc("gateway.dispatched_requests", k)
        _latency.observe("lat.gateway.batch_occupancy", k)
        br = (_rpolicy.breaker("gateway.dispatch")
              if _rsettings.resil else None)
        if _rsettings.resil:
            try:
                _rfaults.fault_point("gateway.dispatch")
            except _rfaults.InjectedFault:
                # Injected dispatch failure: feed the breaker, then
                # serve each request individually through the plain
                # path —
                # a fault drill against one batch must not corrupt or
                # drop any tenant's request.
                if br is not None:
                    br.record_failure()
                _obs.inc("gateway.dispatch_fault_inline")
                for r in live:
                    self._serve_inline(r)
                return
        try:
            # Attribution scope (obs/attrib.py): the batch span's wall
            # time apportions across its member requests; per-group
            # inner scopes in _dispatch_engine narrow comm attribution
            # to the members actually dispatched together.
            with _attrib.scope([(r.tenant, r.qos) for r in live]), \
                    _obs.span("gateway.batch", reqs=k,
                              trace_ids=[r.tctx.trace_id for r in live]
                              ) as sp:
                self._dispatch_engine(live, sp)
        except Exception:
            # Engine-side failure: the gateway inherits the executor's
            # always-safe contract — feed the breaker, serve each
            # unresolved request through the plain dispatch.
            if br is not None:
                br.record_failure()
            _obs.inc("gateway.dispatch_fallback")
            for r in live:
                if not r.future.done():
                    self._serve_inline(r)
        except BaseException as e:   # noqa: BLE001 - deliver, don't die
            for r in live:
                if not r.future.done():
                    r.error(e)
        else:
            if br is not None:
                br.record_success()

    def _dispatch_engine(self, live: List[_GwRequest], sp) -> None:
        import jax.numpy as jnp

        groups: Dict[int, List[_GwRequest]] = {}
        order: List[int] = []
        for r in live:
            token = id(r.A)
            if token not in groups:
                groups[token] = []
                order.append(token)
            groups[token].append(r)
        if len(order) > 1:
            # Cross-matrix pack: one stacked dispatch for the whole
            # batch (requests were batch-formed within one shape
            # bucket).  None = the engine declined (int32 segment-id
            # guard) — fall through to per-matrix dispatch.
            ys = self._engine.multi_matvec(
                [(r.A, r.x) for r in live], _checked=True)
            if ys is not None:
                _obs.inc("gateway.packed")
                if sp is not None:
                    sp.set(path="multi", k=len(live))
                for r, y in zip(live, ys):
                    r.serve(y)
                return
        if sp is not None:
            sp.set(path="grouped", k=len(live), groups=len(order))
        for token in order:
            g = groups[token]
            A = g[0].A
            if len(g) == 1:
                # Single-member group: activate its trace context so
                # the downstream dispatch spans (spmv, dist
                # collectives) auto-tag onto this request's flow arc;
                # the inner attrib scope narrows cost attribution from
                # the whole batch to this one member.
                with _attrib.scope([(g[0].tenant, g[0].qos)]), \
                        _context.use(g[0].tctx):
                    y = self._engine.matvec(A, g[0].x, _checked=True)
                g[0].serve(y)
            else:
                X = jnp.stack(
                    [jnp.asarray(r.x).astype(A.dtype) for r in g],
                    axis=1)
                with _attrib.scope([(r.tenant, r.qos) for r in g]):
                    Y = self._engine.matmat(A, X, _checked=True)
                for i, r in enumerate(g):
                    r.serve(Y[:, i])


# ---------------------------------------------------------------- singleton

_gateway: Optional[Gateway] = None
_gateway_lock = threading.Lock()


def get_gateway() -> Gateway:
    """The process-wide gateway over the process engine (created on
    first use)."""
    global _gateway
    # Double-checked init: the unlocked reads are GIL-atomic single
    # references and can at worst observe None and take the lock.
    if _gateway is None:  # lint: disable=lock-discipline — double-checked fast path
        with _gateway_lock:
            if _gateway is None:
                _gateway = Gateway()
    return _gateway  # lint: disable=lock-discipline — GIL-atomic ref read


def reset_gateway() -> None:
    """Tear down the singleton (tests / fork hygiene)."""
    global _gateway
    with _gateway_lock:
        if _gateway is not None:
            _gateway.shutdown()
        _gateway = None
