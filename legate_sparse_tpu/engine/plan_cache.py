# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Plan cache: bucket-keyed compiled executables.

A *plan* is one AOT-compiled XLA executable (``jax.jit`` lowered and
compiled against ``jax.ShapeDtypeStruct`` operands) for one bucketed
operand shape, keyed on::

    (op, dtype, rows bucket, cols bucket, nnz bucket, k bucket,
     mesh fingerprint, settings epoch)

Calling a plan runs the stored ``Compiled`` object directly — there is
no dispatch-time retrace to even *check* for: the zero-retrace hit
path is structural, and the ``trace.<kernel>`` compile counters prove
it (they increment only while a kernel body is being traced, which for
a plan happens exactly once, inside ``build``).

The settings epoch term means any post-import settings mutation
naturally invalidates plans (stale keys age out of the LRU); the mesh
fingerprint term keys distributed plans to the physical device set
(``parallel.dist_csr.mesh_fingerprint``).  With
``settings.engine_persist_dir`` set, JAX's persistent compilation
cache additionally backs every build, so a *fresh process* pays
deserialization instead of XLA compilation for known buckets.

Counters (always on, ``obs.counters`` contract):

    engine.plan.hits / engine.plan.misses    aggregate cache outcome
    engine.plan.evictions                    LRU pressure
    engine.plan.build_ms                     cumulative compile time
    engine.plan.<plan-id>.hits/.builds/.execs   per-plan rollup
                                             (``trace_summary --plans``)
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from .. import obs as _obs
from ..resilience import faults as _rfaults
from ..resilience import outcomes as _routcomes
from ..resilience import policy as _rpolicy


@dataclass(frozen=True)
class PlanKey:
    """Identity of one compiled plan (see module docstring)."""

    op: str                 # "spmv" | "spmm" | "dist_spmv" | ...
    dtype: str              # canonical numpy dtype name of the values
    rows_b: int             # bucketed output rows
    cols_b: int             # bucketed x/operand length
    nnz_b: int              # bucketed stored-entry count
    k_b: int = 1            # bucketed dense-operand width (SpMM/batch)
    mesh_fp: str = ""       # "" = single-device; folds layout+grid, so
                            # a resharded matrix (parallel.reshard:
                            # new mesh/layout) never aliases its
                            # source's cached plans
    epoch: int = 0          # settings epoch at build time

    @property
    def plan_id(self) -> str:
        """Compact human-readable id used in counter names and the
        ``--plans`` table.  The mesh/layout fingerprint is digested to
        8 hex chars — a prefix truncation would collide two layouts on
        one mesh (``dist_plan_fingerprint`` leads with the mesh
        hash)."""
        pid = (f"{self.op}/{self.dtype}/r{self.rows_b}/c{self.cols_b}"
               f"/z{self.nnz_b}/k{self.k_b}")
        if self.mesh_fp:
            import hashlib

            digest = hashlib.sha1(
                self.mesh_fp.encode()).hexdigest()[:8]
            pid += f"/m{digest}"
        return pid


@dataclass
class Plan:
    """One cached executable plus its ledger.

    ``compiled`` is the AOT executable for eager dispatch (None for
    metadata-only plans, e.g. distributed plans whose executables live
    in the shard_map structure caches); ``traced`` is the jitted
    kernel for use *inside* an ambient trace (solver loops), where an
    AOT executable cannot appear.
    """

    key: PlanKey
    compiled: Optional[Callable] = None
    traced: Optional[Callable] = None
    build_ms: float = 0.0
    hits: int = 0
    execs: int = 0
    meta: Dict[str, Any] = field(default_factory=dict)

    def __call__(self, *args):
        self.execs += 1
        _obs.inc(f"engine.plan.{self.key.plan_id}.execs")
        return self.compiled(*args)


class PlanBuildError(RuntimeError):
    """Raised on the cheap path for a key whose build already failed
    (the negative cache below)."""


class PlanCache:
    """Thread-safe LRU of ``PlanKey -> Plan``."""

    # Bound on the failed-build negative cache (same safety-valve
    # pattern as dist_spgemm's ``_WINDOW_DECLINED``).
    _FAILED_CAP = 256

    def __init__(self, capacity: int = 128):
        self.capacity = max(int(capacity), 1)
        self._lock = threading.Lock()
        self._plans: "OrderedDict[PlanKey, Plan]" = OrderedDict()
        # Keys whose build raised: a reproducible XLA failure must not
        # re-run a multi-second compile attempt on EVERY dispatch of a
        # solver loop — the first failure is cached and later lookups
        # fail fast (routing then falls back to the normal dispatch).
        self._failed: set = set()

    def __len__(self) -> int:
        with self._lock:
            return len(self._plans)

    def lookup(self, key: PlanKey) -> Optional[Plan]:
        """Hit path: returns the plan (LRU-refreshed) or None.  Hit
        counters are bumped here so every caller reports uniformly."""
        with self._lock:
            plan = self._plans.get(key)
            if plan is None:
                return None
            self._plans.move_to_end(key)
            plan.hits += 1
        _obs.inc("engine.plan.hits")
        _obs.inc(f"engine.plan.{key.plan_id}.hits")
        return plan

    def get_or_build(self, key: PlanKey,
                     builder: Callable[[PlanKey], Plan]) -> Tuple[Plan, bool]:
        """Returns ``(plan, hit)``.  The build runs OUTSIDE the cache
        lock — XLA compiles take seconds and must not serialize
        unrelated hits.  Two threads missing the same key concurrently
        may both compile (identical executables; the first insert
        wins) — a rare, benign race that keeps the lock discipline
        trivial; the executor serializes same-plan traffic anyway."""
        plan = self.lookup(key)
        if plan is not None:
            return plan, True
        with self._lock:
            if key in self._failed:
                _obs.inc("engine.plan.failed_fast")
                raise PlanBuildError(
                    f"plan {key.plan_id}: build already failed in "
                    f"this process (cached)")
        _obs.inc("engine.plan.misses")
        _obs.inc(f"engine.plan.{key.plan_id}.builds")
        t0 = time.perf_counter()

        def _build():
            # Resilience site: an injected (or real, transient) XLA
            # compile failure is retried per the engine.plan.build
            # policy before it reaches the negative cache below —
            # only a failure that survives its retry ladder poisons
            # the key.  Inert one flag read with RESIL off.
            _rfaults.fault_point("engine.plan.build")
            return builder(key)

        try:
            with _obs.span("engine.build", plan=key.plan_id):
                plan = _rpolicy.run("engine.plan.build", _build)
        except _routcomes.FinalOutcomeError:
            # A resilience verdict (the site's breaker is open) says
            # nothing about THIS key's buildability — it was never
            # attempted.  Do not poison the negative cache: the key
            # must stay buildable after the breaker heals.
            raise
        except Exception:
            with self._lock:
                if len(self._failed) >= self._FAILED_CAP:
                    self._failed.clear()
                self._failed.add(key)
            _obs.inc("engine.plan.build_failed")
            raise
        plan.build_ms = (time.perf_counter() - t0) * 1e3
        _obs.inc("engine.plan.build_ms", plan.build_ms)
        with self._lock:
            existing = self._plans.get(key)
            if existing is not None:
                # Lost the insert race: adopt the winner (identical
                # executable, and its ledger is the one hits go to).
                plan = existing
            else:
                self._plans[key] = plan
                while len(self._plans) > self.capacity:
                    old_key, _old = self._plans.popitem(last=False)
                    _obs.inc("engine.plan.evictions")
                    _obs.event("engine.plan.evict",
                               plan=old_key.plan_id)
        return plan, False

    def clear(self) -> None:
        with self._lock:
            self._plans.clear()
            self._failed.clear()

    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-plan ledger snapshot (``Engine.stats`` / report)."""
        with self._lock:
            return {
                k.plan_id: {
                    "hits": p.hits,
                    "execs": p.execs,
                    "build_ms": round(p.build_ms, 3),
                    "meta": dict(p.meta),
                }
                for k, p in self._plans.items()
            }


_persist_enabled = False
_persist_lock = threading.Lock()


def maybe_enable_persistent_cache() -> bool:
    """Back plan builds with JAX's persistent compilation cache when
    ``settings.engine_persist_dir`` is set (idempotent; best-effort —
    an old jaxlib without the knobs just skips).  This is what turns
    the plan cache into cross-process warm starts: a fresh serving
    process deserializes known buckets instead of re-running XLA.

    The compilation cache is a PROCESS-GLOBAL jax facility: enabling
    it here persists every XLA compile in the process (non-engine
    kernels included), with the min-compile-time threshold dropped to
    0 so small engine plans qualify.  Deliberate — non-engine retraces
    become warm-startable too — but the operator owns the directory's
    growth (docs/ENGINE.md, scope caveat)."""
    global _persist_enabled
    from ..settings import settings

    path = settings.engine_persist_dir
    if not path:
        return False
    with _persist_lock:
        if _persist_enabled:
            return True
        try:
            import jax

            jax.config.update("jax_compilation_cache_dir", path)
            # Persist everything the engine compiles, not only slow
            # builds (the default threshold skips small kernels).
            try:
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 0.0)
            except Exception:
                pass
            _persist_enabled = True
            _obs.inc("engine.persist.enabled")
            return True
        except Exception as e:  # pragma: no cover - jaxlib-dependent
            _obs.event("engine.persist.failed", error=repr(e)[:200])
            return False


# ---------------------------------------------------------------- builders


def plan_program(key: PlanKey):
    """The (jitted fn, arg_specs, static kwargs, kernel name) quadruple
    a plan for ``key`` lowers — the single spec source shared by the
    compiling builders below and the lower-only verifier hook
    (``lower_plan``), so a contract is checked against EXACTLY the
    program production would compile."""
    import jax

    from ..ops import spmv as spmv_ops
    from ..types import coord_dtype_for

    dt = np.dtype(key.dtype)
    cdt = coord_dtype_for(max(key.cols_b, 1))
    sds = jax.ShapeDtypeStruct
    if key.op == "spmv":
        specs = (
            sds((key.nnz_b,), dt),            # data
            sds((key.nnz_b,), cdt),           # indices
            sds((key.nnz_b,), np.int32),      # row_ids
            sds((), np.int32),                # valid_nnz
            sds((key.cols_b,), dt),           # x
        )
        return (spmv_ops.csr_spmv_rowids_masked, specs,
                {"rows": key.rows_b}, "csr_spmv_rowids_masked")
    if key.op == "spmm":
        specs = (
            sds((key.nnz_b,), dt),
            sds((key.nnz_b,), cdt),
            sds((key.nnz_b,), np.int32),
            sds((), np.int32),
            sds((key.cols_b, key.k_b), dt),
        )
        return (spmv_ops.csr_spmm_rowids_masked, specs,
                {"rows": key.rows_b}, "csr_spmm_rowids_masked")
    if key.op == "spmv_multi":
        b = key.k_b
        specs = (
            sds((b, key.nnz_b), dt),          # stacked data
            sds((b, key.nnz_b), cdt),         # stacked indices
            sds((b, key.nnz_b), np.int32),    # stacked row_ids
            sds((b,), np.int32),              # per-matrix valid_nnz
            sds((b, key.cols_b), dt),         # per-matrix x
        )
        return (spmv_ops.csr_multi_spmv_rowids_masked, specs,
                {"rows": key.rows_b, "b": b},
                "csr_multi_spmv_rowids_masked")
    raise KeyError(f"no plan program for op {key.op!r}")


def lower_plan(key: PlanKey):
    """Lower — WITHOUT compiling — the kernel program
    ``BUILDERS[key.op]`` would AOT-compile for ``key``, against the
    same ``ShapeDtypeStruct`` operands.  Returns the ``jax.stages``
    ``Lowered`` (``.as_text()`` is its StableHLO; ``.jaxpr`` via
    ``jax.make_jaxpr`` on the traced form is the caller's affair).
    This is planverify's entry point: contract checks read the lowered
    IR and never pay (or trigger) an XLA compile."""
    fn, specs, static, _kernel = plan_program(key)
    return fn.lower(*specs, **static)


def _aot(fn, key: PlanKey, arg_specs, **static) -> Callable:
    """Lower + compile ``fn`` (a jitted function) against
    ``ShapeDtypeStruct`` operands — no example arrays materialized."""
    lowered = fn.lower(*arg_specs, **static)
    return lowered.compile()


def build_spmv_plan(key: PlanKey) -> Plan:
    """Bucketed CSR SpMV plan over the masked row-ids kernel.

    Operand layout (what ``matrix_pack`` produces): data/indices padded
    to ``nnz_b`` (zeros / clamped index 0), row ids padded with
    ``rows_b`` — OUT of ``[0, rows_b)``, so ``segment_sum`` drops the
    padded slots entirely (documented jax semantics) and the valid
    prefix reduces in exactly the unpadded order: bit-for-bit equality
    with ``csr_spmv_rowids``."""
    from ..ops import spmv as spmv_ops

    fn, specs, static, kernel = plan_program(key)
    compiled = _aot(fn, key, specs, **static)

    def traced(data, indices, row_ids, valid, x):
        return spmv_ops.csr_spmv_rowids_masked(
            data, indices, row_ids, valid, x, rows=key.rows_b)

    return Plan(key, compiled=compiled, traced=traced,
                meta={"kernel": kernel})


def build_spmm_plan(key: PlanKey) -> Plan:
    """Bucketed CSR SpMM plan (also the executor's stacked-batch
    kernel; same padding contract as the SpMV plan, ``k_b`` wide)."""
    from ..ops import spmv as spmv_ops

    fn, specs, static, kernel = plan_program(key)
    compiled = _aot(fn, key, specs, **static)

    def traced(data, indices, row_ids, valid, X):
        return spmv_ops.csr_spmm_rowids_masked(
            data, indices, row_ids, valid, X, rows=key.rows_b)

    return Plan(key, compiled=compiled, traced=traced,
                meta={"kernel": kernel})


def build_spmv_multi_plan(key: PlanKey) -> Plan:
    """Stacked multi-matrix SpMV plan: ``k_b`` independent matrices
    from the SAME shape bucket (different tenants/matrices, one
    gateway batch) dispatched as one executable.

    Operand slot ``i`` carries matrix ``i``'s pack (the same
    per-matrix pack the SpMV/SpMM plans consume — pack cache terms
    exclude the op, so no re-padding) and its own x vector; segment
    ids are offset per slot by ``rows_b + 1`` so every pack's
    out-of-range padding row id stays in its own discarded segment
    (bit-for-bit contract, see ``csr_multi_spmv_rowids_masked``)."""
    from ..ops import spmv as spmv_ops

    fn, specs, static, kernel = plan_program(key)
    compiled = _aot(fn, key, specs, **static)
    b = key.k_b

    def traced(data, indices, row_ids, valid, X):
        return spmv_ops.csr_multi_spmv_rowids_masked(
            data, indices, row_ids, valid, X, rows=key.rows_b, b=b)

    return Plan(key, compiled=compiled, traced=traced,
                meta={"kernel": kernel})


BUILDERS: Dict[str, Callable[[PlanKey], Plan]] = {
    "spmv": build_spmv_plan,
    "spmm": build_spmm_plan,
    "spmv_multi": build_spmv_multi_plan,
}
