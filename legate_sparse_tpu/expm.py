# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Device-native action of the matrix exponential: ``expm_multiply``.

Computes ``e^{tA} B`` without forming ``e^{tA}`` (scipy
``expm_multiply``; the reference has no matrix-function surface at
all).  TPU-first design: the whole scaling-and-Taylor iteration is one
jitted double ``fori_loop`` of SpMV/SpMM applications — for a block
operand B the inner step is an SpMM, which is exactly the MXU-shaped
workload.

Parameter choice is deliberately table-free (no Al-Mohy-Higham theta
constants): with the trace-shifted operator ``A' = A - mu I`` scaled so
``||t A'||_1 <= s`` with per-step norm <= 1, a fixed Taylor degree
``m`` bounds the truncation error by ``e / (m+1)!``: m=20 gives
~5e-20 (double), m=13 ~4e-11 (single) — below the working precision's
round-off for ``||X|| <= 1``.  This spends at most a few more matvecs
per step than the sharp theta table would, in exchange for no magic
constants; the matvec count stays O(||tA||_1).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .types import index_dtype

__all__ = ["expm_multiply"]


def _one_norm(A) -> float:
    """Exact ||A||_1 (max abs column sum) for sparse/dense operands."""
    try:
        # Package arrays: zero-preserving abs + column-sum kernel.
        return float(np.max(np.asarray(abs(A).sum(axis=0))))
    except Exception:
        return float(np.max(np.sum(np.abs(np.asarray(A)), axis=0)))


def _trace(A) -> complex:
    try:
        return complex(A.trace())
    except Exception:
        return complex(jnp.trace(jnp.asarray(A)))


def _taylor_apply(A_mv, B, t, mu, s, m: int):
    """F = (e^{t(A - mu I)/s})^s B with degree-m Taylor per step, then
    the e^{t mu} factor folded back per step.  One jitted program;
    ``s``/``t``/``mu`` are dynamic (no recompile across time steps or
    operators sharing one matvec closure)."""
    cdtype = B.dtype
    eta = jnp.exp(t * mu / s.astype(t.dtype))

    def outer(i, F):
        def inner(k, carry):
            Bk, acc = carry
            kf = k.astype(jnp.float32).astype(t.dtype)
            Bk = (A_mv(Bk) - mu * Bk) * (t / (s.astype(t.dtype) * kf))
            return Bk, acc + Bk

        _, acc = jax.lax.fori_loop(1, m + 1, inner, (F, F))
        return (eta * acc).astype(cdtype)

    return jax.lax.fori_loop(0, s, outer, B)


# Module-level jit + per-operand matvec cache: repeated expm_multiply
# calls on the same matrix object hit the XLA compile cache instead of
# retracing (the closure is the static arg, so its identity must be
# stable across calls).
_APPLY_JIT = jax.jit(_taylor_apply, static_argnums=(0, 5))
_MV_CACHE: "weakref.WeakKeyDictionary" = None   # built lazily


def _cached_mv(A, key, build):
    """Per-operand {key: closure} cache so the jitted Taylor program's
    static matvec argument keeps a stable identity across calls."""
    global _MV_CACHE
    import weakref

    if _MV_CACHE is None:
        _MV_CACHE = weakref.WeakKeyDictionary()
    try:
        slot = _MV_CACHE.get(A)
    except TypeError:           # unhashable / non-weakrefable operand
        return build()
    if slot is None:
        slot = {}
        try:
            _MV_CACHE[A] = slot
        except TypeError:
            return build()
    if key not in slot:
        slot[key] = build()
    return slot[key]


def expm_multiply(A, B, start=None, stop=None, num=None, endpoint=None,
                  traceA=None):
    """scipy-shaped ``expm_multiply``.

    Single point: returns ``e^A B``.  With ``start/stop/num``: returns
    the stacked ``e^{t_k A} B`` over ``np.linspace(start, stop, num,
    endpoint=endpoint)``, advancing step to step (each interval is one
    jitted Taylor chain, so the full sweep costs one compile).
    LinearOperator inputs (no exact 1-norm available) delegate to host
    scipy.
    """
    from .coverage import scipy_fallback
    from .linalg import LinearOperator, make_linear_operator

    if isinstance(A, LinearOperator):
        import scipy.sparse.linalg as _ssl

        # Re-wrap as a scipy LinearOperator (scipy's internals do
        # operator arithmetic like A - mu*I on it) and supply traceA —
        # scipy calls A.trace() otherwise, which abstract operators
        # lack; a zero shift is always correct (mu only conditions the
        # Taylor scaling, it never changes the result).
        if A.dtype is None:
            A._init_dtype()
        op = A

        def _rmv(x):
            return np.asarray(op.rmatvec(jnp.asarray(x)))

        try:
            op.rmatvec(jnp.zeros((op.shape[0],), dtype=op.dtype))
        except Exception:
            _rmv = None   # scipy's onenormest will report it cleanly
        sp_op = _ssl.LinearOperator(
            op.shape, dtype=op.dtype,
            matvec=lambda x: np.asarray(op.matvec(jnp.asarray(x))),
            rmatvec=_rmv)
        return _ssl.expm_multiply(
            sp_op, np.asarray(B), start=start, stop=stop, num=num,
            endpoint=endpoint,
            traceA=(0.0 if traceA is None else traceA))

    if A.shape[0] != A.shape[1]:
        raise ValueError("expected A to be like a square matrix")

    from .csr import _is_scipy_sparse, csr_array
    from .utils import is_sparse_matrix

    if _is_scipy_sparse(A):
        A = csr_array(A)   # jax-traceable SpMM inside the jitted loop
    n = A.shape[0]
    op = make_linear_operator(A)
    use_spmm = is_sparse_matrix(A)
    B = jnp.asarray(B)
    squeeze = B.ndim == 1
    Bw = B.reshape(n, -1) if squeeze else B

    a_dtype = np.dtype(op.dtype) if op.dtype is not None else Bw.dtype
    cdtype = jnp.result_type(a_dtype, Bw.dtype)
    if not jnp.issubdtype(cdtype, jnp.inexact):
        cdtype = jnp.result_type(cdtype, jnp.float32)
    Bw = Bw.astype(cdtype)
    rdtype = jnp.finfo(cdtype).dtype
    # Degree bound: e/(m+1)! below round-off for per-step norm <= 1.
    m = 13 if jnp.finfo(rdtype).bits == 32 else 20

    mu_c = (_trace(A) if traceA is None else complex(traceA)) / n
    mu = (jnp.asarray(mu_c, dtype=cdtype)
          if jnp.issubdtype(cdtype, jnp.complexfloating)
          else jnp.asarray(mu_c.real, dtype=cdtype))
    norm1 = _one_norm(A) + abs(mu_c)   # shift changes the norm by <= |mu|

    def _build_mv():
        from .linalg import _DenseMatrixLinearOperator

        if use_spmm:
            # SpMM: the MXU-shaped block operand path.
            return lambda X: (A @ X).astype(cdtype)
        if isinstance(op, _DenseMatrixLinearOperator):
            Ad = op.A                   # one GEMM per Taylor term
            return lambda X: (Ad @ X).astype(cdtype)
        return lambda X: jnp.stack(
            [op.matvec(X[:, j]) for j in range(X.shape[1])],
            axis=1).astype(cdtype)

    A_mv = _cached_mv(A, str(cdtype), _build_mv)

    def advance(F, dt: float):
        if dt == 0.0:
            return F
        # A = mu I (or A = 0) needs no special case: the shifted matvec
        # is identically zero, the Taylor sum collapses to F, and the
        # per-step eta factor supplies e^{dt mu} exactly.
        s = max(1, int(np.ceil(norm1 * abs(dt))))
        return _APPLY_JIT(A_mv, F, jnp.asarray(dt, rdtype), mu,
                          jnp.asarray(s, index_dtype()), m)

    if start is None and stop is None and num is None:
        out = advance(Bw, 1.0)
        return np.asarray(out[:, 0] if squeeze else out)

    if num is None:
        num = 50   # scipy default
    if endpoint is None:
        endpoint = True
    ts = np.linspace(float(start), float(stop),
                     int(num), endpoint=endpoint)
    F = advance(Bw, float(ts[0]))
    outs = [F]
    for k in range(1, len(ts)):
        F = advance(F, float(ts[k] - ts[k - 1]))
        outs.append(F)
    stacked = jnp.stack(outs, axis=0)
    if squeeze:
        stacked = stacked[:, :, 0]
    return np.asarray(stacked)
