# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Matrix construction gallery.

Parity with the reference's scipy-compatible ``diags`` constructor
(reference: ``legate_sparse/gallery.py:77-195``): build a DIA data array
from per-diagonal sequences/scalars, then convert to the requested
format.  Layout and validation rules follow scipy (column-aligned DIA).
Offsets/shape handling is done with host numpy (it is O(num_diags)
metadata work); the data array itself is a device array.
"""

from __future__ import annotations

import numbers

import numpy as np

import jax.numpy as jnp

from .dia import dia_array
from .runtime import runtime


def diags(diagonals, offsets=0, shape=None, format=None, dtype=None):
    """Construct a sparse matrix from diagonals (scipy.sparse.diags)."""
    # Normalize: a bare sequence of scalars + scalar offset = one diagonal.
    if np.isscalar(offsets) or isinstance(offsets, numbers.Integral):
        if len(diagonals) == 0 or np.isscalar(diagonals[0]):
            diagonals = [diagonals]
        offsets = [offsets]
    offsets = np.atleast_1d(np.asarray(offsets, dtype=np.int64))
    diagonals = [np.atleast_1d(np.asarray(d)) for d in diagonals]
    if len(diagonals) != len(offsets):
        raise ValueError("number of diagonals != number of offsets")
    if len(np.unique(offsets)) != len(offsets):
        raise ValueError("offset array contains duplicate values")

    if dtype is None:
        dtype = np.result_type(*[d.dtype for d in diagonals])
        if not np.issubdtype(dtype, np.floating) and not np.issubdtype(
            dtype, np.complexfloating
        ):
            dtype = dtype  # keep integer dtypes as scipy does
    dtype = np.dtype(dtype)

    if shape is None:
        m = len(diagonals[0]) + abs(int(offsets[0]))
        shape = (m, m)
    rows, cols = (int(shape[0]), int(shape[1]))

    width = cols  # scipy dia data width
    data = np.zeros((len(offsets), width), dtype=dtype)
    for j, (diag, off) in enumerate(zip(diagonals, offsets)):
        off = int(off)
        length = min(rows + min(off, 0), cols - max(off, 0))
        if length < 0:
            raise ValueError(
                f"Offset {off} (index {j}) out of bounds for shape {shape}"
            )
        start = max(0, off)
        if diag.shape[0] == 1 and length > 1:
            data[j, start : start + length] = diag[0]
        else:
            if diag.shape[0] != length and not (
                diag.shape[0] == 1 and length == 1
            ):
                raise ValueError(
                    f"Diagonal length (index {j}: {diag.shape[0]} at offset "
                    f"{off}) does not agree with array size ({rows}, {cols})."
                )
            data[j, start : start + length] = diag[:length]

    result = dia_array((jnp.asarray(data), jnp.asarray(offsets)),
                       shape=(rows, cols))
    if format in (None, "dia"):
        return result
    return result.asformat(format)


def eye(m, n=None, k=0, dtype=None, format=None):
    """Sparse identity/eye (scipy.sparse.eye shape)."""
    if n is None:
        n = m
    if dtype is None:
        dtype = runtime.default_float
    length = min(int(m) + min(k, 0), int(n) - max(k, 0))
    if length <= 0:
        return diags([np.zeros(0, dtype=dtype)], [0], shape=(int(m), int(n)),
                     format=format, dtype=dtype)
    return diags(
        [np.ones(length, dtype=np.dtype(dtype))], [k],
        shape=(int(m), int(n)), format=format, dtype=dtype,
    )


def identity(n, dtype=None, format=None):
    return eye(n, dtype=dtype, format=format)
