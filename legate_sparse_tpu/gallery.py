# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Matrix construction gallery.

Parity with the reference's scipy-compatible ``diags`` constructor
(reference: ``legate_sparse/gallery.py:77-195``): build a DIA data array
from per-diagonal sequences/scalars, then convert to the requested
format.  Layout and validation rules follow scipy (column-aligned DIA).
Offsets/shape handling is done with host numpy (it is O(num_diags)
metadata work); the data array itself is a device array.
"""

from __future__ import annotations

import numbers

import numpy as np

import jax.numpy as jnp

from .types import index_dtype

from .dia import dia_array
from .runtime import runtime


def diags(diagonals, offsets=0, shape=None, format=None, dtype=None):
    """Construct a sparse matrix from diagonals (scipy.sparse.diags)."""
    # Normalize: a bare sequence of scalars + scalar offset = one diagonal.
    if np.isscalar(offsets) or isinstance(offsets, numbers.Integral):
        if len(diagonals) == 0 or np.isscalar(diagonals[0]):
            diagonals = [diagonals]
        offsets = [offsets]
    offsets = np.atleast_1d(np.asarray(offsets, dtype=np.int64))
    diagonals = [np.atleast_1d(np.asarray(d)) for d in diagonals]
    if len(diagonals) != len(offsets):
        raise ValueError("number of diagonals != number of offsets")
    if len(np.unique(offsets)) != len(offsets):
        raise ValueError("offset array contains duplicate values")

    if dtype is None:
        dtype = np.result_type(*[d.dtype for d in diagonals])
        if not np.issubdtype(dtype, np.floating) and not np.issubdtype(
            dtype, np.complexfloating
        ):
            # scipy.sparse.diags casts integer input to float64 (its
            # FutureWarning notwithstanding), and integer matrices can't
            # reach the SpMV kernels anyway (reference gates dtypes the
            # same way).  Follow the platform float policy.
            dtype = runtime.default_float
    dtype = np.dtype(dtype)

    if shape is None:
        m = len(diagonals[0]) + abs(int(offsets[0]))
        shape = (m, m)
    rows, cols = (int(shape[0]), int(shape[1]))

    width = cols  # scipy dia data width
    data = np.zeros((len(offsets), width), dtype=dtype)
    for j, (diag, off) in enumerate(zip(diagonals, offsets)):
        off = int(off)
        length = min(rows + min(off, 0), cols - max(off, 0))
        if length < 0:
            raise ValueError(
                f"Offset {off} (index {j}) out of bounds for shape {shape}"
            )
        start = max(0, off)
        if diag.shape[0] == 1 and length > 1:
            data[j, start : start + length] = diag[0]
        else:
            if diag.shape[0] != length and not (
                diag.shape[0] == 1 and length == 1
            ):
                raise ValueError(
                    f"Diagonal length (index {j}: {diag.shape[0]} at offset "
                    f"{off}) does not agree with array size ({rows}, {cols})."
                )
            data[j, start : start + length] = diag[:length]

    result = dia_array((jnp.asarray(data), jnp.asarray(offsets)),
                       shape=(rows, cols))
    if format in (None, "dia"):
        return result
    return result.asformat(format)


def eye(m, n=None, k=0, dtype=None, format=None):
    """Sparse identity/eye (scipy.sparse.eye shape)."""
    if n is None:
        n = m
    if dtype is None:
        dtype = runtime.default_float
    length = min(int(m) + min(k, 0), int(n) - max(k, 0))
    if length <= 0:
        return diags([np.zeros(0, dtype=dtype)], [0], shape=(int(m), int(n)),
                     format=format, dtype=dtype)
    return diags(
        [np.ones(length, dtype=np.dtype(dtype))], [k],
        shape=(int(m), int(n)), format=format, dtype=dtype,
    )


def identity(n, dtype=None, format=None):
    return eye(n, dtype=dtype, format=format)


def kron(A, B, format=None):
    """Kronecker product of sparse matrices (scipy ``kron`` semantics).

    Beyond-reference API (the reference falls back to scipy's host
    implementation through the facade clone): computed natively as one
    vectorized COO outer expansion — entry (ra*mB + rb, ca*nB + cb)
    with value va*vb — so the result stays a device ``csr_array``.
    """
    import jax.numpy as jnp

    from .types import coord_dtype_for

    A = _as_csr(A)._canonicalized()
    B = _as_csr(B)._canonicalized()
    mA, nA = A.shape
    mB, nB = B.shape
    cdt = coord_dtype_for(max(mA * mB, nA * nB, 1))
    _require_representable(cdt)
    ra, ca, va = A._coo_parts()
    rb, cb, vb = B._coo_parts()
    ra = ra.astype(cdt)[:, None]
    ca = ca.astype(cdt)[:, None]
    rb = rb.astype(cdt)[None, :]
    cb = cb.astype(cdt)[None, :]
    rows = (ra * mB + rb).reshape(-1)
    cols = (ca * nB + cb).reshape(-1)
    vals = (va[:, None] * vb[None, :]).reshape(-1)
    from .csr import csr_array

    out = csr_array((vals, (rows, cols)), shape=(mA * mB, nA * nB))
    return out.asformat(format)


def _require_representable(cdt) -> None:
    """Raise instead of silently truncating int64 coordinates when x64
    is disabled (same contract as ``kron``)."""
    import jax

    if np.dtype(cdt).itemsize == 8 and not jax.config.jax_enable_x64:
        raise OverflowError(
            "output indices need int64 but x64 is disabled "
            "(LEGATE_SPARSE_TPU_X64=0); enable x64 for shapes this large"
        )


def _as_csr(A):
    """Accept any sparse input (csr_array, dia_array, scipy sparse,
    dense) and return a csr_array — the scipy-parity input contract of
    the free functions below."""
    from .csr import csr_array

    if isinstance(A, csr_array):
        return A
    if hasattr(A, "tocsr"):
        A = A.tocsr()
    if isinstance(A, csr_array):
        return A
    return csr_array(A)


def _tri_mask(A, k: int, keep_lower: bool):
    import jax.numpy as jnp

    from .csr import csr_array
    from .ops.convert import row_ids_from_indptr, indptr_from_row_ids

    A = _as_csr(A)
    row_ids = row_ids_from_indptr(A.indptr, A.nnz)
    d = A.indices.astype(index_dtype()) - row_ids.astype(index_dtype())
    keep = (d <= k) if keep_lower else (d >= k)
    nnz_new = int(jnp.sum(keep))
    from .ops.convert import compact_mask

    data, indices, rows_kept = compact_mask(
        keep, (A.data, A.indices, row_ids), nnz_new
    )
    return csr_array._from_parts(
        data, indices, indptr_from_row_ids(rows_kept, A.shape[0]),
        A.shape, canonical=A._canonical,
    )


def tril(A, k=0, format=None):
    """Lower-triangular part (scipy ``tril`` semantics), computed on
    device by masking ``col - row <= k``."""
    return _tri_mask(A, int(k), keep_lower=True).asformat(format)


def triu(A, k=0, format=None):
    """Upper-triangular part (scipy ``triu`` semantics)."""
    return _tri_mask(A, int(k), keep_lower=False).asformat(format)


def spdiags(data, diags_offsets, m=None, n=None, format=None):
    """scipy.sparse.spdiags: banded constructor from a (nd, n) data
    array in scipy DIA layout (``data[d, j]`` sits on column j)."""
    data = np.atleast_2d(np.asarray(data))
    if not (np.issubdtype(data.dtype, np.floating)
            or np.issubdtype(data.dtype, np.complexfloating)):
        # Same integer-input policy as ``diags``: scipy's doc example
        # passes ints, and integer matrices can't reach the kernels.
        data = data.astype(runtime.default_float)
    if m is None and n is None:
        m = n = data.shape[1]    # scipy >= 1.9 infers a square shape
    if n is None:  # scipy also accepts spdiags(data, offs, (m, n))
        m, n = int(m[0]), int(m[1])
    else:
        m, n = int(m), int(n)
    offsets = np.atleast_1d(np.asarray(diags_offsets, dtype=np.int64))
    if data.shape[1] < n:
        data = np.pad(data, ((0, 0), (0, n - data.shape[1])))
    result = dia_array(
        (jnp.asarray(data[:, :n]), jnp.asarray(offsets)), shape=(m, n)
    )
    if format in (None, "dia"):
        return result
    return result.asformat(format)


def vstack(blocks, format=None, dtype=None):
    """Stack sparse matrices vertically (scipy ``vstack`` for CSR):
    row-wise CSR concatenation — indices unchanged, indptr offset."""
    from .csr import csr_array
    from .utils import cast_to_common_type

    mats = [_as_csr(b) for b in blocks]
    if not mats:
        raise ValueError("blocks must not be empty")
    cols = mats[0].shape[1]
    if any(mat.shape[1] != cols for mat in mats):
        raise ValueError("vstack: mismatching number of columns")
    mats = list(cast_to_common_type(*mats))
    data = jnp.concatenate([mat.data for mat in mats])
    indices = jnp.concatenate([mat.indices for mat in mats])
    parts = [mats[0].indptr]
    offset = mats[0].indptr[-1]
    for mat in mats[1:]:
        parts.append(mat.indptr[1:] + offset)
        offset = offset + mat.indptr[-1]
    indptr = jnp.concatenate(parts)
    rows = sum(mat.shape[0] for mat in mats)
    out = csr_array._from_parts(
        data, indices, indptr, (rows, cols),
        canonical=all(mat.has_canonical_format for mat in mats),
    )
    if dtype is not None:
        out = out.astype(dtype)
    return out.asformat(format)


def hstack(blocks, format=None, dtype=None):
    """Stack sparse matrices horizontally (scipy ``hstack``): COO
    concatenation with column offsets, coalesced back to CSR."""
    from .csr import csr_array
    from .ops.convert import coo_to_csr
    from .types import coord_dtype_for
    from .utils import cast_to_common_type

    mats = [_as_csr(b) for b in blocks]
    if not mats:
        raise ValueError("blocks must not be empty")
    rows = mats[0].shape[0]
    if any(mat.shape[0] != rows for mat in mats):
        raise ValueError("hstack: mismatching number of rows")
    mats = list(cast_to_common_type(*mats))
    cols = sum(mat.shape[1] for mat in mats)
    cdt = coord_dtype_for(max(rows, cols))
    _require_representable(cdt)
    rr, cc, vv = [], [], []
    offset = 0
    for mat in mats:
        r, c, v = mat._coo_parts()
        rr.append(r.astype(cdt))
        cc.append(c.astype(cdt) + np.asarray(offset, dtype=cdt))
        vv.append(v)
        offset += mat.shape[1]
    data, indices, indptr = coo_to_csr(
        jnp.concatenate(rr), jnp.concatenate(cc), jnp.concatenate(vv),
        rows,
    )
    # Blocks occupy disjoint column ranges in ascending order, so the
    # output is canonical exactly when every input is (the stable row
    # sort preserves per-block column order); else unknown.
    out = csr_array._from_parts(
        data, indices, indptr, (rows, cols),
        canonical=(True if all(m.has_canonical_format for m in mats)
                   else None),
    )
    if dtype is not None:
        out = out.astype(dtype)
    return out.asformat(format)


def block_diag(mats, format=None, dtype=None):
    """Block-diagonal sparse matrix (scipy ``block_diag``)."""
    from .csr import csr_array

    from .types import coord_dtype_for

    mats = [_as_csr(b) for b in mats]
    if not mats:
        raise ValueError("blocks must not be empty")
    cols = sum(mat.shape[1] for mat in mats)
    _require_representable(coord_dtype_for(cols))
    cdt = coord_dtype_for(cols)
    padded = []
    col_before = 0
    for mat in mats:
        m_i, n_i = mat.shape
        left = csr_array._from_parts(
            mat.data,
            mat.indices.astype(cdt) + np.asarray(col_before, dtype=cdt),
            mat.indptr, (m_i, cols),
            canonical=mat._canonical,
        )
        padded.append(left)
        col_before += n_i
    out = vstack(padded)
    if dtype is not None:
        out = out.astype(dtype)
    return out.asformat(format)


def random(m, n, density=0.01, format="coo", dtype=None, rng=None,
           random_state=None, data_rvs=None):
    """Random sparse matrix (scipy ``random`` signature incl. the
    legacy ``random_state=`` spelling and ``data_rvs``); the default
    ``format="coo"`` returns a ``coo_array``, matching scipy."""
    from .csr import csr_array

    m, n = int(m), int(n)
    if not 0 <= density <= 1:
        raise ValueError("density expected to be 0 <= density <= 1")
    if rng is None:
        rng = random_state
    rng = rng if isinstance(rng, np.random.Generator) else (
        np.random.default_rng(rng)
    )
    nnz = min(int(round(density * m * n)), m * n)
    flat = rng.choice(m * n, size=nnz, replace=False)
    rows = (flat // n).astype(np.int64)
    cols = (flat % n).astype(np.int64)
    out_dtype = (np.dtype(dtype) if dtype is not None
                 else runtime.default_float)
    if data_rvs is not None:
        vals = np.asarray(data_rvs(nnz)).astype(out_dtype)
    elif np.issubdtype(out_dtype, np.integer):
        # scipy samples random integers for integer dtypes.
        vals = rng.integers(
            np.iinfo(out_dtype).min, np.iinfo(out_dtype).max, size=nnz
        ).astype(out_dtype)
    elif np.issubdtype(out_dtype, np.complexfloating):
        vals = (rng.random(nnz) + 1j * rng.random(nnz)).astype(out_dtype)
    else:
        vals = rng.random(nnz).astype(out_dtype)
    order = np.lexsort((cols, rows))
    A = csr_array(
        (vals[order], (rows[order], cols[order])), shape=(m, n)
    )
    return A.asformat(format)


def powerlaw(m, n=None, nnz_per_row=8, alpha=1.8, rng=None,
             format="csr", dtype=None, directed=True):
    """Power-law (heavy-tailed row-length) random sparse matrix — the
    autotuner's irregular-SpMV and the graph suite's scale-free
    workload.

    Degree distribution: row i's OUT-degree is drawn as
    ``min(nnz_per_row * Zipf(alpha), n)`` — a discrete power law
    P(k) ∝ k^-alpha scaled by the mean-degree knob ``nnz_per_row`` —
    with uniform column (head) endpoints, so in-degrees concentrate
    near Binomial(nnz, 1/n) while out-degrees are heavy-tailed.
    ``alpha`` near 1.5-2 gives the web-graph / social-network skew
    (most rows short, a few huge hubs) that defeats flat-ELL padding
    budgets and starves segment-sum SpMV; larger ``alpha`` thins the
    tail toward a regular matrix.

    ``directed=True`` (default) keeps edges as sampled (the historical
    behavior).  ``directed=False`` symmetrizes — every sampled edge is
    stored in both orientations with the same value, so the result is
    an undirected graph (square only) with power-law TOTAL degree;
    ``nnz`` roughly doubles.  Seeded ``rng`` makes the structure
    deterministic (bench/test usage).  Duplicate coordinates survive
    construction (COO semantics) and merge on the first canonicalizing
    op, so ``nnz`` may slightly undercount after ``sum_duplicates``."""
    from .csr import csr_array

    m = int(m)
    n = m if n is None else int(n)
    rng = rng if isinstance(rng, np.random.Generator) else (
        np.random.default_rng(rng)
    )
    counts = np.minimum(
        nnz_per_row * rng.zipf(alpha, size=m), n
    ).astype(np.int64)
    rows = np.repeat(np.arange(m, dtype=np.int64), counts)
    nnz = int(counts.sum())
    cols = rng.integers(0, n, size=nnz)
    out_dtype = (np.dtype(dtype) if dtype is not None
                 else runtime.default_float)
    vals = rng.random(nnz).astype(out_dtype)
    if not directed:
        if m != n:
            raise ValueError(
                "powerlaw: directed=False requires a square matrix")
        rows, cols = (np.concatenate([rows, cols]),
                      np.concatenate([cols, rows]))
        vals = np.concatenate([vals, vals])
    order = np.lexsort((cols, rows))
    A = csr_array(
        (vals[order], (rows[order], cols[order])), shape=(m, n)
    )
    return A.asformat(format)


def rmat(scale, nnz_per_row=8, a=0.57, b=0.19, c=0.19, rng=None,
         format="csr", dtype=None, directed=True):
    """R-MAT (recursive-matrix) random graph, Graph500-style defaults:
    ``2**scale`` square with ``nnz_per_row * 2**scale`` edges sampled
    by recursive quadrant descent with probabilities ``(a, b, c,
    1-a-b-c)``.

    Degree distribution: the quadrant skew controls the tail — at each
    of the ``scale`` levels an edge lands in quadrant (row-half,
    col-half) with probabilities a (top-left), b (top-right), c
    (bottom-left), d=1-a-b-c; repeated descent concentrates edges on
    low-index vertices, giving approximately power-law in- AND
    out-degrees with heavier tails as ``max(a,b,c,d)`` grows (the
    Graph500 defaults a=0.57, b=c=0.19, d=0.05 target the observed
    web-graph skew; a=b=c=d=0.25 degenerates to an Erdős–Rényi-like
    flat matrix).  ``nnz_per_row`` scales the mean degree.  The skewed
    quadrants produce the power-law degree AND community block
    structure real graphs show — a harder irregular workload than
    :func:`powerlaw`'s independent rows.

    ``directed=True`` (default) keeps edges as sampled;
    ``directed=False`` symmetrizes (both orientations stored with the
    same value — undirected graph, ``nnz`` roughly doubles).
    Vectorized: one ``(nnz, scale)`` uniform block, no Python-level
    recursion.  Duplicate edges survive construction (see
    :func:`powerlaw`)."""
    from .csr import csr_array

    scale = int(scale)
    m = 1 << scale
    d = 1.0 - a - b - c
    if d < 0 or min(a, b, c) < 0:
        raise ValueError(f"quadrant probabilities ({a}, {b}, {c}, "
                         f"{d}) must be non-negative")
    rng = rng if isinstance(rng, np.random.Generator) else (
        np.random.default_rng(rng)
    )
    nnz = int(nnz_per_row) * m
    rows = np.zeros(nnz, dtype=np.int64)
    cols = np.zeros(nnz, dtype=np.int64)
    for _ in range(scale):
        u1 = rng.random(nnz)
        u2 = rng.random(nnz)
        # First split top/bottom by P(bottom) = c + d, then left/right
        # conditioned on the row half (the standard 2x2 factorization).
        row_bit = u1 >= a + b
        p_right = np.where(row_bit, d / max(c + d, 1e-300),
                           b / max(a + b, 1e-300))
        col_bit = u2 < p_right
        rows = rows * 2 + row_bit
        cols = cols * 2 + col_bit
    out_dtype = (np.dtype(dtype) if dtype is not None
                 else runtime.default_float)
    vals = rng.random(nnz).astype(out_dtype)
    if not directed:
        rows, cols = (np.concatenate([rows, cols]),
                      np.concatenate([cols, rows]))
        vals = np.concatenate([vals, vals])
    order = np.lexsort((cols, rows))
    A = csr_array(
        (vals[order], (rows[order], cols[order])), shape=(m, m)
    )
    return A.asformat(format)


def find(A):
    """(row, col, values) of the nonzero entries (scipy ``find``):
    duplicates summed, explicit zeros dropped, returned as numpy
    arrays in row-major order."""
    import jax.numpy as jnp

    from .ops.convert import compact_mask

    A = _as_csr(A)._canonicalized()
    r, c, v = A._coo_parts()
    keep = v != 0
    nnz = int(jnp.sum(keep))
    r2, c2, v2 = compact_mask(keep, (r, c, v), nnz)
    return np.asarray(r2), np.asarray(c2), np.asarray(v2)


def bmat(blocks, format=None, dtype=None):
    """Assemble a sparse matrix from a 2-D grid of sparse blocks
    (scipy ``bmat``); ``None`` entries are zero blocks whose shape is
    inferred from their row/column."""
    from .csr import csr_array

    rows_in = [list(r) for r in blocks]
    if not rows_in or not rows_in[0]:
        raise ValueError("blocks must be a non-empty 2-D grid")
    R, C = len(rows_in), len(rows_in[0])
    if any(len(r) != C for r in rows_in):
        raise ValueError("blocks must have uniform row lengths")
    heights = [None] * R
    widths = [None] * C
    mats = [[None] * C for _ in range(R)]
    for i in range(R):
        for j in range(C):
            b = rows_in[i][j]
            if b is None:
                continue
            m = _as_csr(b)
            mats[i][j] = m
            h, w = m.shape
            if heights[i] is None:
                heights[i] = h
            elif heights[i] != h:
                raise ValueError(
                    f"blocks[{i},:] have incompatible row counts"
                )
            if widths[j] is None:
                widths[j] = w
            elif widths[j] != w:
                raise ValueError(
                    f"blocks[:,{j}] have incompatible column counts"
                )
    if any(h is None for h in heights) or any(w is None for w in widths):
        raise ValueError(
            "every block row and column needs at least one non-None block"
        )
    # Zero blocks take the common dtype of the real blocks so integer
    # grids don't silently upcast to the default float (scipy infers
    # dtype from the given blocks only).
    common = np.result_type(
        *[m.dtype for row in mats for m in row if m is not None]
    )
    out_rows = []
    for i in range(R):
        parts = [
            mats[i][j] if mats[i][j] is not None
            else csr_array((heights[i], widths[j]), dtype=common)
            for j in range(C)
        ]
        out_rows.append(hstack(parts))
    out = vstack(out_rows)
    if dtype is not None:
        out = out.astype(np.dtype(dtype))
    return out.asformat(format)


def block_array(blocks, *, format=None, dtype=None):
    """scipy ``block_array``: ``bmat`` with keyword-only options."""
    return bmat(blocks, format=format, dtype=dtype)


def kronsum(A, B, format=None):
    """Kronecker sum ``kron(A, I_m) + kron(I_n, B)`` for square A
    (n x n) and B (m x m) (scipy ``kronsum``)."""
    A = _as_csr(A)
    B = _as_csr(B)
    if A.shape[0] != A.shape[1]:
        raise ValueError("A is not square")
    if B.shape[0] != B.shape[1]:
        raise ValueError("B is not square")
    # scipy's operand order: kron(I_m, A) + kron(B, I_n).
    L = kron(identity(B.shape[0], dtype=A.dtype), A)
    R_ = kron(B, identity(A.shape[0], dtype=B.dtype))
    return (L + R_).asformat(format)


def mutation_stream(seed, A, n_updates=100, *, insert_frac=0.3,
                    delete_frac=0.1, batch=10, rng=None):
    """Deterministic seeded update stream over an existing sparsity
    pattern — the shared mutation source for tests, chaos drills and
    the bench mutation phase (docs/MUTATION.md).

    Yields ``(rows, cols, vals)`` batches (host int64/float arrays)
    drawn from a mix of three update kinds against the pattern of
    ``A`` (a ``csr_array`` or anything with ``_coo_parts``/scipy
    triple):

    - **overwrite** (the remainder): an existing stored entry gets a
      fresh value — the recommender-weight-refresh case;
    - **insert** (``insert_frac``): a coordinate NOT in the pattern
      gets a new value — edge arrival;
    - **delete** (``delete_frac``): an existing stored entry is set
      to exactly 0.0 — edge removal (the delta layer drops 0.0
      targets structurally at compaction).

    Same ``seed`` (plus the same matrix pattern and knobs) ⇒ the
    bitwise-identical stream, independent of process or platform —
    golden-pinnable by the bench phase.  ``n_updates`` counts
    individual entry updates; the final batch may be short.
    """
    rng = rng if isinstance(rng, np.random.Generator) else (
        np.random.default_rng(seed)
    )
    m, n = A.shape
    if hasattr(A, "_coo_parts"):
        erows, ecols, _ = (np.asarray(p) for p in A._coo_parts())
    else:
        coo = A.tocoo()
        erows, ecols = (np.asarray(coo.row, dtype=np.int64),
                        np.asarray(coo.col, dtype=np.int64))
    erows = erows.astype(np.int64)
    ecols = ecols.astype(np.int64)
    existing = set(zip(erows.tolist(), ecols.tolist()))
    if erows.size == 0 and delete_frac + (1 - insert_frac) > 0:
        raise ValueError("mutation_stream: matrix has no stored "
                         "entries to overwrite or delete")
    n_updates = int(n_updates)
    batch = max(int(batch), 1)
    emitted = 0
    while emitted < n_updates:
        take = min(batch, n_updates - emitted)
        rows = np.zeros(take, dtype=np.int64)
        cols = np.zeros(take, dtype=np.int64)
        vals = np.zeros(take, dtype=np.float64)
        kinds = rng.random(take)
        for i in range(take):
            if kinds[i] < insert_frac:
                # Insert: rejection-sample a coordinate outside the
                # pattern (bounded retry keeps dense corners safe).
                for _ in range(64):
                    r = int(rng.integers(0, m))
                    c = int(rng.integers(0, n))
                    if (r, c) not in existing:
                        break
                existing.add((r, c))
                rows[i], cols[i] = r, c
                vals[i] = float(rng.random()) + 0.5
            elif kinds[i] < insert_frac + delete_frac:
                j = int(rng.integers(0, erows.size))
                rows[i], cols[i] = int(erows[j]), int(ecols[j])
                vals[i] = 0.0
            else:
                j = int(rng.integers(0, erows.size))
                rows[i], cols[i] = int(erows[j]), int(ecols[j])
                vals[i] = float(rng.random()) + 0.5
        emitted += take
        yield rows, cols, vals
