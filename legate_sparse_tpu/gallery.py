# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Matrix construction gallery.

Parity with the reference's scipy-compatible ``diags`` constructor
(reference: ``legate_sparse/gallery.py:77-195``): build a DIA data array
from per-diagonal sequences/scalars, then convert to the requested
format.  Layout and validation rules follow scipy (column-aligned DIA).
Offsets/shape handling is done with host numpy (it is O(num_diags)
metadata work); the data array itself is a device array.
"""

from __future__ import annotations

import numbers

import numpy as np

import jax.numpy as jnp

from .dia import dia_array
from .runtime import runtime


def diags(diagonals, offsets=0, shape=None, format=None, dtype=None):
    """Construct a sparse matrix from diagonals (scipy.sparse.diags)."""
    # Normalize: a bare sequence of scalars + scalar offset = one diagonal.
    if np.isscalar(offsets) or isinstance(offsets, numbers.Integral):
        if len(diagonals) == 0 or np.isscalar(diagonals[0]):
            diagonals = [diagonals]
        offsets = [offsets]
    offsets = np.atleast_1d(np.asarray(offsets, dtype=np.int64))
    diagonals = [np.atleast_1d(np.asarray(d)) for d in diagonals]
    if len(diagonals) != len(offsets):
        raise ValueError("number of diagonals != number of offsets")
    if len(np.unique(offsets)) != len(offsets):
        raise ValueError("offset array contains duplicate values")

    if dtype is None:
        dtype = np.result_type(*[d.dtype for d in diagonals])
        if not np.issubdtype(dtype, np.floating) and not np.issubdtype(
            dtype, np.complexfloating
        ):
            # scipy.sparse.diags casts integer input to float64 (its
            # FutureWarning notwithstanding), and integer matrices can't
            # reach the SpMV kernels anyway (reference gates dtypes the
            # same way).  Follow the platform float policy.
            dtype = runtime.default_float
    dtype = np.dtype(dtype)

    if shape is None:
        m = len(diagonals[0]) + abs(int(offsets[0]))
        shape = (m, m)
    rows, cols = (int(shape[0]), int(shape[1]))

    width = cols  # scipy dia data width
    data = np.zeros((len(offsets), width), dtype=dtype)
    for j, (diag, off) in enumerate(zip(diagonals, offsets)):
        off = int(off)
        length = min(rows + min(off, 0), cols - max(off, 0))
        if length < 0:
            raise ValueError(
                f"Offset {off} (index {j}) out of bounds for shape {shape}"
            )
        start = max(0, off)
        if diag.shape[0] == 1 and length > 1:
            data[j, start : start + length] = diag[0]
        else:
            if diag.shape[0] != length and not (
                diag.shape[0] == 1 and length == 1
            ):
                raise ValueError(
                    f"Diagonal length (index {j}: {diag.shape[0]} at offset "
                    f"{off}) does not agree with array size ({rows}, {cols})."
                )
            data[j, start : start + length] = diag[:length]

    result = dia_array((jnp.asarray(data), jnp.asarray(offsets)),
                       shape=(rows, cols))
    if format in (None, "dia"):
        return result
    return result.asformat(format)


def eye(m, n=None, k=0, dtype=None, format=None):
    """Sparse identity/eye (scipy.sparse.eye shape)."""
    if n is None:
        n = m
    if dtype is None:
        dtype = runtime.default_float
    length = min(int(m) + min(k, 0), int(n) - max(k, 0))
    if length <= 0:
        return diags([np.zeros(0, dtype=dtype)], [0], shape=(int(m), int(n)),
                     format=format, dtype=dtype)
    return diags(
        [np.ones(length, dtype=np.dtype(dtype))], [k],
        shape=(int(m), int(n)), format=format, dtype=dtype,
    )


def identity(n, dtype=None, format=None):
    return eye(n, dtype=dtype, format=format)


def kron(A, B, format=None):
    """Kronecker product of sparse matrices (scipy ``kron`` semantics).

    Beyond-reference API (the reference falls back to scipy's host
    implementation through the facade clone): computed natively as one
    vectorized COO outer expansion — entry (ra*mB + rb, ca*nB + cb)
    with value va*vb — so the result stays a device ``csr_array``.
    """
    import jax.numpy as jnp

    from .types import coord_dtype_for

    A = _as_csr(A)._canonicalized()
    B = _as_csr(B)._canonicalized()
    mA, nA = A.shape
    mB, nB = B.shape
    cdt = coord_dtype_for(max(mA * mB, nA * nB, 1))
    import jax

    if cdt.itemsize == 8 and not jax.config.jax_enable_x64:
        raise OverflowError(
            "kron output indices need int64 but x64 is disabled "
            "(LEGATE_SPARSE_TPU_X64=0); enable x64 for products this "
            "large"
        )
    ra, ca, va = A.tocoo()
    rb, cb, vb = B.tocoo()
    ra = ra.astype(cdt)[:, None]
    ca = ca.astype(cdt)[:, None]
    rb = rb.astype(cdt)[None, :]
    cb = cb.astype(cdt)[None, :]
    rows = (ra * mB + rb).reshape(-1)
    cols = (ca * nB + cb).reshape(-1)
    vals = (va[:, None] * vb[None, :]).reshape(-1)
    from .csr import csr_array

    out = csr_array((vals, (rows, cols)), shape=(mA * mB, nA * nB))
    return out.asformat(format)


def _as_csr(A):
    """Accept any sparse input (csr_array, dia_array, scipy sparse,
    dense) and return a csr_array — the scipy-parity input contract of
    the free functions below."""
    from .csr import csr_array

    if isinstance(A, csr_array):
        return A
    if hasattr(A, "tocsr"):
        A = A.tocsr()
    if isinstance(A, csr_array):
        return A
    return csr_array(A)


def _tri_mask(A, k: int, keep_lower: bool):
    import jax.numpy as jnp

    from .csr import csr_array
    from .ops.convert import row_ids_from_indptr, indptr_from_row_ids

    A = _as_csr(A)
    row_ids = row_ids_from_indptr(A.indptr, A.nnz)
    d = A.indices.astype(jnp.int64) - row_ids.astype(jnp.int64)
    keep = (d <= k) if keep_lower else (d >= k)
    nnz_new = int(jnp.sum(keep))
    from .ops.convert import compact_mask

    data, indices, rows_kept = compact_mask(
        keep, (A.data, A.indices, row_ids), nnz_new
    )
    return csr_array._from_parts(
        data, indices, indptr_from_row_ids(rows_kept, A.shape[0]),
        A.shape, canonical=A._canonical,
    )


def tril(A, k=0, format=None):
    """Lower-triangular part (scipy ``tril`` semantics), computed on
    device by masking ``col - row <= k``."""
    return _tri_mask(A, int(k), keep_lower=True).asformat(format)


def triu(A, k=0, format=None):
    """Upper-triangular part (scipy ``triu`` semantics)."""
    return _tri_mask(A, int(k), keep_lower=False).asformat(format)
