# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Distributed graph analytics: semiring SpMV engine + algorithms.

Graph traversal IS SpMV over a different semiring (the GraphBLAS
observation; the scalable-distributed-SpMV decomposition of
arXiv:1112.5588 applies verbatim once the add/multiply pair is
configurable).  This package holds:

- :mod:`~legate_sparse_tpu.graph.semiring` — the closed semiring
  catalog (``plus-times``, ``min-plus``, ``max-times``, ``or-and``);
- :mod:`~legate_sparse_tpu.graph.algorithms` — distributed BFS, SSSP
  (Bellman-Ford), connected components and PageRank built as iterated
  semiring ``dist_spmv`` (docs/GRAPH.md cookbook);
- :func:`matvec` — the single-device semiring SpMV dispatcher over the
  autotune kernel catalog labels.

The generalized kernels themselves live in ``ops/spmv.py``
(``*_semiring_*``: same masking/IEEE contract as the plus-times
kernels with the padding value generalized to the semiring's additive
identity) and the distributed realizations in ``parallel/dist_csr.py``
(``dist_spmv(..., semiring=)``).
"""

from __future__ import annotations

from .semiring import (  # noqa: F401
    MAX_TIMES,
    MIN_PLUS,
    OR_AND,
    PLUS_TIMES,
    SEMIRINGS,
    Semiring,
    resolve,
)

from .algorithms import (  # noqa: F401
    bfs,
    connected_components,
    pagerank,
    sssp,
)


def matvec(A, x, semiring="plus-times", kernel=None):
    """Single-device semiring SpMV ``y = A (x)`` over the catalog
    kernels, dispatched by autotune registry label.

    ``kernel`` picks the packed structure explicitly: "semiring-csr"
    (default — masked gather/segment-reduce over the row-ids pack),
    "semiring-ell" or "semiring-sliced-ell" (require the matrix's ELL
    / sliced-ELL cache to exist, exactly like the plus-times
    candidates they generalize).  All three produce identical results
    for a given semiring; they are one kernel family with three
    memory layouts, which is why the autotuner may race them.
    """
    import jax.numpy as jnp

    from .. import obs as _obs
    from ..ops import spmv as _sp
    from .semiring import resolve as _resolve

    sr = resolve(semiring) if not isinstance(semiring, Semiring) \
        else semiring
    _obs.inc("graph.matvec." + sr.name)
    label = kernel or "semiring-csr"
    if label == "semiring-ell":
        ell = A._get_ell()
        if ell is None:
            raise ValueError(
                "graph.matvec: kernel='semiring-ell' but the matrix "
                "has no ELL pack (padding budget exceeded?)")
        return _sp.ell_semiring_spmv(ell[0], ell[1], ell[2], x,
                                     sr.add, sr.mul)
    if label == "semiring-sliced-ell":
        bins = A._get_sliced_ell()
        if bins is None:
            raise ValueError(
                "graph.matvec: kernel='semiring-sliced-ell' but the "
                "matrix has no sliced-ELL pack (empty matrix?)")
        return _sp.sliced_ell_semiring_spmv(bins, x, A.shape[0],
                                            sr.add, sr.mul)
    if label != "semiring-csr":
        raise ValueError(f"graph.matvec: unknown kernel {label!r}")
    nnz = jnp.asarray(A.data.shape[0], dtype=jnp.int32)
    return _sp.csr_semiring_spmv_rowids_masked(
        A.data, A.indices, A._get_row_ids(), nnz, x, A.shape[0],
        sr.add, sr.mul)
