# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Distributed graph algorithms as iterated semiring ``dist_spmv``.

Every algorithm here is the same program shape: build the *push
operator* (the transposed — or symmetrized — adjacency, so one
semiring SpMV advances information along edge direction), shard it
over the mesh, and iterate ``y = A_T (x)`` under the algorithm's
semiring with a host-side convergence loop that fetches exactly one
scalar per cycle (the solver modules' one-fetch-per-cycle cadence):

- :func:`bfs` — or-and frontier push; level = the sweep that first
  reaches a vertex;
- :func:`sssp` — Bellman-Ford min-plus relaxation;
- :func:`connected_components` — min-label propagation, which is
  min-plus over the zero-weighted symmetrized structure;
- :func:`pagerank` — damped plus-times power iteration on the
  column-normalized transpose, convergence checked every
  ``conv_test_iters`` iterations.

Multi-source BFS/SSSP batch their frontiers as one (rows, S) operand
through ``dist_spmm(..., semiring=)`` — the distributed arm of the
PR-8 stacked ``multi_matvec`` packing — so S sources cost one
collective schedule per sweep, not S.  (2-d-block layouts are
SpMV-only, so batched sources fall back to a per-source loop there.)

Counters: ``graph.<alg>.runs`` / ``graph.<alg>.iters`` plus the
``graph.dist_spmv.<semiring>`` family rows from the dispatch layer;
all under the ``graph.*`` prefix (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax.numpy as jnp

from .. import obs as _obs
from ..obs import latency as _latency


def _edge_arrays(csgraph, directed: bool, unweighted: bool):
    """Host edge list (rows, cols, w, n) via the csgraph boundary
    helper (stored zeros ARE edges; ``directed=False`` appends the
    reversed copies)."""
    from ..csgraph import _graph_edges

    rows, cols, w, n = _graph_edges(csgraph, directed, unweighted)
    return (np.asarray(rows, dtype=np.int64),
            np.asarray(cols, dtype=np.int64),
            np.asarray(w), n)


def _csr_from_edges(rows, cols, vals, n: int):
    """Package csr_array from a host edge list, deduplicated by
    (row, col) keeping the MINIMUM value — symmetrization can stage
    both stored copies of an undirected edge, and a duplicate must not
    sum (min/or algebra wants one representative; min is the right one
    for every caller here)."""
    from ..csr import csr_array

    key = rows * n + cols
    order = np.lexsort((vals, key))
    key, rows, cols, vals = (key[order], rows[order], cols[order],
                             vals[order])
    first = np.ones(key.shape[0], dtype=bool)
    first[1:] = key[1:] != key[:-1]
    rows, cols, vals = rows[first], cols[first], vals[first]
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(np.bincount(rows, minlength=n), out=indptr[1:])
    return csr_array((vals, cols.astype(np.int64), indptr),
                     shape=(n, n))


def _push_operator(csgraph, directed: bool, unweighted: bool,
                   zero_weights: bool = False):
    """The transposed traversal operator A_T as a package csr_array:
    ``y = A_T (x)`` under the algorithm semiring pushes x along edge
    direction (row u -> col v contributes x[u] to y[v]).
    ``zero_weights`` replaces every weight with 0 (the min-plus
    encoding of label propagation: min over neighbors' labels)."""
    rows, cols, w, n = _edge_arrays(csgraph, directed, unweighted)
    if zero_weights:
        # int32 zeros keep min-plus label propagation in integer
        # algebra end-to-end (no float round-trip for the labels).
        w = np.zeros(w.shape, dtype=np.int32)
    return _csr_from_edges(cols, rows, w, n), n


def _shard_operator(op, mesh, layout):
    from ..parallel import dist_csr as _dc

    return _dc.shard_csr(op, mesh=mesh, layout=layout)


def _shard_vec(v, dA):
    from ..parallel import dist_csr as _dc

    return _dc.shard_vector(jnp.asarray(v), dA.mesh, dA.rows_padded,
                            layout=dA.layout)


def _shard_mat(V, dA):
    from ..parallel import dist_csr as _dc

    return _dc.shard_dense(jnp.asarray(V), dA.mesh, dA.rows_padded)


def _max_iters(n: int, max_iters: Optional[int]) -> int:
    from ..settings import settings

    if max_iters is not None:
        return int(max_iters)
    cap = settings.graph_max_iters
    return int(cap) if cap > 0 else n + 1


def bfs(csgraph, source=0, *, directed: bool = True, mesh=None,
        layout=None, max_iters: Optional[int] = None):
    """Distributed BFS levels by or-and frontier push.

    Returns the int32 level array (hop distance from the source; -1
    unreachable): shape (n,) for a scalar ``source``, (S, n) for a
    sequence (batched frontiers — one ``dist_spmm`` sweep relaxes all
    S sources).  Each sweep fetches one scalar ("any new vertex?").
    Differential twin: ``scipy.sparse.csgraph.breadth_first_order`` /
    unweighted ``dijkstra`` (tests/test_graph.py).
    """
    from ..parallel import dist_csr as _dc

    sources = np.atleast_1d(np.asarray(source, dtype=np.int64))
    scalar = np.ndim(source) == 0
    op, n = _push_operator(csgraph, directed, unweighted=True)
    if np.any((sources < 0) | (sources >= n)):
        raise ValueError(f"bfs: source out of range for n={n}")
    dA = _shard_operator(op, mesh, layout)
    cap = _max_iters(n, max_iters)
    _obs.inc("graph.bfs.runs")
    with _latency.timer("lat.graph.bfs"), \
            _obs.span("graph.bfs", n=n, sources=int(sources.size),
                      layout=dA.layout) as sp:
        batched = sources.size > 1 and dA.grid is None
        if batched:
            F0 = np.zeros((n, sources.size), dtype=bool)
            F0[sources, np.arange(sources.size)] = True
            L0 = np.full((n, sources.size), -1, dtype=np.int32)
            L0[sources, np.arange(sources.size)] = 0
            f = _shard_mat(F0, dA)
            levels = _shard_mat(L0, dA)
            visited = f
            spmv = lambda v: _dc.dist_spmm(dA, v, semiring="or-and")
        else:
            outs = []
            for s in sources:
                outs.append(_bfs_one(dA, int(s), n, cap))
            lv = np.stack(outs) if not scalar else outs[0]
            if sp is not None:
                sp.set(batched=False)
            return lv
        it = 0
        while it < cap:
            nxt = spmv(f)
            new = jnp.logical_and(nxt, jnp.logical_not(visited))
            if not bool(jnp.any(new)):
                break
            it += 1
            levels = jnp.where(new, jnp.int32(it), levels)
            visited = jnp.logical_or(visited, new)
            f = new
        _obs.inc("graph.bfs.iters", it)
        if sp is not None:
            sp.set(iters=it, batched=True)
    return np.asarray(levels)[:n].T


def _bfs_one(dA, s: int, n: int, cap: int) -> np.ndarray:
    from ..parallel import dist_csr as _dc

    f0 = np.zeros(n, dtype=bool)
    f0[s] = True
    l0 = np.full(n, -1, dtype=np.int32)
    l0[s] = 0
    f = _shard_vec(f0, dA)
    visited = f
    levels = _shard_vec(l0, dA)
    it = 0
    while it < cap:
        nxt = _dc.dist_spmv(dA, f, semiring="or-and")
        new = jnp.logical_and(nxt, jnp.logical_not(visited))
        if not bool(jnp.any(new)):
            break
        it += 1
        levels = jnp.where(new, jnp.int32(it), levels)
        visited = jnp.logical_or(visited, new)
        f = new
    _obs.inc("graph.bfs.iters", it)
    return np.asarray(levels)[:n]


def sssp(csgraph, source=0, *, directed: bool = True,
         unweighted: bool = False, mesh=None, layout=None,
         max_iters: Optional[int] = None):
    """Distributed single/multi-source shortest paths by Bellman-Ford
    min-plus relaxation (correct for negative edge weights; raises
    :class:`~..csgraph.NegativeCycleError` on a reachable negative
    cycle, matching the csgraph module).

    Returns float distances, inf unreachable: (n,) for a scalar
    source, (S, n) for a sequence (batched through the semiring
    ``dist_spmm`` on 1-d layouts).  Differential twin:
    ``scipy.sparse.csgraph.dijkstra`` on non-negative weights.
    """
    from ..csgraph import NegativeCycleError
    from ..parallel import dist_csr as _dc

    sources = np.atleast_1d(np.asarray(source, dtype=np.int64))
    scalar = np.ndim(source) == 0
    op, n = _push_operator(csgraph, directed, unweighted)
    if np.any((sources < 0) | (sources >= n)):
        raise ValueError(f"sssp: source out of range for n={n}")
    dA = _shard_operator(op, mesh, layout)
    fdt = np.asarray(op.data).dtype
    # Bellman-Ford terminates in n-1 relaxations on cycle-free
    # distances; improvement at the n-th proves a negative cycle,
    # so the cap is the detector, not a budget.
    cap = n if max_iters is None else _max_iters(n, max_iters)
    _obs.inc("graph.sssp.runs")
    with _latency.timer("lat.graph.sssp"), \
            _obs.span("graph.sssp", n=n, sources=int(sources.size),
                      layout=dA.layout) as sp:
        batched = sources.size > 1 and dA.grid is None
        if batched:
            D0 = np.full((n, sources.size), np.inf, dtype=fdt)
            D0[sources, np.arange(sources.size)] = 0.0
            dist = _shard_mat(D0, dA)
            spmv = lambda v: _dc.dist_spmm(dA, v, semiring="min-plus")
        else:
            if sources.size > 1:
                outs = [sssp(csgraph, int(s), directed=directed,
                             unweighted=unweighted, mesh=mesh,
                             layout=layout, max_iters=max_iters)
                        for s in sources]
                return np.stack(outs)
            d0 = np.full(n, np.inf, dtype=fdt)
            d0[int(sources[0])] = 0.0
            dist = _shard_vec(d0, dA)
            spmv = lambda v: _dc.dist_spmv(dA, v, semiring="min-plus")
        it = 0
        while True:
            relaxed = spmv(dist)
            new = jnp.minimum(dist, relaxed)
            changed = bool(jnp.any(new < dist))
            if not changed:
                break
            it += 1
            dist = new
            if it >= cap:
                raise NegativeCycleError(
                    "sssp: still relaxing after n sweeps — "
                    "reachable negative cycle")
        _obs.inc("graph.sssp.iters", it)
        if sp is not None:
            sp.set(iters=it, batched=batched)
    out = np.asarray(dist)[:n]
    return out.T if batched else (out if scalar else out[None, :])


def connected_components(csgraph, *, mesh=None, layout=None,
                         max_iters: Optional[int] = None):
    """Distributed (weak) connected components by min-label
    propagation — min-plus SpMV over the ZERO-weighted symmetrized
    structure: ``min_j (0 + label[j])`` over neighbors j is exactly
    "adopt the smallest label you can see", iterated to fixpoint in
    O(diameter) sweeps.

    Returns ``(n_components, labels)`` with labels relabeled to
    0..n_components-1 in order of first appearance (scipy's
    convention; the differential test compares partitions up to
    relabeling anyway).
    """
    from ..parallel import dist_csr as _dc

    op, n = _push_operator(csgraph, directed=False, unweighted=True,
                           zero_weights=True)
    dA = _shard_operator(op, mesh, layout)
    cap = _max_iters(n, max_iters)
    _obs.inc("graph.cc.runs")
    with _latency.timer("lat.graph.cc"), \
            _obs.span("graph.cc", n=n, layout=dA.layout) as sp:
        labels = _shard_vec(np.arange(n, dtype=np.int32), dA)
        it = 0
        while it < cap:
            relaxed = _dc.dist_spmv(dA, labels, semiring="min-plus")
            new = jnp.minimum(labels, relaxed.astype(labels.dtype))
            if not bool(jnp.any(new < labels)):
                break
            it += 1
            labels = new
        _obs.inc("graph.cc.iters", it)
        if sp is not None:
            sp.set(iters=it)
    lab = np.asarray(labels)[:n]
    _, relabeled = np.unique(lab, return_inverse=True)
    return int(relabeled.max()) + 1 if n else 0, \
        relabeled.astype(np.int32)


def pagerank(csgraph, *, alpha: float = 0.85, tol: float = 1e-6,
             max_iters: int = 100,
             conv_test_iters: Optional[int] = None, mesh=None,
             layout=None):
    """Distributed PageRank by damped plus-times power iteration on
    the column-normalized transpose M (M[v, u] = 1/outdeg(u) per edge
    u -> v):

        r <- alpha * (M r + dangling_mass / n) + (1 - alpha) / n

    Dangling mass (rank held by zero-out-degree vertices) is a
    device-side dot against the dangling indicator — no extra fetch.
    Convergence (max |r_k - r_{k-cycle}|) is fetched once every
    ``conv_test_iters`` iterations (default
    ``LEGATE_SPARSE_TPU_GRAPH_CONV_ITERS``) — the solver modules'
    one-fetch-per-cycle cadence, which also makes the iteration count
    deterministic at cycle granularity for the bench golden.

    Returns the (n,) rank vector (sums to 1 over real vertices).
    Differential twin: a dense numpy power iteration of the same
    update (tests/test_graph.py).
    """
    from ..parallel import dist_csr as _dc
    from ..settings import settings

    rows, cols, w, n = _edge_arrays(csgraph, directed=True,
                                    unweighted=True)
    if n == 0:
        return np.zeros(0)
    # Dedupe (row, col) BEFORE the degree count: ``_csr_from_edges``
    # keeps one representative per coordinate, so a multigraph edge
    # list (e.g. raw R-MAT output) must not inflate outdeg or M's
    # column sums drop below 1 and rank mass leaks every iteration.
    uniq = np.unique(rows * n + cols)
    rows, cols = uniq // n, uniq % n
    outdeg = np.bincount(rows, minlength=n).astype(np.float64)
    inv_out = np.zeros(n)
    nz = outdeg > 0
    inv_out[nz] = 1.0 / outdeg[nz]
    M = _csr_from_edges(cols, rows, inv_out[rows], n)
    dM = _shard_operator(M, mesh, layout)
    fdt = np.asarray(M.data).dtype
    cycle = int(conv_test_iters or settings.graph_conv_iters)
    r = _shard_vec(np.full(n, 1.0 / n, dtype=fdt), dM)
    dang = _shard_vec((~nz).astype(fdt), dM)
    # Real-row mask: rows_padded > n tail rows must stay exactly 0 or
    # the teleport term would leak rank mass into padding.
    mask = _shard_vec(np.ones(n, dtype=fdt), dM)
    inv_n = 1.0 / n
    _obs.inc("graph.pagerank.runs")
    it = 0
    with _latency.timer("lat.graph.pagerank"), \
            _obs.span("graph.pagerank", n=n, layout=dM.layout) as sp:
        while it < max_iters:
            r_prev = r
            for _ in range(cycle):
                y = _dc.dist_spmv(dM, r)
                dm = jnp.vdot(dang, r)
                r = mask * (alpha * (y + dm * inv_n)
                            + (1.0 - alpha) * inv_n)
                it += 1
                if it >= max_iters:
                    break
            delta = float(jnp.max(jnp.abs(r - r_prev)))
            if delta < tol:
                break
        _obs.inc("graph.pagerank.iters", it)
        if sp is not None:
            sp.set(iters=it)
    return np.asarray(r)[:n]
