# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""The closed semiring catalog the graph engine computes over.

A semiring (add, multiply, additive identity) generalizes the
matrix-vector product: ``y[i] = ADD_j data[i, j] MUL x[j]`` over the
stored entries of row ``i``.  Four closed semirings cover the
classical traversal algorithms (docs/GRAPH.md cookbook):

=============  =====  ========  ==================  =================
name           add    multiply  additive identity   algorithm
=============  =====  ========  ==================  =================
``plus-times`` sum    a * x     0                   PageRank / linalg
``min-plus``   min    a + x     +inf                SSSP, CC labels
``max-times``  max    a * x     -inf                widest/best path
``or-and``     or     a AND x   False               BFS frontiers
=============  =====  ========  ==================  =================

In every entry the additive identity is ALSO the multiplicative
annihilator (0*x = 0; inf + x = inf; -inf capped products; False AND x
= False), which is exactly what lets the padded-slot masking of the
``ops/spmv.py`` kernels generalize: a padded slot's *product* is
replaced by the identity/annihilator and the segment reduction
absorbs it, the same IEEE discipline as the plus-times kernels (mask
the product, never the operand).

``add`` / ``mul`` are the static strings the jitted kernels branch on
(``sum``/``min``/``max`` segment reductions; ``times``/``plus``/``and``
products — ``or`` IS ``max`` over booleans, so no fourth reduction
exists in the lowered IR).  ``collective`` names the cross-shard
all-reduce the 2-d-block distributed realization performs
(psum -> pmin/pmax/por), which is also the ``comm.<op>.<collective>``
ledger kind it is priced under.

The ``or-and`` multiply is *structural*: a stored entry IS an edge
(matching ``csgraph``'s explicit-zero convention), so the product is
the gathered frontier bit, not value arithmetic — an explicitly
stored zero still propagates the frontier.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Union

import jax.numpy as jnp


@dataclass(frozen=True)
class Semiring:
    """One closed semiring: the (add, multiply) pair plus the derived
    static dispatch/pricing fields (see module docstring)."""

    name: str
    add: str             # segment reduction: "sum" | "min" | "max"
    mul: str             # product: "times" | "plus" | "and"
    collective: str      # cross-shard add all-reduce / ledger kind

    def identity(self, dtype):
        """Additive identity as a rank-0 array of ``dtype`` — the
        value padded slots are masked to (== the multiplicative
        annihilator for every catalog entry)."""
        dtype = jnp.dtype(dtype)
        if self.add == "sum":
            return jnp.zeros((), dtype=dtype)
        if dtype == jnp.bool_:
            # or (= max over booleans): False; and-of-all (min): True.
            return jnp.asarray(self.add == "min", dtype=dtype)
        if jnp.issubdtype(dtype, jnp.floating):
            return jnp.asarray(
                jnp.inf if self.add == "min" else -jnp.inf, dtype=dtype)
        info = jnp.iinfo(dtype)
        return jnp.asarray(
            info.max if self.add == "min" else info.min, dtype=dtype)

    def annihilator(self, dtype):
        """Multiplicative annihilator (identical to the additive
        identity in this closed catalog; kept as its own accessor so
        callers state which role they mean)."""
        return self.identity(dtype)


PLUS_TIMES = Semiring("plus-times", add="sum", mul="times",
                      collective="psum")
MIN_PLUS = Semiring("min-plus", add="min", mul="plus",
                    collective="pmin")
MAX_TIMES = Semiring("max-times", add="max", mul="times",
                     collective="pmax")
OR_AND = Semiring("or-and", add="max", mul="and",
                  collective="por")

SEMIRINGS: Dict[str, Semiring] = {
    s.name: s for s in (PLUS_TIMES, MIN_PLUS, MAX_TIMES, OR_AND)
}


def resolve(semiring: Union[str, Semiring]) -> Semiring:
    """Catalog lookup accepting a name or a :class:`Semiring`
    (pass-through — user-defined closed semirings with the same
    ``add``/``mul`` vocabulary dispatch over the same kernels)."""
    if isinstance(semiring, Semiring):
        return semiring
    try:
        return SEMIRINGS[semiring]
    except KeyError:
        raise ValueError(
            f"unknown semiring {semiring!r}; catalog: "
            f"{sorted(SEMIRINGS)}") from None
