# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Matrix Market IO.

Parity with the reference's ``mmread`` (reference:
``legate_sparse/io.py:27-55`` driving the single-task C++ parser
``src/sparse/io/mtx_to_coo.cc:31-143``): reads ``coordinate`` matrices
with real/integer/pattern fields and general/symmetric/skew-symmetric
symmetry (symmetric entries doubled off-diagonal, as the reference does),
producing a ``csr_array``.

Two parser tiers: a native C++ parser (``src/mtx_reader.cc``, loaded via
ctypes — the analog of the reference's C++ leaf task) with a numpy
fallback.  Both run on host; the COO->CSR sort happens on device.
"""

from __future__ import annotations

import numpy as np

from jax.numpy import asarray as jnp_asarray

from .csr import csr_array
from .utils import asarray_1d  # noqa: F401


def _parse_mtx_host(path: str):
    """Pure-numpy matrix-market coordinate parser."""
    with open(path, "rb") as f:
        header = f.readline().decode().strip().lower().split()
        if len(header) < 5 or header[0] != "%%matrixmarket":
            raise ValueError(f"{path}: not a MatrixMarket file")
        _, obj, fmt, field, symmetry = header[:5]
        if obj != "matrix" or fmt != "coordinate":
            raise NotImplementedError(
                f"only 'matrix coordinate' supported, got {obj} {fmt}"
            )
        if field not in ("real", "integer", "pattern", "double"):
            raise NotImplementedError(f"unsupported field {field}")
        if symmetry not in ("general", "symmetric", "skew-symmetric"):
            raise NotImplementedError(f"unsupported symmetry {symmetry}")
        # Skip comments.
        line = f.readline()
        while line.startswith(b"%"):
            line = f.readline()
        m, n, nnz = (int(tok) for tok in line.split())
        raw = np.loadtxt(f, ndmin=2) if nnz > 0 else np.zeros((0, 3))
    if nnz == 0:
        r0 = np.zeros(0, dtype=np.int64)
        c0 = np.zeros(0, dtype=np.int64)
        v0 = np.zeros(0, dtype=np.float64)
    else:
        r0 = raw[:, 0].astype(np.int64) - 1
        c0 = raw[:, 1].astype(np.int64) - 1
        if field == "pattern":
            v0 = np.ones(raw.shape[0], dtype=np.float64)
        else:
            v0 = raw[:, 2].astype(np.float64)
    if symmetry in ("symmetric", "skew-symmetric"):
        # Mirror off-diagonal entries (reference doubles them the same
        # way, ``mtx_to_coo.cc:31-143``).
        off = r0 != c0
        sign = -1.0 if symmetry == "skew-symmetric" else 1.0
        rows = np.concatenate([r0, c0[off]])
        cols = np.concatenate([c0, r0[off]])
        vals = np.concatenate([v0, sign * v0[off]])
    else:
        rows, cols, vals = r0, c0, v0
    return m, n, rows, cols, vals


def mmread(source) -> csr_array:
    """Read a MatrixMarket file into a csr_array.

    Pipeline: native (or numpy) host parse -> native stable COO->CSR
    counting sort when available (skips the device argsort for host
    data) -> one device transfer of the final CSR triple.
    """
    path = str(source)
    try:
        from .utils_native import native_mtx_read

        parsed = native_mtx_read(path)
    except Exception:
        parsed = None
    if parsed is None:
        m, n, rows, cols, vals = _parse_mtx_host(path)
    else:
        m, n, rows, cols, vals = parsed
    try:
        from .utils_native import native_coo_to_csr

        converted = native_coo_to_csr(
            np.asarray(rows), np.asarray(cols), np.asarray(vals), m
        )
    except Exception:
        converted = None
    if converted is not None:
        # Normalize to the canonical dtypes every constructor applies
        # (coord_dtype_for / nnz_dtype()) so the parsed matrix has the same
        # index dtypes whether or not the native library is present.
        from .types import check_nnz, coord_dtype_for, nnz_dtype

        data, indices, indptr = converted
        check_nnz(int(indptr[-1]))
        return csr_array._from_parts(
            jnp_asarray(data),
            jnp_asarray(indices.astype(coord_dtype_for(max(m, n)))),
            jnp_asarray(indptr.astype(nnz_dtype())),
            (m, n), canonical=None,
        )
    return csr_array((vals, (rows, cols)), shape=(m, n))


def mmwrite(target, a) -> None:
    """Write a sparse matrix to MatrixMarket format (reference has
    no writer — checkpoint/output parity gap filled here)."""
    from .csr import csr_array as _csr

    if not isinstance(a, _csr):
        a = _csr(a)
    rows, cols, vals = a._coo_parts()
    rows = np.asarray(rows)
    cols = np.asarray(cols)
    vals = np.asarray(vals)
    with open(str(target), "w") as f:
        f.write("%%MatrixMarket matrix coordinate real general\n")
        f.write(f"{a.shape[0]} {a.shape[1]} {a.nnz}\n")
        for r, c, v in zip(rows, cols, vals):
            f.write(f"{r + 1} {c + 1} {float(v):.17g}\n")


def save_npz(file, matrix, compressed: bool = True) -> None:
    """Persist a csr_array in scipy's ``save_npz`` container format
    (round-trips with ``scipy.sparse.load_npz`` and vice versa).

    Checkpoint/persistence beyond the reference (reader-only IO,
    reference ``io.py:27-55``).
    """
    import numpy as _np

    from .gallery import _as_csr

    matrix = _as_csr(matrix)
    data = _np.asarray(matrix.data)
    arrays = dict(
        format=_np.array(b"csr"),
        shape=_np.asarray(matrix.shape, dtype=_np.int64),
        data=data,
        indices=_np.asarray(matrix.indices),
        indptr=_np.asarray(matrix.indptr),
    )
    if data.dtype.kind == "V" or str(data.dtype) == "bfloat16":
        # npz has no portable bfloat16 encoding (numpy stores the
        # ml_dtypes registration as raw void, unreadable by scipy and
        # np.load alike): persist the raw 16-bit patterns plus a dtype
        # marker — bit-exact through load_npz, and compressed storage
        # (``csr_array.compress``) checkpoints at its true byte size.
        # scipy cannot read a bf16 container; widen before saving when
        # scipy interchange matters.
        arrays["data_dtype"] = _np.array(str(data.dtype).encode())
        arrays["data"] = data.view(_np.uint16)
    if compressed:
        _np.savez_compressed(file, **arrays)
    else:
        _np.savez(file, **arrays)


def load_npz(file) -> csr_array:
    """Load a scipy ``save_npz`` container as a csr_array."""
    import numpy as _np

    with _np.load(file) as f:
        fmt = f["format"].item()
        if isinstance(fmt, bytes):
            fmt = fmt.decode()
        if fmt == "csr":
            data = f["data"]
            if "data_dtype" in f:
                # Compressed-value container (save_npz above): the raw
                # 16-bit patterns reinterpret to the marked dtype —
                # bit-exact, no widening round trip.
                data = data.view(_np.dtype(
                    f["data_dtype"].item().decode()))
            out = csr_array(
                (data, f["indices"], f["indptr"]),
                shape=tuple(int(s) for s in f["shape"]),
            )
            idx_dt = _np.dtype(f["indices"].dtype)
            if (idx_dt.kind == "i" and idx_dt.itemsize
                    < _np.dtype(out.indices.dtype).itemsize):
                # The triple constructor canonicalizes indices to the
                # coord dtype; restore the container's compressed
                # width so storage round-trips exactly.
                out = out.astype_storage(indices=idx_dt)
            return out
    # Non-csr containers (csc/coo/dia/bsr/...): scipy decodes the
    # layout (file-like sources are rewound; np.load consumed them).
    if hasattr(file, "seek"):
        file.seek(0)
    import scipy.sparse as _ss

    return csr_array(_ss.load_npz(file).tocsr())
