# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Device-native MINRES, LSQR, and LSMR.

Same design as the cg/gmres/bicgstab family in ``linalg.py`` (reference
has none of these solvers — its linalg surface is cg/gmres only): the
whole solve is ONE jitted ``lax.while_loop`` with no host sync per
iteration, tolerances and iteration budgets carried as dynamic state so
retuned solves reuse the compiled loop.

- ``minres``: Paige & Saunders Lanczos + Givens QR for symmetric
  (possibly indefinite) systems, optional SPD preconditioner M and
  diagonal ``shift`` (solves ``(A - shift*I) x = b``).
- ``lsqr``: Golub-Kahan bidiagonalization for least-squares /
  rectangular systems with Tikhonov ``damp``; needs matvec + rmatvec
  (both live on device — for sparse operands rmatvec is the cached
  transpose SpMV).
- ``lsmr``: the same bidiagonalization with a second Givens chain
  minimizing ``||A^T r||`` (Fong & Saunders) — the least-squares analog
  of MINRES where LSQR is the analog of CG.

Scalar recurrences (Givens coefficients, norm estimates) are O(1) per
step and fuse into the matvec program; the MXU/VPU work stays the SpMV.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .types import index_dtype

__all__ = ["minres", "lsqr", "lsmr", "differentiable_solve"]


def _sym_ortho(a, b):
    """Stable Givens rotation (c, s, r) with r = hypot(a, b)."""
    r = jnp.hypot(a, b)
    safe = jnp.where(r == 0, jnp.ones_like(r), r)
    c = jnp.where(r == 0, jnp.ones_like(a), a / safe)
    s = jnp.where(r == 0, jnp.zeros_like(b), b / safe)
    return c, s, r


def _givens(a, b):
    """Complex-capable Givens rotation (c, s) annihilating ``b``:

        [ c        s      ] [a]   [r]
        [-conj(s)  conj(c)] [b] = [0]

    with r = hypot(|a|, |b|) real and |c|^2 + |s|^2 = 1.  For real
    operands this is exactly ``_sym_ortho``'s (c, s); the complex
    extension (c = conj(a)/r, s = conj(b)/r) is what the progressive
    Hessenberg QR in ``linalg.gmres`` needs, where MINRES/LSQR/LSMR
    only ever rotate real scalars."""
    if not jnp.issubdtype(jnp.result_type(a, b), jnp.complexfloating):
        c, s, _ = _sym_ortho(a, b)
        return c, s
    r = jnp.hypot(jnp.abs(a), jnp.abs(b))
    safe = jnp.where(r == 0, jnp.ones_like(r), r).astype(a.dtype)
    c = jnp.where(r == 0, jnp.ones_like(a), jnp.conj(a) / safe)
    s = jnp.where(r == 0, jnp.zeros_like(b), jnp.conj(b) / safe)
    return c, s


def _make_normalize(dtype, rdt):
    """Shared bidiagonalization normalizer: (v/||v||, ||v||) with the
    zero-vector guarded (used by both the LSQR and LSMR loops)."""
    def normalize(v):
        nrm = jnp.linalg.norm(v).astype(rdt)
        return v / jnp.where(nrm == 0, 1.0, nrm).astype(dtype), nrm

    return normalize


def _safe_denom(x):
    return jnp.where(x == 0, jnp.ones_like(x), x)


# ------------------------------------------------------------------ MINRES


def _minres_loop(A_mv, M_mv, b, x0, shift, atol, maxiter,
                 conv_test_iters: int):
    dtype = b.dtype
    rdt = jnp.real(b).dtype

    def op(v):
        return A_mv(v) - shift * v

    r1 = b - op(x0)
    y = M_mv(r1)
    beta1 = jnp.sqrt(jnp.maximum(jnp.real(jnp.vdot(r1, y)), 0)).astype(rdt)

    def cond(st):
        return jnp.logical_and(st["iters"] < st["miter"],
                               jnp.logical_not(st["done"]))

    def body(st):
        iters = st["iters"] + 1
        safe_beta = jnp.where(st["beta"] == 0, 1.0, st["beta"])
        v = st["y"] / safe_beta.astype(dtype)
        y = op(v)
        y = y - (st["beta"] / jnp.where(st["oldb"] == 0, 1.0,
                                        st["oldb"])).astype(dtype) \
            * jnp.where(st["iters"] == 0, jnp.zeros_like(y), st["r1"])
        alfa = jnp.real(jnp.vdot(v, y)).astype(rdt)
        y = y - (alfa / safe_beta).astype(dtype) * st["r2"]
        r1, r2 = st["r2"], y
        y = M_mv(r2)
        oldb = st["beta"]
        beta = jnp.sqrt(jnp.maximum(jnp.real(jnp.vdot(r2, y)), 0)) \
            .astype(rdt)

        # Givens QR update of the tridiagonal.
        oldeps = st["epsln"]
        delta = st["cs"] * st["dbar"] + st["sn"] * alfa
        gbar = st["sn"] * st["dbar"] - st["cs"] * alfa
        epsln = st["sn"] * beta
        dbar = -st["cs"] * beta
        cs, sn, gamma = _sym_ortho(gbar, beta)
        gamma = jnp.maximum(gamma, jnp.finfo(rdt).eps)
        phi = cs * st["phibar"]
        phibar = sn * st["phibar"]

        # Solution update.
        denom = (1.0 / gamma).astype(dtype)
        w1, w2 = st["w2"], st["w"]
        w = (v - oldeps.astype(dtype) * w1 - delta.astype(dtype) * w2) \
            * denom
        x = st["x"] + phi.astype(dtype) * w

        check = jnp.logical_or(iters % conv_test_iters == 0,
                               iters >= st["miter"] - 1)
        done = jnp.logical_or(
            st["done"],
            jnp.logical_and(check, phibar <= st["atol"]))
        return dict(x=x, r1=r1, r2=r2, y=y, w=w, w2=w2, oldb=oldb,
                    beta=beta, dbar=dbar, epsln=epsln, phibar=phibar,
                    cs=cs, sn=sn, iters=iters, done=done,
                    atol=st["atol"], miter=st["miter"])

    st0 = dict(
        x=x0, r1=r1, r2=r1, y=y,
        w=jnp.zeros_like(b), w2=jnp.zeros_like(b),
        oldb=jnp.zeros((), rdt), beta=beta1,
        dbar=jnp.zeros((), rdt), epsln=jnp.zeros((), rdt),
        phibar=beta1,
        cs=jnp.asarray(-1.0, rdt), sn=jnp.zeros((), rdt),
        iters=jnp.asarray(0, index_dtype()),
        done=jnp.asarray(beta1 == 0),
        atol=jnp.asarray(atol, rdt),
        miter=jnp.asarray(maxiter, index_dtype()),
    )
    out = jax.lax.while_loop(cond, body, st0)
    return out["x"], out["iters"]


def minres(A, b, x0=None, *, shift=0.0, tol=None, maxiter=None, M=None,
           callback=None, rtol=1e-5, atol=0.0, conv_test_iters: int = 25,
           **kwargs):
    """MINRES for symmetric (indefinite OK) ``(A - shift I) x = b``
    (scipy-shaped; returns ``(x, iters)`` like this package's cg).

    The preconditioner M must be SPD (scipy's requirement too).  Whole
    solve is one jitted while_loop; ``callback``/diagnostic kwargs
    (``show``/``check``) delegate to host scipy.
    """
    from .coverage import scipy_fallback
    from .linalg import (IdentityOperator, _get_atol_rtol, _promote_rhs,
                         make_linear_operator)

    if callback is not None or kwargs:
        import scipy.sparse.linalg as _ssl

        # Keep the native return convention (x, iters) — count the
        # iterations via scipy's per-iteration callback hook (also when
        # the user passed none and we're here for show/check kwargs).
        count = [0]

        def counting_callback(xk):
            count[0] += 1
            if callback is not None:
                callback(xk)

        x_out, _info = scipy_fallback(_ssl.minres, "linalg.minres")(
            A, b, x0=x0, shift=shift, maxiter=maxiter, M=M,
            callback=counting_callback,
            rtol=(tol if tol is not None else rtol), **kwargs)
        return x_out, count[0]

    b = jnp.asarray(b)
    if b.ndim == 2 and b.shape[1] == 1:
        b = b.reshape(-1)
    n = b.shape[0]
    A_op = make_linear_operator(A)
    b = _promote_rhs(b, A_op)
    M_op = (IdentityOperator(A_op.shape, dtype=A_op.dtype)
            if M is None else make_linear_operator(M))
    bnrm = float(jnp.linalg.norm(b))
    atol, _ = _get_atol_rtol(bnrm, tol, atol, rtol)
    if maxiter is None:
        maxiter = 5 * n
    x = (jnp.zeros(n, dtype=b.dtype) if x0 is None
         else jnp.asarray(x0, dtype=b.dtype).reshape(-1))
    shift = jnp.asarray(shift, dtype=b.dtype)
    return _minres_loop(A_op.matvec, M_op.matvec, b, x, shift,
                        atol, int(maxiter), int(conv_test_iters))


# -------------------------------------------------------------------- LSQR


def _lsqr_loop(A_mv, At_mv, b, x0, damp, atol, btol, maxiter,
               conv_test_iters: int):
    dtype = b.dtype
    rdt = jnp.real(b).dtype
    eps = jnp.finfo(rdt).eps

    normalize = _make_normalize(dtype, rdt)

    u0 = b - A_mv(x0)
    u, beta0 = normalize(u0)
    v, alfa0 = normalize(At_mv(u))

    def cond(st):
        return jnp.logical_and(st["iters"] < st["miter"],
                               jnp.logical_not(st["done"]))

    def body(st):
        iters = st["iters"] + 1
        # Bidiagonalization step.
        u, beta = normalize(A_mv(st["v"]) - st["alfa"].astype(dtype)
                            * st["u"])
        v, alfa = normalize(At_mv(u) - beta.astype(dtype) * st["v"])

        # Eliminate the damping term.
        rhobar1 = jnp.sqrt(st["rhobar"] ** 2 + st["damp"] ** 2)
        cs1 = st["rhobar"] / jnp.where(rhobar1 == 0, 1.0, rhobar1)
        sn1 = st["damp"] / jnp.where(rhobar1 == 0, 1.0, rhobar1)
        psi = sn1 * st["phibar"]
        phibar1 = cs1 * st["phibar"]

        # Givens rotation on the bidiagonal.
        cs, sn, rho = _sym_ortho(rhobar1, beta)
        rho_safe = jnp.where(rho == 0, 1.0, rho)
        theta = sn * alfa
        rhobar = -cs * alfa
        phi = cs * phibar1
        phibar = sn * phibar1

        x = st["x"] + (phi / rho_safe).astype(dtype) * st["w"]
        w = v - (theta / rho_safe).astype(dtype) * st["w"]

        # Norm estimates (Frobenius accumulation).
        anorm = jnp.sqrt(st["anorm2"])
        anorm2 = st["anorm2"] + st["alfa"] ** 2 + beta ** 2 \
            + st["damp"] ** 2
        rnorm = jnp.sqrt(phibar ** 2 + st["psi2"] + psi ** 2)
        psi2 = st["psi2"] + psi ** 2
        arnorm = alfa * jnp.abs(sn * phi)
        xnorm = jnp.linalg.norm(x).astype(rdt)

        # scipy stopping rules 1 & 2 (recorded so the caller can report
        # which one fired as istop).
        check = jnp.logical_or(iters % conv_test_iters == 0,
                               iters >= st["miter"] - 1)
        tol1 = st["btol"] * st["bnorm"] + st["atol"] * anorm * xnorm
        stop1 = jnp.logical_or(st["stop1"],
                               jnp.logical_and(check, rnorm <= tol1))
        stop2 = jnp.logical_or(
            st["stop2"],
            jnp.logical_and(check,
                            arnorm <= st["atol"] * anorm * rnorm + eps))
        done = jnp.logical_or(st["done"], jnp.logical_or(stop1, stop2))
        return dict(x=x, u=u, v=v, w=w, alfa=alfa, rhobar=rhobar,
                    phibar=phibar, anorm2=anorm2, psi2=psi2,
                    rnorm=rnorm, arnorm=arnorm, xnorm=xnorm,
                    iters=iters, done=done, stop1=stop1, stop2=stop2,
                    damp=st["damp"],
                    atol=st["atol"], btol=st["btol"],
                    bnorm=st["bnorm"], miter=st["miter"])

    st0 = dict(
        x=x0, u=u, v=v, w=v,
        alfa=alfa0, rhobar=alfa0, phibar=beta0,
        anorm2=jnp.zeros((), rdt), psi2=jnp.zeros((), rdt),
        rnorm=beta0, arnorm=alfa0 * beta0,
        xnorm=jnp.linalg.norm(x0).astype(rdt),
        iters=jnp.asarray(0, index_dtype()),
        done=jnp.asarray(jnp.logical_or(beta0 == 0, alfa0 == 0)),
        stop1=jnp.asarray(False), stop2=jnp.asarray(False),
        damp=jnp.asarray(damp, rdt),
        atol=jnp.asarray(atol, rdt), btol=jnp.asarray(btol, rdt),
        bnorm=jnp.linalg.norm(b).astype(rdt),
        miter=jnp.asarray(maxiter, index_dtype()),
    )
    out = jax.lax.while_loop(cond, body, st0)
    return out


def lsqr(A, b, damp=0.0, atol=1e-6, btol=1e-6, conlim=1e8,
         iter_lim=None, show=False, calc_var=False, x0=None,
         conv_test_iters: int = 10):
    """Least-squares solve of ``min ||A x - b||^2 + damp^2 ||x||^2``
    (scipy ``lsqr``; Golub-Kahan bidiagonalization).

    Returns the scipy-shaped 10-tuple ``(x, istop, itn, r1norm, r2norm,
    anorm, acond, arnorm, xnorm, var)``.  ``acond`` is not estimated
    (returned 0 — scipy's value is itself an estimate); ``var`` is
    zeros(n) as with scipy's ``calc_var=False``, and ``calc_var=True``
    delegates to host scipy.
    """
    from .coverage import scipy_fallback
    from .linalg import _promote_rhs, make_linear_operator

    if calc_var or show:
        import scipy.sparse.linalg as _ssl

        return scipy_fallback(_ssl.lsqr, "linalg.lsqr")(
            A, b, damp=damp, atol=atol, btol=btol, conlim=conlim,
            iter_lim=iter_lim, show=show, calc_var=calc_var, x0=x0)

    b = jnp.asarray(b)
    if b.ndim == 2 and b.shape[1] == 1:
        b = b.reshape(-1)
    A_op = make_linear_operator(A)
    b = _promote_rhs(b, A_op)
    m, n = A_op.shape
    if iter_lim is None:
        iter_lim = 2 * n
    x = (jnp.zeros(n, dtype=b.dtype) if x0 is None
         else jnp.asarray(x0, dtype=b.dtype).reshape(-1))
    if float(jnp.linalg.norm(b)) == 0.0:
        # scipy: b = 0 yields the exact solution x = 0, istop = 0.
        return (np.zeros(n, dtype=np.asarray(b).dtype), 0, 0, 0.0, 0.0,
                0.0, 0.0, 0.0, 0.0, np.zeros(n))
    out = _lsqr_loop(A_op.matvec, A_op.rmatvec, b, x, float(damp),
                     float(atol), float(btol), int(iter_lim),
                     int(conv_test_iters))
    itn = int(out["iters"])
    r2norm = float(out["rnorm"])
    psi2 = float(out["psi2"])
    r1norm = float(np.sqrt(max(r2norm ** 2 - psi2, 0.0)))
    # scipy istop: 1 = Ax=b solved to tolerance (rule 1), 2 = least-
    # squares solution found (rule 2), 0 = exact at entry (x0 solves
    # the system, or b orthogonal to range(A)), 7 = iteration limit.
    if bool(out["stop1"]):
        istop = 1
    elif bool(out["stop2"]):
        istop = 2
    elif itn == 0:
        istop = 0
    else:
        istop = 7
    return (np.asarray(out["x"]), istop, itn, r1norm, r2norm,
            float(np.sqrt(out["anorm2"])), 0.0, float(out["arnorm"]),
            float(out["xnorm"]), np.zeros(n))


# -------------------------------------------------------------------- LSMR


def _lsmr_loop(A_mv, At_mv, b, x0, damp, atol, btol, conlim, maxiter,
               conv_test_iters: int):
    """Fong & Saunders LSMR: Golub-Kahan bidiagonalization with a
    second Givens chain minimizing ||A^T r|| — the least-squares analog
    of MINRES where LSQR is the analog of CG.  One jitted while_loop;
    all per-step work beyond the two matvecs is scalar."""
    dtype = b.dtype
    rdt = jnp.real(b).dtype
    eps = jnp.finfo(rdt).eps

    normalize = _make_normalize(dtype, rdt)

    u, beta0 = normalize(b - A_mv(x0))
    v, alpha0 = normalize(At_mv(u))

    def cond(st):
        return jnp.logical_and(st["iters"] < st["miter"],
                               jnp.logical_not(st["done"]))

    def body(st):
        iters = st["iters"] + 1
        u, beta = normalize(A_mv(st["v"]) - st["alpha"].astype(dtype)
                            * st["u"])
        v, alpha = normalize(At_mv(u) - beta.astype(dtype) * st["v"])

        chat, shat, alphahat = _sym_ortho(st["alphabar"], st["damp"])

        rhoold = st["rho"]
        c, s, rho = _sym_ortho(alphahat, beta)
        thetanew = s * alpha
        alphabar = c * alpha

        rhobarold = st["rhobar"]
        zetaold = st["zeta"]
        thetabar = st["sbar"] * rho
        rhotemp = st["cbar"] * rho
        cbar, sbar, rhobar = _sym_ortho(rhotemp, thetanew)
        zeta = cbar * st["zetabar"]
        zetabar = -sbar * st["zetabar"]

        denom_h = jnp.where(rhoold * rhobarold == 0, 1.0,
                            rhoold * rhobarold)
        hbar = st["h"] - (thetabar * rho / denom_h).astype(dtype) \
            * st["hbar"]
        denom_x = jnp.where(rho * rhobar == 0, 1.0, rho * rhobar)
        x = st["x"] + (zeta / denom_x).astype(dtype) * hbar
        h = v - (thetanew / jnp.where(rho == 0, 1.0, rho)).astype(dtype) \
            * st["h"]

        # ||r|| estimate (the paper's second triangular solve).
        betaacute = chat * st["betadd"]
        betacheck = -shat * st["betadd"]
        betahat = c * betaacute
        betadd = -s * betaacute
        thetatildeold = st["thetatilde"]
        ctildeold, stildeold, rhotildeold = _sym_ortho(
            st["rhodold"], thetabar)
        thetatilde = stildeold * rhobar
        rhodold = ctildeold * rhobar
        betad = -stildeold * st["betad"] + ctildeold * betahat
        tautildeold = (zetaold - thetatildeold * st["tautildeold"]) \
            / jnp.where(rhotildeold == 0, 1.0, rhotildeold)
        taud = (zeta - thetatilde * tautildeold) \
            / jnp.where(rhodold == 0, 1.0, rhodold)
        d2 = st["d2"] + betacheck ** 2
        normr = jnp.sqrt(d2 + (betad - taud) ** 2 + betadd ** 2)

        # scipy's exact accumulator ordering: beta^2 enters normA for
        # THIS iteration's tests, alpha^2 only for the next.
        normA = jnp.sqrt(st["normA2"] + beta ** 2)
        normA2 = st["normA2"] + beta ** 2 + alpha ** 2
        normar = jnp.abs(zetabar)
        normx = jnp.linalg.norm(x).astype(rdt)
        maxrbar = jnp.maximum(st["maxrbar"], rhobarold)
        minrbar = jnp.where(iters > 1,
                            jnp.minimum(st["minrbar"], rhobarold),
                            st["minrbar"])
        condA = (jnp.maximum(maxrbar, rhotemp)
                 / jnp.maximum(jnp.minimum(minrbar, rhotemp), eps))

        # scipy's scale-invariant stopping tests (lsmr.py): test1/2/3
        # plus the machine-precision istop 4/5/6 variants — an additive
        # absolute eps would mis-fire on small-scale data.
        check = jnp.logical_or(iters % conv_test_iters == 0,
                               iters >= st["miter"] - 1)
        test1 = normr / _safe_denom(st["bnorm"])
        test2 = normar / _safe_denom(normA * normr)
        test3 = 1.0 / _safe_denom(condA)
        t1 = test1 / (1.0 + normA * normx / _safe_denom(st["bnorm"]))
        rtol_ = st["btol"] + st["atol"] * normA * normx \
            / _safe_denom(st["bnorm"])

        def latch(prev, fired):
            return jnp.logical_or(prev, jnp.logical_and(check, fired))

        stop1 = latch(st["stop1"], test1 <= rtol_)
        stop2 = latch(st["stop2"], test2 <= st["atol"])
        stop3 = latch(st["stop3"],
                      jnp.logical_and(st["ctol"] > 0,
                                      test3 <= st["ctol"]))
        stop4 = latch(st["stop4"], 1.0 + t1 <= 1.0)
        stop5 = latch(st["stop5"], 1.0 + test2 <= 1.0)
        stop6 = latch(st["stop6"], 1.0 + test3 <= 1.0)
        done = jnp.logical_or(
            st["done"],
            stop1 | stop2 | stop3 | stop4 | stop5 | stop6)
        return dict(x=x, u=u, v=v, h=h, hbar=hbar, alpha=alpha,
                    alphabar=alphabar, rho=rho, rhobar=rhobar,
                    cbar=cbar, sbar=sbar, zeta=zeta, zetabar=zetabar,
                    betadd=betadd, betad=betad, rhodold=rhodold,
                    tautildeold=tautildeold, thetatilde=thetatilde,
                    d2=d2, normA2=normA2, normA=normA, normr=normr,
                    normar=normar,
                    normx=normx, maxrbar=maxrbar, minrbar=minrbar,
                    rhotemp=rhotemp,
                    iters=iters, done=done, stop1=stop1, stop2=stop2,
                    stop3=stop3, stop4=stop4, stop5=stop5, stop6=stop6,
                    ctol=st["ctol"],
                    damp=st["damp"], atol=st["atol"], btol=st["btol"],
                    bnorm=st["bnorm"], miter=st["miter"])

    st0 = dict(
        x=x0, u=u, v=v, h=v, hbar=jnp.zeros_like(v),
        alpha=alpha0, alphabar=alpha0,
        rho=jnp.ones((), rdt), rhobar=jnp.ones((), rdt),
        cbar=jnp.ones((), rdt), sbar=jnp.zeros((), rdt),
        zeta=jnp.zeros((), rdt), zetabar=alpha0 * beta0,
        betadd=beta0, betad=jnp.zeros((), rdt),
        rhodold=jnp.ones((), rdt), tautildeold=jnp.zeros((), rdt),
        thetatilde=jnp.zeros((), rdt), d2=jnp.zeros((), rdt),
        normA2=alpha0 ** 2, normA=alpha0,
        normr=beta0, normar=alpha0 * beta0,
        normx=jnp.linalg.norm(x0).astype(rdt),
        maxrbar=jnp.zeros((), rdt),
        minrbar=jnp.asarray(np.finfo(np.float64).max, rdt),
        rhotemp=jnp.ones((), rdt),
        iters=jnp.asarray(0, index_dtype()),
        done=jnp.asarray(jnp.logical_or(beta0 == 0, alpha0 == 0)),
        stop1=jnp.asarray(False), stop2=jnp.asarray(False),
        stop3=jnp.asarray(False), stop4=jnp.asarray(False),
        stop5=jnp.asarray(False), stop6=jnp.asarray(False),
        ctol=jnp.asarray(0.0 if conlim <= 0 else 1.0 / conlim, rdt),
        damp=jnp.asarray(damp, rdt),
        atol=jnp.asarray(atol, rdt), btol=jnp.asarray(btol, rdt),
        bnorm=jnp.linalg.norm(b).astype(rdt),
        miter=jnp.asarray(maxiter, index_dtype()),
    )
    return jax.lax.while_loop(cond, body, st0)


def lsmr(A, b, damp=0.0, atol=1e-6, btol=1e-6, conlim=1e8,
         maxiter=None, show=False, x0=None, conv_test_iters: int = 10):
    """Iterative least squares minimizing ||A^T r|| (scipy ``lsmr``).

    Returns the scipy-shaped 8-tuple ``(x, istop, itn, normr, normar,
    norma, conda, normx)`` with scipy's istop semantics (1 compatible,
    2 least-squares, 3 condition-limit, 0 zero rhs / exact at entry,
    7 iteration limit).  ``show`` delegates to host scipy.
    """
    from .coverage import scipy_fallback
    from .linalg import _promote_rhs, make_linear_operator

    if show:
        import scipy.sparse.linalg as _ssl

        return scipy_fallback(_ssl.lsmr, "linalg.lsmr")(
            A, b, damp=damp, atol=atol, btol=btol, conlim=conlim,
            maxiter=maxiter, show=show, x0=x0)

    b = jnp.asarray(b)
    if b.ndim == 2 and b.shape[1] == 1:
        b = b.reshape(-1)
    A_op = make_linear_operator(A)
    b = _promote_rhs(b, A_op)
    m, n = A_op.shape
    if maxiter is None:
        maxiter = min(m, n)   # scipy's lsmr default
    x = (jnp.zeros(n, dtype=b.dtype) if x0 is None
         else jnp.asarray(x0, dtype=b.dtype).reshape(-1))
    if x0 is None and float(jnp.linalg.norm(b)) == 0.0:
        # normar = alpha0*beta0 = 0 at entry: scipy returns x=0
        # immediately.  With a nonzero x0 the residual -A@x0 is a real
        # system and the loop must run (scipy has no b==0 shortcut).
        return (np.zeros(n, dtype=np.asarray(b).dtype), 0, 0, 0.0, 0.0,
                0.0, 0.0, 0.0)
    out = _lsmr_loop(A_op.matvec, A_op.rmatvec, b, x, float(damp),
                     float(atol), float(btol), float(conlim),
                     int(maxiter), int(conv_test_iters))
    itn = int(out["iters"])
    conda = float(jnp.maximum(out["maxrbar"], out["rhotemp"])
                  / jnp.minimum(out["minrbar"], out["rhotemp"]))
    # scipy assigns istop 7..1 in sequence so the smallest fired rule
    # wins; 4/5/6 are the machine-precision variants of 1/2/3.
    istop = 7
    for flag, code in (("stop6", 6), ("stop5", 5), ("stop4", 4),
                       ("stop3", 3), ("stop2", 2), ("stop1", 1)):
        if bool(out[flag]):
            istop = code
    if istop == 7 and itn == 0:
        istop = 0
    return (np.asarray(out["x"]), istop, itn, float(out["normr"]),
            float(out["normar"]), float(out["normA"]),
            conda, float(out["normx"]))


# -------------------------------------------------- differentiable solve


def differentiable_solve(A, b, method="cg", M=None, rtol=None,
                         atol=0.0, maxiter=None,
                         conv_test_iters: int = 25):
    """Sparse linear solve that participates in ``jax.grad`` /
    ``jax.vjp`` (a JAX-native extra — neither the reference nor scipy
    has an autodiff story for iterative solvers).

    Built on ``lax.custom_linear_solve``: the forward solve runs this
    package's jitted CG/MINRES while_loop, and the reverse pass solves
    the transposed system with the same loop (for symmetric operators
    the very same solve), so ``grad`` of any scalar loss through ``x =
    solve(A, b)`` costs one extra solve instead of differentiating
    through solver iterations (which ``while_loop`` cannot reverse).

    Differentiable w.r.t. ``b``.  ``A``/``M`` are closed over as
    constants (sparse structures are not pytree leaves).  ``method``:
    'cg' (SPD) or 'minres' (symmetric indefinite); both imply a
    symmetric operator, which is what makes the transpose solve free.
    """
    from .linalg import (IdentityOperator, _cg_loop, _promote_rhs,
                         make_linear_operator)

    if method not in ("cg", "minres"):
        raise ValueError(
            f"method={method!r}: differentiable_solve supports 'cg' "
            "and 'minres' (symmetric operators)")
    b = jnp.asarray(b)
    if b.ndim == 2 and b.shape[1] == 1:
        b = b.reshape(-1)
    n = b.shape[0]
    A_op = make_linear_operator(A)
    b = _promote_rhs(b, A_op)
    if A_op.shape[0] != A_op.shape[1]:
        raise ValueError("expected square matrix")
    M_op = (IdentityOperator(A_op.shape, dtype=A_op.dtype)
            if M is None else make_linear_operator(M))
    if maxiter is None:
        maxiter = 10 * n
    if rtol is None:
        # Attainable in the working precision: 1e-10 stagnates forever
        # in float32 (the TPU-typical non-x64 mode) — scale to eps.
        rtol = float(np.sqrt(np.finfo(
            np.dtype(jnp.real(b).dtype)).eps) * 1e-2)
    x0 = jnp.zeros(n, dtype=b.dtype)

    def mv(x):
        return A_op.matvec(x)

    def solve_fn(matvec, rhs):
        # Tolerance relative to THIS rhs (the reverse pass solves for
        # the cotangent, whose scale differs from b's).
        a_tol = jnp.maximum(
            jnp.asarray(atol, jnp.real(rhs).dtype),
            rtol * jnp.linalg.norm(rhs).astype(jnp.real(rhs).dtype))
        if method == "cg":
            x, _ = _cg_loop(matvec, M_op.matvec, rhs, x0, a_tol,
                            maxiter, conv_test_iters)
        else:
            x, _ = _minres_loop(matvec, M_op.matvec, rhs, x0,
                                jnp.zeros((), rhs.dtype), a_tol,
                                maxiter, conv_test_iters)
        return x

    return jax.lax.custom_linear_solve(
        mv, b, solve_fn, transpose_solve=solve_fn, symmetric=True)
