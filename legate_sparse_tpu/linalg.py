# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Iterative solvers: CG, GMRES, LinearOperator.

Parity target: the reference's solver layer (reference:
``legate_sparse/linalg.py:85-668`` — ``LinearOperator`` family,
``cg_axpby`` fused update, ``cg`` with deferred convergence checks,
restarted ``gmres``).

TPU-first re-design: the reference hides latency by keeping scalars as
Legion futures and testing convergence every ``conv_test_iters``
iterations (``linalg.py:507-533``).  The XLA-native equivalent is
stronger: the *entire* CG iteration runs inside ``lax.while_loop`` under
one ``jit`` — zero host round-trips until the solve finishes; the
convergence cadence is preserved for iteration-count parity.  The fused
``cg_axpby`` kernel (reference ``axpby_template.inl:27-71``) exists for
API parity but fuses automatically when used inside jit.
"""

from __future__ import annotations

import warnings
from functools import partial
from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp

from . import obs as _obs
from .obs import latency as _lat
from .resilience import checkpoint as _rckpt
from .resilience import deadline as _rdeadline
from .resilience import faults as _rfaults
from .resilience import health as _rhealth
from .resilience import policy as _rpolicy
from .settings import settings as _rsettings
from .types import index_dtype

from .csr import csr_array
from .utils import fill_out as _fill_out, is_sparse_matrix


# --------------------------------------------------------------------------
# LinearOperator family (reference ``linalg.py:85-421``)
# --------------------------------------------------------------------------
class LinearOperator:
    """Common interface for matrix-vector products.

    Iterative solvers only need ``A @ v``; this class abstracts matrices,
    callables, and compositions behind ``matvec``/``rmatvec`` the same
    way the reference (and scipy) do.  Matvec implementations must be
    jax-traceable to participate in jitted solver loops.
    """

    ndim = 2

    def __new__(cls, *args, **kwargs):
        if cls is LinearOperator:
            return super().__new__(_CustomLinearOperator)
        obj = super().__new__(cls)
        if (
            type(obj)._matvec == LinearOperator._matvec
            and type(obj)._matmat == LinearOperator._matmat
        ):
            warnings.warn(
                "LinearOperator subclass should implement"
                " at least one of _matvec and _matmat.",
                category=RuntimeWarning,
                stacklevel=2,
            )
        return obj

    def __init__(self, dtype, shape):
        if dtype is not None:
            dtype = np.dtype(dtype)
        self.dtype = dtype
        self.shape = tuple(int(s) for s in shape)

    def _init_dtype(self):
        if self.dtype is None:
            v = jnp.zeros(self.shape[-1])
            self.dtype = np.dtype(self.matvec(v).dtype)

    # -- default implementations, each in terms of the other --
    def _matvec(self, x, out=None):
        return self._matmat(x.reshape(-1, 1), out=out).reshape(-1)

    def _matmat(self, X, out=None):
        cols = [self._matvec(X[:, j]) for j in range(X.shape[1])]
        result = jnp.stack(cols, axis=1)
        return result

    def _rmatvec(self, x, out=None):
        raise NotImplementedError("rmatvec is not defined")

    def matvec(self, x, out=None):
        M, N = self.shape
        if x.shape != (N,) and x.shape != (N, 1):
            raise ValueError("dimension mismatch")
        return self._matvec(x, out=out)

    def rmatvec(self, x, out=None):
        M, N = self.shape
        if x.shape != (M,) and x.shape != (M, 1):
            raise ValueError("dimension mismatch")
        return self._rmatvec(x, out=out)

    def matmat(self, X, out=None):
        if X.ndim != 2:
            raise ValueError("expected 2-d array")
        M, N = self.shape
        if X.shape[0] != N:
            raise ValueError("dimension mismatch")
        return self._matmat(X, out=out)

    def __matmul__(self, x):
        if x.ndim == 1:
            return self.matvec(x)
        return self.matmat(x)


class _CustomLinearOperator(LinearOperator):
    """LinearOperator from user callables (reference ``linalg.py:312-372``)."""

    def __init__(
        self, shape, matvec, rmatvec=None, matmat=None, dtype=None,
        rmatmat=None,
    ):
        super().__init__(dtype, shape)
        self.__matvec_impl = matvec
        self.__rmatvec_impl = rmatvec
        self.__matmat_impl = matmat
        self.__rmatmat_impl = rmatmat
        self._init_dtype()

    def _matvec(self, x, out=None):
        result = self.__matvec_impl(x)
        return _fill_out(result, out)

    def _rmatvec(self, x, out=None):
        if self.__rmatvec_impl is None:
            raise NotImplementedError("rmatvec is not defined")
        return _fill_out(self.__rmatvec_impl(x), out=out)

    def _matmat(self, X, out=None):
        if self.__matmat_impl is not None:
            return _fill_out(self.__matmat_impl(X), out)
        return super()._matmat(X, out=out)


class _SparseMatrixLinearOperator(LinearOperator):
    """Wraps a csr_array; caches the conjugate transpose for rmatvec
    (reference ``linalg.py:375-390``).

    Engine routing (``settings.engine``): construction — always a
    concrete context — eagerly builds the engine's bucketed traceable
    matvec for eligible matrices, so solver loops (cg/gmres/...) run
    their in-trace matvecs through the same cached plan the eager
    ``A @ x`` dispatch uses.  The closure slices back to ``n`` before
    returning, so solver reductions — and results — are bit-for-bit
    the unpadded kernel's (``docs/ENGINE.md``)."""

    def __init__(self, A: csr_array):
        self.A = A
        self.AT = None
        self._engine_mv = None
        from .settings import settings as _settings

        if _settings.engine:
            from . import obs as _obs
            from .engine import get_engine

            # Same "engine on is always safe" contract as
            # route_matvec: a plan-build failure (including the
            # cached-failure fast path) must not make a solve raise
            # where the normal dispatch would succeed.
            try:
                self._engine_mv = get_engine().traceable_matvec(A)
            except Exception as e:
                _obs.inc("engine.route.error")
                _obs.event("engine.route.error", op="solver_matvec",
                           error=repr(e)[:200])
        super().__init__(A.dtype, A.shape)

    def _matvec(self, x, out=None):
        if (self._engine_mv is not None
                and isinstance(x, jax.core.Tracer)
                and np.result_type(self.A.dtype, x.dtype)
                == np.dtype(self.A.dtype)
                and self._engine_fresh()):
            # Inside a solver trace the AOT route declines; the
            # traceable closure keeps the loop on the bucketed kernel.
            # The dtype gate mirrors engine eligibility: a PROMOTED
            # iterate (f64 rhs over an f32 matrix, complex over real —
            # what _promote_rhs arranges) must not be downcast by the
            # closure's astype; those solves keep the normal dispatch.
            return _fill_out(self._engine_mv(x), out)
        return self.A.dot(x, out=out)

    def _engine_fresh(self) -> bool:
        """The construction-time closure captured padded COPIES of the
        operands; an in-place mutation of ``A`` since then (which
        clears ``A._engine_pack``) would make it a silent solve of the
        OLD matrix — fall back to the live dispatch instead."""
        cached = getattr(self.A, "_engine_pack", None)
        return (cached is not None
                and cached[1] is getattr(self._engine_mv, "pack", None))

    def _rmatvec(self, x, out=None):
        if self.AT is None:
            self.AT = self.A.T.conj(copy=False)
        return self.AT.dot(x, out=out)


class _DenseMatrixLinearOperator(LinearOperator):
    def __init__(self, A):
        self.A = jnp.asarray(A)
        super().__init__(self.A.dtype, self.A.shape)

    def _matvec(self, x, out=None):
        return _fill_out(self.A @ x, out)

    def _rmatvec(self, x, out=None):
        return _fill_out(self.A.conj().T @ x, out)


class IdentityOperator(LinearOperator):
    """No-op operator (reference ``linalg.py:392-414``)."""

    def __init__(self, shape, dtype=None):
        super().__init__(dtype, shape)

    def _matvec(self, x, out=None):
        return _fill_out(x, out)

    def _rmatvec(self, x, out=None):
        return _fill_out(x, out)


def maybe_jit(fun, **jit_kwargs):
    """``jax.jit(fun)`` in single-controller runs; the bare function in
    multi-process runs.

    Explicit jit embeds closure-captured arrays as trace CONSTANTS,
    and a multi-controller run forbids constants that span processes
    ("Closing over jax.Array that spans non-addressable devices").
    Eagerly-executed ``lax`` control flow lifts those captures to
    arguments instead, so dropping the wrapper keeps the heavy inner
    scans/loops compiled while making the composition legal on a
    process-spanning mesh.  Single-controller behavior is unchanged.
    """
    if jax.process_count() == 1:
        return jax.jit(fun, **jit_kwargs)
    return fun


def _promote_rhs(b, A_op):
    """Solve in ``result_type(A, b)`` (scipy parity): a real rhs on a
    complex operator — or f32 rhs on an f64 operator — must not build
    mixed-dtype while_loop carries (loud TypeError) or silently cast
    complex iterates down to real (silent wrong answers in gmres)."""
    if A_op.dtype is None:
        return b
    dt = jnp.result_type(A_op.dtype, b.dtype)
    return b.astype(dt) if b.dtype != dt else b


def make_linear_operator(A) -> LinearOperator:
    """Promote matrices/callables to LinearOperator (reference
    ``linalg.py:417-431``).  scipy sparse operands convert to the
    package's csr so every native solver accepts them directly."""
    from .csr import _is_scipy_sparse

    if isinstance(A, LinearOperator):
        return A
    if _is_scipy_sparse(A):
        A = csr_array(A)
    if is_sparse_matrix(A):
        if not isinstance(A, csr_array):
            A = A.tocsr()
        return _SparseMatrixLinearOperator(A)
    return _DenseMatrixLinearOperator(A)


# --------------------------------------------------------------------------
# Fused vector updates (reference ``linalg.py:424-451`` + AXPBY task)
# --------------------------------------------------------------------------
@partial(jax.jit, static_argnames=("isalpha", "negate"))
def _cg_axpby_impl(y, x, a, b, isalpha: bool, negate: bool):
    coef = a / b
    if negate:
        coef = -coef
    if isalpha:
        return coef * x + y  # y = (±a/b)·x + y
    return x + coef * y      # y = x + (±a/b)·y


def cg_axpby(y, x, a, b, isalpha: bool = True, negate: bool = False):
    """y = alpha*x + beta*y with the alpha/beta division fused in-kernel.

    API parity with the reference (``linalg.py:434-451``), which passes
    ``a``/``b`` as futures so alpha = a/b is computed inside the task.
    Under jit the division and AXPBY fuse into one VPU pass anyway; numpy
    ``y`` is updated in place to preserve the reference's mutation
    contract.
    """
    result = _cg_axpby_impl(
        jnp.asarray(y), jnp.asarray(x), jnp.asarray(a), jnp.asarray(b),
        bool(isalpha), bool(negate),
    )
    if isinstance(y, np.ndarray):
        np.copyto(y, np.asarray(result, dtype=y.dtype))
        return y
    return result


def _get_atol_rtol(b_norm, tol=None, atol=0.0, rtol=1e-5):
    """scipy-compatible tolerance resolution (reference ``linalg.py:454-462``)."""
    rtol = float(tol) if tol is not None else rtol
    if atol is None:
        atol = rtol
    atol = max(float(atol), float(rtol) * float(b_norm))
    return atol, rtol


# --------------------------------------------------------------------------
# Mixed-precision iterative refinement (compressed-storage inner solves)
# --------------------------------------------------------------------------

_REFINE_AUTO_CYCLES = 12   # "auto": outer-correction cycle budget
_REFINE_INNER_RTOL = 1e-2  # per-cycle inner residual-reduction target


def _refine_inner_operator(A) -> csr_array:
    """The compressed-storage inner operator behind ``refine=``: one
    precision rung below the system dtype — f64 values drop to f32,
    f32 values to bf16 — with int16 column indices whenever the width
    fits, built through :meth:`csr_array.compress`.  The inner Krylov
    sweep streams the narrow bytes (that is the roofline win); the
    outer full-precision residual correction restores the grade.
    Raises for operands refinement cannot serve: the knob is an
    explicit request, and silently solving unrefined would lie."""
    if is_sparse_matrix(A) and not isinstance(A, csr_array):
        A = A.tocsr()
    if not isinstance(A, csr_array):
        raise ValueError(
            "refine= needs a sparse-matrix operand (the inner solve "
            "runs over compressed csr_array storage); got "
            f"{type(A).__name__}")
    dt = np.dtype(A.dtype)
    if dt == np.float64:
        return A.compress(values="float32")
    if dt == np.float32:
        return A.compress()
    raise ValueError(
        f"refine= serves float32/float64 systems (got {dt.name}: "
        "storage is already low-precision — solve it directly)")


def _refine_cycles(refine) -> int:
    if refine == "auto":
        return _REFINE_AUTO_CYCLES
    cycles = int(refine)
    if cycles <= 0:
        raise ValueError(
            f"refine= must be 'auto' or a positive cycle count, "
            f"got {refine!r}")
    return cycles


def _refined_solve(solver: str, inner_solve: Callable, A_op, A_in,
                   b, x, atol: float, maxiter: int, cycles: int):
    """The shared iterative-refinement driver behind ``cg``/``gmres``
    ``refine=``.

    Classic mixed-precision IR: a full-precision residual
    ``r = b - A x`` against the matrix the caller handed us, an inner
    Krylov correction solve over the compressed-storage operator
    ``A_in`` in f32 vectors (to :data:`_REFINE_INNER_RTOL` relative —
    the grade low-precision storage can actually deliver), then a
    full-precision update ``x += d``.  Convergence is judged on the
    TRUE residual, so the refined solve meets the same ``atol`` the
    unrefined f32/f64 solve would.

    Host-sync cadence contract: ONE stacked fetch per refinement
    cycle — the residual-norm convergence decision (counted as
    ``transfer.host_sync.<solver>_refine``), matching the solvers'
    existing one-fetch-per-cycle discipline; the opt-in health monitor
    (docs/RESILIENCE.md) rides that same fetch.
    """
    site = f"solver.{solver}.refine"
    monitor = _rhealth.Monitor(site) if _rsettings.resil else None
    inner_dt = (jnp.float32 if np.dtype(b.dtype) == np.float64
                else b.dtype)
    total = 0
    rn = None
    with _obs.span(solver + ".refine", n=int(b.shape[0]),
                   cycles=cycles,
                   inner_dtype=np.dtype(A_in.dtype).name) as sp:
        for cycle in range(cycles):
            r = b - A_op.matvec(x)
            rn = float(jnp.linalg.norm(r))     # the per-cycle fetch
            _obs.inc(f"transfer.host_sync.{solver}_refine")
            if monitor is not None:
                monitor.observe(rn, total, partial=x)
            if _rsettings.resil:
                # The refinement fetch IS a cadence point: enforce the
                # request deadline here too, or a refined solve could
                # outlive its budget unnoticed (regression-tested).
                _rdeadline.raise_if_expired(site, iterations=total,
                                            residual=rn, partial=x)
            if rn < atol or total >= maxiter:
                break
            d, it = inner_solve(
                A_in, r.astype(inner_dt),
                max(atol, _REFINE_INNER_RTOL * rn), maxiter - total,
            )
            total += max(int(it), 1)
            x = x + d.astype(b.dtype)
        if sp is not None:
            sp.set(iters=total, resid=rn)
    return x, total


# --------------------------------------------------------------------------
# CG (reference ``linalg.py:465-535``)
# --------------------------------------------------------------------------
def _cg_builders(A_mv: Callable, M_mv: Callable, conv_test_iters: int):
    """The (cond, body) pair of the CG while_loop — shared verbatim by
    the one-shot loop (``_cg_loop``) and the chunked resilience loop
    (``_cg_loop_resil``), so the two apply the *identical* iteration
    and differ only in where the loop bound lives."""

    def cond(state):
        x, r, p, rho, iters, done, atol2, miter = state
        return jnp.logical_and(iters < miter, jnp.logical_not(done))

    def body(state):
        x, r, p, rho_old, iters, done, atol2, miter = state
        z = M_mv(r)
        rho = jnp.vdot(r, z)
        # Safe divides: an exactly-zero residual (x0 == solution) must
        # flow through to the convergence check, not produce NaNs.
        beta = jnp.where(
            jnp.logical_or(iters == 0, rho_old == 0),
            jnp.zeros_like(rho),
            rho / jnp.where(rho_old == 0, jnp.ones_like(rho_old), rho_old),
        )
        p = z + beta * p
        q = A_mv(p)
        pq = jnp.vdot(p, q)
        alpha = jnp.where(
            pq == 0,
            jnp.zeros_like(rho),
            rho / jnp.where(pq == 0, jnp.ones_like(pq), pq),
        )
        x = x + alpha * p
        r = r - alpha * q
        iters = iters + 1
        check = jnp.logical_or(
            iters % conv_test_iters == 0, iters == miter - 1
        )
        rnorm2 = jnp.real(jnp.vdot(r, r))
        done = jnp.logical_or(done, jnp.logical_and(check, rnorm2 < atol2))
        return (x, r, p, rho, iters, done, atol2, miter)

    return cond, body


def _cg_state0(A_mv: Callable, b, x0, atol: float, maxiter: int):
    r0 = b - A_mv(x0)
    return (
        x0,
        r0,
        jnp.zeros_like(b),
        jnp.ones((), dtype=b.dtype),
        jnp.asarray(0, dtype=index_dtype()),
        jnp.asarray(False),
        jnp.asarray(atol, dtype=jnp.real(b).dtype) ** 2,
        jnp.asarray(maxiter, dtype=index_dtype()),
    )


def _cg_loop(A_mv: Callable, M_mv: Callable, b, x0, atol: float,
             maxiter: int, conv_test_iters: int):
    """Whole preconditioned-CG solve as one XLA while_loop.

    State carries (x, r, p, rho, iters, done) plus the loop-invariant
    (atol2, maxiter) *as state* — dynamic values rather than trace-time
    constants, so solves with different tolerances/iteration budgets
    (e.g. a warmup run followed by a timed run) reuse one compiled
    loop instead of recompiling.
    """
    cond, body = _cg_builders(A_mv, M_mv, conv_test_iters)
    out = jax.lax.while_loop(
        cond, body, _cg_state0(A_mv, b, x0, atol, maxiter))
    return out[0], out[4]


def _resil_solver_active() -> bool:
    """Route a solve through the chunked resilience driver?  Requires
    the master switch AND something that needs per-cycle host
    decisions (an active deadline scope, health detection opted in,
    or a checkpoint scope that wants the fetch cadence) — so
    ``LEGATE_SPARSE_TPU_RESIL=1`` alone leaves the one-shot
    while_loop path untouched."""
    return _rsettings.resil and (
        _rdeadline.current() is not None or _rhealth.active()
        or _rckpt.active())


def _cg_loop_resil(A_mv: Callable, M_mv: Callable, b, x0, atol: float,
                   maxiter: int, conv_test_iters: int,
                   site: str = "solver.cg.conv"):
    """Deadline/health-aware CG (docs/RESILIENCE.md): the SAME
    while_loop body as ``_cg_loop``, dispatched in chunks of
    ``conv_test_iters`` iterations with ONE stacked-scalar fetch
    (iters, done, ||r||^2) per chunk — the existing convergence
    cadence, so deadline and health checks add zero extra host syncs.
    The carried Krylov state crosses chunk boundaries intact: the
    sequence of body applications is identical to the one-shot loop.

    Deadline expiry raises ``DeadlineExceeded`` with the partial
    iterate; health verdicts (non-finite/divergence/stagnation, when
    opted in) raise ``SolverHealthError``.  The per-chunk dispatch is
    the ``solver.cg.conv`` fault/retry site: a chunk re-runs from its
    entry state, so retries are bit-identical.

    The carried state keeps the TRUE ``maxiter`` (the chunk bound is a
    separate traced limit in the loop condition), so the in-kernel
    convergence checks — including the ``iters == maxiter - 1`` final
    check — fire at exactly the one-shot loop's iterations and the two
    drivers converge at the same count."""
    cond, body = _cg_builders(A_mv, M_mv, conv_test_iters)
    rdt = jnp.real(b).dtype

    def chunk(state, limit):
        def cond_chunk(st):
            return jnp.logical_and(cond(st), st[4] < limit)

        out = jax.lax.while_loop(cond_chunk, body, state)
        rn2 = jnp.real(jnp.vdot(out[1], out[1]))
        stats = jnp.stack([out[4].astype(rdt), out[5].astype(rdt),
                           rn2.astype(rdt)])
        return out, stats

    chunk_fn = maybe_jit(chunk)
    state = _cg_state0(A_mv, b, x0, atol, maxiter)
    step = max(int(conv_test_iters), 1)
    monitor = _rhealth.Monitor(site)
    ckpt = _rckpt.current()
    it = 0
    resid = None
    while it < maxiter:
        _rdeadline.raise_if_expired(site, iterations=it,
                                    residual=resid, partial=state[0])
        limit = jnp.asarray(min(it + step, maxiter),
                            dtype=index_dtype())

        def attempt(state=state, limit=limit):
            out, stats = chunk_fn(state, limit)
            return out, _rfaults.fault_point(site, stats)

        # Per-chunk cadence latency (dispatch + the convergence fetch
        # below is timed separately — the chunk IS the cadence unit).
        with _lat.timer("lat.cg.chunk."
                        + _lat.shape_bucket(b.shape[0])):
            state, stats = _rpolicy.run(site, attempt)
        # The chunk's one host sync — the same fetch the convergence
        # decision needs (counted like gmres's cadence counter).
        _obs.inc("transfer.host_sync.cg_conv")
        arr = np.asarray(stats)
        it = int(arr[0])
        done = bool(arr[1])
        resid = float(np.sqrt(arr[2]))
        monitor.observe(resid, it, partial=state[0])
        if ckpt is not None and not done:
            # Checkpoint cadence rides the chunk fetch: snapshot the
            # restartable Krylov state (x, r, p) into host buffers.
            ckpt.maybe_save(it, (state[0], state[1], state[2]))
        if done:
            break
    return state[0], state[4]


def cg(
    A,
    b,
    x0=None,
    tol=None,
    maxiter=None,
    M=None,
    callback=None,
    atol=0.0,
    rtol=1e-5,
    conv_test_iters: int = 25,
    refine=None,
):
    """Conjugate Gradient solve of ``A x = b`` (scipy-shaped signature,
    reference ``linalg.py:465-535``).  Returns ``(x, iters)``.

    Without a callback the solve is a single jitted while_loop (no host
    sync per iteration).  With a callback, a Python-level loop mirrors
    the reference's structure so user code observes every iterate.

    ``refine="auto"`` (or a positive cycle count) switches to
    mixed-precision iterative refinement: inner CG sweeps run over the
    compressed-storage operator (``A.compress()`` — bf16 values under
    f32 systems, f32 under f64, int16 indices where they fit) while
    full-precision residual corrections keep the final residual at the
    same ``atol`` the unrefined solve meets (``_refined_solve``).
    """
    b = jnp.asarray(b)
    if b.ndim == 2 and b.shape[1] == 1:
        b = b.reshape(-1)
    assert b.ndim == 1
    assert len(A.shape) == 2 and A.shape[0] == A.shape[1]

    bnrm2 = float(jnp.linalg.norm(b))
    atol, _ = _get_atol_rtol(bnrm2, tol, atol, rtol)
    n = b.shape[0]
    if maxiter is None:
        maxiter = n * 10

    A_op = make_linear_operator(A)
    b = _promote_rhs(b, A_op)
    M_op = (
        IdentityOperator(A_op.shape, dtype=A_op.dtype)
        if M is None
        else make_linear_operator(M)
    )
    x = (jnp.zeros(n, dtype=b.dtype) if x0 is None
         else jnp.asarray(x0, dtype=b.dtype).reshape(-1))

    if refine is not None:
        if M is not None or callback is not None:
            raise ValueError(
                "cg: refine= composes with neither M= nor callback= — "
                "inner sweeps run over the compressed operator without "
                "the outer preconditioner/observer")
        _obs.inc("op.cg")

        def _inner(A_in, r, inner_atol, budget):
            return cg(A_in, r, atol=inner_atol, rtol=0.0,
                      maxiter=budget, conv_test_iters=conv_test_iters)

        return _refined_solve(
            "cg", _inner, A_op, _refine_inner_operator(A), b, x,
            atol, int(maxiter), _refine_cycles(refine))

    _obs.inc("op.cg")
    if callback is None:
        with _lat.timer("lat.cg.solve." + _lat.shape_bucket(n)), \
                _obs.span("cg", n=n, maxiter=int(maxiter)) as sp:
            loop = (_cg_loop_resil if _resil_solver_active()
                    else _cg_loop)
            xs, iters = loop(
                A_op.matvec, M_op.matvec, b, x, atol, int(maxiter),
                int(conv_test_iters),
            )
            if sp is not None:
                # Tracing mode trades one host sync for honest span
                # timing (the fetch is the only trusted completion
                # signal on detached-dispatch backends) and records
                # the true iteration count + per-iter traffic model.
                it = int(iters)
                sp.set(iters=it)
                src = getattr(A_op, "A", None)
                if isinstance(src, csr_array):
                    sp.set(nnz=src.nnz * it,
                           bytes=src.spmv_traffic_bytes(b) * it,
                           flops=2 * src.nnz * it)
        return xs, iters

    # Callback path: Python loop, one deferred pipeline per iteration.
    r = b - A_op.matvec(x)
    p = jnp.zeros_like(b)
    rho = jnp.ones((), dtype=b.dtype)
    iters = 0
    while iters < maxiter:
        with _obs.span("cg.iter", i=iters):
            z = M_op.matvec(r)
            rho_old = rho
            rho = jnp.vdot(r, z)
            beta = jnp.where(iters == 0, jnp.zeros_like(rho),
                             rho / rho_old)
            p = z + beta * p
            q = A_op.matvec(p)
            alpha = rho / jnp.vdot(p, q)
            x = x + alpha * p
            r = r - alpha * q
        iters += 1
        callback(x)
        if (iters % conv_test_iters == 0 or iters == maxiter - 1) and float(
            jnp.linalg.norm(r)
        ) < atol:
            break
    return x, iters


# --------------------------------------------------------------------------
# GMRES (reference ``linalg.py:540-668``)
# --------------------------------------------------------------------------
def _gmres_cycle(A_mv, M_mv, x, b, restart: int):
    """One restart cycle, sync-free: Arnoldi (modified Gram-Schmidt) +
    progressive Givens QR of the Hessenberg + back-substitution +
    solution update, all in one traced program.

    The reference — and this package until PR 2 — stopped the cycle at
    the Hessenberg and round-tripped it to the host for a small
    ``lstsq`` (reference ``linalg.py:640-650``).  Here each new
    Hessenberg column is rotated by the accumulated Givens rotations
    (the ``_sym_ortho``/``_givens`` machinery the MINRES/LSQR/LSMR
    loops already use) as it is produced, so at cycle end the
    factorization R y = g is ready on device: no host transfer exists
    anywhere in the cycle body.

    Returns ``(x_new, stats)`` with ``stats = [beta, resid]``: ``beta``
    is the residual norm at cycle START and ``resid = |g[restart]|``
    the least-squares residual at cycle end — equal to the true
    residual norm of ``x_new`` in exact arithmetic (right-
    preconditioned full cycle).  One host fetch of ``stats`` per cycle
    is the driver's entire convergence cadence.

    Rank deficiency (happy breakdown mid-cycle leaves trailing zero
    columns in R) is handled in the back-substitution: a zero pivot
    contributes y_i = 0, matching ``lstsq``'s minimum-norm solution on
    the decoupled system.
    """
    from .krylov_extra import _givens

    dtype = b.dtype
    rdt = jnp.real(b).dtype
    n = b.shape[0]
    r = b - A_mv(x)
    beta = jnp.linalg.norm(r).astype(rdt)
    V0 = jnp.zeros((restart + 1, n), dtype=dtype)
    V0 = V0.at[0].set(
        jnp.where(beta > 0, r / beta.astype(dtype), r))
    R0 = jnp.zeros((restart, restart), dtype=dtype)
    g0 = jnp.zeros((restart + 1,), dtype=dtype).at[0].set(
        beta.astype(dtype))
    cs0 = jnp.zeros((restart,), dtype=dtype)
    sn0 = jnp.zeros((restart,), dtype=dtype)

    def body(j, carry):
        V, R, g, cs, sn = carry
        w = A_mv(M_mv(V[j]))

        def mgs_step(i, wh):
            w, h = wh
            hij = jnp.vdot(V[i], w) * (i <= j)
            return (w - hij * V[i], h.at[i].set(hij))

        h0 = jnp.zeros((restart + 1,), dtype=dtype)
        w, h = jax.lax.fori_loop(0, j + 1, mgs_step, (w, h0))
        hnorm = jnp.linalg.norm(w)
        h = h.at[j + 1].set(hnorm.astype(dtype))
        V = V.at[j + 1].set(
            jnp.where(hnorm > 1e-30, w / hnorm.astype(dtype), w))

        # Rotate the new column by the accumulated rotations, then form
        # the rotation annihilating its subdiagonal.  O(restart) scalar
        # work fused into the matvec program.
        def rot_step(i, h):
            hi, hi1 = h[i], h[i + 1]
            active = i < j
            new_i = cs[i] * hi + sn[i] * hi1
            new_i1 = -jnp.conj(sn[i]) * hi + jnp.conj(cs[i]) * hi1
            h = h.at[i].set(jnp.where(active, new_i, hi))
            return h.at[i + 1].set(jnp.where(active, new_i1, hi1))

        h = jax.lax.fori_loop(0, j, rot_step, h)
        c, s = _givens(h[j], h[j + 1])
        cs = cs.at[j].set(c)
        sn = sn.at[j].set(s)
        h = h.at[j].set(c * h[j] + s * h[j + 1])
        h = h.at[j + 1].set(jnp.zeros((), dtype))
        g = g.at[j + 1].set(-jnp.conj(s) * g[j])
        g = g.at[j].set(c * g[j])
        R = R.at[:, j].set(h[:restart])
        return (V, R, g, cs, sn)

    V, R, g, cs, sn = jax.lax.fori_loop(
        0, restart, body, (V0, R0, g0, cs0, sn0))

    # Back-substitution on the (restart, restart) triangle — O(m^2)
    # scalar flops, noise next to one SpMV.  Zero pivots (breakdown
    # columns) contribute nothing.
    def back_step(t, y):
        i = restart - 1 - t
        num = g[i] - jnp.dot(R[i], y)
        d = R[i, i]
        safe = jnp.where(d == 0, jnp.ones_like(d), d)
        return y.at[i].set(
            jnp.where(d == 0, jnp.zeros_like(num), num / safe))

    y = jax.lax.fori_loop(0, restart, back_step,
                          jnp.zeros((restart,), dtype=dtype))
    x_new = x + M_mv(y @ V[:restart])
    resid = jnp.abs(g[restart]).astype(rdt)
    return x_new, jnp.stack([beta, resid])


def gmres(
    A,
    b,
    x0=None,
    tol=None,
    restart=None,
    maxiter=None,
    M=None,
    callback=None,
    restrt=None,
    atol=0.0,
    callback_type=None,
    rtol=1e-5,
    refine=None,
):
    """Restarted GMRES (scipy/cupy-shaped signature, reference
    ``linalg.py:540-668``).  Returns ``(x, iters)``.

    ``refine="auto"`` (or a positive cycle count) runs mixed-precision
    iterative refinement: inner restarted-GMRES solves over the
    compressed-storage operator, full-precision residual corrections
    between them — same contract as :func:`cg`'s ``refine=``.

    Each restart cycle — Arnoldi, progressive Givens QR of the
    Hessenberg, triangular solve, solution update — runs as ONE traced
    program with zero host round-trips (``_gmres_cycle``).  The only
    host sync in the whole iteration is one scalar fetch per cycle for
    the convergence decision (counted as
    ``transfer.host_sync.gmres_conv``).  The reference ships the
    Hessenberg to the host for a per-cycle ``lstsq`` (``linalg.py:
    640-650``) — the split this package previously copied and now
    eliminates.
    """
    b = jnp.asarray(b)
    if b.ndim == 2 and b.shape[1] == 1:
        b = b.reshape(-1)
    assert b.ndim == 1
    assert len(A.shape) == 2 and A.shape[0] == A.shape[1]
    assert restrt is None or not restart
    if restrt is not None:
        restart = restrt

    n = b.shape[0]
    bnrm2 = float(jnp.linalg.norm(b))
    atol, _ = _get_atol_rtol(bnrm2, tol, atol, rtol)
    if maxiter is None:
        maxiter = n * 10
    if restart is None:
        restart = 20
    restart = min(int(restart), n)

    A_op = make_linear_operator(A)
    b = _promote_rhs(b, A_op)
    M_op = (
        IdentityOperator(A_op.shape, dtype=A_op.dtype)
        if M is None
        else make_linear_operator(M)
    )
    x = (jnp.zeros(n, dtype=b.dtype) if x0 is None
         else jnp.asarray(x0, dtype=b.dtype).reshape(-1))

    if refine is not None:
        if M is not None or callback is not None:
            raise ValueError(
                "gmres: refine= composes with neither M= nor "
                "callback= — inner cycles run over the compressed "
                "operator without the outer preconditioner/observer")
        _obs.inc("op.gmres")

        def _inner(A_in, r, inner_atol, budget):
            return gmres(A_in, r, atol=inner_atol, rtol=0.0,
                         restart=restart, maxiter=budget)

        return _refined_solve(
            "gmres", _inner, A_op, _refine_inner_operator(A), b, x,
            atol, int(maxiter), _refine_cycles(refine))

    cycle = maybe_jit(
        partial(_gmres_cycle, A_op.matvec, M_op.matvec, restart=restart)
    )

    _obs.inc("op.gmres")
    # Resilience (docs/RESILIENCE.md): the per-cycle dispatch is the
    # ``solver.gmres.conv`` fault/retry site (a cycle re-runs from its
    # entry iterate — bit-identical), the cycle fetch feeds the opt-in
    # health monitor, and deadlines are enforced at the same cadence —
    # all riding the one existing host sync per cycle.
    resil = _rsettings.resil
    monitor = _rhealth.Monitor("solver.gmres.conv") if resil else None
    ckpt = _rckpt.current() if resil else None
    resid_f = None
    iters = 0
    while iters < maxiter:
        if resil:
            _rdeadline.raise_if_expired("solver.gmres.conv",
                                        iterations=iters,
                                        residual=resid_f, partial=x)
        with _lat.timer("lat.gmres.cycle." + _lat.shape_bucket(n)), \
                _obs.span("gmres.cycle", restart=restart,
                          iters_done=iters):
            if resil:
                def _cycle_guarded(x=x):
                    xn, st = cycle(x, b)
                    return xn, _rfaults.fault_point("solver.gmres.conv",
                                                    st)

                x_new, stats = _rpolicy.run("solver.gmres.conv",
                                            _cycle_guarded)
            else:
                x_new, stats = cycle(x, b)
            # The convergence cadence: ONE stacked-scalar fetch per
            # cycle — the only host sync in the restarted iteration
            # (the cycle body is sync-free; tests assert it through
            # this counter).
            _obs.inc("transfer.host_sync.gmres_conv")
            beta_f, resid_f = (float(v) for v in np.asarray(stats))
            if monitor is not None:
                # beta (cycle-start norm) going non-finite is the
                # earliest silent-NaN signal; otherwise judge the
                # cycle-end least-squares residual.
                monitor.observe(
                    beta_f if not np.isfinite(beta_f) else resid_f,
                    iters + restart, partial=x_new)
            if beta_f < atol:
                break          # converged at cycle start: keep x
            x = x_new
        iters += restart
        if ckpt is not None:
            # GMRES restarts from its iterate alone — the Arnoldi seed
            # x is the whole restartable state.
            ckpt.maybe_save(iters, (x,))
        if callback is not None:
            if callback_type == "pr_norm":
                callback(float(jnp.linalg.norm(b - A_op.matvec(x))) / bnrm2)
            else:
                callback(x)
        if resid_f < atol:
            # The Givens estimate equals the true residual norm only in
            # exact arithmetic; confirm on the real residual so MGS
            # drift can never fabricate convergence (one extra sync at
            # suspected convergence only).
            _obs.inc("transfer.host_sync.gmres_conv")
            if float(jnp.linalg.norm(b - A_op.matvec(x))) < atol:
                break
    return x, iters


def _safe_div(num, den):
    """num/den with an exact-0 result (not NaN) when den == 0 — lets
    exactly-converged states flow to the convergence check."""
    return jnp.where(
        den == 0, jnp.zeros_like(num),
        num / jnp.where(den == 0, jnp.ones_like(den), den),
    )


def _bicgstab_body(A_mv: Callable, M_mv: Callable, conv_test_iters: int):
    """One BiCGSTAB iteration as a state->state function (shared by the
    while_loop path and the callback path, so both run the identical
    algorithm with carried shadow-residual/direction state)."""

    def body(state):
        (x, r, rtilde, p, v, rho_prev, alpha, omega, iters, done, atol2,
         miter) = state
        rho = jnp.vdot(rtilde, r)
        beta = _safe_div(rho, rho_prev) * _safe_div(alpha, omega)
        first = iters == 0
        p = jnp.where(first, r, r + beta * (p - omega * v))
        phat = M_mv(p)
        v = A_mv(phat)
        alpha = _safe_div(rho, jnp.vdot(rtilde, v))
        s = r - alpha * v
        shat = M_mv(s)
        t = A_mv(shat)
        omega = _safe_div(jnp.vdot(t, s), jnp.vdot(t, t))
        x = x + alpha * phat + omega * shat
        r = s - omega * t
        iters = iters + 1
        check = jnp.logical_or(
            iters % conv_test_iters == 0, iters == miter - 1
        )
        rnorm2 = jnp.real(jnp.vdot(r, r))
        done = jnp.logical_or(done, jnp.logical_and(check, rnorm2 < atol2))
        return (x, r, rtilde, p, v, rho, alpha, omega, iters, done,
                atol2, miter)

    return body


def _bicgstab_state0(A_mv, b, x0, atol, maxiter):
    r0 = b - A_mv(x0)
    one = jnp.ones((), dtype=b.dtype)
    return (
        x0, r0, r0, jnp.zeros_like(b), jnp.zeros_like(b),
        one, one, one,
        jnp.asarray(0, dtype=index_dtype()), jnp.asarray(False),
        jnp.asarray(atol, dtype=jnp.real(b).dtype) ** 2,
        jnp.asarray(maxiter, dtype=index_dtype()),
    )


def _bicgstab_loop(A_mv: Callable, M_mv: Callable, b, x0, atol: float,
                   maxiter: int, conv_test_iters: int):
    """Whole preconditioned-BiCGSTAB solve as one XLA while_loop (same
    state-carried (atol2, maxiter) and deferred-convergence design as
    ``_cg_loop``)."""

    def cond(state):
        return jnp.logical_and(
            state[8] < state[11], jnp.logical_not(state[9])
        )

    body = _bicgstab_body(A_mv, M_mv, conv_test_iters)
    out = jax.lax.while_loop(
        cond, body, _bicgstab_state0(A_mv, b, x0, atol, maxiter)
    )
    return out[0], out[8]


def bicgstab(
    A,
    b,
    x0=None,
    tol=None,
    maxiter=None,
    M=None,
    callback=None,
    atol=0.0,
    rtol=1e-5,
    conv_test_iters: int = 25,
):
    """BiCGSTAB solve of ``A x = b`` (scipy-shaped signature).

    Beyond-reference solver (the reference ships cg/gmres only,
    ``linalg.py:465-668``): handles non-symmetric systems without
    GMRES's restart memory, entirely jitted like ``cg``.
    """
    A_op = make_linear_operator(A)
    b = jnp.asarray(b)
    if b.ndim == 2 and b.shape[1] == 1:
        b = b.reshape(-1)
    b = _promote_rhs(b, A_op)
    assert b.ndim == 1
    assert len(A_op.shape) == 2 and A_op.shape[0] == A_op.shape[1]
    n = b.shape[0]
    bnrm2 = float(jnp.linalg.norm(b))
    atol, _ = _get_atol_rtol(bnrm2, tol, atol, rtol)
    if maxiter is None:
        maxiter = n * 10
    M_op = (
        IdentityOperator(A_op.shape, dtype=A_op.dtype)
        if M is None
        else make_linear_operator(M)
    )
    x0_arr = (jnp.zeros(n, dtype=b.dtype) if x0 is None
              else jnp.asarray(x0, dtype=b.dtype).reshape(-1))
    _obs.inc("op.bicgstab")
    if callback is None:
        with _lat.timer("lat.bicgstab.solve."
                        + _lat.shape_bucket(n)), \
                _obs.span("bicgstab", n=n, maxiter=int(maxiter)) as sp:
            xs, iters = _bicgstab_loop(
                A_op.matvec, M_op.matvec, b, x0_arr, atol, int(maxiter),
                int(conv_test_iters),
            )
            if sp is not None:
                sp.set(iters=int(iters))
        return xs, iters
    # Callback path: step the SAME state->state iteration (shadow
    # residual and direction state carried across steps) Python-side so
    # user code observes every iterate; r lives in the state, so the
    # convergence check costs no extra matvec.
    body = maybe_jit(_bicgstab_body(A_op.matvec, M_op.matvec,
                                  conv_test_iters=1))
    state = _bicgstab_state0(A_op.matvec, b, x0_arr, atol, int(maxiter))
    iters = 0
    while iters < maxiter:
        state = body(state)
        iters = int(state[8])
        callback(state[0])
        if bool(state[9]):  # done flag: ||r|| < atol at the cadence
            break
    return state[0], iters


def norm(A, ord=None, axis=None):
    """Sparse matrix/vector norms (scipy.sparse.linalg.norm surface).

    Matrix norms (``axis=None``): Frobenius (default/'fro'), 1 /
    -1 (max/min absolute column sum), inf / -inf (max/min absolute row
    sum), 2 (spectral — delegated to scipy on host, it needs an SVD).
    ``axis=0``/``1`` give per-column/per-row vector norms (ord None/2 =
    Euclidean, 1 = abs sum, inf = abs max, -inf = abs min including
    implicit zeros, 0 = count of nonzeros), returned as numpy arrays
    (scipy returns numpy).  Computed on device from the stored values
    (duplicates canonicalized first).
    """
    from .utils import is_sparse_matrix

    if not is_sparse_matrix(A):
        raise TypeError("input is not a sparse matrix")
    A = A.tocsr() if A.format != "csr" else A
    if A.shape[0] == 0 or A.shape[1] == 0:
        raise ValueError("zero-size array to reduction operation")
    if A.nnz and not A.has_canonical_format:
        A.sum_duplicates()

    def absA():
        return A._with_data(jnp.abs(A.data))

    if axis is None:
        if ord in (None, "fro", "f"):
            return float(jnp.sqrt(jnp.sum(jnp.abs(A.data) ** 2)))
        if ord == 1:
            return float(jnp.max(absA().sum(axis=0)))
        if ord == -1:
            return float(jnp.min(absA().sum(axis=0)))
        if ord in (np.inf, float("inf")):
            return float(jnp.max(absA().sum(axis=1)))
        if ord in (-np.inf, float("-inf")):
            return float(jnp.min(absA().sum(axis=1)))
        if ord == 2:
            # Spectral norm needs an SVD; scipy computes it on host.
            import scipy.sparse.linalg as _ssl

            return float(_ssl.norm(A.toscipy(), ord=2))
        raise ValueError(f"Invalid norm order {ord!r} for matrices")

    if axis not in (0, 1, -1, -2):
        raise ValueError(f"invalid axis {axis}")
    axis = axis % 2
    if ord in (None, 2):
        sq = A._with_data(A.data * jnp.conj(A.data))
        return np.asarray(jnp.sqrt(jnp.real(sq.sum(axis=axis))))
    if ord == 1:
        return np.asarray(absA().sum(axis=axis))
    if ord in (np.inf, float("inf")):
        return np.asarray(absA().max(axis=axis))
    if ord in (-np.inf, float("-inf")):
        # Min absolute value per row/column, implicit-zero aware: any
        # row/column with fewer stored entries than its length has an
        # implicit zero, so its min is 0 (scipy semantics via todense).
        counts = np.asarray(A.getnnz(axis=axis))
        # Reducing along ``axis`` spans shape[axis] elements per slice
        # (axis=1: each row has ncols entries).
        full = A.shape[axis]
        m = np.asarray(absA().min(axis=axis))
        return np.where(counts < full, np.minimum(m, 0.0), m)
    if ord == 0:
        # Count of explicit nonzero *values* (scipy counts (x != 0)).
        nz = A._with_data(
            (A.data != 0).astype(jnp.result_type(A.dtype, jnp.float32))
        )
        return np.asarray(nz.sum(axis=axis))
    raise ValueError(f"Invalid norm order {ord!r} for vectors")


# Device-native eigensolvers and extra Krylov solvers (module
# attributes take priority over the __getattr__ fallback below, so
# these shadow the host-scipy versions).
from .eigen import eigs, eigsh, lobpcg, svds  # noqa: E402
from .expm import expm_multiply  # noqa: E402
from .krylov_extra import (differentiable_solve, lsmr, lsqr,  # noqa: E402
                           minres)
from .precond import block_jacobi, jacobi  # noqa: E402


def __getattr__(name):
    """scipy.sparse.linalg fallback for names without a native
    implementation (spsolve, splu, expm, tfqmr, ...): host-side
    scipy with this package's arrays converted at the boundary.  The
    reference offers no fallback here at all (its linalg is cg/gmres
    only); a drop-in replacement must not strand the rest of a user's
    solver code."""
    import scipy.sparse.linalg as _ssl

    from .coverage import scipy_fallback

    try:
        value = getattr(_ssl, name)
    except AttributeError:
        raise AttributeError(
            f"module 'legate_sparse_tpu.linalg' has no attribute {name!r}"
        ) from None
    if callable(value) and not isinstance(value, type):
        value = scipy_fallback(value, f"linalg.{name}")
    globals()[name] = value   # cache: stable identity, one wrapper
    return value
