# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Public module namespace (reference: ``legate_sparse/module.py``)."""

from .csr import csr_array, csr_matrix, spmv, spgemm_csr_csr_csr  # noqa: F401
from .csc import csc_array, csc_matrix  # noqa: F401
from .coo import coo_array, coo_matrix  # noqa: F401
from .dia import dia_array, dia_matrix  # noqa: F401
from .gallery import (  # noqa: F401
    block_array, block_diag, bmat, diags, eye, find, hstack, identity,
    kron, kronsum, random, spdiags, tril, triu, vstack,
)
from .io import load_npz, mmread, mmwrite, save_npz  # noqa: F401
from .types import coord_ty, nnz_ty  # noqa: F401
from .base import CompressedBase


def is_sparse_matrix(o) -> bool:
    from .utils import is_sparse_matrix as _impl

    return _impl(o)


def issparse(o) -> bool:
    return is_sparse_matrix(o)


def isspmatrix(o) -> bool:
    return is_sparse_matrix(o)


def isspmatrix_coo(o) -> bool:
    from .coo import coo_array

    return isinstance(o, coo_array)


def isspmatrix_csc(o) -> bool:
    from .csc import csc_array

    return isinstance(o, csc_array)


def isspmatrix_csr(o) -> bool:
    return isinstance(o, csr_array)


def isspmatrix_dia(o) -> bool:
    return isinstance(o, dia_array)
