# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""legate_sparse_tpu.obs: observability — op-level tracing, counters,
and structured perf evidence.

Twelve pieces (see each module's docstring for the design contract):

- ``trace``    — near-zero-overhead spans (``with obs.span("spmv",
                 nnz=...)``) recording wall time + first-call vs
                 steady-state, exporting newline-JSON and
                 Chrome-trace/Perfetto; structured instant events.
- ``counters`` — always-on process-wide counters (op invocations, nnz
                 processed, bytes moved, transfers, scipy-fallback
                 hits, jit cache misses) with a per-thread buffered
                 lock-free fast path (``counters.handle``) for
                 hot-loop sites.
- ``report``   — aggregation into a per-op table with achieved GB/s
                 against the measured stream roofline.
- ``latency``  — always-on streaming latency histograms (``lat.*``):
                 mergeable fixed-log2-bucket distributions with a
                 documented quantile error bound, written through the
                 same lock-free per-thread-handle pattern as counters.
- ``comm``     — the communication ledger: per-collective interconnect
                 byte predictions from static shard shapes, recorded
                 as ``comm.*`` counters and solver-span attrs.
- ``export``   — OpenMetrics/Prometheus text rendering of all counters
                 and histograms (``snapshot_openmetrics()`` /
                 ``write_openmetrics``; ``LEGATE_SPARSE_TPU_OBS_PROM``
                 arms an atexit snapshot-to-file).
- ``memory``   — phase memory watermarks (``mem.*`` events: RSS,
                 device stats, optional tracemalloc peaks).
- ``regress``  — the bench-trajectory regression gate behind
                 ``tools/bench_compare.py``.
- ``context``  — causal trace ids minted at ``Gateway.submit`` /
                 ``Executor.submit``, carried across worker threads on
                 the request record, auto-tagged onto spans/events and
                 exported as Chrome-trace flow arcs (obs v4).
- ``slo``      — declarative per-(op, QoS) latency objectives with
                 error budgets, evaluated as multi-window burn rates
                 over the ``lat.*`` histograms; inert without
                 ``LEGATE_SPARSE_TPU_OBS_SLO`` (obs v4).
- ``attrib``   — per-tenant cost attribution ledger: wall time, comm
                 bytes, wait, dispatch/compile counts and watermark
                 growth charged to the ``(tenant, qos)`` identity the
                 TraceContext carries, with an exact-conservation
                 split rule for packed multi-tenant batches; inert
                 without ``LEGATE_SPARSE_TPU_OBS_ATTRIB`` (obs v5).
- ``capacity`` — rolling mesh-slice utilization window over the
                 attributed dispatch spans + the pure-function
                 advisory capacity report (``capacity.recommendation``
                 events) joining demand, QoS weight and SLO burn
                 (obs v5).

Enable tracing with ``LEGATE_SPARSE_TPU_OBS=1`` (read once at import,
like the other settings) or programmatically::

    from legate_sparse_tpu import obs
    obs.enable()
    ...             # run the workload
    obs.write_chrome_trace("run.trace.json")
    print(obs.report.summarize(obs.records()))

Disabled (the default) the span API is a no-op returning a shared
null context manager; counters stay live either way.
"""

from . import (  # noqa: F401
    attrib, capacity, comm, context, counters, export, latency, memory,
    regress, report, slo, trace,
)
from .counters import inc, snapshot  # noqa: F401
from .export import snapshot_openmetrics, write_openmetrics  # noqa: F401
from .latency import observe  # noqa: F401
from .trace import (  # noqa: F401
    disable, enable, enabled, event, records, reset, span,
    to_chrome_trace, write_chrome_trace, write_jsonl,
)

__all__ = [
    "attrib", "capacity", "comm", "context", "counters", "export",
    "latency", "memory", "regress", "report", "slo", "trace",
    "inc", "snapshot", "observe",
    "snapshot_openmetrics", "write_openmetrics",
    "enable", "disable", "enabled", "event", "records", "reset", "span",
    "to_chrome_trace", "write_chrome_trace", "write_jsonl",
]


def reset_all() -> None:
    """Convenience: drop buffered trace records AND zero counters and
    histograms (test isolation / between bench phases); SLO window
    baselines reset with them (they are snapshots of the zeroed
    histograms)."""
    trace.reset()
    counters.reset()
    latency.reset()
    slo.reset()
    attrib.reset()
    capacity.reset()
