# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Per-tenant resource attribution ledger (obs v5).

The gateway counts per-tenant *requests*
(``gateway.tenant.<t>.submitted/served/shed``) but device-time,
communication bytes, memory watermarks, and compile cost were pool-wide
aggregates — no controller could answer "which tenant is consuming the
mesh".  This module is the sensor: it rides the existing
:class:`~legate_sparse_tpu.obs.context.TraceContext` (extended to carry
``tenant``/``qos``, minted at ``Gateway.submit`` and carried across
executor workers exactly like trace ids) and attributes, at span close
and comm-ledger record time, wall time, ``comm.*`` bytes,
dispatch/compile counts, and ``mem.*`` watermark deltas to
``(tenant, qos, op)``.

Apportioning rule (declared, deterministic)
-------------------------------------------
Packed multi-tenant batches (``gateway.batch`` over ``multi_matvec`` /
grouped ``matmat``, ``engine.batch`` over a stacked operand) dispatch
ONE device program for K member requests.  Costs are integers (bytes;
wall time in integer ns) and are split **by member request count**:
every member gets ``total // K``; the remainder ``total % K`` is handed
out one unit at a time to members in ascending ``(tenant, qos,
position)`` order.  Integer apportioning means per-tenant sums conserve
EXACTLY against the untagged totals:

- ``sum_t attrib.tenant.<t>.comm_bytes == attrib.total.comm_bytes``,
  and both equal the ``comm.total_bytes`` delta over the attributed
  window (the bytes hook fires inside :func:`comm.record` under the
  same gating as ``comm.total_bytes``);
- ``sum_t attrib.tenant.<t>.wall_ns == attrib.total.wall_ns`` — equal
  to the summed duration of the attributed dispatch spans.

Work with no tenant (direct engine calls, maintenance traffic) is
attributed to the reserved ``__untagged__`` tenant rather than dropped,
so conservation holds for the whole process, not just gateway traffic.

What is attributed where
------------------------
- **bytes / collective calls** — :func:`on_comm`, called by
  ``comm.record``; active whenever ``settings.obs_attrib`` is on
  (needs no tracing).
- **wall time / dispatch + compile counts** — :func:`on_span_close`,
  called by ``trace`` when a span in :data:`DISPATCH_SPANS` closes
  (``gateway.batch`` / ``engine.batch``: the top-level dispatch busy
  spans, never nested in each other).  Spans only exist while tracing
  is on (``LEGATE_SPARSE_TPU_OBS=1``), so wall attribution rides the
  same switch.  A first-call span (compile) bumps ``compiles``.
- **gateway/executor wait** — :func:`on_wait` from the request finish
  paths: every outcome attributes its queue wait, so shed/errored
  requests show up with wait but zero dispatch cost.
- **memory watermark deltas** — :func:`on_mem` from
  ``memory.watermark.__exit__`` (positive RSS growth only, KiB —
  counters are monotone).

Tenant-label cardinality is bounded: :func:`tenant_label` sanitizes
names to a dot-free safe charset and, past
``settings.obs_tenant_cap`` distinct tenants (default 64,
``LEGATE_SPARSE_TPU_OBS_TENANT_CAP``), folds overflow into the
reserved ``__other__`` label, so counter families and OpenMetrics
label sets cannot grow without bound.

Counters (all under ``attrib.``, inert-by-default —
``LEGATE_SPARSE_TPU_OBS_ATTRIB``)::

    attrib.tenant.<tenant>.comm_bytes   attributed interconnect bytes
    attrib.tenant.<tenant>.comm_calls   attributed collective ops
    attrib.tenant.<tenant>.wall_ns      attributed dispatch busy time
    attrib.tenant.<tenant>.wait_ns      attributed queue wait
    attrib.tenant.<tenant>.dispatches   dispatch spans (apportioned
                                        member count)
    attrib.tenant.<tenant>.compiles     first-call dispatch spans
    attrib.tenant.<tenant>.mem_kb       watermark RSS growth
    attrib.op.<tenant>.<qos>.<op>.ns    per-(tenant, qos, op) wall ns
    attrib.total.*                      untagged totals, bumped at the
                                        same hook sites (conservation)
    attrib.fold.other                   tenant-cap folds performed

Overhead contract: with ``settings.obs_attrib`` off every hook is one
attribute read and a return — no counters move, no labels intern, and
results are bit-for-bit identical (nothing here touches dispatch
math).
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

from . import context as _context
from . import counters as _counters
from ..settings import settings as _rsettings

__all__ = [
    "UNTAGGED", "OTHER", "DISPATCH_SPANS", "enabled", "tenant_label",
    "scope", "current_members", "apportion", "on_comm",
    "on_span_close", "on_wait", "on_mem", "tenant_snapshot", "reset",
]

#: Reserved sink for work with no tenant context (conservation).
UNTAGGED = "__untagged__"
#: Reserved fold target once the tenant-label cap is reached.
OTHER = "__other__"
_RESERVED = (UNTAGGED, OTHER)

#: The dispatch busy-span set: top-level spans whose duration is
#: attributed as device time.  ``gateway.batch`` and ``engine.batch``
#: are never nested inside each other (the gateway dispatches the
#: engine facade directly, not through the executor), and
#: ``gateway.inline`` (the gateway's single-request plain dispatch:
#: ineligible matrices — including placed-tenant handles — and
#: fault/breaker degradation) never runs inside either, so summing
#: their durations never double-counts.
DISPATCH_SPANS = frozenset({"gateway.batch", "engine.batch",
                            "gateway.inline"})

# (tenant, qos) member list of the active packed batch, if any; set by
# the gateway/executor dispatch paths around multi-member dispatches.
# A scope wins over the single-request TraceContext.
_scope_var: "contextvars.ContextVar[Optional[Tuple[Tuple[str, str], ...]]]" = \
    contextvars.ContextVar("legate_sparse_tpu_attrib_scope", default=None)

# Distinct non-reserved tenant labels seen (cardinality cap state).
_lock = threading.Lock()
_seen: set = set()

_SAFE = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_-")


def enabled() -> bool:
    """One settings read: the whole-subsystem switch."""
    return _rsettings.obs_attrib


def tenant_label(raw: Optional[str]) -> str:
    """Sanitized, cardinality-capped counter label for a tenant name.

    Characters outside ``[A-Za-z0-9_-]`` (including ``.``, quotes,
    newlines, arbitrary unicode) map to ``_`` so labels are dot-free
    (counter names stay parseable) and OpenMetrics-safe before the
    exporter's own escaping even runs; labels truncate at 64 chars.
    Past ``settings.obs_tenant_cap`` distinct labels, new ones fold to
    ``__other__``.  Reserved labels pass through and never count
    toward the cap."""
    if not raw:
        return UNTAGGED
    raw = str(raw)
    if raw in _RESERVED:
        return raw
    label = "".join(c if c in _SAFE else "_" for c in raw[:64])
    if not label.strip("_"):
        # Fully mangled (e.g. all-unicode name): keep a stable
        # non-empty stand-in rather than colliding with reserved names.
        label = f"t{len(label)}" if label else "t0"
    with _lock:
        if label in _seen:
            return label
        if len(_seen) >= max(1, int(_rsettings.obs_tenant_cap)):
            _counters.handle("attrib.fold.other").inc()
            return OTHER
        _seen.add(label)
        return label


def _qos_label(qos: Optional[str]) -> str:
    if not qos:
        return "none"
    return "".join(c if c in _SAFE else "_" for c in str(qos)[:32])


@contextlib.contextmanager
def scope(members: Sequence[Tuple[Optional[str], Optional[str]]]
          ) -> Iterator[None]:
    """Declare the ``(tenant, qos)`` members of a packed multi-member
    dispatch for the body: every hook fired inside apportions its cost
    across these members (the declared split rule).  Wins over the
    single-request TraceContext.  No-op (and allocation-free beyond
    the contextvar set) when attribution is off or ``members`` is
    empty."""
    if not _rsettings.obs_attrib or not members:
        yield
        return
    resolved = tuple((tenant_label(t), _qos_label(q)) for t, q in members)
    token = _scope_var.set(resolved)
    try:
        yield
    finally:
        _scope_var.reset(token)


def current_members() -> Tuple[Tuple[str, str], ...]:
    """The members the next cost attributes to: the active scope's,
    else the active TraceContext's ``(tenant, qos)``, else
    ``__untagged__``."""
    sc = _scope_var.get()
    if sc:
        return sc
    ctx = _context.current()
    if ctx is not None and getattr(ctx, "tenant", None):
        return ((tenant_label(ctx.tenant), _qos_label(ctx.qos)),)
    return ((UNTAGGED, "none"),)


def apportion(total: int, members: Sequence[Tuple[str, str]]
              ) -> List[int]:
    """Split integer ``total`` across ``members`` by request count:
    ``total // K`` each, remainder one unit at a time in ascending
    ``(tenant, qos, position)`` order.  Deterministic, and
    ``sum(result) == total`` exactly."""
    k = len(members)
    total = int(total)
    base, rem = divmod(total, k)
    shares = [base] * k
    if rem:
        order = sorted(range(k), key=lambda i: (members[i], i))
        for i in order[:rem]:
            shares[i] += 1
    return shares


def _attribute(kind: str, total: int,
               members: Optional[Sequence[Tuple[str, str]]] = None
               ) -> None:
    """Apportion ``total`` integer units of ``kind`` across the active
    members and bump the per-tenant + untagged-total counters at the
    same site (the conservation invariant is by construction)."""
    total = int(total)
    if total <= 0:
        return
    if members is None:
        members = current_members()
    for (tenant, _qos), share in zip(members,
                                     apportion(total, members)):
        if share:
            _counters.handle(f"attrib.tenant.{tenant}.{kind}").inc(share)
    _counters.handle(f"attrib.total.{kind}").inc(total)


# ---------------------------------------------------------------- hooks --
def on_comm(op: str, total_bytes: int, total_calls: int) -> None:
    """``comm.record`` hook: attribute one distributed dispatch's
    predicted interconnect bytes and collective-op count.  Fires under
    the exact gating of ``comm.total_bytes`` (non-zero dispatches
    only), so attributed sums conserve against it exactly."""
    if not _rsettings.obs_attrib:
        return
    members = current_members()
    _attribute("comm_bytes", total_bytes, members)
    _attribute("comm_calls", total_calls, members)


def on_span_close(name: str, dur_ns: int, first: bool) -> None:
    """Span-close hook (from ``trace``): attribute a dispatch span's
    wall time, dispatch count, and compile (first-call) count.  Only
    spans in :data:`DISPATCH_SPANS` are device-time; everything else
    returns immediately."""
    if not _rsettings.obs_attrib or name not in DISPATCH_SPANS:
        return
    members = current_members()
    _attribute("wall_ns", dur_ns, members)
    _attribute("dispatches", len(members), members)
    if first:
        _attribute("compiles", len(members), members)
    for (tenant, qos), share in zip(members,
                                    apportion(int(dur_ns), members)):
        if share:
            _counters.handle(
                f"attrib.op.{tenant}.{qos}.{name}.ns").inc(share)
    # Feed the rolling utilization window (busy-ms estimator).
    from . import capacity as _capacity
    _capacity.note_busy(dur_ns, members)


def on_wait(tenant: Optional[str], qos: Optional[str],
            wait_ns: int) -> None:
    """Request-finish hook: attribute queue wait for every outcome —
    shed and errored requests attribute their wait here and nothing
    else (they never reach a dispatch span or a comm record)."""
    if not _rsettings.obs_attrib:
        return
    _attribute("wait_ns", wait_ns,
               ((tenant_label(tenant), _qos_label(qos)),))


def on_mem(name: str, delta_mb: float) -> None:
    """Watermark-exit hook: attribute positive RSS growth (KiB ints —
    counters are monotone; negative deltas are releases, not cost)."""
    if not _rsettings.obs_attrib:
        return
    kb = int(delta_mb * 1024)
    if kb > 0:
        _attribute("mem_kb", kb)


# ------------------------------------------------------------- surfaces --
def tenant_snapshot(counters_snap: Optional[dict] = None) -> dict:
    """``{tenant: {kind: value}}`` from the ``attrib.tenant.*``
    counters (a live snapshot when none is passed) — the join surface
    for the capacity report, doctor, and the ``--tenants`` table."""
    snap = (_counters.snapshot("attrib.tenant.")
            if counters_snap is None else counters_snap)
    out: dict = {}
    prefix = "attrib.tenant."
    for cname, val in snap.items():
        if not cname.startswith(prefix):
            continue
        body, _, kind = cname[len(prefix):].rpartition(".")
        if not body:
            continue
        out.setdefault(body, {})[kind] = int(val)
    return out


def reset() -> None:
    """Forget seen tenant labels (test isolation; counters are reset
    by ``counters.reset``)."""
    with _lock:
        _seen.clear()
