# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Device-utilization window + advisory capacity report (obs v5).

The second half of the elastic-placement sensor layer
(:mod:`~legate_sparse_tpu.obs.attrib` is the first): a rolling
mesh-slice utilization estimator fed busy time from the tagged
dispatch spans, and a **pure-function** capacity recommendation that
joins three existing signals — per-tenant demand (attributed busy
ns), QoS weight (the gateway's WFQ weights), and SLO burn rate
(``slo.verdicts()``) — into an advisory per-tenant submesh sizing.
This PR only observes: the recommendation is emitted as a
``capacity.recommendation`` event for the PR-19+ placement controller
(ROADMAP item 2, whose actuator is the exactly-priced ``reshard()``)
to consume; nothing here moves data or resizes anything.

Utilization model
-----------------
Busy time is the summed duration of the attributed dispatch spans
(``gateway.batch`` / ``engine.batch`` — top-level, never nested, so
the sum never double-counts).  The window is a bounded deque of
``(ts_ns, busy_ns, tenant)`` samples; :func:`utilization` reports the
busy fraction of the trailing wall window, optionally divided across
``devices`` mesh slices (a single host process drives the whole mesh,
so process busy-fraction IS mesh-slice busy-fraction until a
per-device profiler lands).

Counters (inert-by-default with the attribution ledger)::

    util.busy_ns       total attributed dispatch busy time
    util.dispatches    dispatch spans observed
    capacity.reports   capacity reports emitted

Events::

    capacity.recommendation   one per report: devices, busy_frac and
                              a per-tenant share/devices breakdown

Overhead contract: with ``settings.obs_attrib`` off nothing here is
called (the attrib span hook gates), every public entry returns
immediately on its own flag read, and the window stays empty.
"""

from __future__ import annotations

import json
import math
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from . import attrib as _attrib
from . import counters as _counters
from . import slo as _slo
from . import trace as _trace
from ..settings import settings as _rsettings

__all__ = [
    "BURN_PAGE", "note_busy", "utilization", "recommend",
    "demand_snapshot", "capacity_report", "reset",
]

#: Fast-window burn at or above this marks a tenant "burning" (the
#: same page threshold the SLO evaluator breaches at).
BURN_PAGE = 14.4

#: Bounded sample window: at bench dispatch rates (~1k/s) this holds
#: well over a minute of samples; eviction is by timestamp anyway.
_MAX_SAMPLES = 8192

_lock = threading.Lock()
# (ts_ns, busy_ns, tenant_label) samples, newest right.
_window: "deque[Tuple[int, int, str]]" = deque(maxlen=_MAX_SAMPLES)


def note_busy(dur_ns: int,
              members: Sequence[Tuple[str, str]]) -> None:
    """Feed one closed dispatch span into the window (called by the
    attrib span hook — already gated on ``settings.obs_attrib``).
    The span's duration is apportioned across its members with the
    declared attrib split rule."""
    now = time.monotonic_ns()
    _counters.handle("util.busy_ns").inc(int(dur_ns))
    _counters.handle("util.dispatches").inc()
    shares = _attrib.apportion(int(dur_ns), members)
    with _lock:
        for (tenant, _qos), share in zip(members, shares):
            if share:
                _window.append((now, share, tenant))


def utilization(window_ms: float = 60_000.0, *,
                devices: int = 1,
                now_ns: Optional[int] = None) -> Dict[str, object]:
    """Busy fraction of the trailing ``window_ms`` wall window, total
    and per tenant.  ``devices`` divides the busy fraction across
    mesh slices (advisory; the host process drives the whole mesh).
    Pure over the window state — no counters move."""
    now = time.monotonic_ns() if now_ns is None else int(now_ns)
    horizon = now - int(window_ms * 1e6)
    busy = 0
    per_tenant: Dict[str, int] = {}
    with _lock:
        while _window and _window[0][0] < horizon:
            _window.popleft()
        for _ts, share, tenant in _window:
            busy += share
            per_tenant[tenant] = per_tenant.get(tenant, 0) + share
    wall = max(1, int(window_ms * 1e6)) * max(1, int(devices))
    return {
        "window_ms": float(window_ms),
        "devices": int(devices),
        "busy_ns": int(busy),
        "busy_frac": min(1.0, busy / wall),
        "per_tenant": per_tenant,
    }


def recommend(demand: Dict[str, Dict[str, object]],
              qos_weights: Dict[str, float],
              burns: Dict[Optional[str], float],
              devices: int) -> Dict[str, object]:
    """PURE advisory submesh sizing from the three sensor signals.

    - ``demand``: ``{tenant: {"busy_ns": int, "qos": str}}`` (reserved
      tenants allowed; they compete for share like any other).
    - ``qos_weights``: WFQ weight per QoS class (unknown classes
      weigh 1.0).
    - ``burns``: fast-window burn per QoS class from the SLO
      evaluator; a tenant whose class burns at page level
      (>= :data:`BURN_PAGE`) is "burning" and rounds UP.
    - ``devices``: total mesh devices to apportion.

    Rule: weighted demand ``busy_ns * weight(qos)`` normalizes to a
    share; every demanding tenant gets at least 1 device; burning
    tenants ceil, others floor; if the total overshoots ``devices``,
    the overshoot is trimmed one device at a time from the largest
    non-burning allocations (ties by tenant name — deterministic).
    The result may still exceed ``devices`` when every tenant is
    burning: that IS the signal the mesh is undersized."""
    devices = max(1, int(devices))
    weighted: Dict[str, float] = {}
    for tenant, d in sorted(demand.items()):
        busy = int(d.get("busy_ns", 0))
        if busy <= 0:
            continue
        weight = float(qos_weights.get(d.get("qos"), 1.0))
        weighted[tenant] = busy * weight
    total_w = sum(weighted.values())
    tenants: Dict[str, Dict[str, object]] = {}
    if total_w > 0:
        for tenant, w in sorted(weighted.items()):
            share = w / total_w
            qos = demand[tenant].get("qos")
            burning = float(burns.get(qos, 0.0)) >= BURN_PAGE
            raw = share * devices
            n = math.ceil(raw) if burning else math.floor(raw)
            tenants[tenant] = {
                "share": share,
                "qos": qos,
                "burning": burning,
                "devices": max(1, int(n)),
            }
        overshoot = sum(t["devices"] for t in tenants.values()) - devices
        if overshoot > 0:
            victims = sorted(
                (t for t, rec in tenants.items()
                 if not rec["burning"] and rec["devices"] > 1),
                key=lambda t: (-tenants[t]["devices"], t))
            for t in victims:
                if overshoot <= 0:
                    break
                take = min(overshoot, tenants[t]["devices"] - 1)
                tenants[t]["devices"] -= take
                overshoot -= take
    allocated = sum(t["devices"] for t in tenants.values())
    return {
        "devices": devices,
        "allocated": allocated,
        "undersized": allocated > devices,
        "tenants": tenants,
    }


def demand_snapshot(*, include_wait: bool = False
                    ) -> Dict[str, Dict[str, object]]:
    """Per-tenant demand from the live attribution ledger —
    ``{tenant: {"busy_ns": int, "qos": str|None}}``, the first input
    of :func:`recommend`.  Busy is the attributed dispatch wall time;
    ``include_wait=True`` adds attributed queue wait (the placement
    controller's choice: wait accrues on every armed gateway request,
    so demand keeps moving even with span tracing off).  QoS is the
    tenant's dominant class (largest ``attrib.op.<tenant>.<qos>.*``
    bucket; None when no tagged dispatch span closed yet)."""
    per_qos: Dict[str, Dict[str, int]] = {}
    for cname, val in _counters.snapshot("attrib.op.").items():
        parts = cname[len("attrib.op."):].split(".")
        if len(parts) < 3:
            continue
        tenant, qos = parts[0], parts[1]
        bucket = per_qos.setdefault(tenant, {})
        bucket[qos] = bucket.get(qos, 0) + int(val)
    demand: Dict[str, Dict[str, object]] = {}
    for tenant, info in _attrib.tenant_snapshot().items():
        busy = int(info.get("wall_ns", 0))
        if include_wait:
            busy += int(info.get("wait_ns", 0))
        if busy <= 0:
            continue
        qos_hist = per_qos.get(tenant, {})
        qos = max(sorted(qos_hist), key=qos_hist.get) if qos_hist \
            else None
        demand[tenant] = {"busy_ns": busy, "qos": qos}
    return demand


def capacity_report(devices: int = 1, *,
                    window_ms: float = 60_000.0) -> Optional[dict]:
    """Join the live sensors into one advisory recommendation, bump
    ``capacity.reports`` and emit the ``capacity.recommendation``
    event.  Returns the recommendation dict (None when attribution is
    off — one flag read)."""
    if not _rsettings.obs_attrib:
        return None
    util = utilization(window_ms, devices=devices)
    demand = demand_snapshot()
    burns: Dict[Optional[str], float] = {}
    for v in _slo.verdicts():
        burns[v.qos] = max(burns.get(v.qos, 0.0), v.fast_burn)
    try:
        from ..engine.gateway import QOS_WEIGHTS as qos_weights
    except Exception:  # pragma: no cover - engine layer unavailable
        qos_weights = {}
    rec = recommend(demand, qos_weights, burns, devices)
    rec["busy_frac"] = util["busy_frac"]
    _counters.handle("capacity.reports").inc()
    _trace.event("capacity.recommendation",
                 devices=rec["devices"], allocated=rec["allocated"],
                 undersized=rec["undersized"],
                 busy_frac=round(float(util["busy_frac"]), 6),
                 tenants=json.dumps(rec["tenants"], sort_keys=True))
    return rec


def reset() -> None:
    """Drop the sample window (test isolation)."""
    with _lock:
        _window.clear()
