# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Communication ledger: predicted interconnect bytes for the
distributed layer's collectives, computed from STATIC shard shapes.

Distributed SpMV is communication-bound at scale (Kreutzer et al.,
arXiv:1112.5588) and bytes-moved is the first-order sparse metric
(SpArch, arXiv:2002.08947) — yet the collective sites in ``parallel/``
(all_gather / ppermute halo / psum / all_to_all across dist_csr,
dist_spgemm, dist_build, dist_gmg) used to move bytes over the mesh
with zero accounting.  This module is the ledger: every distributed
dispatch records, per collective kind, how many bytes its collectives
move across the interconnect.  The numbers are derived from the same
static shard shapes/dtypes the shard_map builders close over, so they
are exact predictions — XLA executes exactly these collectives with
exactly these operand shapes — not measurements subject to timer
noise, and they cost a handful of integer multiplies per dispatch.

Accounting convention
---------------------
Bytes are the TOTAL crossing the interconnect, summed over all mesh
devices, counting each transferred element once at its receiver:

- ``all_gather`` of an L-element local block over R shards: every
  device receives the other R-1 blocks  ->  R*(R-1)*L*itemsize.
- halo exchange (two ``ppermute`` rounds of an H-element boundary
  slice): every device receives one slice per direction
  ->  2*R*H*itemsize.
- ``psum`` of an L-element value: ring all-reduce (reduce-scatter +
  all-gather) moves 2*(R-1)*L elements  ->  2*(R-1)*L*itemsize.
- ``all_to_all`` of an (R, C)-row send buffer: each device keeps its
  own row and sends R-1  ->  R*(R-1)*C*itemsize.
- one ``ppermute`` rotation round of an L-element block: every device
  receives the block once  ->  R*L*itemsize.

An R == 1 mesh moves nothing (every formula counts remote receivers,
of which there are none), so a 1-device "distributed" run correctly
ledgers zero interconnect bytes — and ``record`` drops zero-byte
entries rather than emitting noise counters.

Counters (always on, per-thread buffered — ``counters.handle`` — so a
hot eager loop of distributed dispatches never contends on the module
lock)::

    comm.<op>.<collective>          collective ops at <op> dispatch
    comm.<op>.<collective>_bytes    predicted interconnect bytes
    comm.total_calls / comm.total_bytes

Span attrs: the distributed spans (``dist_spmv``, ``dist_cg``,
``dist_gmres``, ``dist_spgemm``, ``bench.dist``) carry ``comm_bytes``
and ``comm_calls`` for the whole dispatch.

Dispatch-level contract (same as every obs counter): an op traced
INSIDE a jitted solver loop records once at trace time, not once per
executed iteration; the solver entry points compensate by recording
per-iteration volumes multiplied by the true iteration count (which is
why their counters need the iteration count to be host-visible —
tracing mode or the callback path).
"""

from __future__ import annotations

from typing import Dict, Optional

from . import attrib as _attrib
from . import counters as _counters

Volumes = Dict[str, int]     # collective kind -> predicted bytes


# ---------------------------------------------------------------- model --
def all_gather_bytes(local_elems: int, itemsize: int, shards: int) -> int:
    """Interconnect bytes of one tiled all_gather of an
    ``local_elems``-element per-device block."""
    if shards <= 1:
        return 0
    return shards * (shards - 1) * int(local_elems) * int(itemsize)


def ppermute_bytes(block_elems: int, itemsize: int, shards: int,
                   rounds: int = 1) -> int:
    """Interconnect bytes of ``rounds`` ring-rotation ppermutes of a
    ``block_elems``-element per-device block (every device receives
    the block once per round)."""
    if shards <= 1:
        return 0
    return int(rounds) * shards * int(block_elems) * int(itemsize)


def halo_exchange_bytes(halo_elems: int, itemsize: int,
                        shards: int) -> int:
    """Interconnect bytes of one two-sided halo exchange (the
    ``_extend_x`` pattern): one ``halo_elems`` boundary slice ppermuted
    in each ring direction."""
    if shards <= 1 or halo_elems <= 0:
        return 0
    return 2 * shards * int(halo_elems) * int(itemsize)


def psum_bytes(elems: int, itemsize: int, shards: int) -> int:
    """Interconnect bytes of one psum (ring all-reduce) of an
    ``elems``-element value."""
    if shards <= 1:
        return 0
    return 2 * (shards - 1) * int(elems) * int(itemsize)


def all_to_all_bytes(row_elems: int, itemsize: int, shards: int) -> int:
    """Interconnect bytes of one tiled all_to_all of an (R, row_elems)
    per-device send buffer (own row stays local)."""
    if shards <= 1:
        return 0
    return shards * (shards - 1) * int(row_elems) * int(itemsize)


def reduce_scatter_bytes(input_elems: int, itemsize: int,
                         shards: int) -> int:
    """Interconnect bytes of one tiled ``psum_scatter`` (ring
    reduce-scatter) of an ``input_elems``-element per-device input over
    ``shards`` devices: each device receives (R-1) partial chunks of
    L/R elements, so the group total is (R-1)*L."""
    if shards <= 1:
        return 0
    return (shards - 1) * int(input_elems) * int(itemsize)


def lowered_op_bytes(kind: str, operand_bytes: int, *,
                     group_sizes=(), moved_pairs: int = 0) -> int:
    """Interconnect bytes of ONE lowered collective op, from its IR
    attributes, under the same total-at-receivers convention as the
    model formulas above — the bridge ``tools/verify`` uses to
    cross-check StableHLO operand shapes against this ledger:

    - ``collective_permute``: ``moved_pairs`` non-identity
      source-target pairs each deliver the per-device operand once
      (matches both the halo rounds — R pairs — and the 2-d chunk
      transpose, whose identity pairs move nothing);
    - ``all_gather``: each replica group of size g has every member
      receive the other g-1 operand blocks  ->  sum g*(g-1)*operand;
    - ``all_reduce`` (psum): ring all-reduce per group  ->
      sum 2*(g-1)*operand;
    - ``reduce_scatter``: each member receives g-1 partial chunks of
      operand/g  ->  sum (g-1)*operand;
    - ``all_to_all``: the operand IS the (g, row) send buffer; own row
      stays local  ->  sum (g-1)*operand.

    ``operand_bytes`` is the per-device operand size read from the IR
    tensor type; ``group_sizes`` the replica-group sizes."""
    ob = int(operand_bytes)
    if kind == "collective_permute":
        return int(moved_pairs) * ob
    per_group = {
        "all_gather": lambda g: g * (g - 1) * ob,
        "all_reduce": lambda g: 2 * (g - 1) * ob,
        "reduce_scatter": lambda g: (g - 1) * ob,
        "all_to_all": lambda g: (g - 1) * ob,
    }
    if kind not in per_group:
        raise KeyError(f"unknown lowered collective kind {kind!r}")
    return sum(per_group[kind](int(g)) for g in group_sizes)


def transpose_moved_chunks(grid_rows: int, grid_cols: int) -> int:
    """Number of vector chunks the 2-d-block input fixup ``ppermute``
    actually moves: chunk k's destination under the row-major ->
    column-panel transpose is (k % R) * C + k // R; fixed points
    (including the whole permutation when R == 1 or C == 1) cost
    nothing."""
    n = grid_rows * grid_cols
    return sum(
        1 for k in range(n)
        if (k % grid_rows) * grid_cols + k // grid_rows != k
    )


# --------------------------------------------------------------- ledger --
def merge(*vols: Volumes) -> Volumes:
    """Sum per-collective volumes across several dicts."""
    out: Volumes = {}
    for v in vols:
        for k, b in v.items():
            out[k] = out.get(k, 0) + int(b)
    return out


def scale(vols: Volumes, k: int) -> Volumes:
    """Volumes for ``k`` repetitions (e.g. per-iteration x iters)."""
    return {name: int(b) * int(k) for name, b in vols.items()}


def total(vols: Volumes) -> int:
    return sum(int(b) for b in vols.values())


def record(op: str, vols: Volumes,
           calls: Optional[Dict[str, int]] = None,
           layout: str = "1d-row") -> int:
    """Account one dispatch of ``op``: bump the ``comm.<op>.*``
    counters per collective kind and the process totals.  ``calls``
    optionally gives the collective-op count per kind (default 1 —
    pass the rotation/iteration counts for chained patterns).
    Zero-byte entries are dropped (nothing crossed the interconnect).
    ``layout`` additionally groups the dispatch under the
    ``comm.layout.<layout>.<op>[_bytes]`` aggregates (per-op totals
    over collective kinds — NOT double-counted into
    ``comm.total_*``), so the ledger can be sliced by partition
    strategy (``tools/trace_summary.py --comm``).  Returns the total
    predicted bytes."""
    total_b = 0
    total_c = 0
    for kind, nbytes in vols.items():
        nbytes = int(nbytes)
        if nbytes <= 0:
            continue
        n_calls = int(calls.get(kind, 1)) if calls else 1
        _counters.handle(f"comm.{op}.{kind}").inc(n_calls)
        _counters.handle(f"comm.{op}.{kind}_bytes").inc(nbytes)
        total_b += nbytes
        total_c += n_calls
    if total_c:
        _counters.handle("comm.total_calls").inc(total_c)
        _counters.handle("comm.total_bytes").inc(total_b)
        _counters.handle(f"comm.layout.{layout}.{op}").inc(total_c)
        _counters.handle(f"comm.layout.{layout}.{op}_bytes").inc(total_b)
        # Same gating as comm.total_bytes — per-tenant attributed
        # sums conserve against it exactly (obs/attrib.py).
        _attrib.on_comm(op, total_b, total_c)
    return total_b


# ------------------------------------------------- structure predictors --
def spmv_volumes(*, shards: int, halo: int, precise_C: Optional[int],
                 x_local_elems: int, itemsize: int,
                 cols: int = 1) -> Volumes:
    """Per-call collective volumes of one distributed SpMV/SpMM x
    realization, mirroring the ``dist_spmv`` dispatch exactly:

    - precise image plan (``precise_C`` = plan width C): one tiled
      all_to_all of (R, C[, cols]) send rows;
    - halo mode (``halo`` >= 0): one two-sided halo exchange of
      ``halo``[* cols] elements (zero when halo == 0 — ``_extend_x``
      returns early and no collective exists in the program);
    - otherwise: one tiled all_gather of the ``x_local_elems``-element
      local x block (``x_local_elems`` already includes ``cols`` for
      SpMM operands).

    ``cols`` is the per-device dense-operand column count for the SpMM
    variants (halo slices and all_to_all rows widen by it).
    """
    if precise_C is not None:
        return {"all_to_all": all_to_all_bytes(
            precise_C * cols, itemsize, shards)}
    if halo >= 0:
        b = halo_exchange_bytes(halo * cols, itemsize, shards)
        return {"ppermute": b} if b else {}
    return {"all_gather": all_gather_bytes(x_local_elems, itemsize,
                                           shards)}


def spmv_volumes_2d(*, grid_rows: int, grid_cols: int, spc: int,
                    rps: int, itemsize: int) -> Volumes:
    """Per-call collective volumes of one 2-d-block distributed SpMV,
    mirroring the ``_block_spmv_2d_fn`` dispatch exactly:

    - input fixup: one ``ppermute`` over the flattened grid moving the
      vector chunks (``spc`` elements each) that the row-major ->
      column-panel transpose displaces — absent (zero bytes, no op in
      the program) on degenerate 1-D grids;
    - x panel assembly: one tiled ``all_gather`` along mesh rows in
      each of the ``grid_cols`` column groups (group size
      ``grid_rows``);
    - output reduction: one tiled ``psum_scatter`` along mesh columns
      in each of the ``grid_rows`` row groups, of the
      ``rps``-element partial row block — recorded under the ``psum``
      kind (it IS the reduce half of an all-reduce).
    """
    moved = transpose_moved_chunks(grid_rows, grid_cols)
    vols = {
        "ppermute": moved * int(spc) * int(itemsize),
        "all_gather": grid_cols * all_gather_bytes(spc, itemsize,
                                                  grid_rows),
        "psum": grid_rows * reduce_scatter_bytes(rps, itemsize,
                                                 grid_cols),
    }
    return {k: b for k, b in vols.items() if b > 0}


def spmv_volumes_2d_semiring(*, grid_rows: int, grid_cols: int,
                             spc: int, rps: int, x_itemsize: int,
                             y_itemsize: int,
                             collective: str) -> Volumes:
    """Per-call collective volumes of one 2-d-block SEMIRING dist
    SpMV, mirroring ``_block_semiring_spmv_2d_fn`` exactly: the input
    fixup ``ppermute`` and x panel ``all_gather`` are the plus-times
    program verbatim (``spmv_volumes_2d``), but ``psum_scatter`` only
    exists for sum — the output reduction is the semiring's add
    ALL-reduce (pmin/pmax/por) of the full ``rps``-element partial
    row block along mesh columns, ring cost 2*(g-1)*rps per row group
    (twice the reduce-scatter half), recorded under the semiring
    ``collective`` kind.  x and y itemsizes differ for ``or-and``
    (bool frontier in, bool out) and mixed-precision operands."""
    moved = transpose_moved_chunks(grid_rows, grid_cols)
    vols = {
        "ppermute": moved * int(spc) * int(x_itemsize),
        "all_gather": grid_cols * all_gather_bytes(spc, x_itemsize,
                                                   grid_rows),
        collective: grid_rows * psum_bytes(rps, y_itemsize, grid_cols),
    }
    return {k: b for k, b in vols.items() if b > 0}


def cg_iteration_volumes(spmv_vols: Volumes, itemsize: int,
                         shards: int) -> Volumes:
    """One iteration of the fused CG while_loop body: the SpMV
    realization plus THREE scalar reductions — rho = <r, z>,
    pq = <p, q>, and rnorm2 = <r, r>.  The residual-norm vdot is
    computed unconditionally every iteration (``conv_test_iters``
    only gates the *decision* made from it, not the reduction), so it
    is part of the per-iteration volume, not a periodic extra.  The
    initial-residual SpMV (r0 = b - A x0) is the caller's +1."""
    return merge(spmv_vols, {"psum": 3 * psum_bytes(1, itemsize, shards)})


def reshard_volumes(*, moved_chunks: int, chunk_elems: int,
                    itemsize: int, shards: int) -> Volumes:
    """One cached chunk-permute reshard program
    (``parallel/reshard.py``): a single ``ppermute`` over the flat
    device order moving ``moved_chunks`` per-device chunks of
    ``chunk_elems`` elements each — chunks whose source and
    destination device coincide are identity pairs and move nothing
    (the same fixed-point discount as ``transpose_moved_chunks``).
    Zero volumes (single shard, or an identity placement) mean the
    lowered program contains no collective at all."""
    if shards <= 1 or moved_chunks <= 0:
        return {}
    b = int(moved_chunks) * int(chunk_elems) * int(itemsize)
    return {"ppermute": b} if b else {}


def gmres_cycle_volumes(spmv_vols: Volumes, restart: int, itemsize: int,
                        shards: int) -> Volumes:
    """One sync-free GMRES restart cycle: ``restart + 1`` SpMV
    realizations (the initial residual plus one per Arnoldi step) and
    the cycle's scalar reductions — ``j + 1`` MGS projections at step
    j plus the column norm, plus the entry residual norm:
    ``restart*(restart+1)/2 + restart + 1`` scalar psums."""
    n_psum = restart * (restart + 1) // 2 + restart + 1
    return merge(scale(spmv_vols, restart + 1),
                 {"psum": n_psum * psum_bytes(1, itemsize, shards)})
