# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Causal trace context: request-scoped trace ids (obs v4).

The obs stack records *what* happened (spans, counters, histograms)
but — pre-v4 — not *which request* each record belonged to: a slow
``gateway.batch`` span could not be joined to the admit that queued it
or the dist collectives it dispatched.  This module closes the loop
with the Legion-profiler idea from the source paper (every task carries
its provenance) mapped onto Python: a tiny immutable
:class:`TraceContext` (trace id + request id), minted at
``Gateway.submit`` / ``Executor.submit``, carried *across worker
threads on the request record itself* (contextvars do not propagate
into executor threads), and re-activated around each dispatch body via
:func:`use`.

While a context is active, ``obs.trace`` auto-tags every span/event
closed on that thread with a ``trace_id`` attr, and the Chrome-trace
exporter emits flow events (``ph: s/t/f``) binding the tagged slices
into one connected arc per request — ``gateway.admit`` →
``gateway.batch`` / ``engine.batch`` → the dist collectives — in
Perfetto / chrome://tracing.

Overhead contract: minting is one shared-counter ``next()`` plus one
small object; activation is one contextvar set/reset.  Nothing here
takes the trace lock, and with tracing disabled the auto-tag read
never happens (span recording is already a no-op).

``profiler_scope(op)`` additionally opens a ``jax.profiler``
TraceAnnotation named ``<op>[<trace-id>]`` when a context is active —
so a future on-TPU ``jax.profiler`` capture joins obs spans to XLA
profile rows by trace id (the standing ``vs_baseline`` debt).
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
from typing import Iterator, Optional

__all__ = [
    "TraceContext", "mint", "current", "current_trace_id", "use",
    "profiler_scope", "reset_ids",
]

# Process-unique mint counter.  ``next()`` on an itertools.count is
# atomic under the GIL — the same idiom as the executor's request ids.
_IDS = itertools.count(1)

_var: "contextvars.ContextVar[Optional[TraceContext]]" = \
    contextvars.ContextVar("legate_sparse_tpu_trace_ctx", default=None)


class TraceContext:
    """Immutable causal identity for one request: ``trace_id`` (the
    flow key, process-unique), the originating request ``rid`` (when
    known), and — obs v5 — the admission identity ``tenant`` /
    ``qos`` the attribution ledger charges costs to.  Ride this on
    the request record to cross threads; activate with :func:`use`."""

    __slots__ = ("trace_id", "rid", "tenant", "qos")

    def __init__(self, trace_id: str, rid: Optional[int] = None,
                 tenant: Optional[str] = None,
                 qos: Optional[str] = None):
        object.__setattr__(self, "trace_id", trace_id)
        object.__setattr__(self, "rid", rid)
        object.__setattr__(self, "tenant", tenant)
        object.__setattr__(self, "qos", qos)

    def __setattr__(self, name, value):  # immutability by contract
        raise AttributeError("TraceContext is immutable")

    def __repr__(self) -> str:
        return (f"TraceContext({self.trace_id!r}, rid={self.rid!r}, "
                f"tenant={self.tenant!r}, qos={self.qos!r})")


def mint(rid: Optional[int] = None, kind: str = "req",
         tenant: Optional[str] = None,
         qos: Optional[str] = None) -> TraceContext:
    """New process-unique context.  If a context is already active on
    this thread (e.g. an outer caller minted one), the active context
    is returned instead — causality attaches to the outermost
    request, and nested submits join its arc (including its tenant:
    costs charge to the outermost admission identity)."""
    cur = _var.get()
    if cur is not None:
        return cur
    return TraceContext(f"{kind}-{next(_IDS):06d}", rid, tenant, qos)


def current() -> Optional[TraceContext]:
    """The active context on this thread/task, or None."""
    return _var.get()


def current_trace_id() -> Optional[str]:
    """The active trace id, or None — the auto-tag fast path."""
    ctx = _var.get()
    return None if ctx is None else ctx.trace_id


@contextlib.contextmanager
def use(ctx: Optional[TraceContext]) -> Iterator[Optional[TraceContext]]:
    """Activate ``ctx`` for the body (tolerates None: no-op).  The
    dispatch-side bracket: worker threads wrap each request's dispatch
    body so downstream spans/events auto-tag with the request's id."""
    if ctx is None:
        yield None
        return
    token = _var.set(ctx)
    try:
        yield ctx
    finally:
        _var.reset(token)


def profiler_scope(op: str):
    """A ``jax.profiler.TraceAnnotation`` named ``<op>[<trace-id>]``
    when a context is active, else a null context.  Host-side only —
    annotates profiler timelines, never the traced program."""
    ctx = _var.get()
    if ctx is None:
        return contextlib.nullcontext()
    try:
        from jax.profiler import TraceAnnotation
    except Exception:  # pragma: no cover - profiler API unavailable
        return contextlib.nullcontext()
    return TraceAnnotation(f"{op}[{ctx.trace_id}]")


def reset_ids() -> None:
    """Restart the mint counter (test isolation only: concurrent
    in-flight requests keep their already-minted ids)."""
    global _IDS
    _IDS = itertools.count(1)
