# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Process-wide counters: op invocations, nnz processed, bytes moved,
host<->device transfers, scipy-fallback hits, jit cache misses.

Counters are ALWAYS on (unlike spans): one dict increment costs tens
of nanoseconds, and the whole point is that a later diagnosis can ask
"how many times did the scipy fallback fire in this run?" without
having had tracing enabled in advance.  Naming convention::

    op.<name>            python-level op dispatches (spmv, spgemm, ...)
    trace.<name>         jax re-traces of a jitted kernel (the body of
                         a @jax.jit function runs only on a cache
                         miss, so an increment there counts compiles)
    jit_miss.<name>      structure-cache misses for the lru_cache'd
                         shard_map builders (each miss = one fresh
                         compile of a distributed kernel)
    transfer.<name>      host<->device movements (shard uploads, host
                         syncs that block on device results)
    scipy_fallback.<name>  host-scipy escape-hatch hits
    platform.<name>      accelerator probe / pinning outcomes
    resil.<name>         resilience-layer accounting (retries,
                         backoff ms, breaker transitions, shed
                         requests, injected faults, health verdicts)
                         — EXACT by contract: the fault drills assert
                         equality, not >= (docs/RESILIENCE.md)
    obs.nnz_processed / obs.bytes_moved / obs.flops
                         accumulated from span attributes (only while
                         tracing is enabled — the attrs are computed
                         lazily at span sites)

``inc`` is intentionally tolerant of float increments (bytes/flops
totals).  Thread safety: increments take the module lock; reads
snapshot under it.

Hot-loop sites (per-iteration solver counters, the per-call comm
ledger) can skip the lock entirely with a **per-thread buffered
handle** (``handle(name)``): ``Handle.inc`` is one attribute add on an
object owned by the calling thread — no lock, no dict.  Buffered
values are merged into every ``get``/``snapshot`` (the flush-on-read
contract), so the public API and its semantics are unchanged; only the
write path got cheaper.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Union

Number = Union[int, float]

_lock = threading.Lock()
_counters: Dict[str, Number] = {}


class Handle:
    """Per-thread buffered counter: the lock-free hot-loop fast path.

    ``inc`` adds to a plain attribute that ONLY the owning thread
    writes (CPython attribute reads are GIL-atomic, so readers in
    other threads see a consistent — at worst slightly stale — value).
    Nothing is ever popped from the handle: ``_total`` grows
    monotonically and readers report ``_total - _base``, where
    ``_base`` is advanced (under the module lock) by ``reset()``.
    That makes reads tear-free and reset race-safe: an increment that
    lands concurrently with a reset simply survives as post-reset
    count — no increment can be lost or double-counted.
    """

    __slots__ = ("name", "_total", "_base", "_thread")

    def __init__(self, name: str):
        self.name = name
        self._total: Number = 0
        self._base: Number = 0
        self._thread = threading.current_thread()

    def inc(self, value: Number = 1) -> None:
        """Owner-thread-only add: no lock taken."""
        self._total += value

    def pending(self) -> Number:
        """Buffered amount not yet consumed by a ``reset()``."""
        return self._total - self._base


_tls = threading.local()
_handles: List[Handle] = []      # registry, appended under _lock

# Registry size that triggers a dead-thread sweep on the next handle
# registration — bounds a thread-pool-per-request service that touches
# fresh threads forever (each dead thread's handles fold their pending
# amounts into the base counters and drop out of the scan path).
_COMPACT_THRESHOLD = 512


def _compact_locked() -> None:
    """Fold handles owned by dead threads into ``_counters`` and drop
    them (call under _lock).  Safe: a dead thread can no longer
    increment, so its pending amount is final."""
    global _handles
    live: List[Handle] = []
    for h in _handles:
        if h._thread.is_alive():
            live.append(h)
            continue
        d = h._total - h._base
        if d:
            _counters[h.name] = _counters.get(h.name, 0) + d
    _handles = live


def handle(name: str) -> Handle:
    """The calling thread's buffered handle for counter ``name``
    (created and registered on first use).  Keep the returned object
    and call ``h.inc()`` in hot loops; ``snapshot()``/``get()`` fold
    the buffered values in automatically."""
    reg = getattr(_tls, "handles", None)
    if reg is None:
        reg = _tls.handles = {}
    h = reg.get(name)
    if h is None:
        h = Handle(name)
        reg[name] = h
        with _lock:
            if len(_handles) >= _COMPACT_THRESHOLD:
                _compact_locked()
            _handles.append(h)
    return h


def _pending_locked() -> Dict[str, Number]:
    """Sum of every live handle's un-reset buffer (call under _lock)."""
    out: Dict[str, Number] = {}
    for h in _handles:
        d = h._total - h._base
        if d:
            out[h.name] = out.get(h.name, 0) + d
    return out


def inc(name: str, value: Number = 1) -> None:
    """Add ``value`` to counter ``name`` (creating it at 0)."""
    with _lock:
        _counters[name] = _counters.get(name, 0) + value


def get(name: str, default: Number = 0) -> Number:
    """Current value of one counter (buffered handles included)."""
    with _lock:
        base = _counters.get(name)
        buf = 0
        for h in _handles:
            if h.name == name:
                buf += h._total - h._base
        if base is None and not buf:
            return default
        return (base or 0) + buf


def snapshot(prefix: Optional[str] = None) -> Dict[str, Number]:
    """Copy of all counters (buffered handles folded in), optionally
    filtered by name prefix."""
    with _lock:
        out = dict(_counters)
        for name, d in _pending_locked().items():
            out[name] = out.get(name, 0) + d
        if prefix is None:
            return out
        return {k: v for k, v in out.items() if k.startswith(prefix)}


def reset(prefix: Optional[str] = None) -> None:
    """Zero all counters, or only those under ``prefix`` (buffered
    handles are re-based, not mutated — see ``Handle``)."""
    with _lock:
        if prefix is None:
            _counters.clear()
        else:
            for k in [k for k in _counters if k.startswith(prefix)]:
                del _counters[k]
        for h in _handles:
            if prefix is None or h.name.startswith(prefix):
                h._base = h._total
