# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Process-wide counters: op invocations, nnz processed, bytes moved,
host<->device transfers, scipy-fallback hits, jit cache misses.

Counters are ALWAYS on (unlike spans): one dict increment costs tens
of nanoseconds, and the whole point is that a later diagnosis can ask
"how many times did the scipy fallback fire in this run?" without
having had tracing enabled in advance.  Naming convention::

    op.<name>            python-level op dispatches (spmv, spgemm, ...)
    trace.<name>         jax re-traces of a jitted kernel (the body of
                         a @jax.jit function runs only on a cache
                         miss, so an increment there counts compiles)
    jit_miss.<name>      structure-cache misses for the lru_cache'd
                         shard_map builders (each miss = one fresh
                         compile of a distributed kernel)
    transfer.<name>      host<->device movements (shard uploads, host
                         syncs that block on device results)
    scipy_fallback.<name>  host-scipy escape-hatch hits
    platform.<name>      accelerator probe / pinning outcomes
    obs.nnz_processed / obs.bytes_moved / obs.flops
                         accumulated from span attributes (only while
                         tracing is enabled — the attrs are computed
                         lazily at span sites)

``inc`` is intentionally tolerant of float increments (bytes/flops
totals).  Thread safety: increments take the module lock; reads
snapshot under it.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Union

Number = Union[int, float]

_lock = threading.Lock()
_counters: Dict[str, Number] = {}


def inc(name: str, value: Number = 1) -> None:
    """Add ``value`` to counter ``name`` (creating it at 0)."""
    with _lock:
        _counters[name] = _counters.get(name, 0) + value


def get(name: str, default: Number = 0) -> Number:
    """Current value of one counter."""
    with _lock:
        return _counters.get(name, default)


def snapshot(prefix: Optional[str] = None) -> Dict[str, Number]:
    """Copy of all counters, optionally filtered by name prefix."""
    with _lock:
        if prefix is None:
            return dict(_counters)
        return {k: v for k, v in _counters.items() if k.startswith(prefix)}


def reset(prefix: Optional[str] = None) -> None:
    """Zero all counters, or only those under ``prefix``."""
    with _lock:
        if prefix is None:
            _counters.clear()
        else:
            for k in [k for k in _counters if k.startswith(prefix)]:
                del _counters[k]
