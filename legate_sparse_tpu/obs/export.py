# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""OpenMetrics / Prometheus text export of counters and histograms.

The serving gateway (ROADMAP item 1) needs scrapeable metrics; this
module makes every always-on ``counters.*`` value and every
``latency.*`` histogram renderable as OpenMetrics text with zero
instrumentation changes — the exposition layer is a pure read of the
snapshots the package already maintains.

Two metric families, name-labelled (one family per kind keeps the
family set closed while the counter/histogram name space stays open):

- ``legate_sparse_tpu_counter_total{name="op.spmv"} 42`` — every
  counter, rendered as an OpenMetrics counter sample.
- ``legate_sparse_tpu_latency{name="lat.spmv.n4096", ...}`` — every
  histogram as a classic cumulative-bucket histogram (``_bucket`` with
  ascending ``le`` boundaries ending in ``+Inf``, plus ``_sum`` and
  ``_count``).  Bucket boundaries are the fixed log2 grid of
  :mod:`.latency`; only occupied buckets are emitted (cumulative
  counts stay correct — an absent boundary merges into the next one).

API::

    from legate_sparse_tpu import obs
    text = obs.export.snapshot_openmetrics()     # the exposition text
    obs.export.write_openmetrics("metrics.prom") # snapshot-to-file

``LEGATE_SPARSE_TPU_OBS_PROM=<path>`` arms an atexit snapshot-to-file
(best effort — a failed write must never mask the process's real
exit), so any run can leave a scrapeable artifact behind without code
changes; long-lived servers call ``write_openmetrics`` on their scrape
path instead.  Containerized runs are *killed*, not exited: the same
env additionally installs a chaining SIGTERM handler (obs v4) that
flushes the snapshot, restores the prior disposition and re-raises, so
the process still dies with the conventional 143 while the artifact
survives.

Obs v4 also hooks SLO evaluation onto the scrape path — every
``snapshot_openmetrics()`` runs ``obs.slo.evaluate()`` first (a single
flag read while ``LEGATE_SPARSE_TPU_OBS_SLO`` is unset), so armed
processes publish fresh ``slo.*`` counters with every scrape — and
provides :func:`parse_openmetrics`, the inverse used by the round-trip
format test and ``tools/doctor.py``.
"""

from __future__ import annotations

import atexit
import os
import re
import signal
from typing import Dict, Optional, Tuple

from . import counters as _counters
from . import latency as _latency

ENV_PROM_FILE = "LEGATE_SPARSE_TPU_OBS_PROM"

_PREFIX = "legate_sparse_tpu"


def _escape_label(value: str) -> str:
    """OpenMetrics label-value escaping: backslash, quote, newline."""
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_value(v) -> str:
    """Sample value: integers render bare (counter totals), floats in
    repr precision (no scientific-notation surprises for small ms)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int) or (isinstance(v, float) and v.is_integer()):
        return str(int(v))
    return repr(float(v))


def render_openmetrics(
        counters_snap: Optional[Dict] = None,
        histograms: Optional[Dict[str, "_latency.Histogram"]] = None,
) -> str:
    """Render the given (or live) snapshots as OpenMetrics text,
    ``# EOF`` terminated.  Deterministic: families and samples are
    name-sorted."""
    if counters_snap is None:
        counters_snap = _counters.snapshot()
    if histograms is None:
        histograms = _latency.snapshot()
    lines = []

    lines.append(f"# TYPE {_PREFIX}_counter counter")
    lines.append(f"# HELP {_PREFIX}_counter Always-on process counters"
                 " (docs/OBSERVABILITY.md naming contract).")
    for name in sorted(counters_snap):
        lines.append(
            f'{_PREFIX}_counter_total{{name="{_escape_label(name)}"}} '
            f"{_fmt_value(counters_snap[name])}")

    lines.append(f"# TYPE {_PREFIX}_latency histogram")
    lines.append(f"# HELP {_PREFIX}_latency Streaming log2-bucket"
                 " histograms (obs/latency.py; ms unless the name says"
                 " otherwise).")
    for name in sorted(histograms):
        hist = histograms[name]
        label = _escape_label(name)
        acc = 0
        for slot, count in hist.nonzero_buckets():
            acc += count
            le = _latency.slot_upper(slot)
            lines.append(
                f'{_PREFIX}_latency_bucket{{name="{label}",'
                f'le="{_fmt_value(le)}"}} {acc}')
        lines.append(
            f'{_PREFIX}_latency_bucket{{name="{label}",le="+Inf"}} '
            f"{acc}")
        lines.append(f'{_PREFIX}_latency_sum{{name="{label}"}} '
                     f"{_fmt_value(hist.sum)}")
        lines.append(f'{_PREFIX}_latency_count{{name="{label}"}} '
                     f"{acc}")

    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def snapshot_openmetrics() -> str:
    """Live snapshot of all counters + histograms as OpenMetrics text
    (the scrape-path API).  Runs SLO evaluation first (obs v4) so the
    rendered text carries this scrape's ``slo.*`` verdict counters;
    one inert flag read while ``LEGATE_SPARSE_TPU_OBS_SLO`` is unset."""
    from . import slo as _slo

    _slo.evaluate()
    return render_openmetrics()


# Parsed sample lines of the two families rendered above.
_COUNTER_LINE_RE = re.compile(
    rf'^{_PREFIX}_counter_total\{{name="((?:[^"\\]|\\.)*)"\}} (\S+)$')
_LATENCY_LINE_RE = re.compile(
    rf'^{_PREFIX}_latency_(bucket|sum|count)'
    rf'\{{name="((?:[^"\\]|\\.)*)"(?:,le="([^"]*)")?\}} (\S+)$')


_UNESCAPE_RE = re.compile(r"\\(.)")
_UNESCAPE_MAP = {"n": "\n", '"': '"', "\\": "\\"}


def _unescape_label(value: str) -> str:
    # One left-to-right pass — sequential str.replace would corrupt
    # ``\\n`` (escaped backslash + literal n) into a newline.
    return _UNESCAPE_RE.sub(
        lambda m: _UNESCAPE_MAP.get(m.group(1), m.group(1)), value)


def parse_openmetrics(text: str) -> Tuple[Dict, Dict]:
    """Parse exposition text produced by :func:`render_openmetrics`
    back into ``(counters, histograms)`` — counters as ``{name:
    value}``, histograms as ``{name: {"buckets": [(le, cumulative),
    ...], "sum": float, "count": int}}``.  The round-trip format test
    and ``tools/doctor.py`` build on this; unparseable non-comment
    lines raise (the format is pinned, not advisory)."""
    counts: Dict[str, float] = {}
    hists: Dict[str, Dict] = {}
    saw_eof = False
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            saw_eof = line.strip() == "# EOF"
            continue
        m = _COUNTER_LINE_RE.match(line)
        if m:
            name = _unescape_label(m.group(1))
            val = float(m.group(2))
            counts[name] = int(val) if val.is_integer() else val
            continue
        m = _LATENCY_LINE_RE.match(line)
        if m:
            kind, raw_name, le, raw = (m.group(1), m.group(2),
                                       m.group(3), m.group(4))
            name = _unescape_label(raw_name)
            h = hists.setdefault(
                name, {"buckets": [], "sum": 0.0, "count": 0})
            if kind == "bucket":
                bound = float("inf") if le == "+Inf" else float(le)
                h["buckets"].append((bound, int(raw)))
            elif kind == "sum":
                h["sum"] = float(raw)
            else:
                h["count"] = int(raw)
            continue
        raise ValueError(f"unparseable OpenMetrics line: {line!r}")
    if not saw_eof:
        raise ValueError("missing # EOF terminator")
    return counts, hists


def write_openmetrics(path: Optional[str] = None) -> str:
    """Write the live snapshot to ``path`` (default: the
    ``LEGATE_SPARSE_TPU_OBS_PROM`` env value).  Returns the path."""
    if path is None:
        path = os.environ.get(ENV_PROM_FILE)
    if not path:
        raise ValueError(
            f"write_openmetrics: no path given and {ENV_PROM_FILE} "
            f"is unset")
    text = snapshot_openmetrics()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)       # atomic vs a concurrent scraper read
    return path


def _atexit_snapshot() -> None:  # pragma: no cover - exercised via env
    try:
        write_openmetrics()
    except Exception:
        # Best effort by contract: a failed metrics write must never
        # mask the process's real exit status.
        pass


def _install_sigterm_flush() -> bool:  # pragma: no cover - subprocess
    """Chain a SIGTERM handler that flushes the snapshot, then defers
    to the prior disposition (default: restore it and re-kill, so the
    process still exits 143 and supervisors see a normal TERM death).
    Containerized runs are killed, not exited — atexit alone leaves no
    artifact there."""
    try:
        prev = signal.getsignal(signal.SIGTERM)
    except (ValueError, OSError):
        return False            # no signal support here

    def _on_sigterm(signum, frame):
        _atexit_snapshot()
        if callable(prev) and prev not in (signal.SIG_IGN,
                                           signal.SIG_DFL):
            prev(signum, frame)
            return
        signal.signal(signal.SIGTERM,
                      prev if prev is not None else signal.SIG_DFL)
        os.kill(os.getpid(), signal.SIGTERM)

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):
        return False            # e.g. imported off the main thread
    return True


if os.environ.get(ENV_PROM_FILE):
    atexit.register(_atexit_snapshot)
    _install_sigterm_flush()
