# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""OpenMetrics / Prometheus text export of counters and histograms.

The serving gateway (ROADMAP item 1) needs scrapeable metrics; this
module makes every always-on ``counters.*`` value and every
``latency.*`` histogram renderable as OpenMetrics text with zero
instrumentation changes — the exposition layer is a pure read of the
snapshots the package already maintains.

Two metric families, name-labelled (one family per kind keeps the
family set closed while the counter/histogram name space stays open):

- ``legate_sparse_tpu_counter_total{name="op.spmv"} 42`` — every
  counter, rendered as an OpenMetrics counter sample.
- ``legate_sparse_tpu_latency{name="lat.spmv.n4096", ...}`` — every
  histogram as a classic cumulative-bucket histogram (``_bucket`` with
  ascending ``le`` boundaries ending in ``+Inf``, plus ``_sum`` and
  ``_count``).  Bucket boundaries are the fixed log2 grid of
  :mod:`.latency`; only occupied buckets are emitted (cumulative
  counts stay correct — an absent boundary merges into the next one).

API::

    from legate_sparse_tpu import obs
    text = obs.export.snapshot_openmetrics()     # the exposition text
    obs.export.write_openmetrics("metrics.prom") # snapshot-to-file

``LEGATE_SPARSE_TPU_OBS_PROM=<path>`` arms an atexit snapshot-to-file
(best effort — a failed write must never mask the process's real
exit), so any run can leave a scrapeable artifact behind without code
changes; long-lived servers call ``write_openmetrics`` on their scrape
path instead.
"""

from __future__ import annotations

import atexit
import os
from typing import Dict, Optional

from . import counters as _counters
from . import latency as _latency

ENV_PROM_FILE = "LEGATE_SPARSE_TPU_OBS_PROM"

_PREFIX = "legate_sparse_tpu"


def _escape_label(value: str) -> str:
    """OpenMetrics label-value escaping: backslash, quote, newline."""
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_value(v) -> str:
    """Sample value: integers render bare (counter totals), floats in
    repr precision (no scientific-notation surprises for small ms)."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int) or (isinstance(v, float) and v.is_integer()):
        return str(int(v))
    return repr(float(v))


def render_openmetrics(
        counters_snap: Optional[Dict] = None,
        histograms: Optional[Dict[str, "_latency.Histogram"]] = None,
) -> str:
    """Render the given (or live) snapshots as OpenMetrics text,
    ``# EOF`` terminated.  Deterministic: families and samples are
    name-sorted."""
    if counters_snap is None:
        counters_snap = _counters.snapshot()
    if histograms is None:
        histograms = _latency.snapshot()
    lines = []

    lines.append(f"# TYPE {_PREFIX}_counter counter")
    lines.append(f"# HELP {_PREFIX}_counter Always-on process counters"
                 " (docs/OBSERVABILITY.md naming contract).")
    for name in sorted(counters_snap):
        lines.append(
            f'{_PREFIX}_counter_total{{name="{_escape_label(name)}"}} '
            f"{_fmt_value(counters_snap[name])}")

    lines.append(f"# TYPE {_PREFIX}_latency histogram")
    lines.append(f"# HELP {_PREFIX}_latency Streaming log2-bucket"
                 " histograms (obs/latency.py; ms unless the name says"
                 " otherwise).")
    for name in sorted(histograms):
        hist = histograms[name]
        label = _escape_label(name)
        acc = 0
        for slot, count in hist.nonzero_buckets():
            acc += count
            le = _latency.slot_upper(slot)
            lines.append(
                f'{_PREFIX}_latency_bucket{{name="{label}",'
                f'le="{_fmt_value(le)}"}} {acc}')
        lines.append(
            f'{_PREFIX}_latency_bucket{{name="{label}",le="+Inf"}} '
            f"{acc}")
        lines.append(f'{_PREFIX}_latency_sum{{name="{label}"}} '
                     f"{_fmt_value(hist.sum)}")
        lines.append(f'{_PREFIX}_latency_count{{name="{label}"}} '
                     f"{acc}")

    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def snapshot_openmetrics() -> str:
    """Live snapshot of all counters + histograms as OpenMetrics text
    (the scrape-path API)."""
    return render_openmetrics()


def write_openmetrics(path: Optional[str] = None) -> str:
    """Write the live snapshot to ``path`` (default: the
    ``LEGATE_SPARSE_TPU_OBS_PROM`` env value).  Returns the path."""
    if path is None:
        path = os.environ.get(ENV_PROM_FILE)
    if not path:
        raise ValueError(
            f"write_openmetrics: no path given and {ENV_PROM_FILE} "
            f"is unset")
    text = snapshot_openmetrics()
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)       # atomic vs a concurrent scraper read
    return path


def _atexit_snapshot() -> None:  # pragma: no cover - exercised via env
    try:
        write_openmetrics()
    except Exception:
        # Best effort by contract: a failed metrics write must never
        # mask the process's real exit status.
        pass


if os.environ.get(ENV_PROM_FILE):
    atexit.register(_atexit_snapshot)
