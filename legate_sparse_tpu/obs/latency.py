# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Streaming latency histograms: mergeable fixed-log2-bucket
distributions with bounded relative quantile error.

The serving arc (ROADMAP item 1) is judged on tail latency — p50/p99
vs offered load — and the autotuner (item 2) consults persisted per-op
timing *distributions*, not means.  Scalar counters can't answer
either: a counter sum hides the tail, and spans cost memory per call.
This module is the fixed-cost answer: every observation lands in one
of ~500 logarithmic buckets, so a histogram is a few KB no matter how
many requests it absorbs, two histograms merge by adding bucket
counts, and any quantile is reconstructible to a *documented* relative
error.

Bucket layout
-------------
``SUB`` sub-buckets per power of two: a positive value ``v`` lands in
bucket ``floor(log2(v) * SUB)`` (clamped to the supported range;
values <= 0 land in a dedicated zero bucket that reports 0.0).
Quantiles report the geometric midpoint of their bucket, so the
relative error of any quantile estimate is bounded by

    REL_ERR = 2 ** (1 / (2 * SUB)) - 1        (~4.4% at SUB = 8)

which ``tests/test_obs_concurrency.py`` pins against exact sorted
quantiles on fuzzed samples.  The clamp range covers ~7.5e-9 .. 1.4e11
— nanoseconds to days in ms units — clamped extremes saturate into the
edge buckets (count preserved, error bound void there by design).

Hot-path contract
-----------------
Same as ``counters``: histograms are ALWAYS on, and the write path is
the per-thread buffered ``HistHandle`` — ``observe`` is one ``log2``,
one list-element add, and one float add on objects owned by the
calling thread.  No lock, no allocation, no device sync, no effect on
any ``trace.*`` / ``transfer.*`` counter (the inertness test pins
this).  Snapshots merge every live handle under the module lock with
the same monotone-total / rebased-base scheme as ``counters.Handle``:
tear-free reads, reset-race-safe (a concurrent observation survives as
post-reset count, never lost or doubled).

Naming convention (docs/OBSERVABILITY.md)::

    lat.<op>.<shape-bucket>      per-op dispatch latency in ms, keyed
                                 by the pow2 shape bucket ("n4096")
    lat.engine.request.<bucket>  end-to-end request latency (submit ->
                                 result; resolved, inline- and
                                 fallback-served requests) through
                                 the executor
    lat.engine.wait.<outcome>    queue wait per request outcome
                                 (resolved/shed/inline/fallback/
                                 error/rejected) — the shed-vs-served
                                 comparison the load shedder is
                                 judged by
    lat.engine.batch_occupancy   requests per dispatched batch
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional

# Sub-buckets per power of two.  8 => quantile relative error <= 4.4%.
SUB = 8
# Documented quantile error bound: estimates report the geometric
# midpoint of a bucket whose bounds are a factor 2**(1/SUB) apart.
REL_ERR = 2 ** (1.0 / (2 * SUB)) - 1

# Supported exponent range (powers of two).  Values in ms: 2**-27 ms
# (~7.5e-9) up to 2**37 ms (~4.3 years).  Slot 0 is the zero bucket.
_MIN_EXP = -27
_MAX_EXP = 37
_LO = _MIN_EXP * SUB
_NSLOTS = (_MAX_EXP - _MIN_EXP) * SUB + 1   # +1 for the zero bucket


def _slot(value: float) -> int:
    """Bucket slot for ``value`` (slot 0 = zero bucket)."""
    if value <= 0.0 or value != value:      # <= 0 and NaN: zero bucket
        return 0
    idx = math.floor(math.log2(value) * SUB) - _LO
    if idx < 0:
        idx = 0
    elif idx >= _NSLOTS - 1:
        idx = _NSLOTS - 2
    return idx + 1


def slot_upper(slot: int) -> float:
    """Upper bound of ``slot`` (0.0 for the zero bucket) — the
    OpenMetrics ``le`` boundary."""
    if slot <= 0:
        return 0.0
    return 2.0 ** ((slot + _LO) / SUB)


def _slot_mid(slot: int) -> float:
    """Representative value of ``slot``: geometric midpoint (the
    REL_ERR-bounded quantile estimate)."""
    if slot <= 0:
        return 0.0
    return 2.0 ** ((slot - 0.5 + _LO) / SUB)


def shape_bucket(n: int) -> str:
    """Stable pow2 shape-bucket label ("n4096") for histogram names.

    Deliberately independent of the engine's (settings-tunable) bucket
    ladder: histogram names must stay comparable across runs with
    different engine configs."""
    return f"n{1 << max(int(n) - 1, 0).bit_length()}"


class Histogram:
    """A merged, immutable-by-convention histogram snapshot."""

    __slots__ = ("name", "counts", "sum")

    def __init__(self, name: str, counts: List[int], total: float):
        self.name = name
        self.counts = counts
        self.sum = total

    @property
    def count(self) -> int:
        return sum(self.counts)

    @property
    def mean(self) -> Optional[float]:
        n = self.count
        return (self.sum / n) if n else None

    def quantile(self, q: float) -> Optional[float]:
        """Nearest-rank quantile estimate, within REL_ERR of the exact
        sorted value (None on an empty histogram)."""
        n = self.count
        if n == 0:
            return None
        rank = max(1, min(n, math.ceil(float(q) * n)))
        acc = 0
        for slot, c in enumerate(self.counts):
            acc += c
            if acc >= rank:
                return _slot_mid(slot)
        return _slot_mid(_NSLOTS - 1)   # pragma: no cover - unreachable

    def max(self) -> Optional[float]:
        """Upper bound of the highest occupied bucket (within one
        bucket width of the true max)."""
        for slot in range(_NSLOTS - 1, -1, -1):
            if self.counts[slot]:
                return slot_upper(slot)
        return None

    def merge(self, other: "Histogram") -> "Histogram":
        """Cross-thread / cross-process combination: bucket counts and
        sums add (the whole point of fixed buckets)."""
        counts = [a + b for a, b in zip(self.counts, other.counts)]
        return Histogram(self.name, counts, self.sum + other.sum)

    def nonzero_buckets(self) -> List[tuple]:
        """[(slot, count), ...] for occupied slots — the sparse
        serialized form."""
        return [(s, c) for s, c in enumerate(self.counts) if c]

    def to_dict(self) -> Dict:
        """Sparse serializable form (trace artifacts, persisted
        ledgers); ``from_dict`` round-trips it."""
        return {
            "sub": SUB,
            "count": self.count,
            "sum": self.sum,
            "buckets": [[s, c] for s, c in self.nonzero_buckets()],
        }

    @classmethod
    def from_dict(cls, name: str, d: Dict) -> "Histogram":
        sub = int(d.get("sub", SUB))
        if sub != SUB:
            # Slot indices are meaningless on a different grid:
            # reinterpreting them would silently skew every quantile
            # by up to 2**(k/sub - k/SUB).
            raise ValueError(
                f"histogram {name!r} was recorded with SUB={sub}, "
                f"this build uses SUB={SUB}; incompatible bucket grid")
        counts = [0] * _NSLOTS
        for s, c in d.get("buckets", []):
            if 0 <= int(s) < _NSLOTS:
                counts[int(s)] += int(c)
        return cls(name, counts, float(d.get("sum", 0.0)))


class HistHandle:
    """Per-thread buffered histogram: the lock-free write path.

    Mirrors ``counters.Handle``: per-slot counts and the running sum
    grow monotonically and ONLY the owning thread writes them;
    ``reset`` (under the module lock) advances the ``_base`` copies
    instead of mutating, so reads are tear-free and a concurrent
    ``observe`` can never be lost or double-counted."""

    __slots__ = ("name", "_counts", "_base", "_sum", "_sum_base",
                 "_thread")

    def __init__(self, name: str):
        self.name = name
        self._counts = [0] * _NSLOTS
        self._base = [0] * _NSLOTS
        self._sum = 0.0
        self._sum_base = 0.0
        self._thread = threading.current_thread()

    def observe(self, value: float) -> None:
        """Owner-thread-only record: no lock taken.  Negative / NaN
        values land in the zero bucket and contribute 0 to the sum
        (the sum must stay monotone for the rebase contract)."""
        v = float(value)
        self._counts[_slot(v)] += 1
        if v > 0.0:
            self._sum += v

    def _pending(self) -> tuple:
        """(counts-delta list, sum-delta) not yet consumed by reset."""
        counts = [t - b for t, b in zip(self._counts, self._base)]
        return counts, self._sum - self._sum_base


_lock = threading.Lock()
_tls = threading.local()
_handles: List[HistHandle] = []          # registry, appended under _lock
# Dead-thread fold target: {name: (counts, sum)} merged under _lock.
_folded: Dict[str, tuple] = {}

_COMPACT_THRESHOLD = 512


def _compact_locked() -> None:
    """Fold handles owned by dead threads into ``_folded`` and drop
    them (call under _lock) — same bound as ``counters``: a
    thread-pool-per-request service must not leak one handle per
    (thread, name) forever."""
    global _handles
    live: List[HistHandle] = []
    for h in _handles:
        if h._thread.is_alive():
            live.append(h)
            continue
        counts, total = h._pending()
        if any(counts) or total:
            base_c, base_s = _folded.get(h.name,
                                         ([0] * _NSLOTS, 0.0))
            _folded[h.name] = (
                [a + b for a, b in zip(base_c, counts)],
                base_s + total)
    _handles = live


def handle(name: str) -> HistHandle:
    """The calling thread's buffered handle for histogram ``name``
    (created and registered on first use).  Keep the returned object
    and call ``h.observe(ms)`` in hot loops."""
    reg = getattr(_tls, "handles", None)
    if reg is None:
        reg = _tls.handles = {}
    h = reg.get(name)
    if h is None:
        h = HistHandle(name)
        reg[name] = h
        with _lock:
            if len(_handles) >= _COMPACT_THRESHOLD:
                _compact_locked()
            _handles.append(h)
    return h


def observe(name: str, value: float) -> None:
    """Record one observation into histogram ``name`` (convenience
    over ``handle(name).observe(value)``)."""
    handle(name).observe(value)


class timer:
    """Context manager recording the wall time of its body (in ms)
    into histogram ``name`` — the dispatch-site instrumentation
    (``with _lat.timer("lat.spmv." + _lat.shape_bucket(n)): ...``).
    Always on, like the histograms themselves: one clock pair + one
    buffered observe, no lock, no device sync."""

    __slots__ = ("name", "_t0")

    def __init__(self, name: str):
        self.name = name

    def __enter__(self) -> "timer":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        observe(self.name,
                (time.perf_counter_ns() - self._t0) / 1e6)


def _merged_locked(name: str) -> Optional[Histogram]:
    counts = [0] * _NSLOTS
    total = 0.0
    seen = False
    folded = _folded.get(name)
    if folded is not None:
        counts = list(folded[0])
        total = folded[1]
        seen = True
    for h in _handles:
        if h.name != name:
            continue
        c, s = h._pending()
        if any(c) or s:
            counts = [a + b for a, b in zip(counts, c)]
            total += s
        seen = True
    if not seen:
        return None
    return Histogram(name, counts, total)


def get(name: str) -> Optional[Histogram]:
    """Merged snapshot of one histogram (None if never observed)."""
    with _lock:
        return _merged_locked(name)


def snapshot(prefix: Optional[str] = None) -> Dict[str, Histogram]:
    """Merged snapshot of all histograms, optionally filtered by name
    prefix.  Tear-free per histogram (each merge reads monotone
    per-thread totals under the module lock).  One O(handles) pass —
    NOT one registry scan per name: this runs on every OpenMetrics
    scrape and trace export, possibly against a near-compaction-bound
    registry, while holding the lock new registrations need."""
    with _lock:
        out: Dict[str, Histogram] = {}
        for name, (counts, total) in _folded.items():
            if prefix is not None and not name.startswith(prefix):
                continue
            out[name] = Histogram(name, list(counts), total)
        for h in _handles:
            name = h.name
            if prefix is not None and not name.startswith(prefix):
                continue
            c, s = h._pending()
            hist = out.get(name)
            if hist is None:
                out[name] = Histogram(name, c, s)
            else:
                hist.counts = [a + b for a, b in zip(hist.counts, c)]
                hist.sum += s
        return dict(sorted(out.items()))


def reset(prefix: Optional[str] = None) -> None:
    """Zero all histograms (or those under ``prefix``): live handles
    are re-based, not mutated; folded dead-thread state is dropped."""
    with _lock:
        for name in [n for n in _folded
                     if prefix is None or n.startswith(prefix)]:
            del _folded[name]
        for h in _handles:
            if prefix is None or h.name.startswith(prefix):
                h._base[:] = h._counts
                h._sum_base = h._sum
