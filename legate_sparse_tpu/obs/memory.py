# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Phase memory watermarks: ``mem.*`` events around bench phases and
solver entry points.

SpGEMM output-nnz blowup, ELL padding expansion, and the halo-extended
x windows all fail as OOMs today — a crash with no number attached.
This module makes the watermark a recorded quantity instead: wrap a
phase in ``with memory.watermark("dist_spgemm")`` and the trace gains
a ``mem.dist_spgemm`` instant event carrying RSS before/after, the
process peak RSS, device memory stats where the backend exposes them
(real accelerators do; the CPU test backend returns nothing), and —
opt-in via ``LEGATE_SPARSE_TPU_OBS_TRACEMALLOC=1`` — the Python-heap
peak across the phase from ``tracemalloc``.

Watermarks follow the span overhead contract: when tracing is disabled
(``obs.enabled()`` false) ``watermark`` is a no-op — one module-global
check, no /proc read, no device-stats RPC — so the instrumentation can
live permanently at the solver entry points.

Sampling sources, best-effort in this order (each guarded — a missing
source drops its keys, never the event):

- ``/proc/self/status`` ``VmRSS``/``VmHWM`` (Linux; exact, cheap);
  fallback ``resource.getrusage`` ``ru_maxrss`` (peak only).
- ``jax.local_devices()[i].memory_stats()``: ``bytes_in_use`` /
  ``peak_bytes_in_use`` summed over addressable devices.
- ``tracemalloc.get_traced_memory()`` when tracing is active (the env
  knob starts it at the first watermark).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import threading

from . import attrib as _attrib
from . import trace as _trace

_TRACEMALLOC_ENV = "LEGATE_SPARSE_TPU_OBS_TRACEMALLOC"
_tls = threading.local()        # per-thread watermark nesting depth


def _rss_mb() -> Dict[str, float]:
    """Current and peak RSS in MiB (Linux /proc, resource fallback)."""
    out: Dict[str, float] = {}
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    out["rss_mb"] = round(int(line.split()[1]) / 1024, 2)
                elif line.startswith("VmHWM:"):
                    out["peak_rss_mb"] = round(
                        int(line.split()[1]) / 1024, 2)
    except OSError:
        pass
    if "peak_rss_mb" not in out:
        try:
            import resource
            import sys

            peak = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            # ru_maxrss is kilobytes on Linux but BYTES on macOS —
            # and macOS is exactly where the /proc path above missed.
            div = 2**20 if sys.platform == "darwin" else 1024
            out["peak_rss_mb"] = round(peak / div, 2)
        except Exception:
            pass
    return out


def _device_mb() -> Dict[str, float]:
    """bytes_in_use / peak_bytes_in_use summed over addressable
    devices, in MiB.  The CPU test backend exposes no stats — then no
    keys are emitted (absence means "backend silent", not 0)."""
    out: Dict[str, float] = {}
    try:
        import jax

        in_use = peak = 0
        seen = False
        for d in jax.local_devices():
            stats = None
            try:
                stats = d.memory_stats()
            except Exception:
                continue
            if not stats:
                continue
            seen = True
            in_use += int(stats.get("bytes_in_use", 0))
            peak += int(stats.get("peak_bytes_in_use",
                                  stats.get("bytes_in_use", 0)))
        if seen:
            out["device_mb"] = round(in_use / 2**20, 2)
            out["device_peak_mb"] = round(peak / 2**20, 2)
    except Exception:
        pass
    return out


def snapshot() -> Dict[str, float]:
    """One memory sample: RSS + peak RSS, device stats where exposed,
    tracemalloc current/peak when active."""
    out = _rss_mb()
    out.update(_device_mb())
    try:
        import tracemalloc

        if tracemalloc.is_tracing():
            cur, peak = tracemalloc.get_traced_memory()
            out["pyheap_mb"] = round(cur / 2**20, 2)
            out["pyheap_peak_mb"] = round(peak / 2**20, 2)
    except Exception:
        pass
    return out


class watermark:
    """Context manager recording a ``mem.<name>`` instant event at
    phase exit with before/after/peak memory attrs (plus any static
    ``attrs`` given at entry — e.g. a predicted allocation size).
    No-op while tracing is disabled."""

    __slots__ = ("name", "attrs", "_before", "_active")

    def __init__(self, name: str, **attrs: Any):
        self.name = name
        self.attrs = attrs
        self._before: Optional[Dict[str, float]] = None
        self._active = False

    def __enter__(self) -> "watermark":
        if not _trace.enabled():
            return self
        self._active = True
        _tls.depth = getattr(_tls, "depth", 0) + 1
        if os.environ.get(_TRACEMALLOC_ENV, "") not in ("", "0"):
            try:
                import tracemalloc

                if not tracemalloc.is_tracing():
                    tracemalloc.start()
                # Only the OUTERMOST watermark resets the peak: an
                # inner phase resetting it would erase allocation peaks
                # the enclosing phase already saw.  Inner watermarks
                # therefore report "peak since the outermost enclosing
                # watermark began" — a superset, never an undercount.
                if _tls.depth == 1:
                    tracemalloc.reset_peak()
            except Exception:
                pass
        self._before = snapshot()
        return self

    def set(self, **attrs: Any) -> "watermark":
        """Attach attrs discovered while the phase runs (e.g. the
        realized output nnz)."""
        self.attrs.update(attrs)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if not self._active:
            return
        _tls.depth = max(getattr(_tls, "depth", 1) - 1, 0)
        after = snapshot()
        ev: Dict[str, Any] = dict(self.attrs)
        before = self._before or {}
        for k, v in before.items():
            ev[f"{k}_before"] = v
        for k, v in after.items():
            ev[f"{k}_after"] = v
        if "rss_mb" in before and "rss_mb" in after:
            ev["rss_delta_mb"] = round(after["rss_mb"] - before["rss_mb"],
                                       2)
            # Per-tenant attribution (obs/attrib.py): watermark growth
            # charges to the active tenant members.
            _attrib.on_mem(self.name, ev["rss_delta_mb"])
        if exc_type is not None:
            # An OOM-adjacent failure is exactly when the watermark
            # matters most: record the error class with the numbers.
            ev["error"] = exc_type.__name__
        _trace.event(f"mem.{self.name}", **ev)


# Convenience alias matching the bench-phase vocabulary.
phase = watermark
