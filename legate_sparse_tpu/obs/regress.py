# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Bench-trajectory regression gate: machine-compare bench JSONs.

Five rounds of ``BENCH_r0*.json`` artifacts were archived and never
diffed — so the VERDICT complaint ("perf asserted, not demonstrated")
can silently recur as an untracked regression between rounds.  This
module is the field-by-field comparator behind
``tools/bench_compare.py``:

- **gated fields**: ``*_ms`` (lower is better), ``*_roofline_ratio``
  (higher is better), and ``*_comm_bytes`` (the static interconnect
  predictions — deterministic, so any growth is a real code change,
  not noise).
- **noise bands**: timing fields on a shared box are only as
  trustworthy as the machine they ran on, and the recorded
  ``stream_samples`` spread measures exactly that (r05's interleaved
  triad samples disagreed by ~2.3x minutes apart).  The allowed
  worsening factor for a timing field is ``1 + band_mult * spread``
  where ``spread = (max - min) / median`` of the stream samples of
  both runs (floored at ``floor`` for runs without a recorded
  spread).  ``comm_bytes`` fields get a fixed 1% tolerance instead —
  byte predictions don't wobble with the machine.  They DO change
  with the mesh, so comm fields are gated only when both runs share
  ``platform`` and ``dist_shards``; a CPU-fallback round vs a live
  multi-chip round is a different program, reported ``incomparable``,
  not regressed.
- **key-superset contract** (BASELINE.md): a gated field present in
  the old run but missing from the new one is itself a failure
  (evidence was dropped), unless ``allow_missing``.

``load_bench`` accepts all three artifact shapes in the repo: the
driver wrapper ``{"n": .., "parsed": {...}}``, a raw bench result
object, or a log file whose last JSON line is the result.
"""

from __future__ import annotations

import fnmatch
import json
from typing import Any, Dict, List, Optional

# Default multiplicative headroom applied to the measured stream
# spread.  The spread states how far the DENOMINATOR moved between
# interleaved samples; small-workload numerators (sub-ms phase
# timings dominated by dispatch) wobble harder than the 512 MB triad,
# so the gate grants a few spreads of headroom before calling a
# regression.  Tighten per-field with --band-mult when a metric is
# known stable.
DEFAULT_BAND_MULT = 3.0
DEFAULT_FLOOR = 0.25
COMM_TOL = 0.01


def load_bench(path: str) -> Dict[str, Any]:
    """Bench result dict from any of the artifact shapes (see module
    docstring).  Raises ValueError when no result object is found."""
    with open(path) as f:
        text = f.read().strip()
    doc = None
    try:
        doc = json.loads(text)
    except ValueError:
        pass
    if isinstance(doc, dict):
        if isinstance(doc.get("parsed"), dict):      # driver wrapper
            return doc["parsed"]
        if "metric" in doc or "schema_version" in doc:
            return doc                               # raw result
    # Log file: last parseable JSON object line wins.
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict):
            return obj
    raise ValueError(f"{path}: no bench result object found")


def stream_spread(result: Dict[str, Any]) -> Optional[float]:
    """Relative spread of the run's stream samples — the measured
    machine-noise magnitude.  (max-min)/median over ``stream_samples``
    when recorded; falls back to the ``stream_gbs``/``stream2_gbs``
    pair of pre-r6 artifacts; None when the run has no spread info."""
    samples = result.get("stream_samples")
    if not samples:
        pair = [result.get("stream_gbs"), result.get("stream2_gbs")]
        samples = [s for s in pair if isinstance(s, (int, float))]
    samples = [float(s) for s in (samples or [])
               if isinstance(s, (int, float)) and s > 0]
    if len(samples) < 2:
        return None
    samples.sort()
    mid = len(samples) // 2
    median = (samples[mid] if len(samples) % 2
              else (samples[mid - 1] + samples[mid]) / 2)
    if median <= 0:
        return None
    return (samples[-1] - samples[0]) / median


def noise_band(old: Dict[str, Any], new: Dict[str, Any],
               floor: float = DEFAULT_FLOOR) -> float:
    """Combined relative noise band of a run pair: the worst recorded
    stream spread of the two, floored at ``floor``."""
    spreads = [s for s in (stream_spread(old), stream_spread(new))
               if s is not None]
    return max(spreads + [floor])


def _gated(name: str, value: Any) -> Optional[str]:
    """Classify a top-level field: 'ms' / 'ratio' / 'comm' / None."""
    if not isinstance(value, (int, float)) or isinstance(value, bool):
        return None
    # NOTE: comm_total_bytes is deliberately NOT gated — it counts
    # dispatch-level records, which vary with jit-cache state; the
    # per-phase *_comm_bytes predictions are the deterministic gate.
    # The saturation latency quantiles are NOT gated either: p50/p99
    # of a closed-loop thread sweep are dominated by GIL/thread
    # scheduling, which the stream-spread noise band does not model —
    # they ride the trajectory table as informational columns, and
    # the phase's deterministic totals (saturation_requests etc.) are
    # its gate instead.
    if name in ("saturation_p50_ms", "saturation_p99_ms"):
        return None
    if name.endswith("_comm_bytes"):
        return "comm"
    if name.endswith("_ms") or name.endswith("_ms_per_iter"):
        return "ms"
    if name.endswith("_roofline_ratio"):
        return "ratio"
    return None


def compare(old: Dict[str, Any], new: Dict[str, Any],
            band_mult: float = DEFAULT_BAND_MULT,
            floor: float = DEFAULT_FLOOR,
            comm_tol: float = COMM_TOL,
            fields: Optional[List[str]] = None,
            allow_missing: bool = False) -> List[Dict[str, Any]]:
    """Field-by-field diff of two bench results.  Returns one finding
    per gated field: ``{field, kind, old, new, worse_by, limit,
    status}`` with status in ok / improved / regressed / missing /
    new.  ``fields`` restricts the gate to fnmatch patterns (plus any
    named field regardless of suffix, compared for equality)."""
    band = noise_band(old, new, floor=floor)
    limit_timing = 1.0 + band_mult * band
    findings: List[Dict[str, Any]] = []
    # Comm predictions are deterministic GIVEN the mesh and platform;
    # across a platform or device-count transition (CPU-fallback round
    # vs live-tunnel round) they are different programs, not a
    # regression — downgrade to informational then.
    comm_comparable = (old.get("platform") == new.get("platform")
                       and old.get("dist_shards") == new.get(
                           "dist_shards"))

    def selected(name: str) -> bool:
        if fields is None:
            return True
        return any(fnmatch.fnmatch(name, pat) for pat in fields)

    names = [k for k in old if _gated(k, old[k]) and selected(k)]
    if fields is not None:
        # Explicitly selected non-suffix fields compare for equality.
        names += [k for k in old
                  if k not in names and selected(k)
                  and isinstance(old[k], (int, float))
                  and not isinstance(old[k], bool)]
    for name in sorted(names):
        kind = _gated(name, old[name]) or "exact"
        old_v = float(old[name])
        new_raw = new.get(name)
        if not isinstance(new_raw, (int, float)) or isinstance(new_raw,
                                                               bool):
            findings.append({
                "field": name, "kind": kind, "old": old_v, "new": None,
                "worse_by": None, "limit": None,
                "status": "new" if allow_missing else "missing",
            })
            continue
        new_v = float(new_raw)
        if kind == "ms":
            worse = new_v / old_v if old_v > 0 else 1.0
            limit = limit_timing
        elif kind == "ratio":
            worse = old_v / new_v if new_v > 0 else float("inf")
            limit = limit_timing
        elif kind == "comm":
            if not comm_comparable:
                findings.append({
                    "field": name, "kind": kind, "old": old_v,
                    "new": new_v, "worse_by": None, "limit": None,
                    "status": "incomparable",
                })
                continue
            worse = new_v / old_v if old_v > 0 else (
                float("inf") if new_v > 0 else 1.0)
            limit = 1.0 + comm_tol
        else:   # exact
            worse = float("inf") if new_v != old_v else 1.0
            limit = 1.0
        if worse > limit:
            status = "regressed"
        elif worse < 1.0:
            status = "improved"
        else:
            status = "ok"
        findings.append({
            "field": name, "kind": kind, "old": old_v, "new": new_v,
            "worse_by": round(worse, 4),
            "limit": round(limit, 4), "status": status,
        })
    # Gated fields that appeared in the new run only: informational.
    for name in sorted(new):
        if name in old or not _gated(name, new.get(name)):
            continue
        if not selected(name):
            continue
        findings.append({
            "field": name, "kind": _gated(name, new[name]),
            "old": None, "new": float(new[name]), "worse_by": None,
            "limit": None, "status": "new",
        })
    return findings


def regressions(findings: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    return [f for f in findings
            if f["status"] in ("regressed", "missing")]


def render_findings(findings: List[Dict[str, Any]],
                    band: Optional[float] = None) -> str:
    """Fixed-width findings table."""
    from .report import format_table

    headers = ["field", "old", "new", "worse_by", "limit", "status"]
    rows = []
    for f in findings:
        rows.append([
            f["field"],
            "-" if f["old"] is None else f"{f['old']:g}",
            "-" if f["new"] is None else f"{f['new']:g}",
            "-" if f["worse_by"] is None else f"{f['worse_by']:.3f}x",
            "-" if f["limit"] is None else f"{f['limit']:.3f}x",
            f["status"],
        ])
    out = []
    if band is not None:
        out.append(f"noise band (stream spread, floored): "
                   f"{band:.3f}")
    out.append(format_table(headers, rows))
    return "\n".join(out)


# Columns of the trajectory table, in display order.  Missing fields
# render as '-' (older rounds predate them — the superset contract
# only runs forward).
TRAJECTORY_FIELDS = [
    "platform", "stream_gbs", "value", "spmv_ms",
    "cpu_roofline_ratio",
    "spmv_bytes_per_nnz", "spmv_bytes_per_nnz_bf16",
    "cg_ms_per_iter", "spgemm_ms",
    "gmg_cycle_ms", "pde_ms_per_iter", "pde_roofline_ratio",
    "pde_bytes_per_iter", "pde_bytes_per_iter_bf16",
    "pde_bytes_ratio",
    "dist_spmv_comm_bytes", "comm_total_bytes",
    "dist2d_layout", "dist2d_spmv_comm_bytes",
    "dist2d_spmv_comm_bytes_bf16",
    "dist2d_spmv_1d_comm_bytes", "dist2d_cg_comm_bytes",
    "dist2d_spgemm_comm_bytes", "dist2d_spgemm_1d_comm_bytes",
    "dist2d_spmv_ms",
    "engine_warm_ms", "engine_batched_ms_per_req",
    "saturation_p99_ms", "irregular_spmv_ms", "irregular_spmv_speedup",
    "irregular_spmv_path", "autotune_verdicts", "obs_overhead_pct",
    "placement_migrations", "placement_reshard_bytes",
    "mutation_updates", "mutation_version_swaps",
    "mutation_compaction_ms",
    "bench_wall_s",
]


def render_trajectory(rounds: List[Dict[str, Any]],
                      labels: List[str]) -> str:
    """One row per round, the key metrics as columns — the whole bench
    history at a glance."""
    from .report import format_table

    headers = ["round"] + TRAJECTORY_FIELDS
    rows = []
    for label, r in zip(labels, rounds):
        row = [label]
        for f in TRAJECTORY_FIELDS:
            v = r.get(f)
            if v is None:
                row.append("-")
            elif isinstance(v, float):
                row.append(f"{v:g}")
            else:
                row.append(str(v))
        rows.append(row)
    return format_table(headers, rows)
