# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Aggregation of trace records into a per-op table.

Turns the raw span stream (``trace.records()`` / a trace file) into
the evidence table the review rounds kept asking for: per op —
call count, first-call time (compile + execute), steady-state time,
nnz/bytes/flops totals, achieved GB/s from the steady-state time, and
the roofline fraction against the stream bandwidth ``bench.py``
already measures.

Per-op GB/s uses STEADY-STATE time only: first calls carry the jit
compile, and mixing them in is exactly the "compile or kernel?"
ambiguity this subsystem exists to remove.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Iterable, List, Optional


def load_records(path: str) -> List[Dict[str, Any]]:
    """Read trace records from a file in either export format
    (newline-JSON from ``write_jsonl`` or Chrome-trace from
    ``write_chrome_trace``).  Chrome events are mapped back to the
    native record shape."""
    with open(path) as f:
        text = f.read()
    text = text.strip()
    if not text:
        return []
    # Newline-JSON lines also start with "{": the whole-file parse
    # only succeeds for the Chrome document (or a 1-record jsonl).
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "traceEvents" not in doc:
        return [doc]        # single-record newline-JSON file
    if isinstance(doc, dict):
        out: List[Dict[str, Any]] = []
        for ev in doc.get("traceEvents", []):
            args = dict(ev.get("args") or {})
            rec: Dict[str, Any] = {
                "name": ev.get("name", "?"),
                "ts_ns": float(ev.get("ts", 0.0)) * 1e3,
                "tid": ev.get("tid", 0),
            }
            if ev.get("ph") == "X":
                rec["type"] = "span"
                rec["dur_ns"] = float(ev.get("dur", 0.0)) * 1e3
                rec["seq"] = args.pop("seq", 0)
                rec["first"] = bool(args.pop("first_call", rec["seq"] == 0))
            elif ev.get("ph") in ("s", "t", "f"):
                # Flow-arc anchors (obs v4 causal request flows): kept
                # as their own record type so the per-op aggregation
                # never mistakes them for instrumentation events.
                rec["type"] = "flow"
                rec["flow_id"] = ev.get("id")
            else:
                rec["type"] = "event"
            if args:
                rec["attrs"] = args
            out.append(rec)
        return out
    return [json.loads(line) for line in text.splitlines() if line.strip()]


def aggregate(records: Iterable[Dict[str, Any]]) -> Dict[str, Dict[str, Any]]:
    """Per-op rollup of span records (events are counted, not timed).

    Returns ``{name: {calls, events, total_ms, first_ms, steady_ms,
    steady_calls, nnz, bytes, flops, gbs, gflops}}``; ``steady_ms`` is
    the mean over non-first calls (None with < 2 calls), ``gbs`` the
    achieved bandwidth bytes/steady-time (None without bytes attrs)."""
    agg: Dict[str, Dict[str, Any]] = {}
    for r in records:
        if r.get("type") == "flow":
            continue            # arc anchors duplicate span timings
        name = r.get("name", "?")
        row = agg.setdefault(name, {
            "calls": 0, "events": 0, "total_ms": 0.0, "first_ms": None,
            "steady_total_ms": 0.0, "steady_calls": 0,
            "steady_nnz": 0, "steady_bytes": 0, "steady_flops": 0,
            "nnz": 0, "bytes": 0, "flops": 0,
        })
        if r.get("type") == "event":
            row["events"] += 1
            continue
        dur_ms = float(r.get("dur_ns", 0)) / 1e6
        row["calls"] += 1
        row["total_ms"] += dur_ms
        attrs = r.get("attrs") or {}
        nnz = attrs.get("nnz")
        nbytes = attrs.get("bytes")
        flops = attrs.get("flops")
        for key, val in (("nnz", nnz), ("bytes", nbytes), ("flops", flops)):
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                row[key] += val
        if r.get("first", r.get("seq", 0) == 0):
            # Several "first" spans can appear after a trace.reset();
            # keep the largest (the real compile is the slow one).
            if row["first_ms"] is None or dur_ms > row["first_ms"]:
                row["first_ms"] = dur_ms
        else:
            row["steady_total_ms"] += dur_ms
            row["steady_calls"] += 1
            for key, val in (("steady_nnz", nnz), ("steady_bytes", nbytes),
                             ("steady_flops", flops)):
                if isinstance(val, (int, float)) and not isinstance(val,
                                                                    bool):
                    row[key] += val
    for row in agg.values():
        n = row["steady_calls"]
        row["steady_ms"] = (row["steady_total_ms"] / n) if n else None
        t_s = row["steady_total_ms"] / 1e3
        row["gbs"] = (row["steady_bytes"] / t_s / 1e9
                      if t_s > 0 and row["steady_bytes"] else None)
        row["gflops"] = (row["steady_flops"] / t_s / 1e9
                         if t_s > 0 and row["steady_flops"] else None)
    return agg


def _fmt(val: Optional[float], pattern: str = "{:.3f}") -> str:
    if val is None:
        return "-"
    return pattern.format(val)


def _fmt_count(val: Any) -> str:
    if not val:
        return "-"
    v = float(val)
    for unit in ("", "K", "M", "G", "T"):
        if abs(v) < 1000:
            return (f"{v:.0f}{unit}" if unit == "" or abs(v) >= 10
                    else f"{v:.1f}{unit}")
        v /= 1000.0
    return f"{v:.1f}P"


def format_table(headers: List[str], rows: List[List[str]],
                 left_cols: int = 1) -> str:
    """Shared fixed-width table renderer: column widths from content,
    first ``left_cols`` columns left-aligned, the rest right-aligned,
    a dash rule under the header.  Used by the per-op table here and
    the regress/comm renderers — one place for the layout logic."""
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]

    def line(cells):
        return "  ".join(
            c.ljust(widths[i]) if i < left_cols else c.rjust(widths[i])
            for i, c in enumerate(cells)
        ).rstrip()

    out = [line(headers), line(["-" * w for w in widths])]
    out.extend(line(r) for r in rows)
    return "\n".join(out)


def render_table(agg: Dict[str, Dict[str, Any]],
                 stream_gbs: Optional[float] = None) -> str:
    """Pretty-print the aggregate as a fixed-width per-op table.
    ``stream_gbs`` (the measured roofline from bench.py) adds a
    ``vs_stream`` column: achieved fraction of the machine ceiling."""
    headers = ["op", "calls", "total_ms", "first_ms", "steady_ms",
               "nnz", "bytes", "GB/s"]
    if stream_gbs:
        headers.append("vs_stream")
    rows = []
    order = sorted(agg.items(), key=lambda kv: -kv[1]["total_ms"])
    for name, row in order:
        if row["calls"] == 0 and row["events"]:
            label = f"{name} (x{row['events']} events)"
            rows.append([label] + ["-"] * (len(headers) - 1))
            continue
        line = [
            name,
            str(row["calls"]),
            _fmt(row["total_ms"]),
            _fmt(row["first_ms"]),
            _fmt(row["steady_ms"], "{:.4f}"),
            _fmt_count(row["nnz"]),
            _fmt_count(row["bytes"]),
            _fmt(row["gbs"], "{:.2f}"),
        ]
        if stream_gbs:
            frac = (row["gbs"] / stream_gbs) if row["gbs"] else None
            line.append(_fmt(frac, "{:.3f}"))
        rows.append(line)
    return format_table(headers, rows)


def summarize(records: Iterable[Dict[str, Any]],
              stream_gbs: Optional[float] = None) -> str:
    """One-shot: aggregate + render."""
    return render_table(aggregate(records), stream_gbs=stream_gbs)


def render_plans_table(counters: Dict[str, Any]) -> str:
    """Per-plan table from the ``engine.plan.*`` counters (embedded in
    a Chrome-trace artifact or taken live from ``counters.snapshot``):
    one row per compiled plan — builds (XLA compiles), cache hits,
    executions — plus the aggregate hit/miss/eviction line and the
    executor's batching totals.  ``tools/trace_summary.py --plans``."""
    per_plan: Dict[str, Dict[str, float]] = {}
    for name, val in counters.items():
        if not name.startswith("engine.plan."):
            continue
        body = name[len("engine.plan."):]
        if body in ("hits", "misses", "evictions", "build_ms"):
            continue                      # aggregate counters
        pid, _, kind = body.rpartition(".")
        if kind not in ("hits", "builds", "execs") or not pid:
            continue
        per_plan.setdefault(
            pid, {"hits": 0, "builds": 0, "execs": 0})[kind] += val
    lines = []
    if per_plan:
        rows = [
            [pid, str(int(r["builds"])), str(int(r["hits"])),
             str(int(r["execs"]))]
            for pid, r in sorted(per_plan.items(),
                                 key=lambda kv: -kv[1]["execs"])
        ]
        lines.append(format_table(["plan", "builds", "hits", "execs"],
                                  rows))
    else:
        lines.append("no engine.plan.* counters recorded "
                     "(engine never dispatched?)")
    hits = counters.get("engine.plan.hits", 0)
    misses = counters.get("engine.plan.misses", 0)
    if hits or misses:
        total = hits + misses
        lines.append(
            f"plan cache: {int(hits)} hits / {int(misses)} misses "
            f"({hits / total:.1%} hit rate), "
            f"{counters.get('engine.plan.build_ms', 0):.0f} ms "
            f"compiling, "
            f"{int(counters.get('engine.plan.evictions', 0))} evictions"
        )
    subs = counters.get("engine.exec.submitted", 0)
    if subs:
        batches = counters.get("engine.exec.batches", 0)
        breqs = counters.get("engine.exec.batched_requests", 0)
        qns = counters.get("engine.exec.queue_ns", 0)
        lines.append(
            f"executor: {int(subs)} submitted, {int(batches)} batches "
            f"({breqs / max(batches, 1):.1f} reqs/batch), "
            f"queue latency {qns / max(breqs, 1) / 1e3:.0f} us/req, "
            f"{int(counters.get('engine.exec.inline', 0))} inline, "
            f"{int(counters.get('engine.exec.backpressure', 0))} "
            f"backpressure"
        )
    return "\n".join(lines)


def render_latency_table(histograms: Dict[str, Any]) -> str:
    """Histogram ledger table from serialized ``lat.*`` histograms
    (the ``histograms`` blob in a Chrome-trace artifact, or
    ``{name: h.to_dict()}`` from a live ``latency.snapshot()``):
    count / mean / p50 / p95 / p99 / max per op and shape bucket.
    ``tools/trace_summary.py --latency`` renders this."""
    from . import latency as _latency

    if not histograms:
        return ("no latency histograms recorded "
                "(no instrumented ops ran?)")
    rows = []
    for name in sorted(histograms):
        try:
            h = _latency.Histogram.from_dict(name, histograms[name])
        except ValueError:
            # Artifact from a build with a different bucket grid
            # (SUB): the distribution is unreadable, not zero.
            rows.append([name, "?"] + ["(incompatible grid)"]
                        + ["-"] * 4)
            continue
        if h.count == 0:
            continue
        rows.append([
            name,
            str(h.count),
            _fmt(h.mean, "{:.4f}"),
            _fmt(h.quantile(0.5), "{:.4f}"),
            _fmt(h.quantile(0.95), "{:.4f}"),
            _fmt(h.quantile(0.99), "{:.4f}"),
            _fmt(h.max(), "{:.4f}"),
        ])
    if not rows:
        return ("no latency histograms recorded "
                "(no instrumented ops ran?)")
    return format_table(
        ["histogram", "count", "mean", "p50", "p95", "p99", "max"],
        rows)


# Aggregate resil.retry.* counter names that are NOT per-site rollups.
_RESIL_RETRY_AGG = ("attempts", "exhausted", "backoff_ms",
                    "budget_exhausted")


def render_resil_table(counters: Dict[str, Any]) -> str:
    """Per-site resilience ledger from the ``resil.*`` counters
    (``tools/trace_summary.py --resil``; naming contract in
    docs/RESILIENCE.md): one row per site that saw any activity —
    injected faults, retries, breaker trips and short-circuits,
    fallback-ladder flips — plus summary lines for shedding,
    deadlines, backoff, budgets, and health verdicts."""
    per_site: Dict[str, Dict[str, float]] = {}

    def row(site: str) -> Dict[str, float]:
        return per_site.setdefault(site, {
            "faults": 0, "retries": 0, "trips": 0,
            "short_circuit": 0, "fallbacks": 0})

    for name, val in counters.items():
        if not name.startswith("resil."):
            continue
        body = name[len("resil."):]
        # Aggregate counters (resil.fault.injected, resil.breaker.
        # trips, ...) parse to an empty site below — the summary lines
        # carry them; only non-empty sites get table rows.
        if body.startswith("fault.") and body.endswith(".injected"):
            site = body[len("fault."):-len(".injected")]
            if site:
                row(site)["faults"] += val
        elif body.startswith("retry."):
            site = body[len("retry."):]
            if site and site not in _RESIL_RETRY_AGG:
                row(site)["retries"] += val
        elif body.startswith("breaker.") and body.endswith(".trips"):
            site = body[len("breaker."):-len(".trips")]
            if site:
                row(site)["trips"] += val
        elif (body.startswith("breaker.")
                and body.endswith(".short_circuit")):
            site = body[len("breaker."):-len(".short_circuit")]
            if site:
                row(site)["short_circuit"] += val
        elif (body.startswith("fallback.")
                and body != "fallback"):
            site = body[len("fallback."):]
            if site:
                row(site)["fallbacks"] += val
    lines = []
    if per_site:
        rows = [
            [site, str(int(r["faults"])), str(int(r["retries"])),
             str(int(r["trips"])), str(int(r["short_circuit"])),
             str(int(r["fallbacks"]))]
            for site, r in sorted(per_site.items())
        ]
        lines.append(format_table(
            ["site", "faults", "retries", "trips", "short_circ",
             "fallbacks"], rows))
    else:
        lines.append("no per-site resil.* counters recorded "
                     "(resilience never engaged?)")
    att = counters.get("resil.retry.attempts", 0)
    if att or counters.get("resil.retry.exhausted", 0):
        lines.append(
            f"retries: {int(att)} attempts, "
            f"{counters.get('resil.retry.backoff_ms', 0):.1f} ms "
            f"backing off, "
            f"{int(counters.get('resil.retry.exhausted', 0))} "
            f"exhausted, "
            f"{int(counters.get('resil.retry.budget_exhausted', 0))} "
            f"budget-dry"
        )
    shed = counters.get("resil.shed", 0)
    ddl = counters.get("resil.deadline.solver", 0)
    if shed or ddl:
        lines.append(
            f"shedding: {int(shed)} requests shed "
            f"({int(counters.get('resil.shed.engine.exec.queue', 0))} "
            f"at admission, "
            f"{int(counters.get('resil.shed.engine.exec.dispatch', 0))}"
            f" at flush), {int(ddl)} solver deadline expiries"
        )
    health = {k[len("resil.health."):]: v for k, v in counters.items()
              if k.startswith("resil.health.")
              and "." not in k[len("resil.health."):]}
    if health:
        lines.append("health: " + ", ".join(
            f"{int(v)} {cause}" for cause, v in sorted(health.items())))
    inj = counters.get("resil.fault.injected", 0)
    if inj:
        lines.append(
            f"faults: {int(inj)} injected, "
            f"{int(counters.get('resil.fault.trace_skipped', 0))} "
            f"trace-suppressed"
        )
    saves = counters.get("resil.ckpt.saves", 0)
    if saves or counters.get("resil.ckpt.restores", 0):
        lines.append(
            f"checkpoints: {int(saves)} saved "
            f"({int(counters.get('resil.ckpt.bytes', 0))} host bytes, "
            f"{counters.get('resil.ckpt.ms', 0):.1f} ms), "
            f"{int(counters.get('resil.ckpt.restores', 0))} restored"
        )
    rec = counters.get("resil.recovery.attempts", 0)
    if rec:
        lines.append(
            f"recoveries: {int(rec)} device losses, "
            f"{int(counters.get('resil.recovery.mesh_shrink', 0))} "
            f"mesh shrinks moving "
            f"{int(counters.get('resil.recovery.reshard_bytes', 0))} "
            f"reshard bytes, "
            f"{int(counters.get('resil.recovery.restored_iters', 0))} "
            f"iterations restored, "
            f"{int(counters.get('resil.recovery.succeeded', 0))} "
            f"solves completed"
        )
    abft = counters.get("resil.abft.checks", 0)
    if abft:
        lines.append(
            f"abft: {int(abft)} checksummed SpMVs, "
            f"{int(counters.get('resil.abft.mismatch', 0))} mismatches"
        )
    return "\n".join(lines)


# Rejection-reason vocabulary shared with outcomes.REJECT_REASONS
# (kept literal here: report renders artifacts from other builds).
_GW_REASONS = ("deadline_shed", "quota", "queue_full", "breaker")


def render_gateway_table(counters: Dict[str, Any]) -> str:
    """Per-tenant admission-gateway ledger from the ``gateway.*``
    counters (``tools/trace_summary.py --gateway``; naming contract in
    docs/OBSERVABILITY.md): one row per tenant that submitted anything
    — submitted / served / shed / error — plus summary lines for batch
    formation (dispatches, packed multi-matrix batches, occupancy),
    per-reason rejections, and degraded-mode inline serves."""
    per_tenant: Dict[str, Dict[str, float]] = {}
    for name, val in counters.items():
        if not name.startswith("gateway.tenant."):
            continue
        body = name[len("gateway.tenant."):]
        tenant, _, kind = body.rpartition(".")
        if not tenant or kind not in ("submitted", "served", "shed",
                                      "error"):
            continue
        per_tenant.setdefault(tenant, {
            "submitted": 0, "served": 0, "shed": 0, "error": 0,
        })[kind] += val
    lines = []
    if per_tenant:
        rows = [
            [t, str(int(r["submitted"])), str(int(r["served"])),
             str(int(r["shed"])), str(int(r["error"]))]
            for t, r in sorted(per_tenant.items(),
                               key=lambda kv: (-kv[1]["submitted"],
                                               kv[0]))
        ]
        lines.append(format_table(
            ["tenant", "submitted", "served", "shed", "error"], rows))
    else:
        lines.append("no gateway.tenant.* counters recorded "
                     "(gateway never engaged?)")
    subs = counters.get("gateway.submitted", 0)
    if subs:
        disp = counters.get("gateway.dispatches", 0)
        dreq = counters.get("gateway.dispatched_requests", 0)
        lines.append(
            f"gateway: {int(subs)} submitted, "
            f"{int(counters.get('gateway.admitted', 0))} admitted, "
            f"{int(disp)} dispatches "
            f"({dreq / max(disp, 1):.1f} reqs/batch, "
            f"{int(counters.get('gateway.packed', 0))} packed "
            f"multi-matrix), "
            f"{int(counters.get('gateway.inline', 0))} inline, "
            f"{int(counters.get('gateway.evicted', 0))} evicted"
        )
    rej = {r: counters.get(f"gateway.rejected.{r}", 0)
           for r in _GW_REASONS}
    if any(rej.values()):
        lines.append("rejections: " + ", ".join(
            f"{int(v)} {r}" for r, v in rej.items() if v))
    degraded = (counters.get("gateway.breaker_inline", 0)
                + counters.get("gateway.admit_fault_inline", 0)
                + counters.get("gateway.dispatch_fault_inline", 0)
                + counters.get("gateway.dispatch_fallback", 0))
    if degraded:
        lines.append(
            f"degraded serves: "
            f"{int(counters.get('gateway.breaker_inline', 0))} "
            f"breaker-inline, "
            f"{int(counters.get('gateway.admit_fault_inline', 0))} "
            f"admit-fault, "
            f"{int(counters.get('gateway.dispatch_fault_inline', 0))} "
            f"dispatch-fault, "
            f"{int(counters.get('gateway.dispatch_fallback', 0))} "
            f"dispatch-fallback"
        )
    return "\n".join(lines)


# Per-tenant attributed-cost kinds (obs/attrib.py naming contract).
_ATTRIB_KINDS = ("wall_ns", "wait_ns", "comm_bytes", "comm_calls",
                 "dispatches", "compiles", "mem_kb")


def render_tenants_table(counters: Dict[str, Any]) -> str:
    """Per-tenant attributed-cost ledger from the ``attrib.tenant.*``
    counters (``tools/trace_summary.py --tenants``; obs/attrib.py
    naming contract): one row per tenant with attributed dispatch
    busy time, queue wait, interconnect bytes/collective calls,
    dispatch/compile counts and watermark growth — plus the
    conservation line checking the attributed byte sum against the
    untagged ``comm.total_bytes`` ledger, and the utilization
    totals."""
    per_tenant: Dict[str, Dict[str, float]] = {}
    for name, val in counters.items():
        if not name.startswith("attrib.tenant."):
            continue
        body = name[len("attrib.tenant."):]
        tenant, _, kind = body.rpartition(".")
        if not tenant or kind not in _ATTRIB_KINDS:
            continue
        per_tenant.setdefault(
            tenant, {k: 0 for k in _ATTRIB_KINDS})[kind] += val
    lines = []
    if per_tenant:
        rows = [
            [t, f"{r['wall_ns'] / 1e6:.3f}", f"{r['wait_ns'] / 1e6:.3f}",
             str(int(r["comm_bytes"])), str(int(r["comm_calls"])),
             str(int(r["dispatches"])), str(int(r["compiles"])),
             str(int(r["mem_kb"]))]
            for t, r in sorted(per_tenant.items(),
                               key=lambda kv: (-kv[1]["wall_ns"],
                                               kv[0]))
        ]
        lines.append(format_table(
            ["tenant", "busy_ms", "wait_ms", "comm_bytes", "comm_calls",
             "dispatches", "compiles", "mem_kb"], rows))
    else:
        lines.append("no attrib.tenant.* counters recorded "
                     "(attribution off — LEGATE_SPARSE_TPU_OBS_ATTRIB "
                     "unset?)")
        return "\n".join(lines)
    attributed_b = sum(int(r["comm_bytes"]) for r in per_tenant.values())
    total_b = int(counters.get("attrib.total.comm_bytes", 0))
    ledger_b = int(counters.get("comm.total_bytes", 0))
    verdict = "exact" if attributed_b == total_b else "VIOLATED"
    lines.append(
        f"conservation: {attributed_b} attributed bytes vs "
        f"{total_b} attributed-window total ({verdict}); untagged "
        f"comm.total_bytes = {ledger_b}")
    busy = counters.get("util.busy_ns", 0)
    if busy:
        lines.append(
            f"utilization: {busy / 1e6:.3f} busy ms over "
            f"{int(counters.get('util.dispatches', 0))} dispatch "
            f"spans, {int(counters.get('capacity.reports', 0))} "
            f"capacity reports")
    folds = counters.get("attrib.fold.other", 0)
    if folds:
        lines.append(f"tenant cap: {int(folds)} labels folded into "
                     f"__other__")
    return "\n".join(lines)


def render_placement_table(counters: Dict[str, Any]) -> str:
    """Elastic-placement control-loop ledger from the ``placement.*``
    counters (``tools/trace_summary.py --placement``;
    legate_sparse_tpu/placement naming contract): controller activity
    (steps/proposals and per-reason holds), migration work (count,
    declared reshard bytes, thrash), and the data-plane view (placed
    tenants, routed admissions, breaker-degraded serves)."""
    placement = {name[len("placement."):]: val
                 for name, val in counters.items()
                 if name.startswith("placement.")}
    if not placement:
        return ("no placement.* counters recorded (placement off — "
                "LEGATE_SPARSE_TPU_PLACEMENT unset?)")
    lines = []
    holds = sorted((k[len("hold."):], int(v))
                   for k, v in placement.items()
                   if k.startswith("hold.") and v)
    hold_s = ", ".join(f"{n} {r}" for r, n in holds) if holds else "none"
    lines.append(
        f"controller: {int(placement.get('steps', 0))} steps, "
        f"{int(placement.get('proposals', 0))} proposals, "
        f"holds: {hold_s}, "
        f"{int(placement.get('watchdog.ticks', 0))} watchdog ticks")
    lines.append(
        f"migrations: {int(placement.get('migrations', 0))} applied, "
        f"{int(placement.get('migration.bytes', 0))} declared reshard "
        f"bytes (priced == measured: comm.dist_reshard.ppermute_bytes"
        f" = {int(counters.get('comm.dist_reshard.ppermute_bytes', 0))}"
        f"), {int(placement.get('thrash', 0))} thrash")
    lines.append(
        f"data plane: {int(placement.get('placed', 0))} tenants "
        f"placed, {int(placement.get('routes', 0))} routed "
        f"admissions, {int(placement.get('degraded_serve', 0))} "
        f"breaker-degraded serves, "
        f"{int(placement.get('shrink.flagged', 0))} shrink flags")
    return "\n".join(lines)


def render_delta_table(counters: Dict[str, Any]) -> str:
    """Streaming-mutation ledger from the ``delta.*`` counters
    (``tools/trace_summary.py --delta``; legate_sparse_tpu/delta
    naming contract, docs/MUTATION.md): buffer activity (update
    batches, applied/overwritten slots, the derived still-pending
    count), compaction work (merges, bytes, version swaps, watermark
    pressure) and the serving view (two-term serves, gateway routes,
    distributed comm pricing)."""
    delta = {name[len("delta."):]: val
             for name, val in counters.items()
             if name.startswith("delta.")}
    if not delta:
        return ("no delta.* counters recorded (delta off — "
                "LEGATE_SPARSE_TPU_DELTA unset?)")
    applied = int(delta.get("applied", 0))
    merged = int(delta.get("compaction.merged", 0))
    lines = []
    lines.append(
        f"buffer: {int(delta.get('updates', 0))} update batches, "
        f"{applied} slots applied, "
        f"{int(delta.get('overwrites', 0))} overwrites, "
        f"{max(applied - merged, 0)} pending")
    lines.append(
        f"compaction: {int(delta.get('compactions', 0))} runs, "
        f"{merged} entries merged, "
        f"{int(delta.get('compaction.bytes', 0))} fresh-base bytes, "
        f"{int(delta.get('swap.versions', 0))} version swaps, "
        f"{int(delta.get('watermark.exceeded', 0))} watermark "
        f"exceedances, {int(delta.get('worker.errors', 0))} worker "
        f"errors")
    lines.append(
        f"serving: {int(delta.get('served', 0))} two-term serves, "
        f"{int(delta.get('routes', 0))} routed admissions, "
        f"comm: {int(counters.get('comm.delta.scatter_bytes', 0))} "
        f"scatter bytes, "
        f"{int(counters.get('comm.delta.all_gather_bytes', 0))} "
        f"all_gather bytes")
    return "\n".join(lines)


def render_flows_table(records: Iterable[Dict[str, Any]]) -> str:
    """Per-request causal-flow ledger (``tools/trace_summary.py
    --flows``): one row per trace id found in span ``trace_id`` /
    ``trace_ids`` attrs — span count, the span names bracketing the
    arc, and the end-to-end wall time from first span start to last
    span end.  Batch spans carry every member's id in ``trace_ids``,
    so one grouped dispatch legitimately appears in several flows."""
    flows: Dict[str, List[Dict[str, Any]]] = {}
    for r in records:
        if r.get("type") != "span":
            continue
        attrs = r.get("attrs") or {}
        ids = []
        tid = attrs.get("trace_id")
        if isinstance(tid, str):
            ids.append(tid)
        tids = attrs.get("trace_ids")
        if isinstance(tids, (list, tuple)):
            ids.extend(t for t in tids if isinstance(t, str))
        for t in ids:
            flows.setdefault(t, []).append(r)
    if not flows:
        return ("no trace-tagged spans recorded "
                "(tracing off, or no gateway/engine requests?)")
    rows = []
    for fid in sorted(flows):
        spans = sorted(flows[fid], key=lambda s: s.get("ts_ns", 0.0))
        t0 = spans[0].get("ts_ns", 0.0)
        t1 = max(s.get("ts_ns", 0.0) + s.get("dur_ns", 0.0)
                 for s in spans)
        busy_ms = sum(s.get("dur_ns", 0.0) for s in spans) / 1e6
        rows.append([
            fid,
            str(len(spans)),
            spans[0].get("name", "?"),
            spans[-1].get("name", "?"),
            _fmt((t1 - t0) / 1e6),
            _fmt(busy_ms),
        ])
    return format_table(
        ["flow", "spans", "first", "last", "wall_ms", "busy_ms"],
        rows, left_cols=4)


def render_slo_table(counters: Dict[str, Any],
                     records: Iterable[Dict[str, Any]] = ()) -> str:
    """SLO burn ledger (``tools/trace_summary.py --slo``): one row per
    SLO seen in ``slo.verdict`` events (latest verdict wins) or in the
    ``slo.breach.*`` counter ledger, plus an evaluation-cadence summary
    line.  Renders artifacts — no live registry access — so it works
    on traces from other processes."""
    latest: Dict[str, Dict[str, Any]] = {}
    for r in records:
        if r.get("type") != "event" or r.get("name") != "slo.verdict":
            continue
        attrs = r.get("attrs") or {}
        slo_name = attrs.get("slo")
        if isinstance(slo_name, str):
            latest[slo_name] = attrs
    breaches = {name[len("slo.breach."):]: val
                for name, val in counters.items()
                if name.startswith("slo.breach.")}
    names = sorted(set(latest) | set(breaches))
    lines = []
    if names:
        rows = []
        for n in names:
            a = latest.get(n, {})
            rows.append([
                n,
                str(a.get("status", "breach" if breaches.get(n)
                          else "?")),
                _fmt(a.get("objective_ms"), "{:.0f}"),
                (f"{a.get('fast_bad')}/{a.get('fast_total')}"
                 if a.get("fast_total") is not None else "-"),
                _fmt(a.get("fast_burn"), "{:.1f}"),
                _fmt(a.get("slow_burn"), "{:.1f}"),
                str(int(breaches.get(n, 0))),
            ])
        lines.append(format_table(
            ["slo", "status", "obj_ms", "fast_bad", "fast_burn",
             "slow_burn", "breaches"], rows, left_cols=2))
    else:
        lines.append("no slo.* activity recorded "
                     "(LEGATE_SPARSE_TPU_OBS_SLO unset, or no "
                     "evaluations ran?)")
    evals = counters.get("slo.evaluations", 0)
    if evals:
        lines.append(
            f"evaluations: {int(evals)} "
            f"({int(counters.get('slo.watchdog.ticks', 0))} from the "
            f"watchdog), "
            f"{int(sum(breaches.values()))} total breaches")
    return "\n".join(lines)
