# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Declarative SLOs over the always-on latency histograms (obs v4).

The gateway records per-QoS wait/latency distributions and the
executor per-request latencies — but "is the fleet burning its latency
budget" was still a human reading ``trace_summary`` tables.  This
module is the machine answer: a registry of per-(op, QoS) latency
objectives with error budgets, evaluated as **multi-window burn
rates** over rebased snapshots of the existing ``lat.*`` histograms
(``obs/latency.py``) — no new measurement path, no new locks on any
hot path.

Burn-rate model (the SRE multi-window form, discretized onto the
evaluation cadence):

- the **fast window** is the histogram delta since the previous
  ``evaluate()`` call (bucket-wise subtraction of the last snapshot —
  the same rebased-snapshot scheme the histograms themselves use);
- the **slow window** is the lifetime accumulation since the last
  ``slo.reset()``;
- per window, ``bad`` = observations above the objective (bucket
  upper bound > objective, so the documented ~4.4% bucket relative
  error never misclassifies a clearly-good bucket), and
  ``burn = (bad/total) / (1 - target)`` — burn 1.0 means exactly
  spending the error budget, 14.4 the classic page-now threshold.

A verdict is ``breach`` when the fast window burns at or above
``fast_burn`` (with at least ``min_events`` observations — empty
windows never page), ``watch`` when only the slow window is at or
above ``slow_burn``, else ``ok``.  Breaches increment the **exact**
counter ``slo.breach.<slo>`` and emit a ``slo.verdict`` event (when
tracing is on), so drills can assert equality, not ``>=``.

Evaluation runs at scrape/export points (``obs.snapshot_openmetrics``
calls :func:`evaluate` first) and from an optional monotonic-clock
watchdog thread.  **Inert by default**: without
``LEGATE_SPARSE_TPU_OBS_SLO`` the evaluator is one flag read returning
``[]``, no ``slo.*`` counter ever moves, and the watchdog never starts
— bit-for-bit the pre-v4 process, pinned by test.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional

from . import counters as _counters
from . import latency as _latency
from . import trace as _trace
from ..settings import settings as _rsettings

__all__ = [
    "Slo", "SloVerdict", "register", "registered", "evaluate",
    "verdicts", "start_watchdog", "stop_watchdog",
    "maybe_start_watchdog", "reset",
]


@dataclass(frozen=True)
class Slo:
    """One latency objective: ``target`` fraction of ``op`` requests
    (for ``qos``, when the op is QoS-classed) must complete within
    ``objective_ms``.  ``hist_prefix`` names the ``lat.*`` histogram
    family the objective is measured against."""

    name: str                   # registry key, e.g. "gateway.interactive"
    op: str                     # e.g. "gateway.request"
    qos: Optional[str]          # QoS class, None for un-classed ops
    hist_prefix: str            # e.g. "lat.gateway.request.interactive"
    objective_ms: float
    target: float = 0.999      # good fraction; budget = 1 - target
    fast_burn: float = 14.4    # breach threshold, fast window
    slow_burn: float = 1.0     # watch threshold, slow window
    min_events: int = 1        # fast-window floor below which no breach

    @property
    def budget(self) -> float:
        return max(1.0 - self.target, 1e-9)


class SloVerdict(NamedTuple):
    """One evaluation result.  ``status`` ∈ ok / watch / breach."""

    slo: str
    op: str
    qos: Optional[str]
    status: str
    objective_ms: float
    target: float
    fast_total: int
    fast_bad: int
    fast_burn: float
    slow_total: int
    slow_bad: int
    slow_burn: float


# Default fleet objectives: one per gateway QoS class (tightest for
# interactive, loosest for background — mirroring the WFQ weights) and
# one for the bare executor.  ``register()`` overrides by name.
DEFAULT_SLOS = (
    Slo("gateway.interactive", "gateway.request", "interactive",
        "lat.gateway.request.interactive", objective_ms=50.0,
        target=0.999),
    Slo("gateway.batch", "gateway.request", "batch",
        "lat.gateway.request.batch", objective_ms=250.0, target=0.99),
    Slo("gateway.background", "gateway.request", "background",
        "lat.gateway.request.background", objective_ms=1000.0,
        target=0.95),
    Slo("engine.request", "engine.request", None,
        "lat.engine.request.", objective_ms=250.0, target=0.99),
    # Graph analytics (PR 16): whole-algorithm wall objectives over
    # the always-on lat.graph.<alg> histograms — loose targets, these
    # are batch traversals, not interactive serving.
    Slo("graph.bfs", "graph.bfs", None, "lat.graph.bfs",
        objective_ms=1000.0, target=0.95),
    Slo("graph.sssp", "graph.sssp", None, "lat.graph.sssp",
        objective_ms=2000.0, target=0.95),
    Slo("graph.cc", "graph.cc", None, "lat.graph.cc",
        objective_ms=2000.0, target=0.95),
    Slo("graph.pagerank", "graph.pagerank", None, "lat.graph.pagerank",
        objective_ms=5000.0, target=0.95),
)

_lock = threading.Lock()
_registry: Dict[str, Slo] = {s.name: s for s in DEFAULT_SLOS}
# Per-SLO fast-window baseline: (counts list, sum) of the merged
# histogram at the previous evaluation.
_baselines: Dict[str, List[int]] = {}
_last_verdicts: List[SloVerdict] = []


def register(slo: Slo) -> None:
    """Add (or replace, by name) an objective."""
    with _lock:
        _registry[slo.name] = slo
        _baselines.pop(slo.name, None)


def registered() -> List[Slo]:
    with _lock:
        return [_registry[k] for k in sorted(_registry)]


def _merged_counts(prefix: str) -> List[int]:
    """Bucket counts of all ``lat.*`` histograms under ``prefix``,
    merged (shape-bucketed families fold into one distribution)."""
    counts = [0] * _latency._NSLOTS
    for hist in _latency.snapshot(prefix).values():
        for slot, c in enumerate(hist.counts):
            counts[slot] += c
    return counts


def _bad_total(counts: List[int], objective_ms: float):
    """(bad, total) observations: a bucket is bad when even its upper
    bound exceeds the objective."""
    bad = total = 0
    for slot, c in enumerate(counts):
        if not c:
            continue
        total += c
        if _latency.slot_upper(slot) > objective_ms * (1 + 1e-9):
            bad += c
    return bad, total


def evaluate() -> List[SloVerdict]:
    """Evaluate every registered SLO against the live histograms.
    Inert (``[]``, zero counter movement) unless
    ``settings.obs_slo`` — the scrape path calls this unconditionally."""
    if not _rsettings.obs_slo:
        return []
    _counters.inc("slo.evaluations")
    out: List[SloVerdict] = []
    with _lock:
        slos = [_registry[k] for k in sorted(_registry)]
        for slo in slos:
            counts = _merged_counts(slo.hist_prefix)
            base = _baselines.get(slo.name)
            if base is None:
                fast = counts
            else:
                # External ``latency.reset()`` rebases live histograms
                # below our baseline — clamp, never count negative.
                fast = [max(0, c - b) for c, b in zip(counts, base)]
            _baselines[slo.name] = counts
            fast_bad, fast_total = _bad_total(fast, slo.objective_ms)
            slow_bad, slow_total = _bad_total(counts, slo.objective_ms)
            fast_burn = ((fast_bad / fast_total) / slo.budget
                         if fast_total else 0.0)
            slow_burn = ((slow_bad / slow_total) / slo.budget
                         if slow_total else 0.0)
            if fast_total >= slo.min_events and \
                    fast_burn >= slo.fast_burn:
                status = "breach"
            elif slow_total and slow_burn >= slo.slow_burn:
                status = "watch"
            else:
                status = "ok"
            out.append(SloVerdict(
                slo=slo.name, op=slo.op, qos=slo.qos, status=status,
                objective_ms=slo.objective_ms, target=slo.target,
                fast_total=fast_total, fast_bad=fast_bad,
                fast_burn=fast_burn, slow_total=slow_total,
                slow_bad=slow_bad, slow_burn=slow_burn))
        _last_verdicts[:] = out
    # Counter/event emission outside the registry lock: the exact-by-
    # contract breach ledger plus a structured verdict record per
    # non-ok SLO (events are no-ops while tracing is off).
    for v in out:
        if v.status == "breach":
            _counters.inc(f"slo.breach.{v.slo}")
        if v.status != "ok":
            _trace.event("slo.verdict", slo=v.slo, status=v.status,
                         objective_ms=v.objective_ms,
                         fast_bad=v.fast_bad, fast_total=v.fast_total,
                         fast_burn=round(v.fast_burn, 3),
                         slow_bad=v.slow_bad, slow_total=v.slow_total,
                         slow_burn=round(v.slow_burn, 3))
    return out


def verdicts() -> List[SloVerdict]:
    """The most recent evaluation's verdicts (empty before the first
    armed evaluation)."""
    with _lock:
        return list(_last_verdicts)


# ------------------------------------------------------------ watchdog --
_watchdog_thread: Optional[threading.Thread] = None
_watchdog_stop = threading.Event()


def start_watchdog(interval_ms: Optional[float] = None) -> bool:
    """Start the daemon evaluation thread on a monotonic-clock cadence
    (``Event.wait`` never goes backwards with wall-clock steps).
    Returns True when (already) running; no-op unless armed and the
    interval is positive."""
    global _watchdog_thread
    if not _rsettings.obs_slo:
        return False
    if interval_ms is None:
        interval_ms = _rsettings.obs_slo_watchdog_ms
    if interval_ms <= 0:
        return False
    with _lock:
        if _watchdog_thread is not None and _watchdog_thread.is_alive():
            return True
        _watchdog_stop.clear()
        interval_s = interval_ms / 1e3

        def _loop():
            while not _watchdog_stop.wait(interval_s):
                try:
                    _counters.inc("slo.watchdog.ticks")
                    evaluate()
                except Exception:   # pragma: no cover - never kill host
                    pass

        _watchdog_thread = threading.Thread(
            target=_loop, name="lst-slo-watchdog", daemon=True)
        _watchdog_thread.start()
    return True


def stop_watchdog() -> None:
    global _watchdog_thread
    t = _watchdog_thread
    if t is None:
        return
    _watchdog_stop.set()
    t.join(timeout=5.0)
    _watchdog_thread = None


def maybe_start_watchdog() -> bool:
    """Arm the watchdog from settings alone (call sites that want the
    env-driven behavior without importing settings)."""
    return start_watchdog()


def reset() -> None:
    """Test isolation: stop the watchdog, drop window baselines and
    verdicts, restore the default registry."""
    stop_watchdog()
    with _lock:
        _registry.clear()
        _registry.update({s.name: s for s in DEFAULT_SLOS})
        _baselines.clear()
        _last_verdicts.clear()
