# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Op-level tracing: near-zero-overhead spans and structured events.

The performance story of this package was "asserted, not demonstrated"
for five review rounds (VERDICT.md): ``bench.py`` emitted one JSON
blob, and when the CPU fallback regressed nobody could tell whether
compile, host<->device transfer, or kernel execution moved.  This
module is the fix: every hot path wraps its python-level dispatch in

    with obs.span("spmv", nnz=nnz, bytes=nbytes) as sp:
        y = kernel(...)
        if sp is not None:
            sp.set(path="ell")     # attrs discovered during the op

and the recorded spans export as newline-JSON or Chrome-trace/Perfetto
format for machine-readable per-op evidence (``report.py`` aggregates
them into the per-op table).

Overhead contract
-----------------
Disabled (the default), ``span()`` touches one module global and
returns a shared no-op context manager — no allocation, no clock read;
the hot-path cost is building the kwargs dict at the call site
(nanoseconds).  Tracing activates only via ``settings``/env
(``LEGATE_SPARSE_TPU_OBS=1``) or an explicit ``enable()`` call.  This
is what lets the spans live permanently in ``csr_array.dot`` and the
solver loops without moving ``bench_wall_s``.

Compile-vs-execute split
------------------------
Spans carry a per-name sequence number: occurrence 0 of a name is the
first call (jit compile + execute through this dispatch), later
occurrences are steady-state.  ``report.py`` splits first-call from
steady-state time with it — the per-op answer to "did compile or
execution move?".

Spans observed *inside* a jax trace (e.g. an ``A @ x`` under
``jax.jit``) measure trace time, not device time — exactly like
``jax.named_scope``.  Python-level dispatch, which is where this
package's per-op decisions (DIA vs ELL vs CSR, window vs all_gather)
happen, is the intended instrumentation point.
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from . import attrib as _attrib
from . import context as _context
from . import counters as _counters

# Span attribute keys that auto-accumulate into the process-wide
# counters when a span closes (tentpole contract: nnz processed and
# bytes moved are counters, not just per-span attrs).
_ACCUMULATED_ATTRS = {"nnz": "obs.nnz_processed", "bytes": "obs.bytes_moved",
                      "flops": "obs.flops"}

_lock = threading.Lock()
_records: List[Dict[str, Any]] = []
_seq_by_name: Dict[str, int] = {}
_tls = threading.local()

# Hard cap on buffered records: an unbounded-session safety valve (a
# long-lived service with tracing left on must not leak memory without
# bound).  Overflow drops new spans and counts them.
MAX_RECORDS = int(os.environ.get("LEGATE_SPARSE_TPU_OBS_MAX_RECORDS",
                                 1_000_000))


def _env_enabled() -> bool:
    val = os.environ.get("LEGATE_SPARSE_TPU_OBS")
    if val is None:
        return False
    return val.lower() not in ("0", "false", "no", "off", "")


_enabled: bool = _env_enabled()


def enabled() -> bool:
    """Fast hot-path check: is tracing on?"""
    return _enabled


def enable() -> None:
    """Turn span/event recording on for this process."""
    global _enabled
    _enabled = True


def disable() -> None:
    """Turn span/event recording off (buffered records are kept)."""
    global _enabled
    _enabled = False


def reset() -> None:
    """Drop all buffered records and per-name sequence state."""
    with _lock:
        _records.clear()
        _seq_by_name.clear()


def _depth_stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class Span:
    """One recorded operation.  Use via ``span()``; ``set()`` attaches
    attributes discovered while the op runs (kernel choice, output
    nnz)."""

    __slots__ = ("name", "attrs", "_t0", "_depth")

    def __init__(self, name: str, attrs: Dict[str, Any]):
        self.name = name
        self.attrs = attrs
        self._t0 = 0
        self._depth = 0

    def set(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    def __enter__(self) -> "Span":
        st = _depth_stack()
        self._depth = len(st)
        st.append(self.name)
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        dur = time.perf_counter_ns() - self._t0
        st = _depth_stack()
        if st and st[-1] == self.name:
            st.pop()
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        # Causal auto-tag (obs/context.py): a span closed while a
        # trace context is active belongs to that request's flow arc.
        # Explicit trace ids (batch spans tagging their members) win.
        if "trace_id" not in self.attrs and "trace_ids" not in self.attrs:
            tid_ctx = _context.current_trace_id()
            if tid_ctx is not None:
                self.attrs["trace_id"] = tid_ctx
        with _lock:
            seq = _seq_by_name.get(self.name, 0)
            _seq_by_name[self.name] = seq + 1
            if len(_records) >= MAX_RECORDS:
                _counters.inc("obs.dropped_records")
            else:
                rec = {
                    "type": "span",
                    "name": self.name,
                    "ts_ns": self._t0,
                    "dur_ns": dur,
                    "depth": self._depth,
                    "seq": seq,
                    "first": seq == 0,
                    "tid": threading.get_ident(),
                }
                if self.attrs:
                    rec["attrs"] = self.attrs
                _records.append(rec)
        # Counter accumulation is independent of the span buffer: it
        # must keep counting even when overflow drops the records
        # (counters advertise process-lifetime totals).
        for key, counter in _ACCUMULATED_ATTRS.items():
            val = self.attrs.get(key)
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                _counters.inc(counter, val)
        # Per-tenant attribution (obs/attrib.py): dispatch busy spans
        # charge their wall time to the active tenant members.
        _attrib.on_span_close(self.name, dur, seq == 0)


class _NullSpan:
    """Shared disabled-mode context manager: no allocation per call."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, exc_type, exc, tb):
        return None

    def set(self, **attrs: Any) -> "_NullSpan":  # tolerate stray .set()
        return self


_NULL_SPAN = _NullSpan()


def span(name: str, **attrs: Any):
    """Context manager recording one operation.

    Yields the live ``Span`` when tracing is enabled (so the body can
    ``sp.set(...)`` late attributes) and ``None`` when disabled —
    guard late-attribute work with ``if sp is not None``.
    """
    if not _enabled:
        return _NULL_SPAN
    return Span(name, attrs)


def complete_span(name: str, start_ns: int, dur_ns: int,
                  **attrs: Any) -> None:
    """Record an externally-timed span — a lifecycle that starts in
    one thread and ends in another (the executor's ``engine.request``
    spans), where a context manager can't bracket it.  Takes the same
    per-name sequence slot and buffer-cap treatment as ``Span``;
    ``depth`` is 0 (cross-thread lifecycles have no nesting stack)."""
    if not _enabled:
        return
    if "trace_id" not in attrs and "trace_ids" not in attrs:
        tid_ctx = _context.current_trace_id()
        if tid_ctx is not None:
            attrs["trace_id"] = tid_ctx
    with _lock:
        seq = _seq_by_name.get(name, 0)
        _seq_by_name[name] = seq + 1
        if len(_records) >= MAX_RECORDS:
            _counters.inc("obs.dropped_records")
            return
        rec: Dict[str, Any] = {
            "type": "span",
            "name": name,
            "ts_ns": int(start_ns),
            "dur_ns": int(dur_ns),
            "depth": 0,
            "seq": seq,
            "first": seq == 0,
            "tid": threading.get_ident(),
        }
        if attrs:
            rec["attrs"] = attrs
        _records.append(rec)


def event(name: str, **attrs: Any) -> None:
    """Record an instant (zero-duration) structured event — e.g. an
    accelerator-probe failure, a collective-realization decline."""
    if not _enabled:
        return
    if "trace_id" not in attrs:
        tid_ctx = _context.current_trace_id()
        if tid_ctx is not None:
            attrs = dict(attrs, trace_id=tid_ctx)
    with _lock:
        if len(_records) >= MAX_RECORDS:
            _counters.inc("obs.dropped_records")
            return
        rec: Dict[str, Any] = {
            "type": "event",
            "name": name,
            "ts_ns": time.perf_counter_ns(),
            "tid": threading.get_ident(),
        }
        if attrs:
            rec["attrs"] = rec_attrs = {}
            for k, v in attrs.items():
                rec_attrs[k] = v
        _records.append(rec)


def records() -> List[Dict[str, Any]]:
    """Snapshot of the buffered records (copy; safe to mutate)."""
    with _lock:
        return [dict(r) for r in _records]


def _json_default(obj: Any) -> Any:
    # Span attrs may carry numpy scalars / dtypes; stringify anything
    # the stdlib encoder rejects rather than losing the whole trace.
    try:
        import numpy as np

        if isinstance(obj, np.integer):
            return int(obj)
        if isinstance(obj, np.floating):
            return float(obj)
    except Exception:
        pass
    return str(obj)


def write_jsonl(path: str) -> int:
    """Export the buffer as newline-JSON (one record per line).
    Returns the number of records written."""
    recs = records()
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r, default=_json_default) + "\n")
    return len(recs)


def to_chrome_trace(extra_metadata: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
    """Render the buffer in Chrome-trace ("Trace Event") format — loads
    directly in Perfetto / chrome://tracing.  Spans become complete
    ("X") events, events become instants ("i"); counters ride along as
    process metadata."""
    pid = os.getpid()
    trace_events: List[Dict[str, Any]] = []
    # Flow anchors: spans tagged with a trace id (obs/context.py) —
    # singly via ``trace_id`` or as a batch member list ``trace_ids``.
    flow_anchors: Dict[str, List[Dict[str, Any]]] = {}
    for r in records():
        ev: Dict[str, Any] = {
            "name": r["name"],
            "pid": pid,
            "tid": r.get("tid", 0),
            "ts": r["ts_ns"] / 1e3,       # Chrome trace wants us
        }
        args = dict(r.get("attrs") or {})
        if r["type"] == "span":
            ev["ph"] = "X"
            ev["dur"] = r["dur_ns"] / 1e3
            args["seq"] = r["seq"]
            args["first_call"] = r["first"]
            ids = []
            tid_one = args.get("trace_id")
            if isinstance(tid_one, str):
                ids.append(tid_one)
            for t in (args.get("trace_ids") or ()):
                if isinstance(t, str):
                    ids.append(t)
            for t in ids:
                flow_anchors.setdefault(t, []).append(ev)
        else:
            ev["ph"] = "i"
            ev["s"] = "p"
        if args:
            ev["args"] = args
        trace_events.append(ev)
    # One flow arc per trace id: Chrome flow events ("s" start / "t"
    # step / "f" finish) bound to the tagged slices render the request
    # as a connected arc (gateway.admit → engine.batch → dist
    # collectives) in Perfetto.  The binding point is the slice
    # enclosing (pid, tid, ts), so each flow record reuses its anchor
    # span's coordinates.
    for trace_id, anchors in sorted(flow_anchors.items()):
        if len(anchors) < 2:
            continue
        anchors.sort(key=lambda ev: ev["ts"])
        last = len(anchors) - 1
        for i, anchor in enumerate(anchors):
            flow: Dict[str, Any] = {
                "name": "request",
                "cat": "flow",
                "ph": "s" if i == 0 else ("f" if i == last else "t"),
                "id": trace_id,
                "pid": pid,
                "tid": anchor["tid"],
                "ts": anchor["ts"],
            }
            if i == last:
                flow["bp"] = "e"
            trace_events.append(flow)
    from . import latency as _latency

    meta: Dict[str, Any] = {
        "counters": _counters.snapshot(),
        # Sparse serialized histograms (obs/latency.py): the artifact
        # carries the full distributions, so tools/trace_summary.py
        # --latency renders p50/p95/p99 from the file alone.
        "histograms": {name: h.to_dict()
                       for name, h in _latency.snapshot().items()},
        "format": "legate_sparse_tpu.obs/1",
    }
    if extra_metadata:
        meta.update(extra_metadata)
    return {"traceEvents": trace_events, "otherData": meta}


def write_chrome_trace(path: str,
                       extra_metadata: Optional[Dict[str, Any]] = None
                       ) -> int:
    """Export the buffer as a Chrome-trace JSON file.  Returns the
    number of trace events written."""
    doc = to_chrome_trace(extra_metadata)
    buf = io.StringIO()
    json.dump(doc, buf, default=_json_default)
    with open(path, "w") as f:
        f.write(buf.getvalue())
    return len(doc["traceEvents"])
