# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Compute kernels for legate_sparse_tpu.

This package is the TPU-native replacement for the reference's C++/CUDA
leaf-task library (reference: ``src/sparse/`` — SpMV, SpGEMM, conversions,
see ``legate_sparse_cpp.cmake:125-192``).  Each reference task has a jitted
XLA implementation here; banded matrices additionally get the gather-free
DIA fast path in ``dia_ops.py``.
"""

from .spmv import csr_spmv, csr_spmm  # noqa: F401
from .convert import (  # noqa: F401
    row_ids_from_indptr,
    indptr_from_row_ids,
    dense_to_csr,
    csr_to_dense,
    coo_to_csr,
    csr_transpose,
    csr_diagonal,
)
from .spgemm import spgemm_csr_csr_csr_impl, coalesce_coo  # noqa: F401
from .dia_ops import dia_spmv, dia_spmm  # noqa: F401
