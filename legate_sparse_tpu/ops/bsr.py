# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Block-sparse (BSR) SpMV — the TPU irregular-path kernel.

Role parity with the reference's general CSR SpMV leaf
(``src/sparse/array/csr/spmv.cc:36-44``, ``spmv.cu:62-152``): the path
for matrices that are *not* banded (those take ``ops/pallas_dia.py``).

Why block-sparse instead of an element-gather kernel: Mosaic's gather
lowering (jax ``pallas/mosaic/lowering.py::_gather_lowering_rule``)
only supports same-shape ``take_along_axis`` along one axis of a 2-D
operand — a per-lane sublane-gather or per-sublane lane-gather.  An
element-gather SpMV needs ``x[c]`` routed from lane ``c % 128`` to an
arbitrary destination lane, which that primitive cannot express in
fewer than three chained permutation stages, all VPU-serialized.  The
TPU-native formulation is the one the hardware is built for: densify
the *present* 128x128 blocks of the sparse matrix and stream them
through the MXU at HBM bandwidth, skipping absent blocks entirely
(the block-sparse "megablocks" pattern).  See IRREGULAR.md for the
measured ceilings of every alternative.

Design:

- Pack time (host numpy, structure-static): the CSR matrix is tiled
  into 128x128 blocks; blocks containing any nonzero are densified and
  stored **transposed** as ``blkT[b, c, r] = A[R0 + r, C0 + c]`` so the
  kernel's matvec ``x_chunk(1,128) @ blkT(128,128)`` lands the result
  lane-major (no in-kernel transpose).  Block ids sorted by
  (block-row, block-col); empty block-rows get one explicit zero block
  so every output row is written.
- Kernel: 1-D grid over blocks.  ``brow``/``bcol`` ride as prefetched
  scalars; the index maps stream the right x chunk and data block per
  step, and the output block spec revisits the same (1,128) y row for
  consecutive blocks of one block-row, accumulating in VMEM (zeroed on
  first visit) — the canonical Pallas reduction pattern.
- Everything is 32-bit on the TPU path (f32 values / int32 ids).

Useful-bandwidth law (random uniform density d): traffic is 64 KiB per
present block regardless of its population, so effective CSR-equivalent
bandwidth is ~ ``819 GB/s * 2 * d`` on v5e — the path wins over the XLA
gather (~4 GB/s measured) above d ≈ 0.25%, and real (clustered) sparse
matrices sit far above their uniform-density equivalent because their
nonzeros concentrate in few blocks.
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

B = 128  # block edge: one lane tile; MXU-native matvec per block

# Present-block scalar ids live in SMEM; cap their footprint (2 int32
# arrays) and the densified data (64 KiB/block in HBM).
MAX_BLOCKS = 1 << 16


def bsr_pack(data, indices, indptr, shape, max_expand: float):
    """Host-side CSR -> transposed-BSR pack, or None over budget.

    Returns ``(blkT, brow, bcol, nbr, nbc)``: ``blkT`` (nb, B, B) with
    ``blkT[b, c, r]``, ``brow``/``bcol`` (nb,) int32 sorted by
    (brow, bcol), ``nbr``/``nbc`` the padded block-grid shape.  The
    budget check (``nb * B*B <= max_expand * nnz``) runs before any
    densification so an over-budget matrix costs one bincount, not GBs.
    """
    rows, cols = shape
    data = np.asarray(data)
    indices = np.asarray(indices)
    indptr = np.asarray(indptr)
    nnz = data.shape[0]
    if nnz == 0 or rows == 0 or cols == 0 or max_expand <= 0:
        return None

    # Native single-pass pack when the C++ helper is built (no global
    # sort — exploits CSR row order); numpy fallback below.
    from ..utils_native import native_bsr_pack

    native = native_bsr_pack(
        indptr, indices, data, rows, cols, float(max_expand), MAX_BLOCKS
    )
    if native == "over_budget":
        return None
    if native is not None:
        return native

    nbr = -(-rows // B)
    nbc = -(-cols // B)
    r = np.repeat(np.arange(rows, dtype=np.int64),
                  np.diff(indptr).astype(np.int64))
    c = indices.astype(np.int64)
    key = (r >> 7) * nbc + (c >> 7)
    uniq, inv = np.unique(key, return_inverse=True)
    # One zero block per empty block-row so y is fully written.
    missing = np.setdiff1d(
        np.arange(nbr, dtype=np.int64), uniq // nbc, assume_unique=False
    )
    nb = uniq.shape[0] + missing.shape[0]
    if nb > MAX_BLOCKS or nb * B * B > max_expand * nnz:
        return None
    all_keys = np.concatenate([uniq, missing * nbc])
    order = np.argsort(all_keys, kind="stable")
    all_keys = all_keys[order]
    # Where each original unique block landed after the merge-sort.
    pos_of_uniq = np.empty(nb, dtype=np.int64)
    pos_of_uniq[order] = np.arange(nb)
    bid = pos_of_uniq[inv]

    blkT = np.zeros((nb, B, B), dtype=np.float32)
    # Transposed fill: slot (block, c % B, r % B).
    flat = (bid * (B * B) + (c & (B - 1)) * B + (r & (B - 1)))
    np.add.at(blkT.reshape(-1), flat, data.astype(np.float32))
    brow = (all_keys // nbc).astype(np.int32)
    bcol = (all_keys % nbc).astype(np.int32)
    return blkT, brow, bcol, nbr, nbc


def _make_kernel(pl):
    def kernel(brow_ref, bcol_ref, blk_ref, x_ref, y_ref):
        i = pl.program_id(0)
        b = brow_ref[i]
        prev = brow_ref[jnp.maximum(i - 1, 0)]
        first = jnp.logical_or(i == 0, b != prev)

        @pl.when(first)
        def _():
            y_ref[...] = jnp.zeros_like(y_ref)

        xc = x_ref[...]          # (1, B)
        blkT = blk_ref[0]        # (B, B), blkT[c, r]
        y_ref[...] += jnp.dot(
            xc, blkT, preferred_element_type=y_ref.dtype
        )

    return kernel


@partial(jax.jit, static_argnames=("nbr", "nbc", "interpret"))
def bsr_spmv_pallas(blkT, brow, bcol, x2d, nbr: int, nbc: int,
                    interpret: bool = False):
    """y2d (nbr, B) = A @ x over present blocks, one grid step each.

    ``x2d`` is x zero-padded and reshaped (nbc, B).  Output rows beyond
    the matrix's true row count are garbage-free (zero blocks pad empty
    block-rows); the caller truncates after ravel.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nb = blkT.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((1, B, B), lambda i, brow, bcol: (i, 0, 0)),
            pl.BlockSpec((1, B), lambda i, brow, bcol: (bcol[i], 0)),
        ],
        out_specs=pl.BlockSpec((1, B), lambda i, brow, bcol: (brow[i], 0)),
    )
    return pl.pallas_call(
        _make_kernel(pl),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((nbr, B), jnp.float32),
        interpret=interpret,
    )(brow, bcol, blkT, x2d)


def _make_spmm_kernel(pl):
    def kernel(brow_ref, bcol_ref, blk_ref, xt_ref, y_ref):
        i = pl.program_id(0)
        b = brow_ref[i]
        prev = brow_ref[jnp.maximum(i - 1, 0)]
        first = jnp.logical_or(i == 0, b != prev)

        @pl.when(first)
        def _():
            y_ref[...] = jnp.zeros_like(y_ref)

        xt = xt_ref[0]           # (k_pad, B): X chunk transposed
        blkT = blk_ref[0]        # (B, B), blkT[c, r]
        y_ref[...] += jnp.dot(
            xt, blkT, preferred_element_type=y_ref.dtype
        )[None]

    return kernel


# SpMM k cap: one (k, B) X chunk + (k, B) Y block must stay far inside
# VMEM next to the 64 KiB data block.
SPMM_MAX_K = 512


@partial(jax.jit, static_argnames=("nbr", "nbc", "interpret"))
def bsr_spmm_pallas(blkT, brow, bcol, xt3, nbr: int, nbc: int,
                    interpret: bool = False):
    """YT (nbr, k_pad, B) = A @ X over present blocks.

    ``xt3`` is X transposed and chunked: (nbc, k_pad, B) with
    ``xt3[c, :, l] = X[c*B + l, :]`` — the transposed layout makes the
    per-block product ``xt(k,B) @ blkT(B,B)`` land lane-major, same
    trick as the SpMV kernel's transposed blocks.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nb = blkT.shape[0]
    k_pad = xt3.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((1, B, B), lambda i, brow, bcol: (i, 0, 0)),
            pl.BlockSpec((1, k_pad, B),
                         lambda i, brow, bcol: (bcol[i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, k_pad, B),
                               lambda i, brow, bcol: (brow[i], 0, 0)),
    )
    return pl.pallas_call(
        _make_spmm_kernel(pl),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((nbr, k_pad, B), jnp.float32),
        interpret=interpret,
    )(brow, bcol, blkT, xt3)


@partial(jax.jit, static_argnames=("nbr", "nbc"))
def bsr_spmv_xla(blkT, brow, bcol, x2d, nbr: int, nbc: int):
    """XLA reference for the same BSR structure (differential testing
    and non-TPU platforms): gather x chunks, batched matvec, segment-sum
    rows of the result."""
    xg = x2d[bcol]                              # (nb, B)
    prod = jnp.einsum("bc,bcr->br", xg, blkT)   # (nb, B)
    return jax.ops.segment_sum(
        prod, brow, num_segments=nbr, indices_are_sorted=True
    )


class BsrStructure:
    """Device-resident pack + dispatch wrapper cached on csr_array.

    ``dtype`` is the matrix value dtype: f32 blocks stream as f32;
    bf16 matrices store bf16 blocks (half the HBM traffic — the
    dominant cost) with f32 MXU accumulation, and results come back
    in the matrix dtype either way.
    """

    def __init__(self, blkT, brow, bcol, nbr, nbc, rows, cols,
                 dtype=jnp.float32):
        self.dtype = jnp.dtype(dtype)
        self.blkT = jnp.asarray(blkT, dtype=self.dtype)
        self.brow = jnp.asarray(brow)
        self.bcol = jnp.asarray(bcol)
        self.nbr = int(nbr)
        self.nbc = int(nbc)
        self.rows = int(rows)
        self.cols = int(cols)
        self.nblocks = int(self.blkT.shape[0])

    def matvec(self, x, interpret: bool):
        pad = self.nbc * B - self.cols
        xf = jnp.asarray(x, dtype=self.dtype).ravel()
        if pad:
            xf = jnp.concatenate(
                [xf, jnp.zeros((pad,), dtype=self.dtype)]
            )
        x2d = xf.reshape(self.nbc, B)
        y2d = bsr_spmv_pallas(
            self.blkT, self.brow, self.bcol, x2d, self.nbr, self.nbc,
            interpret=interpret,
        )
        return y2d.ravel()[: self.rows].astype(self.dtype)

    def matmat(self, X, interpret: bool):
        """Y = A @ X for dense (cols, k) X, k <= SPMM_MAX_K."""
        X = jnp.asarray(X, dtype=self.dtype)
        k = X.shape[1]
        if k > SPMM_MAX_K:
            raise ValueError(
                f"BSR SpMM supports k <= {SPMM_MAX_K}, got {k} "
                "(VMEM budget for the per-block X chunk)"
            )
        pad_r = self.nbc * B - self.cols
        if pad_r:
            X = jnp.concatenate(
                [X, jnp.zeros((pad_r, k), dtype=self.dtype)]
            )
        # Sublane-tile multiple: 8 for f32, 16 for the packed bf16 tile.
        sub = 16 if self.dtype == jnp.bfloat16 else 8
        k_pad = max(-(-k // sub) * sub, sub)
        if k_pad != k:
            X = jnp.concatenate(
                [X, jnp.zeros((X.shape[0], k_pad - k), self.dtype)],
                axis=1,
            )
        # (nbc*B, k_pad) -> (nbc, k_pad, B) transposed chunks.
        xt3 = jnp.swapaxes(X.reshape(self.nbc, B, k_pad), 1, 2)
        yt3 = bsr_spmm_pallas(
            self.blkT, self.brow, self.bcol, xt3, self.nbr, self.nbc,
            interpret=interpret,
        )
        Y = jnp.swapaxes(yt3, 1, 2).reshape(self.nbr * B, k_pad)
        return Y[: self.rows, :k].astype(self.dtype)
