# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""Format conversions as jitted XLA programs.

TPU-native replacements for the reference conversion tasks:

- dense->CSR (reference: ``src/sparse/array/conv/dense_to_csr.cc`` two-pass
  NNZ count + fill, driven single-process from ``csr.py:109-148``) — here a
  fully shardable ``jnp.nonzero(size=...)`` compaction.
- CSR->dense (reference: ``src/sparse/array/conv/csr_to_dense.cc``) — a
  scatter-add.
- pos->coordinates expansion (reference:
  ``src/sparse/array/conv/pos_to_coordinates_template.inl:55-110`` thrust
  scan/scatter/gather chain) — scatter-ones at the row boundaries +
  prefix sum, two streaming O(nnz) ops.
- COO->CSR (reference: ``csr.py:183-219`` stable argsort by row +
  bincount/cumsum) — lexsort + bincount.
- transpose (reference: ``csr.py:512-542`` expand + stable argsort by crd).
- get-diagonal (reference: ``src/sparse/array/csr/get_diagonal.cc``).

Shape discipline: every function takes/returns arrays whose sizes (rows,
nnz) are static at trace time — the XLA analog of the reference blocking
on nnz futures (``csr.py:130,714``).
"""

from __future__ import annotations

from functools import partial

import numpy as np

import jax
import jax.numpy as jnp

from ..types import coord_dtype_for, index_dtype, nnz_dtype


@partial(jax.jit, static_argnames=("nnz",))
def row_ids_from_indptr(indptr: jax.Array, nnz: int) -> jax.Array:
    """Expand CSR indptr to a per-nonzero row-id vector.

    Equivalent of the reference's EXPAND_POS_TO_COORDINATES task
    (``pos_to_coordinates_template.inl:55-110``): scatter a 1 at each
    interior row boundary, then prefix-sum — two streaming O(nnz) ops
    (duplicate boundaries from empty rows accumulate, so the cumsum
    lands on the right row id; boundaries at nnz itself belong to
    empty tail rows and drop harmlessly).  Measured 6.8x faster than
    the previous ``searchsorted`` formulation at 1.4M nnz on CPU, and
    both primitives stream on TPU where the binary search gathers
    don't.
    """
    if nnz == 0:
        return jnp.zeros((0,), dtype=indptr.dtype)
    marks = jnp.zeros((nnz,), jnp.int32).at[indptr[1:-1]].add(
        1, mode="drop"
    )
    return jnp.cumsum(marks).astype(indptr.dtype)


@partial(jax.jit, static_argnames=("rows",))
def indptr_from_row_ids(row_ids: jax.Array, rows: int) -> jax.Array:
    """Inverse expansion: per-nnz row ids (sorted) -> indptr of length rows+1."""
    counts = jnp.bincount(row_ids, length=rows)
    return jnp.concatenate(
        [jnp.zeros((1,), dtype=nnz_dtype()), jnp.cumsum(counts).astype(nnz_dtype())]
    )


def dense_nnz(dense) -> int:
    """Host-blocking nonzero count (the analog of ``int(nnz)`` at
    reference ``csr.py:130`` — shapes must be concrete before compaction)."""
    return int(jnp.count_nonzero(dense))


@partial(jax.jit, static_argnames=("nnz",))
def dense_to_csr(dense: jax.Array, nnz: int):
    """Compact a 2-D dense array into (data, indices, indptr).

    One pass, no single-process bottleneck: ``jnp.nonzero(size=nnz)``
    enumerates nonzeros in row-major = CSR order.  (The reference needs a
    manual 1-process fill task here, an acknowledged scaling limitation,
    ``csr.py:134-145``; on XLA the compaction shards.)
    """
    rows, cols = dense.shape
    ridx, cidx = jnp.nonzero(dense, size=nnz, fill_value=0)
    data = dense[ridx, cidx]
    cdt = coord_dtype_for(max(rows, cols))
    counts = jnp.bincount(ridx, length=rows)
    indptr = jnp.concatenate(
        [jnp.zeros((1,), dtype=nnz_dtype()), jnp.cumsum(counts).astype(nnz_dtype())]
    )
    return data, cidx.astype(cdt), indptr


@partial(jax.jit, static_argnames=("shape",))
def csr_to_dense(data, indices, indptr, shape):
    """Scatter CSR triplets into a dense (rows, cols) array
    (reference task ``csr_to_dense.cc``; duplicates accumulate)."""
    rows, cols = shape
    row_ids = row_ids_from_indptr(indptr, data.shape[0])
    out = jnp.zeros(shape, dtype=data.dtype)
    if data.dtype == jnp.bool_:
        # Scatter-add rejects bool; duplicates accumulate as logical
        # or (max), matching "nonzero wins" semantics.
        return out.at[row_ids, indices].max(data, mode="drop")
    return out.at[row_ids, indices].add(data, mode="drop")


@partial(jax.jit, static_argnames=("rows",))
def coo_to_csr(rows_idx, cols_idx, values, rows: int):
    """Stable sort COO by row, then build indptr.

    Matches reference semantics (``csr.py:183-219``): a *stable* argsort on
    the row indices so intra-row input order is preserved (scipy property
    relied on by ``test_csr_from_coo``), duplicates kept.
    """
    order = jnp.argsort(rows_idx, stable=True)
    r = rows_idx[order]
    c = cols_idx[order]
    v = values[order]
    indptr = indptr_from_row_ids(r, rows)
    return v, c, indptr


@partial(jax.jit, static_argnames=("rows", "cols"))
def csr_transpose(data, indices, indptr, rows: int, cols: int):
    """CSR -> CSR of the transpose.

    Reference algorithm (``csr.py:512-542``): expand pos to row
    coordinates, stably argsort by column index, rebuild pos.  Identical
    structure here — expand, stable sort by ``indices``, bincount.
    """
    nnz = data.shape[0]
    row_ids = row_ids_from_indptr(indptr, nnz)
    order = jnp.argsort(indices, stable=True)
    new_indices = row_ids[order].astype(indices.dtype)
    new_data = data[order]
    new_indptr = indptr_from_row_ids(indices[order], cols)
    return new_data, new_indices, new_indptr


@partial(jax.jit, static_argnames=("rows", "k"))
def csr_diagonal(data, indices, indptr, rows: int, k: int = 0):
    """Extract the k-th diagonal (reference task ``get_diagonal.cc``;
    the reference only supports k=0, ``csr.py:345-368`` — we allow any k).

    For row i the diagonal element is at column i+k; absent entries are 0,
    duplicates sum (scipy semantics).
    """
    nnz = data.shape[0]
    row_ids = row_ids_from_indptr(indptr, nnz)
    on_diag = indices == (row_ids + k).astype(indices.dtype)
    contrib = jnp.where(on_diag, data, jnp.zeros((), dtype=data.dtype))
    return jax.ops.segment_sum(contrib, row_ids, num_segments=rows)


@partial(jax.jit, static_argnames=("nnz_out",))
def compact_mask(mask, arrays, nnz_out: int):
    """Gather elements of each array where mask is True, in order.

    The XLA replacement for the reference's unbound output stores
    (``csr.py:620-621``): callers first materialize ``int(mask.sum())``
    on host, then compact with a static output size.
    """
    idx = jnp.nonzero(mask, size=nnz_out, fill_value=0)[0]
    return tuple(a[idx] for a in arrays)


@partial(jax.jit, static_argnames=("nnz_out",))
def select_rows(data, indices, indptr, rows_idx, nnz_out: int):
    """Gather a row subset into a new CSR triple.

    ``rows_idx`` (k,) row ids (any order, duplicates allowed);
    ``nnz_out`` = the concrete total nnz of the selection (host-summed
    by the caller — the framework's static-shape discipline).  Returns
    (data, indices, indptr) of the (k, cols) result.
    """
    starts = indptr[rows_idx]                       # (k,)
    counts = (indptr[rows_idx + 1] - starts)
    new_indptr = jnp.concatenate(
        [jnp.zeros((1,), nnz_dtype()),
         jnp.cumsum(counts).astype(nnz_dtype())]
    )
    out_row = row_ids_from_indptr(new_indptr, nnz_out)
    pos_in_row = (
        jnp.arange(nnz_out, dtype=starts.dtype)
        - new_indptr[out_row].astype(starts.dtype)
    )
    src = starts[out_row].astype(index_dtype()) + pos_in_row
    return data[src], indices[src], new_indptr
