# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""DIA (banded) kernels: shifted-add SpMV.

The banded matrices the reference benchmarks on (11-diag SpMV sweep,
5-pt Poisson CG — ``examples/spmv_microbenchmark.py``, ``examples/pde.py``)
have a TPU-perfect structure: SpMV over DIA storage is a sum of
statically-shifted elementwise products — zero gathers, pure VPU
streaming at HBM bandwidth.  The reference always converts to CSR and
pays the gather cost (``dia.py:152-190`` conversion, then CSR SpMV);
keeping the DIA fast path is a deliberate improvement, not a port.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("offsets", "shape"))
def dia_spmv(data: jax.Array, x: jax.Array, offsets: Tuple[int, ...],
             shape: Tuple[int, int]) -> jax.Array:
    """y = A @ x for DIA storage (scipy layout A[j-off, j] = data[d, j]).

    ``offsets`` is a static tuple, so the loop unrolls into num_diags
    shifted multiply-adds with static slice bounds — XLA fuses the whole
    thing into one pass over ``data``.
    """
    rows, cols = shape
    width = data.shape[1]
    y = jnp.zeros((rows,), dtype=jnp.result_type(data.dtype, x.dtype))
    for d, off in enumerate(offsets):
        j_lo = max(0, off)
        j_hi = min(min(cols, width), rows + off)
        if j_hi <= j_lo:
            continue
        i_lo, i_hi = j_lo - off, j_hi - off
        y = y.at[i_lo:i_hi].add(data[d, j_lo:j_hi] * x[j_lo:j_hi])
    return y


def band_cover(offsets: Tuple[int, ...], shape: Tuple[int, int],
               width: int) -> int:
    """Number of in-bounds band slots for the given diagonals — the
    slots ``dia_spmv`` actually multiplies (same loop bounds)."""
    rows, cols = shape
    total = 0
    for off in offsets:
        j_lo = max(0, off)
        j_hi = min(min(cols, width), rows + off)
        total += max(0, j_hi - j_lo)
    return total


def csr_band_offsets(indices, row_ids, max_diags: int):
    """Distinct diagonals (col - row) of a CSR structure, or None when
    there are more than ``max_diags`` of them.

    One device sort + two small host syncs — runs once per matrix at
    structure-cache build time (the analog of Legion computing image
    partitions once and caching them, reference §3.2).
    """
    if indices.shape[0] == 0:
        return None
    d = indices.astype(jnp.int64) - row_ids.astype(jnp.int64)
    ds = jnp.sort(d)
    heads = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), ds[1:] != ds[:-1]]
    )
    nd = int(jnp.sum(heads))
    if nd > max_diags:
        return None
    idx = jnp.nonzero(heads, size=nd)[0]
    import numpy as np

    return tuple(int(o) for o in np.asarray(ds[idx]))


@partial(jax.jit, static_argnames=("offsets", "cols", "with_mask"))
def dia_from_csr(data, indices, row_ids, offsets: Tuple[int, ...],
                 cols: int, with_mask: bool = False):
    """Scatter CSR values into scipy-layout DIA storage
    (``dia_data[d, j] = A[j - offsets[d], j]``).  With ``with_mask``,
    also returns the explicit-entry mask (True where a CSR nonzero
    exists) so kernels can skip band *holes* — in-bounds band slots
    with no stored entry, e.g. the zeros ``diags().tocsr()`` drops."""
    offs = jnp.asarray(offsets, dtype=jnp.int64)
    d = indices.astype(jnp.int64) - row_ids.astype(jnp.int64)
    d_idx = jnp.searchsorted(offs, d)
    out = jnp.zeros((len(offsets), cols), dtype=data.dtype)
    out = out.at[d_idx, indices].set(data, mode="drop")
    if not with_mask:
        return out
    mask = jnp.zeros((len(offsets), cols), dtype=bool)
    mask = mask.at[d_idx, indices].set(True, mode="drop")
    return out, mask


@partial(jax.jit, static_argnames=("offsets", "shape"))
def dia_spmv_masked(data: jax.Array, mask: jax.Array, x: jax.Array,
                    offsets: Tuple[int, ...],
                    shape: Tuple[int, int]) -> jax.Array:
    """Shifted-add SpMV over a *holey* band: ``mask`` marks the slots
    that are explicit CSR entries; hole products are masked out (not
    0*x — an inf/nan x entry at a hole must not inject NaN, exactly as
    CSR SpMV never touches it)."""
    rows, cols = shape
    width = data.shape[1]
    y = jnp.zeros((rows,), dtype=jnp.result_type(data.dtype, x.dtype))
    for d, off in enumerate(offsets):
        j_lo = max(0, off)
        j_hi = min(min(cols, width), rows + off)
        if j_hi <= j_lo:
            continue
        i_lo, i_hi = j_lo - off, j_hi - off
        contrib = jnp.where(
            mask[d, j_lo:j_hi],
            data[d, j_lo:j_hi] * x[j_lo:j_hi],
            jnp.zeros((), y.dtype),
        )
        y = y.at[i_lo:i_hi].add(contrib)
    return y


@partial(jax.jit, static_argnames=("offsets", "shape"))
def dia_spmm_masked(data: jax.Array, mask: jax.Array, X: jax.Array,
                    offsets: Tuple[int, ...],
                    shape: Tuple[int, int]) -> jax.Array:
    """Y = A @ X over a holey band (see ``dia_spmv_masked``)."""
    rows, cols = shape
    width = data.shape[1]
    Y = jnp.zeros((rows, X.shape[1]),
                  dtype=jnp.result_type(data.dtype, X.dtype))
    for d, off in enumerate(offsets):
        j_lo = max(0, off)
        j_hi = min(min(cols, width), rows + off)
        if j_hi <= j_lo:
            continue
        i_lo, i_hi = j_lo - off, j_hi - off
        contrib = jnp.where(
            mask[d, j_lo:j_hi, None],
            data[d, j_lo:j_hi, None] * X[j_lo:j_hi, :],
            jnp.zeros((), Y.dtype),
        )
        Y = Y.at[i_lo:i_hi, :].add(contrib)
    return Y


@partial(jax.jit, static_argnames=("offsets", "shape"))
def dia_spmm(data: jax.Array, X: jax.Array, offsets: Tuple[int, ...],
             shape: Tuple[int, int]) -> jax.Array:
    """Y = A @ X for dense X (column-batched shifted adds)."""
    rows, cols = shape
    width = data.shape[1]
    Y = jnp.zeros((rows, X.shape[1]),
                  dtype=jnp.result_type(data.dtype, X.dtype))
    for d, off in enumerate(offsets):
        j_lo = max(0, off)
        j_hi = min(min(cols, width), rows + off)
        if j_hi <= j_lo:
            continue
        i_lo, i_hi = j_lo - off, j_hi - off
        Y = Y.at[i_lo:i_hi, :].add(
            data[d, j_lo:j_hi, None] * X[j_lo:j_hi, :]
        )
    return Y
