# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""DIA (banded) kernels: shifted-add SpMV.

The banded matrices the reference benchmarks on (11-diag SpMV sweep,
5-pt Poisson CG — ``examples/spmv_microbenchmark.py``, ``examples/pde.py``)
have a TPU-perfect structure: SpMV over DIA storage is a sum of
statically-shifted elementwise products — zero gathers, pure VPU
streaming at HBM bandwidth.  The reference always converts to CSR and
pays the gather cost (``dia.py:152-190`` conversion, then CSR SpMV);
keeping the DIA fast path is a deliberate improvement, not a port.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

from ..types import index_dtype


@partial(jax.jit, static_argnames=("offsets", "shape"))
def dia_spmv(data: jax.Array, x: jax.Array, offsets: Tuple[int, ...],
             shape: Tuple[int, int]) -> jax.Array:
    """y = A @ x for DIA storage (scipy layout A[j-off, j] = data[d, j]).

    ``offsets`` is a static tuple, so the loop unrolls into num_diags
    shifted multiply-adds with static slice bounds — XLA fuses the whole
    thing into one pass over ``data``.
    """
    rows, cols = shape
    width = data.shape[1]
    y = jnp.zeros((rows,), dtype=jnp.result_type(data.dtype, x.dtype))
    for d, off in enumerate(offsets):
        j_lo = max(0, off)
        j_hi = min(min(cols, width), rows + off)
        if j_hi <= j_lo:
            continue
        i_lo, i_hi = j_lo - off, j_hi - off
        y = y.at[i_lo:i_hi].add(data[d, j_lo:j_hi] * x[j_lo:j_hi])
    return y


def _band_reach(offsets: Tuple[int, ...]) -> Tuple[int, int]:
    """(P, Q): band reach below/above the main diagonal — the pad
    widths shared by ``pad_dia`` and the fused kernels."""
    return max(0, -min(offsets)), max(0, max(offsets))


@partial(jax.jit, static_argnames=("offsets", "shape", "with_mask"))
def pad_dia(data, offsets: Tuple[int, ...], shape: Tuple[int, int],
            mask=None, with_mask: bool = False):
    """One-time pad of scipy-layout DIA storage for the fused SpMV
    (``dia_spmv_fused``): left pad P = band reach below the diagonal,
    right pad so every length-``rows`` slice at offset ``P + off``
    stays in range.  Invalid (out-of-matrix) slots land in the zero
    pads, which is what makes the fused single-pass form safe at the
    edges.  Cached per structure (``csr_array._get_dia_fused``)."""
    rows, cols = shape
    width = data.shape[1]
    P, Q = _band_reach(offsets)
    right = max(0, rows + Q - width)
    dpad = jnp.pad(data, ((0, 0), (P, right)))
    if not with_mask:
        return dpad, None
    return dpad, jnp.pad(mask, ((0, 0), (P, right)))


@partial(jax.jit, static_argnames=("offsets", "shape"))
def dia_spmv_fused(dpad, mpad, x, offsets: Tuple[int, ...],
                   shape: Tuple[int, int]) -> jax.Array:
    """y = A @ x over the *padded* band layout from ``pad_dia``.

    Unlike ``dia_spmv``'s ``y.at[i_lo:i_hi].add`` chain — whose
    num_diags dynamic-update-slices each force a full pass over y
    (measured: ~0.5x of stream on a multi-core CPU backend, 51 GB/s
    on-chip) — every operand here is a same-length static slice, so
    XLA fuses the whole sum into ONE pass over the band data
    (measured on-chip: 84 GB/s for the pad+slice form; the Pallas
    kernel in ``ops/pallas_dia.py`` remains the real TPU fast path).

    IEEE contract: out-of-matrix slots read 0 from *both* pads
    (0 * 0, never 0 * inf); in-range slots of an exact band are all
    explicit entries; holey bands mask x through ``mpad`` exactly like
    ``dia_spmv_masked``."""
    rows, cols = shape
    P, Q = _band_reach(offsets)
    xpad = jnp.pad(x, (P, max(0, rows + Q - cols)))
    y = jnp.zeros((rows,), dtype=jnp.result_type(dpad.dtype, x.dtype))
    for d, off in enumerate(offsets):
        s = P + off
        dv = jax.lax.slice(dpad[d], (s,), (s + rows,))
        xv = jax.lax.slice(xpad, (s,), (s + rows,))
        if mpad is not None:
            mv = jax.lax.slice(mpad[d], (s,), (s + rows,))
            xv = jnp.where(mv, xv, jnp.zeros((), xv.dtype))
        y = y + dv * xv
    return y


@partial(jax.jit, static_argnames=("offsets", "shape"))
def dia_spmv_nopad(data: jax.Array, mask, x: jax.Array,
                   offsets: Tuple[int, ...],
                   shape: Tuple[int, int]) -> jax.Array:
    """y = A @ x over scipy-layout DIA storage, interior/edge split.

    ``dia_spmv_fused`` pays a full materialized ``jnp.pad`` of ``x``
    (plus a matching band pad at build time) so every diagonal becomes
    a same-length static slice.  On bandwidth-starved CPU backends that
    pad is pure loss: 2 extra passes over ``x`` per SpMV (~20-25% of
    the pde-scale iteration, measured).  Here the INTERIOR rows — every
    row where all offsets stay in range, i.e. all but ~band-reach rows
    at each end — read ``data`` and ``x`` directly with static
    in-bounds slices, and only the edge rows go through the bounded
    ``at[].add`` form on short slices.  No padded copies exist, so the
    kernel's traffic equals the byte model in
    ``csr_array.spmv_traffic_bytes`` exactly.

    Semantics match ``dia_spmv_fused`` — including the hole ``mask``
    (an inf/nan x entry at a hole must not inject NaN — scipy's CSR
    SpMV never touches it) — up to floating-point accumulation order:
    the interior/edge split sums the same terms in a different order,
    so outputs can differ from the padded form at the last ulp.  Do
    not write exact-equality goldens across the two lowerings.
    """
    rows, cols = shape
    width = data.shape[1]
    P, Q = _band_reach(offsets)
    i0 = min(P, rows)
    i1 = max(min(rows, min(cols, width) - Q), i0)
    dt = jnp.result_type(data.dtype, x.dtype)

    def edge(r0: int, r1: int) -> jax.Array:
        ye = jnp.zeros((r1 - r0,), dtype=dt)
        for d, off in enumerate(offsets):
            j_lo = max(r0 + off, 0, off)
            j_hi = min(r1 + off, min(cols, width), rows + off)
            if j_hi <= j_lo:
                continue
            contrib = data[d, j_lo:j_hi] * x[j_lo:j_hi]
            if mask is not None:
                contrib = jnp.where(mask[d, j_lo:j_hi], contrib,
                                    jnp.zeros((), dt))
            ye = ye.at[j_lo - off - r0: j_hi - off - r0].add(contrib)
        return ye

    if i1 <= i0:
        # Band reach spans the whole matrix: every row is an edge row
        # (tiny operands — the bounded form IS the right kernel).
        return edge(0, rows)

    y_int = jnp.zeros((i1 - i0,), dtype=dt)
    for d, off in enumerate(offsets):
        lo, hi = i0 + off, i1 + off
        dv = jax.lax.slice(data[d], (lo,), (hi,))
        xv = jax.lax.slice(x, (lo,), (hi,))
        if mask is not None:
            mv = jax.lax.slice(mask[d], (lo,), (hi,))
            xv = jnp.where(mv, xv, jnp.zeros((), xv.dtype))
        y_int = y_int + dv * xv

    parts = []
    if i0 > 0:
        parts.append(edge(0, i0))
    parts.append(y_int)
    if i1 < rows:
        parts.append(edge(i1, rows))
    return jnp.concatenate(parts) if len(parts) > 1 else parts[0]


def band_cover(offsets: Tuple[int, ...], shape: Tuple[int, int],
               width: int) -> int:
    """Number of in-bounds band slots for the given diagonals — the
    slots ``dia_spmv`` actually multiplies (same loop bounds)."""
    rows, cols = shape
    total = 0
    for off in offsets:
        j_lo = max(0, off)
        j_hi = min(min(cols, width), rows + off)
        total += max(0, j_hi - j_lo)
    return total


def csr_band_offsets(indices, row_ids, max_diags: int):
    """Distinct diagonals (col - row) of a CSR structure, or None when
    there are more than ``max_diags`` of them.

    One device sort + two small host syncs — runs once per matrix at
    structure-cache build time (the analog of Legion computing image
    partitions once and caching them, reference §3.2).
    """
    if indices.shape[0] == 0:
        return None
    d = indices.astype(index_dtype()) - row_ids.astype(index_dtype())
    ds = jnp.sort(d)
    heads = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), ds[1:] != ds[:-1]]
    )
    nd = int(jnp.sum(heads))
    if nd > max_diags:
        return None
    idx = jnp.nonzero(heads, size=nd)[0]
    import numpy as np

    return tuple(int(o) for o in np.asarray(ds[idx]))


@partial(jax.jit, static_argnames=("offsets", "cols", "with_mask"))
def dia_from_csr(data, indices, row_ids, offsets: Tuple[int, ...],
                 cols: int, with_mask: bool = False):
    """Scatter CSR values into scipy-layout DIA storage
    (``dia_data[d, j] = A[j - offsets[d], j]``).  With ``with_mask``,
    also returns the explicit-entry mask (True where a CSR nonzero
    exists) so kernels can skip band *holes* — in-bounds band slots
    with no stored entry, e.g. the zeros ``diags().tocsr()`` drops."""
    offs = jnp.asarray(offsets, dtype=index_dtype())
    d = indices.astype(index_dtype()) - row_ids.astype(index_dtype())
    d_idx = jnp.searchsorted(offs, d)
    out = jnp.zeros((len(offsets), cols), dtype=data.dtype)
    out = out.at[d_idx, indices].set(data, mode="drop")
    if not with_mask:
        return out
    mask = jnp.zeros((len(offsets), cols), dtype=bool)
    mask = mask.at[d_idx, indices].set(True, mode="drop")
    return out, mask


@partial(jax.jit, static_argnames=("offsets", "shape"))
def dia_spmv_masked(data: jax.Array, mask: jax.Array, x: jax.Array,
                    offsets: Tuple[int, ...],
                    shape: Tuple[int, int]) -> jax.Array:
    """Shifted-add SpMV over a *holey* band: ``mask`` marks the slots
    that are explicit CSR entries; hole products are masked out (not
    0*x — an inf/nan x entry at a hole must not inject NaN, exactly as
    CSR SpMV never touches it)."""
    rows, cols = shape
    width = data.shape[1]
    y = jnp.zeros((rows,), dtype=jnp.result_type(data.dtype, x.dtype))
    for d, off in enumerate(offsets):
        j_lo = max(0, off)
        j_hi = min(min(cols, width), rows + off)
        if j_hi <= j_lo:
            continue
        i_lo, i_hi = j_lo - off, j_hi - off
        contrib = jnp.where(
            mask[d, j_lo:j_hi],
            data[d, j_lo:j_hi] * x[j_lo:j_hi],
            jnp.zeros((), y.dtype),
        )
        y = y.at[i_lo:i_hi].add(contrib)
    return y


@partial(jax.jit, static_argnames=("offsets", "shape"))
def dia_spmm_masked(data: jax.Array, mask: jax.Array, X: jax.Array,
                    offsets: Tuple[int, ...],
                    shape: Tuple[int, int]) -> jax.Array:
    """Y = A @ X over a holey band (see ``dia_spmv_masked``)."""
    rows, cols = shape
    width = data.shape[1]
    Y = jnp.zeros((rows, X.shape[1]),
                  dtype=jnp.result_type(data.dtype, X.dtype))
    for d, off in enumerate(offsets):
        j_lo = max(0, off)
        j_hi = min(min(cols, width), rows + off)
        if j_hi <= j_lo:
            continue
        i_lo, i_hi = j_lo - off, j_hi - off
        contrib = jnp.where(
            mask[d, j_lo:j_hi, None],
            data[d, j_lo:j_hi, None] * X[j_lo:j_hi, :],
            jnp.zeros((), Y.dtype),
        )
        Y = Y.at[i_lo:i_hi, :].add(contrib)
    return Y


@partial(jax.jit, static_argnames=("offsets", "shape"))
def dia_spmm(data: jax.Array, X: jax.Array, offsets: Tuple[int, ...],
             shape: Tuple[int, int]) -> jax.Array:
    """Y = A @ X for dense X (column-batched shifted adds)."""
    rows, cols = shape
    width = data.shape[1]
    Y = jnp.zeros((rows, X.shape[1]),
                  dtype=jnp.result_type(data.dtype, X.dtype))
    for d, off in enumerate(offsets):
        j_lo = max(0, off)
        j_hi = min(min(cols, width), rows + off)
        if j_hi <= j_lo:
            continue
        i_lo, i_hi = j_lo - off, j_hi - off
        Y = Y.at[i_lo:i_hi, :].add(
            data[d, j_lo:j_hi, None] * X[j_lo:j_hi, :]
        )
    return Y


@partial(jax.jit, static_argnames=("offsets", "shape"))
def dia_spmm_fused(dpad, mpad, X, offsets: Tuple[int, ...],
                   shape: Tuple[int, int]) -> jax.Array:
    """Y = A @ X over the padded band layout — the SpMM analog of
    ``dia_spmv_fused`` (one fused pass instead of a num_diags-long
    dynamic-update-slice chain)."""
    rows, cols = shape
    P, Q = _band_reach(offsets)
    Xpad = jnp.pad(X, ((P, max(0, rows + Q - cols)), (0, 0)))
    Y = jnp.zeros((rows, X.shape[1]),
                  dtype=jnp.result_type(dpad.dtype, X.dtype))
    k = X.shape[1]
    for d, off in enumerate(offsets):
        s = P + off
        dv = jax.lax.slice(dpad[d], (s,), (s + rows,))[:, None]
        Xv = jax.lax.slice(Xpad, (s, 0), (s + rows, k))
        if mpad is not None:
            mv = jax.lax.slice(mpad[d], (s,), (s + rows,))[:, None]
            Xv = jnp.where(mv, Xv, jnp.zeros((), Xv.dtype))
        Y = Y + dv * Xv
    return Y


def band_product_offsets(offs_a: Tuple[int, ...],
                         offs_b: Tuple[int, ...]) -> Tuple[int, ...]:
    """Diagonals of C = A @ B for banded operands: the Minkowski sum."""
    return tuple(sorted({oa + ob for oa in offs_a for ob in offs_b}))


def band_product_is_full(offs_a, offs_b, offs_c, shape_a, shape_b) -> bool:
    """True when every in-bounds slot of the product band is
    structurally reachable (some (oa, ob) pair contributes to it), i.e.
    the banded SpGEMM's full-band output has exactly the pattern the
    structural (Gustavson/ESC) product would produce.  Host arithmetic
    on static offsets only.

    At matrix boundaries a slot can be in-bounds yet unreachable (e.g.
    A = {-1} only, B = {+1} only: slot (0, 0) needs t = -1).  Such
    products must take the general kernel to keep scipy pattern parity.
    """
    m, k = shape_a
    _, n = shape_b
    by_oc: dict = {o: [] for o in offs_c}
    for oa in offs_a:
        for ob in offs_b:
            j_lo = max(0, ob, oa + ob)
            j_hi = min(n, k + ob, m + oa + ob)
            if j_hi > j_lo:
                by_oc[oa + ob].append((j_lo, j_hi))
    for oc in offs_c:
        want_lo, want_hi = max(0, oc), min(n, m + oc)
        if want_hi <= want_lo:
            continue
        covered = want_lo
        for lo, hi in sorted(by_oc[oc]):
            if lo > covered:
                return False
            covered = max(covered, hi)
        if covered < want_hi:
            return False
    return True


@partial(jax.jit, static_argnames=("offs_a", "offs_b", "offs_c",
                                   "shape_a", "shape_b"))
def dia_spgemm(a_data, b_data, offs_a: Tuple[int, ...],
               offs_b: Tuple[int, ...], offs_c: Tuple[int, ...],
               shape_a: Tuple[int, int], shape_b: Tuple[int, int]):
    """C_dia = A_dia @ B_dia as nd_a*nd_b shifted elementwise multiplies.

    For banded operands this replaces the ESC SpGEMM's expand/sort/
    compress (O(T log T) with device-wide sorts) by pure streaming
    multiply-adds with static slice bounds — the same gather-free
    principle as ``dia_spmv``.  C[i, j] = sum_t A[i, t] B[t, j] with
    t = j - ob, i = j - oa - ob; all bounds are static per (oa, ob).
    """
    m, k = shape_a
    _, n = shape_b
    idx_c = {o: i for i, o in enumerate(offs_c)}
    Cd = jnp.zeros(
        (len(offs_c), n),
        dtype=jnp.result_type(a_data.dtype, b_data.dtype),
    )
    for a_i, oa in enumerate(offs_a):
        for b_i, ob in enumerate(offs_b):
            oc = oa + ob
            j_lo = max(0, ob, oc)
            j_hi = min(n, k + ob, m + oc)
            if j_hi <= j_lo:
                continue
            contrib = (
                a_data[a_i, j_lo - ob : j_hi - ob]
                * b_data[b_i, j_lo:j_hi]
            )
            Cd = Cd.at[idx_c[oc], j_lo:j_hi].add(contrib)
    return Cd


def _band_rows_gather(dia_data, offs, cols: int, r0: int, r1: int,
                      nnz_seg: int):
    """Ragged CSR extraction for band rows [r0, r1): the gather
    formulation, used only for the edge rows (and the no-interior
    fallback) — see ``band_to_csr``."""
    from .convert import row_ids_from_indptr
    from ..types import nnz_dtype

    i = jnp.arange(r0, r1, dtype=index_dtype())
    lo = jnp.searchsorted(offs, -i, side="left")
    hi = jnp.searchsorted(offs, cols - i, side="left")
    ip_seg = jnp.concatenate(
        [jnp.zeros((1,), dtype=nnz_dtype()),
         jnp.cumsum(hi - lo).astype(nnz_dtype())]
    )
    rid = row_ids_from_indptr(ip_seg, nnz_seg).astype(index_dtype())
    pos = (jnp.arange(nnz_seg, dtype=index_dtype())
           - ip_seg[rid].astype(index_dtype()))
    d_idx = lo[rid] + pos
    col = (rid + r0) + offs[d_idx]
    return dia_data[d_idx, col], col


@partial(jax.jit, static_argnames=("offsets", "shape", "nnz"))
def band_to_csr(dia_data, offsets: Tuple[int, ...],
                shape: Tuple[int, int], nnz: int):
    """Full-band DIA -> CSR triple keeping every in-bounds band slot
    (incl. explicit zeros), ``nnz = band_cover(offsets, shape, cols)``.
    Offsets must be sorted; rows come out canonical.

    Three-segment extraction: INTERIOR rows (every offset in range)
    have exactly W entries each, so their row-major values are W static
    slices of the column-aligned band stacked and reshaped — pure
    streaming, no per-entry gathers — and their columns are an iota
    sum.  Only the <= max|offset| edge rows at each end go through the
    ragged gather formulation (``_band_rows_gather``).  This cut the
    banded-SpGEMM bench's conversion stage from ~35 ms to slice speed
    at 1.4M nnz on CPU, and slices/reshapes stream on TPU where the
    1.4M-element gathers do not.
    """
    from ..types import coord_dtype_for, nnz_dtype

    rows, cols = shape
    W = len(offsets)
    offs = jnp.asarray(offsets, dtype=index_dtype())
    i = jnp.arange(rows, dtype=index_dtype())
    # Valid offsets per row: o in [-i, cols-1-i] (contiguous in sorted offs).
    lo = jnp.searchsorted(offs, -i, side="left")
    hi = jnp.searchsorted(offs, cols - i, side="left")
    indptr = jnp.concatenate(
        [jnp.zeros((1,), dtype=nnz_dtype()),
         jnp.cumsum(hi - lo).astype(nnz_dtype())]
    )
    col_dtype = coord_dtype_for(max(rows, cols))

    # Interior range: rows where ALL W offsets land in [0, cols).
    i0 = min(max(0, -offsets[0]), rows)
    i1 = min(rows, max(cols - offsets[-1], 0))
    if i1 <= i0:
        # Band wider than the matrix: every row is an edge row.
        vals, col = _band_rows_gather(dia_data, offs, cols, 0, rows,
                                      nnz)
        return vals, col.astype(col_dtype), indptr

    # Per-segment nnz, host-side closed form (O(W) Python ints).
    nnz_top = sum(max(0, min(i0, cols - o) - max(0, -o))
                  for o in offsets)
    nnz_bot = nnz - nnz_top - (i1 - i0) * W

    ar = jnp.arange(i0, i1, dtype=index_dtype())
    vals_in = jnp.stack(
        [jax.lax.slice_in_dim(dia_data[d], i0 + o, i1 + o)
         for d, o in enumerate(offsets)], axis=1,
    ).reshape(-1)
    cols_in = (ar[:, None] + offs[None, :]).reshape(-1)

    parts_v = []
    parts_c = []
    if nnz_top:
        v_t, c_t = _band_rows_gather(dia_data, offs, cols, 0, i0,
                                     nnz_top)
        parts_v.append(v_t)
        parts_c.append(c_t)
    parts_v.append(vals_in)
    parts_c.append(cols_in)
    if nnz_bot:
        v_b, c_b = _band_rows_gather(dia_data, offs, cols, i1, rows,
                                     nnz_bot)
        parts_v.append(v_b)
        parts_c.append(c_b)
    vals = jnp.concatenate(parts_v) if len(parts_v) > 1 else parts_v[0]
    col = jnp.concatenate(parts_c) if len(parts_c) > 1 else parts_c[0]
    return vals, col.astype(col_dtype), indptr
