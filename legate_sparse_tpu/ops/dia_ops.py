# Copyright 2026.
# SPDX-License-Identifier: Apache-2.0
"""DIA (banded) kernels: shifted-add SpMV.

The banded matrices the reference benchmarks on (11-diag SpMV sweep,
5-pt Poisson CG — ``examples/spmv_microbenchmark.py``, ``examples/pde.py``)
have a TPU-perfect structure: SpMV over DIA storage is a sum of
statically-shifted elementwise products — zero gathers, pure VPU
streaming at HBM bandwidth.  The reference always converts to CSR and
pays the gather cost (``dia.py:152-190`` conversion, then CSR SpMV);
keeping the DIA fast path is a deliberate improvement, not a port.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("offsets", "shape"))
def dia_spmv(data: jax.Array, x: jax.Array, offsets: Tuple[int, ...],
             shape: Tuple[int, int]) -> jax.Array:
    """y = A @ x for DIA storage (scipy layout A[j-off, j] = data[d, j]).

    ``offsets`` is a static tuple, so the loop unrolls into num_diags
    shifted multiply-adds with static slice bounds — XLA fuses the whole
    thing into one pass over ``data``.
    """
    rows, cols = shape
    width = data.shape[1]
    y = jnp.zeros((rows,), dtype=jnp.result_type(data.dtype, x.dtype))
    for d, off in enumerate(offsets):
        j_lo = max(0, off)
        j_hi = min(min(cols, width), rows + off)
        if j_hi <= j_lo:
            continue
        i_lo, i_hi = j_lo - off, j_hi - off
        y = y.at[i_lo:i_hi].add(data[d, j_lo:j_hi] * x[j_lo:j_hi])
    return y


@partial(jax.jit, static_argnames=("offsets", "shape"))
def dia_spmm(data: jax.Array, X: jax.Array, offsets: Tuple[int, ...],
             shape: Tuple[int, int]) -> jax.Array:
    """Y = A @ X for dense X (column-batched shifted adds)."""
    rows, cols = shape
    width = data.shape[1]
    Y = jnp.zeros((rows, X.shape[1]),
                  dtype=jnp.result_type(data.dtype, X.dtype))
    for d, off in enumerate(offsets):
        j_lo = max(0, off)
        j_hi = min(min(cols, width), rows + off)
        if j_hi <= j_lo:
            continue
        i_lo, i_hi = j_lo - off, j_hi - off
        Y = Y.at[i_lo:i_hi, :].add(
            data[d, j_lo:j_hi, None] * X[j_lo:j_hi, :]
        )
    return Y
